"""Engine-wide telemetry: metrics registry, span event log, exports.

The serving stack grew three ad-hoc metric paths — the engine's
``cache_metrics()`` dict, the opt-in ``record_timings``/
``pop_request_timings`` stamp store, and the HTTP frontend's private
``_Percentiles`` window.  None of them can answer the operational
questions the ROADMAP's scale-out items need (route on pool pressure,
shed on queue depth, alert on TTFT p99) from OUTSIDE the process.
This module is the one substrate behind all three, plus the export
surfaces:

- :class:`MetricsRegistry` — always-on counters, callback gauges and
  windowed :class:`WindowHistogram` percentile estimators, rendered to
  Prometheus text exposition (``render_prometheus``) or a plain dict.
- :class:`EventLog` — a lock-light ring buffer of spans / instants /
  counter samples (one ``deque.append`` per event, bounded memory),
  exported as Chrome trace-event JSON (``to_chrome``) loadable in
  Perfetto / ``chrome://tracing``.
- :class:`Telemetry` — the per-engine facade: request-lifecycle hooks
  (enqueued → admitted → first token → finished/preempted/errored)
  feed TTFT / inter-token-gap / queue-wait histograms and lifecycle
  spans from ONE ``time.monotonic()`` stamp per event, so the rolling
  metrics, the Perfetto timeline and the legacy per-request stamp
  store can never disagree.

Design constraints (enforced by tier-1):

- **zero device syncs**: this module never imports jax; every input is
  a host float/int the engine already holds.
- **zero retraces**: telemetry is invisible to jitted programs — it
  adds no arguments, shapes or dtypes to any device call.
- **lock-light**: the hot path (one token) costs one monotonic stamp,
  one small-lock dict hit and one histogram append; events are plain
  tuples appended to a bounded deque.
"""

from __future__ import annotations

import collections
import json
import math
import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["Counter", "Gauge", "WindowHistogram", "MetricsRegistry",
           "EventLog", "Telemetry", "render_prometheus",
           "validate_chrome_trace"]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


class Counter:
    """Monotonic cumulative counter (Prometheus ``counter``)."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name, self.help = name, help
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value

    def snapshot(self):
        return self._value


class Gauge:
    """Instantaneous value: either ``set()`` by the owner or computed
    at scrape time by ``fn`` (preferred — the value is fresh and the
    owner pays nothing per update).  ``kind="counter"`` renders a
    monotonic source (e.g. the block pool's cumulative eviction count)
    with the Prometheus counter type while still reading it lazily."""

    def __init__(self, name: str, help: str = "",
                 fn: Optional[Callable[[], float]] = None,
                 kind: str = "gauge"):
        self.name, self.help, self.fn, self.kind = name, help, fn, kind
        self._value = 0.0

    def set(self, v: float) -> None:
        self._value = v

    @property
    def value(self):
        if self.fn is not None:
            try:
                return self.fn()
            except Exception:
                return None     # a failing callback must not kill scrape
        return self._value

    def snapshot(self):
        return self.value


class WindowHistogram:
    """Sliding-window percentile estimator + cumulative count/sum —
    the generalization of the HTTP frontend's old ``_Percentiles``.

    The window is a preallocated ring of the last ``window`` samples
    (percentiles of recent traffic, the SLO view); ``count``/``sum``
    are cumulative since construction and MONOTONIC across
    ``snapshot()`` calls (the Prometheus summary view — rates come
    from their deltas).  ``reset_window()`` clears only the window
    (benchmarks drop warmup samples without breaking monotonicity).
    """

    kind = "summary"

    def __init__(self, name: str = "", help: str = "",
                 window: int = 2048):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.name, self.help = name, help
        self._window = int(window)
        self._ring: List[float] = [0.0] * self._window
        self._n = 0             # samples currently in the ring
        self._i = 0             # next write index
        self._count = 0         # cumulative, monotonic
        self._sum = 0.0         # cumulative, monotonic
        self._lock = threading.Lock()

    def record(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._ring[self._i] = v
            self._i = (self._i + 1) % self._window
            self._n = min(self._n + 1, self._window)
            self._count += 1
            self._sum += v

    def reset_window(self) -> None:
        """Drop the window samples; cumulative count/sum stand."""
        with self._lock:
            self._n = 0
            self._i = 0

    def _window_sorted(self) -> List[float]:
        with self._lock:
            vals = self._ring[:self._n] if self._n < self._window \
                else list(self._ring)
        vals.sort()
        return vals

    @staticmethod
    def _pct(sorted_vals: Sequence[float], q: float) -> float:
        """Linear-interpolated percentile (numpy 'linear' method) —
        kept dependency-free so this module stays jax/numpy-clean."""
        n = len(sorted_vals)
        if n == 1:
            return sorted_vals[0]
        pos = (q / 100.0) * (n - 1)
        lo = int(math.floor(pos))
        hi = min(lo + 1, n - 1)
        frac = pos - lo
        return sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac

    def percentile(self, q: float) -> Optional[float]:
        vals = self._window_sorted()
        return self._pct(vals, q) if vals else None

    def snapshot(self) -> dict:
        """``count``/``sum`` cumulative (monotonic); ``window`` is the
        current sample count and p50/p90/p99/min/max summarize ONLY
        the window (absent while the window is empty)."""
        vals = self._window_sorted()
        with self._lock:
            out = {"count": self._count, "sum": self._sum,
                   "window": len(vals)}
        if vals:
            out.update(p50=self._pct(vals, 50), p90=self._pct(vals, 90),
                       p99=self._pct(vals, 99), min=vals[0],
                       max=vals[-1])
        return out


class MetricsRegistry:
    """Named metric store with get-or-create accessors.  Creation is
    locked; the returned metric objects are themselves thread-safe, so
    hot paths hold a reference instead of re-looking-up by name."""

    def __init__(self):
        self._metrics: "collections.OrderedDict[str, Any]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()

    def _get_or_create(self, name: str, factory, cls):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(
            name, lambda: Counter(name, help), Counter)

    def gauge(self, name: str, help: str = "",
              fn: Optional[Callable[[], float]] = None,
              kind: str = "gauge") -> Gauge:
        g = self._get_or_create(
            name, lambda: Gauge(name, help, fn=fn, kind=kind), Gauge)
        if fn is not None:
            # a rebuilt engine re-registering on a shared Telemetry must
            # not leave the gauge reading the DEAD engine's state
            g.fn = fn
        return g

    def histogram(self, name: str, help: str = "",
                  window: int = 2048) -> WindowHistogram:
        return self._get_or_create(
            name, lambda: WindowHistogram(name, help, window=window),
            WindowHistogram)

    def items(self) -> List[Tuple[str, Any]]:
        with self._lock:
            return list(self._metrics.items())

    def snapshot(self) -> Dict[str, Any]:
        """Dict view: counters/gauges -> value, histograms -> their
        snapshot dicts."""
        return {name: m.snapshot() for name, m in self.items()}


def _fmt(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, float):
        if math.isnan(v):
            return "NaN"
        if math.isinf(v):
            return "+Inf" if v > 0 else "-Inf"
        return repr(v)
    return str(v)


def render_prometheus(*registries: MetricsRegistry) -> str:
    """Text exposition (``text/plain; version=0.0.4``) for one or more
    registries: counters and gauges as single samples, window
    histograms as summaries (p50/p90/p99 quantiles over the window,
    cumulative ``_count``/``_sum``)."""
    lines: List[str] = []
    seen = set()
    for reg in registries:
        for name, m in reg.items():
            if name in seen:        # first registration wins
                continue
            seen.add(name)
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            lines.append(f"# TYPE {name} {m.kind}")
            if isinstance(m, WindowHistogram):
                snap = m.snapshot()
                for q, key in ((0.5, "p50"), (0.9, "p90"),
                               (0.99, "p99")):
                    if key in snap:
                        lines.append(
                            f'{name}{{quantile="{q}"}} '
                            f'{_fmt(snap[key])}')
                lines.append(f"{name}_count {snap['count']}")
                lines.append(f"{name}_sum {_fmt(snap['sum'])}")
            else:
                v = m.snapshot()
                if v is None:
                    continue        # failed gauge callback: no sample
                lines.append(f"{name} {_fmt(v)}")
    return "\n".join(lines) + "\n"


# ---- event log (spans / instants / counter samples) -------------------

class EventLog:
    """Bounded ring of trace events.  Append is one deque.append of a
    plain tuple (CPython deque appends are atomic — no lock on the hot
    path); readers snapshot via ``list(deque)``.

    Event tuples: ``(ph, name, ts, dur, tid, args)`` with ``ph`` one of
    ``"X"`` (complete span, ``dur`` seconds), ``"i"`` (instant) or
    ``"C"`` (counter sample, ``args`` = series values).  ``ts``/``dur``
    are ``time.monotonic()`` seconds; ``tid`` picks the Perfetto track
    (slot index for per-slot work, :data:`TID_ENGINE` for the engine
    loop, :data:`TID_QUEUE` for queue-side request events)."""

    TID_QUEUE = 0
    TID_ENGINE = 1000

    def __init__(self, capacity: int = 65536):
        self._events: collections.deque = collections.deque(
            maxlen=int(capacity))

    def __len__(self) -> int:
        return len(self._events)

    def span(self, name: str, start: float, dur: float, tid: int = 0,
             args: Optional[dict] = None) -> None:
        self._events.append(("X", name, start, max(0.0, dur), tid,
                             args))

    def instant(self, name: str, ts: Optional[float] = None,
                tid: int = 0, args: Optional[dict] = None) -> None:
        self._events.append(("i", name,
                             time.monotonic() if ts is None else ts,
                             None, tid, args))

    def counter_sample(self, name: str, values: Dict[str, float],
                       ts: Optional[float] = None,
                       tid: Optional[int] = None) -> None:
        self._events.append(("C", name,
                             time.monotonic() if ts is None else ts,
                             None, self.TID_ENGINE if tid is None
                             else tid, dict(values)))

    def clear(self) -> None:
        self._events.clear()

    def snapshot(self) -> List[tuple]:
        return list(self._events)

    def to_chrome(self, process_name: str = "serving-engine",
                  pid: int = 1) -> dict:
        """Chrome trace-event JSON (the Perfetto/chrome://tracing
        format): timestamps in microseconds, ``X`` events carry
        ``dur``, ``C`` events carry their series in ``args``."""
        evs: List[dict] = [{
            "ph": "M", "pid": pid, "tid": 0, "ts": 0,
            "name": "process_name",
            "args": {"name": process_name}}]
        named_tids = {self.TID_QUEUE: "queue",
                      self.TID_ENGINE: "engine-loop"}
        tids_seen = set()
        for ph, name, ts, dur, tid, args in self.snapshot():
            ev = {"ph": ph, "name": name, "pid": pid, "tid": tid,
                  "ts": round(ts * 1e6, 3)}
            if ph == "X":
                ev["dur"] = round((dur or 0.0) * 1e6, 3)
            if ph == "i":
                ev["s"] = "t"       # thread-scoped instant
            if args:
                ev["args"] = args
            evs.append(ev)
            tids_seen.add(tid)
        for tid in sorted(tids_seen):
            evs.append({
                "ph": "M", "pid": pid, "tid": tid,
                "ts": 0, "name": "thread_name",
                "args": {"name": named_tids.get(tid, f"slot-{tid}")}})
        return {"traceEvents": evs, "displayTimeUnit": "ms"}


_CHROME_PHASES = {"X", "i", "C", "M", "B", "E", "b", "e", "n"}


def validate_chrome_trace(obj: Any) -> None:
    """Schema check for Chrome trace-event JSON (what Perfetto's
    legacy-JSON importer requires).  Raises ``ValueError`` on the
    first violation; also round-trips through ``json.dumps`` so a
    non-serializable ``args`` payload cannot slip through to a file
    Perfetto then refuses."""
    if not isinstance(obj, dict) or "traceEvents" not in obj:
        raise ValueError("trace must be an object with 'traceEvents'")
    evs = obj["traceEvents"]
    if not isinstance(evs, list):
        raise ValueError("'traceEvents' must be a list")
    for i, ev in enumerate(evs):
        if not isinstance(ev, dict):
            raise ValueError(f"event {i} is not an object")
        for key in ("ph", "name", "pid", "tid"):
            if key not in ev:
                raise ValueError(f"event {i} missing {key!r}")
        ph = ev["ph"]
        if ph not in _CHROME_PHASES:
            raise ValueError(f"event {i} has unknown phase {ph!r}")
        if "ts" not in ev:
            raise ValueError(f"event {i} missing 'ts'")
        if not isinstance(ev["ts"], (int, float)):
            raise ValueError(f"event {i} 'ts' is not numeric")
        if ph == "X":
            if not isinstance(ev.get("dur"), (int, float)):
                raise ValueError(
                    f"event {i}: complete ('X') event needs numeric "
                    f"'dur'")
            if ev["dur"] < 0:
                raise ValueError(f"event {i}: negative 'dur'")
        if "args" in ev and not isinstance(ev["args"], dict):
            raise ValueError(f"event {i} 'args' is not an object")
    json.dumps(obj)     # must be serializable as-is


# ---- per-request lifecycle facade -------------------------------------

class _Clock:
    """Host-side per-request lifecycle state (plain floats)."""

    __slots__ = ("arrival", "admitted", "first_token", "last_token",
                 "n_tokens", "priority")

    def __init__(self, arrival: float):
        self.arrival = arrival
        self.admitted: Optional[float] = None
        self.first_token: Optional[float] = None
        self.last_token: Optional[float] = None
        self.n_tokens = 0
        # priority class, learned at admission — the SLO watchdog needs
        # it at first-token and finish time, where the engine no longer
        # passes it
        self.priority: Optional[str] = None


class Telemetry:
    """One instance per serving engine (shareable with the serving job
    that owns it): a :class:`MetricsRegistry`, an :class:`EventLog`,
    and the request-lifecycle helpers the engine's state transitions
    call.  Always on — the opt-in part is only ``keep_request_stamps``
    (the legacy per-request raw stamp store behind the engine's
    ``record_timings``/``pop_request_timings`` shim), because per-uri
    retention is unbounded where the histograms are not.

    Metric-name convention: callers prefix by layer — ``zoo_engine_*``
    (ContinuousEngine), ``zoo_serving_*`` (ClusterServing),
    ``zoo_http_*`` (HttpFrontend) — so one Prometheus scrape can merge
    all three registries without collisions (docs/observability.md has
    the catalog)."""

    def __init__(self, events_capacity: int = 65536,
                 window: int = 8192, prefix: str = "zoo_engine_"):
        self.metrics = MetricsRegistry()
        self.events = EventLog(events_capacity)
        self.keep_request_stamps = False
        self._stamps: Dict[str, dict] = {}
        self._clocks: Dict[str, _Clock] = {}
        self._lock = threading.Lock()
        # Optional SloWatchdog (serving/flight.py) fed from the request
        # hooks below — it sees the SAME stamps the histograms and
        # spans record, so SLO judgements and percentiles agree by
        # construction.  None when nobody attached one.
        self.watchdog = None
        p = prefix
        m = self.metrics
        self.c_submitted = m.counter(
            p + "requests_submitted_total",
            "requests accepted by submit()")
        self.c_finished = m.counter(
            p + "requests_finished_total",
            "requests that emitted their final token")
        self.c_preempted = m.counter(
            p + "requests_preempted_total",
            "pool-dry preemptions back to the queue (re-admissions "
            "re-count in submitted)")
        self.c_errored = m.counter(
            p + "requests_errored_total",
            "requests failed in admission/prefill")
        self.c_tokens = m.counter(
            p + "tokens_emitted_total", "generated tokens")
        self.c_ticks = m.counter(
            p + "ticks_total", "engine device steps")
        self.c_chunks = m.counter(
            p + "prefill_chunks_total", "prefill chunks landed")
        self.c_jit_builds = m.counter(
            p + "jit_builds_total",
            "jitted-program cache misses (cold start only in steady "
            "state)")
        self.c_retraces = m.counter(
            p + "retraces_total",
            "retraces counted by TraceGuard regions wired to this "
            "telemetry")
        self.c_spec_proposed = m.counter(
            p + "spec_proposed_total",
            "draft tokens proposed to speculative verify rounds")
        self.c_spec_accepted = m.counter(
            p + "spec_accepted_total",
            "proposed draft tokens the target verify accepted "
            "(acceptance rate = accepted / proposed)")
        self.h_ttft = m.histogram(
            p + "ttft_seconds",
            "arrival -> first token (queueing + prefill)",
            window=window)
        self.h_tpot = m.histogram(
            p + "tpot_seconds",
            "inter-token gap between consecutive emitted tokens",
            window=window)
        self.h_queue_wait = m.histogram(
            p + "queue_wait_seconds", "arrival -> slot admission",
            window=window)
        # QoS front door (serving/frontdoor.py): per-priority-class
        # splits of queue wait and admission grants.  The registry is
        # label-free by design, so classes are name suffixes.  Always
        # registered (scrapes keep a stable catalog); only populated
        # when requests carry a priority.
        self.h_queue_wait_cls = {
            cls: m.histogram(
                p + f"queue_wait_seconds_{cls}",
                f"arrival -> slot admission, {cls}-class requests",
                window=window)
            for cls in ("interactive", "standard", "batch")}
        self.c_class_grants = {
            cls: m.counter(
                p + f"qos_grants_total_{cls}",
                f"slot admissions granted to {cls}-class requests")
            for cls in ("interactive", "standard", "batch")}
        self.h_tick = m.histogram(
            p + "tick_seconds", "engine step wall time",
            window=window)
        self.h_spec_accept = m.histogram(
            p + "spec_accept_len",
            "accepted draft tokens per row per verify round (0..k)",
            window=window)
        # exact acceptance-length counts (NOT windowed): the
        # simulator's calibration source — spec_acceptance() serializes
        # it into diagnostic bundles (docs/simulation.md)
        self._spec_accept_counts: Dict[int, int] = {}
        self._spec_rounds = 0
        # crash-recovery attempt counters (req_redispatched): uri ->
        # total placements, consumed into the request span at finish
        # so a trace shows which requests rode a replica death
        self._redispatch_attempts: Dict[str, int] = {}

    # -- request lifecycle (engine state transitions) ----------------

    def req_enqueued(self, uri: str) -> None:
        now = time.monotonic()
        with self._lock:
            self._clocks[uri] = _Clock(now)
            if self.keep_request_stamps:
                self._stamps[uri] = {"arrival": now, "token_times": []}
        self.c_submitted.inc()
        self.events.instant("enqueued", now, EventLog.TID_QUEUE,
                            {"uri": uri})

    def req_admitted(self, uri: str, slot: int,
                     prefilling: bool = False,
                     priority: Optional[str] = None) -> None:
        now = time.monotonic()
        with self._lock:
            ck = self._clocks.get(uri)
            if ck is None:      # engine driven without submit telemetry
                ck = self._clocks[uri] = _Clock(now)
            ck.admitted = now
            if priority is not None:
                ck.priority = priority
        self.h_queue_wait.record(now - ck.arrival)
        if priority is not None:
            h = self.h_queue_wait_cls.get(priority)
            if h is not None:
                h.record(now - ck.arrival)
                self.c_class_grants[priority].inc()
        if self.watchdog is not None:
            self.watchdog.observe_queue_wait(ck.priority, now - ck.arrival,
                                             uri)
        self.events.span("queue_wait", ck.arrival, now - ck.arrival,
                         EventLog.TID_QUEUE, {"uri": uri})
        args = {"uri": uri, "state": "PREFILLING" if prefilling
                else "DECODE"}
        if priority is not None:
            # replay (serving/sim/) needs per-class attribution from
            # the trace alone — the bundle's only per-request record
            args["priority"] = priority
        self.events.instant("admitted", now, slot, args)

    def req_token(self, uri: str, slot: int) -> None:
        now = time.monotonic()
        with self._lock:
            ck = self._clocks.get(uri)
            if ck is None:
                ck = self._clocks[uri] = _Clock(now)
            first = ck.first_token is None
            if first:
                ck.first_token = now
            else:
                gap = now - ck.last_token
            ck.last_token = now
            ck.n_tokens += 1
            if self.keep_request_stamps:
                st = self._stamps.get(uri)
                if st is not None:
                    st["token_times"].append(now)
        self.c_tokens.inc()
        if first:
            self.h_ttft.record(now - ck.arrival)
            self.events.instant("first_token", now, slot,
                                {"uri": uri})
            if self.watchdog is not None:
                self.watchdog.observe_ttft(ck.priority, now - ck.arrival,
                                           uri)
        else:
            self.h_tpot.record(gap)

    def req_finished(self, uri: str, slot: int,
                     n_tokens: Optional[int] = None) -> None:
        now = time.monotonic()
        with self._lock:
            ck = self._clocks.pop(uri, None)
        self.c_finished.inc()
        if self.watchdog is not None:
            # mean inter-token gap over the whole response — the SLO
            # view of TPOT (a single-token response has no gap)
            tpot = None
            if ck and ck.first_token is not None and ck.n_tokens > 1:
                tpot = (ck.last_token - ck.first_token) / (ck.n_tokens - 1)
            self.watchdog.observe_finish(ck.priority if ck else None,
                                         uri, tpot)
        start = ck.admitted if ck and ck.admitted is not None else now
        args = {"uri": uri,
                "tokens": n_tokens if n_tokens is not None
                else (ck.n_tokens if ck else 0)}
        with self._lock:
            attempts = self._redispatch_attempts.pop(uri, None)
        if attempts is not None:
            # the request survived a replica death: the span records
            # how many placements its at-least-once recovery took
            args["attempts"] = attempts
        self.events.span("request", start, now - start, slot, args)

    def req_preempted(self, uri: str, slot: int,
                      prefilling: bool = False) -> None:
        """Partial tokens are discarded and the request requeues: the
        clock keeps its ORIGINAL arrival (TTFT spans the preemption,
        like the legacy stamp store) but forgets its token history, so
        readmission re-records a first token."""
        now = time.monotonic()
        with self._lock:
            ck = self._clocks.get(uri)
            if ck is not None:
                ck.admitted = None
                ck.first_token = None
                ck.last_token = None
                ck.n_tokens = 0
            if self.keep_request_stamps:
                st = self._stamps.get(uri)
                if st is not None:
                    st["token_times"] = []
        self.c_preempted.inc()
        self.events.instant(
            "preempted", now, slot,
            {"uri": uri, "prefilling": prefilling})

    def req_errored(self, uri: str, exc: Optional[str] = None) -> None:
        with self._lock:
            self._clocks.pop(uri, None)
        if self.watchdog is not None:
            self.watchdog.drop(uri)
        self.c_errored.inc()
        self.events.instant("request_error", None, EventLog.TID_QUEUE,
                            {"uri": uri, "error": exc or ""})

    def req_redispatched(self, uri: str, attempt: int) -> None:
        """The broker re-placed this request on a surviving replica
        after its original replica died (at-least-once recovery).
        ``attempt`` is the TOTAL placement count (first submit = 1),
        surfaced in the request span at finish; the fleet-level
        ``zoo_router_requests_redispatched_total`` counter lives on
        the router, not here, so per-replica registries never
        double-count one fleet event."""
        with self._lock:
            self._redispatch_attempts[uri] = int(attempt)
            if len(self._redispatch_attempts) > 65536:
                self._redispatch_attempts.pop(
                    next(iter(self._redispatch_attempts)))
        self.events.instant("request_redispatched", None,
                            EventLog.TID_QUEUE,
                            {"uri": uri, "attempt": int(attempt)})

    def req_abandoned(self, uri: str, age_s: float) -> None:
        """A published result nobody ever collected was pruned — the
        request's TERMINAL event (it finished long ago; this marks the
        result's silent disposal, which used to be invisible)."""
        self.metrics.counter(
            "zoo_serving_requests_abandoned_total",
            "published results pruned uncollected after the ttl").inc()
        self.events.instant("request_abandoned", None,
                            EventLog.TID_QUEUE,
                            {"uri": uri, "age_s": round(age_s, 3)})

    # -- front door (serving/frontdoor.py) ---------------------------

    def req_cancelled(self, uri: str) -> None:
        """A live cancellation (explicit /v1/cancel or a mid-stream
        client disconnect) aborted the request ahead of the TTL path."""
        self.metrics.counter(
            "zoo_serving_requests_cancelled_total",
            "requests aborted by live cancellation (explicit cancel "
            "or mid-stream disconnect)").inc()
        if self.watchdog is not None:
            self.watchdog.drop(uri)
        self.events.instant("request_cancelled", None,
                            EventLog.TID_QUEUE, {"uri": uri})

    def stream_disconnect(self, uri: str) -> None:
        """An SSE write failed mid-stream — the client hung up; the
        cancel path fires next."""
        self.metrics.counter(
            "zoo_serving_stream_disconnects_total",
            "streaming clients that disconnected mid-response").inc()
        self.events.instant("stream_disconnect", None,
                            EventLog.TID_QUEUE, {"uri": uri})

    def backpressure_rejection(self) -> None:
        """An admission was refused because the bounded queue was full
        (the client got a 429 + Retry-After)."""
        self.metrics.counter(
            "zoo_serving_backpressure_rejections_total",
            "admissions refused with 429 under a full backlog").inc()

    def deadline_shed(self, uri: str) -> None:
        """A request's deadline passed while it waited in the queue, so
        admission shed it BEFORE prefill (terminal ``deadline_exceeded``
        error).  Distinct from the supervisor's in-flight give-up
        (``zoo_router_requests_given_up_total``): this request never
        cost a single engine tick."""
        with self._lock:
            self._clocks.pop(uri, None)
        if self.watchdog is not None:
            self.watchdog.drop(uri)
        self.metrics.counter(
            "zoo_engine_deadline_admission_sheds_total",
            "requests shed at admission because their deadline had "
            "already passed (never reached prefill)").inc()
        self.events.instant("deadline_shed", None, EventLog.TID_QUEUE,
                            {"uri": uri})

    def brownout_shed(self, priority: str) -> None:
        """The front door refused an admission because the brownout
        ladder browned its class out (429 + level-scaled Retry-After)."""
        self.metrics.counter(
            f"zoo_brownout_shed_total_{priority}",
            f"admissions refused with 429 because the brownout ladder "
            f"browned the {priority} class out").inc()

    def brownout_transition(self, level: int, prev: int) -> None:
        """The brownout controller moved the ladder — a trace instant
        (one per transition, not per tick) plus the transition
        counter; the current level rides the flight ring / metrics
        gauge, not this hook."""
        self.metrics.counter(
            "zoo_brownout_transitions_total",
            "brownout ladder level changes (either direction)").inc()
        self.events.instant(
            "brownout_level", None, EventLog.TID_QUEUE,
            {"level": int(level), "prev": int(prev)})

    # -- engine loop -------------------------------------------------

    def tick(self, start: float, dur: float,
             samples: Dict[str, float]) -> None:
        """One engine step: a span on the engine-loop track, a tick
        wall-time histogram sample, and a Perfetto counter track of
        the per-tick gauges (queue depth, row mix, free blocks, ...).
        Every value arrives as a host int/float the engine already
        computed — recording one costs two deque appends."""
        self.c_ticks.inc()
        self.h_tick.record(dur)
        self.events.span("tick", start, dur, EventLog.TID_ENGINE,
                         samples or None)
        if samples:
            self.events.counter_sample("engine", samples, start)

    def spec_round(self, proposed: int, accepted: int,
                   accept_lens) -> None:
        """One speculative verify round across the live rows: counter
        food for the acceptance rate (accepted/proposed, both
        cumulative), the per-row acceptance-length histogram, and an
        instant on the engine track so a Perfetto timeline shows how
        acceptance moves with the workload."""
        self.c_spec_proposed.inc(proposed)
        self.c_spec_accepted.inc(accepted)
        with self._lock:
            self._spec_rounds += 1
            for n in accept_lens:
                self.h_spec_accept.record(float(n))
                k = int(n)
                self._spec_accept_counts[k] = \
                    self._spec_accept_counts.get(k, 0) + 1
        self.events.instant("spec_round", None, EventLog.TID_ENGINE,
                            {"proposed": proposed,
                             "accepted": accepted})

    def spec_acceptance(self) -> Dict[str, Any]:
        """Serializable speculative-acceptance distribution: exact
        counts of accepted draft tokens per row per verify round since
        engine start (no window, no percentile loss).  ``counts`` keys
        are strings so the section round-trips through JSON bundles
        unchanged; the simulator calibrates its stochastic acceptance
        process from this (serving/sim/, docs/simulation.md)."""
        with self._lock:
            counts = {str(k): v for k, v in
                      sorted(self._spec_accept_counts.items())}
            rounds = self._spec_rounds
        total = sum(counts.values())
        mean = (sum(int(k) * v for k, v in counts.items()) / total
                if total else 0.0)
        return {"rounds": rounds, "samples": total,
                "mean_accept_len": round(mean, 6), "counts": counts}

    def jit_build(self, program: str, key: Any) -> None:
        """A jitted-program cache MISS (new (program, shape) variant):
        cold start builds these eagerly; one appearing in steady state
        is the retrace the trace timeline exists to catch."""
        self.c_jit_builds.inc()
        self.events.instant("jit_build", None, EventLog.TID_ENGINE,
                            {"program": program, "key": repr(key)})

    def retrace(self, label: str, count: int, region: str) -> None:
        """TraceGuard-observed compile-cache growth (lint/runtime.py
        feeds this when a guard is built with ``telemetry=``)."""
        self.c_retraces.inc(count)
        self.events.instant("retrace", None, EventLog.TID_ENGINE,
                            {"callable": label, "new_traces": count,
                             "region": region})

    def pool_event(self, kind: str, **info) -> None:
        """BlockPool hook (``event_cb``): evictions / allocation
        failures as instants on the engine track.  Called while the
        engine holds its pool lock — this only appends, it never locks
        or calls back."""
        self.events.instant("pool_" + kind, None, EventLog.TID_ENGINE,
                            info or None)

    # -- legacy stamp store (record_timings shim) --------------------

    def pop_request_stamps(self) -> Dict[str, dict]:
        """Drain the raw per-request stamp store (the engine's
        ``pop_request_timings`` back-compat surface): uri ->
        {"arrival": t, "token_times": [...]}."""
        with self._lock:
            out = self._stamps
            self._stamps = {}
        return out

    # -- maintenance ---------------------------------------------------

    def reset_windows(self) -> None:
        """Clear every histogram's sliding window (cumulative counts
        stand) — benchmarks call this after warmup so compile time
        never pollutes a percentile."""
        for _, metric in self.metrics.items():
            if isinstance(metric, WindowHistogram):
                metric.reset_window()

    def dump_trace(self, path: Optional[str] = None,
                   process_name: str = "serving-engine") -> dict:
        """Chrome trace-event JSON of the event ring (validated before
        return); with ``path``, also written to disk.  Load it at
        https://ui.perfetto.dev or chrome://tracing."""
        trace = self.events.to_chrome(process_name=process_name)
        validate_chrome_trace(trace)
        if path is not None:
            with open(path, "w") as f:
                json.dump(trace, f)
        return trace

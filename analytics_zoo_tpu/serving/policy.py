"""Pure scheduler policy for the serving engine — every admission /
grant / preemption / budget-billing DECISION the continuous-batching
engine makes, as side-effect-free functions over plain data.

This module is the split the multi-replica router (ROADMAP) and the
discrete-event simulator (``serving/sim/``, docs/simulation.md) both
need: ``continuous.py`` executes these decisions against real device
state, the simulator executes the SAME functions against modelled
state, and the equivalence tests in ``tests/test_sim.py`` pin that the
two produce identical decision sequences from the same request
schedule.

Decision points (each names the engine call site it was extracted
from):

* ``grant_rank`` — prefill-chunk grant ordering
  (``ContinuousEngine._grant_rank``): FIFO by admission sequence
  without QoS; aged priority class first, FIFO within a class, with it.
* ``pick_victim`` — pool-dry preemption choice
  (``ContinuousEngine._pick_victim``): PREFILLING rows first (they
  lost no emitted tokens), latest admission among candidates (earliest
  admissions keep strict forward progress, so preemption terminates).
* ``plan_chunks`` — token-budget billing for a chunked tick
  (``ContinuousEngine._chunked_tick`` / ``_spec_chunked_tick``): every
  decode row is billed ``per_row_cost`` positions (1 plain, ``k+1``
  speculative), the remainder grants prefill chunks in grant order,
  each capped by the widest chunk bucket.
* ``select_subqueue`` / ``stride_charge`` — the weighted
  deficit/stride admission order (``WeightedWaitQueue.popleft``).
* ``route_request`` — multi-replica placement (the ``ClusterServing``
  router thread, ``n_replicas > 1``): role match first (prefill/decode
  disaggregation, constant when no replica carries a role), then
  prefix locality (deepest cached-prefix reuse per the fleet
  PrefixDirectory, constant when no directory runs), then pool
  pressure, then per-class SLO goodput, then least-loaded with a
  deterministic round-robin cursor tie-break.
* ``plan_pool_resize`` — the elastic-pool step
  (``ContinuousEngine.maybe_autoresize``): grow under pool pressure,
  hold while SLO-degraded, hand blocks back when the pool runs slack.
* ``plan_brownout`` — the overload degradation ladder
  (``ClusterServing`` broker loop + ``serving/sim`` models): sustained
  breach walks the fleet one level up (shed batch -> clamp standard ->
  drop speculative rounds -> interactive-only); cooldown below the
  recovery threshold walks it back one level at a time, so the
  controller cannot flap (docs/serving_qos.md "Overload & brownout").

Everything here is stdlib-only ON PURPOSE: the simulator (and the
bare-box ``debug.py --replay`` path) import this file with no numpy,
no jax, no serving stack.  Time is always an explicit parameter —
``time.monotonic`` never appears in a decision function, which is what
makes replay deterministic.
"""

from __future__ import annotations

import collections
import time
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: Monotonically bumped whenever a decision function's observable
#: behavior changes.  The simulator stamps it into every event log so
#: a golden-trace mismatch distinguishes "policy changed" from "sim
#: drifted".
SCHEDULER_POLICY_VERSION = 4

#: Priority classes, best-first.  The wire encodes a priority as its
#: index in this tuple (the input queue transports ints, not strings);
#: aging promotes a waiting request one index at a time toward 0.
PRIORITIES: Tuple[str, ...] = ("interactive", "standard", "batch")

DEFAULT_WEIGHTS: Dict[str, float] = {
    "interactive": 8.0, "standard": 4.0, "batch": 1.0}


@dataclass(frozen=True)
class QosPolicy:
    """Admission policy knobs: per-class weights and the aging bound.

    ``weights`` are stride-scheduling shares — a class with weight 8
    gets ~8x the admission slots of weight 1 under contention, it does
    NOT strictly preempt it.  ``aging_s`` is the starvation bound: a
    request that has waited ``aging_s`` is treated as one class better
    (both for its subqueue's stride and for prefill-grant ordering),
    two intervals promotes two classes, so batch work can wait at most
    ``2 * aging_s`` before it competes as interactive.  ``aging_s <= 0``
    disables promotion (weights alone still prevent total starvation:
    a never-popped subqueue's virtual pass stands still while every
    other queue's advances, so it eventually holds the minimum)."""

    weights: Dict[str, float] = field(
        default_factory=lambda: dict(DEFAULT_WEIGHTS))
    aging_s: float = 30.0

    def __post_init__(self):
        for cls in PRIORITIES:
            w = self.weights.get(cls, DEFAULT_WEIGHTS[cls])
            if w <= 0:
                raise ValueError(f"qos weight for {cls!r} must be > 0, "
                                 f"got {w}")
            self.weights.setdefault(cls, DEFAULT_WEIGHTS[cls])

    def class_rank(self, priority: str, waited_s: float) -> int:
        """Aged class index (0 best).  Unknown priorities rank as
        ``standard`` rather than raising — the pump must never die on a
        stale wire value."""
        try:
            idx = PRIORITIES.index(priority)
        except ValueError:
            idx = PRIORITIES.index("standard")
        if self.aging_s > 0 and waited_s > 0:
            idx -= int(waited_s // self.aging_s)
        return max(0, idx)

    def effective_weight(self, priority: str, waited_s: float) -> float:
        return self.weights[PRIORITIES[self.class_rank(priority,
                                                       waited_s)]]


# ---------------------------------------------------------------------------
# decision functions (pure: plain data in, decision out)
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# multi-replica routing (ClusterServing n_replicas > 1)
# ---------------------------------------------------------------------------

#: A replica whose per-class SLO goodput (SloWatchdog.status) falls
#: below this fraction is avoided while any healthy peer exists.
ROUTER_GOODPUT_FLOOR = 0.9

#: A paged replica reporting fewer allocatable blocks than this is
#: treated as pool-pressured (the alloc-fail streak catches sustained
#: pressure; this floor catches it one tick earlier).
ROUTER_MIN_ALLOCATABLE = 1

#: Replica specializations under prefill/decode disaggregation
#: (``ServingConfig.replica_roles``).  ``None`` means symmetric — the
#: replica takes either phase, which is also every replica's role when
#: disaggregation is off (PR 14 behavior, bit-identical ranks).
REPLICA_ROLES: Tuple[str, ...] = ("prefill", "decode")


@dataclass(frozen=True)
class ReplicaSignals:
    """One replica's live routing signals, as plain data — the server
    snapshots these from each replica's engine/watchdog per routed
    request, the simulator fabricates them, and ``route_request``
    never sees anything richer.

    ``queue_depth`` is the replica's total uncompleted load (routed-
    but-unclaimed + engine-waiting + engine-resident).
    ``allocatable_blocks`` is ``BlockPool.allocatable()`` (``None``
    for an arena-mode replica: no pool, never pool-pressured).
    ``goodput`` maps priority class -> SLO goodput fraction from the
    replica's watchdog (``None``/missing class reads as healthy —
    a replica that served nothing yet must not read as degraded).
    ``role`` is the replica's disaggregation specialization
    (``"prefill"`` / ``"decode"`` / ``None`` = symmetric, takes
    either phase).
    ``prefix_blocks`` is THIS request's estimated reuse depth on the
    replica — leading prompt blocks the fleet ``PrefixDirectory``
    says it already holds (HBM index or host KV store), i.e. blocks
    it would not re-prefill.  Per-request, unlike every other field:
    the router fills it from ``PrefixDirectory.match_depths`` after
    snapshotting the rest.  0 (the default, and always when no
    directory runs) keeps ranks bit-identical to the locality-blind
    router."""

    replica: int
    live: bool = True
    queue_depth: int = 0
    allocatable_blocks: Optional[int] = None
    alloc_fail_streak: int = 0
    goodput: Optional[Dict[str, float]] = None
    role: Optional[str] = None
    prefix_blocks: int = 0
    #: Seconds since the replica's pump thread last stamped its
    #: heartbeat (``None`` = no supervisor running / pump never beat).
    #: A liveness input for ``replica_dead``, NOT a rank term —
    #: ``route_request`` ignores it, so fleets without a supervisor
    #: keep bit-identical ranks.
    heartbeat_age_s: Optional[float] = None


def replica_pressured(sig: ReplicaSignals,
                      min_allocatable: int = ROUTER_MIN_ALLOCATABLE
                      ) -> bool:
    """Pool pressure: a live alloc-fail streak, or an allocatable-block
    count below the floor.  Arena replicas are never pressured."""
    if sig.alloc_fail_streak > 0:
        return True
    return (sig.allocatable_blocks is not None
            and sig.allocatable_blocks < min_allocatable)


def replica_degraded(sig: ReplicaSignals, priority: Optional[str],
                     goodput_floor: float = ROUTER_GOODPUT_FLOOR
                     ) -> bool:
    """SLO degradation for THIS request's class: the replica's
    watchdog goodput for the class sits below the floor."""
    if not sig.goodput:
        return False
    cls = priority if priority in PRIORITIES else "standard"
    return sig.goodput.get(cls, 1.0) < goodput_floor


def route_request(replicas: Sequence[ReplicaSignals],
                  priority: Optional[str] = None,
                  rr_cursor: int = 0,
                  *,
                  phase: Optional[str] = None,
                  goodput_floor: float = ROUTER_GOODPUT_FLOOR,
                  min_allocatable: int = ROUTER_MIN_ALLOCATABLE
                  ) -> Optional[int]:
    """Place one request on a replica.  Returns the chosen replica id,
    or ``None`` when no replica is live (the caller's requeue/error
    path).

    Rank order, best first:

    0. role match FIRST, when ``phase`` is given ("prefill"/"decode"
       — the disaggregated router passes the request's current phase):
       a replica whose ``role`` is ``None`` or equals the phase
       outranks a role-mismatched one.  The term is a preference, not
       a partition — with every same-role replica dead (mid
       ``kill_pump`` drain) traffic falls through to the other role
       rather than failing, and with no roles configured anywhere the
       term is constant, leaving ranks bit-identical to the symmetric
       router;
    1. deepest ``prefix_blocks`` (prefix locality, tiered-KV fleets):
       the replica already holding the most leading prompt blocks —
       device index or host store — skips that much re-prefill, which
       dwarfs a few queue positions.  Locality sits BELOW role match
       (a disaggregated prefill replica is still the right place to
       prefill even when a decode replica holds the prefix) and ABOVE
       pool pressure (the reuse frees more blocks than the pressured
       admission would need).  With no directory every signal carries
       the 0 default, the term is constant, and ranks are
       bit-identical to the locality-blind router;
    2. not pool-pressured (``replica_pressured``) — a dry pool means
       admission would preempt or stall, so pressure outranks depth;
    3. not SLO-degraded FOR THIS CLASS (``replica_degraded``) — a
       replica failing interactive targets still takes batch work;
    4. least ``queue_depth`` (least-loaded);
    5. round-robin distance from ``rr_cursor`` — the DETERMINISTIC
       tie-break: equal replicas take turns as the caller advances the
       cursor per routed request, never a coin flip.

    Every signal equal (cold start) this degrades to exactly
    least-loaded round-robin, the documented fallback."""
    live = [r for r in replicas if r.live]
    if not live:
        return None
    n = max(r.replica for r in live) + 1

    def rank(r: ReplicaSignals):
        mismatch = (phase is not None and r.role is not None
                    and r.role != phase)
        return (mismatch,
                -r.prefix_blocks,
                replica_pressured(r, min_allocatable),
                replica_degraded(r, priority, goodput_floor),
                r.queue_depth,
                (r.replica - rr_cursor) % n)

    return min(live, key=rank).replica


# ---------------------------------------------------------------------------
# fleet supervision: declare-dead / retry-budget / pick-retry-target
# (ClusterServing supervisor + serving/sim FleetModel faults)
# ---------------------------------------------------------------------------

def replica_dead(heartbeat_age_s: Optional[float],
                 miss_s: float) -> bool:
    """Liveness verdict for the supervisor: a pump that has not
    stamped its heartbeat for ``miss_s`` seconds is declared dead
    (wedged tick, frozen device, or a thread that silently exited).
    ``miss_s <= 0`` disables heartbeat-based death (escaped pump
    exceptions still declare death explicitly); ``None`` age means no
    beat was ever observed — never declared dead on silence alone,
    the pump may simply not have started."""
    if miss_s <= 0 or heartbeat_age_s is None:
        return False
    return heartbeat_age_s > miss_s


def plan_redispatch(*, attempt: int, retry_budget: int,
                    cancelled: bool = False,
                    age_s: float = 0.0,
                    deadline_s: float = 0.0) -> str:
    """Terminal-or-retry decision for one lost in-flight request (its
    replica was declared dead).  Returns one of:

    - ``"cancel"`` — the client already cancelled it; surface the
      terminal *cancelled*, never resurrect it on a survivor;
    - ``"error"`` — retry budget exhausted (``attempt`` placements
      already happened and ``attempt >= retry_budget``) or the
      request's deadline passed (``deadline_s > 0`` and
      ``age_s > deadline_s``): terminal error, at-least-once gives up
      loudly rather than looping forever;
    - ``"retry"`` — re-dispatch to a survivor (the caller increments
      the attempt counter and emits the client-visible ``restart``).

    ``attempt`` counts placements so far (first submit = 1);
    ``retry_budget`` is the MAX total placements a request may
    consume."""
    if cancelled:
        return "cancel"
    if attempt >= max(1, retry_budget):
        return "error"
    if deadline_s > 0 and age_s > deadline_s:
        return "error"
    return "retry"


def pick_retry_target(replicas: Sequence[ReplicaSignals],
                      priority: Optional[str] = None,
                      rr_cursor: int = 0,
                      *,
                      exclude: Sequence[int] = (),
                      phase: Optional[str] = None) -> Optional[int]:
    """Placement for a re-dispatched request: ``route_request`` over
    the survivors, never the replicas in ``exclude`` (the dead source,
    or a handoff destination that already timed out) even if their
    signals still read live — the supervisor may re-dispatch before
    the death propagates into a fresh snapshot.  Returns ``None``
    when no eligible replica remains (the caller parks or errors)."""
    bad = set(exclude)
    eligible = [r for r in replicas if r.replica not in bad]
    return route_request(eligible, priority, rr_cursor, phase=phase)


def plan_handoff_recovery(*, age_s: float, timeout_s: float,
                          retries: int, retry_budget: int) -> str:
    """Two-phase handoff: the prefill source holds the exported chain
    until the decode side acks adoption.  Given a pending (un-acked)
    handoff's age, decide ``"wait"`` (not yet timed out), ``"retry"``
    (timed out, budget left: re-dispatch to an alternate decode
    replica), or ``"give_up"`` (timed out past the budget: the caller
    errors the request terminally).  ``timeout_s <= 0`` disables the
    timeout — pending entries wait for the ack forever (the pre-
    supervisor fire-and-forget behavior)."""
    if timeout_s <= 0 or age_s <= timeout_s:
        return "wait"
    if retries < max(0, retry_budget):
        return "retry"
    return "give_up"


# ---------------------------------------------------------------------------
# elastic per-replica pool sizing (ContinuousEngine.maybe_autoresize)
# ---------------------------------------------------------------------------

#: Allocatable fraction below which the elastic planner grows the pool
#: (the one-tick-early analog of the alloc-fail streak).
POOL_GROW_FRAC = 0.125

#: Allocatable fraction above which the planner hands blocks back —
#: conservatively high so the pool breathes, not oscillates.
POOL_SHRINK_FRAC = 0.5


def plan_pool_resize(*, n_blocks: int, allocatable: int,
                     alloc_fail_streak: int, step: int, floor: int,
                     ceiling: int,
                     goodput: Optional[Dict[str, float]] = None,
                     goodput_floor: float = ROUTER_GOODPUT_FLOOR,
                     low_frac: float = POOL_GROW_FRAC,
                     high_frac: float = POOL_SHRINK_FRAC) -> int:
    """One elastic-pool step for a paged replica, as a signed block
    delta (positive = grow, negative = shrink, 0 = hold).  Pure policy:
    the engine executes the delta at the eviction boundary
    (``BlockPool.shrink`` stops at the first referenced block, so the
    delta here is a TARGET the executor may clamp).

    Decision order:

    1. grow ``step`` (clamped to ``ceiling``) under pool pressure — a
       live alloc-fail streak, or allocatable at/below
       ``low_frac * n_blocks``;
    2. hold while any priority class's goodput sits below
       ``goodput_floor`` — shrinking a replica that is already missing
       SLOs can only make it worse;
    3. shrink ``step`` when allocatable sits at/above
       ``high_frac * n_blocks`` and the result stays at/above
       ``floor`` (the engine's minimum working set);
    4. otherwise hold."""
    if step <= 0:
        return 0
    if alloc_fail_streak > 0 or allocatable <= low_frac * n_blocks:
        return min(step, max(0, ceiling - n_blocks))
    if goodput and any(g < goodput_floor for g in goodput.values()):
        return 0
    if allocatable >= high_frac * n_blocks and n_blocks - step >= floor:
        return -step
    return 0


# ---------------------------------------------------------------------------
# overload brownout ladder (docs/serving_qos.md "Overload & brownout")
# ---------------------------------------------------------------------------

#: Deepest degradation level: interactive-only serving.  Levels are
#: cumulative — every restriction of level N-1 stays active at N.
BROWNOUT_MAX_LEVEL = 4


@dataclass(frozen=True)
class BrownoutPolicy:
    """Knobs for the overload degradation ladder.

    A tick is a *breach* when any still-admitted class's windowed
    goodput sits below ``goodput_floor``, the admission backlog reaches
    ``queue_high``, the paged pool's alloc-fail streak reaches
    ``alloc_streak_high``, or (``tick_s_high > 0``) the engine tick
    duration exceeds ``tick_s_high``.  ``enter_ticks`` consecutive
    breaches ascend ONE level; descending needs ``exit_ticks``
    consecutive *recovered* ticks — backlog at or below
    ``queue_recover_frac * queue_high`` with no alloc pressure and
    every admitted class back above the floor.  The asymmetric gap
    between breach and recovery is the hysteresis band: a fleet
    hovering at the breach threshold holds its level instead of
    flapping.  ``standard_max_new`` is the level-2 per-request token
    clamp for ``standard`` class (0 disables the clamp)."""

    goodput_floor: float = 0.9
    queue_high: int = 64
    queue_recover_frac: float = 0.5
    alloc_streak_high: int = 4
    tick_s_high: float = 0.0
    enter_ticks: int = 3
    exit_ticks: int = 6
    standard_max_new: int = 16

    def __post_init__(self):
        if not 0.0 < self.goodput_floor <= 1.0:
            raise ValueError(f"goodput_floor must be in (0, 1], got "
                             f"{self.goodput_floor}")
        if self.queue_high < 1:
            raise ValueError(f"queue_high must be >= 1, got "
                             f"{self.queue_high}")
        if not 0.0 <= self.queue_recover_frac <= 1.0:
            raise ValueError(f"queue_recover_frac must be in [0, 1], "
                             f"got {self.queue_recover_frac}")
        if self.enter_ticks < 1 or self.exit_ticks < 1:
            raise ValueError("enter_ticks/exit_ticks must be >= 1")


@dataclass(frozen=True)
class BrownoutState:
    """The controller's whole memory, as plain immutable data: the
    current ladder level plus the consecutive breach/clear streaks the
    hysteresis gates count.  Callers thread it through
    ``plan_brownout`` and persist nothing else, so replays are exact."""

    level: int = 0
    breach_streak: int = 0
    clear_streak: int = 0


def brownout_classes(level: int) -> Tuple[str, ...]:
    """Priority classes still admitted at ``level`` (best-first).
    Shedding is strictly worst-class-first: batch goes at level 1,
    standard at level 4, interactive NEVER sheds."""
    if level >= 4:
        return ("interactive",)
    if level >= 1:
        return ("interactive", "standard")
    return PRIORITIES


def brownout_admit(level: int, priority: Optional[str]) -> bool:
    """Admission verdict for one request under the ladder.  Unknown
    priorities rank as ``standard`` (the ``class_rank`` convention —
    a stale wire value must degrade, not crash)."""
    cls = priority if priority in PRIORITIES else "standard"
    return cls in brownout_classes(level)


def brownout_max_new(level: int, priority: Optional[str],
                     max_new: int, clamp: int) -> int:
    """Level-2 token clamp: ``standard``-class requests are capped at
    ``clamp`` new tokens (never raised, never below 1).  Interactive
    is untouched at every level; batch is already shed by level 2."""
    if level < 2 or clamp <= 0:
        return max_new
    cls = priority if priority in PRIORITIES else "standard"
    if cls != "standard":
        return max_new
    return max(1, min(max_new, clamp))


def brownout_spec_enabled(level: int) -> bool:
    """Level-3 switch: speculative rounds are pure overhead when the
    fleet is saturated (draft ticks burn budget the verify can't
    repay), so the ladder drops them before it sheds standard."""
    return level < 3


def brownout_breached(policy: BrownoutPolicy, level: int, *,
                      goodput: Optional[Dict[str, float]] = None,
                      queue_depth: int = 0,
                      alloc_fail_streak: int = 0,
                      tick_s: Optional[float] = None) -> bool:
    """One tick's breach verdict.  Only classes the CURRENT level still
    admits are judged — a shed class's collapsing goodput must not
    hold the ladder up after the shedding already handled it."""
    if queue_depth >= policy.queue_high:
        return True
    if alloc_fail_streak >= policy.alloc_streak_high:
        return True
    if (policy.tick_s_high > 0 and tick_s is not None
            and tick_s > policy.tick_s_high):
        return True
    if goodput:
        for cls in brownout_classes(level):
            g = goodput.get(cls)
            if g is not None and g < policy.goodput_floor:
                return True
    return False


def brownout_recovered(policy: BrownoutPolicy, level: int, *,
                       goodput: Optional[Dict[str, float]] = None,
                       queue_depth: int = 0,
                       alloc_fail_streak: int = 0,
                       tick_s: Optional[float] = None) -> bool:
    """One tick's recovery verdict — deliberately STRICTER than "not
    breached": the backlog must fall to ``queue_recover_frac`` of the
    breach threshold, not merely below it.  The gap is the hysteresis
    band that keeps the ladder from flapping at the boundary."""
    if queue_depth > policy.queue_recover_frac * policy.queue_high:
        return False
    if alloc_fail_streak > 0:
        return False
    if (policy.tick_s_high > 0 and tick_s is not None
            and tick_s > policy.tick_s_high):
        return False
    if goodput:
        for cls in brownout_classes(level):
            g = goodput.get(cls)
            if g is not None and g < policy.goodput_floor:
                return False
    return True


def plan_brownout(policy: BrownoutPolicy, state: BrownoutState, *,
                  goodput: Optional[Dict[str, float]] = None,
                  queue_depth: int = 0,
                  alloc_fail_streak: int = 0,
                  tick_s: Optional[float] = None) -> BrownoutState:
    """One controller step: fold this tick's overload signals into the
    ladder state.  Pure and deterministic — the live broker
    (``ClusterServing``), ``EngineModel``, and ``FleetModel`` all call
    exactly this function, so the golden-brownout scenario replays the
    production controller byte-for-byte.

    Transitions move ONE level per decision: ``enter_ticks``
    consecutive breaches ascend, ``exit_ticks`` consecutive recovered
    ticks descend, and a tick that is neither (inside the hysteresis
    band) resets BOTH streaks — holding the level is the default
    outcome, flapping requires the signals themselves to oscillate
    across the full band."""
    kw = dict(goodput=goodput, queue_depth=queue_depth,
              alloc_fail_streak=alloc_fail_streak, tick_s=tick_s)
    if brownout_breached(policy, state.level, **kw):
        streak = state.breach_streak + 1
        if (streak >= policy.enter_ticks
                and state.level < BROWNOUT_MAX_LEVEL):
            return BrownoutState(level=state.level + 1)
        return BrownoutState(level=state.level, breach_streak=streak)
    if brownout_recovered(policy, state.level, **kw):
        streak = state.clear_streak + 1
        if streak >= policy.exit_ticks and state.level > 0:
            return BrownoutState(level=state.level - 1)
        return BrownoutState(level=state.level, clear_streak=streak)
    return BrownoutState(level=state.level)


def grant_rank(policy: Optional[QosPolicy], priority: Optional[str],
               waited_s: float, admit_seq: int):
    """Prefill-grant sort key for the chunked ticks.  QoS off: the
    admission sequence number — bit-identical FIFO to the
    pre-front-door engine (the parity guarantee).  QoS on: aged
    priority class first, FIFO within a class, so an interactive
    prompt's chunks land ahead of a batch prompt admitted earlier
    while aging still bounds how long batch can be outranked."""
    if policy is None:
        return admit_seq
    if priority is None:
        return (policy.class_rank("standard", 0.0), admit_seq)
    return (policy.class_rank(priority, waited_s), admit_seq)


def pick_victim(rows: Iterable[Tuple[int, str, int]]) -> int:
    """Pool-dry preemption choice over resident rows, each a
    ``(slot, state, admit_seq)`` triple.  PREFILLING rows first: they
    lost no emitted tokens and requeue cheaply; among candidates,
    always the LATEST admission (earliest admissions keep strict
    forward progress, so repeated preemption terminates)."""
    rows = list(rows)
    pre = [r for r in rows if r[1] == "PREFILLING"]
    return max(pre or rows, key=lambda r: r[2])[0]


def plan_chunks(budget: int, per_row_cost: int, n_decode: int,
                prefill: Sequence[Tuple[int, int]],
                chunk_cap: int) -> Tuple[List[Tuple[int, int]], bool]:
    """Token-budget billing for one chunked tick.  Every decode row is
    billed ``per_row_cost`` positions (1 plain, ``speculation_k + 1``
    speculative); the remainder grants prefill chunks to ``prefill`` —
    ``(slot, remaining_prompt_tokens)`` pairs ALREADY in grant order
    (``grant_rank``) — each chunk capped at ``chunk_cap`` (the widest
    chunk bucket).  Returns ``(chunks, stalled)`` where ``chunks`` is
    ``[(slot, chunk_len), ...]`` and ``stalled`` flags a tick whose
    budget was fully consumed by decode rows while prefill work
    waited (the engine's ``prefill_stall_ticks`` counter)."""
    remaining = budget - per_row_cost * n_decode
    chunks: List[Tuple[int, int]] = []
    for slot, rem in prefill:
        if remaining <= 0:
            break
        clen = min(rem, remaining, chunk_cap)
        if clen <= 0:
            continue
        chunks.append((slot, clen))
        remaining -= clen
    return chunks, bool(prefill) and not chunks


def select_subqueue(entries: Iterable[Tuple[Tuple[str, str], float,
                                            float]]):
    """The weighted-stride pop decision: given ``(key, pass, head
    enqueue time)`` for every NONEMPTY subqueue, return the key to
    serve — minimum virtual pass, oldest head entry on ties (two idle
    subqueues re-armed at the same clock must pop FIFO)."""
    best_key = None
    best_rank: Optional[Tuple[float, float]] = None
    for key, pv, enq_t in entries:
        rank = (pv, enq_t)
        if best_rank is None or rank < best_rank:
            best_key, best_rank = key, rank
    return best_key


def stride_charge(policy: QosPolicy, priority: str,
                  waited_s: float) -> float:
    """Virtual-pass advance for serving one entry: ``1 / effective
    weight``.  Aging shrinks a promoted subqueue's stride, so a
    starved batch tenant catches up instead of merely not falling
    further behind."""
    return 1.0 / policy.effective_weight(priority, waited_s)


class WeightedWaitQueue:
    """Weighted deficit/stride scheduler over (priority class, tenant)
    FIFO subqueues, exposing the exact ``collections.deque`` surface
    the engine uses for ``self._waiting`` (``append`` / ``appendleft``
    / ``popleft`` / ``remove`` / iteration / ``len``) so QoS admission
    is a constructor-time swap, not a call-site rewrite.

    Entries are the engine's ``_Req`` tuples; the scheduler reads only
    their ``priority`` / ``tenant`` / ``enq_t`` attributes (absent
    attributes degrade to standard/shared/now).  Each subqueue carries
    a virtual ``pass``; ``popleft`` serves the minimum-pass nonempty
    subqueue (``select_subqueue``) and advances its pass by
    ``stride_charge`` — equal passes per unit work means admission
    slots divide proportionally to weight across classes and EQUALLY
    across tenants inside a class (each (class, tenant) pair is its
    own subqueue at the class weight).

    ``appendleft`` is the engine's requeue path (preemption, blocked
    admission): the entry returns to the FRONT of its own subqueue and
    the pop's stride charge is refunded, so bouncing off a full pool
    costs a tenant nothing.  All call sites run under the engine lock —
    no internal locking.

    ``clock`` injects the time source (default ``time.monotonic``):
    the simulator drives the SAME scheduler on virtual time, which is
    what makes its event logs reproducible byte-for-byte."""

    def __init__(self, policy: QosPolicy, clock=time.monotonic):
        self.policy = policy
        self._now = clock
        self._queues: "collections.OrderedDict[Tuple[str, str], collections.deque]" = \
            collections.OrderedDict()
        self._pass: Dict[Tuple[str, str], float] = {}
        self._clock = 0.0
        self._charges: Dict[int, Tuple[Tuple[str, str], float]] = {}
        self._n = 0

    @staticmethod
    def _key(req) -> Tuple[str, str]:
        return (getattr(req, "priority", "standard"),
                getattr(req, "tenant", ""))

    def _subqueue(self, req) -> collections.deque:
        key = self._key(req)
        q = self._queues.get(key)
        if q is None:
            q = self._queues[key] = collections.deque()
        if not q:
            # (re)arming an idle subqueue: clamp its pass to the global
            # virtual clock, or a long-idle tenant would bank credit
            # and burst past everyone on return
            self._pass[key] = max(self._pass.get(key, 0.0), self._clock)
        return q

    def append(self, req) -> None:
        """Enqueue at the tail — except that a deadline-carrying entry
        (``req.deadline_t > 0``, monotonic seconds) ranks earliest-
        deadline-first WITHIN its subqueue: it slots ahead of the first
        entry with a later deadline or none at all (no-deadline entries
        read as infinitely patient).  Traffic without deadlines takes
        the plain tail append, so FIFO order — and with it the QoS-off
        parity guarantee — is bit-identical when nobody sends one."""
        q = self._subqueue(req)
        dl = getattr(req, "deadline_t", 0.0) or 0.0
        if dl > 0 and q:
            for i, other in enumerate(q):
                od = getattr(other, "deadline_t", 0.0) or 0.0
                if od <= 0 or od > dl:
                    q.insert(i, req)
                    self._n += 1
                    return
        q.append(req)
        self._n += 1

    def appendleft(self, req) -> None:
        self._subqueue(req).appendleft(req)
        self._n += 1
        ent = self._charges.pop(id(req), None)
        if ent is not None:
            key, prior_pass = ent
            if key == self._key(req):
                self._pass[key] = prior_pass    # requeue is cost-neutral

    def popleft(self):
        if self._n == 0:
            raise IndexError("pop from an empty WeightedWaitQueue")
        now = self._now()
        best_key = select_subqueue(
            (key, self._pass[key], getattr(q[0], "enq_t", now))
            for key, q in self._queues.items() if q)
        q = self._queues[best_key]
        req = q.popleft()
        self._n -= 1
        pv = self._pass[best_key]
        self._clock = max(self._clock, pv)
        waited = now - getattr(req, "enq_t", now)
        self._pass[best_key] = pv + stride_charge(
            self.policy, best_key[0], waited)
        if len(self._charges) > 4096:   # requeues long consumed
            self._charges.clear()
        self._charges[id(req)] = (best_key, pv)
        return req

    def remove(self, req) -> None:
        key = self._key(req)
        q = self._queues.get(key)
        if q is None:
            raise ValueError("WeightedWaitQueue.remove(x): x not in queue")
        q.remove(req)       # raises ValueError like deque when absent
        self._n -= 1

    def __iter__(self):
        for q in self._queues.values():
            yield from q

    def __len__(self) -> int:
        return self._n

    def __bool__(self) -> bool:
        return self._n > 0

    def depths(self) -> Dict[Tuple[str, str], int]:
        """Per-(class, tenant) backlog snapshot (telemetry food)."""
        return {k: len(q) for k, q in self._queues.items() if q}

"""analytics_zoo_tpu — a TPU-native rebuild of Analytics Zoo.

A unified analytics + AI framework with the capabilities of Analytics Zoo
(Intel's Spark/BigDL platform: see SURVEY.md), re-designed from scratch for
TPU hardware on JAX/XLA:

- ``common``   — context bootstrap (the ``init_orca_context`` analog: builds a
                 `jax.sharding.Mesh` over TPU devices instead of a
                 SparkContext over executors), config tree, logging.
- ``parallel`` — mesh specs, partition rules, collectives; ring attention for
                 sequence parallelism (no reference counterpart; TPU-first).
- ``data``     — ``XShards``-style sharded data layer with host->HBM prefetch
                 (replaces orca.data / FeatureSet / ImageSet / TextSet).
- ``learn``    — Estimator API (``fit/evaluate/predict``) compiling to a
                 single pjit train step (replaces BigDL DistriOptimizer +
                 Orca's TF/torch/horovod backends).
- ``models``   — built-in model zoo (NCF, Wide&Deep, BERT, forecasters, ...).
- ``zouwu``    — time-series toolkit (forecasters + AutoTS).
- ``automl``   — HPO engine (replaces Ray-Tune-based search).
- ``serving``  — continuous-batching inference server + queue clients
                 (replaces Flink/Redis Cluster Serving).
- ``frames``   — DataFrame-style NNEstimator/NNModel (replaces NNFrames).

Reference parity map: SURVEY.md §2 component inventory.
"""

from analytics_zoo_tpu.version import __version__

from analytics_zoo_tpu.common.context import (
    init_context,
    init_orca_context,
    stop_orca_context,
    OrcaContext,
)

__all__ = [
    "__version__",
    "init_context",
    "init_orca_context",
    "stop_orca_context",
    "OrcaContext",
]

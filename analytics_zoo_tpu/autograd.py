"""Define-by-expression autograd API.

Reference parity: zoo/pipeline/api/autograd/ + pyzoo/zoo/pipeline/api/
autograd.py — `Variable` expressions (abs, mean, clip, mm, ...) composed
into `CustomLoss` / custom layers, which the reference lowered to a BigDL
graph.  Here a Variable composes a pure jnp function, so a CustomLoss is
just a jittable `(preds, targets) -> scalar` that fuses into the Estimator's
train step, and a CustomLayer is a flax module — JAX *is* the autograd, so
this module is only the expression-building surface.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = [
    "Variable", "Parameter", "CustomLoss", "CustomLayer",
    "abs", "mean", "sum", "clip", "square", "sqrt", "exp", "log", "pow",
    "maximum", "minimum", "mm", "dot", "stack", "expand_dims", "squeeze",
    "softmax", "softsign", "softplus", "l2_normalize", "epsilon",
]

class Variable:
    """A symbolic array expression: composes a pure function env -> jnp."""

    def __init__(self, fn: Callable[[Dict[int, Any]], Any],
                 params: Tuple["Parameter", ...] = (),
                 name: Optional[str] = None):
        self._fn = fn
        self._params = tuple(params)
        self.name = name

    @staticmethod
    def placeholder(name: Optional[str] = None) -> "Variable":
        v = Variable(None, name=name)
        v._fn = lambda env: env[id(v)]
        return v

    # -- evaluation ------------------------------------------------------

    def eval(self, env: Dict["Variable", Any]) -> jnp.ndarray:
        return self._fn({id(k): val for k, val in env.items()})

    def _lower(self, env_by_id):
        return self._fn(env_by_id)

    # -- operator algebra ------------------------------------------------

    @staticmethod
    def _lift(other) -> Callable:
        if isinstance(other, Variable):
            return other._fn, other._params
        return (lambda env: other), ()

    def _binop(self, other, op) -> "Variable":
        ofn, op_params = Variable._lift(other)
        return Variable(lambda env: op(self._fn(env), ofn(env)),
                        self._params + tuple(op_params))

    def __add__(self, o):
        return self._binop(o, lambda a, b: a + b)

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, lambda a, b: a - b)

    def __rsub__(self, o):
        return self._binop(o, lambda a, b: b - a)

    def __mul__(self, o):
        return self._binop(o, lambda a, b: a * b)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, lambda a, b: a / b)

    def __rtruediv__(self, o):
        return self._binop(o, lambda a, b: b / a)

    def __pow__(self, o):
        return self._binop(o, lambda a, b: a ** b)

    def __neg__(self):
        return Variable(lambda env: -self._fn(env), self._params)

    def __getitem__(self, idx):
        return Variable(lambda env: self._fn(env)[idx], self._params)

    def _unary(self, op) -> "Variable":
        return Variable(lambda env: op(self._fn(env)), self._params)


class Parameter(Variable):
    """A trainable weight usable inside an expression (ref: autograd
    Parameter).  Materializes as a flax param when the expression is wrapped
    in a :class:`CustomLayer`."""

    _count = 0

    def __init__(self, shape: Sequence[int], init_weight=None,
                 init: Callable = None, name: Optional[str] = None):
        Parameter._count += 1
        self.shape = tuple(shape)
        self.init_weight = init_weight
        self.initializer = init or nn.initializers.lecun_normal() \
            if len(shape) >= 2 else (init or nn.initializers.zeros)
        pname = name or f"parameter_{Parameter._count}"
        super().__init__(None, name=pname)
        self._params = (self,)
        self._fn = lambda env: env[id(self)]


# ---------------------------------------------------------------------------
# expression functions (module-level, numpy axis semantics)
# ---------------------------------------------------------------------------


def _wrap_unary(op):
    def f(v: Variable, *args, **kw):
        if not isinstance(v, Variable):
            return op(v, *args, **kw)
        return Variable(lambda env: op(v._fn(env), *args, **kw), v._params)
    return f


abs = _wrap_unary(jnp.abs)                      # noqa: A001
square = _wrap_unary(jnp.square)
sqrt = _wrap_unary(jnp.sqrt)
exp = _wrap_unary(jnp.exp)
log = _wrap_unary(jnp.log)
softmax = _wrap_unary(jax.nn.softmax)
softsign = _wrap_unary(jax.nn.soft_sign)
softplus = _wrap_unary(jax.nn.softplus)
expand_dims = _wrap_unary(jnp.expand_dims)
squeeze = _wrap_unary(jnp.squeeze)


def mean(v: Variable, axis=None, keepdims: bool = False) -> Variable:
    return v._unary(lambda a: jnp.mean(a, axis=axis, keepdims=keepdims))


def sum(v: Variable, axis=None, keepdims: bool = False) -> Variable:  # noqa: A001
    return v._unary(lambda a: jnp.sum(a, axis=axis, keepdims=keepdims))


def clip(v: Variable, min_value, max_value) -> Variable:
    return v._unary(lambda a: jnp.clip(a, min_value, max_value))


def pow(v: Variable, p) -> Variable:  # noqa: A001
    return v._unary(lambda a: a ** p)


def maximum(a: Variable, b) -> Variable:
    return a._binop(b, jnp.maximum)


def minimum(a: Variable, b) -> Variable:
    return a._binop(b, jnp.minimum)


def mm(a: Variable, b: Variable, axes: Optional[Sequence[int]] = None) \
        -> Variable:
    """Batched matmul (ref: autograd.mm).  `axes` follows the reference's
    batch-dot convention; default contracts last axis of a with first
    non-batch axis of b."""
    if axes is not None:
        def op(x, y):
            return jax.lax.batch_matmul(
                jnp.moveaxis(x, axes[0], -1), jnp.moveaxis(y, axes[1], -2))
    else:
        def op(x, y):
            return x @ y
    return a._binop(b, op)


def dot(a: Variable, b: Variable, axes=None) -> Variable:
    return mm(a, b, axes)


def stack(vs: Sequence[Variable], axis: int = 1) -> Variable:
    params: List[Parameter] = []
    for v in vs:
        params.extend(v._params)
    return Variable(
        lambda env: jnp.stack([v._fn(env) for v in vs], axis=axis),
        tuple(params))


def l2_normalize(v: Variable, axis: int = -1) -> Variable:
    return v._unary(
        lambda a: a / (jnp.linalg.norm(a, axis=axis, keepdims=True) + 1e-12))


def epsilon() -> float:
    return 1e-7


# ---------------------------------------------------------------------------
# CustomLoss / CustomLayer
# ---------------------------------------------------------------------------


class CustomLoss:
    """Loss from a Variable expression (ref: autograd.CustomLoss).

    Two constructions:
      * ``CustomLoss(loss_var, y_true=..., y_pred=...)`` — a prebuilt
        expression over two placeholders;
      * ``CustomLoss.from_function(fn)`` — ``fn(y_true, y_pred) -> Variable``.

    Instances are callable ``(preds, targets) -> scalar`` — the signature
    every Estimator/keras ``compile`` accepts — and reduce with a mean over
    any non-scalar result (reference semantics: per-sample loss averaged).
    """

    def __init__(self, loss_var: Variable, y_true: Variable,
                 y_pred: Variable):
        self.loss_var = loss_var
        self.y_true = y_true
        self.y_pred = y_pred

    @staticmethod
    def from_function(fn: Callable[[Variable, Variable], Variable]) \
            -> "CustomLoss":
        yt, yp = Variable.placeholder("y_true"), Variable.placeholder("y_pred")
        return CustomLoss(fn(yt, yp), yt, yp)

    def __call__(self, preds, targets):
        out = self.loss_var.eval({self.y_true: targets, self.y_pred: preds})
        return jnp.mean(out)


def custom_loss(fn: Callable[[Variable, Variable], Variable]) -> CustomLoss:
    """Decorator/helper: `loss = custom_loss(lambda yt, yp: mean(abs(yt-yp)))`."""
    return CustomLoss.from_function(fn)


class CustomLayer(nn.Module):
    """Layer from a Variable expression with :class:`Parameter` weights
    (ref: autograd CustomLayer/Lambda-with-Parameter).  Usable inside
    keras Sequential/Model like any other layer."""

    out_var: Variable = None
    in_vars: Tuple[Variable, ...] = ()

    @nn.compact
    def __call__(self, x, train: bool = False):
        xs = x if isinstance(x, (list, tuple)) else [x]
        if len(xs) != len(self.in_vars):
            raise ValueError(
                f"CustomLayer takes {len(self.in_vars)} inputs, got {len(xs)}")
        env = {id(v): a for v, a in zip(self.in_vars, xs)}
        # dedupe: a Parameter used twice in the expression must register once
        unique = {id(p): p for p in self.out_var._params}
        for p in unique.values():
            if p.init_weight is not None:
                w = self.param(p.name,
                               lambda rng, sw=p.init_weight: jnp.asarray(sw))
            else:
                w = self.param(p.name, p.initializer, p.shape)
            env[id(p)] = w
        return self.out_var._lower(env)


# register CustomLayer for keras symbolic dispatch
from analytics_zoo_tpu.keras.engine import symbolic as _symbolic  # noqa: E402

CustomLayer = _symbolic(CustomLayer)

"""tpulint analyzer — stdlib-``ast`` staging/tracing rules for JAX.

Generic linters see Python; the expensive bugs in this codebase live in
the seam between host Python and staged XLA.  A ``float()`` on a traced
value is a blocking device sync, an ``if`` on a traced array is a
``TracerBoolConversionError`` at best and a silent per-call retrace at
worst, and a missing ``donate_argnums`` doubles the HBM a train step
holds.  Every rule here encodes one of those seams.

The analysis is two-tier, which is what keeps the false-positive rate
workable on a codebase that interleaves host orchestration with jitted
calls (``serving/continuous.py`` is 1.4k lines of exactly that):

1.  **Module index.**  Build lexical scopes, a local call graph, and
    the set of *traced* functions: seeded from ``jax.jit`` / ``pjit``
    decorations and call sites (including ``jax.jit(partial(f, ...))``
    and aliases like ``fn = a if cond else b``), transform/combinator
    arguments (``lax.scan`` bodies, ``jax.vmap`` targets,
    ``custom_vjp`` rules, ``pallas_call`` kernels), and methods of
    ``nn.Module`` subclasses — then closed over intra-module calls and
    lexical nesting.  A param-staticness fixpoint then separates array
    params from config flags: a param bound by ``partial(fn,
    use_sample=...)`` at the jit site, named in ``static_argnames``,
    carrying a literal default, or receiving only static expressions at
    every local call site is *static*, so ``if use_sample:`` is a
    compile-time branch, not a tracer branch.
2.  **Rule pass.**  Walk each function with that context (traced?,
    which names hold device values?, loop depth) and emit findings.

The analyzed code is never imported; everything here is stdlib.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

RULES: Dict[str, str] = {
    "TZ000": "file could not be parsed",
    "TZ001": "host-device sync inside traced code or a per-iteration host loop",
    "TZ002": "Python `if`/`while` branches on a traced value",
    "TZ003": "`jnp` ops inside a Python loop over a dynamic/shape-dependent range",
    "TZ004": "`jax.jit` constructed per call (inside a loop, under trace, or immediately invoked)",
    "TZ005": "mutable or array-valued default argument on a jitted entry point",
    "TZ006": "host RNG (`np.random`/`random`) inside traced code",
    "TZ007": "`jnp.asarray`/`jnp.array` without explicit dtype in a serving hot path",
    "TZ008": "train-step-shaped jit without `donate_argnums`",
    # TZ1xx: concurrency family — implemented in lockflow.py, listed
    # here so --list-rules/--select/--rules see one catalog.
    "TZ101": "write to a lock-guarded attribute outside its owning lock",
    "TZ102": "blocking call (device sync/sleep/IO) while holding a lock",
    "TZ103": "callback under lock is not provably record-only",
    "TZ104": "inconsistent lock-acquisition order (deadlock cycle)",
    "TZ105": "double-acquire of a non-reentrant Lock",
    "TZ106": "manually acquired lock not released on an early exit path",
    "TZ107": "shared mutable state touched from a threaded entry point "
             "with no lock held",
    "TZ108": "Condition.wait without an enclosing predicate re-check loop",
}

# Files where implicit-dtype conversions (TZ007) matter: the request
# path, where a promotion changes the compiled signature per call.
DEFAULT_HOT_PATHS: Tuple[str, ...] = (
    "serving/",
    "models/lm.py",
    "models/speculative.py",
    "ops/",
    "learn/inference_model.py",
)

_JIT_CALLS = {"jax.jit", "jit", "pjit", "jax.pjit", "nn.jit", "shard_map",
              "jax.experimental.shard_map.shard_map"}
_PARTIAL_CALLS = {"partial", "functools.partial"}
_DEVICE_GET = {"jax.device_get", "device_get"}
_NP_CONVERT = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
               "np.ascontiguousarray"}
# TZ007 targets -> index of the positional dtype argument
_JNP_CONVERT = {"jnp.asarray": 1, "jnp.array": 1, "jax.numpy.asarray": 1,
                "jax.numpy.array": 1, "jnp.zeros": 1, "jnp.ones": 1,
                "jax.numpy.zeros": 1, "jax.numpy.ones": 1,
                "jnp.full": 2, "jax.numpy.full": 2,
                "jnp.empty": 1, "jax.numpy.empty": 1}
# Calls whose *result* is a host/static value even on device inputs.
_STATIC_CALLS = {"len", "str", "isinstance", "getattr", "hasattr", "type",
                 "tuple", "sorted", "zip", "enumerate", "range", "dict",
                 "frozenset", "repr", "format",
                 "jnp.ndim", "jnp.shape", "jnp.size", "jnp.result_type",
                 "jnp.promote_types", "jnp.dtype", "jax.eval_shape",
                 "np.dtype", "jnp.issubdtype", "np.issubdtype"}
_DEVICE_PREFIXES = ("jnp.", "jax.numpy.", "jax.lax.", "lax.", "jax.random.",
                    "jax.nn.", "jax.scipy.", "jsp.", "jax.ops.")
_DEVICE_EXACT = {"jax.device_put"}
# Combinators/transforms whose function-valued arguments are traced.
_COMBINATOR_TAILS = {"scan", "while_loop", "fori_loop", "cond", "switch",
                     "associative_scan", "map", "checkpoint", "remat",
                     "vmap", "pmap", "grad", "value_and_grad", "custom_vjp",
                     "custom_jvp", "pallas_call", "defvjp", "defjvp"}
_COMBINATOR_BARE = {"vmap", "pmap", "grad", "value_and_grad", "checkpoint",
                    "remat", "pallas_call", "custom_vjp", "custom_jvp"}
_STATIC_ANNOTATIONS = {"bool", "str", "int"}
_TRAIN_STEP_RE = re.compile(r"(train|update|fit|sgd|optimizer)_?step", re.I)
_SUPPRESS_RE = re.compile(
    r"#\s*tpulint:\s*disable(?P<next>-next-line)?\s*=\s*"
    r"(?P<rules>all|[A-Z]{2}\d{3}(?:\s*,\s*[A-Z]{2}\d{3})*)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    text: str = ""      # stripped source line — the baseline match key

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


def _dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_device_call(dotted: Optional[str]) -> bool:
    if not dotted or dotted in _STATIC_CALLS:
        return False
    return dotted in _DEVICE_EXACT or dotted.startswith(_DEVICE_PREFIXES)


_COMBINATOR_ROOTS = {"jax", "lax", "jnp", "nn", "pl", "flax", "linen"}


def _is_combinator(dotted: Optional[str]) -> bool:
    if not dotted or "tree" in dotted:       # jax.tree.map runs on host
        return False
    tail = dotted.rsplit(".", 1)[-1]
    if tail not in _COMBINATOR_TAILS:
        return False
    if "." not in dotted:
        return tail in _COMBINATOR_BARE
    # require a JAX-ish root so executor.map / pool.map stay host code
    root = dotted.split(".", 1)[0]
    return root in _COMBINATOR_ROOTS or tail in ("defvjp", "defjvp")


def _literal_default(node: Optional[ast.AST]) -> bool:
    """Defaults that hash/compare as compile-time constants."""
    if node is None:
        return False
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.UnaryOp) and isinstance(node.operand, ast.Constant):
        return True
    if isinstance(node, ast.Tuple):
        return all(_literal_default(e) for e in node.elts)
    return False


def _bad_default(node: Optional[ast.AST]) -> bool:
    """Defaults that are mutable or array-valued (TZ005)."""
    if node is None:
        return False
    if isinstance(node, (ast.List, ast.Dict, ast.Set)):
        return True
    if isinstance(node, ast.Call):
        return True        # np.zeros(...), jnp.asarray(...), dict(), ...
    return False


class _Class:
    def __init__(self, name: str, node: ast.ClassDef, scope: "_Scope"):
        self.name = name
        self.node = node
        self.scope = scope
        self.bases: List[str] = [d for d in (_dotted(b) for b in node.bases) if d]
        self.is_module = False      # nn.Module-ish, filled in later


class _Func:
    def __init__(self, node: ast.AST, qualname: str, scope: "_Scope",
                 cls: Optional[_Class]):
        self.node = node
        self.qualname = qualname
        self.name = node.name
        self.scope = scope          # the scope of this function's *body*
        self.cls = cls
        self.traced = False
        self.seed = False           # direct jit/transform boundary
        self.seed_static: Set[str] = set()   # params bound statically at the seed
        self.edges_in: List[Tuple[Optional["_Func"], ast.Call]] = []
        self.edges_out: List["_Func"] = []
        self.device_names: Set[str] = set()

        a = node.args
        pos = list(a.posonlyargs) + list(a.args)
        self.params: List[str] = [p.arg for p in pos if p.arg not in ("self", "cls")]
        self.kwonly: List[str] = [p.arg for p in a.kwonlyargs]
        self.all_params = self.params + self.kwonly
        self.literal_static: Set[str] = set()
        defaults = [None] * (len(pos) - len(a.defaults)) + list(a.defaults)
        for p, d in zip(pos, defaults):
            if _literal_default(d):
                self.literal_static.add(p.arg)
        for p, d in zip(a.kwonlyargs, a.kw_defaults):
            if _literal_default(d):
                self.literal_static.add(p.arg)
        for p in pos + list(a.kwonlyargs):
            ann = getattr(p, "annotation", None)
            if isinstance(ann, ast.Name) and ann.id in _STATIC_ANNOTATIONS:
                self.literal_static.add(p.arg)
        # optimistically static; the fixpoint demotes (seeds are pinned there)
        self.static: Dict[str, bool] = {p: True for p in self.all_params}
        self.bad_defaults: List[ast.AST] = [d for d in list(a.defaults) +
                                            [k for k in a.kw_defaults if k]
                                            if _bad_default(d)]


class _Scope:
    def __init__(self, kind: str, parent: Optional["_Scope"], qualname: str,
                 func: Optional[_Func] = None, cls: Optional[_Class] = None):
        self.kind = kind            # "module" | "class" | "function"
        self.parent = parent
        self.qualname = qualname
        self.func = func            # the _Func whose body this scope is
        self.cls = cls
        self.funcs: Dict[str, _Func] = {}
        self.classes: Dict[str, _Class] = {}
        self.aliases: Dict[str, Tuple[str, ...]] = {}

    def chain(self) -> List["_Scope"]:
        out, s = [], self
        while s is not None:
            out.append(s)
            s = s.parent
        return out


class _ModuleIndex:
    """Pass 1+2: scopes, seeds, call graph, traced closure, staticness."""

    def __init__(self, tree: ast.Module):
        self.module_scope = _Scope("module", None, "")
        self.funcs: List[_Func] = []
        self._collect(tree.body, self.module_scope, cls=None)
        self._mark_modules()
        self._apply_methods = self._collect_apply_methods(tree)
        self._index(tree.body, self.module_scope)
        self._close_traced()
        self._staticness_fixpoint()
        self._compute_device_names()

    # -- pass 1: scopes / defs / aliases ------------------------------------
    def _collect(self, body: Sequence[ast.stmt], scope: _Scope,
                 cls: Optional[_Class]) -> None:
        for st in body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{scope.qualname}.{st.name}" if scope.qualname else st.name
                fn = _Func(st, qual, None, cls if scope.kind == "class" else None)
                child = _Scope("function", scope, qual, func=fn)
                fn.scope = child
                scope.funcs[st.name] = fn
                self.funcs.append(fn)
                self._collect(st.body, child, cls=None)
            elif isinstance(st, ast.ClassDef):
                qual = f"{scope.qualname}.{st.name}" if scope.qualname else st.name
                c = _Class(st.name, st, None)
                child = _Scope("class", scope, qual, cls=c)
                c.scope = child
                scope.classes[st.name] = c
                self._collect(st.body, child, cls=c)
            elif isinstance(st, ast.Assign) and len(st.targets) == 1 and \
                    isinstance(st.targets[0], ast.Name):
                tgt = st.targets[0].id
                if isinstance(st.value, ast.Name):
                    scope.aliases[tgt] = (st.value.id,)
                elif isinstance(st.value, ast.IfExp) and \
                        isinstance(st.value.body, ast.Name) and \
                        isinstance(st.value.orelse, ast.Name):
                    scope.aliases[tgt] = (st.value.body.id, st.value.orelse.id)
            if isinstance(st, (ast.If, ast.For, ast.While, ast.With, ast.Try)):
                for attr in ("body", "orelse", "finalbody"):
                    self._collect(getattr(st, attr, []) or [], scope, cls)
                for h in getattr(st, "handlers", []) or []:
                    self._collect(h.body, scope, cls)

    def _mark_modules(self) -> None:
        classes: List[_Class] = []

        def walk(s: _Scope) -> None:
            classes.extend(s.classes.values())
            for f in s.funcs.values():
                walk(f.scope)
            for c in s.classes.values():
                walk(c.scope)

        walk(self.module_scope)
        by_name = {c.name: c for c in classes}
        changed = True
        while changed:
            changed = False
            for c in classes:
                if c.is_module:
                    continue
                for b in c.bases:
                    tail = b.rsplit(".", 1)[-1]
                    if "Module" in tail or (b in by_name and by_name[b].is_module):
                        c.is_module = True
                        changed = True

    # -- name resolution ----------------------------------------------------
    def _resolve_func(self, name: str, scope: _Scope,
                      _depth: int = 0) -> Optional[_Func]:
        if _depth > 8:
            return None
        for s in scope.chain():
            if s.kind == "class":
                continue            # class bodies are not in method scope
            if name in s.funcs:
                return s.funcs[name]
            if name in s.aliases:
                for tgt in s.aliases[name]:
                    r = self._resolve_func(tgt, s, _depth + 1)
                    if r is not None:
                        return r
                return None
        return None

    def _resolve_method(self, name: str, scope: _Scope) -> Optional[_Func]:
        for s in scope.chain():
            if s.kind == "class" and name in s.funcs:
                return s.funcs[name]
            if s.func is not None and s.func.cls is not None:
                owner = s.func.cls.scope
                if name in owner.funcs:
                    return owner.funcs[name]
        return None

    def _call_targets(self, node: ast.AST, scope: _Scope,
                      ) -> List[Tuple[_Func, Set[str]]]:
        """Functions a jit/transform argument expression refers to, plus
        the param names it binds statically (partial kwargs)."""
        if isinstance(node, ast.Name):
            f = self._resolve_func(node.id, scope)
            return [(f, set())] if f else []
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and node.value.id == "self":
            f = self._resolve_method(node.attr, scope)
            return [(f, set())] if f else []
        if isinstance(node, ast.Call) and _dotted(node.func) in _PARTIAL_CALLS \
                and node.args:
            inner = self._call_targets(node.args[0], scope)
            bound = {kw.arg for kw in node.keywords if kw.arg}
            return [(f, s | bound) for f, s in inner]
        if isinstance(node, ast.IfExp):
            return (self._call_targets(node.body, scope) +
                    self._call_targets(node.orelse, scope))
        return []

    # -- pass 2: seeds + call edges -----------------------------------------
    def _seed(self, fn: Optional[_Func], static: Set[str],
              jit_call: Optional[ast.Call]) -> None:
        if fn is None:
            return
        fn.seed = True
        fn.seed_static |= static
        if jit_call is not None:
            for kw in jit_call.keywords:
                if kw.arg == "static_argnames":
                    for n in ast.walk(kw.value):
                        if isinstance(n, ast.Constant) and isinstance(n.value, str):
                            fn.seed_static.add(n.value)
                elif kw.arg == "static_argnums":
                    for n in ast.walk(kw.value):
                        if isinstance(n, ast.Constant) and isinstance(n.value, int):
                            if 0 <= n.value < len(fn.params):
                                fn.seed_static.add(fn.params[n.value])

    def _module_traced_method(self, fn: _Func, node: ast.AST) -> bool:
        """Which methods of an ``nn.Module`` subclass are traced?  Not
        all of them — wrapper classes (Keras-style nets) hang host
        orchestration (`fit`, `predict`, I/O) off the same class.  The
        trace-shaped ones are ``__call__``/``setup``, ``@nn.compact``
        methods, and anything referenced as an ``apply`` method
        (``model.apply(..., method=Cls.meth)``) anywhere in the module;
        the call-graph closure pulls in their helpers."""
        if fn.name in ("__call__", "setup"):
            return True
        for dec in node.decorator_list:
            d = _dotted(dec)
            if d and d.rsplit(".", 1)[-1] in ("compact", "remat", "jit"):
                return True
        return fn.qualname in self._apply_methods

    def _collect_apply_methods(self, tree: ast.Module) -> Set[str]:
        """Qualnames referenced as ``Cls.meth`` in any ``*.apply(...)``
        call (positionally or via ``method=``)."""
        out: Set[str] = set()
        classes: Dict[str, str] = {}

        def walk_scope(s: _Scope) -> None:
            for name, c in s.classes.items():
                classes.setdefault(name, c.scope.qualname)
                walk_scope(c.scope)
            for f in s.funcs.values():
                walk_scope(f.scope)

        walk_scope(self.module_scope)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if not d or not d.endswith(".apply"):
                continue
            for arg in list(node.args) + [k.value for k in node.keywords]:
                ad = _dotted(arg)
                if ad and "." in ad:
                    cls, meth = ad.rsplit(".", 1)
                    if cls in classes:
                        out.add(f"{classes[cls]}.{meth}")
        return out

    def _index(self, body: Sequence[ast.stmt], scope: _Scope) -> None:
        for st in body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = scope.funcs[st.name]
                for dec in st.decorator_list:
                    d = _dotted(dec)
                    if d in _JIT_CALLS or _is_combinator(d):
                        self._seed(fn, set(), None)
                    elif isinstance(dec, ast.Call):
                        dc = _dotted(dec.func)
                        if dc in _JIT_CALLS or _is_combinator(dc):
                            self._seed(fn, set(), dec)
                        elif dc in _PARTIAL_CALLS and dec.args:
                            inner = _dotted(dec.args[0])
                            if inner in _JIT_CALLS or _is_combinator(inner):
                                self._seed(fn, set(), dec)
                if fn.cls is not None and fn.cls.is_module and \
                        self._module_traced_method(fn, st):
                    fn.seed = True
                self._index(st.body, fn.scope)
                continue
            if isinstance(st, ast.ClassDef):
                self._index(st.body, scope.classes[st.name].scope)
                continue
            if isinstance(st, (ast.If, ast.While, ast.For, ast.AsyncFor,
                               ast.With, ast.AsyncWith, ast.Try)):
                # scan only the header expressions here; the nested
                # statement lists recurse so defs land in the right scope
                for child in ast.iter_child_nodes(st):
                    if isinstance(child, ast.expr):
                        self._scan_calls(child, scope)
                for item in getattr(st, "items", []) or []:
                    self._scan_calls(item.context_expr, scope)
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(st, attr, None)
                    if isinstance(sub, list):
                        self._index(sub, scope)
                for h in getattr(st, "handlers", []) or []:
                    self._index(h.body, scope)
            else:
                self._scan_calls(st, scope)

    def _scan_calls(self, node: ast.AST, scope: _Scope) -> None:
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            d = _dotted(sub.func)
            if d in _JIT_CALLS and sub.args:
                for fn, static in self._call_targets(sub.args[0], scope):
                    self._seed(fn, static, sub)
            elif _is_combinator(d):
                for arg in list(sub.args) + [k.value for k in sub.keywords]:
                    for fn, static in self._call_targets(arg, scope):
                        self._seed(fn, static, None)
            elif d is not None and scope.func is not None:
                callee = None
                if "." not in d:
                    callee = self._resolve_func(d, scope)
                elif d.startswith("self.") and d.count(".") == 1:
                    callee = self._resolve_method(d.split(".")[1], scope)
                if callee is not None:
                    callee.edges_in.append((scope.func, sub))
                    scope.func.edges_out.append(callee)

    # -- traced closure -----------------------------------------------------
    def _close_traced(self) -> None:
        work = [f for f in self.funcs if f.seed]
        for f in work:
            f.traced = True
        while work:
            f = work.pop()
            nxt = list(f.edges_out)
            nxt.extend(f.scope.funcs.values())      # nested defs trace too
            for g in nxt:
                if not g.traced:
                    g.traced = True
                    work.append(g)

    # -- param staticness ---------------------------------------------------
    def _expr_static(self, expr: ast.AST, scope: _Scope) -> bool:
        if isinstance(expr, ast.Constant):
            return True
        if isinstance(expr, ast.Attribute):
            v = expr.value
            return isinstance(v, ast.Name) and v.id in ("self", "cls")
        if isinstance(expr, ast.Name):
            for s in scope.chain():
                f = s.func
                if f is None:
                    continue
                if expr.id in f.static:
                    return (not f.traced) or f.static[expr.id]
            if self._resolve_func(expr.id, scope) is not None:
                return True
            return False
        if isinstance(expr, ast.UnaryOp):
            return self._expr_static(expr.operand, scope)
        if isinstance(expr, (ast.BoolOp,)):
            return all(self._expr_static(v, scope) for v in expr.values)
        if isinstance(expr, ast.BinOp):
            return self._expr_static(expr.left, scope) and \
                self._expr_static(expr.right, scope)
        if isinstance(expr, ast.Compare):
            return self._expr_static(expr.left, scope) and \
                all(self._expr_static(c, scope) for c in expr.comparators)
        if isinstance(expr, ast.IfExp):
            return all(self._expr_static(e, scope)
                       for e in (expr.test, expr.body, expr.orelse))
        if isinstance(expr, ast.Tuple):
            return all(self._expr_static(e, scope) for e in expr.elts)
        return False

    def _staticness_fixpoint(self) -> None:
        for f in self.funcs:
            if not f.traced:
                continue
            if f.seed:
                for p in f.all_params:
                    f.static[p] = (p in f.seed_static or
                                   p in f.literal_static)
            elif not f.edges_in:
                # combinator bodies / unresolved callees: params are the
                # array boundary unless literally defaulted
                for p in f.all_params:
                    f.static[p] = p in f.literal_static
        changed = True
        rounds = 0
        while changed and rounds < 10:
            changed, rounds = False, rounds + 1
            for f in self.funcs:
                if not f.traced or f.seed or not f.edges_in:
                    continue
                for caller, call in f.edges_in:
                    if caller is None:
                        continue
                    bound: Dict[str, ast.AST] = {}
                    if any(isinstance(a, ast.Starred) for a in call.args):
                        bound = {p: ast.Call(func=ast.Name(id="_", ctx=ast.Load()),
                                             args=[], keywords=[])
                                 for p in f.params}      # unknown -> dynamic
                    else:
                        for p, a in zip(f.params, call.args):
                            bound[p] = a
                        for kw in call.keywords:
                            if kw.arg:
                                bound[kw.arg] = kw.value
                    for p, a in bound.items():
                        if p in f.static and f.static[p] and \
                                p not in f.literal_static and \
                                not self._expr_static(a, caller.scope):
                            f.static[p] = False
                            changed = True

    # -- device-name dataflow ----------------------------------------------
    def _compute_device_names(self) -> None:
        for f in self.funcs:
            self._device_pass(f.scope, f.node.body, f.device_names)
        self.module_device: Set[str] = set()
        # module-level assignments from device calls (rare, but cheap)

    def _device_pass(self, scope: _Scope, body: Sequence[ast.stmt],
                     names: Set[str]) -> None:
        for st in body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if isinstance(st, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                value = st.value
                targets = st.targets if isinstance(st, ast.Assign) else \
                    [st.target]
                if value is None:
                    continue
                dev = expr_is_device(value, scope, self)
                aug = isinstance(st, ast.AugAssign)
                for t in targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            if dev:
                                names.add(n.id)
                            elif not aug:    # `x += 1` keeps x on device
                                names.discard(n.id)
            elif isinstance(st, ast.For):
                if expr_is_device(st.iter, scope, self):
                    for n in ast.walk(st.target):
                        if isinstance(n, ast.Name):
                            names.add(n.id)
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(st, attr, None)
                if isinstance(sub, list) and sub and isinstance(sub[0], ast.stmt):
                    self._device_pass(scope, sub, names)
            for h in getattr(st, "handlers", []) or []:
                self._device_pass(scope, h.body, names)

    def is_tracked(self, name: str, scope: _Scope) -> bool:
        """Does ``name`` hold a traced/device value in this scope chain?"""
        for s in scope.chain():
            f = s.func
            if f is None:
                continue
            if name in f.device_names:
                return True
            if name in f.static:            # i.e. name is a param of f
                return f.traced and not f.static[name]
        return False


_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "itemsize"}


def expr_is_device(expr: ast.AST, scope: _Scope, index: _ModuleIndex) -> bool:
    """Conservatively: does this expression produce/contain a traced or
    device value?  ``.shape``/``.ndim``/``len()``/``isinstance()`` punch
    through to static, as do identity comparisons (``x is None``)."""
    if isinstance(expr, ast.Constant):
        return False
    if isinstance(expr, ast.Name):
        return index.is_tracked(expr.id, scope)
    if isinstance(expr, ast.Attribute):
        if expr.attr in _STATIC_ATTRS:
            return False
        return expr_is_device(expr.value, scope, index)
    if isinstance(expr, ast.Call):
        d = _dotted(expr.func)
        if d in ("int", "float", "bool", "len") or d in _STATIC_CALLS:
            return False                     # result lives on host
        if d in _DEVICE_GET:
            return False                     # fetches TO host by definition
        if d and (d.startswith("np.") or d.startswith("numpy.")):
            return False                     # numpy results live on host
        if _is_device_call(d):
            return True
        return any(expr_is_device(a, scope, index) for a in expr.args) or \
            any(expr_is_device(k.value, scope, index) for k in expr.keywords)
    if isinstance(expr, ast.Subscript):
        return expr_is_device(expr.value, scope, index)
    if isinstance(expr, ast.Compare):
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in expr.ops):
            return False                     # `x is None` is trace-static
        return expr_is_device(expr.left, scope, index) or \
            any(expr_is_device(c, scope, index) for c in expr.comparators)
    if isinstance(expr, (ast.BoolOp,)):
        return any(expr_is_device(v, scope, index) for v in expr.values)
    if isinstance(expr, ast.BinOp):
        return expr_is_device(expr.left, scope, index) or \
            expr_is_device(expr.right, scope, index)
    if isinstance(expr, ast.UnaryOp):
        return expr_is_device(expr.operand, scope, index)
    if isinstance(expr, ast.IfExp):
        return any(expr_is_device(e, scope, index)
                   for e in (expr.test, expr.body, expr.orelse))
    if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
        return any(expr_is_device(e, scope, index) for e in expr.elts)
    if isinstance(expr, ast.Starred):
        return expr_is_device(expr.value, scope, index)
    return False


def _mentions_dynamic(expr: ast.AST, scope: _Scope, index: _ModuleIndex) -> bool:
    """Like expr_is_device but WITHOUT the ``.shape`` shield — a range
    over ``x.shape[0]`` is still a shape-dependent unroll."""
    for n in ast.walk(expr):
        if isinstance(n, ast.Name) and index.is_tracked(n.id, scope):
            return True
        if isinstance(n, ast.Call) and _is_device_call(_dotted(n.func)):
            return True
    return False


class _RulePass:
    def __init__(self, index: _ModuleIndex, path: str, lines: List[str],
                 hot: bool, suppressed: Dict[int, Set[str]]):
        self.index = index
        self.path = path
        self.lines = lines
        self.hot = hot
        self.suppressed = suppressed
        self.findings: List[Finding] = []

    def emit(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 1)
        sup = self.suppressed.get(line, set())
        if "all" in sup or rule in sup:
            return
        text = self.lines[line - 1].strip() if 0 < line <= len(self.lines) else ""
        self.findings.append(Finding(rule, self.path, line,
                                     getattr(node, "col_offset", 0) + 1,
                                     message, text))

    # -- entry --------------------------------------------------------------
    def run(self, tree: ast.Module) -> List[Finding]:
        self._stmts(tree.body, self.index.module_scope, traced=False, loop=0)
        for f in self.index.funcs:
            if f.seed and f.bad_defaults:
                for d in f.bad_defaults:
                    self.emit("TZ005", d,
                              f"mutable/array-valued default on jitted "
                              f"`{f.name}`: evaluated once at def time, "
                              f"hashed (or aliased) across every trace; "
                              f"use None and build it inside, or a tuple")
            self._stmts(f.node.body, f.scope, traced=f.traced, loop=0)
        self.findings.sort(key=lambda x: (x.path, x.line, x.rule))
        return self.findings

    # -- statement walk -----------------------------------------------------
    def _stmts(self, body: Sequence[ast.stmt], scope: _Scope, traced: bool,
               loop: int) -> None:
        for st in body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # body is visited as its own function; decorators/defaults
                # evaluate in THIS scope
                for dec in st.decorator_list:
                    self._exprs(dec, scope, traced, loop)
                continue
            if isinstance(st, ast.ClassDef):
                continue            # methods visited as their own functions
            if isinstance(st, ast.If):
                self._guard(st.test, scope, traced, kind="if")
                self._exprs(st.test, scope, traced, loop)
                self._stmts(st.body, scope, traced, loop)
                self._stmts(st.orelse, scope, traced, loop)
            elif isinstance(st, ast.While):
                self._guard(st.test, scope, traced, kind="while")
                self._exprs(st.test, scope, traced, loop)
                self._stmts(st.body, scope, traced, loop + 1)
                self._stmts(st.orelse, scope, traced, loop + 1)
            elif isinstance(st, ast.For):
                if traced:
                    self._unroll(st, scope)
                self._exprs(st.iter, scope, traced, loop)
                self._stmts(st.body, scope, traced, loop + 1)
                self._stmts(st.orelse, scope, traced, loop + 1)
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                for item in st.items:
                    self._exprs(item.context_expr, scope, traced, loop)
                self._stmts(st.body, scope, traced, loop)
            elif isinstance(st, ast.Try):
                self._stmts(st.body, scope, traced, loop)
                for h in st.handlers:
                    self._stmts(h.body, scope, traced, loop)
                self._stmts(st.orelse, scope, traced, loop)
                self._stmts(st.finalbody, scope, traced, loop)
            else:
                for child in ast.iter_child_nodes(st):
                    if isinstance(child, ast.expr):
                        self._exprs(child, scope, traced, loop)

    # -- TZ002 --------------------------------------------------------------
    def _guard(self, test: ast.expr, scope: _Scope, traced: bool,
               kind: str) -> None:
        if traced and expr_is_device(test, scope, self.index):
            self.emit("TZ002", test,
                      f"`{kind}` on a traced value stages only one branch "
                      f"(or raises TracerBoolConversionError); use "
                      f"jnp.where/lax.cond, or bind the flag statically "
                      f"(partial kwarg / static_argnames)")

    # -- TZ003 --------------------------------------------------------------
    def _unroll(self, st: ast.For, scope: _Scope) -> None:
        it = st.iter
        if isinstance(it, ast.Call) and _dotted(it.func) == "enumerate" \
                and it.args:
            it = it.args[0]
        if not (isinstance(it, ast.Call) and _dotted(it.func) == "range"):
            return
        if not any(_mentions_dynamic(a, scope, self.index) for a in it.args):
            return
        body_has_device = any(
            isinstance(n, ast.Call) and _is_device_call(_dotted(n.func))
            for s in st.body for n in ast.walk(s))
        if body_has_device:
            self.emit("TZ003", st,
                      "Python loop over a dynamic/shape-dependent range "
                      "unrolls one op-copy per iteration into the XLA "
                      "graph and retraces per length; use lax.scan/"
                      "fori_loop or a static bound")

    # -- expression-level rules (TZ001/TZ004/TZ006/TZ007) -------------------
    def _exprs(self, expr: ast.expr, scope: _Scope, traced: bool,
               loop: int) -> None:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            self._sync(node, d, scope, traced, loop)
            self._jit_site(node, d, scope, traced, loop)
            if traced and d and (d.startswith("np.random.") or
                                 d.startswith("numpy.random.") or
                                 d.startswith("random.")):
                self.emit("TZ006", node,
                          f"`{d}` inside traced code runs once at trace "
                          f"time and folds to a constant — every call "
                          f"replays the same 'random' draw; thread a "
                          f"jax.random key instead")
            if self.hot and d in _JNP_CONVERT:
                explicit = len(node.args) > _JNP_CONVERT[d] or \
                    any(k.arg == "dtype" for k in node.keywords)
                if not explicit:
                    self.emit("TZ007", node,
                              f"`{d}` without an explicit dtype in a "
                              f"serving hot path: weak-type promotion "
                              f"(or a stray float64) changes the "
                              f"compiled signature and retraces; pass "
                              f"dtype=")

    def _sync(self, node: ast.Call, d: Optional[str], scope: _Scope,
              traced: bool, loop: int) -> None:
        hard = None
        if d and d.endswith(".item") and not node.args:
            hard = ".item()"
        elif d in _DEVICE_GET:
            hard = "jax.device_get"
        elif d == "jax.block_until_ready" or (d and
                                              d.endswith(".block_until_ready")):
            hard = "block_until_ready"
        if hard is not None:
            if traced:
                self.emit("TZ001", node,
                          f"{hard} inside traced code forces a host sync "
                          f"mid-graph (or fails under jit); return the "
                          f"value and fetch on the host")
            elif loop > 0:
                self.emit("TZ001", node,
                          f"{hard} inside a host loop syncs every "
                          f"iteration; batch the fetch once outside the "
                          f"loop (one device_get of the whole pytree)")
            return
        wrap = None
        if d in ("int", "float", "bool") and len(node.args) == 1:
            wrap = d
        elif d in _NP_CONVERT and node.args:
            wrap = d
        if wrap is None:
            return
        arg = node.args[0]
        direct = any(isinstance(n, ast.Call) and _is_device_call(_dotted(n.func))
                     for n in ast.walk(arg))
        if traced:
            if direct or expr_is_device(arg, scope, self.index):
                self.emit("TZ001", node,
                          f"{wrap}() on a traced value inside traced code "
                          f"is a concretization error under jit and a "
                          f"blocking sync outside it; keep it on device")
        else:
            if direct:
                self.emit("TZ001", node,
                          f"{wrap}() wrapping a device computation syncs "
                          f"per call and launches a tiny kernel; compute "
                          f"on device in the jitted program, or fetch a "
                          f"batch once with np.asarray and pick on host")
            elif loop > 0 and expr_is_device(arg, scope, self.index):
                self.emit("TZ001", node,
                          f"{wrap}() on a device value inside a host loop "
                          f"syncs every iteration; hoist one batched "
                          f"fetch out of the loop")

    def _jit_site(self, node: ast.Call, d: Optional[str], scope: _Scope,
                  traced: bool, loop: int) -> None:
        # immediately-invoked jit: jax.jit(f, ...)(args)
        if isinstance(node.func, ast.Call) and \
                _dotted(node.func.func) in _JIT_CALLS:
            self.emit("TZ004", node,
                      "jax.jit(...)(...) compiles and throws the cache "
                      "away — every call retraces; bind the jitted "
                      "callable once and reuse it")
        if d not in _JIT_CALLS:
            return
        if loop > 0:
            self.emit("TZ004", node,
                      "jax.jit constructed inside a loop makes a fresh "
                      "compile cache per iteration; hoist it out (or "
                      "memoize like a step-cache dict)")
        elif traced:
            self.emit("TZ004", node,
                      "jax.jit under trace re-enters staging per call; "
                      "construct jits at init/module scope")
        # TZ008: train-step-shaped target without donation
        if node.args:
            names: List[str] = []
            tgt = node.args[0]
            if isinstance(tgt, ast.Call) and \
                    _dotted(tgt.func) in _PARTIAL_CALLS and tgt.args:
                tgt = tgt.args[0]
            dt = _dotted(tgt)
            if dt:
                names.append(dt.rsplit(".", 1)[-1])
            donated = any(k.arg in ("donate_argnums", "donate_argnames")
                          for k in node.keywords)
            if names and _TRAIN_STEP_RE.search(names[0]) and not donated:
                self.emit("TZ008", node,
                          f"jit of `{names[0]}` without donate_argnums: "
                          f"the old params/opt-state stay live while the "
                          f"update computes, doubling peak HBM; donate "
                          f"the state argument")


def _suppressions(lines: List[str]) -> Dict[int, Set[str]]:
    out: Dict[int, Set[str]] = {}
    for i, raw in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(raw)
        if not m:
            continue
        rules = {"all"} if m.group("rules") == "all" else \
            {r.strip() for r in m.group("rules").split(",")}
        target = i + 1 if m.group("next") else i
        out.setdefault(target, set()).update(rules)
    return out


def analyze_source(src: str, path: str,
                   hot_paths: Sequence[str] = DEFAULT_HOT_PATHS,
                   concurrency: bool = True) -> List[Finding]:
    """Analyze one module's source. ``path`` is used for reporting and
    hot-path matching (posix-normalized substring match).  The
    concurrency pass (TZ1xx, lockflow.py) runs by default; pass
    ``concurrency=False`` (CLI ``--no-concurrency``) for staging rules
    only."""
    posix = path.replace(os.sep, "/")
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding("TZ000", path, e.lineno or 1, (e.offset or 0) + 1,
                        f"could not parse: {e.msg}", "")]
    lines = src.splitlines()
    index = _ModuleIndex(tree)
    hot = any(pat in posix for pat in hot_paths)
    sup = _suppressions(lines)
    findings = _RulePass(index, path, lines, hot, sup).run(tree)
    if concurrency:
        # import here: lockflow imports Finding/_dotted from this module
        from analytics_zoo_tpu.lint.lockflow import run_lockflow
        findings.extend(run_lockflow(tree, path, lines, sup))
        findings.sort(key=lambda x: (x.path, x.line, x.rule))
    return findings


def analyze_file(path: str, hot_paths: Sequence[str] = DEFAULT_HOT_PATHS,
                 rel_to: Optional[str] = None,
                 concurrency: bool = True) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    rep = path
    if rel_to:
        try:
            rep = os.path.relpath(path, rel_to)
        except ValueError:
            rep = path
    return analyze_source(src, rep.replace(os.sep, "/"), hot_paths,
                          concurrency=concurrency)


def iter_py_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if not d.startswith(".") and
                                 d != "__pycache__")
                for f in sorted(files):
                    if f.endswith(".py"):
                        out.append(os.path.join(root, f))
        elif p.endswith(".py"):
            out.append(p)
    return out


def analyze_paths(paths: Iterable[str],
                  hot_paths: Sequence[str] = DEFAULT_HOT_PATHS,
                  rel_to: Optional[str] = None,
                  concurrency: bool = True) -> List[Finding]:
    """Analyze files/directories; directory walks skip hidden dirs and
    ``__pycache__``.  Paths are reported relative to ``rel_to`` (default
    cwd) so baselines are stable across checkouts."""
    if rel_to is None:
        rel_to = os.getcwd()
    findings: List[Finding] = []
    for f in iter_py_files(paths):
        findings.extend(analyze_file(f, hot_paths, rel_to,
                                     concurrency=concurrency))
    findings.sort(key=lambda x: (x.path, x.line, x.rule))
    return findings

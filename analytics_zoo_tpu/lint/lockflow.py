"""tpulint lockflow — concurrency rules TZ101..TZ108.

The serving fleet is a deeply multithreaded system: per-replica pump
threads, a router thread, HTTP scrape threads, elastic-resize cadence,
and pool callbacks that fire *under the pool lock* with a documented
"record-only" contract.  None of that is visible to the staging rules
(TZ00x), so this module adds a lock-context analysis over the same
stdlib-``ast`` substrate:

1.  **Lock discovery.**  Locks are attributes/names assigned
    ``threading.Lock()`` / ``RLock()`` / ``Condition()`` anywhere in
    the module, plus lock-ish-named context managers (``*lock*``,
    ``*cond*``) the module did not construct itself.  Identity is
    class-scoped for ``self.X`` (``Engine._pool_lock``) so two
    instances share one order discipline.
2.  **Held-set tracking.**  Each function body is walked with the set
    of locks held at every statement: ``with lock:`` regions scope
    naturally; manual ``acquire()``/``release()`` pairs are tracked
    linearly, with ``try/finally`` release recognised as
    path-complete.
3.  **Call-edge propagation.**  Held sets flow across intra-module
    call edges (``self.meth(...)``, bare local calls, and local
    functions passed as arguments — the ``tree_map(scatter, ...)``
    pattern) to a fixpoint, so a helper that only ever runs under its
    caller's lock is analyzed as such.

The rules (catalog in docs/lint.md):

- **TZ101** — write to a guarded attribute outside its owning lock.
  Guarding is inferred ("assigned under lock L in at least one
  non-init method, and L is the only such lock") or declared with a
  ``# tpulint: guarded-by(_lock)`` comment on any write line.
- **TZ102** — blocking call (``jax.device_get``/``device_put``,
  ``block_until_ready``, ``.item()``, ``time.sleep``, blocking
  ``queue.get``/thread ``join``, socket/file I/O) while holding a
  lock.  A device sync under the pool lock stalls every thread that
  touches the pool for a full D2H round trip.
- **TZ103** — callback discipline: a ``*_cb``/``on_*`` callable
  invoked while holding a lock, or a callable registered via
  ``event_cb``/``spill_cb``/``index_cb``/``evict_cb``/``handoff_cb``
  whose body is not record-only (acquires locks, calls jax, does
  I/O).  Registered callables that resolve locally are checked
  directly; a cross-module registration to a pool-side hook cannot be
  verified and is flagged for an explicit baseline decision.
- **TZ104** — inconsistent lock-acquisition order: the module-level
  graph of (held A -> acquired B) edges contains a cycle.
- **TZ105** — double-acquire of a non-reentrant ``Lock`` (directly,
  or via a call chain whose entry context already holds it).
- **TZ106** — a manually ``acquire()``-d lock reaches a ``return`` or
  ``raise`` with no ``try/finally`` release on that path.
- **TZ107** — module-level mutable state (or a class attribute)
  mutated from a known-threaded entry point (``_pump``, ``_loop*``,
  ``_route_loop``, HTTP ``do_*`` handlers, ``maybe_autoresize``,
  ``threading.Thread(target=...)`` targets) with no lock held.
- **TZ108** — ``Condition.wait`` outside a ``while`` predicate loop
  (``wait_for`` passes; a timed wait used as a bounded nap should be
  baselined with its justification).

Like the staging rules, everything here is a static approximation:
the escape hatches are ``# tpulint: disable=TZ10x`` for one site and
the baseline ledger for deliberate keepers.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from analytics_zoo_tpu.lint.analyzer import Finding, _dotted

__all__ = ["run_lockflow", "LOCK_RULES"]

LOCK_RULES: Dict[str, str] = {
    "TZ101": "write to a lock-guarded attribute outside its owning lock",
    "TZ102": "blocking call (device sync/sleep/IO) while holding a lock",
    "TZ103": "callback under lock is not provably record-only",
    "TZ104": "inconsistent lock-acquisition order (deadlock cycle)",
    "TZ105": "double-acquire of a non-reentrant Lock",
    "TZ106": "manually acquired lock not released on an early exit path",
    "TZ107": "shared mutable state touched from a threaded entry point "
             "with no lock held",
    "TZ108": "Condition.wait without an enclosing predicate re-check loop",
}

_LOCK_CTORS = {
    "threading.Lock": "lock", "Lock": "lock",
    "threading.RLock": "rlock", "RLock": "rlock",
    "threading.Condition": "condition", "Condition": "condition",
    "multiprocessing.Lock": "lock", "multiprocessing.RLock": "rlock",
}
_LOCKISH_RE = re.compile(r"(lock|mutex|cond)", re.I)
_CONDISH_RE = re.compile(r"cond", re.I)
_GUARDED_BY_RE = re.compile(
    r"#\s*tpulint:\s*guarded-by\(\s*(?P<lock>[A-Za-z_][A-Za-z0-9_]*)\s*\)")

# TZ102: calls that block the calling thread (or force a device
# rendezvous).  Deliberately tight — a noisy blocking set would teach
# people to ignore the rule.
_BLOCKING_EXACT = {
    "jax.device_get": "jax.device_get (D2H sync)",
    "device_get": "device_get (D2H sync)",
    "jax.device_put": "jax.device_put (H2D transfer)",
    "device_put": "device_put (H2D transfer)",
    "jax.block_until_ready": "block_until_ready (device rendezvous)",
    "time.sleep": "time.sleep",
    "socket.create_connection": "socket connect",
    "urllib.request.urlopen": "url fetch",
    "urlopen": "url fetch",
    "subprocess.run": "subprocess",
    "subprocess.check_output": "subprocess",
    "subprocess.call": "subprocess",
    "open": "file open",
}
_THREADISH_RE = re.compile(r"(thread|worker|pump|proc)", re.I)
_QUEUEISH_RE = re.compile(r"(^|_)(in_?q|out_?q|q|queue|jobs|work)\d*$", re.I)
_SOCKISH_RE = re.compile(r"(sock|conn)", re.I)

# TZ103: the pool/engine hook kwargs whose callables must be
# record-only, and the invocation-site names treated as callbacks.
_CB_KWARGS = ("event_cb", "spill_cb", "index_cb", "evict_cb", "handoff_cb")
# hooks documented to fire OUTSIDE any lock may register cross-module
# callables without a baseline entry; under-lock hooks may not
_CB_KWARGS_UNDER_LOCK = ("event_cb", "spill_cb", "index_cb", "evict_cb")
_CB_INVOKE_NAMES = {"on_done", "on_error", "on_token", "callback", "cb"}
# jax roots whose calls disqualify a record-only callback (tree_util
# is host-side bookkeeping and allowed)
_JAXISH_RE = re.compile(r"^(jax|jnp|lax)\.")

_THREAD_ROOT_NAMES = {"_pump", "_route_loop", "maybe_autoresize"}
_MUTATING_METHODS = {"append", "appendleft", "extend", "extendleft", "add",
                     "insert", "remove", "discard", "pop", "popleft",
                     "popitem", "clear", "update", "setdefault"}


class _FnRec:
    """One function/method: identity, context, and everything the walk
    recorded about it (findings are derived after propagation)."""

    def __init__(self, node: ast.AST, key: str, cls: Optional[str],
                 parent_key: Optional[str]):
        self.node = node
        self.key = key              # module-unique qualname
        self.name = node.name
        self.cls = cls              # nearest enclosing class name
        self.parent_key = parent_key
        self.threaded = False
        # records: (data..., node, held_tuple)
        self.calls: List[Tuple[str, Tuple[str, ...]]] = []  # callee key, held
        self.attr_writes: List[Tuple[str, ast.AST, Tuple[str, ...]]] = []
        self.blocking: List[Tuple[str, ast.AST, Tuple[str, ...]]] = []
        self.cb_invokes: List[Tuple[str, ast.AST, Tuple[str, ...]]] = []
        self.module_writes: List[Tuple[str, ast.AST, Tuple[str, ...]]] = []
        self.acquires: List[Tuple[str, ast.AST, Tuple[str, ...]]] = []


class _Lockflow:
    def __init__(self, tree: ast.Module, path: str, lines: List[str],
                 suppressed: Dict[int, Set[str]]):
        self.tree = tree
        self.path = path
        self.lines = lines
        self.suppressed = suppressed
        self.findings: List[Finding] = []

        self.locks: Dict[str, str] = {}          # lock id -> kind
        self.fns: Dict[str, _FnRec] = {}
        self.module_funcs: Dict[str, str] = {}   # bare name -> key
        self.methods: Dict[Tuple[str, str], str] = {}
        self.nested: Dict[Tuple[str, str], str] = {}
        self.class_names: Set[str] = set()
        self.module_mutables: Set[str] = set()
        self.thread_targets: Set[str] = set()    # method/function names
        self.thread_classes: Set[str] = set()    # Thread subclasses
        # (cls, attr) -> lock id declared via guarded-by comment
        self.declared_guards: Dict[Tuple[str, str], str] = {}
        self.guard_lines = {
            i: m.group("lock")
            for i, raw in enumerate(lines, start=1)
            for m in [_GUARDED_BY_RE.search(raw)] if m}
        # TZ104 order edges: (a, b) -> first (node, fn_key)
        self.order_edges: Dict[Tuple[str, str], Tuple[ast.AST, str]] = {}
        # TZ103 registrations: (kwarg, value expr, node, fn)
        self.registrations: List[Tuple[str, ast.expr, ast.AST, _FnRec]] = []
        # TZ105/TZ106/TZ108 findings are emitted during the walk
        self.entry: Dict[str, Set[str]] = {}

    # -- emission -----------------------------------------------------

    def emit(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 1)
        sup = self.suppressed.get(line, set())
        if "all" in sup or rule in sup:
            return
        text = self.lines[line - 1].strip() \
            if 0 < line <= len(self.lines) else ""
        self.findings.append(Finding(rule, self.path, line,
                                     getattr(node, "col_offset", 0) + 1,
                                     message, text))

    # -- pass 1: discovery --------------------------------------------

    def discover(self) -> None:
        self._discover_body(self.tree.body, cls=None, parent=None)
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if d and d.rsplit(".", 1)[-1] == "Thread":
                for kw in node.keywords:
                    if kw.arg != "target":
                        continue
                    td = _dotted(kw.value)
                    if td:
                        self.thread_targets.add(td.rsplit(".", 1)[-1])

    def _discover_body(self, body: Sequence[ast.stmt], cls: Optional[str],
                       parent: Optional[str]) -> None:
        for st in body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                key = f"{parent}.{st.name}" if parent else (
                    f"{cls}.{st.name}" if cls else st.name)
                rec = _FnRec(st, key, cls, parent)
                self.fns[key] = rec
                if cls is not None and parent is None:
                    self.methods[(cls, st.name)] = key
                elif parent is not None:
                    self.nested[(parent, st.name)] = key
                else:
                    self.module_funcs[st.name] = key
                self._discover_lock_defs(st, cls, key)
                self._discover_body(st.body, cls, key)
            elif isinstance(st, ast.ClassDef):
                self.class_names.add(st.name)
                if any("Thread" in (_dotted(b) or "") for b in st.bases):
                    self.thread_classes.add(st.name)
                self._discover_body(st.body, st.name, None)
            else:
                if cls is None and parent is None:
                    self._discover_module_state(st)
                for sub in ast.walk(st):
                    if isinstance(sub, ast.ClassDef):
                        self.class_names.add(sub.name)
                        self._discover_body(sub.body, sub.name, None)
                    elif isinstance(sub, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)) and \
                            not isinstance(st, (ast.FunctionDef,
                                                ast.AsyncFunctionDef)):
                        pass    # handled when its parent body recurses

    def _discover_lock_defs(self, fn: ast.AST, cls: Optional[str],
                            key: str) -> None:
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Assign) or \
                    not isinstance(sub.value, ast.Call):
                continue
            kind = _LOCK_CTORS.get(_dotted(sub.value.func) or "")
            if kind is None:
                continue
            for tgt in sub.targets:
                td = _dotted(tgt)
                if td and td.startswith("self.") and td.count(".") == 1 \
                        and cls is not None:
                    self.locks[f"{cls}.{td[5:]}"] = kind
                elif isinstance(tgt, ast.Name):
                    self.locks[f"{key}.{tgt.id}"] = kind

    def _discover_module_state(self, st: ast.stmt) -> None:
        if isinstance(st, ast.Assign):
            mutable = isinstance(st.value, (ast.List, ast.Dict, ast.Set,
                                            ast.ListComp, ast.DictComp))
            if isinstance(st.value, ast.Call):
                d = _dotted(st.value.func) or ""
                mutable = d.rsplit(".", 1)[-1] in (
                    "list", "dict", "set", "deque", "defaultdict",
                    "OrderedDict", "Counter")
                kind = _LOCK_CTORS.get(d)
                if kind is not None:
                    for tgt in st.targets:
                        if isinstance(tgt, ast.Name):
                            self.locks[tgt.id] = kind
                    return
            if mutable:
                for tgt in st.targets:
                    if isinstance(tgt, ast.Name):
                        self.module_mutables.add(tgt.id)

    # -- lock identity ------------------------------------------------

    def lock_id(self, expr: ast.AST, rec: _FnRec) -> Optional[str]:
        d = _dotted(expr)
        if not d:
            return None
        if d.startswith("self.") and d.count(".") == 1 and rec.cls:
            cand = f"{rec.cls}.{d[5:]}"
            if cand in self.locks or _LOCKISH_RE.search(d[5:]):
                return cand
            return None
        if "." not in d:
            for scope in (rec.key, rec.parent_key):
                if scope and f"{scope}.{d}" in self.locks:
                    return f"{scope}.{d}"
            if d in self.locks:
                return d
            if _LOCKISH_RE.search(d):
                return f"{rec.key}.{d}"
            return None
        # foreign-object lock (s.cond, frontend._pool_lock): identity
        # is the dotted path itself, module-scoped
        if _LOCKISH_RE.search(d.rsplit(".", 1)[-1]):
            return d
        return None

    def kind_of(self, lock_id: str) -> str:
        if lock_id in self.locks:
            return self.locks[lock_id]
        return "condition" if _CONDISH_RE.search(
            lock_id.rsplit(".", 1)[-1]) else "unknown"

    def _short(self, lock_id: str) -> str:
        return lock_id.rsplit(".", 1)[-1]

    # -- pass 2: per-function walk ------------------------------------

    def walk_all(self) -> None:
        for rec in self.fns.values():
            ctx = _WalkCtx()
            self._walk_stmts(rec.node.body, rec, ctx)

    def _record_acquire(self, lock: str, node: ast.AST, rec: _FnRec,
                        ctx: "_WalkCtx") -> None:
        for held in ctx.held:
            if held == lock:
                if self.kind_of(lock) in ("lock", "condition"):
                    self.emit("TZ105", node,
                              f"`{self._short(lock)}` is non-reentrant and "
                              f"already held here — this acquire "
                              f"deadlocks the thread against itself; use "
                              f"one region or split a _locked() helper")
            elif (held, lock) not in self.order_edges:
                self.order_edges[(held, lock)] = (node, rec.key)
        rec.acquires.append((lock, node, tuple(ctx.held)))
        ctx.held.append(lock)

    def _walk_stmts(self, body: Sequence[ast.stmt], rec: _FnRec,
                    ctx: "_WalkCtx") -> None:
        for st in body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue        # nested defs walk as their own functions
            if isinstance(st, (ast.With, ast.AsyncWith)):
                pushed = []
                for item in st.items:
                    self._scan_expr(item.context_expr, rec, ctx)
                    lock = self.lock_id(item.context_expr, rec)
                    if lock is not None:
                        self._record_acquire(lock, item.context_expr,
                                             rec, ctx)
                        pushed.append(lock)
                self._walk_stmts(st.body, rec, ctx)
                for lock in reversed(pushed):
                    if lock in ctx.held:
                        ctx.held.remove(lock)
            elif isinstance(st, ast.Try):
                fin = set()
                for fst in st.finalbody:
                    for sub in ast.walk(fst):
                        if isinstance(sub, ast.Call) and \
                                isinstance(sub.func, ast.Attribute) and \
                                sub.func.attr == "release":
                            lid = self.lock_id(sub.func.value, rec)
                            if lid:
                                fin.add(lid)
                ctx.protected |= fin
                self._walk_stmts(st.body, rec, ctx)
                for h in st.handlers:
                    self._walk_stmts(h.body, rec, ctx)
                self._walk_stmts(st.orelse, rec, ctx)
                ctx.protected -= fin
                self._walk_stmts(st.finalbody, rec, ctx)
            elif isinstance(st, ast.If):
                self._scan_expr(st.test, rec, ctx)
                self._walk_stmts(st.body, rec, ctx)
                self._walk_stmts(st.orelse, rec, ctx)
            elif isinstance(st, ast.While):
                self._scan_expr(st.test, rec, ctx)
                ctx.in_while += 1
                self._walk_stmts(st.body, rec, ctx)
                self._walk_stmts(st.orelse, rec, ctx)
                ctx.in_while -= 1
            elif isinstance(st, (ast.For, ast.AsyncFor)):
                self._scan_expr(st.iter, rec, ctx)
                self._walk_stmts(st.body, rec, ctx)
                self._walk_stmts(st.orelse, rec, ctx)
            elif isinstance(st, (ast.Return, ast.Raise)):
                for child in ast.iter_child_nodes(st):
                    if isinstance(child, ast.expr):
                        self._scan_expr(child, rec, ctx)
                leaked = [l for l in ctx.manual if l in ctx.held and
                          l not in ctx.protected]
                for lock in leaked:
                    verb = "return" if isinstance(st, ast.Return) else "raise"
                    self.emit("TZ106", st,
                              f"`{self._short(lock)}` was acquire()d "
                              f"manually and this `{verb}` leaves without "
                              f"releasing it — every later acquirer "
                              f"deadlocks; use `with` or try/finally")
            elif isinstance(st, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                value = st.value
                if value is not None:
                    self._scan_expr(value, rec, ctx)
                targets = st.targets if isinstance(st, ast.Assign) \
                    else [st.target]
                for tgt in targets:
                    self._record_write(tgt, st, rec, ctx)
            elif isinstance(st, ast.Delete):
                for tgt in st.targets:
                    self._record_write(tgt, st, rec, ctx)
            else:
                for child in ast.iter_child_nodes(st):
                    if isinstance(child, ast.expr):
                        self._scan_expr(child, rec, ctx)

    def _record_write(self, tgt: ast.AST, st: ast.stmt, rec: _FnRec,
                      ctx: "_WalkCtx") -> None:
        # unwrap one subscript level: self.x[i] = v writes x
        base = tgt
        if isinstance(base, (ast.Subscript, ast.Starred)):
            self._scan_expr(base, rec, ctx)
            base = base.value
        if isinstance(base, ast.Attribute):
            bd = _dotted(base)
            if bd and bd.startswith("self.") and bd.count(".") == 1 \
                    and rec.cls:
                attr = bd[5:]
                rec.attr_writes.append((attr, st, tuple(ctx.held)))
                g = self.guard_lines.get(getattr(st, "lineno", 0))
                if g:
                    self.declared_guards[(rec.cls, attr)] = \
                        f"{rec.cls}.{g}"
            elif bd and bd.split(".", 1)[0] in self.class_names:
                rec.module_writes.append((bd, st, tuple(ctx.held)))
        elif isinstance(base, ast.Name):
            if base.id in self.module_mutables and base is not tgt:
                rec.module_writes.append((base.id, st, tuple(ctx.held)))
            elif base.id in self.module_mutables and \
                    isinstance(tgt, ast.Name) and \
                    any(isinstance(n, ast.Global) and base.id in n.names
                        for n in ast.walk(rec.node)):
                rec.module_writes.append((base.id, st, tuple(ctx.held)))
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                self._record_write(elt, st, rec, ctx)

    # -- expression scan (calls) --------------------------------------

    def _scan_expr(self, expr: ast.AST, rec: _FnRec,
                   ctx: "_WalkCtx") -> None:
        for node in self._walk_no_lambda(expr):
            if not isinstance(node, ast.Call):
                continue
            self._handle_call(node, rec, ctx)

    @staticmethod
    def _walk_no_lambda(expr: ast.AST):
        """ast.walk, but do not descend into Lambda bodies or nested
        defs — their code runs later, not under the current locks."""
        stack = [expr]
        while stack:
            node = stack.pop()
            yield node
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.Lambda, ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    continue
                stack.append(child)

    def _handle_call(self, node: ast.Call, rec: _FnRec,
                     ctx: "_WalkCtx") -> None:
        d = _dotted(node.func)
        held = tuple(ctx.held)
        # manual acquire/release
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("acquire", "release"):
            lock = self.lock_id(node.func.value, rec)
            if lock is not None:
                if node.func.attr == "acquire":
                    self._record_acquire(lock, node, rec, ctx)
                    ctx.manual.append(lock)
                else:
                    if lock in ctx.held:
                        ctx.held.remove(lock)
                    if lock in ctx.manual:
                        ctx.manual.remove(lock)
                return
        # Condition.wait discipline (held or not: a wait outside any
        # lock is its own bug, but the predicate loop is the rule here)
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("wait", "wait_for"):
            lock = self.lock_id(node.func.value, rec)
            if lock is not None and self.kind_of(lock) == "condition" \
                    and node.func.attr == "wait" and ctx.in_while == 0:
                self.emit("TZ108", node,
                          f"`{self._short(lock)}.wait()` outside a "
                          f"`while <predicate>` loop: wakeups are "
                          f"spurious and racy by spec — re-check the "
                          f"predicate in a loop, or use wait_for()")
        # blocking calls
        blk = self._blocking_label(node, d)
        if blk is not None:
            rec.blocking.append((blk, node, held))
        # callback invocation site
        tail = (d or "").rsplit(".", 1)[-1]
        if tail and (tail.endswith("_cb") or tail in _CB_INVOKE_NAMES):
            rec.cb_invokes.append((tail, node, held))
        # callback registration kwargs
        for kw in node.keywords:
            if kw.arg in _CB_KWARGS:
                self.registrations.append((kw.arg, kw.value, node, rec))
        # call edges (direct + local functions passed as arguments)
        callee = self._resolve_call(d, rec)
        if callee is not None:
            rec.calls.append((callee, held))
        for arg in list(node.args) + [k.value for k in node.keywords]:
            ad = _dotted(arg)
            target = self._resolve_call(ad, rec)
            if target is not None:
                rec.calls.append((target, held))

    def _blocking_label(self, node: ast.Call, d: Optional[str],
                        ) -> Optional[str]:
        if d in _BLOCKING_EXACT:
            return _BLOCKING_EXACT[d]
        if not isinstance(node.func, ast.Attribute):
            return None
        tail = node.func.attr
        recv = _dotted(node.func.value) or ""
        recv_leaf = recv.rsplit(".", 1)[-1]
        if tail == "block_until_ready":
            return "block_until_ready (device rendezvous)"
        if tail == "item" and not node.args and not node.keywords:
            return ".item() (D2H sync)"
        if tail == "join" and recv_leaf and _THREADISH_RE.search(recv_leaf):
            return f"{recv_leaf}.join() (thread join)"
        if tail == "get" and recv_leaf and _QUEUEISH_RE.search(recv_leaf):
            nowait = any(
                kw.arg == "block" and isinstance(kw.value, ast.Constant)
                and kw.value.value is False for kw in node.keywords)
            if not nowait:
                return f"{recv_leaf}.get() (blocking queue get)"
        if tail in ("recv", "accept", "connect", "sendall") and \
                recv_leaf and _SOCKISH_RE.search(recv_leaf):
            return f"{recv_leaf}.{tail}() (socket I/O)"
        return None

    def _resolve_call(self, d: Optional[str], rec: _FnRec,
                      ) -> Optional[str]:
        if not d:
            return None
        if d.startswith("self.") and d.count(".") == 1 and rec.cls:
            return self.methods.get((rec.cls, d[5:]))
        if "." not in d:
            for scope in (rec.key, rec.parent_key):
                if scope and (scope, d) in self.nested:
                    return self.nested[(scope, d)]
            return self.module_funcs.get(d)
        return None

    # -- pass 3: entry-context fixpoint --------------------------------

    def propagate(self) -> None:
        self.entry = {k: set() for k in self.fns}
        changed = True
        rounds = 0
        while changed and rounds < 50:
            changed, rounds = False, rounds + 1
            for rec in self.fns.values():
                base = self.entry[rec.key]
                for callee, held in rec.calls:
                    add = set(held) | base
                    tgt = self.entry.get(callee)
                    if tgt is not None and not add <= tgt:
                        tgt |= add
                        changed = True

    def may_held(self, rec: _FnRec, held: Tuple[str, ...]) -> Set[str]:
        return set(held) | self.entry.get(rec.key, set())

    # -- pass 4: derived findings --------------------------------------

    def _init_exempt(self) -> Set[str]:
        """Functions reachable only through ``__init__`` construction:
        single-threaded by definition, so their bare writes are setup,
        not races."""
        out: Set[str] = set()
        for (cls, name), key in self.methods.items():
            if name in ("__init__", "__new__", "__del__"):
                work = [key]
                while work:
                    k = work.pop()
                    if k in out:
                        continue
                    out.add(k)
                    for callee, _ in self.fns[k].calls:
                        if self.fns[callee].cls == cls:
                            work.append(callee)
        return out

    def rule_tz101(self) -> None:
        exempt = self._init_exempt()
        # (cls, attr) -> list of (rec, node, held)
        writes: Dict[Tuple[str, str],
                     List[Tuple[_FnRec, ast.AST, Tuple[str, ...]]]] = {}
        for rec in self.fns.values():
            if rec.key in exempt or rec.cls is None:
                continue
            for attr, node, held in rec.attr_writes:
                writes.setdefault((rec.cls, attr), []).append(
                    (rec, node, held))
        for (cls, attr), sites in writes.items():
            guard = self.declared_guards.get((cls, attr))
            if guard is None:
                own = set()
                for rec, node, held in sites:
                    for lock in self.may_held(rec, held):
                        if lock.startswith(f"{cls}.") and \
                                self.kind_of(lock) != "condition":
                            own.add(lock)
                if len(own) != 1:
                    continue        # unguarded or ambiguous: no inference
                guard = own.pop()
            for rec, node, held in sites:
                if guard not in self.may_held(rec, held):
                    self.emit("TZ101", node,
                              f"`self.{attr}` is guarded by "
                              f"`{self._short(guard)}` (assigned under it "
                              f"elsewhere or declared guarded-by) but "
                              f"this write holds "
                              f"{self._held_desc(rec, held)}; take the "
                              f"lock or annotate the true owner")

    def _held_desc(self, rec: _FnRec, held: Tuple[str, ...]) -> str:
        locks = self.may_held(rec, held)
        if not locks:
            return "no lock"
        return "only " + ", ".join(
            f"`{self._short(l)}`" for l in sorted(locks))

    def rule_tz102(self) -> None:
        for rec in self.fns.values():
            for label, node, held in rec.blocking:
                locks = self.may_held(rec, held)
                if not locks:
                    continue
                names = ", ".join(f"`{self._short(l)}`"
                                  for l in sorted(locks))
                self.emit("TZ102", node,
                          f"{label} while holding {names}: every thread "
                          f"contending on the lock stalls for the full "
                          f"call — record under the lock, do the "
                          f"blocking work after releasing it")

    def rule_tz103(self) -> None:
        for rec in self.fns.values():
            for name, node, held in rec.cb_invokes:
                locks = self.may_held(rec, held)
                if not locks:
                    continue
                names = ", ".join(f"`{self._short(l)}`"
                                  for l in sorted(locks))
                self.emit("TZ103", node,
                          f"callback `{name}` invoked while holding "
                          f"{names}: an arbitrary callable under a lock "
                          f"can block or re-enter and deadlock — "
                          f"collect results and invoke after release")
        for kwarg, value, node, rec in self.registrations:
            self._check_registration(kwarg, value, node, rec)

    def _check_registration(self, kwarg: str, value: ast.expr,
                            node: ast.AST, rec: _FnRec) -> None:
        if isinstance(value, ast.Constant):        # None / default
            return
        if isinstance(value, ast.IfExp):
            self._check_registration(kwarg, value.body, node, rec)
            self._check_registration(kwarg, value.orelse, node, rec)
            return
        vd = _dotted(value)
        target_key = self._resolve_call(vd, rec)
        if isinstance(value, ast.Lambda):
            reason = self._impurity(value.body, rec)
            if reason:
                self.emit("TZ103", value,
                          f"`{kwarg}` lambda is not record-only: "
                          f"{reason}; this hook fires under the "
                          f"caller's lock — record and defer")
            return
        if target_key is not None:
            target = self.fns[target_key]
            reason = self._impurity(target.node, rec, skip_def=True)
            if reason:
                self.emit("TZ103", node,
                          f"`{kwarg}={vd}` is not record-only: "
                          f"{reason}; this hook fires under the "
                          f"caller's lock — record under the lock and "
                          f"do the real work after release")
            return
        if kwarg in _CB_KWARGS_UNDER_LOCK:
            self.emit("TZ103", node,
                      f"`{kwarg}={vd or '<expr>'}` cannot be verified "
                      f"record-only (defined outside this module); the "
                      f"hook fires under the caller's pool lock — if "
                      f"the callee only records under its own leaf "
                      f"lock, baseline this with that justification")

    def _impurity(self, body: ast.AST, rec: _FnRec,
                  skip_def: bool = False) -> Optional[str]:
        """Why a callback body is not record-only, or None if clean."""
        nodes = ast.walk(body)
        if skip_def:
            nodes = (n for n in ast.walk(body)
                     if n is not body)
        for n in nodes:
            if isinstance(n, (ast.With, ast.AsyncWith)):
                for item in n.items:
                    lid = self.lock_id(item.context_expr, rec)
                    if lid is not None:
                        return (f"acquires `{self._short(lid)}` "
                                f"(line {n.lineno})")
            if not isinstance(n, ast.Call):
                continue
            d = _dotted(n.func) or ""
            if isinstance(n.func, ast.Attribute) and \
                    n.func.attr == "acquire":
                lid = self.lock_id(n.func.value, rec)
                if lid is not None:
                    return (f"acquires `{self._short(lid)}` "
                            f"(line {n.lineno})")
            if _JAXISH_RE.match(d) and not d.startswith("jax.tree_util."):
                return f"calls `{d}` (line {n.lineno})"
            blk = self._blocking_label(n, d)
            if blk is not None:
                return f"{blk} (line {n.lineno})"
        return None

    def rule_tz104(self) -> None:
        # adjacency over recorded order edges, cycles via DFS coloring
        adj: Dict[str, Set[str]] = {}
        for (a, b) in self.order_edges:
            if a != b:
                adj.setdefault(a, set()).add(b)
        # strongly connected components (iterative Tarjan-lite: for the
        # handful of locks per module, repeated reachability is fine)
        def reaches(src: str, dst: str) -> bool:
            seen, work = set(), [src]
            while work:
                n = work.pop()
                if n == dst:
                    return True
                if n in seen:
                    continue
                seen.add(n)
                work.extend(adj.get(n, ()))
            return False

        for (a, b), (node, fn_key) in sorted(
                self.order_edges.items(),
                key=lambda kv: getattr(kv[1][0], "lineno", 0)):
            if a == b or not reaches(b, a):
                continue
            back = self.order_edges.get((b, a))
            where = (f"line {getattr(back[0], 'lineno', '?')}"
                     if back else "another path")
            self.emit("TZ104", node,
                      f"lock order inversion: `{self._short(b)}` "
                      f"acquired while holding `{self._short(a)}`, but "
                      f"{where} acquires them in the opposite order — "
                      f"two threads interleaving these paths deadlock; "
                      f"pick one global order")

    def rule_tz105_propagated(self) -> None:
        # direct double-acquire is emitted during the walk; this adds
        # the cross-function case: fn acquires L and some caller path
        # already holds L
        for rec in self.fns.values():
            ctx_held = self.entry.get(rec.key, set())
            if not ctx_held:
                continue
            for lock, node, held in rec.acquires:
                if lock in ctx_held and lock not in held and \
                        self.kind_of(lock) in ("lock", "condition"):
                    self.emit("TZ105", node,
                              f"`{self._short(lock)}` is non-reentrant "
                              f"and a caller of `{rec.name}` already "
                              f"holds it on some path — this acquire "
                              f"deadlocks that path; hoist the lock or "
                              f"add a _locked() variant")

    def rule_tz107(self) -> None:
        threaded: Set[str] = set()
        for rec in self.fns.values():
            if (rec.name in _THREAD_ROOT_NAMES
                    or rec.name.startswith("_loop")
                    or rec.name.startswith("do_")
                    or rec.name in self.thread_targets
                    or (rec.name == "run" and rec.cls in
                        self.thread_classes)):
                threaded.add(rec.key)
        work = list(threaded)
        while work:
            k = work.pop()
            for callee, _ in self.fns[k].calls:
                if callee not in threaded:
                    threaded.add(callee)
                    work.append(callee)
        for key in threaded:
            rec = self.fns[key]
            for name, node, held in rec.module_writes:
                if self.may_held(rec, held):
                    continue
                self.emit("TZ107", node,
                          f"`{name}` is shared mutable state and "
                          f"`{rec.name}` runs on a pump/handler thread "
                          f"with no lock held here — concurrent "
                          f"mutation corrupts it; guard it with a lock "
                          f"or make it thread-local")

    # -- driver --------------------------------------------------------

    def run(self) -> List[Finding]:
        self.discover()
        self.walk_all()
        self.propagate()
        self.rule_tz101()
        self.rule_tz102()
        self.rule_tz103()
        self.rule_tz104()
        self.rule_tz105_propagated()
        self.rule_tz107()
        self.findings.sort(key=lambda x: (x.path, x.line, x.rule))
        return self.findings


class _WalkCtx:
    def __init__(self) -> None:
        self.held: List[str] = []
        self.manual: List[str] = []
        self.protected: Set[str] = set()
        self.in_while = 0


def run_lockflow(tree: ast.Module, path: str, lines: List[str],
                 suppressed: Dict[int, Set[str]]) -> List[Finding]:
    """Run the TZ101..TZ108 concurrency pass over one parsed module."""
    return _Lockflow(tree, path, lines, suppressed).run()

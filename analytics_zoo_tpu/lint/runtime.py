"""tpulint runtime — retrace accounting the static rules cannot see.

The static analyzer proves shapes of code; it cannot prove that a
serving engine's steady-state decode compiles exactly once.  That is a
*dynamic* property: every new ``(shape, dtype, static-arg)`` signature
grows a jitted callable's compile cache by one, so the cache size IS
the retrace counter.  :class:`TraceGuard` snapshots cache sizes for a
set of jitted callables on entry and diffs them on exit — zero growth
means zero retraces.

Targets are resolved liberally: a jitted callable is tracked directly;
a dict/list/tuple is searched for jitted values; any other object has
``vars()`` walked one level (including dict/list attrs), which picks up
e.g. ``ContinuousEngine``'s ``_step_cache`` dict and ``_prefill``/
``_paged_admit`` attributes without the engine knowing the guard
exists.  Callables that *appear* inside a tracked container during the
guarded region (a fresh shape-bucket compile) count from zero — which
is exactly how the "one shape bucket per request" failure mode shows
up as a nonzero total.

Usage::

    with trace_guard(engine, budget=0):
        for _ in range(100):
            engine.step()          # raises RetraceError on any retrace
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

__all__ = ["RetraceError", "TraceGuard", "trace_guard", "retrace_count"]


class RetraceError(RuntimeError):
    """A jitted callable retraced more than its budget allows."""

    def __init__(self, message: str, counts: Dict[str, int]):
        super().__init__(message)
        self.counts = counts


def retrace_count(fn: Any) -> int:
    """Compile-cache size of a jitted callable (0 if unreadable)."""
    try:
        return int(fn._cache_size())
    except Exception:
        return 0


def _is_jitted(obj: Any) -> bool:
    return callable(obj) and callable(getattr(obj, "_cache_size", None))


def _collect(label: str, obj: Any, out: Dict[str, Any], depth: int) -> None:
    if _is_jitted(obj):
        out[label] = obj
        return
    if depth <= 0:
        return
    if isinstance(obj, dict):
        for k, v in obj.items():
            _collect(f"{label}[{k!r}]", v, out, depth - 1)
    elif isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            _collect(f"{label}[{i}]", v, out, depth - 1)
    else:
        try:
            attrs = vars(obj)
        except TypeError:
            return
        for k, v in attrs.items():
            _collect(f"{label}.{k}" if label else k, v, out, depth - 1)


class TraceGuard:
    """Context manager bounding retraces across a set of jitted
    callables.  ``budget`` is the total number of *new* traces allowed
    inside the guarded region (0 = steady state, nothing may compile).
    """

    def __init__(self, *targets: Any, budget: int = 0,
                 name: Optional[str] = None, telemetry: Any = None):
        self._targets: Tuple[Any, ...] = targets
        self.budget = int(budget)
        self.name = name or "trace_guard"
        # duck-typed serving.telemetry.Telemetry (this module must not
        # import the serving stack): each observed retrace is reported
        # via telemetry.retrace(label, count, region) on exit, whether
        # or not the budget tolerates it — the Perfetto timeline shows
        # WHEN a steady-state compile happened, not just that it did.
        # Falls back to the first target's own ``telemetry`` attribute
        # (an engine guard reports into that engine's event log with no
        # extra plumbing at the call site).
        if telemetry is None and targets:
            telemetry = getattr(targets[0], "telemetry", None)
        self._telemetry = telemetry if callable(
            getattr(telemetry, "retrace", None)) else None
        self._before: Dict[str, int] = {}
        self._entered = False

    def _snapshot(self) -> Dict[str, Any]:
        fns: Dict[str, Any] = {}
        for i, t in enumerate(self._targets):
            root = type(t).__name__ if not isinstance(t, (dict, list, tuple)) \
                else f"arg{i}"
            _collect(root if len(self._targets) > 1 or not _is_jitted(t)
                     else (getattr(t, "__name__", None) or root),
                     t, fns, depth=2)
        return fns

    def __enter__(self) -> "TraceGuard":
        self._before = {label: retrace_count(fn)
                        for label, fn in self._snapshot().items()}
        self._entered = True
        return self

    def counts(self) -> Dict[str, int]:
        """Retraces per callable since ``__enter__`` (new callables
        count their full cache size)."""
        out: Dict[str, int] = {}
        for label, fn in self._snapshot().items():
            grew = retrace_count(fn) - self._before.get(label, 0)
            if grew:
                out[label] = grew
        return out

    def total(self) -> int:
        return sum(self.counts().values())

    def __exit__(self, exc_type, exc, tb) -> bool:
        self._entered = False
        if exc_type is not None:
            return False
        counts = self.counts()
        total = sum(counts.values())
        if self._telemetry is not None:
            for label, grew in sorted(counts.items()):
                self._telemetry.retrace(label, grew, self.name)
        if total > self.budget:
            detail = ", ".join(f"{k}: +{v}" for k, v in
                               sorted(counts.items())) or "none"
            raise RetraceError(
                f"{self.name}: {total} retrace(s) exceed budget "
                f"{self.budget} ({detail}) — a steady-state hot loop "
                f"should not grow any compile cache; look for shape/"
                f"dtype drift or per-call jit construction", counts)
        return False


def trace_guard(*targets: Any, budget: int = 0,
                name: Optional[str] = None,
                telemetry: Any = None) -> TraceGuard:
    """Guard a region against retraces of ``targets`` (jitted
    callables, dicts of them, or objects holding them).  ``telemetry``
    (or the first target's own ``telemetry`` attribute) receives a
    ``retrace`` event per observed compile-cache growth."""
    return TraceGuard(*targets, budget=budget, name=name,
                      telemetry=telemetry)

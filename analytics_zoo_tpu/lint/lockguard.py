"""tpulint lockguard — runtime lock-discipline checks, the TraceGuard
twin of the static TZ1xx pass (lockflow.py).

The static pass proves shapes of code; it cannot see a lock that only
exists at runtime, a callback registered through three layers of
indirection, or the order two REAL threads actually take.  LockGuard
closes that gap in tests: it swaps every ``threading.Lock``/``RLock``
attribute of its targets (one ``vars()`` level deep, so an engine's
``telemetry`` sub-object's leaf locks are covered too) for an
instrumented wrapper that records, per thread,

- the **acquisition-order graph**: every (held A -> acquired B) edge
  with the source line that created it.  A cycle in that graph is a
  latent deadlock even if this run never interleaved into it —
  recording converts a probabilistic hang into a deterministic
  assertion;
- **under-lock blocking calls**: ``jax.device_get``/``device_put`` and
  ``time.sleep`` are patched (module attributes, restored on exit) to
  note when they run while the calling thread holds any instrumented
  lock — the runtime analog of TZ102;
- **hold sites**: the acquiring source line per lock, so a finding
  names code, not objects.

A same-thread re-acquire of a non-reentrant Lock raises
:class:`LockGuardError` immediately instead of deadlocking the test
run (the runtime analog of TZ105).

Usage::

    with lock_guard(engine) as lg:
        for _ in range(20):
            engine.step()
        lg.assert_clean()       # no inversions, nothing blocking

Static pass and runtime guard are cross-validated on the same
fixtures: ``tests/tpulint_fixtures/bad_tz104.py`` is importable, and
``test_lockguard.py`` drives its seeded inversion through both.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["LockGuardError", "LockGuard", "lock_guard"]

_LOCK_TYPES = (type(threading.Lock()), type(threading.RLock()))
_RLOCK_TYPE = type(threading.RLock())
_THIS_FILE = os.path.abspath(__file__)


class LockGuardError(AssertionError):
    """Lock discipline violated inside a guarded region."""


def _call_site() -> str:
    """`file:line` of the nearest stack frame outside this module."""
    for frame in reversed(traceback.extract_stack()):
        if os.path.abspath(frame.filename) != _THIS_FILE:
            return f"{frame.filename}:{frame.lineno}"
    return "<unknown>"


class _InstrumentedLock:
    """Duck-typed stand-in for a ``threading`` lock: delegates to the
    real lock, reporting every acquire/release to the guard."""

    def __init__(self, guard: "LockGuard", name: str, real: Any):
        self._guard = guard
        self.name = name
        self._real = real
        self._reentrant = isinstance(real, _RLOCK_TYPE)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._guard._before_acquire(self)
        got = self._real.acquire(blocking, timeout) if timeout != -1 \
            else self._real.acquire(blocking)
        if got:
            self._guard._acquired(self)
        return got

    def release(self) -> None:
        self._real.release()
        self._guard._released(self)

    def locked(self) -> bool:
        return self._real.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.release()


class LockGuard:
    """Context manager instrumenting the locks of ``targets`` and
    recording acquisition order, hold sites, and under-lock blocking
    calls.  ``patch_blocking=False`` skips the jax/time monkeypatches
    (pure order checking)."""

    def __init__(self, *targets: Any, name: Optional[str] = None,
                 patch_blocking: bool = True):
        self._targets = targets
        self.name = name or "lock_guard"
        self._patch_blocking = patch_blocking
        # (owner object, attr name, original lock) for restoration
        self._replaced: List[Tuple[Any, str, Any]] = []
        self._wrappers: Dict[int, _InstrumentedLock] = {}  # id(real)
        self._held = threading.local()
        self._rec = threading.Lock()    # guards the record dicts below
        # (outer name, inner name) -> "site (outer held at site)"
        self._edges: Dict[Tuple[str, str], str] = {}
        # (call label, locks held, site)
        self._blocking: List[Tuple[str, Tuple[str, ...], str]] = []
        self._patches: List[Tuple[Any, str, Any]] = []

    # -- instrumentation ----------------------------------------------

    def _wrap(self, owner: Any, attr: str, real: Any) -> None:
        w = self._wrappers.get(id(real))
        if w is None:
            w = _InstrumentedLock(
                self, f"{type(owner).__name__}.{attr}", real)
            self._wrappers[id(real)] = w
        setattr(owner, attr, w)
        self._replaced.append((owner, attr, real))

    def _instrument(self, obj: Any, depth: int) -> None:
        try:
            attrs = vars(obj)
        except TypeError:
            return
        for k, v in list(attrs.items()):
            if isinstance(v, _LOCK_TYPES):
                self._wrap(obj, k, v)
            elif isinstance(v, threading.Condition):
                # instrument the condition's inner lock: waiters and
                # notifiers then participate in the order graph.  Only
                # plain-Lock conditions — Condition captures an
                # RLock's _release_save/_acquire_restore as bound
                # methods at construction, which would bypass the
                # wrapper and unbalance the held stack
                inner = v._lock
                if type(inner) is _LOCK_TYPES[0] and \
                        id(inner) not in self._wrappers:
                    w = _InstrumentedLock(
                        self, f"{type(obj).__name__}.{k}", inner)
                    self._wrappers[id(inner)] = w
                    # Condition delegates acquire/release through
                    # attributes captured at construction — rebind them
                    v._lock = w
                    v.acquire = w.acquire
                    v.release = w.release
                    self._replaced.append((v, "_lock", inner))
                    self._replaced.append((v, "acquire", inner.acquire))
                    self._replaced.append((v, "release", inner.release))
            elif depth > 0 and hasattr(v, "__dict__") and \
                    not isinstance(v, type):
                self._instrument(v, depth - 1)

    def _patch(self, mod: Any, attr: str) -> None:
        orig = getattr(mod, attr, None)
        if orig is None:
            return
        label = f"{getattr(mod, '__name__', mod)}.{attr}"

        def wrapper(*a, _orig=orig, _label=label, **kw):
            held = tuple(l.name for l in self._stack())
            if held:
                with self._rec:
                    self._blocking.append((_label, held, _call_site()))
            return _orig(*a, **kw)

        setattr(mod, attr, wrapper)
        self._patches.append((mod, attr, orig))

    def __enter__(self) -> "LockGuard":
        for t in self._targets:
            self._instrument(t, depth=1)
        if self._patch_blocking:
            self._patch(time, "sleep")
            try:
                import jax
            except Exception:   # no jax in this env: order checks only
                jax = None
            if jax is not None:
                self._patch(jax, "device_get")
                self._patch(jax, "device_put")
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        for mod, attr, orig in reversed(self._patches):
            setattr(mod, attr, orig)
        for owner, attr, real in reversed(self._replaced):
            setattr(owner, attr, real)
        self._patches.clear()
        self._replaced.clear()
        return False

    # -- recording (called from _InstrumentedLock) --------------------

    def _stack(self) -> List[_InstrumentedLock]:
        if not hasattr(self._held, "stack"):
            self._held.stack = []
        return self._held.stack

    def _before_acquire(self, lock: _InstrumentedLock) -> None:
        stack = self._stack()
        if not lock._reentrant and any(l is lock for l in stack):
            raise LockGuardError(
                f"{self.name}: double-acquire of non-reentrant "
                f"{lock.name} at {_call_site()} — the un-guarded run "
                f"deadlocks here")

    def _acquired(self, lock: _InstrumentedLock) -> None:
        stack = self._stack()
        site = _call_site()
        with self._rec:
            for outer in stack:
                self._edges.setdefault((outer.name, lock.name), site)
        stack.append(lock)

    def _released(self, lock: _InstrumentedLock) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is lock:
                del stack[i]
                break

    # -- results ------------------------------------------------------

    def order_edges(self) -> Dict[Tuple[str, str], str]:
        """(held, acquired) -> source line that first recorded it."""
        with self._rec:
            return dict(self._edges)

    def inversions(self) -> List[str]:
        """Human-readable description of every cycle in the order
        graph (pairwise inversions and longer cycles)."""
        edges = self.order_edges()
        adj: Dict[str, List[str]] = {}
        for a, b in edges:
            adj.setdefault(a, []).append(b)

        def reaches(src: str, dst: str) -> bool:
            seen, work = set(), [src]
            while work:
                n = work.pop()
                if n == dst:
                    return True
                if n in seen:
                    continue
                seen.add(n)
                work.extend(adj.get(n, ()))
            return False

        out, seen_pairs = [], set()
        for (a, b), site in sorted(edges.items()):
            if frozenset((a, b)) in seen_pairs:
                continue
            if reaches(b, a):
                seen_pairs.add(frozenset((a, b)))
                back = edges.get((b, a))
                out.append(
                    f"{a} -> {b} at {site}"
                    + (f" inverts {b} -> {a} at {back}" if back
                       else f" closes a cycle back to {a}"))
        return out

    def blocking_calls(self) -> List[Tuple[str, Tuple[str, ...], str]]:
        """(call, locks held, site) for every patched blocking call
        that ran while this thread held an instrumented lock."""
        with self._rec:
            return list(self._blocking)

    def assert_clean(self) -> None:
        """Raise :class:`LockGuardError` on any recorded order
        inversion or under-lock blocking call."""
        problems = [f"lock-order inversion: {d}" for d in
                    self.inversions()]
        problems += [
            f"blocking call under lock: {call} holding "
            f"{', '.join(held)} at {site}"
            for call, held, site in self.blocking_calls()]
        if problems:
            raise LockGuardError(
                f"{self.name}: {len(problems)} lock-discipline "
                f"violation(s):\n  " + "\n  ".join(problems))


def lock_guard(*targets: Any, name: Optional[str] = None,
               patch_blocking: bool = True) -> LockGuard:
    """Guard a region with instrumented locks over ``targets`` (an
    engine, a store, any object holding ``threading`` locks one
    attribute level deep).  Pair with ``assert_clean()``."""
    return LockGuard(*targets, name=name, patch_blocking=patch_blocking)

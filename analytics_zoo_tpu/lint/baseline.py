"""tpulint baseline — the ledger of findings we deliberately keep.

A baseline entry matches a finding on ``(path, rule, text)`` where
``text`` is the stripped source line.  Matching on line *content*
instead of line *number* keeps the baseline stable under unrelated
edits above the finding; if the flagged line itself changes, the entry
stops matching and the finding resurfaces — which is the behaviour you
want when someone rewrites a deliberately-kept sync site.

Every entry carries a ``reason``: the one-line justification for why
the finding stays.  ``--write-baseline`` preserves reasons for entries
that still match and stamps ``TODO: justify`` on new ones, so an
unjustified baseline is visible in review.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

from analytics_zoo_tpu.lint.analyzer import Finding

_VERSION = 1


class Baseline:
    def __init__(self, entries: Optional[List[Dict[str, str]]] = None):
        self.entries: List[Dict[str, str]] = entries or []
        self._index: Dict[Tuple[str, str, str], Dict[str, str]] = {
            (e["path"], e["rule"], e["text"]): e for e in self.entries}

    def match(self, finding: Finding) -> Optional[Dict[str, str]]:
        return self._index.get((finding.path, finding.rule, finding.text))


def load_baseline(path: str) -> Baseline:
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if data.get("version") != _VERSION:
        raise ValueError(f"unsupported baseline version in {path}: "
                         f"{data.get('version')!r}")
    return Baseline(data.get("entries", []))


def apply_baseline(findings: Sequence[Finding], baseline: Optional[Baseline],
                   ) -> Tuple[List[Finding], List[Finding]]:
    """Split findings into (kept, suppressed-by-baseline)."""
    if baseline is None:
        return list(findings), []
    kept, suppressed = [], []
    for f in findings:
        (suppressed if baseline.match(f) else kept).append(f)
    return kept, suppressed


def stale_entries(baseline: Baseline, findings: Sequence[Finding],
                  analyzed_paths: Sequence[str]) -> List[Dict[str, str]]:
    """Baseline entries that matched NO finding in this run even
    though their file was analyzed — the flagged line moved enough to
    change its text, or the finding was fixed.  Either way the entry
    is dead weight that would silently shadow a future finding with
    the same text, so the CLI fails on it with a pointed message
    instead of ignoring it.  Entries for files outside the analyzed
    set are left alone (a partial run must not flag the rest of the
    ledger)."""
    matched = {(f.path, f.rule, f.text) for f in findings}
    analyzed = set(analyzed_paths)
    return [e for e in baseline.entries
            if e["path"] in analyzed
            and (e["path"], e["rule"], e["text"]) not in matched]


#: The placeholder ``--write-baseline`` stamps on a new entry.  An
#: entry still carrying it was never justified by a human; the CLI
#: fails an unfiltered run on it (same posture as a stale entry).
TODO_REASON = "TODO: justify"


def todo_entries(baseline: Baseline) -> List[Dict[str, str]]:
    """Baseline entries whose ``reason`` is still the write-time
    placeholder.  A baseline exists to carry *justified* exceptions;
    a ``TODO: justify`` that survives past its own PR is a suppressed
    finding nobody signed off on, so the CLI fails on it instead of
    letting the placeholder quietly become permanent."""
    return [e for e in baseline.entries
            if e.get("reason", "").strip() == TODO_REASON]


def write_baseline(path: str, findings: Sequence[Finding],
                   old: Optional[Baseline] = None) -> int:
    """Write all ``findings`` as the new baseline, preserving reasons
    from ``old`` where entries still match.  Returns the entry count."""
    entries: List[Dict[str, str]] = []
    seen = set()
    for f in findings:
        key = (f.path, f.rule, f.text)
        if key in seen:
            continue
        seen.add(key)
        prior = old.match(f) if old is not None else None
        entries.append({
            "path": f.path,
            "rule": f.rule,
            "line": f.line,        # informational; matching ignores it
            "text": f.text,
            "reason": (prior or {}).get("reason", TODO_REASON),
        })
    with open(path, "w", encoding="utf-8") as fp:
        json.dump({"version": _VERSION, "entries": entries}, fp, indent=2,
                  sort_keys=False)
        fp.write("\n")
    return len(entries)

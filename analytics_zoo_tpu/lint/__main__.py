import sys

from analytics_zoo_tpu.lint.cli import main

if __name__ == "__main__":
    sys.exit(main())

"""tpulint CLI — ``python -m analytics_zoo_tpu.lint <paths>``.

Exit codes: 0 clean (all findings baselined or none), 1 non-baselined
findings, 2 parse failures (reported as TZ000 alongside any findings).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from analytics_zoo_tpu.lint.analyzer import (DEFAULT_HOT_PATHS, RULES,
                                             analyze_paths)
from analytics_zoo_tpu.lint.baseline import (Baseline, apply_baseline,
                                             load_baseline, write_baseline)

DEFAULT_BASELINE = "tpulint_baseline.json"


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m analytics_zoo_tpu.lint",
        description="JAX staging/tracing analyzer (rules TZ001..TZ008). "
                    "See docs/lint.md for the rule catalog.")
    p.add_argument("paths", nargs="*", default=["analytics_zoo_tpu"],
                   help="files or directories to analyze "
                        "(default: analytics_zoo_tpu)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help=f"baseline file (default: {DEFAULT_BASELINE} "
                        f"if present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current findings to the baseline file "
                        "(preserving existing reasons) and exit 0")
    p.add_argument("--select", default=None, metavar="TZ001,TZ007",
                   help="comma-separated rule IDs to report (default all)")
    p.add_argument("--hot-path", action="append", default=None,
                   metavar="PAT", help="hot-path substring pattern for "
                   "TZ007 (repeatable; default: "
                   + ", ".join(DEFAULT_HOT_PATHS) + ")")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = _parser().parse_args(argv)

    if args.list_rules:
        for rid, desc in sorted(RULES.items()):
            print(f"{rid}  {desc}")
        return 0

    hot = tuple(args.hot_path) if args.hot_path else DEFAULT_HOT_PATHS
    findings = analyze_paths(args.paths, hot_paths=hot)

    if args.select:
        selected = {r.strip() for r in args.select.split(",")}
        findings = [f for f in findings if f.rule in selected]

    baseline_path = args.baseline or DEFAULT_BASELINE
    baseline: Optional[Baseline] = None
    if not args.no_baseline and not args.write_baseline and \
            os.path.exists(baseline_path):
        baseline = load_baseline(baseline_path)

    if args.write_baseline:
        old = load_baseline(baseline_path) if os.path.exists(baseline_path) \
            else None
        n = write_baseline(baseline_path, findings, old)
        print(f"tpulint: wrote {n} baseline entries to {baseline_path}",
              file=sys.stderr)
        return 0

    kept, suppressed = apply_baseline(findings, baseline)
    parse_failures = [f for f in kept if f.rule == "TZ000"]

    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_dict() for f in kept],
            "baselined": len(suppressed),
            "total": len(findings),
        }, indent=2))
    else:
        for f in kept:
            print(f.format())
        tail = f"tpulint: {len(kept)} finding(s)"
        if suppressed:
            tail += f", {len(suppressed)} baselined"
        print(tail, file=sys.stderr)

    if parse_failures:
        return 2
    return 1 if kept else 0

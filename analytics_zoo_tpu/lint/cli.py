"""tpulint CLI — ``python -m analytics_zoo_tpu.lint <paths>``.

Exit codes: 0 clean (all findings baselined or none), 1 non-baselined
findings or stale baseline entries, 2 parse failures (reported as
TZ000 alongside any findings).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from analytics_zoo_tpu.lint.analyzer import (DEFAULT_HOT_PATHS, RULES,
                                             analyze_paths, iter_py_files)
from analytics_zoo_tpu.lint.baseline import (Baseline, apply_baseline,
                                             load_baseline, stale_entries,
                                             todo_entries, write_baseline)

DEFAULT_BASELINE = "tpulint_baseline.json"


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m analytics_zoo_tpu.lint",
        description="JAX staging/tracing analyzer (rules TZ001..TZ008) "
                    "+ concurrency pass (TZ101..TZ108). "
                    "See docs/lint.md for the rule catalog.")
    p.add_argument("paths", nargs="*", default=["analytics_zoo_tpu"],
                   help="files or directories to analyze "
                        "(default: analytics_zoo_tpu)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help=f"baseline file (default: {DEFAULT_BASELINE} "
                        f"if present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current findings to the baseline file "
                        "(preserving existing reasons) and exit 0")
    p.add_argument("--select", default=None, metavar="TZ001,TZ007",
                   help="comma-separated rule IDs to report (default all)")
    p.add_argument("--rules", default=None, metavar="TZ1",
                   help="comma-separated rule-ID PREFIXES to report "
                        "(e.g. --rules TZ1 runs the concurrency family "
                        "in isolation); combines with --select")
    p.add_argument("--no-concurrency", action="store_true",
                   help="skip the TZ1xx lock-context pass (staging "
                        "rules only)")
    p.add_argument("--hot-path", action="append", default=None,
                   metavar="PAT", help="hot-path substring pattern for "
                   "TZ007 (repeatable; default: "
                   + ", ".join(DEFAULT_HOT_PATHS) + ")")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = _parser().parse_args(argv)

    if args.list_rules:
        for rid, desc in sorted(RULES.items()):
            print(f"{rid}  {desc}")
        return 0

    hot = tuple(args.hot_path) if args.hot_path else DEFAULT_HOT_PATHS
    findings = analyze_paths(args.paths, hot_paths=hot,
                             concurrency=not args.no_concurrency)

    filtered = False
    if args.select:
        filtered = True
        selected = {r.strip() for r in args.select.split(",")}
        findings = [f for f in findings if f.rule in selected]
    if args.rules:
        filtered = True
        prefixes = tuple(r.strip() for r in args.rules.split(","))
        findings = [f for f in findings if f.rule.startswith(prefixes)]

    baseline_path = args.baseline or DEFAULT_BASELINE
    baseline: Optional[Baseline] = None
    if not args.no_baseline and not args.write_baseline and \
            os.path.exists(baseline_path):
        baseline = load_baseline(baseline_path)

    if args.write_baseline:
        old = load_baseline(baseline_path) if os.path.exists(baseline_path) \
            else None
        n = write_baseline(baseline_path, findings, old)
        print(f"tpulint: wrote {n} baseline entries to {baseline_path}",
              file=sys.stderr)
        return 0

    kept, suppressed = apply_baseline(findings, baseline)
    parse_failures = [f for f in kept if f.rule == "TZ000"]

    # stale-entry detection: an entry whose file was analyzed but whose
    # (path, rule, text) matched nothing is dead — the line was fixed
    # or rewritten.  Only meaningful on an unfiltered run (a --select/
    # --rules/--no-concurrency run simply doesn't produce the family).
    stale: List[dict] = []
    todo: List[dict] = []
    if baseline is not None and not filtered and not args.no_concurrency:
        rel = os.getcwd()
        analyzed = [os.path.relpath(f, rel).replace(os.sep, "/")
                    for f in iter_py_files(args.paths)]
        stale = stale_entries(baseline, findings, analyzed)
        # unjustified entries fail the same unfiltered runs stale ones
        # do: a partial run must not nag about the rest of the ledger,
        # but CI's full run refuses a "TODO: justify" placeholder that
        # outlived its own PR
        todo = todo_entries(baseline)

    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_dict() for f in kept],
            "baselined": len(suppressed),
            "stale_baseline": stale,
            "todo_baseline": todo,
            "total": len(findings),
        }, indent=2))
    else:
        for f in kept:
            print(f.format())
        for e in stale:
            print(f"tpulint: stale baseline entry (source line moved or "
                  f"was fixed): {e['path']}: {e['rule']} \"{e['text']}\" "
                  f"— refresh with --write-baseline or delete the entry",
                  file=sys.stderr)
        for e in todo:
            print(f"tpulint: unjustified baseline entry: {e['path']}: "
                  f"{e['rule']} \"{e['text']}\" still says "
                  f"\"TODO: justify\" — replace the placeholder with "
                  f"the real reason this finding is kept",
                  file=sys.stderr)
        tail = f"tpulint: {len(kept)} finding(s)"
        if suppressed:
            tail += f", {len(suppressed)} baselined"
        if stale:
            tail += f", {len(stale)} STALE baseline entr" + \
                ("y" if len(stale) == 1 else "ies")
        if todo:
            tail += f", {len(todo)} UNJUSTIFIED baseline entr" + \
                ("y" if len(todo) == 1 else "ies")
        print(tail, file=sys.stderr)

    if parse_failures:
        return 2
    return 1 if kept or stale or todo else 0

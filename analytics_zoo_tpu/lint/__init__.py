"""tpulint — static + runtime staging/tracing analysis for JAX code.

Static half (``analyzer``): a stdlib-``ast`` linter with JAX-specific
rules (TZ001..TZ008) that understands which functions are traced —
reachability from ``jax.jit``/``pjit`` seeds through a local call graph
— so it can tell host orchestration code from staged code instead of
flagging the whole repo.

Runtime half (``runtime``): :func:`trace_guard`, a context manager that
counts retraces per jitted callable via the compile-cache size and
raises when a budget is exceeded — the dynamic complement the static
rules cannot express ("this decode loop retraces zero times in steady
state").

Run the CLI with ``python -m analytics_zoo_tpu.lint <paths>``.
"""

from analytics_zoo_tpu.lint.analyzer import (  # noqa: F401
    DEFAULT_HOT_PATHS,
    Finding,
    RULES,
    analyze_file,
    analyze_paths,
    analyze_source,
)
from analytics_zoo_tpu.lint.baseline import (  # noqa: F401
    apply_baseline,
    load_baseline,
    write_baseline,
)
from analytics_zoo_tpu.lint.runtime import (  # noqa: F401
    RetraceError,
    TraceGuard,
    retrace_count,
    trace_guard,
)

__all__ = [
    "DEFAULT_HOT_PATHS",
    "Finding",
    "RULES",
    "analyze_file",
    "analyze_paths",
    "analyze_source",
    "apply_baseline",
    "load_baseline",
    "write_baseline",
    "RetraceError",
    "TraceGuard",
    "retrace_count",
    "trace_guard",
]

"""tpulint — static + runtime staging/tracing/concurrency analysis.

Static half: a stdlib-``ast`` linter with JAX-specific staging rules
(``analyzer``, TZ001..TZ008) that understands which functions are
traced — reachability from ``jax.jit``/``pjit`` seeds through a local
call graph — so it can tell host orchestration code from staged code
instead of flagging the whole repo; plus a concurrency family
(``lockflow``, TZ101..TZ108) built on a lock-context analysis of the
same trees: held-lock sets per statement, propagated across
intra-module call edges, checking guarded-attribute discipline,
blocking calls and callback purity under locks, acquisition order,
release paths, threaded-entry-point state, and ``Condition.wait``
loops.

Runtime half: :func:`trace_guard` (``runtime``) counts retraces per
jitted callable via the compile-cache size and raises over budget;
:func:`lock_guard` (``lockguard``) instruments ``threading`` locks to
record acquisition order and under-lock blocking calls at test time —
each the dynamic complement of its static family, cross-validated on
the same fixtures.

Run the CLI with ``python -m analytics_zoo_tpu.lint <paths>``.
"""

from analytics_zoo_tpu.lint.analyzer import (  # noqa: F401
    DEFAULT_HOT_PATHS,
    Finding,
    RULES,
    analyze_file,
    analyze_paths,
    analyze_source,
)
from analytics_zoo_tpu.lint.baseline import (  # noqa: F401
    apply_baseline,
    load_baseline,
    stale_entries,
    write_baseline,
)
from analytics_zoo_tpu.lint.lockflow import (  # noqa: F401
    LOCK_RULES,
    run_lockflow,
)
from analytics_zoo_tpu.lint.lockguard import (  # noqa: F401
    LockGuard,
    LockGuardError,
    lock_guard,
)
from analytics_zoo_tpu.lint.runtime import (  # noqa: F401
    RetraceError,
    TraceGuard,
    retrace_count,
    trace_guard,
)

__all__ = [
    "DEFAULT_HOT_PATHS",
    "Finding",
    "RULES",
    "LOCK_RULES",
    "analyze_file",
    "analyze_paths",
    "analyze_source",
    "apply_baseline",
    "load_baseline",
    "stale_entries",
    "write_baseline",
    "run_lockflow",
    "LockGuard",
    "LockGuardError",
    "lock_guard",
    "RetraceError",
    "TraceGuard",
    "retrace_count",
    "trace_guard",
]

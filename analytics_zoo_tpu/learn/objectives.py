"""Loss functions (reference parity: Keras-API objectives,
ref: zoo/pipeline/api/keras/objectives/ + pyzoo mirrors).

All losses take ``(preds, targets)`` and return a scalar mean loss; all are
pure jnp so they fuse into the train step.  String names accepted by
Estimators resolve through ``get_loss``.
"""

from __future__ import annotations

from typing import Callable, Union

import jax.numpy as jnp
import optax

LossFn = Callable[..., jnp.ndarray]


def mean_squared_error(preds, targets):
    return jnp.mean(jnp.square(preds - targets))


def mean_absolute_error(preds, targets):
    return jnp.mean(jnp.abs(preds - targets))


def binary_crossentropy(logits, targets):
    """Targets in {0,1}; preds are logits (pre-sigmoid)."""
    return jnp.mean(
        optax.sigmoid_binary_cross_entropy(logits, targets.astype(jnp.float32)))


def sparse_categorical_crossentropy(logits, labels):
    """Integer labels; logits pre-softmax."""
    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(
            logits, labels.astype(jnp.int32)))


def categorical_crossentropy(logits, onehot):
    return jnp.mean(optax.softmax_cross_entropy(logits, onehot))


def huber(preds, targets, delta: float = 1.0):
    return jnp.mean(optax.huber_loss(preds, targets, delta))


_LOSSES = {
    "mse": mean_squared_error,
    "mean_squared_error": mean_squared_error,
    "mae": mean_absolute_error,
    "mean_absolute_error": mean_absolute_error,
    "bce": binary_crossentropy,
    "binary_crossentropy": binary_crossentropy,
    "sparse_categorical_crossentropy": sparse_categorical_crossentropy,
    "categorical_crossentropy": categorical_crossentropy,
    "huber": huber,
}


def get_loss(loss: Union[str, LossFn]) -> LossFn:
    if callable(loss):
        return loss
    key = str(loss).lower()
    if key not in _LOSSES:
        raise ValueError(f"unknown loss {loss!r}; known: {sorted(_LOSSES)}")
    return _LOSSES[key]

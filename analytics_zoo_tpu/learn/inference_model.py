"""InferenceModel — unified, thread-safe batched inference.

Reference surface (SURVEY.md §2.3; ref: Scala pipeline/inference/
InferenceModel.scala + AbstractModel/FloatModel, OpenVinoInferenceSupportive
JNI): one handle that loads BigDL/Caffe/TF/Torch/OpenVINO-IR models and
serves thread-safe ``predict`` from a pool of native predictors (int8
calibration optional).

TPU re-design: the "multi-format zoo" collapses to flax modules + orbax
param trees (anything exported by ``Estimator.save``); XLA replaces the
predictor pool — compiled executables are thread-safe, so concurrency
needs only a lock around the compile cache, not N model replicas.
Variable request sizes hit a BUCKETED jit cache (next-pow2 padding), the
TPU analog of OpenVINO's fixed-shape compiled networks: a bounded set of
compiled programs, no recompile per request size.  The reference's int8
calibration role is filled by ``load_flax(..., quantize="int8")`` —
weight-only symmetric int8 with dequant fused into the jitted forward
(learn/quantize.py; measured ~4x weight compression, sub-5% logit
deviation, no calibration set needed).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Sequence, Tuple

import jax
import numpy as np


def _next_bucket(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def filter_prompt_buckets(prompt_buckets: Sequence[int],
                          max_position: int,
                          max_new_tokens: int) -> Tuple[int, ...]:
    """Prompt buckets usable by a generator: a bucket only counts if the
    padded prompt + generation still fits the model's position table.
    Shared by load_flax_generator and ContinuousEngine so the two entry
    paths can never disagree about which prompts are servable."""
    limit = int(max_position) - int(max_new_tokens)
    out = tuple(b for b in sorted(set(int(b) for b in prompt_buckets))
                if b <= limit)
    if not out:
        raise ValueError(
            f"no prompt bucket fits: max_position {max_position} - "
            f"max_new_tokens {max_new_tokens} = {limit} < smallest "
            f"bucket {min(prompt_buckets)}")
    return out


class InferenceModel:
    """ref-parity methods: load / predict / (doLoadTF etc. collapse to
    ``load``).

    Args:
      concurrent_num: kept for API parity (the reference sized its
        predictor pool with it); XLA needs no pool, so it only caps the
        semaphore guarding host-side staging memory.
    """

    def __init__(self, concurrent_num: int = 4,
                 batch_buckets: Sequence[int] = (1, 8, 32, 128)):
        self._apply_fn: Optional[Callable] = None
        self._variables = None
        self._buckets = tuple(sorted(batch_buckets))
        self._jit: Optional[Callable] = None
        self._jit_outer = True  # False = host-loop apply_fn (spec decode)
        self.spec_stats = None  # cumulative speculative-decoding stats
        self._compile_lock = threading.Lock()
        self._sem = threading.Semaphore(max(1, concurrent_num))
        self._takes_train: Optional[str] = None
        # optional host-side input normaliser (generator prompt padding)
        self._pre_pad: Optional[Callable] = None
        # generator-only serving bounds (load_flax_generator sets them)
        self.max_prompt_width: Optional[int] = None
        self.prompt_pad_id: Optional[int] = None

    # ---- loading -----------------------------------------------------

    def _install_quantized(self, variables, quantize,
                           allow_mxu: bool = False):
        """Shared weight-quantization staging for every load path:
        quantize the tree, stage it in device memory ONCE (the numpy
        leaves quantize_params builds would otherwise be re-uploaded on
        every predict call), and install the fused dequant.

        ``int8_mxu`` (on-MXU execution) is only valid where the model is
        a flax-linen tree the method interceptor can rewrite —
        ``load_flax`` sets ``allow_mxu``; importer-wrapped models
        (OpenVINO/TF/torch translators) and the generation scan keep the
        weight-only modes."""
        self.quant_stats = None
        self._int8_mxu = False
        if quantize == "int8_mxu" and not allow_mxu:
            raise ValueError(
                "quantize='int8_mxu' is only supported by load_flax "
                "(flax-linen models); use 'int8' (weight-only) here")
        if quantize:
            from analytics_zoo_tpu.learn.quantize import (
                dequantize, quantize_params)

            mode = quantize
            if quantize == "int8_mxu":
                mode = "int8"           # same storage format
                self._int8_mxu = True
            variables, self.quant_stats = quantize_params(variables,
                                                          mode)
            variables = jax.device_put(variables)
            self._dequant = None if self._int8_mxu else dequantize
        else:
            self._dequant = None
        return variables

    def load_flax(self, model, variables,
                  quantize: Optional[str] = None) -> "InferenceModel":
        """Serve a flax module with a {'params': ..., [...]} tree.

        quantize: None | "int8" (weight-only symmetric int8, per-channel
        scales, dequant fused into the jitted forward — the reference's
        OpenVINO int8 role; the memory-capacity mode) | "int8_mxu"
        (on-MXU int8: dynamic per-tensor activation quantization and
        int8 x int8 -> int32 Dense/Conv — the speed mode, ~2x MXU
        int8 rate; docs/serving.md) | "bf16" (cast weights to bfloat16).
        ``self.quant_stats`` reports the measured weight-bytes compression.
        """
        import inspect

        self.model = model
        self._variables = self._install_quantized(variables, quantize,
                                                  allow_mxu=True)
        self._takes_train = None    # re-derive per model: a stale value
        #                             from a previous load would pass an
        #                             unexpected kwarg into the new model
        try:
            sig = inspect.signature(type(model).__call__)
            if "train" in sig.parameters:
                self._takes_train = "train"
            elif "deterministic" in sig.parameters:
                self._takes_train = "deterministic"
        except (TypeError, ValueError):
            pass

        int8_mxu = self._int8_mxu

        def apply_fn(variables, *feats):
            if self._dequant is not None:
                variables = self._dequant(variables)
            kw = {}
            if self._takes_train == "train":
                kw["train"] = False
            elif self._takes_train == "deterministic":
                kw["deterministic"] = True
            if int8_mxu:
                from analytics_zoo_tpu.learn.quantize import int8_call

                return int8_call(model, variables, *feats, **kw)
            return model.apply(variables, *feats, **kw)

        with self._compile_lock:
            # publish the new model and drop the stale wrapper as one
            # step: a predict() compiling concurrently must not publish
            # a wrapper built from the OLD apply_fn over this reset
            self._apply_fn = apply_fn
            self._jit = None    # new model -> stale compiled wrapper
        self._pre_pad = None    # a stale generator pad hook would corrupt
        #                         plain-model inputs
        self.max_prompt_width = None    # ditto the serving bounds limit
        self.prompt_pad_id = None
        self._gen_max_new_tokens = None
        self._jit_outer = True  # ditto a stale host-loop (draft) flag
        self.spec_stats = None  # ditto stale speculative stats
        self._spec_draft = False
        return self

    def load_flax_generator(self, model, variables, max_new_tokens: int,
                            prompt_buckets: Sequence[int] = (16, 32, 64,
                                                             128),
                            pad_id: int = 0,
                            quantize: Optional[str] = None,
                            draft_model=None, draft_variables=None,
                            speculation_k: int = 4
                            ) -> "InferenceModel":
        """Serve autoregressive GENERATION from a TransformerLM: predict
        takes right-padded prompts [B, P] (+ optional per-row lengths [B])
        and returns [B, max_new_tokens] generated token ids.

        The prompt dim is padded up to ``prompt_buckets`` (the seq-dim
        analog of the batch buckets) so the KV-cache generation scan
        compiles a bounded set of shapes.  When lengths are omitted they
        are inferred as the non-``pad_id`` trailing-pad width of each row.
        ``quantize``: None | "int8" | "bf16" — same weight-only scheme as
        ``load_flax`` (dequant fused into the jitted scan), covering the
        int8-LLM-serving role.  No reference counterpart (SURVEY.md §2.5:
        no generative LM upstream) — the serving face of
        models/lm.generate.

        ``draft_model``/``draft_variables`` switch decoding to
        SPECULATIVE (models/speculative.py): the draft proposes
        ``speculation_k`` tokens per round and the target verifies them
        in one cached forward — identical greedy output, fewer
        host round-trips per token by the acceptance rate.  Per-request
        stats land in ``self.spec_stats``.  ``quantize`` applies to
        the TARGET only (the draft is small; quantizing it buys little).
        """
        from analytics_zoo_tpu.models.lm import generate

        if (draft_model is None) != (draft_variables is None):
            raise ValueError("pass draft_model and draft_variables "
                             "together (or neither)")
        self.model = model
        self._variables = self._install_quantized(variables, quantize)
        self._takes_train = None
        # a bucket only counts if the padded prompt + generation still
        # fits the model's position table — otherwise a prompt that
        # genuinely fits would fail generate()'s length check after
        # bucket padding.  Speculative decoding needs k+1 extra cache
        # slack (verify overshoot) and must fit BOTH models' position
        # tables, so its limit is tighter — validated HERE so a request
        # the serving bounds-check admits can never fail at predict time.
        eff_max_pos = model.max_position
        eff_new = max_new_tokens
        if draft_model is not None:
            eff_max_pos = min(model.max_position,
                              draft_model.max_position)
            eff_new = max_new_tokens + int(speculation_k) + 1
        pbuckets = filter_prompt_buckets(prompt_buckets,
                                         eff_max_pos, eff_new)
        # serving batcher reads these to bounds-check ragged prompts
        # per-request and to cross-check its own pad id against the
        # generator's (a mismatch would silently miscount prompt lengths)
        self.max_prompt_width = pbuckets[-1]
        self.prompt_pad_id = int(pad_id)
        # continuous-batching serving builds its engine from these
        self._gen_max_new_tokens = int(max_new_tokens)
        self._gen_prompt_buckets = pbuckets

        if draft_model is not None:
            from analytics_zoo_tpu.models.speculative import (
                speculative_generate)

            # host-loop apply_fn: a fused dequant would re-run EAGERLY
            # per request (no outer jit to fold it into) — dequantize
            # once at load instead, like make_continuous_engine
            if self._dequant is not None:
                self._variables = jax.device_put(
                    self._dequant(self._variables))
                self._dequant = None

            def apply_fn(variables, prompts, lengths):
                # host-loop orchestration (each round is jitted inside);
                # _compiled() must NOT wrap this in an outer jit
                toks, stats = speculative_generate(
                    model, variables, draft_model, draft_variables,
                    prompts, max_new_tokens, k=speculation_k,
                    prompt_len=lengths)
                # CUMULATIVE since load (lock: predicts may run from
                # several serving threads; chunked predicts call this
                # once per chunk) — a per-request hook would be racy.
                # Batch-bucket padding adds phantom all-pad rows whose
                # lengths are 0 (pre_pad rejects real empty prompts):
                # count only REAL rows or the acceptance diagnostic
                # reflects padding, not traffic.
                real = np.asarray(lengths) > 0
                with self._spec_stats_lock:
                    agg = self.spec_stats or {
                        "rounds": 0, "emitted_tokens": 0,
                        "row_rounds": 0}
                    agg["rounds"] += stats["rounds"]
                    agg["emitted_tokens"] += int(
                        stats["per_row_emitted"][real].sum())
                    agg["row_rounds"] += stats["rounds"] * int(real.sum())
                    agg["mean_accepted_per_round"] = (
                        agg["emitted_tokens"] / max(1, agg["row_rounds"]))
                    self.spec_stats = agg
                return toks

            self._jit_outer = False
            self._spec_stats_lock = threading.Lock()
            self.spec_stats = None
            self._spec_draft = True
            self._spec_draft_model = draft_model
            self._spec_draft_variables = draft_variables
            self._spec_k = int(speculation_k)
        else:
            def apply_fn(variables, prompts, lengths):
                if self._dequant is not None:
                    variables = self._dequant(variables)
                return generate(model, variables, prompts,
                                max_new_tokens, prompt_len=lengths)

            self._jit_outer = True
            self.spec_stats = None      # stale draft-run stats would lie
            self._spec_draft = False

        def pre_pad(inputs):
            prompts = np.asarray(inputs[0])
            if len(inputs) > 1:
                lengths = np.asarray(inputs[1], np.int32)
            else:
                nonpad = prompts != pad_id
                # length = index of last non-pad + 1 (right padding)
                lengths = np.where(
                    nonpad.any(axis=1),
                    prompts.shape[1] - np.argmax(nonpad[:, ::-1], axis=1),
                    0).astype(np.int32)
            if (lengths <= 0).any():
                raise ValueError(
                    "empty prompt (length 0) — generation needs at least "
                    "one real token per row")
            pb = _next_bucket(prompts.shape[1], pbuckets)
            if prompts.shape[1] < pb:
                prompts = np.concatenate(
                    [prompts, np.full((len(prompts), pb - prompts.shape[1]),
                                      pad_id, prompts.dtype)], axis=1)
            elif prompts.shape[1] > pb:
                raise ValueError(
                    f"prompt length {prompts.shape[1]} exceeds the largest "
                    f"usable prompt bucket {pb}")
            return prompts, lengths

        with self._compile_lock:
            # same publish discipline as load_flax: new apply_fn and
            # wrapper reset are atomic against a concurrent compile
            self._apply_fn = apply_fn
            self._jit = None
        self._pre_pad = pre_pad
        return self

    def make_continuous_engine(self, max_slots: int = 8,
                               eos_id: Optional[int] = None,
                               ticks_per_step: int = 1,
                               cache_dtype=None,
                               kernel: str = "gather",
                               kv_dtype: Optional[str] = None,
                               mesh=None, partition_rules=None,
                               paged: bool = False,
                               block_size: int = 16,
                               n_blocks: Optional[int] = None,
                               hbm_fraction: Optional[float] = None,
                               enable_prefix_cache: bool = True,
                               chunked: bool = False,
                               tick_token_budget: Optional[int] = None,
                               speculation_k: Optional[int] = None,
                               elastic_pool: bool = False,
                               kv_host_store_bytes: int = 0,
                               prefix_directory=None,
                               replica_id: int = 0,
                               fault_injector=None,
                               record_timings: bool = False,
                               telemetry=None, qos=None,
                               flight=None, flight_capacity: int = 2048):
        """Build a ``serving.continuous.ContinuousEngine`` from a model
        loaded via ``load_flax_generator`` (quantized weights dequantize
        once at build — the engine trades the at-rest memory win for
        per-token speed; keep the batch path for memory-bound serving).

        ``mesh`` (with a ``tp`` axis) serves models beyond one chip's
        HBM: weights + KV arena shard over tp (docs/serving.md
        'tp-sharded generation').

        ``paged=True`` swaps the per-slot KV arena for the block-pool
        cache (serving/paged_cache.py: pay-as-you-grow block
        allocation, automatic prefix sharing, preemption-to-queue —
        docs/serving_memory.md); ``block_size``/``n_blocks``/
        ``hbm_fraction``/``enable_prefix_cache`` size and tune it.
        ``kernel="fused"`` reads the pool through the Pallas
        paged-attention kernel instead of the gather reference, and
        ``kv_dtype="int8"`` stores blocks quantized with per-row
        scales (~1.9x more blocks at equal HBM) — both paged-only
        (docs/serving_memory.md 'Fused kernel & int8 blocks').

        ``chunked=True`` turns on the token-budget tick scheduler:
        prompts prefill in ``tick_token_budget``-bounded chunks fused
        with active decodes in one device call per tick — long joiners
        stop stalling residents (docs/serving_memory.md 'Scheduler').

        A draft-loaded handle (``load_flax_generator(draft_model=...)``)
        builds a SPECULATIVE engine; it composes with ``paged`` and
        ``chunked`` freely (docs/serving_memory.md 'Composed modes').
        ``speculation_k`` overrides the per-round proposal depth stored
        at load (``None`` keeps it); it is rejected without a draft.

        ``qos`` (a ``serving.frontdoor.QosPolicy``) turns admission and
        prefill-grant order into a weighted fair share over (priority
        class, tenant) — the serving front door's scheduler
        (docs/serving_qos.md).  ``None`` keeps plain FIFO.

        ``elastic_pool=True`` (paged only) arms the elastic block
        pool: the engine probes free HBM for a grow ceiling at build
        and ``maybe_autoresize``/``resize_pool`` then move ``n_blocks``
        in block-granular steps at the eviction boundary
        (docs/serving_memory.md 'Disaggregation & elastic pools').

        ``kv_host_store_bytes`` (paged only, no draft) arms the tiered
        KV memory: evicted prefix chains spill to a bounded host-RAM
        store and re-admit at admission via a host->HBM copy instead
        of a re-prefill; ``prefix_directory`` (a shared
        ``serving.kv_store.PrefixDirectory``) plus ``replica_id``
        additionally publish this engine's prefix residency fleet-wide
        for locality-aware routing (docs/serving_memory.md
        'Tiered KV memory').

        ``flight`` / ``flight_capacity`` configure the engine's
        always-on per-tick flight recorder (serving/flight.py;
        ``flight_capacity=0`` disables, a shared
        ``flight.FlightRecorder`` can be passed in so the serving
        layer can bundle it — docs/debugging.md)."""
        from analytics_zoo_tpu.serving.continuous import ContinuousEngine

        if getattr(self, "_gen_max_new_tokens", None) is None:
            raise ValueError("continuous batching needs a model loaded "
                             "via load_flax_generator")
        variables = self._variables
        if self._dequant is not None:
            variables = jax.device_put(self._dequant(variables))
        spec = {}
        if getattr(self, "_spec_draft", False):
            # a draft-loaded handle builds a SPECULATIVE engine: the
            # spec-tightened prompt buckets stored at load (k+1 slack,
            # both position tables) are exactly the engine's own limit
            spec = dict(draft_model=self._spec_draft_model,
                        draft_variables=self._spec_draft_variables,
                        speculation_k=(self._spec_k
                                       if speculation_k is None
                                       else int(speculation_k)))
        elif speculation_k is not None:
            raise ValueError(
                "speculation_k needs a draft model: load one via "
                "load_flax_generator(draft_model=..., "
                "draft_variables=...)")
        return ContinuousEngine(
            self.model, variables,
            max_new_tokens=self._gen_max_new_tokens,
            max_slots=max_slots,
            prompt_buckets=self._gen_prompt_buckets,
            eos_id=eos_id, pad_id=self.prompt_pad_id,
            ticks_per_step=ticks_per_step, cache_dtype=cache_dtype,
            kernel=kernel, kv_dtype=kv_dtype,
            mesh=mesh, partition_rules=partition_rules,
            paged=paged, block_size=block_size, n_blocks=n_blocks,
            hbm_fraction=hbm_fraction,
            enable_prefix_cache=enable_prefix_cache,
            chunked=chunked, tick_token_budget=tick_token_budget,
            elastic_pool=elastic_pool,
            kv_host_store_bytes=kv_host_store_bytes,
            prefix_directory=prefix_directory, replica_id=replica_id,
            fault_injector=fault_injector,
            record_timings=record_timings, telemetry=telemetry,
            qos=qos, flight=flight, flight_capacity=flight_capacity,
            **spec)

    def load_openvino(self, xml_path: str, bin_path: str = None,
                      quantize: Optional[str] = None) -> "InferenceModel":
        """ref-parity: InferenceModel.loadOpenVINO — an OpenVINO IR
        (.xml + .bin) served on TPU via the net/openvino_ir.py
        translator; ``quantize="int8"`` covers the IR int8-calibration
        role (weight-only, no calibration set needed)."""
        from analytics_zoo_tpu.net.openvino_ir import OpenVINONet

        net = OpenVINONet.from_ir(xml_path, bin_path)
        return self.load_flax(net, net.init(None), quantize=quantize)

    def load_tf(self, path_or_fn, signature: str = "serving_default",
                quantize: Optional[str] = None) -> "InferenceModel":
        """ref-parity: InferenceModel.loadTF — a SavedModel dir (local or
        remote gs://, s3://, hdfs://; TF's filesystem layer resolves it),
        keras file, or concrete tf.function served on TPU via the TFNet
        translator."""
        from analytics_zoo_tpu.net import Net

        net = Net.load_tf(path_or_fn, signature=signature)
        return self.load_flax(net, net.init(None), quantize=quantize)

    def load_torch(self, module) -> "InferenceModel":
        """ref-parity: InferenceModel.loadTorch — a torch nn.Module (or
        path torch.load can read) served on TPU via TorchNet conversion."""
        from analytics_zoo_tpu.net import Net, TorchNet

        net = module if isinstance(module, TorchNet) \
            else Net.load_torch(module)
        return self.load_flax(net, net.init(None))

    def load(self, path: str, model) -> "InferenceModel":
        """Restore an ``Estimator.save`` export for `model` (flax module).

        The orbax payload is {'params': ..., optional 'batch_stats': ...}
        (see learn/estimator.py save()).
        """
        import os

        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        restored = ckptr.restore(os.path.abspath(path))
        return self.load_flax(model, restored)

    # ---- predict -----------------------------------------------------

    def _compiled(self) -> Callable:
        # one jit wrapper; jax's own per-shape trace cache (driven by the
        # bucket padding in predict) bounds compilations.  Host-loop
        # apply_fns (speculative decoding) jit their own inner rounds
        # and must not be wrapped again.
        if not getattr(self, "_jit_outer", True):
            return self._apply_fn
        with self._compile_lock:
            if self._jit is None:
                self._jit = jax.jit(self._apply_fn)
            return self._jit

    def predict(self, *inputs: np.ndarray) -> np.ndarray:
        """Batched forward; inputs are [N, ...] host arrays. N is padded
        up to the next bucket so compiled-shape count stays bounded."""
        return self.predict_async(*inputs)()

    def predict_async(self, *inputs: np.ndarray) -> Callable[[], np.ndarray]:
        """Dispatch the forward WITHOUT blocking on the device.

        Returns a zero-arg callable that blocks until the result is ready
        and yields the numpy output.  XLA dispatch is asynchronous, so the
        host can batch/decode the next request while this one computes —
        the serving loop's pipelining hook."""
        if self._apply_fn is None:
            raise RuntimeError("load a model first")
        if self._pre_pad is not None:
            inputs = self._pre_pad(inputs)
        n = len(inputs[0])
        bucket = _next_bucket(n, self._buckets)
        if n > bucket:          # n above the largest bucket: chunk
            # serial chunking keeps device memory bounded to ONE chunk in
            # flight (dispatch-all would stage the entire input in HBM)
            return lambda: self._predict_chunked(inputs, bucket)
        padded = []
        for a in inputs:
            a = np.asarray(a)
            if len(a) < bucket:
                pad = np.zeros((bucket - len(a),) + a.shape[1:], a.dtype)
                a = np.concatenate([a, pad])
            padded.append(a)
        with self._sem:
            out = self._compiled()(
                self._variables, *padded)
        # start the D2H transfer now: on tunneled/remote devices the fetch
        # round-trip dominates, so it must overlap the next batch's compute
        jax.tree.map(lambda x: x.copy_to_host_async(), out)
        return lambda: jax.tree.map(lambda x: np.asarray(x)[:n], out)

    def _predict_chunked(self, inputs, bucket: int):
        n = len(inputs[0])
        outs = []
        for lo in range(0, n, bucket):
            outs.append(self.predict(*[np.asarray(a)[lo:lo + bucket]
                                       for a in inputs]))
        return jax.tree.map(lambda *xs: np.concatenate(xs), *outs)

    def set_concurrency(self, n: int) -> "InferenceModel":
        """Resize the host-staging semaphore (ServingConfig.core_number)."""
        self._sem = threading.Semaphore(max(1, n))
        return self

"""Triggers — checkpoint/validation cadence control.

Reference parity (ref: BigDL Trigger zoo surfaced via
pyzoo/zoo/pipeline/api/keras/optimizers + Estimator.set_checkpoint;
SURVEY.md §5 checkpoint/resume): EveryEpoch, SeveralIteration, MaxEpoch,
MaxIteration, MinLoss, MaxScore, And/Or combinators.
"""

from __future__ import annotations

from typing import Dict


class Trigger:
    def __call__(self, state: Dict) -> bool:  # state: step/epoch/metrics
        raise NotImplementedError

    def __and__(self, other):
        return _And(self, other)

    def __or__(self, other):
        return _Or(self, other)


class EveryEpoch(Trigger):
    """Fires at each epoch boundary (state['epoch_end'] flag)."""

    def __call__(self, s):
        return bool(s.get("epoch_end"))


class SeveralIteration(Trigger):
    def __init__(self, interval: int):
        self.interval = max(1, interval)

    def __call__(self, s):
        step = s.get("step", 0)
        return step > 0 and step % self.interval == 0


class MaxIteration(Trigger):
    def __init__(self, n: int):
        self.n = n

    def __call__(self, s):
        return s.get("step", 0) >= self.n


class MaxEpoch(Trigger):
    def __init__(self, n: int):
        self.n = n

    def __call__(self, s):
        return s.get("epoch", 0) >= self.n


class MinLoss(Trigger):
    def __init__(self, min_loss: float):
        self.min_loss = min_loss

    def __call__(self, s):
        loss = s.get("metrics", {}).get("loss")
        return loss is not None and loss < self.min_loss


class MaxScore(Trigger):
    def __init__(self, metric: str, max_score: float):
        self.metric, self.max_score = metric, max_score

    def __call__(self, s):
        v = s.get("metrics", {}).get(self.metric)
        return v is not None and v > self.max_score


class _And(Trigger):
    def __init__(self, a, b):
        self.a, self.b = a, b

    def __call__(self, s):
        return self.a(s) and self.b(s)


class _Or(Trigger):
    def __init__(self, a, b):
        self.a, self.b = a, b

    def __call__(self, s):
        return self.a(s) or self.b(s)


class EarlyStopping:
    """Epoch-end callback: stop fit() when a monitored metric hasn't
    improved for `patience` epochs (keras-parity training control; the
    reference's closest analog is the MinLoss/MaxScore end triggers).

    Pass an instance in ``fit(callbacks=[EarlyStopping(...)])``; it
    returns True from its callback to request the stop.  ``best`` and
    ``stopped_epoch`` are inspectable afterwards.
    """

    # opt-in marker: fit() only honors stop-requesting return values from
    # callbacks that declare it (ordinary loggers can't truncate a run)
    requests_stop = True

    def __init__(self, monitor: str = "val_loss", patience: int = 3,
                 min_delta: float = 0.0, mode: str = "min"):
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be 'min' or 'max', got {mode!r}")
        self.monitor = monitor
        self.patience = max(1, int(patience))
        self.min_delta = float(min_delta)
        self.mode = mode
        self.reset()

    def reset(self):
        """Fresh tracking state; fit() calls this at train start so an
        instance can be reused across fit() calls (keras on_train_begin
        semantics)."""
        self.best = None
        self.wait = 0
        self.stopped_epoch = None

    def _improved(self, v: float) -> bool:
        if self.best is None:
            return True
        if self.mode == "min":
            return v < self.best - self.min_delta
        return v > self.best + self.min_delta

    def __call__(self, stats: dict):
        v = stats.get(self.monitor)
        if v is None:
            import logging

            logging.getLogger("analytics_zoo_tpu").warning(
                "EarlyStopping: metric %r not in epoch stats %s",
                self.monitor, sorted(stats))
            return False
        if self._improved(float(v)):
            self.best = float(v)
            self.wait = 0
            return False
        self.wait += 1
        if self.wait >= self.patience:
            self.stopped_epoch = stats.get("epoch")
            return True
        return False

"""Triggers — checkpoint/validation cadence control.

Reference parity (ref: BigDL Trigger zoo surfaced via
pyzoo/zoo/pipeline/api/keras/optimizers + Estimator.set_checkpoint;
SURVEY.md §5 checkpoint/resume): EveryEpoch, SeveralIteration, MaxEpoch,
MaxIteration, MinLoss, MaxScore, And/Or combinators.
"""

from __future__ import annotations

from typing import Dict


class Trigger:
    def __call__(self, state: Dict) -> bool:  # state: step/epoch/metrics
        raise NotImplementedError

    def __and__(self, other):
        return _And(self, other)

    def __or__(self, other):
        return _Or(self, other)


class EveryEpoch(Trigger):
    """Fires at each epoch boundary (state['epoch_end'] flag)."""

    def __call__(self, s):
        return bool(s.get("epoch_end"))


class SeveralIteration(Trigger):
    def __init__(self, interval: int):
        self.interval = max(1, interval)

    def __call__(self, s):
        step = s.get("step", 0)
        return step > 0 and step % self.interval == 0


class MaxIteration(Trigger):
    def __init__(self, n: int):
        self.n = n

    def __call__(self, s):
        return s.get("step", 0) >= self.n


class MaxEpoch(Trigger):
    def __init__(self, n: int):
        self.n = n

    def __call__(self, s):
        return s.get("epoch", 0) >= self.n


class MinLoss(Trigger):
    def __init__(self, min_loss: float):
        self.min_loss = min_loss

    def __call__(self, s):
        loss = s.get("metrics", {}).get("loss")
        return loss is not None and loss < self.min_loss


class MaxScore(Trigger):
    def __init__(self, metric: str, max_score: float):
        self.metric, self.max_score = metric, max_score

    def __call__(self, s):
        v = s.get("metrics", {}).get(self.metric)
        return v is not None and v > self.max_score


class _And(Trigger):
    def __init__(self, a, b):
        self.a, self.b = a, b

    def __call__(self, s):
        return self.a(s) and self.b(s)


class _Or(Trigger):
    def __init__(self, a, b):
        self.a, self.b = a, b

    def __call__(self, s):
        return self.a(s) or self.b(s)

"""Validation metrics (reference parity: BigDL ValidationMethods surfaced as
zoo metrics — Top1Accuracy, Top5Accuracy, Loss, MAE, MSE, AUC;
ref: pyzoo/zoo/pipeline/api/keras/metrics.py, SURVEY.md §5).

A metric is a pure fn ``(preds, targets) -> scalar`` plus a name; epoch
aggregation is a sample-weighted mean on host.  AUC is host-side (sorting
doesn't belong in the train step).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Sequence, Union

import jax.numpy as jnp
import numpy as np


def top1_accuracy(logits, labels):
    return jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))


def top5_accuracy(logits, labels):
    top5 = jnp.argsort(logits, -1)[..., -5:]
    hit = jnp.any(top5 == labels[..., None], axis=-1)
    return jnp.mean(hit.astype(jnp.float32))


def binary_accuracy(logits, targets):
    preds = (logits > 0).astype(jnp.int32)
    return jnp.mean((preds == targets.astype(jnp.int32)).astype(jnp.float32))


def mae_metric(preds, targets):
    return jnp.mean(jnp.abs(preds - targets))


def mse_metric(preds, targets):
    return jnp.mean(jnp.square(preds - targets))


_METRICS: Dict[str, Callable] = {
    "accuracy": top1_accuracy,
    "top1accuracy": top1_accuracy,
    "top5accuracy": top5_accuracy,
    "binary_accuracy": binary_accuracy,
    "mae": mae_metric,
    "mse": mse_metric,
}


def get_metric(m: Union[str, Callable]):
    if callable(m):
        return getattr(m, "__name__", "metric"), m
    key = str(m).lower().replace(" ", "")
    if key not in _METRICS:
        raise ValueError(f"unknown metric {m!r}; known: {sorted(_METRICS)}")
    return key, _METRICS[key]


def resolve_metrics(ms: Sequence[Union[str, Callable]]):
    return [get_metric(m) for m in (ms or [])]


class EpochAccumulator:
    """Sample-weighted running means for a dict of per-batch scalars."""

    def __init__(self):
        self._sums: Dict[str, float] = {}
        self._n = 0

    def add(self, scalars: Dict[str, float], n_samples: int):
        for k, v in scalars.items():
            self._sums[k] = self._sums.get(k, 0.0) + float(v) * n_samples
        self._n += n_samples

    def result(self) -> Dict[str, float]:
        if self._n == 0:
            return {}
        return {k: v / self._n for k, v in self._sums.items()}


def auc(scores: np.ndarray, labels: np.ndarray) -> float:
    """Host-side ROC-AUC via rank statistic (ties get midranks)."""
    scores = np.asarray(scores).ravel()
    labels = np.asarray(labels).ravel().astype(bool)
    n_pos = int(labels.sum())
    n_neg = labels.size - n_pos
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    order = np.argsort(scores, kind="mergesort")
    ranks = np.empty_like(order, dtype=np.float64)
    sorted_scores = scores[order]
    i = 0
    while i < len(sorted_scores):
        j = i
        while j + 1 < len(sorted_scores) and \
                sorted_scores[j + 1] == sorted_scores[i]:
            j += 1
        ranks[order[i:j + 1]] = (i + j) / 2.0 + 1.0
        i = j + 1
    return float((ranks[labels].sum() - n_pos * (n_pos + 1) / 2)
                 / (n_pos * n_neg))

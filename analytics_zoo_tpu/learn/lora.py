"""LoRA fine-tuning as a functional param-tree transform.

Beyond-parity extension (the reference has no parameter-efficient
fine-tuning at all): any flax model in the zoo fine-tunes with frozen
base weights and rank-r adapters on its matmul kernels.  No module
surgery — adapters live under a reserved ``__lora__`` key of the params
pytree and the Estimator merges ``W + (alpha/r)·A@B`` inside the jitted
step, so train/eval/predict/serving all see merged weights while the
optimizer (via ``optax.multi_transform``) updates ONLY the adapters.

Why this design on TPU: the merge is O(r·(in+out)) FLOPs per kernel per
step — noise next to the matmuls — and in exchange the Adam moments
exist only for the adapters (the usual 2/3 of training HBM for the base
model vanishes), checkpoints of a fine-tune are megabytes, and the whole
thing composes with pjit sharding because it is just a pytree transform
traced into the same XLA program.

Usage::

    est = Estimator.from_flax(model, loss=..., optimizer=optax.adamw(1e-4),
                              lora=LoRAConfig(rank=8))
    est.fit(data, ...)
    adapters = est.lora_params()          # tiny tree to save/ship
    baked = est.merged_params()           # base + adapters, for serving
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

LORA_KEY = "__lora__"

# matches the zoo's transformer kernels (models/lm.py, models/transformer
# .py naming) plus generic flax Dense layers; conv and embedding tables
# stay frozen-dense by default, the standard LoRA choice
DEFAULT_TARGETS = (r"(query|key|value|attn_out|ffn_up|ffn_down"
                   r"|Dense_\d+|dense\w*)/kernel$")

# N-D kernels (flax DenseGeneral) factorize along the layer's TRUE
# in->out split, not an arbitrary reshape: query/key/value kernels are
# [hidden, heads, head_dim] (1 input dim), attn_out is [heads, head_dim,
# hidden] (2 input dims).  An N-D kernel with no split entry fails loud —
# a silently wrong factorization trains but is not LoRA.
DEFAULT_SPLITS = ((r"(query|key|value)/kernel$", 1),
                  (r"attn_out/kernel$", 2))


@dataclass(frozen=True)
class LoRAConfig:
    rank: int = 8
    alpha: float = 16.0
    target_regex: str = DEFAULT_TARGETS
    # (regex, n_input_dims) for kernels with ndim > 2
    splits: Tuple[Tuple[str, int], ...] = DEFAULT_SPLITS
    # adapters train in f32 for optimizer stability; the merged delta is
    # cast to the base kernel's dtype at apply time
    dtype: Any = jnp.float32

    @property
    def scale(self) -> float:
        return float(self.alpha) / float(self.rank)


def _n_in_dims(path: Tuple[str, ...], leaf, cfg: LoRAConfig) -> int:
    if leaf.ndim == 2:
        return 1
    name = "/".join(path)
    for pat, n in cfg.splits:
        if re.search(pat, name):
            return n
    raise ValueError(
        f"LoRA target {name!r} has ndim={leaf.ndim} and no entry in "
        f"LoRAConfig.splits declares its input-dims split; add "
        f"(regex, n_input_dims) for it")


def _flat(params) -> Dict[Tuple[str, ...], Any]:
    return {tuple(str(k.key) for k in path): leaf
            for path, leaf in jax.tree_util.tree_flatten_with_path(
                params)[0]}


def target_paths(params, cfg: LoRAConfig):
    """Paths (tuples of keys) of every matmul kernel (ndim >= 2) the
    regex selects.  Raises when nothing matches — a silent no-adapter
    fine-tune that trains nothing is the worst failure mode."""
    pat = re.compile(cfg.target_regex)
    hits = [p for p, leaf in _flat(params).items()
            if getattr(leaf, "ndim", 0) >= 2
            and pat.search("/".join(p))]
    if not hits:
        raise ValueError(
            f"LoRA target_regex {cfg.target_regex!r} matched no "
            f"kernel; available paths include "
            f"{['/'.join(p) for p in list(_flat(params))[:8]]}")
    return hits


def _lora_name(path: Tuple[str, ...]) -> str:
    # '::' so partition-rule regexes written for model kernels (e.g.
    # r'ffn_up/kernel') can never accidentally match an adapter leaf
    return "::".join(path)


def init_lora(params, cfg: LoRAConfig, rng) -> Dict[str, Any]:
    """Adapter tree {name: {'a': [in, r], 'b': [r, out]}} where in/out
    are the kernel's flattened input/output dims (N-D DenseGeneral
    kernels use their declared split).  b starts at zero so the merged
    model equals the base model at step 0."""
    import numpy as np

    flat = _flat(params)
    out = {}
    for i, path in enumerate(target_paths(params, cfg)):
        w = flat[path]
        nin = _n_in_dims(path, w, cfg)
        fan_in = int(np.prod(w.shape[:nin]))
        fan_out = int(np.prod(w.shape[nin:]))
        k = jax.random.fold_in(rng, i)
        out[_lora_name(path)] = {
            "a": (jax.random.normal(k, (fan_in, cfg.rank), cfg.dtype)
                  / jnp.sqrt(jnp.float32(fan_in)).astype(cfg.dtype)),
            "b": jnp.zeros((cfg.rank, fan_out), cfg.dtype),
        }
    return out


def split_lora(params):
    """(base_params, adapter_tree_or_None) from a possibly-augmented
    params tree."""
    if isinstance(params, dict) and LORA_KEY in params:
        base = {k: v for k, v in params.items() if k != LORA_KEY}
        return base, params[LORA_KEY]
    return params, None


def merge_lora(params, cfg: LoRAConfig):
    """Fold adapters into their kernels: W + scale·A@B, cast to W.dtype.
    Returns plain params (no __lora__ key); pass-through when the tree
    has no adapters."""
    base, lora = split_lora(params)
    if lora is None:
        return params

    merged = dict(_flat(base))
    for name, ab in lora.items():
        path = tuple(name.split("::"))
        w = merged[path]
        delta = (ab["a"].astype(jnp.float32)
                 @ ab["b"].astype(jnp.float32)) * cfg.scale
        merged[path] = (w.astype(jnp.float32)
                        + delta.reshape(w.shape)).astype(w.dtype)

    def rebuild(tree, prefix=()):
        if isinstance(tree, dict):
            return {k: rebuild(v, prefix + (k,)) for k, v in tree.items()}
        return merged[prefix]

    return rebuild(base)


def wrap_optimizer(tx, has_lora: bool):
    """Freeze everything but the adapters.  optax.multi_transform keeps
    optimizer state ONLY for the 'train' partition — the memory win."""
    import optax

    if not has_lora:
        return tx

    def labels(params):
        return {k: jax.tree.map(lambda _: "train", v)
                if k == LORA_KEY
                else jax.tree.map(lambda _: "frozen", v)
                for k, v in params.items()}

    return optax.multi_transform(
        {"train": tx, "frozen": optax.set_to_zero()}, labels)


# partition rule for adapter leaves: replicate.  Ranks are tiny (r ≤ 64
# against hidden sizes in the hundreds+), so sharding them buys nothing
# and replication keeps the merge collective-free under any mesh.
from jax.sharding import PartitionSpec as _P  # noqa: E402

LORA_RULES = ((re.escape(LORA_KEY), _P()),)

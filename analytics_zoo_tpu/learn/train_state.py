"""TrainState — the unit of training state the whole framework moves around.

Replaces the reference's scattered state (BigDL Module weights inside
AllReduceParameter blocks + optimizer snapshots; torch/TF runner state dicts;
SURVEY.md §2.3): one pytree holding params, optimizer state, step, RNG and
(optionally) batch statistics, shardable by partition rules and checkpointed
as a unit by Orbax.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
from flax import struct
from flax.training import train_state


class ZooTrainState(train_state.TrainState):
    """flax TrainState + mutable batch_stats (BatchNorm) + base RNG key."""

    batch_stats: Optional[Any] = None
    rng: Optional[jax.Array] = struct.field(default=None)

    def step_rng(self) -> jax.Array:
        """Per-step dropout key: fold the step counter into the base key —
        deterministic given seed, distinct per step, no host round-trip."""
        return jax.random.fold_in(self.rng, self.step)


def create_train_state(
    rng: jax.Array,
    apply_fn: Callable,
    variables: dict,
    tx,
) -> ZooTrainState:
    return ZooTrainState.create(
        apply_fn=apply_fn,
        params=variables["params"],
        tx=tx,
        batch_stats=variables.get("batch_stats"),
        rng=rng,
    )

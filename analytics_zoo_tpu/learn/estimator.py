"""Estimator — unified fit/evaluate/predict on the TPU mesh.

Reference surface (SURVEY.md §2.3): ``zoo.orca.learn.*.Estimator`` —
``from_keras`` / ``from_torch`` / ``from_graph`` / ``from_bigdl`` backends,
each a different distributed runtime (BigDL DistriOptimizer over Spark
BlockManager, Ray actors + gloo DDP, MultiWorkerMirroredStrategy, horovod).

TPU-native re-design: **one** runtime. The entire DistriOptimizer /
AllReduceParameter machinery (ref: pipeline/estimator/Estimator.scala and
BigDL's block-partitioned all-reduce) collapses into a single pjit-compiled
``train_step`` whose gradient synchronisation is the XLA-emitted
reduce-scatter/all-gather over ICI implied by the state/data shardings.
There are no runners, no actors, no parameter blocks: the mesh IS the
cluster and the compiled step IS the optimizer loop body.

``Estimator.from_flax`` is the native constructor; ``from_keras`` /
``from_torch`` names are kept as shims that accept creator functions
returning flax modules (SURVEY's creator-fn contract), so reference users
find the entry points they know.
"""

from __future__ import annotations

import inspect
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax

from analytics_zoo_tpu.common.config import TrainConfig
from analytics_zoo_tpu.common.context import (
    OrcaContext, effective_process_count as _nhosts,
    effective_process_index as _hidx)
from analytics_zoo_tpu.common.log import MetricLogger, logger
from analytics_zoo_tpu.data.loader import (
    DataCreator, NumpyBatchIterator, device_prefetch, make_global_batch)
from analytics_zoo_tpu.learn.metrics import (
    EpochAccumulator, resolve_metrics)
from analytics_zoo_tpu.learn.objectives import get_loss
from analytics_zoo_tpu.learn.train_state import ZooTrainState, create_train_state
from analytics_zoo_tpu.learn.triggers import EveryEpoch, Trigger
from analytics_zoo_tpu.parallel.mesh import batch_axes, make_mesh
from analytics_zoo_tpu.parallel.partition import (
    DP_RULES, PartitionRules, data_process_groups, data_sharding,
    state_sharding, with_sharding_constraint)
from jax.sharding import PartitionSpec as P


def _cpu_sync_every(mesh) -> int:
    """Dispatch-drift barrier interval for MULTI-device XLA:CPU meshes
    (0 = no barrier).  XLA:CPU's in-process collectives kill the process
    when one participant misses a 40 s rendezvous window; with many
    virtual devices on few host cores, a long unsynchronised dispatch
    queue lets per-device execution drift that far (observed ~30 async
    steps on an 8-device mesh on a 1-core host).  Single-device runs
    have no rendezvous, and TPU runs must not pay a mid-epoch D2H
    round-trip — both stay barrier-free."""
    if jax.default_backend() != "cpu":
        return 0
    return 8 if mesh.devices.size > 1 else 0


def _model_accepts(model, kwarg: str) -> bool:
    try:
        sig = inspect.signature(type(model).__call__)
    except (TypeError, ValueError):
        return False
    return kwarg in sig.parameters


class FlaxEstimator:
    """Train/eval/predict a flax module on the mesh.

    Args:
      model: flax ``nn.Module``.
      loss: name or callable ``(preds, labels) -> scalar``.
      optimizer: optax transform (or learning-rate float -> adam(lr)).
      metrics: names or callables evaluated on (preds, labels).
      feature_cols / label_cols: which batch keys feed the model / loss.
        Features are passed positionally in order.
      partition_rules: param-path regex -> PartitionSpec (default: DP).
      mesh: defaults to the active context's mesh (or a fresh dp mesh).
    """

    def __init__(
        self,
        model,
        loss: Union[str, Callable],
        optimizer,
        *,
        metrics: Sequence[Union[str, Callable]] = (),
        feature_cols: Sequence[str] = ("x",),
        label_cols: Sequence[str] = ("y",),
        partition_rules: PartitionRules = DP_RULES,
        mesh=None,
        config: Optional[TrainConfig] = None,
        model_dir: Optional[str] = None,
        param_loss: Optional[Callable] = None,
        lora=None,
        initial_variables=None,
    ):
        self.model = self._maybe_convert_torch(model)
        # Optional penalty over the param tree (keras-API W_regularizer
        # lowering) added to the training loss inside the jitted step.
        self.param_loss = param_loss
        self.loss_fn = get_loss(loss)
        if isinstance(optimizer, (int, float)):
            optimizer = optax.adam(float(optimizer))
        # LoRA (learn/lora.py): adapters join the params tree under
        # __lora__, the optimizer is masked to them, and _forward merges
        # W + scale·A@B before apply — one transform, every model.
        # pretrained weights to seed instead of random init (HF imports,
        # Estimator.save exports): a {'params': ...} tree or bare params
        self._initial_variables = initial_variables
        self.lora = lora
        if lora is not None:
            from analytics_zoo_tpu.learn.lora import wrap_optimizer

            optimizer = wrap_optimizer(optimizer, True)
        self.tx = optimizer
        self.metric_fns = resolve_metrics(metrics)
        self.feature_cols = tuple(feature_cols)
        self.label_cols = tuple(label_cols)
        if lora is not None:
            from analytics_zoo_tpu.learn.lora import LORA_RULES

            partition_rules = tuple(LORA_RULES) + tuple(partition_rules)
        self.rules = partition_rules
        self.config = config or TrainConfig()
        self.model_dir = model_dir
        if mesh is None:
            try:
                mesh = OrcaContext.get_context().mesh
            except RuntimeError:
                mesh = make_mesh(axes={"dp": -1})
        self.mesh = mesh
        self.state: Optional[ZooTrainState] = None
        self._state_sharding = None
        self._data_sharding = data_sharding(self.mesh)
        # (n_groups, my_group, group_of_process): how the process boundary
        # lies relative to the batch axes.  dp across hosts -> one data
        # shard per process; a pp/ep/tp-only boundary -> processes are
        # batch REPLICAS and must feed identical rows (see
        # parallel.partition.data_process_groups).
        self._data_groups = data_process_groups(self._data_sharding)
        self._takes_train = _model_accepts(model, "train")
        self._takes_det = _model_accepts(model, "deterministic")
        self._jit_train_step = None
        self._jit_eval_step = None
        self._jit_predict_step = None
        self._epoch = 0
        self._global_step = 0
        self._prof_active = False

    @staticmethod
    def _maybe_convert_torch(model):
        """torch nn.Modules become TorchNets HERE — the common depth — so
        every entry point (from_flax/from_torch/AutoEstimator trials) gets
        conversion, not just the from_torch facade."""
        try:
            import torch
        except ImportError:
            return model
        if isinstance(model, torch.nn.Module):
            from analytics_zoo_tpu.net import TorchNet

            return TorchNet.from_torch(model)
        return model

    # ------------------------------------------------------------------
    # model application helpers
    # ------------------------------------------------------------------

    def _apply_kwargs(self, train: bool) -> Dict[str, Any]:
        kw: Dict[str, Any] = {}
        if self._takes_train:
            kw["train"] = train
        elif self._takes_det:
            kw["deterministic"] = not train
        return kw

    def _forward(self, params, batch_stats, batch, rng, train: bool):
        """Returns (preds, new_batch_stats, aux_loss).

        ``aux_loss`` is the sum of everything modules sowed into the
        ``"losses"`` collection during a TRAIN forward (MoE load-balancing
        losses, models/moe.py; any custom regulariser a user sows) — added
        to the training loss by _train_step.  Eval applies run without
        mutable collections, so sown losses drop out there (eval loss stays
        comparable across MoE/dense models)."""
        if self.lora is not None:
            # gradients flow to the adapters THROUGH this merge; the
            # base kernels' grads are computed too but the masked
            # optimizer discards them (learn/lora.py)
            from analytics_zoo_tpu.learn.lora import merge_lora

            params = merge_lora(params, self.lora)
        variables = {"params": params}
        has_bs = batch_stats is not None
        if has_bs:
            variables["batch_stats"] = batch_stats
        feats = [batch[c] for c in self.feature_cols]
        kw = self._apply_kwargs(train)
        rngs = {"dropout": rng} if (train and rng is not None) else None
        if train:
            out, mut = self.model.apply(
                variables, *feats, mutable=["batch_stats", "losses"],
                rngs=rngs, **kw)
            leaves = jax.tree.leaves(mut.get("losses", {}))
            # whether the model sows aux losses is STATIC (trace-time):
            # models without them never pay a metrics entry
            self._has_aux_losses = bool(leaves)
            aux = sum((jnp.sum(leaf) for leaf in leaves),
                      jnp.float32(0.0))
            new_bs = mut["batch_stats"] if has_bs else None
            return out, new_bs, aux
        out = self.model.apply(variables, *feats, rngs=rngs, **kw)
        return out, batch_stats, jnp.float32(0.0)

    def _labels(self, batch):
        ys = [batch[c] for c in self.label_cols]
        return ys[0] if len(ys) == 1 else tuple(ys)

    # ------------------------------------------------------------------
    # compiled steps
    # ------------------------------------------------------------------

    def _train_step(self, state: ZooTrainState, batch):
        accum = int(getattr(self.config, "accum_steps", 1) or 1)
        if accum > 1:
            return self._train_step_accum(state, batch, accum)
        rng = state.step_rng()

        def loss_of(params):
            preds, new_bs, aux = self._forward(
                params, state.batch_stats, batch, rng, train=True)
            loss = self.loss_fn(preds, self._labels(batch)) + aux
            if self.param_loss is not None:
                loss = loss + self.param_loss(params)
            return loss, (preds, new_bs, aux)

        (loss, (preds, new_bs, aux)), grads = jax.value_and_grad(
            loss_of, has_aux=True)(state.params)
        new_state = state.apply_gradients(grads=grads, batch_stats=new_bs)
        mets = {"loss": loss}
        if getattr(self, "_has_aux_losses", False):
            # observability: the sown component (MoE load balance etc.)
            # reported beside the total it is already inside of
            mets["aux_loss"] = aux
        labels = self._labels(batch)
        for name, fn in self.metric_fns:
            mets[name] = fn(preds, labels)
        return new_state, mets

    def _train_step_accum(self, state: ZooTrainState, batch, accum: int):
        """Gradient accumulation: the global batch is split into `accum`
        microbatches scanned sequentially; averaged grads feed ONE optimizer
        update, so the math equals the full-batch step (for mean-reduced
        losses) at 1/accum the activation memory.  The reference has no
        counterpart (its effective batch scaled with executor count,
        SURVEY.md §2.3); on TPU this is how a big global batch fits HBM —
        remat trades FLOPs for memory, accumulation trades steps for it."""
        rng = state.step_rng()
        baxes = batch_axes(self.mesh) or None

        def split(v):
            b = v.shape[0]
            if b % accum:
                raise ValueError(
                    f"global batch {b} not divisible by "
                    f"accum_steps={accum}")
            mb = v.reshape((accum, b // accum) + v.shape[1:])
            # keep microbatch rows sharded over the dp-like axes
            return with_sharding_constraint(mb, P(None, baxes))

        mbs = {k: split(v) for k, v in batch.items()}

        def loss_of(params, mb, bs, r):
            preds, new_bs, aux = self._forward(params, bs, mb, r,
                                               train=True)
            loss = self.loss_fn(preds, self._labels(mb)) + aux
            if self.param_loss is not None:
                loss = loss + self.param_loss(params)
            return loss, (preds, new_bs, aux)

        def body(carry, xs):
            g_acc, loss_acc, aux_acc, bs = carry
            mb, i = xs
            (loss, (preds, new_bs, aux)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(
                state.params, mb, bs, jax.random.fold_in(rng, i))
            g_acc = jax.tree.map(jnp.add, g_acc, grads)
            return (g_acc, loss_acc + loss, aux_acc + aux, new_bs), preds

        zeros = jax.tree.map(jnp.zeros_like, state.params)
        (g_acc, loss_sum, aux_sum, bs_final), preds = jax.lax.scan(
            body, (zeros, jnp.float32(0.0), jnp.float32(0.0),
                   state.batch_stats),
            (mbs, jnp.arange(accum)))
        grads = jax.tree.map(lambda g: g / accum, g_acc)
        new_state = state.apply_gradients(grads=grads,
                                          batch_stats=bs_final)
        # models may return pytree predictions (e.g. SSD's (locs, cls))
        preds = jax.tree.map(
            lambda p: p.reshape((-1,) + p.shape[2:]), preds)
        mets = {"loss": loss_sum / accum}
        if getattr(self, "_has_aux_losses", False):
            mets["aux_loss"] = aux_sum / accum
        labels = self._labels(batch)
        for name, fn in self.metric_fns:
            mets[name] = fn(preds, labels)
        return new_state, mets

    def _eval_step(self, state: ZooTrainState, batch, weights):
        """Masked eval: per-sample losses/metrics via singleton-batch vmap,
        weighted by `weights` (0 for padding rows)."""
        preds, _, _ = self._forward(
            state.params, state.batch_stats, batch, None, train=False)
        labels = self._labels(batch)

        def per_sample(fn):
            def one(p, l):
                if isinstance(l, tuple):
                    return fn(p[None], tuple(x[None] for x in l))
                return fn(p[None], l[None])
            return jax.vmap(one)

        w = weights.astype(jnp.float32)
        denom = jnp.maximum(w.sum(), 1.0)
        loss = (per_sample(self.loss_fn)(preds, labels) * w).sum() / denom
        if self.param_loss is not None:
            # keep eval loss comparable to the training loss (keras includes
            # regularization penalties in evaluate)
            loss = loss + self.param_loss(state.params)
        mets = {"loss": loss}
        for name, fn in self.metric_fns:
            mets[name] = (per_sample(fn)(preds, labels) * w).sum() / denom
        return mets

    def _predict_step(self, state: ZooTrainState, batch):
        preds, _, _ = self._forward(
            state.params, state.batch_stats, batch, None, train=False)
        return preds

    def _set_cols(self, feature_cols, label_cols):
        """Column changes must invalidate compiled steps: the traces close
        over the column names, and jax's cache would otherwise silently hit
        on an old trace reading the old columns."""
        fc = tuple(feature_cols) if feature_cols else self.feature_cols
        lc = tuple(label_cols) if label_cols else self.label_cols
        if (fc, lc) != (self.feature_cols, self.label_cols):
            self.feature_cols, self.label_cols = fc, lc
            self._jit_train_step = None
            self._jit_eval_step = None
            self._jit_predict_step = None

    def _build_jits(self):
        # accum_steps is baked into the train-step trace: a config change
        # after the first fit must invalidate the cached jit (same
        # requirement _set_cols documents for column names)
        accum = int(getattr(self.config, "accum_steps", 1) or 1)
        if self._jit_train_step is not None and \
                getattr(self, "_jit_accum", accum) != accum:
            self._jit_train_step = None   # eval/predict don't see accum
        if self._jit_train_step is None:
            donate = self.config.donate_state and not self.config.debug_nans
            self._jit_train_step = jax.jit(
                self._train_step,
                donate_argnums=(0,) if donate else (),
                out_shardings=(self._state_sharding, None))
            self._jit_accum = accum
        if self._jit_eval_step is None:
            self._jit_eval_step = jax.jit(self._eval_step)
            self._jit_predict_step = jax.jit(self._predict_step)

    # ------------------------------------------------------------------
    # state init
    # ------------------------------------------------------------------

    def _ensure_state(self, sample_batch: Dict[str, np.ndarray]):
        if self.state is not None:
            return
        seed = self.config.seed
        # Init batch must divide the mesh's batch axes (shard_map paths are
        # strict about divisibility), so tile the sample up to one row per
        # batch-mesh slice instead of using a single row.
        from analytics_zoo_tpu.parallel.mesh import mesh_batch_size

        nb = max(1, mesh_batch_size(self.mesh))

        def rows(c):
            v = np.asarray(sample_batch[c])
            if len(v) >= nb:
                return v[:nb]
            reps = -(-nb // max(1, len(v)))
            return np.tile(v, (reps,) + (1,) * (v.ndim - 1))[:nb]

        feats = [jnp.asarray(rows(c)) for c in self.feature_cols]
        # Per-column (row_shape, dtype) — lets save() persist enough to
        # rebuild state on load without the caller resupplying sample data.
        self.sample_spec = {
            c: (tuple(np.asarray(sample_batch[c]).shape[1:]),
                str(np.asarray(sample_batch[c]).dtype))
            for c in sample_batch}
        kw = self._apply_kwargs(train=False)

        def init_fn():
            # RNG keys are created INSIDE the traced function: a key built
            # eagerly and closed over would be embedded as a program
            # constant, and materialising that constant does a hidden
            # device->host fetch — which on tunneled devices permanently
            # degrades the H2D link (~1.6 GB/s -> ~20 MB/s) before
            # training even starts.
            root = jax.random.key(seed)
            init_rng, train_rng = jax.random.split(root)
            variables = self.model.init(
                {"params": init_rng, "dropout": init_rng}, *feats, **kw)
            if self.lora is not None:
                from analytics_zoo_tpu.learn.lora import (
                    LORA_KEY, init_lora)

                variables = dict(variables)
                variables["params"] = dict(variables["params"])
                variables["params"][LORA_KEY] = init_lora(
                    variables["params"], self.lora,
                    jax.random.fold_in(root, 2))
            return create_train_state(train_rng, self.model.apply,
                                      variables, self.tx)

        shapes = jax.eval_shape(init_fn)
        self._state_sharding = state_sharding(self.mesh, shapes, self.rules)
        if self._initial_variables is not None:
            self.state = self._build_seeded_state(shapes, seed)
        else:
            self.state = jax.jit(
                init_fn, out_shardings=self._state_sharding)()
        n_params = sum(int(np.prod(p.shape))
                       for p in jax.tree.leaves(self.state.params))
        logger.info("initialised %s params=%s mesh=%s",
                    type(self.model).__name__, f"{n_params:,}",
                    dict(self.mesh.shape))

    def _build_seeded_state(self, shapes, seed):
        """Build the train state DIRECTLY from caller-provided weights
        (initial_variables) — the random base init is never materialised
        (a full throwaway tree would double peak HBM at exactly the
        large-checkpoint imports this serves).  Each leaf lands with the
        state's dtype and sharding; shape mismatches fail loud naming
        the problem.  With LoRA the seeded tree is the FROZEN BASE and
        adapters get their usual fresh init (same seed-derived values as
        the unseeded path).  A source tree saved from a LoRA run may
        carry a ``__lora__`` subtree — it is DROPPED (seed
        ``merged_params()`` instead to bake adapters in)."""
        from analytics_zoo_tpu.learn.lora import LORA_KEY, init_lora

        src = self._initial_variables
        src_extra = {}
        if isinstance(src, dict) and "params" in src:
            src_extra = {k: v for k, v in src.items() if k != "params"}
            src = src["params"]
        if isinstance(src, dict) and LORA_KEY in src:
            src = {k: v for k, v in src.items() if k != LORA_KEY}

        dst_params = shapes.params
        if self.lora is not None:
            dst_params = {k: v for k, v in dst_params.items()
                          if k != LORA_KEY}
        shapes_dst = jax.tree.map(lambda x: tuple(x.shape), dst_params)
        shapes_src = jax.tree.map(lambda x: tuple(np.asarray(x).shape),
                                  src)
        if shapes_dst != shapes_src:
            raise ValueError(
                "initial_variables do not match the model's param "
                "shapes — wrong checkpoint for this architecture?")
        # batch-stats models (BatchNorm): fresh running statistics under
        # pretrained weights silently corrupt inference — require them
        if shapes.batch_stats is not None and "batch_stats" not in \
                src_extra:
            raise ValueError(
                "this model carries batch_stats (BatchNorm running "
                "statistics); initial_variables must include them "
                "(pass the full saved variables, not just params) — "
                "fresh statistics under pretrained weights would "
                "silently corrupt inference")

        pspec = self._state_sharding.params
        base_spec = ({k: v for k, v in pspec.items() if k != LORA_KEY}
                     if self.lora is not None else pspec)
        params_dev = jax.tree.map(
            lambda dst, sh, s: jax.device_put(
                np.asarray(s).astype(dst.dtype), sh),
            dst_params, base_spec, src)
        bs_dev = None
        if shapes.batch_stats is not None:
            bs_dev = jax.tree.map(
                lambda dst, sh, s: jax.device_put(
                    np.asarray(s).astype(dst.dtype), sh),
                shapes.batch_stats, self._state_sharding.batch_stats,
                src_extra["batch_stats"])

        lora_cfg = self.lora

        def assemble(params, batch_stats):
            root = jax.random.key(seed)
            _, train_rng = jax.random.split(root)
            if lora_cfg is not None:
                params = {**params,
                          LORA_KEY: init_lora(params, lora_cfg,
                                              jax.random.fold_in(root,
                                                                 2))}
            variables = {"params": params}
            if batch_stats is not None:
                variables["batch_stats"] = batch_stats
            return create_train_state(train_rng, self.model.apply,
                                      variables, self.tx)

        return jax.jit(assemble,
                       out_shardings=self._state_sharding,
                       static_argnames=())(params_dev, bs_dev)

    # ------------------------------------------------------------------
    # observability (SURVEY §5; ref: KerasNet.set_tensorboard ->
    # BigDL TrainSummary under log_dir/app_name)
    # ------------------------------------------------------------------

    def set_tensorboard(self, log_dir: str, app_name: str = "zoo"):
        import os

        self.config.tensorboard_dir = os.path.join(log_dir, app_name,
                                                   "train")
        self.config.metrics_jsonl = os.path.join(log_dir, app_name,
                                                 "train.jsonl")
        os.makedirs(self.config.tensorboard_dir, exist_ok=True)
        return self

    def set_profile(self, logdir: str, start_step: int = 5,
                    n_steps: int = 5):
        """Capture a jax.profiler trace for `n_steps` once training reaches
        `start_step` (skips compile/warmup noise)."""
        self.config.profile = (logdir, start_step, n_steps)
        return self

    # ------------------------------------------------------------------
    # public API (reference parity: fit/evaluate/predict/save/load)
    # ------------------------------------------------------------------

    def fit(
        self,
        data,
        epochs: int = 1,
        batch_size: Optional[int] = None,
        validation_data=None,
        feature_cols: Optional[Sequence[str]] = None,
        label_cols: Optional[Sequence[str]] = None,
        checkpoint_trigger: Optional[Trigger] = None,
        callbacks: Sequence[Callable[[Dict], None]] = (),
        auto_resume: bool = False,
    ) -> List[Dict[str, float]]:
        """Train. `batch_size` is GLOBAL (reference semantics: total across
        the cluster); when omitted it falls back to the data container's
        own batch_size (TFDataset carries one) and then 32. Returns
        per-epoch stats dicts (reference: Orca runner stats lists).

        ``auto_resume=True`` makes the call restart-idempotent (SURVEY §5
        elastic recovery; pairs with scripts/run_elastic.py): if
        ``config.checkpoint_dir`` holds a checkpoint, restore it and
        train only the REMAINING epochs toward the ``epochs`` total —
        a respawned process group continues where the dead one stopped,
        with no resume logic in user code."""
        batch_size = _resolve_batch(batch_size, data, "batch_size")
        if validation_data is None:
            validation_data = getattr(data, "val", None)
        self._set_cols(feature_cols, label_cols)
        n_hosts = _nhosts()
        n_groups, my_group, _ = self._data_groups
        if batch_size < 1 or batch_size % n_groups:
            raise ValueError(f"global batch {batch_size} must be positive "
                             f"and divisible by data-shard group count "
                             f"{n_groups}")
        # rows each PROCESS contributes per step: one data shard per
        # GROUP; group-mates (processes replicated along the batch dim,
        # e.g. across a pp boundary) feed identical rows
        per_host = batch_size // n_groups
        shuffle = not self.config.deterministic
        from analytics_zoo_tpu.data.feature_set import DiskFeatureSet
        is_disk = isinstance(data, DiskFeatureSet)
        self._check_host_local_source(data)
        if is_disk:
            # DISK tier streams through the native prefetch thread.  Each
            # host streams its OWN shard file (host-local data, like
            # XShards).
            n_local = len(data)
        else:
            arrays = _host_local(data, self._data_groups)
            n_local = len(next(iter(arrays.values())))
        min_steps = None
        if n_hosts > 1:
            # Host-local sources (disk shards, XShards) may hold uneven row
            # counts; every host must run the SAME step count or the
            # collective program deadlocks.  One allgather of the row count
            # settles the global minimum — and must happen BEFORE any
            # per-host record access or iterator validation (sample_block
            # on an empty shard, batch-size checks) so a too-small host
            # raises the same error everywhere instead of deadlocking its
            # peers inside a collective.
            fp = data.fingerprint() if is_disk else 0
            gathered = _allgather_counts(n_local, fp)
            min_rows = int(gathered[:, 0].min())
            pairs = [tuple(r) for r in gathered.tolist() if r[0] > 0]
            if is_disk and not _allow_shared_disk() and \
                    len(set(pairs)) < len(pairs):
                raise ValueError(
                    "two or more hosts opened an identical DiskFeatureSet "
                    "shard (same row count and content fingerprint) — that "
                    "is ONE replicated/shared file, which would train its "
                    "rows once per host.  Spill per-host shards (use a "
                    "'{host}' placeholder in the path); if these really "
                    "are distinct shards, set "
                    "ANALYTICS_ZOO_TPU_ALLOW_SHARED_DISK=1")
            min_steps = min_rows // per_host
            if min_steps < 1:
                raise ValueError(
                    f"global batch {batch_size} needs {per_host} rows per "
                    f"host but the smallest host shard holds only "
                    f"{min_rows} rows")
        if is_disk:
            self._ensure_state(data.sample_block())
            it = data.batch_iterator(
                per_host, shuffle=shuffle,
                seed=self.config.seed + my_group)
        else:
            self._ensure_state(arrays)
            it = NumpyBatchIterator(
                arrays, per_host, shuffle=shuffle, drop_remainder=True,
                seed=self.config.seed + my_group)
        if min_steps is not None and min_steps < it.steps_per_epoch():
            it = _StepLimitIterator(it, min_steps)
        self._build_jits()
        if auto_resume:
            if not self.config.checkpoint_dir:
                raise ValueError(
                    "fit(auto_resume=True) needs config.checkpoint_dir — "
                    "there is nowhere to resume from")
            mgr = self._checkpoint_manager(self.config.checkpoint_dir)
            latest = mgr.latest_step()
            if n_hosts > 1:
                # hosts must AGREE on the resume point before any of them
                # commits to an epoch count (mismatched counts deadlock
                # the collective program — same reason fit allgathers row
                # counts).  Disagreement means checkpoint_dir is not the
                # shared storage the contract requires (e.g. a replaced
                # VM with an empty local disk): fail the same way on
                # every host.
                seen = _allgather_counts(
                    -1 if latest is None else int(latest))[:, 0]
                if len(set(seen.tolist())) > 1:
                    raise ValueError(
                        f"auto_resume: hosts see different latest "
                        f"checkpoints {seen.tolist()} under "
                        f"{self.config.checkpoint_dir!r} — the dir must "
                        f"be shared storage (gs://...) visible to every "
                        f"host")
            if latest is not None:
                self.load_checkpoint(self.config.checkpoint_dir)
                logger.info(
                    "auto-resume: restored step %d (epoch %d) from %s",
                    self._global_step, self._epoch,
                    self.config.checkpoint_dir)
                if self._global_step % max(1, it.steps_per_epoch()):
                    logger.warning(
                        "auto-resume: restored step %d is mid-epoch "
                        "(steps_per_epoch=%d); resume is EPOCH-"
                        "granular, so the partial epoch's leading "
                        "batches will be trained again — use an epoch-"
                        "boundary checkpoint_trigger (EveryEpoch) when "
                        "exact-once matters", self._global_step,
                        it.steps_per_epoch())
            if self._epoch >= epochs:
                logger.info("auto-resume: %d epochs already complete",
                            self._epoch)
                return []
            epochs = epochs - self._epoch
            # continue the shuffle-seed schedule where the dead
            # incarnation stopped (deterministic mode is unaffected)
            inner = getattr(it, "_it", it)
            if hasattr(inner, "epoch"):
                inner.epoch = self._epoch
        # NOTE: _global_step is tracked host-side (incremented per step,
        # synced from device only on checkpoint restore).  Reading
        # int(self.state.step) here would be a D2H fetch before the hot
        # loop — on tunneled devices the FIRST device->host fetch
        # permanently degrades the H2D link (~1.6 GB/s -> ~55 MB/s),
        # throttling the entire input pipeline that follows.
        trigger = checkpoint_trigger or (
            EveryEpoch() if self.config.checkpoint_dir else None)
        mlog = MetricLogger(jsonl_path=self.config.metrics_jsonl,
                            tensorboard_dir=self.config.tensorboard_dir,
                            log_every=self.config.log_every_steps)
        prof = self.config.profile      # (logdir, start_step, n_steps)
        prof_active = False
        history: List[Dict[str, float]] = []
        for cb in callbacks:
            # stateful stop-requesting callbacks (EarlyStopping) restart
            # fresh per fit; ordinary callbacks are never touched (same
            # opt-in principle as requests_stop)
            if getattr(cb, "requests_stop", False):
                getattr(cb, "reset", lambda: None)()
        log_every = max(1, self.config.log_every_steps)
        debug_nans_was = None
        if self.config.debug_nans:
            debug_nans_was = jax.config.jax_debug_nans
            jax.config.update("jax_debug_nans", True)
        try:
            return self._fit_epochs(
                epochs, it, batch_size, validation_data, trigger, mlog,
                prof, history, log_every, callbacks)
        finally:
            # fault injection / data errors must not leak an active trace
            # (next start_trace would fail) or an open jsonl handle
            if self._prof_active:
                jax.profiler.stop_trace()
                self._prof_active = False
            if debug_nans_was is not None:
                jax.config.update("jax_debug_nans", debug_nans_was)
            mlog.close()

    def _fit_epochs(self, epochs, it, batch_size, validation_data, trigger,
                    mlog, prof, history, log_every, callbacks):
        prof_active = False
        sync_every = _cpu_sync_every(self.mesh)
        for _ in range(epochs):
            t0 = time.perf_counter()
            n_steps = 0
            step_mets: List[Dict[str, jax.Array]] = []
            for gbatch in device_prefetch(
                    it.epoch_batches(), self.mesh,
                    sharding=self._data_sharding,
                    pack=bool(getattr(self.config, "pack_transfer", True))):
                # Hot loop: never block on device values here — metrics stay
                # on-device (async dispatch continues); host sync happens
                # only at log points and epoch end.
                if prof and not prof_active and \
                        self._global_step >= prof[1]:
                    jax.profiler.start_trace(prof[0])
                    prof_active = self._prof_active = True
                self.state, mets = self._jit_train_step(self.state, gbatch)
                step_mets.append(mets)
                n_steps += 1
                self._global_step += 1
                if sync_every and n_steps % sync_every == 0:
                    jax.block_until_ready(mets["loss"])
                if prof_active and self._global_step >= prof[1] + prof[2]:
                    jax.block_until_ready(mets["loss"])
                    jax.profiler.stop_trace()
                    prof_active = self._prof_active = False
                    prof = None
                if self.config.fault_inject_step and \
                        self._global_step == self.config.fault_inject_step:
                    raise RuntimeError(
                        f"injected fault at step {self._global_step} "
                        "(TrainConfig.fault_inject_step)")
                if n_steps % log_every == 0:
                    # one batched D2H for the whole metric dict — per-leaf
                    # np.asarray pays a full round-trip per metric on
                    # tunneled/remote devices
                    mlog.log(self._global_step, jax.device_get(mets),
                             n_samples=batch_size * log_every)
                if trigger and trigger({"step": self._global_step,
                                        "epoch": self._epoch}):
                    self._maybe_checkpoint()
            # Epoch barrier: stack every step's metrics on-device into ONE
            # array per metric and fetch those.  Two properties matter on
            # tunneled/remote devices: (a) the barrier must be a real value
            # fetch, not jax.block_until_ready — which acknowledges enqueue,
            # not completion, and would credit the epoch with compute still
            # draining in the device queue; (b) the fetch must be O(metrics)
            # transfers, not O(steps x metrics) — device_get on a list of
            # per-step dicts pays a full round-trip per leaf.
            acc = EpochAccumulator()
            if step_mets:
                fetched = _fetch_stacked(step_mets)
                dt = time.perf_counter() - t0
                for i in range(n_steps):
                    acc.add({k: float(v[i]) for k, v in fetched.items()},
                            batch_size)
            else:
                dt = time.perf_counter() - t0
            self._epoch += 1
            stats = acc.result()
            stats["num_samples"] = float(n_steps * batch_size)
            stats["samples_per_sec"] = (n_steps * batch_size) / dt if dt else 0
            if validation_data is not None:
                val = self.evaluate(validation_data, batch_size=batch_size)
                stats.update({f"val_{k}": v for k, v in val.items()})
            if trigger and trigger({"step": self._global_step,
                                    "epoch": self._epoch, "epoch_end": True,
                                    "metrics": stats}):
                self._maybe_checkpoint()
            stop = False
            for cb in callbacks:
                ret = cb({"epoch": self._epoch, **stats})
                # only callbacks that OPT IN (requests_stop attr, e.g.
                # EarlyStopping) may stop training via their return value
                # — an ordinary logger returning something truthy must
                # never silently truncate a 50-epoch run
                if getattr(cb, "requests_stop", False):
                    stop = bool(ret) or stop
            logger.info("epoch %d: %s", self._epoch,
                        {k: round(v, 5) for k, v in stats.items()})
            history.append(stats)
            if _nhosts() > 1 and any(
                    getattr(cb, "requests_stop", False)
                    for cb in callbacks):
                # hosts must agree on the epoch count or the next
                # collective deadlocks: any host's stop stops everyone.
                # (Gated on a stop-capable callback existing — no
                # per-epoch barrier for ordinary multihost fits.)
                stop = bool(_allgather_counts(int(stop))[:, 0].max())
            if stop:
                logger.info("early stop at epoch %d", self._epoch)
                break
        return history

    def _check_host_local_source(self, data):
        """Host-local sources (DiskFeatureSet/XShards) hold DISJOINT rows
        per process; on a mesh whose process boundary is NOT along the
        batch axes (batch-replica groups), those rows cannot satisfy the
        required replication — raise instead of feeding inconsistent
        global arrays.  Applies to fit, evaluate and predict alike."""
        from analytics_zoo_tpu.data.feature_set import DiskFeatureSet
        from analytics_zoo_tpu.data.shards import XShards

        n_groups = self._data_groups[0]
        n_hosts = _nhosts()
        if n_groups != n_hosts and isinstance(
                data, (DiskFeatureSet, XShards)):
            raise ValueError(
                "host-local data sources (DiskFeatureSet/XShards) hold "
                "DISJOINT rows per process, but this mesh's process "
                f"boundary makes {n_hosts} processes form {n_groups} "
                "batch-replica group(s) that must feed identical rows. "
                "Feed replicated in-memory arrays, or lay the mesh out "
                "with the batch (dp/fsdp) axes across processes")

    def _local_n(self, data):
        """Host-local row count WITHOUT touching any records (safe to call
        before the multihost alignment collective even on an empty shard).
        Returns (n_local, arrays-or-None); arrays are reused downstream so
        in-memory data is normalised exactly once."""
        from analytics_zoo_tpu.data.feature_set import DiskFeatureSet

        if isinstance(data, DiskFeatureSet):
            return len(data), None
        arrays = _host_local(data, self._data_groups)
        return len(next(iter(arrays.values()))), arrays

    def _local_eval_stream(self, data, per_host, arrays=None):
        """Iterator of host-local fixed-order chunks of <= per_host rows.
        The DISK tier streams block-by-block (never materialised to DRAM —
        the whole point of the tier); everything else uses the arrays
        `_local_n` already normalised."""
        from analytics_zoo_tpu.data.feature_set import DiskFeatureSet

        if isinstance(data, DiskFeatureSet):
            return data.batches(per_host, shuffle=False,
                                drop_remainder=False)
        if arrays is None:
            arrays = _host_local(data, self._data_groups)
        n = len(next(iter(arrays.values())))

        def gen():
            for lo in range(0, n, per_host):
                yield {k: v[lo:lo + per_host] for k, v in arrays.items()}

        return gen()

    def _chunk_plan(self, n_local: int, per_host: int):
        """Multihost chunk alignment for eval/predict.

        Hosts hold uneven row counts (disk shards, XShards); each chunk is
        one collective (`make_array_from_process_local_data`), so all hosts
        must emit the SAME number of chunks.  One allgather of the row
        counts lets every host derive every other host's deterministic
        chunk sizes locally.  Returns ``(n_chunks, global_counts)`` where
        ``global_counts[j]`` is the true row total of chunk j across hosts,
        or None on a single host.
        """
        if _nhosts() == 1:
            return None
        counts = _allgather_counts(n_local)[:, 0]
        if counts.min() == 0:
            # every host raises the same error (the allgather already ran)
            # instead of a zero-row host dying early and deadlocking peers
            raise ValueError(
                f"evaluate/predict need rows on every host, but local row "
                f"counts are {counts.tolist()} (host order)")

        def sizes(n):
            s = [per_host] * (n // per_host)
            if n % per_host:
                s.append(n % per_host)
            return s

        per_host_sizes = [sizes(int(c)) for c in counts]
        n_chunks = max(len(s) for s in per_host_sizes)
        # global row totals must count each DATA-SHARD GROUP once: batch
        # replica processes (e.g. across a pp boundary) hold the same rows,
        # so sum over one representative process per group
        _, _, gop = self._data_groups
        reps = {}
        for p in range(len(per_host_sizes)):
            g = gop[p] if gop and p < len(gop) else p
            reps.setdefault(g, p)
        rep_sizes = [per_host_sizes[p] for p in sorted(reps.values())]
        gcounts = [sum(s[j] for s in rep_sizes if j < len(s))
                   for j in range(n_chunks)]
        return n_chunks, gcounts

    def _sample_of(self, data) -> Dict[str, np.ndarray]:
        from analytics_zoo_tpu.data.feature_set import DiskFeatureSet

        if isinstance(data, DiskFeatureSet):
            return data.sample_block()
        return _host_local(data, self._data_groups)

    def evaluate(self, data, batch_size: Optional[int] = None,
                 feature_cols=None, label_cols=None) -> Dict[str, float]:
        batch_size = _resolve_batch(batch_size, data, "batch_per_thread")
        self._set_cols(feature_cols, label_cols)
        per_host = max(1, batch_size // self._data_groups[0])
        self._check_host_local_source(data)
        # multihost alignment FIRST — before any record access, so a bad
        # host raises everywhere instead of deadlocking peers (see fit)
        n_local, arrays = self._local_n(data)
        plan = self._chunk_plan(n_local, per_host)
        sample = arrays if arrays is not None else self._sample_of(data)
        self._ensure_state(sample)
        self._build_jits()
        acc = EpochAccumulator()
        stream = self._local_eval_stream(data, per_host, arrays)
        mets_list, counts = [], []
        sync_every = _cpu_sync_every(self.mesh)
        for j, chunk in enumerate(
                _padded_chunks(stream, plan and plan[0], sample)):
            real = len(next(iter(chunk.values())))
            chunk, w = _pad_batch(chunk, per_host)
            gbatch = make_global_batch(self.mesh, chunk, self._data_sharding)
            gw = make_global_batch(self.mesh, {"w": w},
                                   self._data_sharding)["w"]
            # keep metrics on-device: blocking here would serialise eval
            # steps and pay a device round-trip per chunk
            mets_list.append(self._jit_eval_step(self.state, gbatch, gw))
            # ...except on the multi-device CPU mesh, where an
            # unbounded dispatch queue can breach XLA:CPU's 40 s
            # collective-rendezvous wall (_cpu_sync_every)
            if sync_every and len(mets_list) % sync_every == 0:
                jax.block_until_ready(mets_list[-1])
            # exact global row count per chunk: the zero-weight padding
            # rows never enter the metric averages
            counts.append(real if plan is None else plan[1][j])
        if mets_list:
            fetched = _fetch_stacked(mets_list)
            for i, cnt in enumerate(counts):
                acc.add({k: float(v[i]) for k, v in fetched.items()}, cnt)
        return acc.result()

    def predict(self, data, batch_size: Optional[int] = None,
                feature_cols=None) -> np.ndarray:
        batch_size = _resolve_batch(batch_size, data, "batch_per_thread")
        self._set_cols(feature_cols, None)
        per_host = max(1, batch_size // self._data_groups[0])
        self._check_host_local_source(data)
        # multihost alignment FIRST — before any record access (see fit)
        n_local, arrays = self._local_n(data)
        plan = self._chunk_plan(n_local, per_host)
        sample = arrays if arrays is not None else self._sample_of(data)
        for c in self.feature_cols:
            if c not in sample:
                raise KeyError(f"feature col {c!r} missing from predict data")
        self._ensure_state(sample)
        self._build_jits()
        outs, window = [], []
        single_host = _nhosts() == 1
        stream = self._local_eval_stream(data, per_host, arrays)
        for chunk in _padded_chunks(stream, plan and plan[0], sample):
            chunk = {k: v for k, v in chunk.items()
                     if k in self.feature_cols}
            real = len(next(iter(chunk.values())))
            chunk, _ = _pad_batch(chunk, per_host)
            gbatch = make_global_batch(self.mesh, chunk, self._data_sharding)
            preds = self._jit_predict_step(self.state, gbatch)
            # slice on-device, fetch in windowed batches: chunks pipeline
            # (no per-chunk round-trip) while device memory stays bounded
            # to `window` chunks of outputs instead of the whole dataset
            local = preds if single_host else _local_rows(preds)
            window.append(jax.tree.map(lambda a: a[:real], local))
            if len(window) >= 8:
                outs.extend(jax.device_get(window))
                window.clear()
        outs.extend(jax.device_get(window))
        return jax.tree.map(lambda *xs: np.concatenate(xs), *outs)

    # ------------------------------------------------------------------
    # checkpointing (Orbax; ref parity: set_checkpoint / save / load)
    # ------------------------------------------------------------------

    def _ckpt_items(self):
        return {"params": self.state.params,
                "opt_state": self.state.opt_state,
                "step": self.state.step,
                "batch_stats": self.state.batch_stats,
                "rng": jax.random.key_data(self.state.rng),
                "epoch": self._epoch}

    def _maybe_checkpoint(self):
        if self.config.checkpoint_dir:
            self.save_checkpoint(self.config.checkpoint_dir)

    def save_checkpoint(self, path: str):
        import orbax.checkpoint as ocp

        mgr = self._checkpoint_manager(path)
        mgr.save(int(self.state.step),
                 args=ocp.args.StandardSave(self._ckpt_items()))
        mgr.wait_until_finished()

    def load_checkpoint(self, path: str, step: Optional[int] = None):
        """Sharding-aware restore: arrays come back with this estimator's
        partition layout even if saved under a different mesh."""
        import orbax.checkpoint as ocp

        mgr = self._checkpoint_manager(path)
        step = step if step is not None else mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {path}")
        if self.state is None:
            raise RuntimeError(
                "call fit/evaluate once (or _ensure_state) before "
                "load_checkpoint so state structure is known")
        tpl = self._ckpt_items()
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            if isinstance(x, jax.Array) else x, tpl)
        restored = mgr.restore(step, args=ocp.args.StandardRestore(abstract))
        self.state = self.state.replace(
            params=restored["params"], opt_state=restored["opt_state"],
            step=restored["step"], batch_stats=restored["batch_stats"],
            rng=jax.random.wrap_key_data(restored["rng"]))
        self._epoch = int(restored.get("epoch", 0))
        # re-sync the host-side step counter (the one deliberate D2H read)
        self._global_step = int(np.asarray(restored["step"]))

    def _checkpoint_manager(self, path: str):
        import orbax.checkpoint as ocp

        path = _abs(path)
        if not hasattr(self, "_ckpt_mgrs"):
            self._ckpt_mgrs = {}
        if path not in self._ckpt_mgrs:
            self._ckpt_mgrs[path] = ocp.CheckpointManager(
                path,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=self.config.keep_checkpoints, create=True))
        return self._ckpt_mgrs[path]

    def save(self, path: str):
        """Export trained params (+batch_stats) — the reference's
        Estimator.save model export."""
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        payload = {"params": self.state.params}
        if self.state.batch_stats is not None:
            payload["batch_stats"] = self.state.batch_stats
        ckptr.save(_abs(path), payload, force=True)
        ckptr.wait_until_finished()

    def load(self, path: str, sample_data=None):
        import orbax.checkpoint as ocp

        if self.state is None:
            if sample_data is None:
                raise ValueError("load before first fit needs sample_data "
                                 "to build the state structure")
            self._ensure_state(DataCreator.to_arrays(sample_data))
        ckptr = ocp.StandardCheckpointer()
        tpl = {"params": self.state.params}
        if self.state.batch_stats is not None:
            tpl["batch_stats"] = self.state.batch_stats
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=x.sharding), tpl)
        restored = ckptr.restore(_abs(path), abstract)
        self.state = self.state.replace(
            params=restored["params"],
            batch_stats=restored.get("batch_stats"))

    def get_model(self):
        """(model, params) — ref parity: Estimator.get_model."""
        return self.model, None if self.state is None else self.state.params

    def lora_params(self):
        """The adapter tree alone — megabytes, the thing a fine-tune
        ships (learn/lora.py)."""
        from analytics_zoo_tpu.learn.lora import split_lora

        if self.lora is None or self.state is None:
            raise RuntimeError("no LoRA state: pass lora=LoRAConfig(...) "
                               "and fit/evaluate first")
        return split_lora(self.state.params)[1]

    def merged_params(self):
        """Base params with adapters folded in (W + scale·A@B) — plain
        tree for serving/InferenceModel, no __lora__ key."""
        from analytics_zoo_tpu.learn.lora import merge_lora

        if self.lora is None or self.state is None:
            raise RuntimeError("no LoRA state: pass lora=LoRAConfig(...) "
                               "and fit/evaluate first")
        return jax.device_get(merge_lora(self.state.params, self.lora))


def _abs(path: str) -> str:
    import os

    from analytics_zoo_tpu.common import fs

    # remote checkpoint dirs (gs://...) pass through verbatim — orbax
    # resolves the scheme via etils/tensorstore; os.path.abspath would
    # mangle the URI into a local path and silently checkpoint to disk
    if fs.is_remote(path):
        return path
    return os.path.abspath(path)


def _fetch_stacked(mets_list, chunk: int = 512):
    """Fetch a list of per-step scalar-metric dicts as dict of (n,) numpy
    arrays in O(metrics x n/chunk) device transfers.

    Two scaling traps this avoids: device_get on the raw list pays a full
    round-trip per leaf (O(n x metrics) — seconds per epoch on tunneled
    devices), while one giant stack builds an HLO with n operands
    (trace/lowering time explodes for long epochs).  Chunked eager stacks
    keep both costs linear with small constants.  The first stack dispatch
    is also the real epoch completion barrier's work — values must exist.
    """
    keys = list(mets_list[0].keys())
    stacked = {}
    for k in keys:
        vals = [m[k] for m in mets_list]
        stacked[k] = [jnp.stack(vals[i:i + chunk])
                      for i in range(0, len(vals), chunk)]
    # ONE device_get for every metric's chunks — per-key fetches would
    # pay a full round-trip per metric
    fetched = jax.device_get(stacked)
    return {k: np.concatenate(parts) for k, parts in fetched.items()}


def _resolve_batch(batch_size, data, attr: str) -> int:
    """Explicit batch_size wins; otherwise the data container's own
    metadata (TFDataset carries the reference's batch_size /
    batch_per_thread); otherwise the historical default of 32."""
    if batch_size is not None:
        return batch_size
    meta = getattr(data, attr, None)
    if isinstance(meta, int) and meta > 0:
        return meta
    return 32


def _allow_shared_disk() -> bool:
    """Kill-switch for the replicated-shard heuristic (distinct shards can
    in principle collide on the count+content fingerprint)."""
    import os

    return os.environ.get("ANALYTICS_ZOO_TPU_ALLOW_SHARED_DISK", "") == "1"


def _allgather_counts(n_local: int, fingerprint: int = 0) -> np.ndarray:
    """All hosts' (row count, content fingerprint) pairs, in process order
    (one tiny collective; replaces any out-of-band host coordination the
    reference did through the Spark driver).  Shape (n_hosts, 2); callers
    that only need counts use column 0 / ``.min()``."""
    from jax.experimental import multihost_utils

    return np.atleast_2d(np.asarray(multihost_utils.process_allgather(
        np.array([n_local, fingerprint], np.int64))))


class _StepLimitIterator:
    """Caps an epoch iterator at `max_steps` batches so every host runs the
    same number of collective steps even with uneven local row counts."""

    def __init__(self, it, max_steps: int):
        self._it = it
        self.max_steps = max_steps

    def steps_per_epoch(self) -> int:
        return min(self._it.steps_per_epoch(), self.max_steps)

    def epoch_batches(self):
        it = self._it
        e0 = getattr(it, "epoch", None)
        gen = it.epoch_batches()

        def limited():
            n = 0
            for b in gen:
                yield b
                n += 1
                if n >= self.max_steps:
                    break
            # release the source promptly (disk readers hold a ring buffer
            # + prefetch thread in their finally blocks)
            if hasattr(gen, "close"):
                gen.close()
            # NumpyBatchIterator only advances its epoch counter when its
            # generator runs to natural exhaustion; truncation would freeze
            # the shuffle seed at epoch 0 — advance it here if the source
            # didn't (disk iterators advance eagerly).
            if e0 is not None and getattr(it, "epoch", None) == e0:
                it.epoch = e0 + 1

        return limited()


def _padded_chunks(stream, n_chunks, sample):
    """Yield `stream`'s chunks, then zero-row chunks (shaped like `sample`'s
    columns) until `n_chunks` total — hosts that run out of rows still
    participate in the remaining collectives.  n_chunks=None: no padding."""
    j = 0
    for chunk in stream:
        yield chunk
        j += 1
    if n_chunks is not None and j < n_chunks:
        empty = {k: np.zeros((0,) + np.asarray(v).shape[1:],
                             np.asarray(v).dtype)
                 for k, v in sample.items()}
        while j < n_chunks:
            yield empty
            j += 1


def _host_local(data, groups=None) -> Dict[str, np.ndarray]:
    """Normalise `data` to this host's local rows.

    XShards are already host-disjoint (readers slice files per host);
    in-memory dicts/tuples are assumed REPLICATED across hosts (the natural
    way users pass ndarrays) and are row-sliced per DATA-SHARD GROUP here
    (`groups` = estimator._data_groups) — otherwise every host would feed
    identical rows into the global batch, silently training on duplicates.
    Group-mates (processes that are batch replicas, e.g. across a pp-only
    process boundary) intentionally keep identical rows.  Row counts
    truncate to the per-group share so every host runs the same step count
    (collective programs must agree)."""
    from analytics_zoo_tpu.data.shards import XShards

    arrays = DataCreator.to_arrays(data)
    ngroups, gi, _ = groups or (_nhosts(), _hidx(),
                                None)
    if _nhosts() == 1 or ngroups == 1 or \
            isinstance(data, XShards):
        return arrays
    n = len(next(iter(arrays.values())))
    per_group = n // ngroups
    lo = gi * per_group
    return {k: v[lo:lo + per_group] for k, v in arrays.items()}


def _pad_batch(batch: Dict[str, np.ndarray], to: int):
    n = len(next(iter(batch.values())))
    w = np.zeros(to, np.float32)
    w[:n] = 1.0
    if n == to:
        return batch, w
    out = {}
    for k, v in batch.items():
        pad = np.zeros((to - n,) + v.shape[1:], v.dtype)
        out[k] = np.concatenate([v, pad])
    return out, w


def _local_rows(preds) -> Any:
    """Fetch this host's rows of a (possibly sharded) prediction pytree."""
    def one(a):
        if _nhosts() == 1:
            return np.asarray(a)
        # multihost: concatenate this host's row shards in order, deduping
        # replicas (a replicated dim yields one shard per device with the
        # same rows and index[0].start of None).
        by_start = {}
        for s in a.addressable_shards:
            start = (s.index[0].start or 0) if s.index and \
                isinstance(s.index[0], slice) else 0
            by_start.setdefault(start, s)
        ordered = [by_start[k] for k in sorted(by_start)]
        return np.concatenate([np.asarray(s.data) for s in ordered])
    return jax.tree.map(one, preds)


def _route_train_config(config, kw):
    """`config` on the constructor facade is the reference's model-creator
    config dict; a TrainConfig passed there is clearly meant for the
    estimator — route it into kw instead of silently dropping it."""
    if isinstance(config, TrainConfig):
        kw.setdefault("config", config)
        return None
    return config


class Estimator:
    """Constructor facade — reference parity with zoo.orca.learn.*.Estimator."""

    @staticmethod
    def from_flax(*, model=None, model_creator=None, loss=None,
                  optimizer=None, config: Optional[dict] = None,
                  **kw) -> FlaxEstimator:
        config = _route_train_config(config, kw)
        if model is None:
            if model_creator is None:
                raise ValueError("need model or model_creator")
            model = model_creator(config or {})
        if optimizer is None:
            optimizer = optax.adam(1e-3)
        return FlaxEstimator(model, loss or "mse", optimizer, **kw)

    # Reference entry-point names. from_keras accepted tf.keras models;
    # here it accepts our keras/flax modules so orchestration code ports by
    # swapping the model definition.
    from_keras = from_flax

    @staticmethod
    def from_torch(*, model=None, model_creator=None, loss=None,
                   optimizer=None, config: Optional[dict] = None,
                   **kw) -> FlaxEstimator:
        """ref-parity: zoo.orca.learn.pytorch.Estimator.from_torch.

        A real torch nn.Module is converted to JAX via TorchNet (torch.fx
        graph -> pure function + param pytree, ref TorchNet.scala) and then
        trained by the same pjit Estimator; flax modules pass through."""
        config = _route_train_config(config, kw)
        if model is None:
            if model_creator is None:
                raise ValueError("need model or model_creator")
            model = model_creator(config or {})
        if optimizer is None:
            optimizer = optax.adam(1e-3)
        # conversion happens inside FlaxEstimator.__init__ (all paths)
        return FlaxEstimator(model, loss or "mse", optimizer, **kw)
    from_graph = from_flax
    from_bigdl = from_flax

    @staticmethod
    def from_openvino(*, model_path: Optional[str] = None,
                      bin_path: Optional[str] = None, **kw):
        """ref-parity name: zoo.orca.learn.openvino.Estimator.from_openvino
        (batch inference with OpenVINO IR over Spark partitions).

        The IR's ``.xml + .bin`` FORMAT is read directly
        (net/openvino_ir.py translates the graph to one XLA-compiled
        function; no IE runtime involved) and served by the same
        predict/evaluate machinery as every other estimator.  Like the
        reference's OpenVINO estimator, this one is INFERENCE-ONLY:
        ``fit`` raises (an IR is a frozen deployment artifact — train
        the original model instead)."""
        from analytics_zoo_tpu.net.openvino_ir import OpenVINONet

        if not model_path:
            raise ValueError("from_openvino needs model_path=<model.xml>")
        net = OpenVINONet.from_ir(model_path, bin_path)
        est = FlaxEstimator(net, kw.pop("loss", None) or "mse",
                            optax.sgd(0.0), **kw)

        def _no_fit(*a, **k):
            raise NotImplementedError(
                "OpenVINO estimators are inference-only (the IR is a "
                "frozen artifact — ref parity with "
                "zoo.orca.learn.openvino); use predict/evaluate, or "
                "train the original model via from_flax/from_torch")

        est.fit = _no_fit
        return est

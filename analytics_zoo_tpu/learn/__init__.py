from analytics_zoo_tpu.learn.estimator import Estimator, FlaxEstimator
from analytics_zoo_tpu.learn.train_state import ZooTrainState, create_train_state
from analytics_zoo_tpu.learn.triggers import EarlyStopping
from analytics_zoo_tpu.learn.lora import LoRAConfig
from analytics_zoo_tpu.learn import objectives, metrics, triggers

__all__ = ["Estimator", "FlaxEstimator", "ZooTrainState",
           "create_train_state", "objectives", "metrics", "triggers",
           "EarlyStopping", "LoRAConfig"]

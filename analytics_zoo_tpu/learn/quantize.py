"""Weight quantization for inference (the reference's OpenVINO int8 story).

Reference surface (SURVEY.md §2.3; ref: pipeline/inference/ — OpenVINO IR
loading with optional int8 calibration): serve a trained model with
quantized weights for smaller memory and higher throughput.

TPU re-design: **weight-only symmetric int8** with per-output-channel
scales.  Weights live in HBM as int8 (4x smaller than f32); the dequant
(`q.astype(f32) * scale`) happens INSIDE the jitted forward, where XLA
fuses it into the consumer matmul's operand read — serving memory drops
~4x while activations/compute stay in bf16/f32, which preserves accuracy
without calibration data (the reason the reference needed a calibration
set was quantized *activations*; weight-only needs none).  ``bf16`` mode
is the cheaper half-measure: cast weights to bfloat16 (2x smaller,
bit-level TPU-native).

Two execution modes share the int8 storage format:

- ``"int8"`` — **memory-capacity knob** (measured, SERVING_BENCH.json:
  resnet18 int8 91 req/s vs 139 fp @64 clients, 3.97x weight
  compression).  Weights dequantize inside the jitted forward (fused
  into the consumer matmul's operand read); compute stays f32/bf16.
  Wins when HBM is the binding constraint; costs ~35% req/s.
- ``"int8_mxu"`` — **on-MXU int8** (VERDICT r4 ask #4): activations are
  quantized DYNAMICALLY per-tensor (runtime abs-max — no calibration
  set, the thing the reference's OpenVINO int8 needed one for), and
  ``nn.Dense``/``nn.Conv`` execute as int8 x int8 -> int32
  ``dot_general``/``conv_general_dilated`` (``preferred_element_type``)
  with the float rescale applied to the int32 accumulator.  The MXU's
  int8 throughput is ~2x its bf16 rate, so this is the speed mode.
  No model surgery: a flax method interceptor (``int8_call``) rewrites
  the Dense/Conv call sites at apply time; layers whose kernels were
  not quantized (too small / not 2-D) run their normal float path.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_Q = "__q8__"
_S = "__q8_scale__"


def _is_qleaf(node) -> bool:
    return isinstance(node, dict) and _Q in node


def _quant_leaf(w: np.ndarray, min_size: int):
    w = np.asarray(w)
    if w.ndim < 2 or w.size < min_size or \
            w.dtype not in (np.float32, np.float64):
        return w
    # per-output-channel (last axis) symmetric scale
    amax = np.max(np.abs(w), axis=tuple(range(w.ndim - 1)), keepdims=True)
    scale = (np.maximum(amax, 1e-12) / 127.0).astype(np.float32)
    q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
    return {_Q: q, _S: scale}


def quantize_params(tree, mode: str = "int8",
                    min_size: int = 1024) -> Tuple[Any, Dict[str, float]]:
    """Quantize a variables pytree.  Returns (new_tree, stats) where stats
    reports the weight-bytes ratio.  Leaves smaller than `min_size`
    elements (biases, norm scales) stay f32 — they are noise in the memory
    budget and matter for accuracy."""
    before = sum(np.asarray(l).nbytes for l in jax.tree.leaves(tree))
    if mode == "bf16":
        new = jax.tree.map(
            lambda l: jnp.asarray(l, jnp.bfloat16)
            if np.asarray(l).dtype in (np.float32, np.float64)
            and np.asarray(l).ndim >= 2 else l, tree)
    elif mode == "int8":
        new = jax.tree.map(lambda l: _quant_leaf(l, min_size), tree)
    else:
        raise ValueError(f"unknown quantize mode {mode!r} (int8|bf16)")
    after = 0
    for leaf in jax.tree.leaves(new, is_leaf=_is_qleaf):
        if _is_qleaf(leaf):
            after += leaf[_Q].nbytes + leaf[_S].nbytes
        else:
            after += np.asarray(leaf).nbytes
    return new, {"weight_bytes_f32": before, "weight_bytes_quant": after,
                 "compression": round(before / max(after, 1), 2)}


def dequantize(tree):
    """Inverse transform — runs inside jit, so XLA fuses the int8 load +
    scale into the consuming op."""
    return jax.tree.map(
        lambda n: n[_Q].astype(jnp.float32) * n[_S] if _is_qleaf(n) else n,
        tree, is_leaf=_is_qleaf)


# ---------------------------------------------------------------------------
# on-MXU int8 execution (quantized activations, int32 accumulation)
# ---------------------------------------------------------------------------

def _dyn_quant(x):
    """Dynamic per-tensor symmetric activation quantization: runtime
    abs-max -> scale, so NO calibration pass is needed.  Per-tensor (not
    per-channel) keeps the rescale a scalar multiply on the int32
    accumulator."""
    xs = jnp.maximum(jnp.max(jnp.abs(x)) / 127.0, 1e-12)
    xq = jnp.clip(jnp.round(x / xs), -127, 127).astype(jnp.int8)
    return xq, xs.astype(jnp.float32)


def _dense_int8(mod, x, kernel):
    wq, ws = kernel[_Q], kernel[_S]
    xq, xs = _dyn_quant(x)
    acc = lax.dot_general(xq, wq, (((x.ndim - 1,), (0,)), ((), ())),
                          preferred_element_type=jnp.int32)
    y = acc.astype(jnp.float32) * (xs * ws.reshape(-1))
    if mod.use_bias:
        y = y + mod.get_variable("params", "bias")
    return y.astype(x.dtype) if x.dtype != jnp.float32 else y


def _canon_padding(p, nsp):
    """flax nn.Conv padding -> the lax form, or None when not lowerable
    (CIRCULAR/CAUSAL strings need flax's own pre-padding)."""
    if isinstance(p, str):
        return p if p in ("SAME", "VALID") else None
    if isinstance(p, int):
        return [(p, p)] * nsp
    try:
        out = [(e, e) if isinstance(e, int) else tuple(e) for e in p]
    except TypeError:
        return None
    return out if len(out) == nsp else None


def _conv_int8(mod, x, kernel, padding):
    """nn.Conv on the MXU's int8 path.  Covers the channel-last layouts
    flax emits (1-3 spatial dims, strides/padding/dilations/groups pass
    through); exotic configs take the float path upstream."""
    wq, ws = kernel[_Q], kernel[_S]
    nsp = wq.ndim - 2                       # spatial dims
    sp = "DHW"[-nsp:]
    dn = lax.conv_dimension_numbers(
        x.shape, wq.shape,
        (f"N{sp}C", f"{sp}IO", f"N{sp}C"))

    def _tup(v, default=1):
        if v is None:
            return (default,) * nsp
        if isinstance(v, int):
            return (v,) * nsp
        return tuple(v)

    xq, xs = _dyn_quant(x)
    acc = lax.conv_general_dilated(
        xq, wq, window_strides=_tup(mod.strides),
        padding=padding,
        lhs_dilation=_tup(getattr(mod, "input_dilation", None)),
        rhs_dilation=_tup(getattr(mod, "kernel_dilation", None)),
        dimension_numbers=dn,
        feature_group_count=int(getattr(mod, "feature_group_count", 1)),
        preferred_element_type=jnp.int32)
    y = acc.astype(jnp.float32) * (xs * ws.reshape(-1))
    if mod.use_bias:
        y = y + mod.get_variable("params", "bias")
    return y.astype(x.dtype) if x.dtype != jnp.float32 else y


def _qleaf_paths(variables) -> Dict[tuple, Any]:
    """Map module-path tuples (scope-relative, 'params' stripped) to
    quantized kernel leaves."""
    out = {}

    def walk(node, path):
        if _is_qleaf(node):
            out[path] = node
            return
        if isinstance(node, dict) or hasattr(node, "items"):
            for k, v in node.items():
                walk(v, path + (k,))

    params = variables.get("params", {}) if hasattr(variables, "get") \
        else {}
    walk(params, ())
    return out


def int8_call(model, variables, *args, **kwargs):
    """Run ``model.apply(variables, *args, **kwargs)`` with quantized
    ``nn.Dense``/``nn.Conv`` layers executing as int8 x int8 -> int32 on
    the MXU (dynamic per-tensor activation scales).

    Robustness contract: ``apply`` itself runs on the DEQUANTIZED tree,
    so every consumer this path does not intercept — ``nn.Embed``
    tables, ``nn.DenseGeneral``/attention kernels, Dense subclasses,
    keyword-arg calls, exotic conv configs — computes the correct float
    result (weight-only semantics) instead of reading an int8 dict and
    crashing.  The interceptor pulls the int8 leaves from a side map
    keyed by module path; XLA dead-code-eliminates the dequantized
    copies of every kernel the interceptor actually replaced."""
    import flax.linen as nn

    qmap = _qleaf_paths(variables)
    deq = dequantize(variables)

    def interceptor(next_fun, iargs, ikwargs, context):
        mod = context.module
        if context.method_name == "__call__" and \
                type(mod) in (nn.Dense, nn.Conv) and not ikwargs \
                and iargs and hasattr(iargs[0], "ndim"):
            kernel = qmap.get(tuple(mod.path) + ("kernel",))
            if kernel is not None:
                x = iargs[0]
                if type(mod) is nn.Dense:
                    # only the plain configuration: a scan/vmap-lifted
                    # Dense carries a stacked (3-D) kernel, and a custom
                    # dot_general / non-default precision would be
                    # silently replaced — both take the float fallback
                    if kernel[_Q].ndim == 2 \
                            and getattr(mod, "dot_general", None) is None \
                            and getattr(mod, "dot_general_cls", None) \
                            is None \
                            and getattr(mod, "precision", None) is None:
                        return _dense_int8(mod, x, kernel)
                    return next_fun(*iargs, **ikwargs)
                nsp = kernel[_Q].ndim - 2
                padding = _canon_padding(mod.padding, nsp)
                if nsp in (1, 2, 3) and x.ndim == nsp + 2 \
                        and getattr(mod, "mask", None) is None \
                        and padding is not None:
                    return _conv_int8(mod, x, kernel, padding)
                # unsupported conv config: float path (weight-only
                # semantics) via the dequantized tree below
        return next_fun(*iargs, **ikwargs)

    with nn.intercept_methods(interceptor):
        return model.apply(deq, *args, **kwargs)


__all__ = ["quantize_params", "dequantize", "int8_call"]

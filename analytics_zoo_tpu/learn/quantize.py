"""Weight quantization for inference (the reference's OpenVINO int8 story).

Reference surface (SURVEY.md §2.3; ref: pipeline/inference/ — OpenVINO IR
loading with optional int8 calibration): serve a trained model with
quantized weights for smaller memory and higher throughput.

TPU re-design: **weight-only symmetric int8** with per-output-channel
scales.  Weights live in HBM as int8 (4x smaller than f32); the dequant
(`q.astype(f32) * scale`) happens INSIDE the jitted forward, where XLA
fuses it into the consumer matmul's operand read — serving memory drops
~4x while activations/compute stay in bf16/f32, which preserves accuracy
without calibration data (the reason the reference needed a calibration
set was quantized *activations*; weight-only needs none).  ``bf16`` mode
is the cheaper half-measure: cast weights to bfloat16 (2x smaller,
bit-level TPU-native).

**Scope — a MEMORY-CAPACITY knob, not a throughput knob** (measured,
SERVING_BENCH.json: resnet18 int8 91 req/s vs 139 fp @64 clients, 3.97x
weight compression).  The fused dequant adds work to every forward, so
int8 TRADES ~35% throughput for ~4x model capacity; it wins when HBM is
the binding constraint — more co-resident models per chip, weights that
otherwise would not fit, bigger KV arenas beside the weights — and
loses when raw req/s on a single resident model is all that matters
(serve fp/bf16 there).  True on-MXU int8 (quantized activations,
int8xint8->int32 `dot_general`) would need per-layer activation scale
calibration and model-surgery on the matmul call sites; that is a
deliberate non-goal for the GENERIC param-tree path here, which must
quantize any loaded model without touching its module code.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_Q = "__q8__"
_S = "__q8_scale__"


def _is_qleaf(node) -> bool:
    return isinstance(node, dict) and _Q in node


def _quant_leaf(w: np.ndarray, min_size: int):
    w = np.asarray(w)
    if w.ndim < 2 or w.size < min_size or \
            w.dtype not in (np.float32, np.float64):
        return w
    # per-output-channel (last axis) symmetric scale
    amax = np.max(np.abs(w), axis=tuple(range(w.ndim - 1)), keepdims=True)
    scale = (np.maximum(amax, 1e-12) / 127.0).astype(np.float32)
    q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
    return {_Q: q, _S: scale}


def quantize_params(tree, mode: str = "int8",
                    min_size: int = 1024) -> Tuple[Any, Dict[str, float]]:
    """Quantize a variables pytree.  Returns (new_tree, stats) where stats
    reports the weight-bytes ratio.  Leaves smaller than `min_size`
    elements (biases, norm scales) stay f32 — they are noise in the memory
    budget and matter for accuracy."""
    before = sum(np.asarray(l).nbytes for l in jax.tree.leaves(tree))
    if mode == "bf16":
        new = jax.tree.map(
            lambda l: jnp.asarray(l, jnp.bfloat16)
            if np.asarray(l).dtype in (np.float32, np.float64)
            and np.asarray(l).ndim >= 2 else l, tree)
    elif mode == "int8":
        new = jax.tree.map(lambda l: _quant_leaf(l, min_size), tree)
    else:
        raise ValueError(f"unknown quantize mode {mode!r} (int8|bf16)")
    after = 0
    for leaf in jax.tree.leaves(new, is_leaf=_is_qleaf):
        if _is_qleaf(leaf):
            after += leaf[_Q].nbytes + leaf[_S].nbytes
        else:
            after += np.asarray(leaf).nbytes
    return new, {"weight_bytes_f32": before, "weight_bytes_quant": after,
                 "compression": round(before / max(after, 1), 2)}


def dequantize(tree):
    """Inverse transform — runs inside jit, so XLA fuses the int8 load +
    scale into the consuming op."""
    return jax.tree.map(
        lambda n: n[_Q].astype(jnp.float32) * n[_S] if _is_qleaf(n) else n,
        tree, is_leaf=_is_qleaf)


__all__ = ["quantize_params", "dequantize"]

"""TCMFForecaster — temporal-convolutional matrix factorization.

Reference surface (SURVEY.md §2.5; ref: pyzoo/zoo/zouwu/model/forecast.py
TCMFForecaster over zoo.tcmf / DeepGLO-style model, distributed via Ray):
high-dimensional multi-series forecasting by factorizing the series matrix
Y [n, T] ≈ F [n, k] · X [k, T] — n can be huge (AdServer-scale), the
temporal dynamics live in the low-rank basis X, and a temporal conv net
learns X's dynamics to roll the basis forward.

TPU re-design: no Ray actors — the whole alternating objective is jitted:
  1. reconstruction: joint SGD on (F, X) minimizing ||Y - F X||^2 (+ l2),
     one fused XLA step over the full matrices (MXU matmuls);
  2. dynamics: a causal dilated-conv net (models.forecast.TCNNet) trained
     on windows of X to predict the next basis step;
  3. forecast: autoregressively roll X forward h steps with the TCN,
     then Ŷ_future = F · X̂ — again one matmul on the MXU.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from analytics_zoo_tpu.common.log import logger


class TCMFForecaster:
    """ref-parity: zouwu TCMFForecaster (fit / predict / evaluate).

    Args:
      rank: latent dimension k of the factorization.
      window: TCN look-back length over the basis X.
      l2: factor regularization weight.
    """

    def __init__(self, rank: int = 16, window: int = 24, l2: float = 1e-4,
                 tcn_channels=(32, 32), lr: float = 1e-2, seed: int = 0):
        self.rank = rank
        self.window = window
        self.l2 = l2
        self.tcn_channels = tuple(tcn_channels)
        self.lr = lr
        self.seed = seed
        self.F: Optional[jax.Array] = None
        self.X: Optional[jax.Array] = None
        self._tcn = None
        self._tcn_params = None

    # ------------------------------------------------------------------

    def fit(self, y: np.ndarray, *, epochs: int = 300,
            tcn_epochs: int = 200, verbose: bool = False) -> Dict:
        """y: [n_series, T] float matrix (NaNs are masked out of the
        reconstruction loss — the reference's missing-data story)."""
        y = np.asarray(y, np.float32)
        if y.ndim != 2:
            raise ValueError(f"y must be [n_series, T], got {y.shape}")
        n, T = y.shape
        if T <= self.window + 1:
            raise ValueError(f"series length {T} must exceed window+1="
                             f"{self.window + 1}")
        k = self.rank
        key = jax.random.key(self.seed)
        kf, kx, kt = jax.random.split(key, 3)
        mask = jnp.asarray(~np.isnan(y))
        yj = jnp.nan_to_num(jnp.asarray(y))
        scale = float(np.nanstd(y) or 1.0)
        F = jax.random.normal(kf, (n, k)) * 0.1
        X = jax.random.normal(kx, (k, T)) * 0.1
        tx = optax.adam(self.lr)
        opt = tx.init((F, X))

        def recon_loss(FX):
            F, X = FX
            err = jnp.where(mask, yj - F @ X, 0.0)
            denom = jnp.maximum(1, mask.sum())
            return (jnp.sum(err * err) / denom / (scale * scale)
                    + self.l2 * (jnp.mean(F * F) + jnp.mean(X * X)))

        @jax.jit
        def recon_step(FX, opt):
            loss, g = jax.value_and_grad(recon_loss)(FX)
            upd, opt = tx.update(g, opt, FX)
            return optax.apply_updates(FX, upd), opt, loss

        FX = (F, X)
        loss = None
        for ep in range(epochs):
            FX, opt, loss = recon_step(FX, opt)
            if verbose and (ep + 1) % 50 == 0:
                logger.info("tcmf recon %d: %.5f", ep + 1,
                            float(loss))
        self.F, self.X = FX
        recon = float(loss)

        # ---- dynamics: TCN over the basis ----------------------------
        from analytics_zoo_tpu.models.forecast import TCN

        self._tcn = TCN(output_dim=k, horizon=1, dropout=0.0,
                        channels=self.tcn_channels)
        from analytics_zoo_tpu.zouwu.preprocessing import roll

        Xh = np.asarray(self.X.T)                     # [T, k]
        w = self.window
        xs, ys = roll(Xh, lookback=w, horizon=1)      # [N,w,k], [N,1,k]
        variables = self._tcn.init(kt, jnp.asarray(xs[:1]))
        t2 = optax.adam(self.lr)
        o2 = t2.init(variables["params"])

        def tcn_loss(p, xb, yb):
            pred = self._tcn.apply({"params": p}, xb)
            return jnp.mean((pred - yb) ** 2)

        @jax.jit
        def tcn_step(p, o, xb, yb):
            loss, g = jax.value_and_grad(tcn_loss)(p, xb, yb)
            upd, o = t2.update(g, o, p)
            return optax.apply_updates(p, upd), o, loss

        p = variables["params"]
        xsj, ysj = jnp.asarray(xs), jnp.asarray(ys)
        tloss = None
        for ep in range(tcn_epochs):
            p, o2, tloss = tcn_step(p, o2, xsj, ysj)
        self._tcn_params = p
        stats = {"recon_loss": recon, "tcn_loss": float(tloss)}
        logger.info("TCMF fit done: %s", stats)
        return stats

    # ------------------------------------------------------------------

    def predict(self, horizon: int = 24) -> np.ndarray:
        """Roll the basis forward `horizon` steps; return [n, horizon]."""
        if self.F is None:
            raise RuntimeError("fit first")
        w, k = self.window, self.rank

        def roll(carry, _):
            window = carry                                # [w, k]
            nxt = self._tcn.apply({"params": self._tcn_params},
                                  window[None])[0, -1]    # [k]
            return jnp.concatenate([window[1:], nxt[None]]), nxt

        x_last = self.X.T[-w:]                            # [w, k]
        _, xs = jax.lax.scan(roll, x_last, None, length=horizon)
        return np.asarray(self.F @ xs.T)                  # [n, horizon]

    def evaluate(self, y_true: np.ndarray,
                 metrics=("mse",)) -> Dict[str, float]:
        pred = self.predict(y_true.shape[1])
        out = {}
        for m in metrics:
            if m == "mse":
                out[m] = float(np.mean((pred - y_true) ** 2))
            elif m == "mae":
                out[m] = float(np.mean(np.abs(pred - y_true)))
            elif m == "smape":
                out[m] = float(np.mean(
                    2 * np.abs(pred - y_true)
                    / (np.abs(pred) + np.abs(y_true) + 1e-8)))
            else:
                raise ValueError(f"unknown metric {m}")
        return out

    # ------------------------------------------------------------------

    def save(self, path: str):
        import os
        import pickle

        os.makedirs(path, exist_ok=True)
        blob = {"cfg": (self.rank, self.window, self.l2, self.tcn_channels,
                        self.lr, self.seed),
                "F": np.asarray(self.F), "X": np.asarray(self.X),
                "tcn_params": jax.tree.map(np.asarray, self._tcn_params)}
        with open(os.path.join(path, "tcmf.pkl"), "wb") as f:
            pickle.dump(blob, f)

    @staticmethod
    def load(path: str) -> "TCMFForecaster":
        import os
        import pickle

        from analytics_zoo_tpu.models.forecast import TCN

        with open(os.path.join(path, "tcmf.pkl"), "rb") as f:
            blob = pickle.load(f)
        rank, window, l2, chans, lr, seed = blob["cfg"]
        fc = TCMFForecaster(rank=rank, window=window, l2=l2,
                            tcn_channels=chans, lr=lr, seed=seed)
        fc.F = jnp.asarray(blob["F"])
        fc.X = jnp.asarray(blob["X"])
        fc._tcn = TCN(output_dim=rank, horizon=1, dropout=0.0,
                      channels=chans)
        fc._tcn_params = jax.tree.map(jnp.asarray, blob["tcn_params"])
        return fc

"""TCMFForecaster — temporal-convolutional matrix factorization.

Reference surface (SURVEY.md §2.5; ref: pyzoo/zoo/zouwu/model/forecast.py
TCMFForecaster over zoo.tcmf / DeepGLO-style model, distributed via Ray):
high-dimensional multi-series forecasting by factorizing the series matrix
Y [n, T] ≈ F [n, k] · X [k, T] — n can be huge (AdServer-scale), the
temporal dynamics live in the low-rank basis X, and a temporal conv net
learns X's dynamics to roll the basis forward.

TPU re-design: no Ray actors — the whole alternating objective is jitted:
  1. reconstruction: joint SGD on (F, X) minimizing ||Y - F X||^2 (+ l2),
     one fused XLA step over the full matrices (MXU matmuls);
  2. dynamics: a causal dilated-conv net (models.forecast.TCNNet) trained
     on windows of X to predict the next basis step;
  3. forecast: autoregressively roll X forward h steps with the TCN,
     then Ŷ_future = F · X̂ — again one matmul on the MXU.

Reference-scale n (the reason the reference distributed TCMF over Ray):
``series_block=B`` streams the reconstruction in row blocks so device
memory is O(B·T + k·T) — Y stays host-side, F (and its Adam state) lives
host-side per block, only X + one block are resident.  The math is the
SAME joint step: the loss decomposes over rows, every gradient is taken
at epoch-start values (∂F_b from the block alone; ∂X accumulated across
blocks), and Adam is elementwise — so the streamed update equals the
dense update exactly, up to float summation order (equivalence test:
tests/test_tcmf.py).  Multi-series scale-out across hosts composes the
same way the reference's Ray actors did: block ranges per host.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from analytics_zoo_tpu.common.log import logger


class TCMFForecaster:
    """ref-parity: zouwu TCMFForecaster (fit / predict / evaluate).

    Args:
      rank: latent dimension k of the factorization.
      window: TCN look-back length over the basis X.
      l2: factor regularization weight.
    """

    def __init__(self, rank: int = 16, window: int = 24, l2: float = 1e-4,
                 tcn_channels=(32, 32), lr: float = 1e-2, seed: int = 0,
                 series_block: Optional[int] = None,
                 collect_memory_stats: bool = False):
        self.rank = rank
        self.window = window
        self.l2 = l2
        self.tcn_channels = tuple(tcn_channels)
        self.lr = lr
        self.seed = seed
        # series_block=B streams the factorization in [B, T] row blocks:
        # device memory O(B*T + k*T) instead of O(n*T) — the path for n
        # beyond HBM (the reference's distributed-TCMF scale).
        self.series_block = series_block
        self.F: Optional[jax.Array] = None      # [n, k] (numpy when
        #                                         streaming — host-resident)
        self.X: Optional[jax.Array] = None      # [k, T]
        self._tcn = None
        self._tcn_params = None
        # opt-in (costs an O(live-arrays) scan per block, and measures
        # PROCESS-global live arrays — meaningful in a dedicated process
        # / test, misleading next to unrelated resident models).  Reports
        # the largest single live device array seen during fit.
        self.collect_memory_stats = collect_memory_stats
        self.peak_device_elems: Optional[int] = None

    # ------------------------------------------------------------------

    def fit(self, y: np.ndarray, *, epochs: int = 300,
            tcn_epochs: int = 200, verbose: bool = False) -> Dict:
        """y: [n_series, T] float matrix (NaNs are masked out of the
        reconstruction loss — the reference's missing-data story)."""
        y = np.asarray(y, np.float32)
        if y.ndim != 2:
            raise ValueError(f"y must be [n_series, T], got {y.shape}")
        n, T = y.shape
        if T <= self.window + 1:
            raise ValueError(f"series length {T} must exceed window+1="
                             f"{self.window + 1}")
        k = self.rank
        key = jax.random.key(self.seed)
        kf, kx, kt = jax.random.split(key, 3)
        scale = float(np.nanstd(y) or 1.0)
        X = jax.random.normal(kx, (k, T)) * 0.1
        if self.series_block:
            recon = self._fit_recon_streamed(y, X, kf, epochs, scale,
                                             verbose)
        else:
            recon = self._fit_recon_dense(y, X, kf, epochs, scale,
                                          verbose)

        # ---- dynamics: TCN over the basis ----------------------------
        from analytics_zoo_tpu.models.forecast import TCN

        self._tcn = TCN(output_dim=k, horizon=1, dropout=0.0,
                        channels=self.tcn_channels)
        from analytics_zoo_tpu.zouwu.preprocessing import roll

        Xh = np.asarray(self.X.T)                     # [T, k]
        w = self.window
        xs, ys = roll(Xh, lookback=w, horizon=1)      # [N,w,k], [N,1,k]
        variables = self._tcn.init(kt, jnp.asarray(xs[:1]))
        t2 = optax.adam(self.lr)
        o2 = t2.init(variables["params"])

        def tcn_loss(p, xb, yb):
            pred = self._tcn.apply({"params": p}, xb)
            return jnp.mean((pred - yb) ** 2)

        @jax.jit
        def tcn_step(p, o, xb, yb):
            loss, g = jax.value_and_grad(tcn_loss)(p, xb, yb)
            upd, o = t2.update(g, o, p)
            return optax.apply_updates(p, upd), o, loss

        p = variables["params"]
        xsj, ysj = jnp.asarray(xs), jnp.asarray(ys)
        tloss = None
        for ep in range(tcn_epochs):
            p, o2, tloss = tcn_step(p, o2, xsj, ysj)
        self._tcn_params = p
        stats = {"recon_loss": recon, "tcn_loss": float(tloss)}
        logger.info("TCMF fit done: %s", stats)
        return stats

    # ------------------------------------------------------------------
    # reconstruction backends
    # ------------------------------------------------------------------

    def _fit_recon_dense(self, y, X, kf, epochs, scale, verbose) -> float:
        """Whole-matrix joint step (n fits in device memory)."""
        n, T = y.shape
        k = self.rank
        mask = jnp.asarray(~np.isnan(y))
        yj = jnp.nan_to_num(jnp.asarray(y))
        F = jax.random.normal(kf, (n, k)) * 0.1
        tx = optax.adam(self.lr)
        opt = tx.init((F, X))

        def recon_loss(FX):
            F, X = FX
            err = jnp.where(mask, yj - F @ X, 0.0)
            denom = jnp.maximum(1, mask.sum())
            return (jnp.sum(err * err) / denom / (scale * scale)
                    + self.l2 * (jnp.mean(F * F) + jnp.mean(X * X)))

        @jax.jit
        def recon_step(FX, opt):
            loss, g = jax.value_and_grad(recon_loss)(FX)
            upd, opt = tx.update(g, opt, FX)
            return optax.apply_updates(FX, upd), opt, loss

        FX = (F, X)
        loss = None
        for ep in range(epochs):
            FX, opt, loss = recon_step(FX, opt)
            if verbose and (ep + 1) % 50 == 0:
                logger.info("tcmf recon %d: %.5f", ep + 1, float(loss))
        self.F, self.X = FX
        return float(loss)

    def _fit_recon_streamed(self, y, X, kf, epochs, scale,
                            verbose) -> float:
        """Row-block streaming joint step — the SAME update as
        `_fit_recon_dense` (gradients at epoch-start values; the loss
        decomposes over row blocks; Adam is elementwise, so per-block
        Adam state equals the dense state sliced), with device memory
        O(B·T + k·T).  Y, F and F's Adam moments stay host-side numpy;
        each epoch streams every block through one jitted kernel,
        accumulating X's gradient across blocks on device."""
        n, T = y.shape
        k, B = self.rank, int(self.series_block)
        nb = (n + B - 1) // B
        # global constants of the objective (the dense step's
        # denominators); the NaN mask is computed ONCE — it never
        # changes during fit
        mask_np = ~np.isnan(y)
        denom = float(max(1, mask_np.sum()))
        y = np.nan_to_num(y)
        sc2 = scale * scale
        # host-resident factor + Adam moments (float32, [n, k] each) —
        # the moments are the SAME optax.adam state as the dense path,
        # sliced per block (ScaleByAdamState fields are plain arrays)
        F = jax.device_get(jax.random.normal(kf, (n, k)) * 0.1)
        F = F.astype(np.float32)
        mF = np.zeros((n, k), np.float32)
        vF = np.zeros((n, k), np.float32)
        txF = optax.adam(self.lr)
        optF_tmpl = txF.init(jnp.zeros((1, k)))     # state STRUCTURE
        txX = optax.adam(self.lr)
        optX = txX.init(X)

        @jax.jit
        def block_grads(Fb, X, yb, maskb):
            """Loss contribution + gradients of THIS block at epoch-start
            values.  l2 terms use the dense objective's global means:
            mean(F*F) decomposes as sum(Fb*Fb)/(n*k)."""
            err = jnp.where(maskb, yb - Fb @ X, 0.0)
            part = jnp.sum(err * err) / denom / sc2 \
                + self.l2 * jnp.sum(Fb * Fb) / (n * k)
            gFb = (-2.0 / denom / sc2) * (err @ X.T) \
                + self.l2 * 2.0 * Fb / (n * k)
            gX_part = (-2.0 / denom / sc2) * (Fb.T @ err)
            return part, gFb, gX_part

        @jax.jit
        def adam_block(Fb, gFb, mb, vb, count):
            """One optimizer definition for both backends: rebuild the
            optax.adam state from the sliced moments and step it."""
            st = jax.tree.map(lambda x: x, optF_tmpl)   # copy structure
            st = (st[0]._replace(count=count, mu=mb, nu=vb),) + st[1:]
            upd, st = txF.update(gFb, st, Fb)
            return (optax.apply_updates(Fb, upd),
                    st[0].mu, st[0].nu)

        @jax.jit
        def apply_X(X, gX, optX):
            # the l2 term on X is global — add it once, after the sum
            gX = gX + self.l2 * 2.0 * X / (k * T)
            upd, optX = txX.update(gX, optX, X)
            return optax.apply_updates(X, upd), optX

        peak = 0
        baseline_refs = []
        if self.collect_memory_stats:
            # peak must attribute arrays to THIS fit: under a shared
            # process (e.g. a test suite) unrelated live arrays would
            # otherwise dominate the max.  Weakrefs keep the id check
            # precise — a dead baseline array's id can be legitimately
            # reused by a new (counted) array.
            import weakref

            for a in jax.live_arrays():
                try:
                    baseline_refs.append(weakref.ref(a))
                except TypeError:       # non-weakref-able array impl
                    pass
        loss = None
        for ep in range(epochs):
            count = jnp.int32(ep)       # optax counts UPDATES SO FAR
            gX = jnp.zeros_like(X)
            total = jnp.float32(0.0)
            for b in range(nb):
                lo, hi = b * B, min((b + 1) * B, n)
                Fb_dev = jnp.asarray(F[lo:hi])      # one H2D per block
                part, gFb, gX_part = block_grads(
                    Fb_dev, X, jnp.asarray(y[lo:hi]),
                    jnp.asarray(mask_np[lo:hi]))
                total = total + part
                gX = gX + gX_part
                Fb, mb, vb = adam_block(
                    Fb_dev, gFb, jnp.asarray(mF[lo:hi]),
                    jnp.asarray(vF[lo:hi]), count)
                if self.collect_memory_stats:
                    # sample while the block's arrays are LIVE — the
                    # honest transient footprint, not the between-epochs
                    # floor (largest single array created by this fit)
                    alive_baseline = {id(r()) for r in baseline_refs
                                      if r() is not None}
                    peak = max(peak, max(
                        (a.size for a in jax.live_arrays()
                         if id(a) not in alive_baseline), default=0))
                # one fetch for the block's factor + both Adam moments
                # (host-resident streaming is the point of this path)
                Fb, mb, vb = jax.device_get((Fb, mb, vb))
                F[lo:hi] = Fb
                mF[lo:hi] = mb
                vF[lo:hi] = vb
            # reported loss is at epoch-START values, like the dense
            # value_and_grad (X's l2 term added before X is updated);
            # it stays a device scalar — only the log point and the
            # final return ever materialize it on host
            loss = total + self.l2 * jnp.mean(X * X)
            X, optX = apply_X(X, gX, optX)
            if verbose and (ep + 1) % 50 == 0:
                logger.info("tcmf recon %d (streamed): %.5f", ep + 1,
                            float(loss))
        self.F, self.X = F, X
        if self.collect_memory_stats:
            self.peak_device_elems = int(peak)
        return float(loss)

    # ------------------------------------------------------------------

    def predict(self, horizon: int = 24) -> np.ndarray:
        """Roll the basis forward `horizon` steps; return [n, horizon]."""
        if self.F is None:
            raise RuntimeError("fit first")
        w, k = self.window, self.rank

        def roll(carry, _):
            window = carry                                # [w, k]
            nxt = self._tcn.apply({"params": self._tcn_params},
                                  window[None])[0, -1]    # [k]
            return jnp.concatenate([window[1:], nxt[None]]), nxt

        x_last = jnp.asarray(self.X).T[-w:]               # [w, k]
        _, xs = jax.lax.scan(roll, x_last, None, length=horizon)
        # host-side matmul keeps the streamed path's F off-device (block
        # it if n*horizon ever matters; the output is host numpy anyway)
        return np.asarray(self.F) @ np.asarray(xs).T      # [n, horizon]

    def evaluate(self, y_true: np.ndarray,
                 metrics=("mse",)) -> Dict[str, float]:
        pred = self.predict(y_true.shape[1])
        out = {}
        for m in metrics:
            if m == "mse":
                out[m] = float(np.mean((pred - y_true) ** 2))
            elif m == "mae":
                out[m] = float(np.mean(np.abs(pred - y_true)))
            elif m == "smape":
                out[m] = float(np.mean(
                    2 * np.abs(pred - y_true)
                    / (np.abs(pred) + np.abs(y_true) + 1e-8)))
            else:
                raise ValueError(f"unknown metric {m}")
        return out

    # ------------------------------------------------------------------

    def save(self, path: str):
        import os
        import pickle

        os.makedirs(path, exist_ok=True)
        blob = {"cfg": (self.rank, self.window, self.l2, self.tcn_channels,
                        self.lr, self.seed, self.series_block),
                "F": np.asarray(self.F), "X": np.asarray(self.X),
                "tcn_params": jax.tree.map(np.asarray, self._tcn_params)}
        with open(os.path.join(path, "tcmf.pkl"), "wb") as f:
            pickle.dump(blob, f)

    @staticmethod
    def load(path: str) -> "TCMFForecaster":
        import os
        import pickle

        from analytics_zoo_tpu.models.forecast import TCN

        with open(os.path.join(path, "tcmf.pkl"), "rb") as f:
            blob = pickle.load(f)
        cfg = blob["cfg"]
        sb = cfg[6] if len(cfg) > 6 else None   # pre-streaming blobs
        rank, window, l2, chans, lr, seed = cfg[:6]
        fc = TCMFForecaster(rank=rank, window=window, l2=l2,
                            tcn_channels=chans, lr=lr, seed=seed,
                            series_block=sb)
        # F stays HOST-side: predict matmuls it in numpy, and pushing an
        # AdServer-scale [n, k] to device on load would defeat the
        # streamed path's memory contract
        fc.F = np.asarray(blob["F"])
        fc.X = jnp.asarray(blob["X"])
        fc._tcn = TCN(output_dim=rank, horizon=1, dropout=0.0,
                      channels=chans)
        fc._tcn_params = jax.tree.map(jnp.asarray, blob["tcn_params"])
        return fc

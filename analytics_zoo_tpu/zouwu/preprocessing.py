"""Time-series preprocessing — rolling windows, scalers, datetime features.

Reference surface (SURVEY.md §2.5, §3.6; ref: pyzoo/zoo/automl/feature/
time_sequence.py ``TimeSequenceFeatureTransformer`` + zouwu/preprocessing/):
sliding-window (x, y) generation from a timestamped DataFrame, standard/
minmax scaling with inverse for post-prediction un-scaling, and calendar
feature extraction.

Host-side numpy (data prep is IO/CPU work; the TPU sees ready windows).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


def roll(data: np.ndarray, lookback: int, horizon: int = 1,
         target_cols: Optional[Sequence[int]] = None
         ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """Sliding windows over [T, F] (or [T]) series.

    Returns x [N, lookback, F], y [N, horizon, D] where D indexes
    ``target_cols`` (default: all features).  ``horizon=0`` means
    inference windows: x may extend to the very end of the series (the
    last window's forecast is the true future) and y is None.
    """
    data = np.asarray(data, np.float32)
    if data.ndim == 1:
        data = data[:, None]
    T, F = data.shape
    n = T - lookback - horizon + 1
    if n <= 0:
        raise ValueError(
            f"series length {T} < lookback {lookback} + horizon {horizon}")
    idx = np.arange(lookback)[None, :] + np.arange(n)[:, None]
    x = data[idx]
    if horizon == 0:
        return x, None
    yidx = np.arange(horizon)[None, :] + np.arange(n)[:, None] + lookback
    y = data[yidx]
    if target_cols is not None:
        y = y[:, :, list(target_cols)]
    return x, y


def train_val_test_split(data: np.ndarray, val_ratio: float = 0.1,
                         test_ratio: float = 0.1):
    """Chronological split (shuffling leaks the future into training)."""
    n = len(data)
    n_test = int(n * test_ratio)
    n_val = int(n * val_ratio)
    n_train = n - n_val - n_test
    return data[:n_train], data[n_train:n_train + n_val], \
        data[n_train + n_val:]


class StandardScaler:
    """fit/transform/inverse_transform over the feature axis."""

    def fit(self, data: np.ndarray) -> "StandardScaler":
        d = np.asarray(data, np.float64)
        self.mean_ = d.mean(axis=0)
        self.scale_ = np.maximum(d.std(axis=0), 1e-8)
        return self

    def transform(self, data):
        return ((np.asarray(data) - self.mean_) / self.scale_).astype(
            np.float32)

    def fit_transform(self, data):
        return self.fit(data).transform(data)

    def inverse_transform(self, data, target_cols=None):
        mean, scale = self.mean_, self.scale_
        if target_cols is not None:
            mean, scale = mean[list(target_cols)], scale[list(target_cols)]
        return np.asarray(data) * scale + mean


class MinMaxScaler:
    def fit(self, data) -> "MinMaxScaler":
        d = np.asarray(data, np.float64)
        self.min_ = d.min(axis=0)
        self.range_ = np.maximum(d.max(axis=0) - self.min_, 1e-8)
        return self

    def transform(self, data):
        return ((np.asarray(data) - self.min_) / self.range_).astype(
            np.float32)

    def fit_transform(self, data):
        return self.fit(data).transform(data)

    def inverse_transform(self, data, target_cols=None):
        mn, rg = self.min_, self.range_
        if target_cols is not None:
            mn, rg = mn[list(target_cols)], rg[list(target_cols)]
        return np.asarray(data) * rg + mn


_DT_FEATURES = ("hour", "dayofweek", "day", "month", "is_weekend")


def datetime_features(index, features: Sequence[str] = _DT_FEATURES
                      ) -> np.ndarray:
    """Calendar features from a pandas DatetimeIndex/Series → [T, len]."""
    import pandas as pd

    idx = pd.DatetimeIndex(index)
    cols: List[np.ndarray] = []
    for f in features:
        if f == "is_weekend":
            cols.append((idx.dayofweek >= 5).astype(np.float32))
        else:
            cols.append(getattr(idx, f).to_numpy().astype(np.float32))
    return np.stack(cols, axis=1)


class TimeSequenceFeatureTransformer:
    """ref-parity: fit_transform(df) -> (x, y) windows with scaling +
    calendar features; ``inverse`` un-scales predictions.

    Args:
      dt_col / target_col / extra_feature_cols: DataFrame columns.
      lookback / horizon: window sizes.
    """

    def __init__(self, dt_col: str = "datetime", target_col: str = "value",
                 extra_feature_cols: Sequence[str] = (),
                 lookback: int = 24, horizon: int = 1,
                 with_datetime_features: bool = True,
                 scaler: Optional[object] = None):
        self.dt_col = dt_col
        self.target_col = target_col
        self.extra = tuple(extra_feature_cols)
        self.lookback = lookback
        self.horizon = horizon
        self.with_dt = with_datetime_features
        self.scaler = scaler if scaler is not None else StandardScaler()
        self._fitted = False

    def _matrix(self, df) -> np.ndarray:
        cols = [np.asarray(df[self.target_col], np.float32)[:, None]]
        for c in self.extra:
            cols.append(np.asarray(df[c], np.float32)[:, None])
        if self.with_dt and self.dt_col in df:
            cols.append(datetime_features(df[self.dt_col]))
        return np.concatenate(cols, axis=1)

    def fit_transform(self, df) -> Tuple[np.ndarray, np.ndarray]:
        mat = self._matrix(df)
        mat = self.scaler.fit_transform(mat)
        self._fitted = True
        return roll(mat, self.lookback, self.horizon, target_cols=[0])

    def transform(self, df, with_y: bool = True):
        """with_y=True: training windows (x, y).  with_y=False: inference
        windows — x reaches the END of the series, so the last row's
        prediction is the true next-``horizon`` forecast."""
        if not self._fitted:
            raise RuntimeError("fit_transform first")
        mat = self.scaler.transform(self._matrix(df))
        if not with_y:
            x, _ = roll(mat, self.lookback, 0)
            return x
        return roll(mat, self.lookback, self.horizon, target_cols=[0])

    def inverse(self, y_scaled: np.ndarray) -> np.ndarray:
        """Un-scale model outputs back to target units."""
        return self.scaler.inverse_transform(y_scaled, target_cols=[0])

    def state(self) -> Dict:
        return {"dt_col": self.dt_col, "target_col": self.target_col,
                "extra": self.extra, "lookback": self.lookback,
                "horizon": self.horizon, "with_dt": self.with_dt,
                "scaler_cls": type(self.scaler).__name__,
                "scaler_state": {k: v.tolist() for k, v in
                                 vars(self.scaler).items()}}

    @staticmethod
    def from_state(s: Dict) -> "TimeSequenceFeatureTransformer":
        t = TimeSequenceFeatureTransformer(
            dt_col=s["dt_col"], target_col=s["target_col"],
            extra_feature_cols=s["extra"], lookback=s["lookback"],
            horizon=s["horizon"], with_datetime_features=s["with_dt"],
            scaler={"StandardScaler": StandardScaler,
                    "MinMaxScaler": MinMaxScaler}[s["scaler_cls"]]())
        for k, v in s["scaler_state"].items():
            setattr(t.scaler, k, np.asarray(v))
        t._fitted = True
        return t

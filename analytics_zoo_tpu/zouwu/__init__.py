"""Zouwu — time-series toolkit (SURVEY.md §2.5: forecasters + AutoTS;
ref: pyzoo/zoo/zouwu/)."""

from analytics_zoo_tpu.zouwu.forecaster import (
    Forecaster, LSTMForecaster, MTNetForecaster, Seq2SeqForecaster,
    TCNForecaster)
from analytics_zoo_tpu.zouwu.preprocessing import (
    MinMaxScaler, StandardScaler, TimeSequenceFeatureTransformer,
    datetime_features, roll, train_val_test_split)
from analytics_zoo_tpu.zouwu.autots import AutoTSTrainer, TSPipeline
from analytics_zoo_tpu.zouwu.tcmf import TCMFForecaster

__all__ = [
    "Forecaster", "LSTMForecaster", "TCNForecaster", "MTNetForecaster",
    "Seq2SeqForecaster", "TCMFForecaster",
    "roll", "train_val_test_split", "StandardScaler", "MinMaxScaler",
    "datetime_features", "TimeSequenceFeatureTransformer",
    "AutoTSTrainer", "TSPipeline",
]

"""Zouwu forecasters — user-facing time-series models.

Reference surface (SURVEY.md §2.5; ref: pyzoo/zoo/zouwu/model/forecast.py —
``LSTMForecaster``, ``MTNetForecaster``, ``TCNForecaster``,
``Seq2SeqForecaster``; each wraps a Keras/TF net with fit/predict/evaluate
and is also usable as an AutoTS model builder).

Each forecaster wraps a flax net from ``models/forecast.py`` in a
``FlaxEstimator``; x is [N, lookback, F], y is [N, horizon, D] (a [N, D]
or [N] y is auto-expanded). ``evaluate`` reports the reference metric set
(mse/mae/smape/rmse).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np
import optax

from analytics_zoo_tpu.learn.estimator import FlaxEstimator
from analytics_zoo_tpu.models.forecast import (
    LSTMNet, MTNet, Seq2SeqTS, TCN)


def _metric_fns():
    return {
        "mse": lambda y, p: float(np.mean((y - p) ** 2)),
        "rmse": lambda y, p: float(np.sqrt(np.mean((y - p) ** 2))),
        "mae": lambda y, p: float(np.mean(np.abs(y - p))),
        "smape": lambda y, p: float(100 * np.mean(
            2 * np.abs(p - y) / np.maximum(np.abs(y) + np.abs(p), 1e-8))),
    }


class Forecaster:
    """Base: subclasses set ``self.model`` (a flax module) before super().

    ref-parity methods: fit(x, y) / predict(x) / evaluate(x, y, metrics) /
    save(path) / restore(path).
    """

    def __init__(self, model, lr: float = 1e-3, loss: str = "mse",
                 metric: str = "mse"):
        self.model = model
        self.metric = metric
        self.estimator = FlaxEstimator(
            model, loss, optax.adam(lr), feature_cols=("x",),
            label_cols=("y",))

    @staticmethod
    def _shape_y(y: np.ndarray, horizon: int) -> np.ndarray:
        y = np.asarray(y, np.float32)
        if y.ndim == 1:
            y = y[:, None]
        if y.ndim == 2:  # [N, D] -> [N, horizon(=1), D]
            y = y[:, None, :] if horizon == 1 else y[:, :, None]
        return y

    @property
    def _horizon(self) -> int:
        return int(getattr(self.model, "horizon", 1))

    def fit(self, x, y, validation_data=None, epochs: int = 1,
            batch_size: int = 32) -> Dict[str, float]:
        data = {"x": np.asarray(x, np.float32),
                "y": self._shape_y(y, self._horizon)}
        val = None
        if validation_data is not None:
            vx, vy = validation_data
            val = {"x": np.asarray(vx, np.float32),
                   "y": self._shape_y(vy, self._horizon)}
        hist = self.estimator.fit(data, epochs=epochs,
                                  batch_size=batch_size,
                                  validation_data=val)
        return hist[-1]

    def predict(self, x, batch_size: int = 128) -> np.ndarray:
        return self.estimator.predict({"x": np.asarray(x, np.float32)},
                                      batch_size=batch_size)

    def evaluate(self, x, y, metrics: Sequence[str] = ("mse",),
                 batch_size: int = 128) -> Dict[str, float]:
        preds = self.predict(x, batch_size)
        y = self._shape_y(y, self._horizon)
        fns = _metric_fns()
        return {m: fns[m](y, preds) for m in metrics}

    def save(self, path: str):
        self.estimator.save(path)

    def restore(self, path: str, sample_x: Optional[np.ndarray] = None):
        sample = None if sample_x is None else \
            {"x": np.asarray(sample_x, np.float32)}
        self.estimator.load(path, sample_data=sample)

    load = restore


class LSTMForecaster(Forecaster):
    """ref-parity ctor: target_dim, feature_dim, lstm_units, dropouts,
    lr, loss."""

    def __init__(self, target_dim: int = 1, feature_dim: int = 1,
                 lstm_units: Sequence[int] = (16, 8),
                 dropouts: Sequence[float] = (0.2, 0.2),
                 horizon: int = 1, lr: float = 1e-3, loss: str = "mse"):
        self.feature_dim = feature_dim
        super().__init__(
            LSTMNet(output_dim=target_dim, horizon=horizon,
                    hidden_sizes=tuple(lstm_units),
                    dropouts=tuple(dropouts)), lr=lr, loss=loss)


class TCNForecaster(Forecaster):
    """ref-parity ctor: target_dim, feature_dim, channels, kernel_size."""

    def __init__(self, target_dim: int = 1, feature_dim: int = 1,
                 channels: Sequence[int] = (32, 32, 32),
                 kernel_size: int = 3, dropout: float = 0.1,
                 horizon: int = 1, lr: float = 1e-3, loss: str = "mse"):
        self.feature_dim = feature_dim
        super().__init__(
            TCN(output_dim=target_dim, horizon=horizon,
                channels=tuple(channels), kernel_size=kernel_size,
                dropout=dropout), lr=lr, loss=loss)


class MTNetForecaster(Forecaster):
    """ref-parity ctor: target_dim, feature_dim, long_series_num,
    series_length, ar_window_size, cnn_hid_size.

    Input x must be [N, (long_series_num+1)*series_length, F].
    """

    def __init__(self, target_dim: int = 1, feature_dim: int = 1,
                 long_series_num: int = 4, series_length: int = 8,
                 ar_window_size: int = 4, cnn_hid_size: int = 32,
                 rnn_hid_size: int = 32, horizon: int = 1,
                 lr: float = 1e-3, loss: str = "mse"):
        self.feature_dim = feature_dim
        super().__init__(
            MTNet(output_dim=target_dim, horizon=horizon,
                  long_num=long_series_num, series_length=series_length,
                  ar_window=ar_window_size, cnn_filters=cnn_hid_size,
                  rnn_hidden=rnn_hid_size), lr=lr, loss=loss)


class Seq2SeqForecaster(Forecaster):
    """ref-parity ctor: target_dim, feature_dim, lstm_hidden_dim,
    lstm_layer_num, future_seq_len."""

    def __init__(self, target_dim: int = 1, feature_dim: int = 1,
                 lstm_hidden_dim: int = 64, lstm_layer_num: int = 1,
                 future_seq_len: int = 1, lr: float = 1e-3,
                 loss: str = "mse"):
        self.feature_dim = feature_dim
        super().__init__(
            Seq2SeqTS(output_dim=target_dim, horizon=future_seq_len,
                      hidden_size=lstm_hidden_dim,
                      num_layers=lstm_layer_num), lr=lr, loss=loss)

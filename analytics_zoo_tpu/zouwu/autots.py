"""AutoTS — automated time-series pipeline (search + deployable bundle).

Reference surface (SURVEY.md §2.5, §3.6; ref: pyzoo/zoo/zouwu/autots/
forecast.py — ``AutoTSTrainer.fit(train_df, val_df)`` running Ray-Tune
trials of (feature transform + model fit_eval), returning a ``TSPipeline``
with fit/evaluate/predict/save/load).

TPU re-design: trials run through ``automl.SearchEngine`` on-host (one chip
time-shared); a trial = build forecaster from config → short fit →
validation metric. The winning (transformer, forecaster, config) bundle is
a ``TSPipeline`` persisted as JSON + orbax params.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional, Sequence

import numpy as np

from analytics_zoo_tpu.automl import hp
from analytics_zoo_tpu.automl.search import MedianStopper, SearchEngine
from analytics_zoo_tpu.common.log import logger
from analytics_zoo_tpu.zouwu.forecaster import (
    LSTMForecaster, Seq2SeqForecaster, TCNForecaster, _metric_fns)
from analytics_zoo_tpu.zouwu.preprocessing import (
    TimeSequenceFeatureTransformer)

_MODEL_BUILDERS = {
    "lstm": lambda cfg, horizon: LSTMForecaster(
        horizon=horizon,
        lstm_units=(int(cfg.get("units", 16)),) * int(cfg.get("layers", 2)),
        dropouts=(float(cfg.get("dropout", 0.2)),) * int(
            cfg.get("layers", 2)),
        lr=float(cfg.get("lr", 1e-3))),
    "tcn": lambda cfg, horizon: TCNForecaster(
        horizon=horizon,
        channels=(int(cfg.get("units", 32)),) * int(cfg.get("layers", 3)),
        kernel_size=int(cfg.get("kernel_size", 3)),
        dropout=float(cfg.get("dropout", 0.1)),
        lr=float(cfg.get("lr", 1e-3))),
    "seq2seq": lambda cfg, horizon: Seq2SeqForecaster(
        future_seq_len=horizon,
        lstm_hidden_dim=int(cfg.get("units", 32)),
        lstm_layer_num=int(cfg.get("layers", 1)),
        lr=float(cfg.get("lr", 1e-3))),
}

_DEFAULT_SPACE = {
    "model": hp.choice(["tcn", "lstm"]),
    "units": hp.choice([16, 32, 64]),
    "layers": hp.choice([1, 2, 3]),
    "lr": hp.loguniform(1e-4, 1e-2),
    "dropout": hp.uniform(0.0, 0.3),
    "batch_size": hp.choice([32, 64]),
}


class TSPipeline:
    """Deployable bundle: feature transformer + trained forecaster."""

    def __init__(self, transformer: TimeSequenceFeatureTransformer,
                 forecaster, config: Dict):
        self.transformer = transformer
        self.forecaster = forecaster
        self.config = dict(config)

    # ---- inference / continued training ------------------------------

    def predict(self, df, batch_size: int = 128) -> np.ndarray:
        """Forecasts in ORIGINAL units, one row per input window."""
        x = self.transformer.transform(df, with_y=False)
        preds = self.forecaster.predict(x, batch_size=batch_size)
        return self.transformer.inverse(preds[..., 0])

    def evaluate(self, df, metrics: Sequence[str] = ("mse",),
                 batch_size: int = 128) -> Dict[str, float]:
        x, y = self.transformer.transform(df, with_y=True)
        preds = self.forecaster.predict(x, batch_size=batch_size)
        y_true = self.transformer.inverse(y[..., 0])
        y_pred = self.transformer.inverse(preds[..., 0])
        fns = _metric_fns()
        return {m: fns[m](y_true, y_pred) for m in metrics}

    def fit(self, df, epochs: int = 1, batch_size: int = 32):
        """Incremental fit on new data (ref: TSPipeline.fit)."""
        x, y = self.transformer.transform(df, with_y=True)
        return self.forecaster.fit(x, y, epochs=epochs,
                                   batch_size=batch_size)

    # ---- persistence -------------------------------------------------

    def save(self, path: str):
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "pipeline.json"), "w") as f:
            json.dump({"config": self.config,
                       "transformer": self.transformer.state()}, f)
        self.forecaster.save(os.path.join(path, "model"))

    @staticmethod
    def load(path: str) -> "TSPipeline":
        with open(os.path.join(path, "pipeline.json")) as f:
            meta = json.load(f)
        cfg = meta["config"]
        transformer = TimeSequenceFeatureTransformer.from_state(
            meta["transformer"])
        builder = _MODEL_BUILDERS[cfg.get("model", "tcn")]
        forecaster = builder(cfg, transformer.horizon)
        n_feat = 1 + len(transformer.extra) + (5 if transformer.with_dt
                                               else 0)
        sample = np.zeros((2, transformer.lookback, n_feat), np.float32)
        forecaster.restore(os.path.join(path, "model"), sample_x=sample)
        return TSPipeline(transformer, forecaster, cfg)


class AutoTSTrainer:
    """ref-parity ctor: dt_col, target_col, horizon, extra_features_col."""

    def __init__(self, dt_col: str = "datetime", target_col: str = "value",
                 horizon: int = 1, extra_features_col: Sequence[str] = (),
                 lookback: int = 24,
                 search_space: Optional[Dict] = None):
        self.dt_col = dt_col
        self.target_col = target_col
        self.horizon = horizon
        self.extra = tuple(extra_features_col)
        self.lookback = lookback
        self.space = search_space or dict(_DEFAULT_SPACE)

    def fit(self, train_df, validation_df=None, *, n_sampling: int = 6,
            epochs: int = 2, metric: str = "mse", seed: int = 0,
            distributed: bool = False) -> TSPipeline:
        transformer = TimeSequenceFeatureTransformer(
            dt_col=self.dt_col, target_col=self.target_col,
            extra_feature_cols=self.extra, lookback=self.lookback,
            horizon=self.horizon)
        x, y = transformer.fit_transform(train_df)
        if validation_df is not None:
            vx, vy = transformer.transform(validation_df)
        else:
            n_val = max(1, len(x) // 5)
            x, vx = x[:-n_val], x[-n_val:]
            y, vy = y[:-n_val], y[-n_val:]

        def trainable(config: Dict, report):
            model_name = config.get("model", "tcn")
            forecaster = _MODEL_BUILDERS[model_name](config, self.horizon)
            bs = int(config.get("batch_size", 32))
            last = {}
            for ep in range(epochs):
                forecaster.fit(x, y, epochs=1, batch_size=bs)
                last = forecaster.evaluate(vx, vy, metrics=(metric,))
                report(ep, last[metric])
            trainable._last = (forecaster, config)
            return last

        engine = SearchEngine(trainable, self.space, metric=metric,
                              mode="min", n_sampling=n_sampling, seed=seed,
                              scheduler=MedianStopper(),
                              distributed=distributed)
        best = engine.run()
        logger.info("AutoTS best config=%s %s=%.5f", best.config,
                    metric, best.metric)
        # reuse the winner's trained forecaster if it was the last trial
        # run; otherwise retrain it (later trials overwrote the stash).
        # Distributed mode never reuses the stash: only the winning
        # process holds it (local-mesh-trained), and every process must
        # enter the global-mesh retrain together or the reusing process
        # deadlocks its peers' collectives.
        forecaster, cfg = getattr(trainable, "_last", (None, None))
        if distributed and SearchEngine._nprocs() > 1:
            cfg = None
        if cfg is not best.config:
            forecaster = _MODEL_BUILDERS[best.config.get("model", "tcn")](
                best.config, self.horizon)
            forecaster.fit(x, y, epochs=epochs,
                           batch_size=int(best.config.get("batch_size",
                                                          32)))
        return TSPipeline(transformer, forecaster, best.config)

"""Hyper-parameter search-space primitives.

Reference surface (SURVEY.md §2.5; ref: pyzoo/zoo/orca/automl/hp.py — thin
wrappers over ray.tune sample functions: ``hp.choice``, ``hp.uniform``,
``hp.quniform``, ``hp.loguniform``, ``hp.randint``, ``hp.grid_search``).

Here the samplers are plain objects with a ``sample(rng)`` method — no Ray.
A search space is a (possibly nested) dict whose leaf samplers are resolved
per trial by ``sample_config``; ``grid_search`` leaves enumerate instead.
"""

from __future__ import annotations

import itertools
import math
from typing import Any, Dict, List, Sequence

import numpy as np


class Sampler:
    def sample(self, rng: np.random.Generator):
        raise NotImplementedError


class choice(Sampler):
    def __init__(self, options: Sequence[Any]):
        self.options = list(options)

    def sample(self, rng):
        return self.options[int(rng.integers(len(self.options)))]


class uniform(Sampler):
    def __init__(self, lower: float, upper: float):
        self.lower, self.upper = float(lower), float(upper)

    def sample(self, rng):
        return float(rng.uniform(self.lower, self.upper))


class quniform(Sampler):
    def __init__(self, lower: float, upper: float, q: float = 1.0):
        self.lower, self.upper, self.q = float(lower), float(upper), float(q)

    def sample(self, rng):
        v = rng.uniform(self.lower, self.upper)
        return float(np.clip(round(v / self.q) * self.q,
                             self.lower, self.upper))


class loguniform(Sampler):
    def __init__(self, lower: float, upper: float):
        self.lower, self.upper = float(lower), float(upper)

    def sample(self, rng):
        return float(math.exp(rng.uniform(math.log(self.lower),
                                          math.log(self.upper))))


class randint(Sampler):
    """Uniform integer in [lower, upper) — ray.tune semantics."""

    def __init__(self, lower: int, upper: int):
        self.lower, self.upper = int(lower), int(upper)

    def sample(self, rng):
        return int(rng.integers(self.lower, self.upper))


class grid_search:
    """Exhaustive leaf: every value appears in the trial grid."""

    def __init__(self, values: Sequence[Any]):
        self.values = list(values)


def _walk(space: Dict, prefix=()):
    for k, v in space.items():
        if isinstance(v, dict):
            yield from _walk(v, prefix + (k,))
        else:
            yield prefix + (k,), v


def _set(cfg: Dict, path, value):
    for k in path[:-1]:
        cfg = cfg.setdefault(k, {})
    cfg[path[-1]] = value


def sample_config(space: Dict, rng: np.random.Generator) -> Dict:
    """One concrete config: samplers sampled, grid leaves ignored here."""
    cfg: Dict = {}
    for path, v in _walk(space):
        if isinstance(v, Sampler):
            _set(cfg, path, v.sample(rng))
        elif isinstance(v, grid_search):
            continue
        else:
            _set(cfg, path, v)
    return cfg


def grid_configs(space: Dict) -> List[Dict]:
    """Cartesian product over all grid_search leaves (non-grid samplers are
    sampled later per trial; constants pass through). Returns [{}] when the
    space has no grid leaves."""
    grids = [(p, v.values) for p, v in _walk(space)
             if isinstance(v, grid_search)]
    if not grids:
        return [{}]
    out = []
    for combo in itertools.product(*[vals for _, vals in grids]):
        cfg: Dict = {}
        for (path, _), val in zip(grids, combo):
            _set(cfg, path, val)
        out.append(cfg)
    return out


def _merge(base: Dict, over: Dict) -> Dict:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _merge(out[k], v)
        else:
            out[k] = v
    return out

"""AutoEstimator — HPO-driven Estimator construction.

Reference surface (SURVEY.md §2.5; ref: pyzoo/zoo/orca/automl/
auto_estimator.py — ``AutoEstimator.from_torch/from_keras(model_creator)``
→ ``.fit(data, search_space, n_sampling, metric)`` over Ray Tune →
``get_best_model()`` / ``get_best_config()``).

Each trial builds a fresh ``FlaxEstimator`` from ``model_creator(config)``,
trains on the (shared, host-resident) data, evaluates on validation data,
and reports the metric; the engine handles sampling/pruning. Trials run
sequentially on the chip — XLA's compile cache makes same-shape trials
cheap after the first.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import optax

from analytics_zoo_tpu.automl.search import MedianStopper, SearchEngine
from analytics_zoo_tpu.learn.estimator import Estimator, FlaxEstimator


def _default_optimizer_creator(config: Dict):
    return optax.adam(float(config.get("lr", 1e-3)))


class AutoEstimator:
    def __init__(self, model_creator: Callable[[Dict], Any], *,
                 loss: Any = "mse",
                 optimizer_creator: Callable[[Dict], Any] = None,
                 feature_cols=("x",), label_cols=("y",),
                 metrics=(), name: str = "auto_estimator"):
        self.model_creator = model_creator
        self.loss = loss
        self.optimizer_creator = optimizer_creator or \
            _default_optimizer_creator
        self.feature_cols = tuple(feature_cols)
        self.label_cols = tuple(label_cols)
        self.metrics = metrics
        self.name = name
        self.best_estimator: Optional[FlaxEstimator] = None
        self.best_config: Optional[Dict] = None
        self.best_trial = None

    @staticmethod
    def from_flax(model_creator, **kw) -> "AutoEstimator":
        return AutoEstimator(model_creator, **kw)

    # reference entry-point names
    from_keras = from_flax
    from_torch = from_flax

    def _build(self, config: Dict) -> FlaxEstimator:
        return Estimator.from_flax(
            model=self.model_creator(config), loss=self.loss,
            optimizer=self.optimizer_creator(config),
            feature_cols=self.feature_cols, label_cols=self.label_cols,
            metrics=self.metrics)

    def fit(self, data, validation_data=None, *, search_space: Dict,
            n_sampling: int = 4, epochs: int = 1, metric: str = "loss",
            mode: str = "min", batch_size: int = 32,
            early_stop: bool = True, seed: int = 0,
            distributed: bool = False) -> "AutoEstimator":
        """Search, then retain the best estimator (already trained).

        ``batch_size``/``epochs`` may also live in the search space under
        the same names; config values win.
        """
        val = validation_data if validation_data is not None else data

        def trainable(config: Dict, report):
            est = self._build(config)
            bs = int(config.get("batch_size", batch_size))
            n_ep = int(config.get("epochs", epochs))
            for ep in range(n_ep):
                est.fit(data, epochs=1, batch_size=bs)
                stats = est.evaluate(val, batch_size=bs)
                report(ep, float(stats[metric]))
            stats = est.evaluate(val, batch_size=bs)
            # stash so the winning trial's estimator can be retained
            trainable._last = (est, config)
            return {k: float(v) for k, v in stats.items()}

        scheduler = MedianStopper(mode=mode) if early_stop else None
        engine = SearchEngine(trainable, search_space, metric=metric,
                              mode=mode, n_sampling=n_sampling, seed=seed,
                              scheduler=scheduler, distributed=distributed)
        best = engine.run()
        self.best_trial = best
        self.best_config = best.config
        # retrain the winner if its estimator isn't the last one stashed
        # (later trials overwrote the stash).  Distributed mode NEVER
        # reuses the stash: only the process that ran the winning trial
        # holds it (trained on its local mesh), and all processes must
        # enter the global-mesh retrain fit together or the reusing
        # process deadlocks its peers' collectives.
        est, cfg = getattr(trainable, "_last", (None, None))
        if distributed and SearchEngine._nprocs() > 1:
            cfg = None
        if cfg is not best.config:
            est = self._build(best.config)
            est.fit(data, epochs=int(best.config.get("epochs", epochs)),
                    batch_size=int(best.config.get("batch_size",
                                                   batch_size)))
        self.best_estimator = est
        return self

    def get_best_model(self):
        if self.best_estimator is None:
            raise RuntimeError("call fit first")
        return self.best_estimator

    def get_best_config(self) -> Dict:
        if self.best_config is None:
            raise RuntimeError("call fit first")
        return self.best_config

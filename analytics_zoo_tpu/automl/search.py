"""Trial search engine — the Ray-Tune replacement.

Reference surface (SURVEY.md §2.5, §3.6; ref: pyzoo/zoo/automl/search/
RayTuneSearchEngine — ``tune.run(trainable)`` over Ray trial actors, plus
zoo.orca.automl's ``AutoEstimator`` driving it).

TPU-native re-design: trials are *processes on the host*, not cluster
actors — a TPU chip is time-shared, so the engine runs trials sequentially
by default (each trial owns the chip; XLA compilation caches across trials)
with an optional thread pool for CPU-bound trainables. Median-stopping
early termination replaces Tune's schedulers.

A trainable is ``fn(config) -> float | dict`` (reported metric[s]), or an
iterator protocol via ``report`` callback for per-epoch metrics.
"""

from __future__ import annotations

import dataclasses
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from analytics_zoo_tpu.automl import hp as hp_mod
from analytics_zoo_tpu.common.log import logger


@dataclasses.dataclass
class Trial:
    trial_id: int
    config: Dict
    metric: Optional[float] = None
    metrics: Dict[str, Any] = dataclasses.field(default_factory=dict)
    status: str = "pending"   # pending | running | done | error | pruned
    error: Optional[str] = None
    duration_s: float = 0.0
    history: List[float] = dataclasses.field(default_factory=list)


class MedianStopper:
    """Prune a trial whose intermediate metric is worse than the running
    median of completed metrics at the same epoch (Tune scheduler analog)."""

    def __init__(self, mode: str = "min", grace_epochs: int = 1):
        self.mode = mode
        self.grace = grace_epochs
        self._per_epoch: Dict[int, List[float]] = {}

    def record(self, epoch: int, value: float):
        self._per_epoch.setdefault(epoch, []).append(value)

    def should_stop(self, epoch: int, value: float) -> bool:
        if epoch < self.grace:
            return False
        seen = self._per_epoch.get(epoch, [])
        if len(seen) < 3:
            return False
        med = float(np.median(seen))
        return value > med if self.mode == "min" else value < med


class SearchEngine:
    """ref-parity: SearchEngine.run(trainable) -> best trial.

    Args:
      trainable: ``fn(config, report) -> float|dict`` — ``report(epoch,
        value)`` enables median-stopping (raise ``StopTrial`` is internal).
      search_space: dict of constants / hp samplers / hp.grid_search.
      metric: key to optimise when the trainable returns a dict.
      mode: "min" | "max".
      n_sampling: random samples drawn ON TOP of each grid combination.
    """

    def __init__(self, trainable: Callable, search_space: Dict,
                 metric: str = "loss", mode: str = "min",
                 n_sampling: int = 1, seed: int = 0,
                 max_concurrent: int = 1,
                 scheduler: Optional[MedianStopper] = None):
        self.trainable = trainable
        self.space = search_space
        self.metric = metric
        self.mode = mode
        self.n_sampling = max(1, n_sampling)
        self.seed = seed
        self.max_concurrent = max(1, max_concurrent)
        self.scheduler = scheduler
        self.trials: List[Trial] = []

    class StopTrial(Exception):
        pass

    def _configs(self) -> List[Dict]:
        rng = np.random.default_rng(self.seed)
        out = []
        for grid_cfg in hp_mod.grid_configs(self.space):
            for _ in range(self.n_sampling):
                cfg = hp_mod.sample_config(self.space, rng)
                out.append(hp_mod._merge(cfg, grid_cfg))
        return out

    def _run_one(self, trial: Trial):
        trial.status = "running"
        t0 = time.perf_counter()

        def report(epoch: int, value: float):
            trial.history.append(float(value))
            if self.scheduler is not None:
                self.scheduler.record(epoch, float(value))
                if self.scheduler.should_stop(epoch, float(value)):
                    raise SearchEngine.StopTrial()

        try:
            result = self.trainable(trial.config, report)
            if isinstance(result, dict):
                trial.metrics = result
                trial.metric = float(result[self.metric])
            else:
                trial.metric = float(result)
                trial.metrics = {self.metric: trial.metric}
            trial.status = "done"
        except SearchEngine.StopTrial:
            trial.status = "pruned"
            trial.metric = trial.history[-1] if trial.history else None
        except Exception:
            trial.status = "error"
            trial.error = traceback.format_exc()
            logger.warning("trial %d failed:\n%s", trial.trial_id,
                           trial.error)
        trial.duration_s = time.perf_counter() - t0

    def run(self) -> Trial:
        configs = self._configs()
        self.trials = [Trial(i, c) for i, c in enumerate(configs)]
        if self.max_concurrent == 1:
            for t in self.trials:
                self._run_one(t)
                logger.info("trial %d/%d %s %s=%s (%.1fs)", t.trial_id + 1,
                            len(self.trials), t.status, self.metric,
                            t.metric, t.duration_s)
        else:
            with ThreadPoolExecutor(self.max_concurrent) as pool:
                list(pool.map(self._run_one, self.trials))
        return self.best_trial()

    def best_trial(self) -> Trial:
        done = [t for t in self.trials
                if t.status == "done" and t.metric is not None]
        if not done:
            errs = [t.error for t in self.trials if t.error]
            raise RuntimeError(
                "no successful trials" + (f"; first error:\n{errs[0]}"
                                          if errs else ""))
        key = (min if self.mode == "min" else max)
        return key(done, key=lambda t: t.metric)

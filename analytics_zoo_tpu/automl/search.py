"""Trial search engine — the Ray-Tune replacement.

Reference surface (SURVEY.md §2.5, §3.6; ref: pyzoo/zoo/automl/search/
RayTuneSearchEngine — ``tune.run(trainable)`` over Ray trial actors, plus
zoo.orca.automl's ``AutoEstimator`` driving it).

TPU-native re-design: trials are *processes on the host*, not cluster
actors — a TPU chip is time-shared, so the engine runs trials sequentially
by default (each trial owns the chip; XLA compilation caches across trials)
with an optional thread pool for CPU-bound trainables. Median-stopping
early termination replaces Tune's schedulers.

A trainable is ``fn(config) -> float | dict`` (reported metric[s]), or an
iterator protocol via ``report`` callback for per-epoch metrics.
"""

from __future__ import annotations

import dataclasses
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from analytics_zoo_tpu.automl import hp as hp_mod
from analytics_zoo_tpu.common.log import logger


@dataclasses.dataclass
class Trial:
    trial_id: int
    config: Dict
    metric: Optional[float] = None
    metrics: Dict[str, Any] = dataclasses.field(default_factory=dict)
    status: str = "pending"   # pending | running | done | error | pruned
    error: Optional[str] = None
    duration_s: float = 0.0
    history: List[float] = dataclasses.field(default_factory=list)
    # (epoch, value) pairs as reported — epochs may be arbitrary keys
    # (step counts, non-contiguous); distributed mode exchanges THESE so
    # peer MedianStopper merges land in the right bucket
    reports: List[Any] = dataclasses.field(default_factory=list)


class MedianStopper:
    """Prune a trial whose intermediate metric is worse than the running
    median of completed metrics at the same epoch (Tune scheduler analog)."""

    def __init__(self, mode: str = "min", grace_epochs: int = 1):
        self.mode = mode
        self.grace = grace_epochs
        self._per_epoch: Dict[int, List[float]] = {}

    def record(self, epoch: int, value: float):
        self._per_epoch.setdefault(epoch, []).append(value)

    def should_stop(self, epoch: int, value: float) -> bool:
        if epoch < self.grace:
            return False
        seen = self._per_epoch.get(epoch, [])
        if len(seen) < 3:
            return False
        med = float(np.median(seen))
        return value > med if self.mode == "min" else value < med


class SearchEngine:
    """ref-parity: SearchEngine.run(trainable) -> best trial.

    Args:
      trainable: ``fn(config, report) -> float|dict`` — ``report(epoch,
        value)`` enables median-stopping (raise ``StopTrial`` is internal).
      search_space: dict of constants / hp samplers / hp.grid_search.
      metric: key to optimise when the trainable returns a dict.
      mode: "min" | "max".
      n_sampling: random samples drawn ON TOP of each grid combination.
    """

    def __init__(self, trainable: Callable, search_space: Dict,
                 metric: str = "loss", mode: str = "min",
                 n_sampling: int = 1, seed: int = 0,
                 max_concurrent: int = 1,
                 scheduler: Optional[MedianStopper] = None,
                 distributed: bool = False,
                 history_pad: int = 64):
        self.trainable = trainable
        self.space = search_space
        self.metric = metric
        self.mode = mode
        self.n_sampling = max(1, n_sampling)
        self.seed = seed
        self.max_concurrent = max(1, max_concurrent)
        self.scheduler = scheduler
        self.distributed = distributed
        self.history_pad = history_pad
        self.trials: List[Trial] = []

    class StopTrial(Exception):
        pass

    def _configs(self) -> List[Dict]:
        rng = np.random.default_rng(self.seed)
        out = []
        for grid_cfg in hp_mod.grid_configs(self.space):
            for _ in range(self.n_sampling):
                cfg = hp_mod.sample_config(self.space, rng)
                out.append(hp_mod._merge(cfg, grid_cfg))
        return out

    def _run_one(self, trial: Trial):
        trial.status = "running"
        t0 = time.perf_counter()

        def report(epoch: int, value: float):
            trial.history.append(float(value))
            trial.reports.append((float(epoch), float(value)))
            if self.scheduler is not None:
                self.scheduler.record(epoch, float(value))
                if self.scheduler.should_stop(epoch, float(value)):
                    raise SearchEngine.StopTrial()

        try:
            result = self.trainable(trial.config, report)
            if isinstance(result, dict):
                trial.metrics = result
                trial.metric = float(result[self.metric])
            else:
                trial.metric = float(result)
                trial.metrics = {self.metric: trial.metric}
            trial.status = "done"
        except SearchEngine.StopTrial:
            trial.status = "pruned"
            trial.metric = trial.history[-1] if trial.history else None
        except Exception:
            trial.status = "error"
            trial.error = traceback.format_exc()
            logger.warning("trial %d failed:\n%s", trial.trial_id,
                           trial.error)
        trial.duration_s = time.perf_counter() - t0

    def run(self) -> Trial:
        configs = self._configs()
        self.trials = [Trial(i, c) for i, c in enumerate(configs)]
        if self.distributed and self._nprocs() > 1:
            self._run_distributed()
            return self.best_trial()
        if self.max_concurrent == 1:
            for t in self.trials:
                self._run_one(t)
                logger.info("trial %d/%d %s %s=%s (%.1fs)", t.trial_id + 1,
                            len(self.trials), t.status, self.metric,
                            t.metric, t.duration_s)
        else:
            with ThreadPoolExecutor(self.max_concurrent) as pool:
                list(pool.map(self._run_one, self.trials))
        return self.best_trial()

    # -- cluster-distributed trials (ref: RayTuneSearchEngine ran trials
    # -- as Ray actors across the cluster, SURVEY §3.6) -----------------
    @staticmethod
    def _nprocs() -> int:
        import jax

        return jax.process_count()

    _ST_CODE = {"done": 0.0, "pruned": 1.0, "error": 2.0}
    _CODE_ST = {0: "done", 1: "pruned", 2: "error", 3: "noop"}

    def _run_distributed(self):
        """Round-based SPMD trial schedule over `jax.process_count()`
        processes: every process builds the SAME deterministic trial
        queue (same seed), round r assigns trial ``r*P + pid`` to
        process ``pid``, and one `process_allgather` per round merges
        (status, metric, per-epoch history) so (a) every process ends
        with the full trial table — `best_trial()` agrees everywhere
        with no driver — and (b) the MedianStopper prunes round r+1
        against the merged history of ALL processes' earlier trials,
        not just the local ones.

        The collective is per-round, not per-epoch: processes run their
        trial of a round at full speed and synchronise once, trading
        stopper freshness within a round for zero mid-trial barriers
        (a straggler trial can never deadlock a peer's collective)."""
        import jax
        from jax.experimental import multihost_utils

        P = self._nprocs()
        pid = jax.process_index()
        n = len(self.trials)
        pad = self.history_pad
        # row layout: [status, has_metric, metric, n_reports,
        #              ep0, v0, ep1, v1, ...] — has_metric is a separate
        # flag (NOT NaN-in-band) so a legitimately-NaN metric from a
        # diverged trial survives the exchange as NaN, and reports travel
        # as (epoch, value) PAIRS so arbitrary epoch keys (step counts,
        # non-contiguous) land in the right MedianStopper bucket on peers
        rounds = (n + P - 1) // P
        for r in range(rounds):
            tid = r * P + pid
            mine = self.trials[tid] if tid < n else None
            if mine is not None:
                # trial isolation (the Ray-actor-resources analog): the
                # trainable sees a process-LOCAL mesh and single-host
                # semantics — estimators inside trials must not emit
                # cross-process collectives while peers run different
                # configs at different speeds
                from analytics_zoo_tpu.common.context import (
                    OrcaContext, local_process_scope)

                try:
                    OrcaContext.get_context()
                    scope = local_process_scope()
                except RuntimeError:        # no context: pure-fn trainable
                    import contextlib

                    scope = contextlib.nullcontext()
                with scope:
                    self._run_one(mine)
                logger.info("[proc %d] trial %d/%d %s %s=%s (%.1fs)",
                            pid, tid + 1, n, mine.status, self.metric,
                            mine.metric, mine.duration_s)
            row = np.zeros(4 + 2 * pad, np.float64)
            if mine is None:
                row[0] = 3.0                        # noop pad slot
            else:
                row[0] = self._ST_CODE.get(mine.status, 2.0)
                if mine.metric is not None:
                    row[1], row[2] = 1.0, mine.metric
                if len(mine.reports) > pad:
                    logger.warning(
                        "trial %d reported %d times but history_pad=%d; "
                        "later reports are dropped from the exchange "
                        "(raise SearchEngine(history_pad=...))",
                        mine.trial_id, len(mine.reports), pad)
                row[3] = len(mine.reports)
                for j, (ep, v) in enumerate(mine.reports[:pad]):
                    row[4 + 2 * j], row[5 + 2 * j] = ep, v
            table = np.atleast_2d(np.asarray(
                multihost_utils.process_allgather(row)))
            for q in range(P):
                tid_q, st = r * P + q, int(table[q, 0])
                if st == 3 or tid_q >= n:
                    continue
                t = self.trials[tid_q]
                # own trials too: the gathered row is float32 (x64 off),
                # so adopting it everywhere keeps every process's trial
                # table BIT-identical — best_trial() can never disagree
                # on a tie that local float64 precision would break
                t.status = self._CODE_ST.get(st, "error")
                t.metric = float(table[q, 2]) if table[q, 1] else None
                stored = min(int(table[q, 3]), pad)
                t.reports = [(float(table[q, 4 + 2 * j]),
                              float(table[q, 5 + 2 * j]))
                             for j in range(stored)]
                t.history = [v for _, v in t.reports]
                if q == pid and t.metrics:
                    # the owner keeps its full metrics dict (a dict-
                    # returning trainable may report secondary metrics
                    # the row can't carry) — only the optimised key is
                    # snapped to the exchanged float32 value
                    if t.metric is not None:
                        t.metrics[self.metric] = t.metric
                else:
                    t.metrics = {self.metric: t.metric} \
                        if t.metric is not None else {}
                if self.scheduler is not None and q != pid:
                    # merge the peer's reports (at their TRUE epoch keys)
                    # so the NEXT round's pruning medians see the whole
                    # cluster (own reports were recorded live)
                    for ep, v in t.reports:
                        self.scheduler.record(ep, v)

    def best_trial(self) -> Trial:
        # a diverged trial may legitimately report metric=NaN ('done',
        # but incomparable) — exclude it or min()/max() returns NaN-
        # poisoned garbage depending on trial order
        done = [t for t in self.trials
                if t.status == "done" and t.metric is not None
                and not np.isnan(t.metric)]
        if not done:
            errs = [t.error for t in self.trials if t.error]
            raise RuntimeError(
                "no successful trials" + (f"; first error:\n{errs[0]}"
                                          if errs else ""))
        key = (min if self.mode == "min" else max)
        return key(done, key=lambda t: t.metric)

"""AutoML — hyper-parameter search without Ray (SURVEY.md §2.5:
replaces pyzoo/zoo/automl's RayTuneSearchEngine + orca.automl)."""

from analytics_zoo_tpu.automl import hp
from analytics_zoo_tpu.automl.search import (
    MedianStopper, SearchEngine, Trial)
from analytics_zoo_tpu.automl.auto_estimator import AutoEstimator

__all__ = ["hp", "SearchEngine", "MedianStopper", "Trial", "AutoEstimator"]

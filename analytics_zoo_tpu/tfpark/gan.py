"""GANEstimator — alternating two-optimizer adversarial training.

Reference surface (SURVEY.md §2.3 TFPark row; ref: pyzoo/zoo/tfpark/gan/
gan_estimator.py, modeled on tf.contrib.gan's GANEstimator): user supplies
generator/discriminator model fns, per-network loss fns and optimizers; the
estimator alternates D and G updates over the input stream.

TPU re-design: BOTH sub-steps live in ONE jitted function — d-grads,
d-update, g-grads, g-update fuse into a single XLA program per batch (no
per-network session runs); noise is drawn on-device from the train-state
RNG; batches arrive through the same make_global_batch dp-sharding path the
main Estimator uses.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from analytics_zoo_tpu.common.log import logger
from analytics_zoo_tpu.data.loader import (DataCreator, NumpyBatchIterator,
                                           device_prefetch)
from analytics_zoo_tpu.parallel.mesh import make_mesh
from analytics_zoo_tpu.parallel.partition import data_sharding


# -- built-in GAN losses (ref: tf.contrib.gan losses used by the TFPark
# estimator).  d_loss(real_logits, fake_logits); g_loss(fake_logits).

def minimax_d_loss(real, fake):
    return (jnp.mean(optax.sigmoid_binary_cross_entropy(
        real, jnp.ones_like(real)))
        + jnp.mean(optax.sigmoid_binary_cross_entropy(
            fake, jnp.zeros_like(fake))))


def minimax_g_loss(fake):
    # non-saturating variant (the practical default)
    return jnp.mean(optax.sigmoid_binary_cross_entropy(
        fake, jnp.ones_like(fake)))


def lsgan_d_loss(real, fake):
    return 0.5 * (jnp.mean((real - 1.0) ** 2) + jnp.mean(fake ** 2))


def lsgan_g_loss(fake):
    return 0.5 * jnp.mean((fake - 1.0) ** 2)


def wasserstein_d_loss(real, fake):
    return jnp.mean(fake) - jnp.mean(real)


def wasserstein_g_loss(fake):
    return -jnp.mean(fake)


_LOSSES = {
    "minimax": (minimax_d_loss, minimax_g_loss),
    "lsgan": (lsgan_d_loss, lsgan_g_loss),
    "wasserstein": (wasserstein_d_loss, wasserstein_g_loss),
}


class GANEstimator:
    """Adversarial trainer over flax generator/discriminator modules.

    Args:
      generator: flax module, noise [B, noise_dim] -> sample.
      discriminator: flax module, sample -> logits.
      loss: name in {"minimax", "lsgan", "wasserstein"} OR a pair
        (d_loss_fn(real_logits, fake_logits), g_loss_fn(fake_logits)).
      generator_optimizer / discriminator_optimizer: optax transforms.
      noise_dim: latent dimension sampled N(0, 1) on device.
      d_steps: discriminator updates per generator update (WGAN-style
        n_critic); the extra D steps run inside the same jit.
    """

    def __init__(self, generator, discriminator, *,
                 loss: Any = "minimax",
                 generator_optimizer=None, discriminator_optimizer=None,
                 noise_dim: int = 64, d_steps: int = 1,
                 mesh=None, seed: int = 0):
        self.gen = generator
        self.disc = discriminator
        if isinstance(loss, str):
            if loss not in _LOSSES:
                raise ValueError(f"unknown GAN loss {loss!r}; "
                                 f"have {sorted(_LOSSES)}")
            self.d_loss_fn, self.g_loss_fn = _LOSSES[loss]
        else:
            self.d_loss_fn, self.g_loss_fn = loss
        self.g_tx = generator_optimizer or optax.adam(2e-4, b1=0.5)
        self.d_tx = discriminator_optimizer or optax.adam(2e-4, b1=0.5)
        self.noise_dim = noise_dim
        self.d_steps = d_steps
        if mesh is None:
            try:
                from analytics_zoo_tpu.common.context import OrcaContext
                mesh = OrcaContext.get_context().mesh
            except RuntimeError:
                mesh = make_mesh(axes={"dp": -1})
        self.mesh = mesh
        self.seed = seed
        self.state: Optional[Dict[str, Any]] = None
        self._jit_step = None
        self._data_sharding = data_sharding(self.mesh)

    # ------------------------------------------------------------------

    def _ensure_state(self, sample_real: np.ndarray):
        if self.state is not None:
            return
        root = jax.random.key(self.seed)
        kg, kd, ktrain = jax.random.split(root, 3)
        noise = jnp.zeros((1, self.noise_dim), jnp.float32)
        gv = self.gen.init(kg, noise)
        fake = self.gen.apply(gv, noise)
        dv = self.disc.init(kd, fake)
        self.state = {
            "g_params": gv["params"], "d_params": dv["params"],
            "g_opt": self.g_tx.init(gv["params"]),
            "d_opt": self.d_tx.init(dv["params"]),
            "rng": ktrain, "step": jnp.zeros((), jnp.int32),
        }
        n = sum(int(np.prod(p.shape))
                for p in jax.tree.leaves((gv, dv)))
        logger.info("GANEstimator init: %s params total, mesh=%s",
                    f"{n:,}", dict(self.mesh.shape))

    def _build_step(self):
        if self._jit_step is not None:
            return

        def step(state, real):
            rng = jax.random.fold_in(state["rng"], state["step"])
            b = real.shape[0]

            def d_one(carry, key):
                d_params, d_opt = carry
                noise = jax.random.normal(key, (b, self.noise_dim))
                fake = self.gen.apply({"params": state["g_params"]}, noise)
                fake = jax.lax.stop_gradient(fake)

                def dl(p):
                    return self.d_loss_fn(
                        self.disc.apply({"params": p}, real),
                        self.disc.apply({"params": p}, fake))
                d_loss, gd = jax.value_and_grad(dl)(d_params)
                upd, d_opt = self.d_tx.update(gd, d_opt, d_params)
                return (optax.apply_updates(d_params, upd), d_opt), d_loss

            keys = jax.random.split(rng, self.d_steps + 1)
            (d_params, d_opt), d_losses = jax.lax.scan(
                d_one, (state["d_params"], state["d_opt"]),
                keys[:self.d_steps])

            def gl(p):
                noise = jax.random.normal(keys[-1], (b, self.noise_dim))
                fake = self.gen.apply({"params": p}, noise)
                return self.g_loss_fn(
                    self.disc.apply({"params": d_params}, fake))
            g_loss, gg = jax.value_and_grad(gl)(state["g_params"])
            upd, g_opt = self.g_tx.update(gg, state["g_opt"],
                                          state["g_params"])
            new = {
                "g_params": optax.apply_updates(state["g_params"], upd),
                "d_params": d_params, "g_opt": g_opt, "d_opt": d_opt,
                "rng": state["rng"], "step": state["step"] + 1,
            }
            return new, {"d_loss": d_losses[-1], "g_loss": g_loss}

        self._jit_step = jax.jit(step, donate_argnums=(0,))

    # ------------------------------------------------------------------

    def fit(self, data, epochs: int = 1, batch_size: int = 32,
            feature_col: str = "x") -> list:
        """data: ndarray of real samples, dict with `feature_col`, XShards,
        or a creator fn (the Estimator data contract)."""
        if isinstance(data, np.ndarray):
            data = {feature_col: data}
        arrays = DataCreator.to_arrays(data)
        if feature_col in arrays:
            real = arrays[feature_col]
        elif len(arrays) == 1:
            real = next(iter(arrays.values()))
        else:
            raise KeyError(
                f"feature_col {feature_col!r} not in data columns "
                f"{sorted(arrays)} — ambiguous which one holds the real "
                "samples")
        self._ensure_state(real)
        self._build_step()
        it = NumpyBatchIterator({"x": real}, batch_size, seed=self.seed)
        history = []
        for ep in range(epochs):
            acc: list = []
            # device_prefetch double-buffers H2D staging against compute,
            # same as the main Estimator's fit loop; metrics stay on device
            # until epoch end so no per-step host sync blocks the pipeline
            for gb in device_prefetch(it.epoch_batches(), self.mesh,
                                      sharding=self._data_sharding):
                self.state, mets = self._jit_step(self.state, gb["x"])
                acc.append(mets)
            n = len(acc)
            stats = {k: float(np.mean([np.asarray(m[k]) for m in acc]))
                     for k in (acc[0] if acc else {})}
            stats["epoch"] = ep + 1
            stats["steps"] = n
            history.append(stats)
            logger.info("GAN epoch %d: %s", ep + 1, stats)
        return history

    def generate(self, n: int, seed: int = 0) -> np.ndarray:
        """Sample n outputs from the trained generator."""
        if self.state is None:
            raise RuntimeError("fit first")
        noise = jax.random.normal(jax.random.key(seed),
                                  (n, self.noise_dim))
        out = self.gen.apply({"params": self.state["g_params"]}, noise)
        return np.asarray(out)

"""TFDataset — the reference's dataset-bridging surface.

Reference surface (SURVEY.md §2.2; ref: pyzoo/zoo/tfpark/tf_dataset.py):
``TFDataset.from_rdd / from_ndarrays / from_image_set / from_text_set /
from_feature_set`` adapted every data container into the TF1 per-partition
feeding pipeline, carrying batch size and tensor structure metadata.

TPU re-design: there is no TF1 session to feed — the pjit Estimator
consumes host-local array dicts.  TFDataset is therefore a thin,
named-constructor adapter that (a) normalises any framework container to
the column-dict currency, (b) carries the reference's
batch_size/batch_per_thread semantics so ported call sites keep working,
and (c) plugs directly into ``Estimator.fit/evaluate/predict`` (whose
``DataCreator`` accepts it like any dict).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np


class TFDataset:
    """Adapter carrying (columns, batch metadata) — accepted anywhere the
    estimators take data (DataCreator normalises via ``to_arrays()``)."""

    def __init__(self, arrays: Dict[str, np.ndarray],
                 batch_size: int = -1, batch_per_thread: int = -1):
        lens = {k: len(v) for k, v in arrays.items()}
        if len(set(lens.values())) > 1:
            raise ValueError(f"ragged columns: {lens}")
        self.arrays = {k: np.asarray(v) for k, v in arrays.items()}
        # reference semantics: batch_size is the GLOBAL training batch;
        # batch_per_thread is the per-worker inference batch
        self.batch_size = batch_size
        self.batch_per_thread = batch_per_thread

    # -- reference-parity constructors ---------------------------------

    @staticmethod
    def from_ndarrays(tensors, batch_size: int = -1,
                      batch_per_thread: int = -1,
                      val_tensors=None) -> "TFDataset":
        """tensors: dict of ndarrays, or (x, y) tuple like the reference's
        (features, labels) pair."""
        from analytics_zoo_tpu.data.loader import DataCreator

        ds = TFDataset(DataCreator.to_arrays(tensors), batch_size,
                       batch_per_thread)
        if val_tensors is not None:
            ds.val = TFDataset.from_ndarrays(val_tensors)
        return ds

    @staticmethod
    def from_rdd(shards, batch_size: int = -1, batch_per_thread: int = -1,
                 **_compat) -> "TFDataset":
        """ref: from_rdd(rdd) — here the partitioned currency is XShards
        (SURVEY §2.2: XShards replaces the RDD)."""
        return TFDataset(shards.to_numpy_dict(), batch_size,
                         batch_per_thread)

    @staticmethod
    def from_image_set(image_set, batch_size: int = -1,
                       batch_per_thread: int = -1) -> "TFDataset":
        """ref: from_image_set(ImageSet) — images (+labels when present)
        become the x/y columns after the transform chain has run."""
        d = image_set.to_numpy_dict()
        return TFDataset(d, batch_size, batch_per_thread)

    @staticmethod
    def from_text_set(text_set, batch_size: int = -1,
                      batch_per_thread: int = -1) -> "TFDataset":
        """ref: from_text_set(TextSet) — tokens/labels after
        tokenize/word2idx/shape_sequence."""
        return TFDataset(text_set.to_numpy_dict(), batch_size,
                         batch_per_thread)

    @staticmethod
    def from_feature_set(feature_set, batch_size: int = -1,
                         batch_per_thread: int = -1) -> "TFDataset":
        """ref: from_feature_set(FeatureSet) — DRAM tier only; the DISK
        tier streams and should be passed to fit() directly."""
        from analytics_zoo_tpu.data.feature_set import DiskFeatureSet

        if isinstance(feature_set, DiskFeatureSet):
            raise TypeError(
                "DiskFeatureSet streams from disk — pass it to "
                "Estimator.fit directly instead of materialising it "
                "through TFDataset")
        return TFDataset(dict(feature_set.arrays), batch_size,
                         batch_per_thread)

    # -- consumption ----------------------------------------------------

    def to_arrays(self) -> Dict[str, np.ndarray]:
        return self.arrays

    def column_names(self) -> Sequence[str]:
        return list(self.arrays)

    def __len__(self) -> int:
        return len(next(iter(self.arrays.values()))) if self.arrays else 0


__all__ = ["TFDataset"]

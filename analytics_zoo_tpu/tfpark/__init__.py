"""tfpark — reference-parity namespace for the TF1 training suite.

Reference surface (SURVEY.md §2.3, ref: pyzoo/zoo/tfpark/): KerasModel,
TFEstimator (tf.estimator clone), TFOptimizer (grad extraction into the
BigDL optimizer), TFPredictor, TFDataset, GANEstimator.

TPU mapping — every entry point exists, backed by the native JAX stack
instead of a TF1 session:
  KerasModel    -> the keras API itself (compile/fit on flax modules);
                   ``KerasModel(model)`` returns the model unchanged after
                   validating it, since our keras models ARE estimators.
  TFEstimator   -> learn.Estimator (same fit/evaluate/predict contract).
  TFOptimizer   -> subsumed by the pjit train step (there is no separate
                   grad-extraction machine to port; the whole point of the
                   rebuild is that XLA fuses forward/backward/update).
  TFPredictor   -> learn.InferenceModel.
  TFDataset     -> data.DataCreator / XShards streams.
  GANEstimator  -> tfpark.gan.GANEstimator (alternating two-optimizer
                   adversarial training in one jitted step).
"""

from analytics_zoo_tpu.learn.estimator import Estimator as TFEstimator
from analytics_zoo_tpu.learn.inference_model import (
    InferenceModel as TFPredictor)
from analytics_zoo_tpu.tfpark.gan import GANEstimator
from analytics_zoo_tpu.tfpark.tf_dataset import TFDataset
from analytics_zoo_tpu.tfpark import text  # noqa: F401 (NLP estimators)


def KerasModel(model):
    """ref-parity: tfpark.KerasModel wrapped a compiled tf.keras model; our
    keras models already carry compile/fit/evaluate/predict."""
    from analytics_zoo_tpu.keras.engine import KerasNet

    if not isinstance(model, KerasNet):
        raise TypeError(
            f"KerasModel wraps analytics_zoo_tpu.keras models, got "
            f"{type(model).__name__}")
    return model


__all__ = ["TFEstimator", "TFPredictor", "KerasModel", "GANEstimator",
           "TFDataset", "text"]

"""tfpark.text — NLP estimators over TextSet (reference-parity glue).

Reference surface (SURVEY.md §2.3 TFPark suite "NLP estimators"; ref:
pyzoo/zoo/tfpark/text/estimator/ — TextEstimator base plus
TextClassification / BERTClassifier estimators driving TF1 sessions):
estimator-level entry points that take a prepared ``TextSet`` (or raw
arrays) and run fit / evaluate / predict / distributed inference.

TPU re-design: one thin ``TextEstimator`` base adapts text containers to
the ONE pjit runtime (``learn.FlaxEstimator``).  There is no session or
graph machinery to port — the estimators differ only in which flax model
and column mapping they bind:

  TextClassificationEstimator  -> models.TextClassifier (CNN/LSTM/GRU)
  KNRMEstimator                -> models.KNRM (text matching, pairs)
  BERTClassifier               -> models.BERTForSequenceClassification
  NEREstimator / POSEstimator / IntentEntityEstimator
                               -> tfpark.text.keras taggers
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Union

import numpy as np
import optax

from analytics_zoo_tpu.data.text import TextSet
from analytics_zoo_tpu.learn.estimator import FlaxEstimator
from analytics_zoo_tpu.tfpark.text import keras
from analytics_zoo_tpu.tfpark.text.keras import (
    NER, POSTagger, IntentEntity, intent_entity_loss)


def _text_arrays(data) -> Dict[str, np.ndarray]:
    """TextSet / (TextSet, TextSet) pair / dict / (x, y) -> array dict."""
    if isinstance(data, TextSet):
        return data.to_numpy_dict()                  # {"tokens", "y"}
    if isinstance(data, (tuple, list)) and len(data) == 2 and \
            all(isinstance(t, TextSet) for t in data):
        a, b = (t.to_numpy_dict() for t in data)
        # matching pair: labels ride on the first set (ref: KNRM corpus
        # relevance labels are attached to the query side)
        return {"text1": a["tokens"], "text2": b["tokens"], "y": a["y"]}
    return data


class TextEstimator:
    """Base NLP estimator: binds a flax model + column mapping onto the
    pjit runtime and accepts TextSet inputs everywhere.

    (ref: tfpark.text.estimator.TextEstimator — model_fn + input_fn glue
    onto TFEstimator; here the runtime is the shared FlaxEstimator.)
    """

    def __init__(self, model, loss, optimizer=None, *,
                 feature_cols: Sequence[str] = ("tokens",),
                 label_cols: Sequence[str] = ("y",),
                 metrics: Sequence = ("accuracy",), **kw):
        self.estimator = FlaxEstimator(
            model, loss, optimizer if optimizer is not None
            else optax.adam(1e-3),
            feature_cols=feature_cols, label_cols=label_cols,
            metrics=metrics, **kw)

    @property
    def model(self):
        return self.estimator.model

    def fit(self, data, epochs: int = 1, batch_size: int = 32,
            validation_data=None, **kw):
        if validation_data is not None:
            validation_data = _text_arrays(validation_data)
        return self.estimator.fit(
            _text_arrays(data), epochs=epochs, batch_size=batch_size,
            validation_data=validation_data, **kw)

    def evaluate(self, data, batch_size: int = 32, **kw):
        return self.estimator.evaluate(_text_arrays(data),
                                       batch_size=batch_size, **kw)

    def predict(self, data, batch_size: int = 32, **kw):
        return self.estimator.predict(_text_arrays(data),
                                      batch_size=batch_size, **kw)

    def save_checkpoint(self, path: str):
        return self.estimator.save_checkpoint(path)

    def load_checkpoint(self, path: str, step: Optional[int] = None):
        return self.estimator.load_checkpoint(path, step)

    def save(self, path: str):
        return self.estimator.save(path)

    def load(self, path: str, sample_data=None):
        if sample_data is not None:
            sample_data = _text_arrays(sample_data)
        return self.estimator.load(path, sample_data)


class TextClassificationEstimator(TextEstimator):
    """ref-parity: tfpark text classification estimator over
    models.TextClassifier (token CNN/LSTM/GRU encoder + softmax)."""

    def __init__(self, class_num: int, vocab_size: int, *,
                 token_length: int = 200, sequence_length: int = 500,
                 encoder: str = "cnn", encoder_output_dim: int = 256,
                 embed_weights: Optional[np.ndarray] = None,
                 optimizer=None, **kw):
        from analytics_zoo_tpu.models.text import TextClassifier

        super().__init__(
            TextClassifier(class_num=class_num, vocab_size=vocab_size,
                           token_length=token_length,
                           sequence_length=sequence_length,
                           encoder=encoder,
                           encoder_output_dim=encoder_output_dim,
                           embed_weights=embed_weights),
            "sparse_categorical_crossentropy", optimizer, **kw)


class KNRMEstimator(TextEstimator):
    """ref-parity: kernel-pooled text-matching estimator over models.KNRM.
    Data: {"text1", "text2", "y"} arrays or an (query TextSet, doc
    TextSet) pair; `target_mode="ranking"` trains logistic relevance."""

    def __init__(self, vocab_size: int, *, text1_length: int = 10,
                 text2_length: int = 40, embed_dim: int = 300,
                 kernel_num: int = 21, sigma: float = 0.1,
                 exact_sigma: float = 0.001, target_mode: str = "ranking",
                 embed_weights: Optional[np.ndarray] = None,
                 optimizer=None, **kw):
        from analytics_zoo_tpu.models.text import KNRM

        loss = "bce" if target_mode == "ranking" \
            else "sparse_categorical_crossentropy"
        metrics = kw.pop("metrics", ("binary_accuracy",)
                         if target_mode == "ranking" else ("accuracy",))
        super().__init__(
            KNRM(vocab_size=vocab_size, text1_length=text1_length,
                 text2_length=text2_length, embed_dim=embed_dim,
                 kernel_num=kernel_num, sigma=sigma,
                 exact_sigma=exact_sigma, target_mode=target_mode,
                 embed_weights=embed_weights),
            loss, optimizer,
            feature_cols=("text1", "text2"), metrics=metrics, **kw)

    def fit(self, data, epochs: int = 1, batch_size: int = 32, **kw):
        arrays = dict(_text_arrays(data))
        if "y" in arrays and self.model.target_mode == "ranking":
            # BCE against a [B, 1] score column
            arrays["y"] = np.asarray(arrays["y"],
                                     np.float32).reshape(-1, 1)
        return super().fit(arrays, epochs=epochs, batch_size=batch_size,
                           **kw)


class BERTClassifier(TextEstimator):
    """ref-parity: tfpark.text.estimator.BERTClassifier — sequence
    classification over the BERT encoder (here models.BERT, with flash
    attention / remat / TP partition rules available via the model)."""

    def __init__(self, num_classes: int, *, bert=None, optimizer=None,
                 **kw):
        from analytics_zoo_tpu.models import (
            BERT_PARTITION_RULES, BERTForSequenceClassification)

        kw.setdefault("partition_rules", BERT_PARTITION_RULES)
        super().__init__(
            BERTForSequenceClassification(num_classes=num_classes,
                                          bert=bert),
            "sparse_categorical_crossentropy",
            optimizer if optimizer is not None else optax.adamw(2e-5),
            feature_cols=("input_ids",), label_cols=("y",), **kw)


def token_accuracy(logits, labels):
    """Per-token accuracy over non-pad positions is not knowable here
    (pad id lives in the data), so this reports plain per-token accuracy —
    the reference's taggers did the same."""
    import jax.numpy as jnp

    return jnp.mean(
        (jnp.argmax(logits, -1) == labels.astype(jnp.int32)))


class NEREstimator(TextEstimator):
    """Sequence tagger estimator over tfpark.text.keras.NER."""

    def __init__(self, num_entities: int, vocab_size: int, *,
                 embed_dim: int = 100, hidden: int = 100, optimizer=None,
                 **kw):
        kw.setdefault("metrics", (token_accuracy,))
        super().__init__(
            NER(vocab_size=vocab_size, embed_dim=embed_dim, hidden=hidden,
                num_entities=num_entities),
            "sparse_categorical_crossentropy", optimizer, **kw)


class POSEstimator(TextEstimator):
    """Sequence tagger estimator over tfpark.text.keras.POSTagger."""

    def __init__(self, num_pos_tags: int, vocab_size: int, *,
                 embed_dim: int = 100, hidden: int = 100, optimizer=None,
                 **kw):
        kw.setdefault("metrics", (token_accuracy,))
        super().__init__(
            POSTagger(vocab_size=vocab_size, embed_dim=embed_dim,
                      hidden=hidden, num_pos_tags=num_pos_tags),
            "sparse_categorical_crossentropy", optimizer, **kw)


class IntentEntityEstimator(TextEstimator):
    """Joint intent + entity estimator over tfpark.text.keras.IntentEntity.
    Data columns: tokens, intent (int per row), entity (int per token)."""

    def __init__(self, num_intents: int, num_entities: int,
                 vocab_size: int, *, embed_dim: int = 100,
                 hidden: int = 100, optimizer=None, **kw):
        kw.setdefault("metrics", ())
        super().__init__(
            IntentEntity(vocab_size=vocab_size, embed_dim=embed_dim,
                         hidden=hidden, num_intents=num_intents,
                         num_entities=num_entities),
            intent_entity_loss, optimizer,
            label_cols=("intent", "entity"), **kw)


__all__ = [
    "TextEstimator", "TextClassificationEstimator", "KNRMEstimator",
    "BERTClassifier", "NEREstimator", "POSEstimator",
    "IntentEntityEstimator", "keras",
    "NER", "POSTagger", "IntentEntity",
]

"""tfpark.text.keras — named NLP models (sequence taggers + intent).

Reference surface (SURVEY.md §2.3 TFPark suite; ref: pyzoo/zoo/tfpark/text/
keras/ — ``TextModel`` base with ``NER``, ``POSTagger``, ``IntentEntity``
built on TF1 Keras): word-embedding + recurrent encoders with per-token
and/or per-utterance heads.

TPU re-design: flax modules whose encoders are bidirectional GRU stacks
(two ``nn.RNN`` scans — XLA compiles each to one fused loop; the pair runs
as independent programs) and whose heads are plain MXU matmuls.  They plug
into ``tfpark.text.TextEstimator`` (or ``learn.Estimator`` directly) rather
than carrying their own session machinery — compile/fit/predict is the one
pjit runtime.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp
import numpy as np


class BiRNN(nn.Module):
    """Bidirectional recurrent encoder over [B, T, F] -> [B, T, 2H]."""

    hidden: int
    rnn_type: str = "gru"
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        from analytics_zoo_tpu.models.rnn import make_cell

        fwd = nn.RNN(make_cell(self.rnn_type, self.hidden, dtype=self.dtype),
                     name="fwd")(x)
        bwd = nn.RNN(make_cell(self.rnn_type, self.hidden, dtype=self.dtype),
                     reverse=True, keep_order=True, name="bwd")(x)
        return jnp.concatenate([fwd, bwd], axis=-1)


class TextModel(nn.Module):
    """Shared encoder: word embedding -> BiGRU (ref: TextModel base)."""

    vocab_size: int
    embed_dim: int = 100
    hidden: int = 100
    dropout: float = 0.25
    embed_weights: Optional[np.ndarray] = None
    dtype: jnp.dtype = jnp.bfloat16

    def encode(self, tokens, train: bool):
        from analytics_zoo_tpu.models.text import _embedding

        x = _embedding(self.vocab_size, self.embed_dim,
                       self.embed_weights, "word_embedding")(tokens)
        x = x.astype(self.dtype)
        h = BiRNN(self.hidden, dtype=self.dtype, name="birnn")(x)
        return nn.Dropout(self.dropout, deterministic=not train)(h)


class NER(TextModel):
    """Named-entity tagger: per-token entity logits [B, T, num_entities]
    (ref: tfpark.text.keras.NER)."""

    num_entities: int = 9

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        h = self.encode(tokens, train)
        return nn.Dense(self.num_entities, dtype=jnp.float32,
                        name="entity_head")(h)


class POSTagger(TextModel):
    """Part-of-speech tagger: per-token tag logits [B, T, num_pos_tags]
    (ref: tfpark.text.keras.POSTagger)."""

    num_pos_tags: int = 45

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        h = self.encode(tokens, train)
        return nn.Dense(self.num_pos_tags, dtype=jnp.float32,
                        name="pos_head")(h)


class IntentEntity(TextModel):
    """Joint intent classification + entity tagging
    (ref: tfpark.text.keras.IntentEntity): shared encoder, an utterance
    head over the final states and a per-token entity head.  Returns
    ``(intent_logits [B, I], entity_logits [B, T, E])``."""

    num_intents: int = 8
    num_entities: int = 9

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        h = self.encode(tokens, train)             # [B, T, 2H]
        # utterance representation: max over time (pad rows contribute
        # -inf-free zeros after masking)
        mask = (tokens > 0)[:, :, None]
        pooled = jnp.max(jnp.where(mask, h, -1e9), axis=1)
        intent = nn.Dense(self.num_intents, dtype=jnp.float32,
                          name="intent_head")(pooled)
        entity = nn.Dense(self.num_entities, dtype=jnp.float32,
                          name="entity_head")(h)
        return intent, entity


def intent_entity_loss(preds, labels):
    """Joint loss for IntentEntity: CE(intent) + per-token CE(entity)."""
    import optax

    intent_logits, entity_logits = preds
    intent_y, entity_y = labels
    li = jnp.mean(optax.softmax_cross_entropy_with_integer_labels(
        intent_logits, intent_y.astype(jnp.int32)))
    le = jnp.mean(optax.softmax_cross_entropy_with_integer_labels(
        entity_logits, entity_y.astype(jnp.int32)))
    return li + le


__all__ = ["TextModel", "BiRNN", "NER", "POSTagger", "IntentEntity",
           "intent_entity_loss"]

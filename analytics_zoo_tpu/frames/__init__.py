"""NNFrames — DataFrame-style train/predict stages (SURVEY.md §2.4;
ref: zoo/pipeline/nnframes/)."""

from analytics_zoo_tpu.frames.nnframes import (
    ChainedPreprocessing, NNClassifier, NNClassifierModel, NNEstimator,
    NNImageReader, NNModel, Preprocessing, ScalerPreprocessing,
    df_to_arrays)

__all__ = [
    "NNEstimator", "NNModel", "NNClassifier", "NNClassifierModel",
    "NNImageReader", "Preprocessing", "ChainedPreprocessing",
    "ScalerPreprocessing", "df_to_arrays",
]

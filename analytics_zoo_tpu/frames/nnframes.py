"""NNFrames — DataFrame-native Estimator/Transformer pipeline stages.

Reference surface (SURVEY.md §2.4; ref: zoo/pipeline/nnframes/
NNEstimator.scala + pyzoo/zoo/pipeline/nnframes/nn_classifier.py): Spark ML
``Estimator``/``Transformer`` integration — ``NNEstimator(model, criterion,
feature_preprocessing).setFeaturesCol(...).fit(df)`` → ``NNModel`` whose
``transform(df)`` appends a prediction column; ``NNClassifier`` /
``NNClassifierModel`` specialise to argmax classification; ``NNImageReader``
loads images into DataFrame rows.

TPU re-design: the DataFrame is pandas (host-resident; XShards of
DataFrames for the sharded case) — there is no Spark SQL engine underneath,
because the reference's use of it was row↔Sample marshalling, which here is
a single ``np.stack`` per column. The training itself delegates to the
pjit-compiled ``FlaxEstimator``.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Union

import numpy as np

from analytics_zoo_tpu.data.shards import XShards
from analytics_zoo_tpu.learn.estimator import FlaxEstimator
from analytics_zoo_tpu.utils.transform import Chain, Transform


def _is_df(x) -> bool:
    import pandas as pd
    return isinstance(x, pd.DataFrame)


class Preprocessing(Transform):
    """Composable column→ndarray step (ref: feature Preprocessing chain).

    A Preprocessing wraps ``fn(np.ndarray) -> np.ndarray`` applied to the
    stacked column; chain with ``>>`` (shared base: utils.transform).
    """

    def __init__(self, fn: Callable[[np.ndarray], np.ndarray],
                 name: str = "preprocessing"):
        super().__init__(fn, name)


class ChainedPreprocessing(Chain, Preprocessing):
    """ref-parity: ChainedPreprocessing(list) — left-to-right composition."""


Preprocessing.chain_cls = ChainedPreprocessing


class ScalerPreprocessing(Preprocessing):
    def __init__(self, mean: float = 0.0, scale: float = 1.0):
        super().__init__(lambda a: ((a - mean) / scale).astype(np.float32),
                         "scaler")


def _col_to_array(df, col: str) -> np.ndarray:
    """Stack a DataFrame column of scalars or array-likes into [N, ...]."""
    vals = df[col].to_list()
    first = vals[0]
    if isinstance(first, (list, tuple, np.ndarray)):
        return np.stack([np.asarray(v) for v in vals])
    return np.asarray(df[col].to_numpy())


def df_to_arrays(df, feature_cols: Sequence[str],
                 label_cols: Sequence[str] = (),
                 feature_preprocessing: Optional[Preprocessing] = None):
    """DataFrame → estimator batch dict (the row↔Sample marshalling
    analog of ref NNEstimator's Preprocessing-to-Tensor path)."""
    out = {}
    for c in feature_cols:
        a = _col_to_array(df, c)
        if feature_preprocessing is not None:
            a = feature_preprocessing(a)
        out[c] = a
    for c in label_cols:
        out[c] = _col_to_array(df, c)
    return out


class NNEstimator:
    """ref-parity: NNEstimator(model, criterion) with setters; fit(df) →
    NNModel."""

    def __init__(self, model, criterion: Union[str, Callable],
                 optimizer=None, *,
                 feature_preprocessing: Optional[Preprocessing] = None):
        self.model = model
        self.criterion = criterion
        self.optimizer = optimizer
        self.feature_preprocessing = feature_preprocessing
        self.feature_cols: List[str] = ["features"]
        self.label_cols: List[str] = ["label"]
        self.batch_size = 32
        self.max_epoch = 1

    # Spark-ML-style fluent setters (reference API shape).
    def setFeaturesCol(self, *cols: str) -> "NNEstimator":
        self.feature_cols = list(cols)
        return self

    def setLabelCol(self, *cols: str) -> "NNEstimator":
        self.label_cols = list(cols)
        return self

    def setBatchSize(self, bs: int) -> "NNEstimator":
        self.batch_size = int(bs)
        return self

    def setMaxEpoch(self, n: int) -> "NNEstimator":
        self.max_epoch = int(n)
        return self

    def _make_estimator(self) -> FlaxEstimator:
        import optax

        opt = self.optimizer if self.optimizer is not None \
            else optax.adam(1e-3)
        return FlaxEstimator(self.model, self.criterion, opt,
                             feature_cols=tuple(self.feature_cols),
                             label_cols=tuple(self.label_cols))

    def _arrays(self, df):
        if isinstance(df, XShards):
            import pandas as pd

            shards = df.collect()
            df = pd.concat(shards, ignore_index=True) \
                if _is_df(shards[0]) else df.to_numpy_dict()
        if _is_df(df):
            return df_to_arrays(df, self.feature_cols, self.label_cols,
                                self.feature_preprocessing)
        return df  # already a dict of arrays

    def fit(self, df, validation_df=None) -> "NNModel":
        est = self._make_estimator()
        val = self._arrays(validation_df) \
            if validation_df is not None else None
        est.fit(self._arrays(df), epochs=self.max_epoch,
                batch_size=self.batch_size, validation_data=val)
        return self._model_cls()(est, self.feature_cols,
                                 self.feature_preprocessing)

    def _model_cls(self):
        return NNModel


class NNModel:
    """ref-parity: Transformer — transform(df) appends ``prediction``."""

    prediction_col = "prediction"

    def __init__(self, estimator: FlaxEstimator,
                 feature_cols: Sequence[str],
                 feature_preprocessing: Optional[Preprocessing] = None):
        self.estimator = estimator
        self.feature_cols = list(feature_cols)
        self.feature_preprocessing = feature_preprocessing
        self.batch_size = 128

    def setBatchSize(self, bs: int) -> "NNModel":
        self.batch_size = int(bs)
        return self

    def _predict_arrays(self, df) -> np.ndarray:
        arrays = df_to_arrays(df, self.feature_cols, (),
                              self.feature_preprocessing) \
            if _is_df(df) else df
        return self.estimator.predict(arrays, batch_size=self.batch_size)

    def _post(self, preds: np.ndarray):
        return [np.asarray(p) for p in preds]  # row-wise vectors

    def transform(self, df):
        if isinstance(df, XShards):
            return df.transform_shard(self.transform)
        preds = self._post(self._predict_arrays(df))
        out = df.copy()
        out[self.prediction_col] = preds
        return out

    def save(self, path: str):
        self.estimator.save(path)


class NNClassifier(NNEstimator):
    """ref-parity: NNClassifier — classification specialisation (integer
    labels, CE loss default)."""

    def __init__(self, model, criterion="sparse_categorical_crossentropy",
                 optimizer=None, **kw):
        super().__init__(model, criterion, optimizer, **kw)

    def _model_cls(self):
        return NNClassifierModel


class NNClassifierModel(NNModel):
    """transform() yields the argmax class id (float, Spark ML parity)."""

    def _post(self, preds: np.ndarray):
        return np.argmax(np.asarray(preds), axis=-1).astype(
            np.float64).tolist()


class NNImageReader:
    """ref-parity: NNImageReader.readImages(path) — images as DataFrame
    rows (ref: zoo pipeline/nnframes/NNImageReader.scala, image schema
    origin/height/width/nChannels/data).

    The TPU edition returns a pandas DataFrame whose ``image`` column holds
    decoded RGB ndarrays (HWC uint8; float32 after resize), decoded by the
    C++ data plane (libjpeg/libpng, PIL fallback — data/image.py), plus the
    schema columns.  Feed it straight to NNEstimator/NNClassifier with
    ``setFeaturesCol("image")``.
    """

    @staticmethod
    def readImages(path: str, resize_h: int = -1, resize_w: int = -1,
                   with_label: bool = False, num_shards: int = 1):
        """Read a dir (or one-subdir-per-class tree when with_label)."""
        import pandas as pd

        from analytics_zoo_tpu.data.image import ImageResize, ImageSet

        iset = ImageSet.read(path, num_shards=num_shards,
                             with_label=with_label)
        if resize_h > 0 and resize_w > 0:
            iset = iset.transform(ImageResize(resize_h, resize_w))
        rows = {"origin": [], "image": [], "height": [], "width": [],
                "n_channels": [], "label": []}
        for shard in iset.shards.collect():
            for img, label, p in zip(shard["image"], shard["label"],
                                     shard["path"]):
                rows["origin"].append(p)
                rows["image"].append(img)
                rows["height"].append(img.shape[0])
                rows["width"].append(img.shape[1])
                rows["n_channels"].append(img.shape[2])
                rows["label"].append(int(label))
        df = pd.DataFrame(rows)
        if not with_label:
            df = df.drop(columns=["label"])
        df.attrs["class_names"] = iset.class_names
        return df

"""FeatureSet: cached training sets with pluggable memory tiers.

Reference (SURVEY.md §2.2, ref: zoo feature/dataset/ — FeatureSet,
DRAMFeatureSet, PmemFeatureSet over memkind JNI, DiskFeatureSet): the Scala
side caches the training set in a chosen memory tier and exposes a minibatch
stream to the optimizer.

TPU rebuild: the tiers become
  * DRAM    — host-RAM dict of ndarrays (the default; analog of
              DRAMFeatureSet),
  * DISK    — a ZREC record file of packed row-blocks read by the native
              C++ prefetch thread through a ring buffer (analog of
              PmemFeatureSet/DiskFeatureSet: capacity beyond RAM at
              near-sequential-IO speed, with the copy loop off the GIL).

Both tiers yield per-host batch dicts; `device_stream` composes with
`loader.device_prefetch` for the HBM double-buffer stage.
"""

from __future__ import annotations

import os
import tempfile
from typing import Dict, Iterator, Optional, Sequence

import numpy as np

from analytics_zoo_tpu.data.loader import NumpyBatchIterator, device_prefetch
from analytics_zoo_tpu.data.shards import XShards

BLOCK_ROWS_DEFAULT = 4096


def _host_path(path: str) -> str:
    """Per-host shard-file naming: a ``{host}`` placeholder expands to this
    process's index, so N hosts spill/stream N disjoint files from one
    path template (the multihost DISK-tier contract: each host owns the
    shard file it writes — nothing is replicated)."""
    if "{host}" in path:
        import jax

        return path.format(host=jax.process_index())
    return path


class FeatureSet:
    """DRAM-tier feature set (ref: FeatureSet.rdd / DRAMFeatureSet)."""

    def __init__(self, arrays: Dict[str, np.ndarray]):
        lens = {k: len(v) for k, v in arrays.items()}
        if len(set(lens.values())) > 1:
            raise ValueError(f"ragged arrays: {lens}")
        self.arrays = {k: np.asarray(v) for k, v in arrays.items()}

    # -- constructors ------------------------------------------------------
    @staticmethod
    def from_arrays(arrays: Dict[str, np.ndarray]) -> "FeatureSet":
        return FeatureSet(arrays)

    @staticmethod
    def from_shards(shards: XShards) -> "FeatureSet":
        return FeatureSet(shards.to_numpy_dict())

    # -- API ---------------------------------------------------------------
    def __len__(self) -> int:
        return len(next(iter(self.arrays.values()))) if self.arrays else 0

    def batches(self, batch_size: int, *, shuffle: bool = True,
                drop_remainder: bool = True, seed: int = 0, epoch: int = 0
                ) -> Iterator[Dict[str, np.ndarray]]:
        it = NumpyBatchIterator(self.arrays, batch_size, shuffle=shuffle,
                                drop_remainder=drop_remainder, seed=seed)
        it.epoch = epoch
        return it.epoch_batches()

    def device_stream(self, mesh, batch_size: int, *, depth: int = 2,
                      sharding=None, **kw):
        return device_prefetch(self.batches(batch_size, **kw), mesh,
                               depth=depth, sharding=sharding)

    def to_disk(self, path: Optional[str] = None,
                block_rows: int = BLOCK_ROWS_DEFAULT) -> "DiskFeatureSet":
        """Spill to the DISK tier: write row-blocks to a ZREC record file.

        ``path`` may be a remote URI (gs://, s3://, memory://; a
        ``{host}`` placeholder composes — each host uploads its own
        shard object): the file is written locally and pushed out, and
        the returned DiskFeatureSet streams from the primed local cache,
        not back over the wire."""
        from analytics_zoo_tpu import native

        from analytics_zoo_tpu.common import fs

        if path is None:
            fd, path = tempfile.mkstemp(suffix=".zrec")
            os.close(fd)
        path = _host_path(path)
        local = path
        if fs.is_remote(path):
            fd, local = tempfile.mkstemp(suffix=".zrec")
            os.close(fd)
        n = len(self)
        with native.RecordWriter(local) as w:
            for lo in range(0, n, block_rows):
                block = {k: v[lo:lo + block_rows]
                         for k, v in self.arrays.items()}
                w.write(native.pack_batch(block))
        if fs.is_remote(path):
            fs.upload(local, path)
            fs.prime_cache(local, path)
            os.remove(local)    # the cache copy is now the local source
        return DiskFeatureSet(path)


class DiskFeatureSet:
    """DISK-tier feature set over a ZREC file (ref: DiskFeatureSet /
    PmemFeatureSet — memory tier beyond DRAM, zoo feature/pmem/).

    Row-blocks are streamed by a *native* reader thread into a ring buffer
    (file IO + memcpy run in C++ while JAX computes), then re-batched to the
    requested batch size in numpy.  Block order is shuffled per epoch;
    intra-block order is preserved (the reference's PMEM path likewise
    shuffles at the chunk level).

    Multihost: the file is HOST-LOCAL — each host streams the shard it owns
    (spill with a ``{host}`` placeholder path, or any per-host path).  The
    Estimator aligns step/chunk counts across hosts via one row-count
    allgather, so uneven shards train on ``min_rows`` per host and
    evaluate/predict over every row exactly once.
    """

    def __init__(self, path: str, *, ring_mb: int = 128):
        from analytics_zoo_tpu import native

        from analytics_zoo_tpu.common import fs

        path = _host_path(path)
        self.path = path
        self._native = native
        # remote shard URIs (each host downloads only ITS {host} shard)
        # materialise through the per-process cache: the native reader
        # mmaps a real local file — streaming ZREC over object-store
        # range reads would serialise the prefetch thread on the wire
        self.reader = native.RecordReader(fs.local_copy(path))
        self.ring_bytes = ring_mb << 20
        meta = native.unpack_batch(self.reader.get(0)) if len(self.reader) \
            else {}
        self.colnames = sorted(meta)
        # Exact total: sum each block's header row count (header peek over
        # the mmap — no payload copies).  Files written through the public
        # RecordWriter/pack_batch API may have arbitrarily uneven blocks.
        self._n = sum(native.peek_batch_rows(self.reader.get(i))
                      for i in range(len(self.reader)))

    def __len__(self) -> int:
        return self._n

    def batches(self, batch_size: int, *, shuffle: bool = True,
                drop_remainder: bool = True, seed: int = 0, epoch: int = 0
                ) -> Iterator[Dict[str, np.ndarray]]:
        if self._n == 0 or (batch_size > self._n and drop_remainder):
            # a silent zero-batch epoch/eval would look like running while
            # doing nothing; with drop_remainder=False and rows present the
            # single short batch is emitted (the DRAM eval/predict contract)
            raise ValueError(
                f"per-host batch {batch_size} > host rows {self._n}")
        native = self._native
        nblocks = len(self.reader)
        order = np.arange(nblocks)
        if shuffle:
            np.random.default_rng(seed + epoch).shuffle(order)
        ring = native.RingBuffer(self.ring_bytes)
        pf = native.Prefetcher(self.reader, ring, order.tolist(), loop=False)
        try:
            # Deque of blocks + a row cursor into the head block: each output
            # batch concatenates exactly the slices it needs (linear copies —
            # no re-concatenation of the whole pending buffer per batch).
            import collections

            pend: collections.deque = collections.deque()
            head_off = 0
            pend_rows = 0

            def emit(n):
                nonlocal head_off, pend_rows
                pieces: Dict[str, list] = {}
                need = n
                while need:
                    block = pend[0]
                    blen = len(next(iter(block.values()))) - head_off
                    take = min(need, blen)
                    for k, v in block.items():
                        pieces.setdefault(k, []).append(
                            v[head_off:head_off + take])
                    need -= take
                    if take == blen:
                        pend.popleft()
                        head_off = 0
                    else:
                        head_off += take
                pend_rows -= n
                return {k: np.concatenate(v) if len(v) > 1 else v[0]
                        for k, v in pieces.items()}

            while True:
                blob = ring.pop()
                if blob is None:
                    break
                block = native.unpack_batch(blob)
                pend.append(block)
                pend_rows += len(next(iter(block.values())))
                while pend_rows >= batch_size:
                    yield emit(batch_size)
            if pend_rows and not drop_remainder:
                yield emit(pend_rows)
        finally:
            ring.close()
            pf.stop()

    def device_stream(self, mesh, batch_size: int, *, depth: int = 2,
                      sharding=None, **kw):
        return device_prefetch(self.batches(batch_size, **kw), mesh,
                               depth=depth, sharding=sharding)

    def fingerprint(self) -> int:
        """Content fingerprint (row count + full first/last record hash),
        used by the Estimator to detect N hosts accidentally opening ONE
        replicated/shared shard file instead of per-host shards.  Distinct
        shards that differ anywhere in their first or last block hash
        differently; a genuine collision can be overridden with
        ANALYTICS_ZOO_TPU_ALLOW_SHARED_DISK=1."""
        import hashlib

        h = hashlib.blake2b(digest_size=7)
        h.update(str(self._n).encode())
        nrec = len(self.reader)
        if nrec:
            h.update(bytes(self.reader.get(0)))
            h.update(bytes(self.reader.get(nrec - 1)))
        return int.from_bytes(h.digest(), "little")

    def sample_block(self) -> Dict[str, np.ndarray]:
        """First row-block (shape/dtype probe) — reads one record, no
        prefetch thread / ring buffer involved."""
        if not len(self.reader):
            raise ValueError(f"{self.path} holds no records")
        return self._native.unpack_batch(self.reader.get(0))

    def batch_iterator(self, batch_size: int, *, shuffle: bool = True,
                       seed: int = 0) -> "_DiskEpochIterator":
        """NumpyBatchIterator-compatible epoch iterator (Estimator.fit's
        data protocol): each epoch_batches() call streams a fresh shuffled
        pass through the native prefetch thread."""
        return _DiskEpochIterator(self, batch_size, shuffle, seed)

    def to_dram(self) -> FeatureSet:
        cols: Dict[str, list] = {}
        for i in range(len(self.reader)):
            for k, v in self._native.unpack_batch(self.reader.get(i)).items():
                cols.setdefault(k, []).append(v)
        return FeatureSet({k: np.concatenate(v) for k, v in cols.items()})

    def close(self):
        self.reader.close()


class _DiskEpochIterator:
    """Adapter: DiskFeatureSet -> the epoch_batches() protocol fit uses."""

    def __init__(self, dfs: DiskFeatureSet, batch_size: int, shuffle: bool,
                 seed: int):
        self.dfs = dfs
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0

    def steps_per_epoch(self) -> int:
        return len(self.dfs) // self.batch_size

    def epoch_batches(self):
        it = self.dfs.batches(self.batch_size, shuffle=self.shuffle,
                              drop_remainder=True, seed=self.seed,
                              epoch=self.epoch)
        self.epoch += 1
        return it

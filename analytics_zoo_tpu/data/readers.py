"""File readers -> XShards.

Reference (SURVEY.md §2.2, ref: pyzoo/zoo/orca/data/pandas/preprocessing.py):
``zoo.orca.data.pandas.read_csv/read_json`` load file globs into
SparkXShards of pandas DataFrames, partitioned across Spark executors.

Here files are partitioned across TPU-VM *hosts* (deterministic round-robin
by sorted path so every host sees a disjoint set), then each host reads its
files into local shards — one shard per file, or `shards_per_host` re-split.

Paths may be remote URIs (``gs://``, ``s3://``, ``hdfs://``,
``memory://`` — the reference read HDFS/S3 through Spark, ref: pyzoo/
zoo/orca/data/pandas/preprocessing.py); common.fs dispatches by scheme
and plain local paths keep the native C++ CSV fast path.
"""

from __future__ import annotations

from typing import Callable, List, Optional

import jax

from analytics_zoo_tpu.common import fs
from analytics_zoo_tpu.common.context import (
    effective_process_count as _nhosts,
    effective_process_index as _hidx)
from analytics_zoo_tpu.data.shards import XShards


def _expand(path_or_glob) -> List[str]:
    if isinstance(path_or_glob, (list, tuple)):
        out: List[str] = []
        for p in path_or_glob:
            out.extend(_expand(p))
        return sorted(set(out))
    if fs.isdir(path_or_glob):
        return sorted(
            fs.join(path_or_glob, f) for f in fs.listdir(path_or_glob)
            if not f.startswith(("_", ".")))
    matches = fs.glob(path_or_glob)
    if not matches and fs.exists(path_or_glob):
        matches = [path_or_glob]
    if not matches:
        raise FileNotFoundError(f"no files match {path_or_glob!r}")
    return matches


def _host_slice(files: List[str], host_index: Optional[int],
                num_hosts: Optional[int]) -> List[str]:
    hi = _hidx() if host_index is None else host_index
    nh = _nhosts() if num_hosts is None else num_hosts
    # Hosts beyond len(files) naturally get an empty list — never duplicate
    # a file across hosts.
    return files[hi::nh]


def _read_files(reader: Callable, path, shards_per_host, host_index,
                num_hosts, **kwargs) -> XShards:
    files = _expand(path)
    mine = _host_slice(files, host_index, num_hosts)
    shards = [reader(f, **kwargs) for f in mine]
    xs = XShards(
        shards,
        num_hosts=_nhosts() if num_hosts is None else num_hosts,
        host_index=_hidx() if host_index is None else host_index)
    if shards_per_host and shards:
        xs = xs.repartition(shards_per_host)
    return xs


def _read_csv_one(path, backend: str = "auto", **pandas_kwargs):
    """One CSV file -> pandas DataFrame.

    backend="native" uses the C++ multithreaded parser (numeric CSVs only;
    the Spark-parallel-ingest replacement — SURVEY.md §2.2); "pandas" always
    uses pandas; "auto" tries native and falls back on non-numeric content,
    pandas-specific kwargs, or a missing toolchain.
    """
    import pandas as pd

    if backend == "native" and pandas_kwargs:
        raise ValueError(
            f"backend='native' does not accept pandas kwargs "
            f"{sorted(pandas_kwargs)}; use backend='pandas' or 'auto'")
    if backend != "pandas" and not pandas_kwargs:
        try:
            from analytics_zoo_tpu import native

            # remote URIs materialise through the per-process cache —
            # the C++ parser wants a real file (and a numeric-CSV
            # download is usually cheaper than row-wise remote reads)
            return pd.DataFrame(native.read_csv_native(fs.local_copy(path)))
        except Exception:
            if backend == "native":
                raise
    # pandas resolves fsspec URIs (gs://, s3://, memory://) natively —
    # but if the native attempt above already downloaded the file, parse
    # the cached copy instead of paying the transfer twice
    if fs.is_remote(path) and backend != "pandas" and not pandas_kwargs:
        path = fs.local_copy(path)
    return pd.read_csv(path, **pandas_kwargs)


def read_csv(path, shards_per_host: Optional[int] = None, *,
             host_index: Optional[int] = None,
             num_hosts: Optional[int] = None, backend: str = "auto",
             **pandas_kwargs) -> XShards:
    """ref-parity: zoo.orca.data.pandas.read_csv."""
    return _read_files(_read_csv_one, path, shards_per_host, host_index,
                       num_hosts, backend=backend, **pandas_kwargs)


def read_json(path, shards_per_host: Optional[int] = None, *,
              host_index: Optional[int] = None,
              num_hosts: Optional[int] = None, **pandas_kwargs) -> XShards:
    """ref-parity: zoo.orca.data.pandas.read_json."""
    import pandas as pd

    return _read_files(pd.read_json, path, shards_per_host, host_index,
                       num_hosts, **pandas_kwargs)


def read_parquet(path, shards_per_host: Optional[int] = None, *,
                 host_index: Optional[int] = None,
                 num_hosts: Optional[int] = None, **pandas_kwargs) -> XShards:
    import pandas as pd

    return _read_files(pd.read_parquet, path, shards_per_host, host_index,
                       num_hosts, **pandas_kwargs)


def from_ndarrays(data, num_shards: int = 1) -> XShards:
    """In-memory ndarray/dict/tuple -> XShards (ref: XShards.partition)."""
    return XShards.partition(data, num_shards)

"""XShards — the partitioned-data currency of the framework.

Reference (SURVEY.md §2.2, ref: pyzoo/zoo/orca/data/shard.py): ``XShards`` /
``SparkXShards`` wrap an RDD of heterogeneous payloads (pandas DataFrames,
dicts of ndarrays) with ``transform_shard`` / ``collect`` / ``repartition``;
``RayXShards`` hands partitions to training-worker actors.

TPU-native re-design: there are no executor JVMs — each TPU-VM host process
holds its *local* shards in host RAM as a plain list, and the global dataset
is the union over `jax.process_count()` hosts.  Shard boundaries exist for
(a) streaming/memory granularity and (b) deterministic global sharding:
`global_shard_index = host_index * per_host + local_index`.  All transforms
are eager local maps (numpy/pandas are already C-speed; Spark's lazy DAG
bought nothing on a single host).
"""

from __future__ import annotations

import bisect
import copy
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np


def _is_pandas(x) -> bool:
    try:
        import pandas as pd

        return isinstance(x, (pd.DataFrame, pd.Series))
    except ImportError:  # pragma: no cover
        return False


def shard_len(payload) -> int:
    """Row count of one shard payload (dict-of-ndarrays | ndarray | DF)."""
    if isinstance(payload, dict):
        if not payload:
            return 0
        return shard_len(next(iter(payload.values())))
    if isinstance(payload, (list, tuple)):
        return shard_len(payload[0]) if payload else 0
    return len(payload)


class XShards:
    """A list of local shards + awareness of sibling hosts.

    API parity with the reference's SparkXShards where it makes sense:
    ``transform_shard``, ``collect``, ``num_partitions``, ``repartition``,
    ``partition`` (static constructor), ``zip``, ``split``, plus
    numpy-centric helpers the estimators use (``to_numpy_dict``,
    ``row_count``).
    """

    def __init__(self, shards: Sequence[Any], *, num_hosts: int = 1,
                 host_index: int = 0):
        self._shards: List[Any] = list(shards)
        self.num_hosts = num_hosts
        self.host_index = host_index

    # ---- constructors -------------------------------------------------

    @staticmethod
    def partition(data: Any, num_shards: Optional[int] = None) -> "XShards":
        """Split an in-memory ndarray / dict / tuple-of-ndarrays into shards
        (ref: zoo.orca.data.XShards.partition)."""
        n = num_shards or 1
        total = shard_len(data)
        n = max(1, min(n, total)) if total else 1
        bounds = np.linspace(0, total, n + 1).astype(int)

        def take(x, lo, hi):
            if isinstance(x, dict):
                return {k: take(v, lo, hi) for k, v in x.items()}
            if isinstance(x, (list, tuple)):
                return type(x)(take(v, lo, hi) for v in x)
            return x[lo:hi]

        return XShards([take(data, bounds[i], bounds[i + 1])
                        for i in range(n)])

    @staticmethod
    def from_list(records: Sequence[Any],
                  num_shards: Optional[int] = None) -> "XShards":
        """Split a flat sequence of arbitrary records (rows) into shards.

        Unlike ``partition`` — which treats list/tuple payloads as
        *columns* — this slices the sequence row-wise, for payloads like
        [(path, label), ...] or [TextFeature, ...].
        """
        records = list(records)
        n = max(1, min(num_shards or 1, len(records) or 1))
        bounds = np.linspace(0, len(records), n + 1).astype(int)
        return XShards([records[bounds[i]:bounds[i + 1]]
                        for i in range(n)])

    # ---- core ops -----------------------------------------------------

    def transform_shard(self, fn: Callable, *args) -> "XShards":
        return XShards([fn(s, *args) for s in self._shards],
                       num_hosts=self.num_hosts, host_index=self.host_index)

    def collect(self) -> List[Any]:
        """Local shards (this host's partition of the global dataset)."""
        return list(self._shards)

    def num_partitions(self) -> int:
        return len(self._shards)

    def repartition(self, num_partitions: int) -> "XShards":
        """Re-split local shards into `num_partitions` equal pieces.

        Only supports payloads we can concat (ndarray / dict / DataFrame).
        """
        merged = self._concat(self._shards)
        return XShards.partition(merged, num_partitions)._with_host(
            self.num_hosts, self.host_index)

    def zip(self, other: "XShards") -> "XShards":
        if other.num_partitions() != self.num_partitions():
            raise ValueError("zip requires equal partition counts")
        return XShards([(a, b) for a, b in zip(self._shards, other._shards)],
                       num_hosts=self.num_hosts, host_index=self.host_index)

    def split(self, weights: Sequence[float], seed: int = 0):
        """Random row-level split (e.g. train/val). Returns len(weights)
        XShards."""
        rng = np.random.default_rng(seed)
        outs: List[List[Any]] = [[] for _ in weights]
        cum = np.cumsum(np.asarray(weights, dtype=np.float64))
        cum = cum / cum[-1]
        for s in self._shards:
            n = shard_len(s)
            u = rng.random(n)
            masks = []
            lo = 0.0
            for hi in cum:
                masks.append((u >= lo) & (u < hi))
                lo = hi
            for i, m in enumerate(masks):
                outs[i].append(self._mask(s, m))
        return [XShards(o, num_hosts=self.num_hosts,
                        host_index=self.host_index) for o in outs]

    # ---- numpy/pandas bridging ---------------------------------------

    def to_numpy_dict(self) -> Dict[str, np.ndarray]:
        """Concatenate all local shards into one dict of ndarrays.

        pandas shards become {col: values}; plain ndarrays become {"x": a}.
        """
        merged = self._concat(self._shards)
        if _is_pandas(merged):
            return {c: merged[c].to_numpy() for c in merged.columns}
        if isinstance(merged, dict):
            return {k: np.asarray(v) for k, v in merged.items()}
        if isinstance(merged, (list, tuple)):
            return {f"x{i}": np.asarray(v) for i, v in enumerate(merged)}
        return {"x": np.asarray(merged)}

    def row_count(self) -> int:
        return sum(shard_len(s) for s in self._shards)

    def get_schema(self):
        """Column names of the first shard (pandas parity helper)."""
        if not self._shards:
            return None
        s = self._shards[0]
        if _is_pandas(s):
            return {"columns": list(s.columns)}
        if isinstance(s, dict):
            return {"columns": list(s.keys())}
        return None

    # ---- internals ----------------------------------------------------

    def _with_host(self, num_hosts, host_index):
        self.num_hosts, self.host_index = num_hosts, host_index
        return self

    @staticmethod
    def _mask(payload, mask):
        if isinstance(payload, dict):
            return {k: XShards._mask(v, mask) for k, v in payload.items()}
        if isinstance(payload, (list, tuple)):
            return type(payload)(XShards._mask(v, mask) for v in payload)
        return payload[mask]  # ndarray and pandas share the same indexing

    @staticmethod
    def _concat(shards: Sequence[Any]):
        if not shards:
            return {}
        first = shards[0]
        if len(shards) == 1:
            return copy.copy(first)
        if _is_pandas(first):
            import pandas as pd

            return pd.concat(shards, ignore_index=True)
        if isinstance(first, dict):
            return {k: np.concatenate([np.asarray(s[k]) for s in shards])
                    for k in first}
        if isinstance(first, (list, tuple)):
            return type(first)(
                np.concatenate([np.asarray(s[i]) for s in shards])
                for i in range(len(first)))
        return np.concatenate([np.asarray(s) for s in shards])


class SparkXShards(XShards):
    """Alias retained for reference API parity (there is no Spark here)."""

"""TextSet — distributed text pipeline: tokenize → index → shape → embed.

Reference surface (SURVEY.md §2.2; ref: Scala feature/text/TextSet.scala +
pyzoo/zoo/feature/text/text_set.py): ``TextSet.read``, chained stages
``tokenize`` / ``normalize`` / ``word2idx`` / ``shape_sequence`` /
``generate_sample``; GloVe loading for ``WordEmbedding``.

TPU re-design: host-side numpy/python (text prep is CPU work); the output
is a dict of padded int32 token matrices ready for ``device_put``. The
word-index build is a host reduction over shards instead of a Spark
``reduceByKey``.
"""

from __future__ import annotations

import re
from collections import Counter
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.data.shards import XShards

_TOKEN_RE = re.compile(r"[^\W_]+(?:'[^\W_]+)?", re.UNICODE)


def tokenize(text: str) -> List[str]:
    return _TOKEN_RE.findall(text)


def normalize(tokens: List[str]) -> List[str]:
    return [t.lower() for t in tokens]


class TextFeature:
    """One sample: raw text (+ optional label) and derived fields."""

    def __init__(self, text: str, label: Optional[int] = None):
        self.text = text
        self.label = label
        self.tokens: Optional[List[str]] = None
        self.indices: Optional[np.ndarray] = None


class TextSet:
    """ref-parity stages, eager per-shard application."""

    PAD_ID = 0
    OOV_ID = 1
    FIRST_WORD_ID = 2

    def __init__(self, shards: XShards,
                 word_index: Optional[Dict[str, int]] = None):
        self.shards = shards
        self.word_index = word_index

    # ---- constructors -------------------------------------------------

    @staticmethod
    def from_texts(texts: Sequence[str],
                   labels: Optional[Sequence[int]] = None,
                   num_shards: int = 1) -> "TextSet":
        labels = labels if labels is not None else [None] * len(texts)
        feats = [TextFeature(t, l) for t, l in zip(texts, labels)]
        return TextSet(XShards.from_list(feats, num_shards))

    @staticmethod
    def read_csv(path: str, text_col: str = "text",
                 label_col: Optional[str] = "label",
                 num_shards: int = 1) -> "TextSet":
        import pandas as pd

        df = pd.read_csv(path)
        labels = df[label_col].tolist() if label_col and label_col in df \
            else None
        return TextSet.from_texts(df[text_col].tolist(), labels, num_shards)

    # ---- stages -------------------------------------------------------

    def tokenize(self) -> "TextSet":
        def fn(feats):
            for f in feats:
                f.tokens = normalize(tokenize(f.text))
            return feats
        return TextSet(self.shards.transform_shard(fn), self.word_index)

    def word2idx(self, remove_topn: int = 0,
                 max_words_num: Optional[int] = None,
                 existing_index: Optional[Dict[str, int]] = None
                 ) -> "TextSet":
        """Build (or adopt) the word index and map tokens to ids.
        ids: 0=pad, 1=oov, 2.. = vocabulary by frequency rank."""
        if existing_index is not None:
            index = dict(existing_index)
        else:
            counts: Counter = Counter()
            for feats in self.shards.collect():
                for f in feats:
                    if f.tokens is None:
                        raise RuntimeError("call tokenize() before word2idx")
                    counts.update(f.tokens)
            ranked = [w for w, _ in counts.most_common()]
            ranked = ranked[remove_topn:]
            if max_words_num is not None:
                ranked = ranked[:max_words_num]
            index = {w: i + TextSet.FIRST_WORD_ID
                     for i, w in enumerate(ranked)}

        def fn(feats):
            for f in feats:
                f.indices = np.asarray(
                    [index.get(t, TextSet.OOV_ID) for t in f.tokens],
                    np.int32)
            return feats
        return TextSet(self.shards.transform_shard(fn), index)

    def shape_sequence(self, length: int,
                       trunc_mode: str = "pre") -> "TextSet":
        """Pad (post) / truncate (pre|post) to fixed `length`."""
        def fn(feats):
            for f in feats:
                idx = f.indices
                if len(idx) > length:
                    idx = idx[-length:] if trunc_mode == "pre" \
                        else idx[:length]
                elif len(idx) < length:
                    idx = np.concatenate(
                        [idx, np.zeros(length - len(idx), np.int32)])
                f.indices = idx
            return feats
        return TextSet(self.shards.transform_shard(fn), self.word_index)

    # ---- outputs ------------------------------------------------------

    def to_numpy_dict(self) -> Dict[str, np.ndarray]:
        toks, labels = [], []
        for feats in self.shards.collect():
            for f in feats:
                if f.indices is None:
                    raise RuntimeError(
                        "run tokenize/word2idx/shape_sequence first")
                toks.append(f.indices)
                labels.append(-1 if f.label is None else int(f.label))
        return {"tokens": np.stack(toks),
                "y": np.asarray(labels, np.int32)}

    def vocab_size(self) -> int:
        """Embedding-table rows needed: covers pad, oov and the HIGHEST
        word id (a user-supplied existing_index may be sparse, so counting
        entries would under-size the table and silently clamp gathers)."""
        if self.word_index is None:
            raise RuntimeError("word2idx not run")
        top = max(self.word_index.values(),
                  default=TextSet.FIRST_WORD_ID - 1)
        return max(top + 1, TextSet.FIRST_WORD_ID)


def load_glove(path: str, word_index: Dict[str, int],
               embed_dim: int) -> Tuple[np.ndarray, int]:
    """GloVe txt → embedding matrix aligned to `word_index`
    (ref: WordEmbedding loading). Rows 0 (pad) and 1 (oov) are zero /
    mean-init; OOV words get small random vectors. Returns (weights,
    n_hits)."""
    # size by the max id, not len(): a user-supplied index may be sparse
    vocab_rows = max(max(word_index.values(),
                         default=TextSet.FIRST_WORD_ID - 1) + 1,
                     TextSet.FIRST_WORD_ID)
    rng = np.random.default_rng(0)
    weights = rng.normal(0, 0.1, (vocab_rows, embed_dim)).astype(np.float32)
    weights[TextSet.PAD_ID] = 0.0
    hits = 0
    import io

    from analytics_zoo_tpu.common import fs

    with fs.open(path, "rb") as raw, \
            io.TextIOWrapper(raw, encoding="utf-8") as f:
        for line in f:
            parts = line.rstrip().split(" ")
            if len(parts) != embed_dim + 1:
                continue
            idx = word_index.get(parts[0])
            if idx is not None:
                weights[idx] = np.asarray(parts[1:], np.float32)
                hits += 1
    return weights, hits

"""ImageSet — distributed image pipeline with a transform chain.

Reference surface (SURVEY.md §2.2; ref: Scala feature/image/ +
pyzoo/zoo/feature/image/imageset.py, imagePreprocessing.py): ``ImageSet.
read(path)`` (local/distributed), OpenCV-backed chained transforms
(``ImageResize``, ``ImageCenterCrop``, ``ImageRandomCrop``, ``ImageHFlip``,
``ImageChannelNormalize``, ``ImageMatToTensor``), ``ImageSet.transform``.

TPU re-design: decode is host-side PIL (the reference's OpenCV JNI analog;
the C++ data plane handles raw-tensor fast paths), transforms are pure
numpy on NHWC float arrays — the TPU consumes ready [N, H, W, C] batches.
Distribution = XShards of file lists per host, not Spark partitions.
"""

from __future__ import annotations

import os
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.data.shards import XShards
from analytics_zoo_tpu.utils.transform import Chain, Transform

IMAGE_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".gif", ".webp")


# ---------------------------------------------------------------------------
# transforms (ref: ImageProcessing subclasses). Each is ndarray -> ndarray,
# image layout HWC float32 (or uint8 pre-normalize); chain with >>.
# ---------------------------------------------------------------------------

class ImageTransform(Transform):
    pass


class ChainedImageTransform(Chain, ImageTransform):
    pass


ImageTransform.chain_cls = ChainedImageTransform


def ImageResize(h: int, w: int) -> ImageTransform:
    def fn(img):
        from PIL import Image

        arr = np.asarray(img)
        pil = Image.fromarray(arr.astype(np.uint8) if arr.dtype != np.uint8
                              else arr)
        return np.asarray(pil.resize((w, h), Image.BILINEAR),
                          dtype=arr.dtype)
    return ImageTransform(fn, f"resize({h},{w})")


def ImageCenterCrop(h: int, w: int) -> ImageTransform:
    def fn(img):
        H, W = img.shape[:2]
        top, left = max(0, (H - h) // 2), max(0, (W - w) // 2)
        return img[top:top + h, left:left + w]
    return ImageTransform(fn, f"center_crop({h},{w})")


def ImageRandomCrop(h: int, w: int, seed: int = 0) -> ImageTransform:
    rng = np.random.default_rng(seed)

    def fn(img):
        H, W = img.shape[:2]
        top = int(rng.integers(0, max(1, H - h + 1)))
        left = int(rng.integers(0, max(1, W - w + 1)))
        return img[top:top + h, left:left + w]
    return ImageTransform(fn, f"random_crop({h},{w})")


def ImageHFlip(prob: float = 0.5, seed: int = 0) -> ImageTransform:
    rng = np.random.default_rng(seed)

    def fn(img):
        return img[:, ::-1] if rng.random() < prob else img
    return ImageTransform(fn, f"hflip({prob})")


def ImageChannelNormalize(*args) -> ImageTransform:
    """(mR,mG,mB[,sR,sG,sB]) — subtract means, divide stds (ref arg order)."""
    n = len(args) // 2 if len(args) >= 6 else len(args)
    means = np.asarray(args[:n], np.float32)
    stds = np.asarray(args[n:] or [1.0] * n, np.float32)

    def fn(img):
        return ((img.astype(np.float32) - means) / stds)
    return ImageTransform(fn, "channel_normalize")


def ImageMatToTensor(to_chw: bool = False) -> ImageTransform:
    """float32 conversion; TPU wants NHWC so to_chw defaults False
    (the reference's BigDL path wanted CHW)."""
    def fn(img):
        img = img.astype(np.float32)
        return img.transpose(2, 0, 1) if to_chw else img
    return ImageTransform(fn, "to_tensor")


# ---------------------------------------------------------------------------
# ImageSet
# ---------------------------------------------------------------------------

def _to_rgb(img: np.ndarray) -> np.ndarray:
    """Normalise any decoder output to 3-channel RGB.  The in-tree native
    decoder already requests RGB (JCS_RGB / PNG_FORMAT_RGB in
    dataplane.cpp), so this is a defensive shim for alternate builds:
    grayscale (1), gray+alpha (2) and RGBA (4) all map to RGB so batch
    shapes never depend on which decoder a host compiled in."""
    if img.ndim == 2:
        img = img[..., None]
    c = img.shape[-1]
    if c == 1:
        return np.repeat(img, 3, axis=-1)
    if c == 2:                      # gray + alpha: drop alpha, splat gray
        return np.repeat(img[..., :1], 3, axis=-1)
    if c == 4:
        return np.ascontiguousarray(img[..., :3])
    return img


def decode_image_bytes(raw) -> np.ndarray:
    """Encoded JPEG/PNG bytes -> RGB uint8 HWC (native C++ decode with the
    GIL released; PIL long-tail fallback). The bytes-input sibling of
    `_read_image` — serving and in-memory pipelines share it."""
    from analytics_zoo_tpu import native

    try:
        return _to_rgb(native.decode_image(raw))
    except Exception:
        import io

        from PIL import Image

        with Image.open(io.BytesIO(raw)) as im:
            return np.asarray(im.convert("RGB"))


def _read_image(path: str) -> np.ndarray:
    """Decode one image to RGB uint8 HWC.

    Prefers the C++ data plane (libjpeg/libpng, GIL released — SURVEY §2.3
    native-decode obligation); PIL covers the long tail of formats (bmp,
    gif, webp, CMYK jpegs) and hosts whose .so was built without image
    support.  Remote URIs (gs://, s3://, memory://) fetch bytes through
    common.fs and share the bytes-input decode path."""
    from analytics_zoo_tpu.common import fs

    if fs.is_remote(path):
        with fs.open(path, "rb") as f:
            return decode_image_bytes(f.read())
    from analytics_zoo_tpu import native

    try:
        return _to_rgb(native.decode_image(path))
    except Exception:
        from PIL import Image

        with Image.open(path) as im:
            return np.asarray(im.convert("RGB"))


def _read_images(paths: Sequence[str]) -> List[np.ndarray]:
    """Threaded decode: the native path releases the GIL per call, so a
    small pool gives near-linear speedup (the Spark-partition analog)."""
    if len(paths) < 4:
        return [_read_image(p) for p in paths]
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=min(8, os.cpu_count() or 4)) as ex:
        return list(ex.map(_read_image, paths))


class ImageSet:
    """A set of (image, label, path) triples backed by XShards.

    ref-parity constructors: ``read(path)`` (flat dir or one-subdir-per-
    class layout, which also yields labels), ``from_arrays``.
    """

    def __init__(self, shards: XShards,
                 class_names: Optional[List[str]] = None):
        self.shards = shards
        self.class_names = class_names

    @staticmethod
    def read(path: str, num_shards: int = 1,
             with_label: bool = False) -> "ImageSet":
        """Read images under `path` (local dir or remote gs://, s3://,
        memory:// URI). with_label: subdir name = class."""
        from analytics_zoo_tpu.common import fs

        records: List[Tuple[str, int]] = []
        class_names: Optional[List[str]] = None
        if with_label:
            class_names = sorted(
                d for d in fs.listdir(path)
                if fs.isdir(fs.join(path, d)))
            for ci, cname in enumerate(class_names):
                cdir = fs.join(path, cname)
                for f in sorted(fs.listdir(cdir)):
                    if f.lower().endswith(IMAGE_EXTS):
                        records.append((fs.join(cdir, f), ci))
        else:
            for root, _, files in fs.walk(path):
                for f in sorted(files):
                    if f.lower().endswith(IMAGE_EXTS):
                        records.append((fs.join(root, f), -1))
        if not records:
            raise FileNotFoundError(f"no images under {path}")

        def load(recs):
            return {"image": _read_images([p for p, _ in recs]),
                    "label": np.asarray([l for _, l in recs], np.int32),
                    "path": [p for p, _ in recs]}

        shards = XShards.from_list(records, num_shards).transform_shard(load)
        return ImageSet(shards, class_names)

    @staticmethod
    def from_arrays(images: np.ndarray,
                    labels: Optional[np.ndarray] = None,
                    num_shards: int = 1) -> "ImageSet":
        labels = labels if labels is not None else \
            np.full(len(images), -1, np.int32)
        records = list(zip(list(images), np.asarray(labels)))

        def pack(recs):
            return {"image": [im for im, _ in recs],
                    "label": np.asarray([l for _, l in recs], np.int32),
                    "path": [""] * len(recs)}

        return ImageSet(
            XShards.from_list(records, num_shards).transform_shard(pack))

    def transform(self, t: ImageTransform) -> "ImageSet":
        def apply(shard):
            return {**shard, "image": [t(im) for im in shard["image"]]}
        return ImageSet(self.shards.transform_shard(apply),
                        self.class_names)

    def to_numpy_dict(self):
        """Stack into {'x': [N,H,W,C] f32, 'y': [N]} for the estimators.
        Requires uniform image shapes (apply Resize/Crop first)."""
        merged = {}
        for shard in self.shards.collect():
            for k, v in shard.items():
                merged.setdefault(k, []).extend(
                    v if isinstance(v, list) else list(v))
        x = np.stack(merged["image"]).astype(np.float32)
        return {"x": x, "y": np.asarray(merged["label"], np.int32)}

    def get_image(self) -> List[np.ndarray]:
        out = []
        for shard in self.shards.collect():
            out.extend(shard["image"])
        return out

from analytics_zoo_tpu.data.shards import XShards, SparkXShards, shard_len
from analytics_zoo_tpu.data.readers import (
    read_csv, read_json, read_parquet, from_ndarrays)
from analytics_zoo_tpu.data.loader import (
    NumpyBatchIterator, shards_to_iterator, make_global_batch,
    device_prefetch, DataCreator)
from analytics_zoo_tpu.data.feature_set import FeatureSet, DiskFeatureSet

# reference-parity namespace: zoo.orca.data.pandas.read_csv
from analytics_zoo_tpu.data import readers as pandas  # noqa: F401

__all__ = [
    "XShards", "SparkXShards", "shard_len",
    "read_csv", "read_json", "read_parquet", "from_ndarrays",
    "NumpyBatchIterator", "shards_to_iterator", "make_global_batch",
    "device_prefetch", "DataCreator", "pandas",
    "FeatureSet", "DiskFeatureSet",
]

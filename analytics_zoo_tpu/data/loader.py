"""Host->HBM batch pipeline: shuffle, batch, shard, prefetch.

Replaces the reference's FeatureSet/DataSet minibatch stream and the
per-backend loader glue (SURVEY.md §2.2: Scala feature/dataset/ DRAM/PMEM
tiers; pyzoo/zoo/tfpark/tf_dataset.py; orca data-creator contract).

TPU shape of the problem: the hot loop consumes one *globally-sharded* batch
per step.  Each host materialises only its local rows (its XShards), and
`jax.make_array_from_process_local_data` assembles the global jax.Array over
the mesh's batch axes.  A small prefetch deque overlaps host-side batch
assembly + H2D transfer with device compute (the DRAM->HBM double-buffer
analog of FeatureSet's memory tiers).
"""

from __future__ import annotations

from functools import lru_cache as _functools_cache
from typing import Any, Callable, Dict, Iterator, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from analytics_zoo_tpu.common.context import \
    effective_process_count as _nhosts
from analytics_zoo_tpu.data.shards import XShards, shard_len
from analytics_zoo_tpu.parallel.partition import data_sharding


class NumpyBatchIterator:
    """Epoch iterator over a dict of host-local ndarrays.

    Yields dicts of ndarrays with leading dim = per-host batch size.
    Shuffles with a per-epoch seed (deterministic-data-order mode is then
    just a fixed seed — the reference's implicit Spark-partition order was
    not even reproducible; SURVEY.md §5 race-detection notes).
    """

    def __init__(self, arrays: Dict[str, np.ndarray], batch_size: int, *,
                 shuffle: bool = True, drop_remainder: bool = True,
                 seed: int = 0):
        if not arrays:
            raise ValueError("empty arrays dict")
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        lens = {k: len(v) for k, v in arrays.items()}
        if len(set(lens.values())) != 1:
            raise ValueError(f"ragged arrays: {lens}")
        self.arrays = arrays
        self.n = next(iter(lens.values()))
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_remainder = drop_remainder
        self.seed = seed
        self.epoch = 0
        if batch_size > self.n:
            raise ValueError(
                f"per-host batch {batch_size} > host rows {self.n}")

    def steps_per_epoch(self) -> int:
        if self.drop_remainder:
            return self.n // self.batch_size
        return -(-self.n // self.batch_size)

    def epoch_batches(self) -> Iterator[Dict[str, np.ndarray]]:
        end = (self.n // self.batch_size) * self.batch_size \
            if self.drop_remainder else self.n
        if self.shuffle:
            # permute ONCE per epoch per column, then serve contiguous
            # zero-copy slices — measured 1.8x the per-batch fancy-index
            # gather (and the per-step critical path drops to a view).
            # Cost: one transient dataset copy per epoch, the standard
            # DRAM-tier time-memory trade (BASELINE.md NCF profile).
            rng = np.random.default_rng(self.seed + self.epoch)
            idx = rng.permutation(self.n)
            arrays = {k: v[idx] for k, v in self.arrays.items()}
        else:
            arrays = self.arrays
        for lo in range(0, end, self.batch_size):
            yield {k: v[lo:lo + self.batch_size] for k, v in arrays.items()}
        self.epoch += 1


def shards_to_iterator(shards: XShards, per_host_batch: int,
                       **kw) -> NumpyBatchIterator:
    return NumpyBatchIterator(shards.to_numpy_dict(), per_host_batch, **kw)


def make_global_batch(mesh: Mesh, batch: Dict[str, np.ndarray],
                      sharding: Optional[NamedSharding] = None,
                      pack: bool = False) -> Dict[str, jax.Array]:
    """Host-local batch dict -> globally-sharded jax.Array dict.

    ``pack=True`` ships the whole batch as ONE row-major uint8 buffer
    (one ``device_put``/assembly instead of one per column) and unpacks
    on-device via slice + bitcast.  Each transfer has a fixed dispatch
    cost — per-call runtime overhead, and a full round-trip latency on
    tunneled devices — so for many-column batches (recommenders: user,
    item, label, ...) packing collapses k fixed costs into one.  The
    pack itself is a single host memcpy at DRAM bandwidth.
    """
    sh = sharding or data_sharding(mesh)
    if pack:
        packed = _pack_rows(batch)
        if packed is not None:
            buf, spec = packed
            if _nhosts() == 1:
                gbuf = jax.device_put(buf, sh)
            else:
                gbuf = jax.make_array_from_process_local_data(sh, buf)
            if gbuf.shape[0] != buf.shape[0]:
                # multihost: the assembled array holds GLOBAL rows (local
                # x data-shard groups); globalise the spec's leading dims
                spec = tuple(
                    (k, (gbuf.shape[0],) + shape[1:], dt, rb)
                    for (k, shape, dt, rb) in spec)
            return _unpacker(spec)(gbuf)
    if _nhosts() == 1:
        return {k: jax.device_put(v, sh) for k, v in batch.items()}
    return {k: jax.make_array_from_process_local_data(sh, v)
            for k, v in batch.items()}


def _pack_rows(batch: Dict[str, np.ndarray]):
    """Pack columns (all sharing leading dim B) into a [B, total_row_bytes]
    uint8 buffer + a static spec for on-device unpacking.  Returns None if
    the batch can't be packed (mismatched leading dims)."""
    cols = []
    spec = []
    B = None
    for k, v in batch.items():
        v = np.asarray(v)
        # match device_put semantics under disabled x64: 64-bit dtypes
        # canonicalize to their 32-bit counterparts BEFORE byte-packing
        canon = jax.dtypes.canonicalize_dtype(v.dtype)
        v = np.ascontiguousarray(v, dtype=canon)
        if B is None:
            B = v.shape[0]
        if v.ndim == 0 or v.shape[0] != B:
            return None
        rows = v.view(np.uint8).reshape(B, -1)
        spec.append((k, v.shape, v.dtype.str, rows.shape[1]))
        cols.append(rows)
    if not cols:
        return None
    return np.concatenate(cols, axis=1), tuple(spec)


@_functools_cache
def _unpacker(spec):
    """Jitted on-device unpack for a packed-row buffer: per column, slice
    its byte range and bitcast back to the original dtype/shape.  Row
    sharding (dp over dim 0) propagates through — no reshard."""
    from jax import lax

    def unpack(buf):
        out = {}
        off = 0
        for name, shape, dtypestr, rowbytes in spec:
            dt = np.dtype(dtypestr)
            sl = lax.slice_in_dim(buf, off, off + rowbytes, axis=1)
            off += rowbytes
            if dt == np.bool_:
                arr = sl.reshape(shape) != 0
            elif dt.itemsize == 1:
                arr = lax.bitcast_convert_type(sl, dt).reshape(shape)
            else:
                arr = lax.bitcast_convert_type(
                    sl.reshape(shape[0], -1, dt.itemsize), dt)
                arr = arr.reshape(shape)
            out[name] = arr
        return out

    return jax.jit(unpack)


def device_prefetch(batches: Iterator[Dict[str, np.ndarray]], mesh: Mesh, *,
                    depth: int = 3,
                    sharding: Optional[NamedSharding] = None,
                    pack: bool = False
                    ) -> Iterator[Dict[str, jax.Array]]:
    """Overlap H2D transfer with compute: keep `depth` batches in flight,
    staged by a background thread.

    ``device_put`` is nominally async, but on tunneled/remote devices the
    call itself blocks for the full transfer — staged on the consumer
    thread, every step would pay transfer + compute SERIALLY.  A worker
    thread turns the transfer into true double-buffering: it fills a
    bounded queue (depth = HBM staging bound) while the main thread
    dispatches compute.  numpy gather + device_put release the GIL for the
    copy, so the threads genuinely overlap.

    On the CPU backend the transfer is a host memcpy — there is nothing
    to overlap — and a ``device_put`` issued from a second thread can
    deadlock against a concurrently-executing jitted program in the XLA
    CPU client (observed on forced multi-device hosts: worker pinned in
    ``device_put``, consumer pinned in the jit step, indefinitely), so
    stage inline on the consumer thread there.
    """
    import queue as _queue
    import threading

    sh = sharding or data_sharding(mesh)
    if jax.default_backend() == "cpu":
        for b in batches:
            yield make_global_batch(mesh, b, sh, pack=pack)
        return
    q: _queue.Queue = _queue.Queue(maxsize=max(1, depth))
    stop = threading.Event()
    _END = object()

    def worker():
        try:
            for b in batches:
                if stop.is_set():
                    return
                q.put(make_global_batch(mesh, b, sh, pack=pack))
            q.put(_END)
        except BaseException as e:  # surface reader errors to the consumer
            q.put(e)

    t = threading.Thread(target=worker, daemon=True,
                         name="zoo-device-prefetch")
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
        # unblock the worker if it is waiting on a full queue
        while t.is_alive():
            try:
                q.get_nowait()
            except _queue.Empty:
                t.join(timeout=0.1)


class DataCreator:
    """The reference's data-creator contract (SURVEY.md §2.2: estimators
    accept ``data_creator(config) -> loader``).  Anything acceptable to
    `Estimator.fit` normalises through here: XShards, dict of ndarrays,
    (x, y) tuples, or a callable(config) returning one of those."""

    @staticmethod
    def to_arrays(data: Any, config: Optional[dict] = None,
                  feature_cols: Optional[Sequence[str]] = None,
                  label_cols: Optional[Sequence[str]] = None
                  ) -> Dict[str, np.ndarray]:
        if callable(data):
            data = data(config or {})
        # TFDataset bridging adapter (tfpark surface; duck-typed — also
        # covers subclasses — to keep the data layer import-free of tfpark)
        if not isinstance(data, dict) and callable(
                getattr(data, "to_arrays", None)):
            data = data.to_arrays()
        # FeatureSet tiers (import locally — feature_set imports loader)
        from analytics_zoo_tpu.data import feature_set as _fs
        if isinstance(data, _fs.DiskFeatureSet):
            data = data.to_dram()       # eval/predict paths materialise
        if isinstance(data, _fs.FeatureSet):
            d = dict(data.arrays)
        elif isinstance(data, XShards):
            d = data.to_numpy_dict()
        elif isinstance(data, dict):
            d = {k: np.asarray(v) for k, v in data.items()}
        elif isinstance(data, (tuple, list)) and len(data) == 2:
            x, y = data
            d = {}
            if isinstance(x, dict):
                d.update({k: np.asarray(v) for k, v in x.items()})
            else:
                d["x"] = np.asarray(x)
            if isinstance(y, dict):
                d.update({k: np.asarray(v) for k, v in y.items()})
            else:
                d["y"] = np.asarray(y)
        else:
            raise TypeError(f"unsupported data type {type(data)}")
        if feature_cols or label_cols:
            sel = {}
            for c in list(feature_cols or []) + list(label_cols or []):
                if c not in d:
                    raise KeyError(f"column {c!r} not in data "
                                   f"(have {sorted(d)})")
                sel[c] = d[c]
            d = sel
        return d

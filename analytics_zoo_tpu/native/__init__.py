"""ctypes bindings for the native host data plane (``dataplane.cpp``).

The reference's native layer is JNI-bound C++ (SURVEY.md §2.3: OpenVINO
`libzoo_inference`-style .so, memkind/PMEM FeatureSet tier, OpenCV ops —
ref: zoo/pipeline/inference/, zoo feature/pmem/).  pybind11 is not in this
image, so the rebuild binds via a pure C ABI + ctypes.  The shared object is
compiled from source on first use with g++ (cached next to the source,
keyed on source mtime), mirroring how the reference ships `make-dist.sh`
built artifacts.

Exposed wrappers:
  RingBuffer           bounded byte queue; blocking push/pop release the GIL
  read_csv_native      multithreaded numeric CSV -> dict[str, np.ndarray]
  RecordWriter/Reader  ZREC length-prefixed record file, mmap zero-copy read
  Prefetcher           C++ thread streaming records into a RingBuffer
  pack_batch/unpack_batch   tensor-dict <-> bytes codec for ZREC payloads
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "dataplane.cpp")
_SO = os.path.join(_HERE, "libzoo_dataplane.so")

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


class NativeUnavailable(RuntimeError):
    """Raised when the .so cannot be built (no g++) — callers fall back."""


def _build_so() -> str:
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return _SO
    # PID-unique tmp + atomic replace: concurrent first-use builds (multiple
    # worker processes, shared FS) must not corrupt each other's output.
    tmp = f"{_SO}.{os.getpid()}.tmp"
    base = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
            _SRC, "-o", tmp]
    # image decode needs system libjpeg/libpng; retry without if absent so
    # the tensor data plane still builds on minimal hosts
    attempts = [base + ["-DZOO_WITH_IMAGE", "-ljpeg", "-lpng"], base]
    last_err = ""
    for cmd in attempts:
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
            os.replace(tmp, _SO)
            return _SO
        except FileNotFoundError as e:
            raise NativeUnavailable(f"g++ not found: {e}") from e
        except subprocess.CalledProcessError as e:
            last_err = e.stderr[-2000:]
    raise NativeUnavailable(f"native build failed:\n{last_err}")


def load_lib() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        lib = ctypes.CDLL(_build_so())
        c = ctypes.c_void_p, ctypes.c_long, ctypes.c_int, ctypes.c_size_t
        P, L, I, S = c
        lib.zrb_create.restype = P
        lib.zrb_create.argtypes = [S, L]
        lib.zrb_destroy.argtypes = [P]
        lib.zrb_close.argtypes = [P]
        lib.zrb_push.restype = I
        lib.zrb_push.argtypes = [P, ctypes.c_void_p, S, I]
        lib.zrb_peek_len.restype = L
        lib.zrb_peek_len.argtypes = [P, I]
        lib.zrb_pop.restype = L
        lib.zrb_pop.argtypes = [P, ctypes.c_void_p, S, I]
        lib.zrb_depth.restype = L
        lib.zrb_depth.argtypes = [P]
        lib.zrb_bytes.restype = L
        lib.zrb_bytes.argtypes = [P]
        lib.zdp_last_error.restype = ctypes.c_char_p
        lib.zcsv_open.restype = P
        lib.zcsv_open.argtypes = [ctypes.c_char_p, I]
        lib.zcsv_nrows.restype = L
        lib.zcsv_nrows.argtypes = [P]
        lib.zcsv_ncols.restype = I
        lib.zcsv_ncols.argtypes = [P]
        lib.zcsv_col_name.restype = ctypes.c_char_p
        lib.zcsv_col_name.argtypes = [P, I]
        lib.zcsv_col_is_int.restype = I
        lib.zcsv_col_is_int.argtypes = [P, I]
        lib.zcsv_col_data.restype = ctypes.POINTER(ctypes.c_double)
        lib.zcsv_col_data.argtypes = [P, I]
        lib.zcsv_col_idata.restype = ctypes.POINTER(ctypes.c_int64)
        lib.zcsv_col_idata.argtypes = [P, I]
        lib.zcsv_close.argtypes = [P]
        lib.zrec_writer_open.restype = P
        lib.zrec_writer_open.argtypes = [ctypes.c_char_p]
        lib.zrec_write.restype = L
        lib.zrec_write.argtypes = [P, ctypes.c_void_p, S]
        lib.zrec_writer_close.restype = I
        lib.zrec_writer_close.argtypes = [P]
        lib.zrec_open.restype = P
        lib.zrec_open.argtypes = [ctypes.c_char_p]
        lib.zrec_count.restype = L
        lib.zrec_count.argtypes = [P]
        lib.zrec_len.restype = L
        lib.zrec_len.argtypes = [P, L]
        lib.zrec_ptr.restype = ctypes.c_void_p
        lib.zrec_ptr.argtypes = [P, L]
        lib.zrec_read.restype = L
        lib.zrec_read.argtypes = [P, L, ctypes.c_void_p, S]
        lib.zrec_close.argtypes = [P]
        lib.zpf_start.restype = P
        lib.zpf_start.argtypes = [P, P, ctypes.POINTER(ctypes.c_long), L, I]
        lib.zpf_stop.argtypes = [P]
        # image decode symbols are absent when the .so was built without
        # libjpeg/libpng (ZOO_WITH_IMAGE unset)
        try:
            lib.zimg_decode.restype = ctypes.POINTER(ctypes.c_ubyte)
            lib.zimg_decode.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_long),
                ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_int)]
            lib.zimg_decode_mem.restype = ctypes.POINTER(ctypes.c_ubyte)
            lib.zimg_decode_mem.argtypes = [
                ctypes.c_void_p, S, ctypes.POINTER(ctypes.c_long),
                ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_int)]
            lib.zimg_free.argtypes = [ctypes.POINTER(ctypes.c_ubyte)]
        except AttributeError:
            pass
        _lib = lib
        return lib


def available() -> bool:
    try:
        load_lib()
        return True
    except NativeUnavailable:
        return False


def _err() -> str:
    return load_lib().zdp_last_error().decode()


# ---------------------------------------------------------------------------
# Ring buffer
# ---------------------------------------------------------------------------

class RingBuffer:
    """Bounded byte queue backed by the C++ condvar ring (single consumer)."""

    def __init__(self, capacity_bytes: int = 64 << 20, max_items: int = 0):
        self._lib = load_lib()
        self._h = self._lib.zrb_create(capacity_bytes, max_items)

    def push(self, data: bytes, timeout: float = -1) -> bool:
        rc = self._lib.zrb_push(self._h, data, len(data),
                                int(timeout * 1000) if timeout >= 0 else -1)
        if rc == -2:
            raise RuntimeError("ring buffer closed")
        if rc == -3:
            raise ValueError("item larger than ring capacity")
        return rc == 0

    def pop(self, timeout: float = -1) -> Optional[bytes]:
        """Next item, or None when the ring is closed and drained."""
        ms = int(timeout * 1000) if timeout >= 0 else -1
        while True:
            n = self._lib.zrb_peek_len(self._h, ms)
            if n == -2:
                return None
            if n == -1:
                raise TimeoutError("ring buffer pop timed out")
            buf = ctypes.create_string_buffer(int(n))
            got = self._lib.zrb_pop(self._h, buf, int(n), ms)
            if got == -2:
                return None
            if got == -3:
                continue  # a different (larger) item won the race; re-peek
            if got == -1:
                raise TimeoutError("ring buffer pop timed out")
            return buf.raw[:got]

    def close(self):
        self._lib.zrb_close(self._h)

    def depth(self) -> int:
        return self._lib.zrb_depth(self._h)

    def nbytes(self) -> int:
        return self._lib.zrb_bytes(self._h)

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.zrb_destroy(self._h)
            self._h = None


# ---------------------------------------------------------------------------
# CSV
# ---------------------------------------------------------------------------

def read_csv_native(path: str, n_threads: int = 0) -> Dict[str, np.ndarray]:
    """Parse an all-numeric CSV (header required) into column arrays.

    Column dtypes match pandas: int64 when every field is an integer
    literal, float64 otherwise (empty fields -> NaN force float64).
    Raises ValueError on non-numeric content or duplicate header names —
    callers (data.readers) fall back to pandas for those files.
    """
    lib = load_lib()
    h = lib.zcsv_open(os.fspath(path).encode(), n_threads)
    if not h:
        raise ValueError(f"native csv parse failed for {path}: {_err()}")
    try:
        nrows = lib.zcsv_nrows(h)
        ncols = lib.zcsv_ncols(h)
        names = [lib.zcsv_col_name(h, i).decode() for i in range(ncols)]
        if len(set(names)) != ncols:
            raise ValueError(
                f"duplicate column names in {path}: {names} "
                "(pandas fallback handles de-duplication)")
        out: Dict[str, np.ndarray] = {}
        for i, name in enumerate(names):
            if lib.zcsv_col_is_int(h, i):
                ptr, dt = lib.zcsv_col_idata(h, i), np.int64
            else:
                ptr, dt = lib.zcsv_col_data(h, i), np.float64
            if nrows:
                out[name] = np.ctypeslib.as_array(ptr, shape=(nrows,)).copy()
            else:
                out[name] = np.empty(0, dt)
        return out
    finally:
        lib.zcsv_close(h)


# ---------------------------------------------------------------------------
# Image decode (SURVEY §2.3 native obligation: host-side C++ decode)
# ---------------------------------------------------------------------------

def image_available() -> bool:
    """True when the .so was built with libjpeg/libpng support."""
    try:
        return hasattr(load_lib(), "zimg_decode")
    except NativeUnavailable:
        return False


def decode_image(path_or_bytes) -> np.ndarray:
    """Decode a JPEG/PNG to an RGB uint8 HWC array via the C++ data plane.

    The decode runs with the GIL released (ctypes), so threading over
    files gives real parallelism — the Spark-partition-decode analog.
    Raises ValueError on undecodable input, NativeUnavailable when the
    library lacks image support (callers fall back to PIL).
    """
    lib = load_lib()
    if not hasattr(lib, "zimg_decode"):
        raise NativeUnavailable("built without libjpeg/libpng")
    h = ctypes.c_long()
    w = ctypes.c_long()
    c = ctypes.c_int()
    if isinstance(path_or_bytes, (bytes, bytearray, memoryview)):
        buf = bytes(path_or_bytes)
        ptr = lib.zimg_decode_mem(buf, len(buf),
                                  ctypes.byref(h), ctypes.byref(w),
                                  ctypes.byref(c))
    else:
        ptr = lib.zimg_decode(os.fspath(path_or_bytes).encode(),
                              ctypes.byref(h), ctypes.byref(w),
                              ctypes.byref(c))
    if not ptr:
        raise ValueError(f"native image decode failed: {_err()}")
    try:
        n = h.value * w.value * c.value
        arr = np.ctypeslib.as_array(ptr, shape=(n,)).copy()
        return arr.reshape(h.value, w.value, c.value)
    finally:
        lib.zimg_free(ptr)


# ---------------------------------------------------------------------------
# Record store
# ---------------------------------------------------------------------------

class RecordWriter:
    def __init__(self, path: str):
        self._lib = load_lib()
        self._h = self._lib.zrec_writer_open(os.fspath(path).encode())
        if not self._h:
            raise IOError(_err())

    def write(self, data: bytes) -> int:
        idx = self._lib.zrec_write(self._h, data, len(data))
        if idx < 0:
            raise IOError(_err())
        return idx

    def close(self):
        if self._h:
            if self._lib.zrec_writer_close(self._h) != 0:
                self._h = None
                raise IOError(_err())
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class RecordReader:
    def __init__(self, path: str):
        self._lib = load_lib()
        self._h = self._lib.zrec_open(os.fspath(path).encode())
        if not self._h:
            raise IOError(_err())

    def __len__(self) -> int:
        return self._lib.zrec_count(self._h)

    def get(self, i: int) -> memoryview:
        """Zero-copy view into the mmap'd file (valid until close)."""
        n = self._lib.zrec_len(self._h, i)
        if n < 0:
            raise IndexError(i)
        ptr = self._lib.zrec_ptr(self._h, i)
        return memoryview((ctypes.c_char * n).from_address(ptr)) \
            if n else memoryview(b"")

    def get_bytes(self, i: int) -> bytes:
        return bytes(self.get(i))

    def close(self):
        if getattr(self, "_h", None):
            self._lib.zrec_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        self.close()


class Prefetcher:
    """C++ reader thread streaming records (given order) into a RingBuffer."""

    def __init__(self, reader: RecordReader, ring: RingBuffer,
                 order: Sequence[int], loop: bool = False):
        self._lib = load_lib()
        self._reader = reader   # keep alive
        self._ring = ring
        arr = (ctypes.c_long * len(order))(*order)
        self._h = self._lib.zpf_start(reader._h, ring._h, arr, len(order),
                                      1 if loop else 0)

    def stop(self):
        if getattr(self, "_h", None):
            self._lib.zpf_stop(self._h)
            self._h = None

    def __del__(self):
        self.stop()


# ---------------------------------------------------------------------------
# Tensor-dict <-> bytes codec (ZREC payload format)
# ---------------------------------------------------------------------------
# record := u32 n_arrays | n_arrays * [u16 name_len | name_utf8 |
#           u8 dtype_code_len | dtype_str | u8 ndim | u64*ndim shape |
#           u64 nbytes | raw little-endian bytes]

def pack_batch(batch: Dict[str, np.ndarray]) -> bytes:
    parts: List[bytes] = [struct.pack("<I", len(batch))]
    for name, a in batch.items():
        a = np.ascontiguousarray(a)
        nb = name.encode()
        dt = a.dtype.str.encode()  # e.g. b'<f4'
        parts.append(struct.pack("<H", len(nb)))
        parts.append(nb)
        parts.append(struct.pack("<B", len(dt)))
        parts.append(dt)
        parts.append(struct.pack("<B", a.ndim))
        parts.append(struct.pack(f"<{a.ndim}Q", *a.shape) if a.ndim else b"")
        raw = a.tobytes()
        parts.append(struct.pack("<Q", len(raw)))
        parts.append(raw)
    return b"".join(parts)


def peek_batch_rows(data) -> int:
    """Row count (first array's leading dim) of a packed batch, reading only
    the first header — no array payload is copied, so scanning every block
    of a mmap'd ZREC file at open is cheap."""
    mv = memoryview(data)
    (n,) = struct.unpack_from("<I", mv, 0)
    if not n:
        return 0
    off = 4
    (nlen,) = struct.unpack_from("<H", mv, off); off += 2 + nlen
    (dlen,) = struct.unpack_from("<B", mv, off); off += 1 + dlen
    (ndim,) = struct.unpack_from("<B", mv, off); off += 1
    if not ndim:
        return 1
    (rows,) = struct.unpack_from("<Q", mv, off)
    return rows


def unpack_batch(data) -> Dict[str, np.ndarray]:
    mv = memoryview(data)
    (n,) = struct.unpack_from("<I", mv, 0)
    off = 4
    out: Dict[str, np.ndarray] = {}
    for _ in range(n):
        (nlen,) = struct.unpack_from("<H", mv, off); off += 2
        name = bytes(mv[off:off + nlen]).decode(); off += nlen
        (dlen,) = struct.unpack_from("<B", mv, off); off += 1
        dt = bytes(mv[off:off + dlen]).decode(); off += dlen
        (ndim,) = struct.unpack_from("<B", mv, off); off += 1
        shape = struct.unpack_from(f"<{ndim}Q", mv, off) if ndim else ()
        off += 8 * ndim
        (nbytes,) = struct.unpack_from("<Q", mv, off); off += 8
        a = np.frombuffer(mv[off:off + nbytes], dtype=dt).reshape(shape)
        off += nbytes
        out[name] = a.copy()  # own the memory (mv may be ring-buffer scratch)
    return out

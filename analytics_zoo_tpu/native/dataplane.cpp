// zoo_dataplane: host-side native data plane for the TPU rebuild.
//
// Reference obligation (SURVEY.md §2.3 "Native (C++/JNI) component list"):
// analytics-zoo's native layer is MKL-DNN/TF-JNI/libtorch-JNI/OpenVINO/
// memkind-PMEM (ref: zoo/pipeline/inference/, zoo feature/pmem/).  The TPU
// rebuild keeps compute native via XLA; *this* module is the host data plane
// that replaces Spark's parallel ingest + the FeatureSet DRAM/PMEM tiers
// (ref: zoo feature/dataset/, feature/pmem/ArrayLike over memkind):
//
//   1. zrb_*  — bounded byte ring buffer (condvar-blocking MPSC) used to
//               hand off batches from a native reader thread to the Python
//               consumer; calls block with the GIL released (ctypes).
//   2. zcsv_* — multithreaded numeric CSV parser (chunk at newline
//               boundaries, strtod per field) -> column-major double arrays.
//               Replaces Spark's parallel csv ingest for the numeric tables
//               the reference's recommendation/timeseries pipelines use.
//   3. zrec_* — length-prefixed record file with u64 index footer, mmap'd
//               zero-copy reads.  The DiskFeatureSet / ArrayRecord analog.
//   4. zpf_*  — background std::thread streaming records (in caller-given
//               order, optionally looping) from a zrec file into a zrb ring:
//               file IO + memcpy overlap JAX device compute.
//
// C ABI only — consumed from Python via ctypes (no pybind11 in this image).

#include <atomic>
#include <cerrno>
#include <chrono>
#include <cmath>
#include <condition_variable>
#include <functional>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

thread_local std::string g_last_error;

void set_error(const std::string &msg) { g_last_error = msg; }

// ---------------------------------------------------------------------------
// 1. Ring buffer
// ---------------------------------------------------------------------------

struct RingBuffer {
  size_t capacity_bytes;
  size_t max_items;
  std::deque<std::vector<uint8_t>> items;
  size_t bytes = 0;
  bool closed = false;
  std::mutex mu;
  std::condition_variable not_full;
  std::condition_variable not_empty;
};

bool wait_pred(std::unique_lock<std::mutex> &lk, std::condition_variable &cv,
               int timeout_ms, const std::function<bool()> &pred) {
  if (timeout_ms < 0) {
    cv.wait(lk, pred);
    return true;
  }
  return cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), pred);
}

}  // namespace

extern "C" {

void *zrb_create(size_t capacity_bytes, long max_items) {
  auto *rb = new RingBuffer();
  rb->capacity_bytes = capacity_bytes ? capacity_bytes : SIZE_MAX;
  rb->max_items = max_items > 0 ? (size_t)max_items : SIZE_MAX;
  return rb;
}

void zrb_destroy(void *h) { delete static_cast<RingBuffer *>(h); }

void zrb_close(void *h) {
  auto *rb = static_cast<RingBuffer *>(h);
  std::lock_guard<std::mutex> lk(rb->mu);
  rb->closed = true;
  rb->not_empty.notify_all();
  rb->not_full.notify_all();
}

// 0 ok; -1 timeout; -2 closed; -3 item larger than capacity.
int zrb_push(void *h, const void *data, size_t len, int timeout_ms) {
  auto *rb = static_cast<RingBuffer *>(h);
  if (len > rb->capacity_bytes) return -3;
  std::unique_lock<std::mutex> lk(rb->mu);
  bool ok = wait_pred(lk, rb->not_full, timeout_ms, [&] {
    return rb->closed || (rb->bytes + len <= rb->capacity_bytes &&
                          rb->items.size() < rb->max_items);
  });
  if (rb->closed) return -2;
  if (!ok) return -1;
  rb->items.emplace_back((const uint8_t *)data, (const uint8_t *)data + len);
  rb->bytes += len;
  rb->not_empty.notify_one();
  return 0;
}

// Length of the next item (>=0); -1 timeout; -2 closed and drained.
long zrb_peek_len(void *h, int timeout_ms) {
  auto *rb = static_cast<RingBuffer *>(h);
  std::unique_lock<std::mutex> lk(rb->mu);
  bool ok = wait_pred(lk, rb->not_empty, timeout_ms,
                      [&] { return rb->closed || !rb->items.empty(); });
  if (!rb->items.empty()) return (long)rb->items.front().size();
  if (rb->closed) return -2;
  (void)ok;
  return -1;
}

// Bytes written (>=0); -1 timeout; -2 closed+drained; -3 out too small.
long zrb_pop(void *h, void *out, size_t out_cap, int timeout_ms) {
  auto *rb = static_cast<RingBuffer *>(h);
  std::unique_lock<std::mutex> lk(rb->mu);
  wait_pred(lk, rb->not_empty, timeout_ms,
            [&] { return rb->closed || !rb->items.empty(); });
  if (rb->items.empty()) return rb->closed ? -2 : -1;
  auto &front = rb->items.front();
  if (front.size() > out_cap) return -3;
  size_t n = front.size();
  std::memcpy(out, front.data(), n);
  rb->bytes -= n;
  rb->items.pop_front();
  rb->not_full.notify_one();
  return (long)n;
}

long zrb_depth(void *h) {
  auto *rb = static_cast<RingBuffer *>(h);
  std::lock_guard<std::mutex> lk(rb->mu);
  return (long)rb->items.size();
}

long zrb_bytes(void *h) {
  auto *rb = static_cast<RingBuffer *>(h);
  std::lock_guard<std::mutex> lk(rb->mu);
  return (long)rb->bytes;
}

const char *zdp_last_error() { return g_last_error.c_str(); }

}  // extern "C"

// ---------------------------------------------------------------------------
// 2. CSV parser
// ---------------------------------------------------------------------------

namespace {

// A column being parsed: integer storage while every field looks like an
// int64 (pandas dtype parity — "1" is int64, "1.0"/""/NaN promote the whole
// column to float64), with lossless int64 precision via strtoll.
struct ColBuf {
  std::vector<int64_t> ivals;
  std::vector<double> dvals;
  bool is_int = true;

  void promote() {
    if (!is_int) return;
    dvals.reserve(ivals.size());
    for (int64_t v : ivals) dvals.push_back((double)v);
    ivals.clear();
    is_int = false;
  }
  void push_double(double v) {
    promote();
    dvals.push_back(v);
  }
  size_t size() const { return is_int ? ivals.size() : dvals.size(); }
};

struct CsvTable {
  std::vector<std::string> names;
  std::vector<ColBuf> cols;  // column-major
  long nrows = 0;
  std::string error;
};

bool looks_int(const char *buf, size_t n) {
  size_t i = (buf[0] == '+' || buf[0] == '-') ? 1 : 0;
  if (i == n) return false;
  for (; i < n; ++i)
    if (buf[i] < '0' || buf[i] > '9') return false;
  return true;
}

// Parse [begin, end) — full lines only — into ncols column buffers.
// Returns false on malformed / non-numeric input.
bool parse_chunk(const char *begin, const char *end, size_t ncols,
                 std::vector<ColBuf> &cols, std::string &err) {
  cols.assign(ncols, {});
  const char *p = begin;
  while (p < end) {
    const char *eol = (const char *)memchr(p, '\n', end - p);
    const char *line_end = eol ? eol : end;
    // tolerate CRLF and blank trailing lines
    const char *le = line_end;
    if (le > p && le[-1] == '\r') --le;
    if (le > p) {
      size_t c = 0;
      const char *f = p;
      while (true) {
        const char *comma = (const char *)memchr(f, ',', le - f);
        const char *fe = comma ? comma : le;
        if (c >= ncols) {
          err = "row has more fields than header";
          return false;
        }
        if (fe == f) {
          cols[c].push_double(NAN);  // empty field (pandas: NaN -> float64)
        } else {
          char *parse_end = nullptr;
          // strto* need NUL-terminated; fields are short — copy to buf.
          char buf[64];
          size_t n = (size_t)(fe - f);
          if (n >= sizeof(buf)) {
            err = "field too long for numeric parse";
            return false;
          }
          std::memcpy(buf, f, n);
          buf[n] = 0;
          if (looks_int(buf, n)) {
            errno = 0;
            int64_t iv = strtoll(buf, &parse_end, 10);
            if (errno == ERANGE) {
              // Out-of-int64-range literal: pandas keeps it exact
              // (uint64/object); a double would silently lose precision.
              // Fail the native parse so callers fall back to pandas.
              err = std::string("integer out of int64 range: '") + buf + "'";
              return false;
            }
            if (parse_end && *parse_end == 0) {
              if (cols[c].is_int)
                cols[c].ivals.push_back(iv);
              else
                cols[c].dvals.push_back((double)iv);
              goto next_field;
            }
          }
          {
            // strtod accepts C99 hex floats ("0x1A" -> 26.0) which pandas
            // treats as strings — reject them to keep auto-mode fallback
            // behaviour identical to pandas.
            if (memchr(buf, 'x', n) || memchr(buf, 'X', n)) {
              err = std::string("non-numeric field: '") + buf + "'";
              return false;
            }
            double v = strtod(buf, &parse_end);
            while (parse_end && *parse_end == ' ') ++parse_end;
            if (!parse_end || *parse_end != 0 || parse_end == buf) {
              err = std::string("non-numeric field: '") + buf + "'";
              return false;
            }
            cols[c].push_double(v);
          }
        }
      next_field:
        ++c;
        if (!comma) break;
        f = comma + 1;
        if (f == le) {  // trailing comma -> empty last field
          if (c >= ncols) {
            err = "row has more fields than header";
            return false;
          }
          cols[c++].push_double(NAN);
          break;
        }
      }
      if (c != ncols) {
        err = "row has fewer fields than header";
        return false;
      }
    }
    if (!eol) break;
    p = eol + 1;
  }
  return true;
}

}  // namespace

extern "C" {

void *zcsv_open(const char *path, int n_threads) {
  FILE *fp = fopen(path, "rb");
  if (!fp) {
    set_error(std::string("cannot open ") + path + ": " + strerror(errno));
    return nullptr;
  }
  fseek(fp, 0, SEEK_END);
  long sz = ftell(fp);
  fseek(fp, 0, SEEK_SET);
  std::vector<char> data((size_t)sz);
  if (sz > 0 && fread(data.data(), 1, (size_t)sz, fp) != (size_t)sz) {
    fclose(fp);
    set_error("short read");
    return nullptr;
  }
  fclose(fp);

  auto *t = new CsvTable();
  // header line
  const char *begin = data.data();
  const char *end = begin + data.size();
  const char *eol = (const char *)memchr(begin, '\n', data.size());
  if (!eol) {
    set_error("no header line");
    delete t;
    return nullptr;
  }
  {
    const char *he = eol;
    if (he > begin && he[-1] == '\r') --he;
    const char *f = begin;
    while (f <= he) {
      const char *comma = (const char *)memchr(f, ',', he - f);
      const char *fe = comma ? comma : he;
      std::string name(f, fe);
      // strip quotes/space
      while (!name.empty() && (name.front() == ' ' || name.front() == '"'))
        name.erase(name.begin());
      while (!name.empty() && (name.back() == ' ' || name.back() == '"'))
        name.pop_back();
      t->names.push_back(name);
      if (!comma) break;
      f = comma + 1;
      if (f > he) break;
    }
  }
  size_t ncols = t->names.size();
  if (ncols == 0) {
    set_error("empty header");
    delete t;
    return nullptr;
  }

  // split body into chunks at newline boundaries
  const char *body = eol + 1;
  size_t body_len = (size_t)(end - body);
  int nt = n_threads > 0 ? n_threads
                         : (int)std::thread::hardware_concurrency();
  if (nt < 1) nt = 1;
  size_t min_chunk = 1 << 20;  // 1 MiB: don't spawn threads for small files
  int chunks = (int)std::min<size_t>((size_t)nt,
                                     std::max<size_t>(1, body_len / min_chunk));
  std::vector<std::pair<const char *, const char *>> ranges;
  const char *cp = body;
  for (int i = 0; i < chunks; ++i) {
    const char *ce = (i == chunks - 1)
                         ? end
                         : body + body_len * (size_t)(i + 1) / (size_t)chunks;
    if (ce < end) {
      const char *nl = (const char *)memchr(ce, '\n', end - ce);
      ce = nl ? nl + 1 : end;
    }
    if (cp < ce) ranges.emplace_back(cp, ce);
    cp = ce;
  }

  std::vector<std::vector<ColBuf>> parts(ranges.size());
  std::vector<std::string> errs(ranges.size());
  std::vector<std::thread> threads;
  for (size_t i = 0; i < ranges.size(); ++i) {
    threads.emplace_back([&, i] {
      parse_chunk(ranges[i].first, ranges[i].second, ncols, parts[i],
                  errs[i]);
    });
  }
  for (auto &th : threads) th.join();
  for (auto &e : errs) {
    if (!e.empty()) {
      set_error(e);
      delete t;
      return nullptr;
    }
  }
  // stitch: a column is int64 only if every chunk kept it int
  t->cols.assign(ncols, {});
  size_t total = 0;
  for (auto &p : parts) total += p.empty() ? 0 : p[0].size();
  for (size_t c = 0; c < ncols; ++c) {
    bool is_int = true;
    for (auto &p : parts)
      if (!p.empty() && !p[c].is_int) is_int = false;
    ColBuf &dst = t->cols[c];
    dst.is_int = is_int;
    if (is_int) {
      dst.ivals.reserve(total);
      for (auto &p : parts)
        if (!p.empty())
          dst.ivals.insert(dst.ivals.end(), p[c].ivals.begin(),
                           p[c].ivals.end());
    } else {
      dst.dvals.reserve(total);
      for (auto &p : parts) {
        if (p.empty()) continue;
        p[c].promote();
        dst.dvals.insert(dst.dvals.end(), p[c].dvals.begin(),
                         p[c].dvals.end());
      }
    }
  }
  t->nrows = (long)total;
  return t;
}

long zcsv_nrows(void *h) { return static_cast<CsvTable *>(h)->nrows; }
int zcsv_ncols(void *h) {
  return (int)static_cast<CsvTable *>(h)->names.size();
}
const char *zcsv_col_name(void *h, int i) {
  auto *t = static_cast<CsvTable *>(h);
  if (i < 0 || (size_t)i >= t->names.size()) return nullptr;
  return t->names[(size_t)i].c_str();
}
// 1 if column i is int64-typed (pandas dtype parity), else 0.
int zcsv_col_is_int(void *h, int i) {
  auto *t = static_cast<CsvTable *>(h);
  if (i < 0 || (size_t)i >= t->cols.size()) return 0;
  return t->cols[(size_t)i].is_int ? 1 : 0;
}
const double *zcsv_col_data(void *h, int i) {
  auto *t = static_cast<CsvTable *>(h);
  if (i < 0 || (size_t)i >= t->cols.size() || t->cols[(size_t)i].is_int)
    return nullptr;
  return t->cols[(size_t)i].dvals.data();
}
const int64_t *zcsv_col_idata(void *h, int i) {
  auto *t = static_cast<CsvTable *>(h);
  if (i < 0 || (size_t)i >= t->cols.size() || !t->cols[(size_t)i].is_int)
    return nullptr;
  return t->cols[(size_t)i].ivals.data();
}
void zcsv_close(void *h) { delete static_cast<CsvTable *>(h); }

}  // extern "C"

// ---------------------------------------------------------------------------
// 3. Record store (ZREC)
// ---------------------------------------------------------------------------
//
// Layout:  "ZREC0001" | records: [u64 len | bytes]* |
//          index: u64 offset * n | u64 n | u64 index_off | "ZRECIDX1"

namespace {

constexpr char kMagic[9] = "ZREC0001";
constexpr char kFooter[9] = "ZRECIDX1";

struct RecWriter {
  FILE *fp = nullptr;
  std::vector<uint64_t> offsets;
  uint64_t pos = 0;
};

struct RecReader {
  int fd = -1;
  const uint8_t *map = nullptr;
  size_t map_len = 0;
  const uint64_t *index = nullptr;
  uint64_t n = 0;
};

}  // namespace

extern "C" {

void *zrec_writer_open(const char *path) {
  FILE *fp = fopen(path, "wb");
  if (!fp) {
    set_error(std::string("cannot create ") + path + ": " + strerror(errno));
    return nullptr;
  }
  auto *w = new RecWriter();
  w->fp = fp;
  fwrite(kMagic, 1, 8, fp);
  w->pos = 8;
  return w;
}

long zrec_write(void *h, const void *data, size_t len) {
  auto *w = static_cast<RecWriter *>(h);
  uint64_t len64 = (uint64_t)len;
  w->offsets.push_back(w->pos);
  if (fwrite(&len64, 8, 1, w->fp) != 1 ||
      (len && fwrite(data, 1, len, w->fp) != len)) {
    set_error("write failed");
    return -1;
  }
  w->pos += 8 + len;
  return (long)(w->offsets.size() - 1);
}

int zrec_writer_close(void *h) {
  auto *w = static_cast<RecWriter *>(h);
  uint64_t index_off = w->pos;
  uint64_t n = (uint64_t)w->offsets.size();
  int ok = 1;
  if (n && fwrite(w->offsets.data(), 8, n, w->fp) != n) ok = 0;
  if (fwrite(&n, 8, 1, w->fp) != 1) ok = 0;
  if (fwrite(&index_off, 8, 1, w->fp) != 1) ok = 0;
  if (fwrite(kFooter, 1, 8, w->fp) != 8) ok = 0;
  fclose(w->fp);
  delete w;
  if (!ok) set_error("footer write failed");
  return ok ? 0 : -1;
}

void *zrec_open(const char *path) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) {
    set_error(std::string("cannot open ") + path + ": " + strerror(errno));
    return nullptr;
  }
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < 8 + 24) {
    close(fd);
    set_error("not a ZREC file (too small)");
    return nullptr;
  }
  void *map = mmap(nullptr, (size_t)st.st_size, PROT_READ, MAP_SHARED, fd, 0);
  if (map == MAP_FAILED) {
    close(fd);
    set_error(std::string("mmap failed: ") + strerror(errno));
    return nullptr;
  }
  const uint8_t *base = (const uint8_t *)map;
  size_t len = (size_t)st.st_size;
  if (memcmp(base, kMagic, 8) != 0 ||
      memcmp(base + len - 8, kFooter, 8) != 0) {
    munmap(map, len);
    close(fd);
    set_error("bad ZREC magic/footer");
    return nullptr;
  }
  uint64_t index_off, n;
  memcpy(&index_off, base + len - 16, 8);
  memcpy(&n, base + len - 24, 8);
  if (index_off + n * 8 + 24 != len) {
    munmap(map, len);
    close(fd);
    set_error("corrupt ZREC index");
    return nullptr;
  }
  auto *r = new RecReader();
  r->fd = fd;
  r->map = base;
  r->map_len = len;
  r->index = (const uint64_t *)(base + index_off);
  r->n = n;
  return r;
}

long zrec_count(void *h) { return (long)static_cast<RecReader *>(h)->n; }

long zrec_len(void *h, long i) {
  auto *r = static_cast<RecReader *>(h);
  if (i < 0 || (uint64_t)i >= r->n) return -1;
  uint64_t len;
  memcpy(&len, r->map + r->index[i], 8);
  return (long)len;
}

const void *zrec_ptr(void *h, long i) {
  auto *r = static_cast<RecReader *>(h);
  if (i < 0 || (uint64_t)i >= r->n) return nullptr;
  return r->map + r->index[i] + 8;
}

long zrec_read(void *h, long i, void *out, size_t cap) {
  auto *r = static_cast<RecReader *>(h);
  long len = zrec_len(h, i);
  if (len < 0) return -1;
  if ((size_t)len > cap) return -3;
  memcpy(out, r->map + r->index[i] + 8, (size_t)len);
  return len;
}

void zrec_close(void *h) {
  auto *r = static_cast<RecReader *>(h);
  if (r->map) munmap((void *)r->map, r->map_len);
  if (r->fd >= 0) close(r->fd);
  delete r;
}

// -------------------------------------------------------------------------
// 4. Prefetcher: reader thread zrec -> zrb
// -------------------------------------------------------------------------

struct Prefetcher {
  std::thread th;
  std::atomic<bool> stop{false};
};

void *zpf_start(void *rec_h, void *rb_h, const long *order, long n,
                int loop) {
  auto *r = static_cast<RecReader *>(rec_h);
  auto *rb = static_cast<RingBuffer *>(rb_h);
  std::vector<long> ord(order, order + n);
  auto *pf = new Prefetcher();
  pf->th = std::thread([r, rb, ord = std::move(ord), loop, pf] {
    // Close the ring on EVERY exit path: a consumer blocked in zrb_pop with
    // an infinite timeout must never be stranded by a dead producer.
    do {
      for (long i : ord) {
        if (pf->stop.load()) {
          zrb_close((void *)rb);
          return;
        }
        long len = zrec_len((void *)r, i);
        if (len < 0) continue;
        const void *p = zrec_ptr((void *)r, i);
        // push with short timeouts so `stop` is honoured promptly
        while (!pf->stop.load()) {
          int rc = zrb_push((void *)rb, p, (size_t)len, 50);
          if (rc == 0) break;
          if (rc == -2 || rc == -3) {  // ring closed by consumer / oversized
            zrb_close((void *)rb);
            return;
          }
        }
      }
    } while (loop && !pf->stop.load());
    zrb_close((void *)rb);
  });
  return pf;
}

void zpf_stop(void *h) {
  auto *pf = static_cast<Prefetcher *>(h);
  pf->stop.store(true);
  if (pf->th.joinable()) pf->th.join();
  delete pf;
}

}  // extern "C"

// -------------------------------------------------------------------------
// 5. Image decode: JPEG (libjpeg) / PNG (libpng) -> RGB8 HWC buffers.
//
// The reference's image path was OpenCV behind BigDL's JNI wrapper (SURVEY
// §2.2 ImageSet row, §2.3 native obligations: host-side C++ decode, no
// pure-Python stand-ins).  System libjpeg/libpng replace OpenCV here; the
// Python side (ImageSet / NNImageReader) threads over files with the GIL
// released, so decode parallelism matches the Spark-partition decode the
// reference got for free.
// -------------------------------------------------------------------------

#ifdef ZOO_WITH_IMAGE
#include <csetjmp>
#include <jpeglib.h>
#include <png.h>

namespace {

struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jb;
  char msg[JMSG_LENGTH_MAX];
};

void jpeg_err_exit(j_common_ptr cinfo) {
  auto *e = reinterpret_cast<JpegErr *>(cinfo->err);
  (*cinfo->err->format_message)(cinfo, e->msg);
  longjmp(e->jb, 1);
}

unsigned char *decode_jpeg(const unsigned char *data, size_t n, long *h,
                           long *w, int *c) {
  jpeg_decompress_struct cinfo;
  JpegErr err;
  cinfo.err = jpeg_std_error(&err.mgr);
  err.mgr.error_exit = jpeg_err_exit;
  unsigned char *out = nullptr;
  if (setjmp(err.jb)) {
    set_error(std::string("jpeg decode: ") + err.msg);
    std::free(out);
    jpeg_destroy_decompress(&cinfo);
    return nullptr;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char *>(data), n);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  const long W = cinfo.output_width, H = cinfo.output_height;
  const int C = cinfo.output_components;  // 3 after JCS_RGB
  out = static_cast<unsigned char *>(std::malloc((size_t)W * H * C));
  if (!out) longjmp(err.jb, 1);
  while (cinfo.output_scanline < cinfo.output_height) {
    unsigned char *row = out + (size_t)cinfo.output_scanline * W * C;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  *h = H; *w = W; *c = C;
  return out;
}

unsigned char *decode_png(const unsigned char *data, size_t n, long *h,
                          long *w, int *c) {
  png_image img;
  std::memset(&img, 0, sizeof img);
  img.version = PNG_IMAGE_VERSION;
  if (!png_image_begin_read_from_memory(&img, data, n)) {
    set_error(std::string("png decode: ") + img.message);
    return nullptr;
  }
  img.format = PNG_FORMAT_RGB;
  const size_t stride = PNG_IMAGE_ROW_STRIDE(img);
  auto *out = static_cast<unsigned char *>(
      std::malloc(PNG_IMAGE_BUFFER_SIZE(img, stride)));
  if (!out) {
    png_image_free(&img);
    set_error("png decode: oom");
    return nullptr;
  }
  if (!png_image_finish_read(&img, nullptr, out, (png_int_32)stride,
                             nullptr)) {
    set_error(std::string("png decode: ") + img.message);
    std::free(out);
    return nullptr;
  }
  *h = img.height; *w = img.width; *c = 3;
  return out;
}

}  // namespace

extern "C" {

unsigned char *zimg_decode_mem(const void *data, size_t n, long *h, long *w,
                               int *c) {
  const auto *p = static_cast<const unsigned char *>(data);
  if (n >= 2 && p[0] == 0xFF && p[1] == 0xD8) return decode_jpeg(p, n, h, w, c);
  if (n >= 4 && p[0] == 0x89 && p[1] == 'P' && p[2] == 'N' && p[3] == 'G')
    return decode_png(p, n, h, w, c);
  set_error("unrecognized image magic (JPEG/PNG supported natively)");
  return nullptr;
}

unsigned char *zimg_decode(const char *path, long *h, long *w, int *c) {
  FILE *f = std::fopen(path, "rb");
  if (!f) {
    set_error(std::string("open ") + path + ": " + std::strerror(errno));
    return nullptr;
  }
  std::fseek(f, 0, SEEK_END);
  long n = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  std::vector<unsigned char> buf((size_t)std::max(0L, n));
  size_t got = n > 0 ? std::fread(buf.data(), 1, (size_t)n, f) : 0;
  std::fclose(f);
  if ((long)got != n) {
    set_error(std::string("short read on ") + path);
    return nullptr;
  }
  return zimg_decode_mem(buf.data(), buf.size(), h, w, c);
}

void zimg_free(unsigned char *p) { std::free(p); }

}  // extern "C"
#endif  // ZOO_WITH_IMAGE

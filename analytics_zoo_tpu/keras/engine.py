"""Keras engine: symbolic tensors, Sequential / functional Model, KerasNet.

Reference parity: zoo/pipeline/api/keras/models (Sequential, Model),
KerasNet.compile/fit/evaluate/predict driving the zoo Estimator
(pyzoo/zoo/pipeline/api/keras/engine/topology.py).  Here the topology is a
flax module and compile/fit lower onto the shared pjit Estimator — the whole
model executes as ONE XLA program per step; there is no per-layer dispatch at
runtime.

The functional API (`y = Dense(4)(x); Model(x, y)`) is built by symbolic
dispatch: calling a layer on a :class:`KTensor` records a graph node instead
of executing flax (flax forbids calling unbound modules), and ``Model``
replays the recorded graph inside one compact ``__call__``.
"""

from __future__ import annotations

import inspect
import itertools
import pickle
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.keras.regularizers import Regularizer

__all__ = ["KTensor", "Input", "Sequential", "Model", "KerasNet",
           "symbolic", "merge"]


def _path_str(path) -> str:
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in path)


def _natural_key(s: str):
    import re

    return tuple(int(t) if t.isdigit() else t
                 for t in re.split(r"(\d+)", s))


def _ordered_params(params) -> List[Tuple[str, Any]]:
    """(path, leaf) pairs in natural (digit-aware) path order, so
    layers_2 sorts before layers_10."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    items = [(_path_str(p), leaf) for p, leaf in flat]
    items.sort(key=lambda kv: _natural_key(kv[0]))
    return items


# ---------------------------------------------------------------------------
# symbolic graph
# ---------------------------------------------------------------------------

_ids = itertools.count()


class KTensor:
    """Symbolic tensor: a node output in a functional-API graph."""

    def __init__(self, layer: Optional[nn.Module], inputs: Sequence["KTensor"],
                 shape: Optional[Tuple[Optional[int], ...]] = None,
                 call_kwargs: Optional[dict] = None):
        self.layer = layer            # None for placeholders (Input)
        self.inputs = tuple(inputs)
        self.shape = shape
        self.call_kwargs = dict(call_kwargs or {})
        self.uid = next(_ids)

    def __repr__(self):
        who = type(self.layer).__name__ if self.layer is not None else "Input"
        return f"KTensor<{who}#{self.uid}>"


def Input(shape: Sequence[Optional[int]], name: Optional[str] = None,
          dtype=None) -> KTensor:
    """Placeholder for a functional-API input. `shape` EXCLUDES the batch
    dim (keras semantics)."""
    kt = KTensor(None, (), shape=tuple(shape))
    kt.name = name
    kt.dtype = dtype or jnp.float32
    return kt


def _contains_ktensor(x) -> bool:
    if isinstance(x, KTensor):
        return True
    if isinstance(x, (list, tuple)):
        return any(isinstance(e, KTensor) for e in x)
    return False


def symbolic(cls):
    """Class decorator: make `layer(ktensor)` record a graph node.

    flax's metaclass has already wrapped ``__call__`` for scope management;
    we interpose a plain dispatcher ABOVE it so symbolic calls never reach
    flax (which would raise on unbound modules), while concrete calls fall
    through to the original wrapped method untouched.
    """
    orig = cls.__call__

    def dispatch(self, *args, **kwargs):
        if args and _contains_ktensor(args[0]):
            ins = args[0] if isinstance(args[0], (list, tuple)) else [args[0]]
            return KTensor(self, ins, call_kwargs=kwargs)
        return orig(self, *args, **kwargs)

    dispatch.inner_fn = getattr(orig, "inner_fn", orig)
    cls.__call__ = dispatch
    return cls


def _toposort(outputs: Sequence[KTensor]) -> List[KTensor]:
    order, seen = [], set()

    def visit(t: KTensor):
        if t.uid in seen:
            return
        seen.add(t.uid)
        for i in t.inputs:
            visit(i)
        order.append(t)

    for o in outputs:
        visit(o)
    return order


# ---------------------------------------------------------------------------
# regularization collection
# ---------------------------------------------------------------------------

_KERNEL_NAMES = ("kernel", "embedding")


def _layer_penalty(layer: nn.Module, subtree) -> jnp.ndarray:
    pen = jnp.zeros((), jnp.float32)
    w_reg = getattr(layer, "W_regularizer", None)
    b_reg = getattr(layer, "b_regularizer", None)
    if not isinstance(w_reg, Regularizer):
        w_reg = None
    if not isinstance(b_reg, Regularizer):
        b_reg = None
    if w_reg is None and b_reg is None:
        return pen
    flat = jax.tree_util.tree_flatten_with_path(subtree)[0]
    for path, leaf in flat:
        name = str(path[-1].key) if path else ""
        if w_reg is not None and name in _KERNEL_NAMES:
            pen = pen + w_reg(leaf)
        if b_reg is not None and name == "bias":
            pen = pen + b_reg(leaf)
    return pen


def collect_penalty(net: "KerasNet", params) -> jnp.ndarray:
    """Sum of L1/L2 penalties declared by any layer of `net` (recursing into
    nested Sequential/Model)."""
    pen = jnp.zeros((), jnp.float32)
    for field, layer in net._child_layers():
        sub = params.get(field) if isinstance(params, dict) else None
        if sub is None:
            continue
        if isinstance(layer, KerasNet):
            pen = pen + collect_penalty(layer, sub)
        else:
            pen = pen + _layer_penalty(layer, sub)
    return pen


# ---------------------------------------------------------------------------
# KerasNet: compile/fit/evaluate/predict mixin
# ---------------------------------------------------------------------------


class KerasNet(nn.Module):
    """Base for Sequential/Model: keras-style training surface lowered onto
    the shared :class:`~analytics_zoo_tpu.learn.estimator.FlaxEstimator`
    (ref: KerasNet.compile/fit in pyzoo keras engine/topology.py)."""

    def _child_layers(self) -> List[Tuple[str, nn.Module]]:
        raise NotImplementedError

    @property
    def n_inputs(self) -> int:
        return 1

    # -- training surface ------------------------------------------------

    def compile(self, optimizer="sgd", loss="mse", metrics=None, lr=None):
        """Record the training config; the Estimator is built lazily at
        first fit/evaluate (needs sample data for shape inference).  The raw
        spec (not the optax object) is stored so compiled models pickle."""
        object.__setattr__(self, "_compile_cfg", {
            "optimizer": optimizer,
            "lr": lr,
            "loss": loss,
            "metrics": list(metrics or []),
        })
        object.__setattr__(self, "_estimator", None)
        return self

    def _feature_cols(self, n: int) -> Tuple[str, ...]:
        return tuple(f"x{i}" for i in range(n))

    def _as_dict(self, x, y=None) -> Dict[str, np.ndarray]:
        xs = list(x) if isinstance(x, (list, tuple)) else [x]
        d = {f"x{i}": np.asarray(a) for i, a in enumerate(xs)}
        if y is not None:
            d["y"] = np.asarray(y)
        return d

    def _get_estimator(self, n_feats: int):
        if getattr(self, "_estimator", None) is not None:
            return self._estimator
        if not hasattr(self, "_compile_cfg"):
            raise RuntimeError("call compile(...) before fit/evaluate")
        from analytics_zoo_tpu.learn.estimator import Estimator
        from analytics_zoo_tpu.keras.objectives import get_loss

        from analytics_zoo_tpu.keras.optimizers import get_optimizer
        cfg = self._compile_cfg
        est = Estimator.from_flax(
            model=self,
            loss=get_loss(cfg["loss"]),
            optimizer=get_optimizer(cfg["optimizer"], cfg.get("lr")),
            metrics=cfg["metrics"],
            feature_cols=self._feature_cols(n_feats),
            label_cols=("y",),
            param_loss=lambda params: collect_penalty(self, params),
        )
        tb = getattr(self, "_tb_cfg", None)
        if tb is not None:
            est.set_tensorboard(*tb)
        object.__setattr__(self, "_estimator", est)
        return est

    def fit(self, x, y, batch_size: int = 32, nb_epoch: int = 1,
            epochs: Optional[int] = None, validation_data=None, **kw):
        data = self._as_dict(x, y)
        est = self._get_estimator(len(data) - 1)
        val = None
        if validation_data is not None:
            val = self._as_dict(*validation_data)
        return est.fit(data, epochs=epochs or nb_epoch,
                       batch_size=batch_size, validation_data=val, **kw)

    def evaluate(self, x, y, batch_size: int = 32) -> Dict[str, float]:
        data = self._as_dict(x, y)
        return self._get_estimator(len(data) - 1).evaluate(
            data, batch_size=batch_size)

    def predict(self, x, batch_size: int = 32,
                distributed: bool = False) -> np.ndarray:
        data = self._as_dict(x)
        return self._get_estimator(len(data)).predict(
            data, batch_size=batch_size)

    def predict_classes(self, x, batch_size: int = 32) -> np.ndarray:
        return np.argmax(self.predict(x, batch_size), axis=-1)

    # -- weights ---------------------------------------------------------
    # get/set_weights expose params as a flat list in LAYER order.  Plain
    # tree.leaves would sort dict keys lexicographically ("layers_10" <
    # "layers_2"), silently scrambling >=10-layer stacks — so paths are
    # ordered with a natural (digit-aware) sort.

    def get_weights(self) -> List[np.ndarray]:
        est = getattr(self, "_estimator", None)
        if est is None or est.state is None:
            raise RuntimeError("model has no weights yet (fit/build first)")
        return [np.asarray(w) for _, w in _ordered_params(est.state.params)]

    def set_weights(self, weights: Sequence[np.ndarray]):
        est = getattr(self, "_estimator", None)
        if est is None or est.state is None:
            raise RuntimeError("model has no weights yet (fit/build first)")
        items = _ordered_params(est.state.params)
        if len(weights) != len(items):
            raise ValueError(f"expected {len(items)} arrays, got "
                             f"{len(weights)}")
        by_path = {p: jnp.asarray(w).reshape(l.shape)
                   for (p, l), w in zip(items, weights)}
        new = jax.tree_util.tree_map_with_path(
            lambda p, l: by_path[_path_str(p)], est.state.params)
        est.state = est.state.replace(params=new)

    def set_tensorboard(self, log_dir: str, app_name: str = "zoo"):
        """ref-parity: KerasNet.set_tensorboard (BigDL TrainSummary)."""
        object.__setattr__(self, "_tb_cfg", (log_dir, app_name))
        est = getattr(self, "_estimator", None)
        if est is not None:
            est.set_tensorboard(log_dir, app_name)
        return self

    def summary(self) -> str:
        lines = [f"{type(self).__name__}"]
        for field, layer in self._child_layers():
            lines.append(f"  {field}: {type(layer).__name__}")
        s = "\n".join(lines)
        print(s)
        return s

    # -- persistence (ref: KerasNet.save/Net.load) -----------------------

    def save(self, path: str):
        import os
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "topology.pkl"), "wb") as f:
            pickle.dump(self, f)
        est = getattr(self, "_estimator", None)
        if est is not None and est.state is not None:
            import flax.serialization as ser
            with open(os.path.join(path, "weights.msgpack"), "wb") as f:
                f.write(ser.to_bytes({"params": est.state.params}))
            spec = getattr(est, "sample_spec", None)
            if spec:
                with open(os.path.join(path, "input_spec.pkl"), "wb") as f:
                    pickle.dump(spec, f)

    @staticmethod
    def load(path: str, sample_x=None) -> "KerasNet":
        import os
        with open(os.path.join(path, "topology.pkl"), "rb") as f:
            net: KerasNet = pickle.load(f)
        wpath = os.path.join(path, "weights.msgpack")
        spath = os.path.join(path, "input_spec.pkl")
        if os.path.exists(wpath):
            import flax.serialization as ser
            if sample_x is not None:
                n_in = len(sample_x) if isinstance(sample_x, (list, tuple)) \
                    else 1
                est = net._get_estimator(n_in)
                sample = net._as_dict(sample_x)
            elif os.path.exists(spath):
                # rebuild a dummy sample from the spec captured at save time
                with open(spath, "rb") as f:
                    spec = pickle.load(f)
                sample = {c: np.zeros((1,) + tuple(shape), dtype=dt)
                          for c, (shape, dt) in spec.items()}
                est = net._get_estimator(
                    len([c for c in sample if c != "y"]))
            else:
                raise ValueError(
                    f"{path} has saved weights but no input spec; pass "
                    "sample_x so the model can be rebuilt before restore "
                    "(silently returning random weights would be worse)")
            est._ensure_state(sample)
            with open(wpath, "rb") as f:
                restored = ser.from_bytes(
                    {"params": est.state.params}, f.read())
            est.state = est.state.replace(params=restored["params"])
        return net


# pickling: drop the estimator (holds jitted fns / device arrays) and any
# compile spec that isn't plain data (custom optax objects / lambdas)
def _kerasnet_getstate(self):
    d = dict(self.__dict__)
    d.pop("_estimator", None)
    cfg = d.get("_compile_cfg")
    if cfg is not None and not (isinstance(cfg["optimizer"], str)
                                and isinstance(cfg["loss"], str)
                                and all(isinstance(m, str)
                                        for m in cfg["metrics"])):
        d.pop("_compile_cfg", None)
    return d


KerasNet.__getstate__ = _kerasnet_getstate


# ---------------------------------------------------------------------------
# Sequential
# ---------------------------------------------------------------------------


@symbolic
class Sequential(KerasNet):
    """Linear layer stack (ref: keras-API Sequential,
    zoo/pipeline/api/keras/models/Topology.scala Sequential)."""

    layers: Tuple[nn.Module, ...] = ()

    @nn.compact
    def __call__(self, x, train: bool = False):
        for layer in self.layers:
            x = _call_layer(layer, x, train)
        return x

    def add(self, layer: nn.Module) -> "Sequential":
        # flax dataclasses are frozen; Sequential is mutated only BEFORE
        # binding (keras .add build phase), so object.__setattr__ is safe.
        object.__setattr__(self, "layers", tuple(self.layers) + (layer,))
        return self

    def _child_layers(self):
        return [(f"layers_{i}", l) for i, l in enumerate(self.layers)]


def _call_layer(layer, x, train: bool, extra_kwargs: Optional[dict] = None):
    """Invoke a child layer, passing `train` only if accepted.
    `extra_kwargs` replays kwargs recorded at symbolic-call time."""
    fn = getattr(type(layer), "__call__", None)
    inner = getattr(fn, "inner_fn", fn)
    try:
        params = inspect.signature(inner).parameters
        takes_train = "train" in params
    except (TypeError, ValueError):
        takes_train = False
    kw = dict(extra_kwargs or {})
    kw.pop("train", None)
    if takes_train:
        kw["train"] = train
    if isinstance(x, (list, tuple)) and getattr(
            layer, "_takes_list", False):
        return layer(list(x), **kw)
    return layer(x, **kw)


# ---------------------------------------------------------------------------
# functional Model
# ---------------------------------------------------------------------------


@symbolic
class Model(KerasNet):
    """Functional-API graph model (ref: keras Model / zoo GraphNet).

    Built from Input placeholders and symbolic layer calls; executes the
    recorded DAG inside one compact call so XLA sees a single program.
    """

    graph_inputs: Tuple[KTensor, ...] = ()
    graph_outputs: Tuple[KTensor, ...] = ()
    ops: Tuple[nn.Module, ...] = ()          # derived; topological order

    def __post_init__(self):
        super().__post_init__()
        if self.graph_inputs:
            # Re-derived on every init (flax .clone() re-runs __post_init__
            # with `ops` already set — the non-field attrs must come back).
            order = [t for t in _toposort(self.graph_outputs)
                     if t.layer is not None]
            # dedupe shared layers (keras layer reuse => shared params)
            seen, ops = {}, []
            for t in order:
                if id(t.layer) not in seen:
                    seen[id(t.layer)] = len(ops)
                    ops.append(t.layer)
            if not self.ops:
                object.__setattr__(self, "ops", tuple(ops))
            object.__setattr__(self, "_op_index", seen)
            object.__setattr__(self, "_order", order)

    @classmethod
    def from_io(cls, input, output) -> "Model":
        ins = tuple(input) if isinstance(input, (list, tuple)) else (input,)
        outs = tuple(output) if isinstance(output, (list, tuple)) else (output,)
        return cls(graph_inputs=ins, graph_outputs=outs)

    @property
    def n_inputs(self) -> int:
        return len(self.graph_inputs)

    @nn.compact
    def __call__(self, *xs, train: bool = False):
        if len(xs) != len(self.graph_inputs):
            raise ValueError(f"model takes {len(self.graph_inputs)} inputs, "
                             f"got {len(xs)}")
        env: Dict[int, Any] = {t.uid: x
                               for t, x in zip(self.graph_inputs, xs)}
        for t in self._order:
            ins = [env[i.uid] for i in t.inputs]
            layer = self.ops[self._op_index[id(t.layer)]]
            arg = ins[0] if len(ins) == 1 else list(ins)
            env[t.uid] = _call_layer(layer, arg, train,
                                     extra_kwargs=t.call_kwargs)
        outs = tuple(env[t.uid] for t in self.graph_outputs)
        return outs[0] if len(outs) == 1 else outs

    def _child_layers(self):
        return [(f"ops_{i}", l) for i, l in enumerate(self.ops)]


def _model_new(input, output):
    return Model.from_io(input, output)


# keras spelling: Model(input=..., output=...)
_real_model_init = Model.__init__


def _model_init(self, *args, input=None, output=None, **kwargs):
    if input is not None or output is not None:
        m = Model.from_io(input, output)
        _real_model_init(self, graph_inputs=m.graph_inputs,
                         graph_outputs=m.graph_outputs)
        return
    _real_model_init(self, *args, **kwargs)


Model.__init__ = _model_init


def merge(inputs: Sequence[KTensor], mode: str = "sum",
          concat_axis: int = -1) -> KTensor:
    """Functional merge of symbolic tensors (ref: keras `merge`)."""
    from analytics_zoo_tpu.keras.layers import Merge
    return Merge(mode=mode, concat_axis=concat_axis)(list(inputs))

"""Activation registry (ref: keras-API activation strings,
zoo/pipeline/api/keras/layers/core — `activation="relu"` etc.)."""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp


def linear(x):
    return x


def hard_sigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


_ACTIVATIONS = {
    "linear": linear,
    "relu": jax.nn.relu,
    "relu6": jax.nn.relu6,
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
    "softmax": jax.nn.softmax,
    "log_softmax": jax.nn.log_softmax,
    "softplus": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "hard_sigmoid": hard_sigmoid,
    "elu": jax.nn.elu,
    "selu": jax.nn.selu,
    "gelu": jax.nn.gelu,
    "swish": jax.nn.swish,
    "silu": jax.nn.silu,
    "leaky_relu": jax.nn.leaky_relu,
}


def get_activation(name: Optional[Union[str, Callable]]) -> Callable:
    if name is None:
        return linear
    if callable(name):
        return name
    try:
        return _ACTIVATIONS[name.lower()]
    except KeyError:
        raise ValueError(
            f"unknown activation {name!r}; one of {sorted(_ACTIVATIONS)}")

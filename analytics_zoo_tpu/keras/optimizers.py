"""Optimizer strings → optax (ref: keras-API `compile(optimizer="adam")`;
the reference lowers these to BigDL OptimMethods — here they lower to optax
gradient transforms applied inside the single pjit'd train step)."""

from __future__ import annotations

from typing import Union

import optax


_FACTORIES = {
    "sgd": lambda lr: optax.sgd(lr if lr is not None else 0.01),
    "momentum": lambda lr: optax.sgd(lr if lr is not None else 0.01,
                                     momentum=0.9),
    "adam": lambda lr: optax.adam(lr if lr is not None else 1e-3),
    "adamw": lambda lr: optax.adamw(lr if lr is not None else 1e-3),
    "adamax": lambda lr: optax.adamax(lr if lr is not None else 2e-3),
    "nadam": lambda lr: optax.nadam(lr if lr is not None else 1e-3),
    "adagrad": lambda lr: optax.adagrad(lr if lr is not None else 1e-2),
    "adadelta": lambda lr: optax.adadelta(lr if lr is not None else 1.0),
    "rmsprop": lambda lr: optax.rmsprop(lr if lr is not None else 1e-3),
    "lamb": lambda lr: optax.lamb(lr if lr is not None else 1e-3),
}


def get_optimizer(opt: Union[str, optax.GradientTransformation],
                  lr: float = None) -> optax.GradientTransformation:
    if isinstance(opt, str):
        try:
            return _FACTORIES[opt.lower()](lr)
        except KeyError:
            raise ValueError(
                f"unknown optimizer {opt!r}; one of {sorted(_FACTORIES)}")
    return opt

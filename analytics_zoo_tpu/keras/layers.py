"""Keras-1.2-compatible layer catalog on flax.

Reference parity: zoo/pipeline/api/keras/layers/ (~100 Keras 1.2.2 layers
reimplemented over BigDL) + pyzoo/zoo/pipeline/api/keras/layers mirrors.
Here each layer is a thin flax module with keras-style constructor args
(`output_dim`, `init`, `activation`, `border_mode`, `subsample`,
`W_regularizer`, ...).  Layout is channels-LAST (NHWC) — the TPU/XLA-native
layout — where the reference (BigDL) defaulted to NCHW; `dim_ordering`
arguments are accepted for API compatibility and must be "tf"/default.

Keras-2 spellings (Conv2D, MaxPool2D, ...) are exported as aliases
(ref: zoo/pipeline/api/keras2/).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from analytics_zoo_tpu.keras.activations import get_activation
from analytics_zoo_tpu.keras.engine import symbolic
from analytics_zoo_tpu.keras.initializers import constant_init, get_initializer
from analytics_zoo_tpu.keras.regularizers import Regularizer

__all__ = [
    # core
    "Dense", "Activation", "Dropout", "Flatten", "Reshape", "Permute",
    "RepeatVector", "Merge", "Highway", "MaxoutDense", "Masking", "Lambda",
    # advanced activations
    "LeakyReLU", "ELU", "PReLU", "ThresholdedReLU",
    # noise / regularization
    "GaussianNoise", "GaussianDropout", "SpatialDropout1D", "SpatialDropout2D",
    "SpatialDropout3D",
    # embeddings & norm
    "Embedding", "BatchNormalization", "LayerNormalization",
    # conv
    "Convolution1D", "Convolution2D", "Convolution3D", "AtrousConvolution1D",
    "AtrousConvolution2D", "SeparableConvolution2D", "Deconvolution2D",
    "Cropping1D", "Cropping2D", "Cropping3D", "UpSampling1D", "UpSampling2D",
    "UpSampling3D", "ZeroPadding1D", "ZeroPadding2D", "ZeroPadding3D",
    "LocallyConnected1D", "LocallyConnected2D",
    # pooling
    "MaxPooling1D", "MaxPooling2D", "MaxPooling3D", "AveragePooling1D",
    "AveragePooling2D", "AveragePooling3D", "GlobalMaxPooling1D",
    "GlobalMaxPooling2D", "GlobalMaxPooling3D", "GlobalAveragePooling1D",
    "GlobalAveragePooling2D", "GlobalAveragePooling3D",
    # recurrent
    "SimpleRNN", "LSTM", "GRU", "ConvLSTM2D", "Bidirectional",
    "TimeDistributed",
    # keras2 aliases
    "Conv1D", "Conv2D", "Conv3D", "Conv2DTranspose", "SeparableConv2D",
    "MaxPool1D", "MaxPool2D", "MaxPool3D", "AvgPool1D", "AvgPool2D",
    "AvgPool3D",
]


def _pair(v, n=2):
    if isinstance(v, (tuple, list)):
        return tuple(v)
    return (v,) * n


def _check_tf_ordering(dim_ordering):
    if dim_ordering not in (None, "tf", "default", "channels_last"):
        raise ValueError(
            "only channels-last ('tf') layout is supported on TPU; got "
            f"dim_ordering={dim_ordering!r}")


# ---------------------------------------------------------------------------
# core
# ---------------------------------------------------------------------------


@symbolic
class Dense(nn.Module):
    """ref: keras layers/core Dense (zoo keras-API Dense)."""
    output_dim: int
    init: Any = "glorot_uniform"
    activation: Any = None
    W_regularizer: Optional[Regularizer] = None
    b_regularizer: Optional[Regularizer] = None
    bias: bool = True
    input_shape: Optional[Tuple[int, ...]] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        y = nn.Dense(self.output_dim, use_bias=self.bias,
                     kernel_init=get_initializer(self.init))(x)
        return get_activation(self.activation)(y)


@symbolic
class Activation(nn.Module):
    activation: Any = "linear"

    @nn.compact
    def __call__(self, x, train: bool = False):
        return get_activation(self.activation)(x)


@symbolic
class Dropout(nn.Module):
    """ref: keras Dropout. `p` is the DROP rate (keras-1.2 spelling)."""
    p: float = 0.5

    @nn.compact
    def __call__(self, x, train: bool = False):
        return nn.Dropout(rate=self.p, deterministic=not train)(x)


@symbolic
class Flatten(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = False):
        return x.reshape((x.shape[0], -1))


@symbolic
class Reshape(nn.Module):
    target_shape: Tuple[int, ...] = ()

    @nn.compact
    def __call__(self, x, train: bool = False):
        return x.reshape((x.shape[0],) + tuple(self.target_shape))


@symbolic
class Permute(nn.Module):
    """dims are 1-indexed over non-batch axes (keras semantics)."""
    dims: Tuple[int, ...] = ()

    @nn.compact
    def __call__(self, x, train: bool = False):
        return jnp.transpose(x, (0,) + tuple(d for d in self.dims))


@symbolic
class RepeatVector(nn.Module):
    n: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        return jnp.repeat(x[:, None, :], self.n, axis=1)


@symbolic
class Masking(nn.Module):
    """Zeroes timesteps whose features all equal mask_value (keras Masking;
    downstream layers see zeros — explicit masks are not propagated, which
    matches the reference's BigDL lowering of padded sequences)."""
    mask_value: float = 0.0

    @nn.compact
    def __call__(self, x, train: bool = False):
        keep = jnp.any(x != self.mask_value, axis=-1, keepdims=True)
        return jnp.where(keep, x, 0.0)


@symbolic
class Lambda(nn.Module):
    """Arbitrary jnp expression as a layer (ref: keras Lambda; the zoo
    autograd CustomLoss machinery covers the loss-side equivalent)."""
    function: Callable = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        return self.function(x)


@symbolic
class Merge(nn.Module):
    """ref: keras Merge (mode: sum/mul/concat/ave/max/min/dot/cos)."""
    mode: str = "sum"
    concat_axis: int = -1
    _takes_list: bool = True

    @nn.compact
    def __call__(self, xs, train: bool = False):
        if not isinstance(xs, (list, tuple)):
            raise ValueError("Merge expects a list of inputs")
        m = self.mode
        if m == "sum":
            out = xs[0]
            for x in xs[1:]:
                out = out + x
            return out
        if m == "mul":
            out = xs[0]
            for x in xs[1:]:
                out = out * x
            return out
        if m == "ave":
            return sum(xs) / len(xs)
        if m == "max":
            out = xs[0]
            for x in xs[1:]:
                out = jnp.maximum(out, x)
            return out
        if m == "min":
            out = xs[0]
            for x in xs[1:]:
                out = jnp.minimum(out, x)
            return out
        if m == "concat":
            return jnp.concatenate(xs, axis=self.concat_axis)
        if m == "dot":
            a, b = xs
            return jnp.sum(a * b, axis=-1, keepdims=True)
        if m == "cos":
            a, b = xs
            na = a / (jnp.linalg.norm(a, axis=-1, keepdims=True) + 1e-8)
            nb = b / (jnp.linalg.norm(b, axis=-1, keepdims=True) + 1e-8)
            return jnp.sum(na * nb, axis=-1, keepdims=True)
        raise ValueError(f"unknown merge mode {m!r}")


@symbolic
class Highway(nn.Module):
    """ref: keras Highway — y = t*h(x) + (1-t)*x."""
    activation: Any = "tanh"
    init: Any = "glorot_uniform"
    bias: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        d = x.shape[-1]
        h = get_activation(self.activation)(
            nn.Dense(d, use_bias=self.bias,
                     kernel_init=get_initializer(self.init))(x))
        t = jax.nn.sigmoid(
            nn.Dense(d, use_bias=self.bias,
                     bias_init=constant_init(-2.0))(x))
        return t * h + (1 - t) * x


@symbolic
class MaxoutDense(nn.Module):
    """ref: keras MaxoutDense — max over nb_feature linear maps."""
    output_dim: int
    nb_feature: int = 4
    bias: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        y = nn.Dense(self.output_dim * self.nb_feature, use_bias=self.bias)(x)
        y = y.reshape(y.shape[:-1] + (self.nb_feature, self.output_dim))
        return jnp.max(y, axis=-2)


# ---------------------------------------------------------------------------
# advanced activations
# ---------------------------------------------------------------------------


@symbolic
class LeakyReLU(nn.Module):
    alpha: float = 0.3

    @nn.compact
    def __call__(self, x, train: bool = False):
        return jax.nn.leaky_relu(x, self.alpha)


@symbolic
class ELU(nn.Module):
    alpha: float = 1.0

    @nn.compact
    def __call__(self, x, train: bool = False):
        return jax.nn.elu(x, self.alpha)


@symbolic
class PReLU(nn.Module):
    """Learnable per-channel negative slope."""

    @nn.compact
    def __call__(self, x, train: bool = False):
        alpha = self.param("alpha", constant_init(0.25), (x.shape[-1],))
        return jnp.where(x >= 0, x, alpha * x)


@symbolic
class ThresholdedReLU(nn.Module):
    theta: float = 1.0

    @nn.compact
    def __call__(self, x, train: bool = False):
        return jnp.where(x > self.theta, x, 0.0)


# ---------------------------------------------------------------------------
# noise
# ---------------------------------------------------------------------------


@symbolic
class GaussianNoise(nn.Module):
    sigma: float = 0.1

    @nn.compact
    def __call__(self, x, train: bool = False):
        if not train:
            return x
        rng = self.make_rng("dropout")
        return x + self.sigma * jax.random.normal(rng, x.shape, x.dtype)


@symbolic
class GaussianDropout(nn.Module):
    p: float = 0.5

    @nn.compact
    def __call__(self, x, train: bool = False):
        if not train or self.p <= 0:
            return x
        rng = self.make_rng("dropout")
        std = np.sqrt(self.p / (1.0 - self.p))
        return x * (1 + std * jax.random.normal(rng, x.shape, x.dtype))


def _spatial_dropout(ndim_broadcast):
    dims = tuple(ndim_broadcast)

    @symbolic
    class _SD(nn.Module):
        p: float = 0.5

        @nn.compact
        def __call__(self, x, train: bool = False):
            return nn.Dropout(rate=self.p, broadcast_dims=dims,
                              deterministic=not train)(x)

    return _SD


SpatialDropout1D = _spatial_dropout((1,))        # (B, T, C): drop whole C
SpatialDropout2D = _spatial_dropout((1, 2))      # (B, H, W, C)
SpatialDropout3D = _spatial_dropout((1, 2, 3))
SpatialDropout1D.__name__ = "SpatialDropout1D"
SpatialDropout2D.__name__ = "SpatialDropout2D"
SpatialDropout3D.__name__ = "SpatialDropout3D"


# ---------------------------------------------------------------------------
# embeddings & normalization
# ---------------------------------------------------------------------------


@symbolic
class Embedding(nn.Module):
    """ref: keras Embedding (zoo keras-API Embedding)."""
    input_dim: int
    output_dim: int
    init: Any = "uniform"
    W_regularizer: Optional[Regularizer] = None
    input_length: Optional[int] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        return nn.Embed(self.input_dim, self.output_dim,
                        embedding_init=get_initializer(self.init))(
                            x.astype(jnp.int32))


@symbolic
class BatchNormalization(nn.Module):
    """ref: keras BatchNormalization. Running stats live in the
    `batch_stats` collection and update during training via the Estimator's
    mutable pass."""
    epsilon: float = 1e-3
    momentum: float = 0.99
    axis: int = -1
    beta_init: Any = "zero"
    gamma_init: Any = "one"

    @nn.compact
    def __call__(self, x, train: bool = False):
        return nn.BatchNorm(
            use_running_average=not train, axis=self.axis,
            momentum=self.momentum, epsilon=self.epsilon,
            bias_init=get_initializer(self.beta_init, "zeros"),
            scale_init=get_initializer(self.gamma_init, "ones"))(x)


@symbolic
class LayerNormalization(nn.Module):
    epsilon: float = 1e-6

    @nn.compact
    def __call__(self, x, train: bool = False):
        return nn.LayerNorm(epsilon=self.epsilon)(x)


# ---------------------------------------------------------------------------
# convolution
# ---------------------------------------------------------------------------


def _conv_padding(border_mode: str):
    if border_mode in ("valid", "same"):
        return border_mode.upper()
    raise ValueError(f"border_mode must be valid|same, got {border_mode!r}")


@symbolic
class Convolution1D(nn.Module):
    """ref: keras Convolution1D. Input (B, steps, C)."""
    nb_filter: int
    filter_length: int
    init: Any = "glorot_uniform"
    activation: Any = None
    border_mode: str = "valid"
    subsample_length: int = 1
    dilation_rate: int = 1
    W_regularizer: Optional[Regularizer] = None
    b_regularizer: Optional[Regularizer] = None
    bias: bool = True
    input_shape: Optional[Tuple[int, ...]] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        y = nn.Conv(self.nb_filter, (self.filter_length,),
                    strides=(self.subsample_length,),
                    kernel_dilation=(self.dilation_rate,),
                    padding=_conv_padding(self.border_mode),
                    use_bias=self.bias,
                    kernel_init=get_initializer(self.init))(x)
        return get_activation(self.activation)(y)


@symbolic
class Convolution2D(nn.Module):
    """ref: keras Convolution2D. Input (B, H, W, C) — channels-last."""
    nb_filter: int
    nb_row: int
    nb_col: int
    init: Any = "glorot_uniform"
    activation: Any = None
    border_mode: str = "valid"
    subsample: Tuple[int, int] = (1, 1)
    dilation_rate: Tuple[int, int] = (1, 1)
    W_regularizer: Optional[Regularizer] = None
    b_regularizer: Optional[Regularizer] = None
    bias: bool = True
    dim_ordering: Optional[str] = None
    input_shape: Optional[Tuple[int, ...]] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        _check_tf_ordering(self.dim_ordering)
        y = nn.Conv(self.nb_filter, (self.nb_row, self.nb_col),
                    strides=_pair(self.subsample),
                    kernel_dilation=_pair(self.dilation_rate),
                    padding=_conv_padding(self.border_mode),
                    use_bias=self.bias,
                    kernel_init=get_initializer(self.init))(x)
        return get_activation(self.activation)(y)


@symbolic
class Convolution3D(nn.Module):
    nb_filter: int
    kernel_dim1: int
    kernel_dim2: int
    kernel_dim3: int
    activation: Any = None
    border_mode: str = "valid"
    subsample: Tuple[int, int, int] = (1, 1, 1)
    bias: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        y = nn.Conv(self.nb_filter,
                    (self.kernel_dim1, self.kernel_dim2, self.kernel_dim3),
                    strides=_pair(self.subsample, 3),
                    padding=_conv_padding(self.border_mode),
                    use_bias=self.bias)(x)
        return get_activation(self.activation)(y)


def AtrousConvolution1D(nb_filter, filter_length, atrous_rate=1, **kw):
    """ref: keras AtrousConvolution1D → dilated Conv1D."""
    return Convolution1D(nb_filter, filter_length,
                         dilation_rate=atrous_rate, **kw)


def AtrousConvolution2D(nb_filter, nb_row, nb_col, atrous_rate=(1, 1), **kw):
    return Convolution2D(nb_filter, nb_row, nb_col,
                         dilation_rate=_pair(atrous_rate), **kw)


@symbolic
class SeparableConvolution2D(nn.Module):
    """Depthwise + pointwise (ref: keras SeparableConvolution2D)."""
    nb_filter: int
    nb_row: int
    nb_col: int
    activation: Any = None
    border_mode: str = "valid"
    subsample: Tuple[int, int] = (1, 1)
    depth_multiplier: int = 1
    bias: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        c = x.shape[-1]
        y = nn.Conv(c * self.depth_multiplier, (self.nb_row, self.nb_col),
                    strides=_pair(self.subsample),
                    padding=_conv_padding(self.border_mode),
                    feature_group_count=c, use_bias=False)(x)
        y = nn.Conv(self.nb_filter, (1, 1), use_bias=self.bias)(y)
        return get_activation(self.activation)(y)


@symbolic
class Deconvolution2D(nn.Module):
    """Transposed conv (ref: keras Deconvolution2D)."""
    nb_filter: int
    nb_row: int
    nb_col: int
    activation: Any = None
    border_mode: str = "valid"
    subsample: Tuple[int, int] = (1, 1)
    bias: bool = True
    output_shape: Optional[Tuple[int, ...]] = None   # accepted, inferred

    @nn.compact
    def __call__(self, x, train: bool = False):
        y = nn.ConvTranspose(self.nb_filter, (self.nb_row, self.nb_col),
                             strides=_pair(self.subsample),
                             padding=_conv_padding(self.border_mode),
                             use_bias=self.bias)(x)
        return get_activation(self.activation)(y)


@symbolic
class LocallyConnected1D(nn.Module):
    """Unshared conv (ref: keras LocallyConnected1D): per-position weights.
    Lowered to patch extraction + one einsum so the MXU sees a single
    batched contraction."""
    nb_filter: int
    filter_length: int
    activation: Any = None
    subsample_length: int = 1
    bias: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        patches = lax.conv_general_dilated_patches(
            x, (self.filter_length,), (self.subsample_length,), "VALID",
            dimension_numbers=("NWC", "WIO", "NWC"))
        # patches: (B, L_out, C*filter_length)
        L = patches.shape[1]
        w = self.param("kernel", nn.initializers.lecun_normal(),
                       (L, patches.shape[-1], self.nb_filter))
        y = jnp.einsum("blp,lpf->blf", patches, w)
        if self.bias:
            b = self.param("bias", nn.initializers.zeros,
                           (L, self.nb_filter))
            y = y + b
        return get_activation(self.activation)(y)


@symbolic
class LocallyConnected2D(nn.Module):
    nb_filter: int
    nb_row: int
    nb_col: int
    activation: Any = None
    subsample: Tuple[int, int] = (1, 1)
    bias: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        patches = lax.conv_general_dilated_patches(
            x, (self.nb_row, self.nb_col), _pair(self.subsample), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        B, H, W, P = patches.shape
        w = self.param("kernel", nn.initializers.lecun_normal(),
                       (H, W, P, self.nb_filter))
        y = jnp.einsum("bhwp,hwpf->bhwf", patches, w)
        if self.bias:
            b = self.param("bias", nn.initializers.zeros,
                           (H, W, self.nb_filter))
            y = y + b
        return get_activation(self.activation)(y)


def _crop(x, crops):
    slices = [slice(None)]
    for (lo, hi) in crops:
        slices.append(slice(lo, x.shape[len(slices)] - hi))
    slices.append(slice(None))
    return x[tuple(slices)]


@symbolic
class Cropping1D(nn.Module):
    cropping: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x, train: bool = False):
        return _crop(x, [self.cropping])


@symbolic
class Cropping2D(nn.Module):
    cropping: Tuple[Tuple[int, int], Tuple[int, int]] = ((0, 0), (0, 0))

    @nn.compact
    def __call__(self, x, train: bool = False):
        return _crop(x, list(self.cropping))


@symbolic
class Cropping3D(nn.Module):
    cropping: Tuple = ((1, 1), (1, 1), (1, 1))

    @nn.compact
    def __call__(self, x, train: bool = False):
        return _crop(x, list(self.cropping))


@symbolic
class UpSampling1D(nn.Module):
    length: int = 2

    @nn.compact
    def __call__(self, x, train: bool = False):
        return jnp.repeat(x, self.length, axis=1)


@symbolic
class UpSampling2D(nn.Module):
    size: Tuple[int, int] = (2, 2)

    @nn.compact
    def __call__(self, x, train: bool = False):
        s = _pair(self.size)
        return jnp.repeat(jnp.repeat(x, s[0], axis=1), s[1], axis=2)


@symbolic
class UpSampling3D(nn.Module):
    size: Tuple[int, int, int] = (2, 2, 2)

    @nn.compact
    def __call__(self, x, train: bool = False):
        s = _pair(self.size, 3)
        y = jnp.repeat(x, s[0], axis=1)
        y = jnp.repeat(y, s[1], axis=2)
        return jnp.repeat(y, s[2], axis=3)


@symbolic
class ZeroPadding1D(nn.Module):
    padding: Union[int, Tuple[int, int]] = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        p = _pair(self.padding)
        return jnp.pad(x, ((0, 0), p, (0, 0)))


@symbolic
class ZeroPadding2D(nn.Module):
    padding: Union[int, Tuple[int, int]] = (1, 1)

    @nn.compact
    def __call__(self, x, train: bool = False):
        ph, pw = _pair(self.padding)
        return jnp.pad(x, ((0, 0), (ph, ph), (pw, pw), (0, 0)))


@symbolic
class ZeroPadding3D(nn.Module):
    padding: Tuple[int, int, int] = (1, 1, 1)

    @nn.compact
    def __call__(self, x, train: bool = False):
        p = _pair(self.padding, 3)
        return jnp.pad(x, ((0, 0), (p[0], p[0]), (p[1], p[1]),
                           (p[2], p[2]), (0, 0)))


# ---------------------------------------------------------------------------
# pooling
# ---------------------------------------------------------------------------


def _pool_layer(name, ndim, reducer):
    @symbolic
    class _Pool(nn.Module):
        pool_size: Any = 2
        strides: Any = None
        border_mode: str = "valid"
        pool_length: Any = None      # keras-1.2 1D spelling
        stride: Any = None

        @nn.compact
        def __call__(self, x, train: bool = False):
            size = self.pool_length if self.pool_length is not None \
                else self.pool_size
            window = _pair(size, ndim)
            st = self.stride if self.stride is not None else self.strides
            strides = _pair(st, ndim) if st is not None else window
            pad = _conv_padding(self.border_mode)
            if reducer == "max":
                return nn.max_pool(x, window, strides=strides, padding=pad)
            return nn.avg_pool(x, window, strides=strides, padding=pad)

    _Pool.__name__ = name
    return _Pool


MaxPooling1D = _pool_layer("MaxPooling1D", 1, "max")
MaxPooling2D = _pool_layer("MaxPooling2D", 2, "max")
MaxPooling3D = _pool_layer("MaxPooling3D", 3, "max")
AveragePooling1D = _pool_layer("AveragePooling1D", 1, "avg")
AveragePooling2D = _pool_layer("AveragePooling2D", 2, "avg")
AveragePooling3D = _pool_layer("AveragePooling3D", 3, "avg")


def _global_pool(name, axes, reducer):
    @symbolic
    class _GPool(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = False):
            fn = jnp.max if reducer == "max" else jnp.mean
            return fn(x, axis=axes)

    _GPool.__name__ = name
    return _GPool


GlobalMaxPooling1D = _global_pool("GlobalMaxPooling1D", (1,), "max")
GlobalMaxPooling2D = _global_pool("GlobalMaxPooling2D", (1, 2), "max")
GlobalMaxPooling3D = _global_pool("GlobalMaxPooling3D", (1, 2, 3), "max")
GlobalAveragePooling1D = _global_pool("GlobalAveragePooling1D", (1,), "avg")
GlobalAveragePooling2D = _global_pool("GlobalAveragePooling2D", (1, 2), "avg")
GlobalAveragePooling3D = _global_pool("GlobalAveragePooling3D", (1, 2, 3),
                                      "avg")


# ---------------------------------------------------------------------------
# recurrent
# ---------------------------------------------------------------------------


def _carry_hidden(cell_kind: str, carry):
    if cell_kind == "lstm":
        return carry[1]     # (c, h) → h
    return carry


class _RecurrentBase(nn.Module):
    """Shared RNN scaffolding: lax.scan via nn.RNN (XLA-friendly — no
    per-timestep python)."""
    output_dim: int = 0
    activation: Any = "tanh"
    inner_activation: Any = "sigmoid"   # keras 1.2 gate activation
    return_sequences: bool = False
    go_backwards: bool = False
    dropout: float = 0.0          # input dropout (keras dropout_W)
    input_shape: Optional[Tuple[int, ...]] = None

    _cell_kind = "simple"

    def _make_cell(self):
        act = get_activation(self.activation)
        gate = get_activation(self.inner_activation)
        if self._cell_kind == "lstm":
            return nn.OptimizedLSTMCell(self.output_dim, gate_fn=gate,
                                        activation_fn=act)
        if self._cell_kind == "gru":
            return nn.GRUCell(self.output_dim, gate_fn=gate,
                              activation_fn=act)
        return nn.SimpleCell(self.output_dim, activation_fn=act)

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.dropout:
            x = nn.Dropout(rate=self.dropout, deterministic=not train)(x)
        rnn = nn.RNN(self._make_cell(), return_carry=True,
                     reverse=self.go_backwards, keep_order=True)
        carry, seq = rnn(x)
        if self.return_sequences:
            return seq
        return _carry_hidden(self._cell_kind, carry)


@symbolic
class SimpleRNN(_RecurrentBase):
    """ref: keras SimpleRNN."""
    _cell_kind = "simple"


@symbolic
class LSTM(_RecurrentBase):
    """ref: keras LSTM (zoo keras-API LSTM)."""
    _cell_kind = "lstm"


@symbolic
class GRU(_RecurrentBase):
    """ref: keras GRU."""
    _cell_kind = "gru"


@symbolic
class ConvLSTM2D(nn.Module):
    """ref: keras ConvLSTM2D. Input (B, T, H, W, C)."""
    nb_filter: int
    nb_row: int = 3
    nb_col: int = 3
    border_mode: str = "same"
    return_sequences: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        cell = nn.ConvLSTMCell(self.nb_filter, (self.nb_row, self.nb_col),
                               padding=_conv_padding(self.border_mode))
        carry, seq = nn.RNN(cell, return_carry=True)(x)
        return seq if self.return_sequences else carry[1]


@symbolic
class Bidirectional(nn.Module):
    """ref: keras Bidirectional wrapper. `layer` must be one of our
    recurrent layers; params are NOT shared between directions."""
    layer: nn.Module = None
    merge_mode: str = "concat"

    @nn.compact
    def __call__(self, x, train: bool = False):
        # clone() defaults to parent=None (unbound); re-parent explicitly so
        # the per-direction copies bind under this module's scope
        fwd = self.layer.clone(go_backwards=False, name="forward",
                               parent=self)
        bwd = self.layer.clone(go_backwards=True, name="backward",
                               parent=self)
        a = fwd(x, train=train)
        b = bwd(x, train=train)
        if self.merge_mode == "concat":
            return jnp.concatenate([a, b], axis=-1)
        if self.merge_mode == "sum":
            return a + b
        if self.merge_mode == "mul":
            return a * b
        if self.merge_mode == "ave":
            return (a + b) / 2
        raise ValueError(f"unknown merge_mode {self.merge_mode!r}")


@symbolic
class TimeDistributed(nn.Module):
    """Apply `layer` to every timestep of (B, T, ...) — lowered to one
    reshaped call so XLA sees a single big batch (no per-step loop)."""
    layer: nn.Module = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        B, T = x.shape[0], x.shape[1]
        flat = x.reshape((B * T,) + x.shape[2:])
        from analytics_zoo_tpu.keras.engine import _call_layer
        y = _call_layer(self.layer, flat, train)
        return y.reshape((B, T) + y.shape[1:])


# ---------------------------------------------------------------------------
# keras-2 aliases (ref: zoo/pipeline/api/keras2)
# ---------------------------------------------------------------------------


def Conv1D(filters, kernel_size, strides=1, padding="valid", activation=None,
           dilation_rate=1, use_bias=True, **kw):
    return Convolution1D(filters, kernel_size, activation=activation,
                         border_mode=padding, subsample_length=strides,
                         dilation_rate=dilation_rate, bias=use_bias, **kw)


def Conv2D(filters, kernel_size, strides=(1, 1), padding="valid",
           activation=None, dilation_rate=(1, 1), use_bias=True, **kw):
    kh, kw_ = _pair(kernel_size)
    return Convolution2D(filters, kh, kw_, activation=activation,
                         border_mode=padding, subsample=_pair(strides),
                         dilation_rate=_pair(dilation_rate), bias=use_bias,
                         **kw)


def Conv3D(filters, kernel_size, strides=(1, 1, 1), padding="valid",
           activation=None, use_bias=True, **kw):
    k = _pair(kernel_size, 3)
    return Convolution3D(filters, k[0], k[1], k[2], activation=activation,
                         border_mode=padding, subsample=_pair(strides, 3),
                         bias=use_bias, **kw)


def Conv2DTranspose(filters, kernel_size, strides=(1, 1), padding="valid",
                    activation=None, use_bias=True, **kw):
    kh, kw_ = _pair(kernel_size)
    return Deconvolution2D(filters, kh, kw_, activation=activation,
                           border_mode=padding, subsample=_pair(strides),
                           bias=use_bias, **kw)


def SeparableConv2D(filters, kernel_size, strides=(1, 1), padding="valid",
                    activation=None, depth_multiplier=1, use_bias=True, **kw):
    kh, kw_ = _pair(kernel_size)
    return SeparableConvolution2D(filters, kh, kw_, activation=activation,
                                  border_mode=padding,
                                  subsample=_pair(strides),
                                  depth_multiplier=depth_multiplier,
                                  bias=use_bias, **kw)


MaxPool1D = MaxPooling1D
MaxPool2D = MaxPooling2D
MaxPool3D = MaxPooling3D
AvgPool1D = AveragePooling1D
AvgPool2D = AveragePooling2D
AvgPool3D = AveragePooling3D


# ---------------------------------------------------------------------------
# tensor-manipulation / elementwise layers (zoo additions — ref:
# zoo pipeline/api/keras/layers Select/Narrow/Squeeze/Exp/Log/Power/
# Sqrt/Square/Abs/Negative/CAdd/CMul/Scale/SReLU/LRN2D/ResizeBilinear.
# In BigDL these existed because graphs could not use host control flow;
# here each is a thin named wrapper over the obvious jnp op so ported
# model definitions keep their vocabulary.)
# ---------------------------------------------------------------------------


@symbolic
class Select(nn.Module):
    """ref: Select(dim, index) — pick one slice along `dim` (dim counts
    the batch axis, like the reference; negative dims allowed)."""
    dim: int
    index: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        d = x.shape[self.dim]
        if not -d <= self.index < d:
            # jnp.take would silently fill NaNs for an OOB index
            raise ValueError(
                f"Select index {self.index} out of range for dim "
                f"{self.dim} of size {d}")
        return jnp.take(x, self.index, axis=self.dim)


@symbolic
class Narrow(nn.Module):
    """ref: Narrow(dim, offset, length) — contiguous slice along `dim`."""
    dim: int
    offset: int
    length: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        return lax.slice_in_dim(x, self.offset, self.offset + self.length,
                                axis=self.dim % x.ndim)


@symbolic
class Squeeze(nn.Module):
    """ref: Squeeze(dim) — drop a size-1 axis (dim=None: every size-1
    axis EXCEPT the batch axis, matching the reference's sample-level
    semantics; squeezing axis 0 would break batched serving's unpad
    slicing for batch-size-1 requests)."""
    dim: Optional[int] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.dim is None:
            axes = tuple(i for i in range(1, x.ndim) if x.shape[i] == 1)
            return jnp.squeeze(x, axis=axes) if axes else x
        if self.dim % x.ndim == 0:
            raise ValueError("Squeeze cannot drop the batch axis")
        return jnp.squeeze(x, axis=self.dim)


@symbolic
class ExpandDim(nn.Module):
    """ref: ExpandDim(dim) — insert a size-1 axis."""
    dim: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        return jnp.expand_dims(x, self.dim)


def _elementwise(name: str, fn):
    @symbolic
    class _E(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = False):
            return fn(x)

    _E.__name__ = _E.__qualname__ = name
    _E.__doc__ = f"ref: zoo keras layer {name} — elementwise jnp.{name.lower()}."
    return _E


Exp = _elementwise("Exp", jnp.exp)
Log = _elementwise("Log", jnp.log)
Sqrt = _elementwise("Sqrt", jnp.sqrt)
Square = _elementwise("Square", jnp.square)
Abs = _elementwise("Abs", jnp.abs)
Negative = _elementwise("Negative", jnp.negative)


@symbolic
class Power(nn.Module):
    """ref: Power(power, scale, shift) — (scale*x + shift) ** power."""
    power: float
    scale: float = 1.0
    shift: float = 0.0

    @nn.compact
    def __call__(self, x, train: bool = False):
        return jnp.power(self.scale * x + self.shift, self.power)


@symbolic
class CAdd(nn.Module):
    """ref: CAdd(size) — learnable per-element bias, broadcast to x."""
    size: Sequence[int]

    @nn.compact
    def __call__(self, x, train: bool = False):
        b = self.param("bias", constant_init(0.0), tuple(self.size))
        return x + b


@symbolic
class CMul(nn.Module):
    """ref: CMul(size) — learnable per-element scale, broadcast to x."""
    size: Sequence[int]

    @nn.compact
    def __call__(self, x, train: bool = False):
        w = self.param("weight", constant_init(1.0), tuple(self.size))
        return x * w


@symbolic
class Scale(nn.Module):
    """ref: Scale(size) — learnable elementwise affine (CMul then CAdd)."""
    size: Sequence[int]

    @nn.compact
    def __call__(self, x, train: bool = False):
        w = self.param("weight", constant_init(1.0), tuple(self.size))
        b = self.param("bias", constant_init(0.0), tuple(self.size))
        return x * w + b


@symbolic
class SReLU(nn.Module):
    """ref: SReLU — s-shaped rectifier with four learnable per-channel
    parameters (t_r, a_r, t_l, a_l): y = t_r + a_r*(x - t_r) for x >= t_r,
    x in between, t_l + a_l*(x - t_l) for x <= t_l."""

    @nn.compact
    def __call__(self, x, train: bool = False):
        shape = (x.shape[-1],)
        t_l = self.param("t_left", constant_init(0.0), shape)
        a_l = self.param("a_left", constant_init(0.2), shape)
        t_r = self.param("t_right", constant_init(1.0), shape)
        a_r = self.param("a_right", constant_init(0.2), shape)
        y = jnp.where(x >= t_r, t_r + a_r * (x - t_r), x)
        return jnp.where(x <= t_l, t_l + a_l * (x - t_l), y)


@symbolic
class LRN2D(nn.Module):
    """ref: LRN2D — local response normalization across channels (NHWC):
    x / (k + alpha/n * sum_{channel window} x^2) ** beta."""
    alpha: float = 1e-4
    k: float = 1.0
    beta: float = 0.75
    n: int = 5

    @nn.compact
    def __call__(self, x, train: bool = False):
        sq = jnp.square(x)
        half = self.n // 2
        # sum over a channel window via reduce_window on the last axis
        window = (1,) * (x.ndim - 1) + (self.n,)
        pads = [(0, 0)] * (x.ndim - 1) + [(half, self.n - 1 - half)]
        ssum = lax.reduce_window(sq, 0.0, lax.add, window,
                                 (1,) * x.ndim, pads)
        return x / jnp.power(self.k + self.alpha / self.n * ssum, self.beta)


@symbolic
class ResizeBilinear(nn.Module):
    """ref: ResizeBilinear(output_height, output_width) — NHWC resize."""
    output_height: int
    output_width: int
    align_corners: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.align_corners:
            # jax.image.resize only implements half-pixel sampling;
            # silently producing different pixel values would be a quiet
            # parity break (cf. _check_tf_ordering's loud refusal)
            raise ValueError(
                "align_corners=True is not supported (jax.image.resize "
                "uses half-pixel centers); re-export the model with "
                "align_corners=False")
        shape = (x.shape[0], self.output_height, self.output_width,
                 x.shape[-1])
        return jax.image.resize(x, shape, method="bilinear")


__all__ += [
    "Select", "Narrow", "Squeeze", "ExpandDim",
    "Exp", "Log", "Sqrt", "Square", "Abs", "Negative", "Power",
    "CAdd", "CMul", "Scale", "SReLU", "LRN2D", "ResizeBilinear",
]

"""Keras-1.2-compatible API on flax/JAX.

Reference parity: zoo/pipeline/api/keras/{layers,models,objectives,metrics}
and pyzoo/zoo/pipeline/api/keras — the reference reimplements the Keras 1.2.2
surface over BigDL tensors; here the same surface is a thin, tpu-idiomatic
adapter over flax modules compiled by the shared Estimator (one pjit'd train
step; XLA emits the collectives).
"""

from analytics_zoo_tpu.keras.engine import (Input, KerasNet, Model,
                                            Sequential, merge)
from analytics_zoo_tpu.keras import layers  # noqa: F401
from analytics_zoo_tpu.keras.layers import *  # noqa: F401,F403
from analytics_zoo_tpu.keras.optimizers import get_optimizer
from analytics_zoo_tpu.keras.regularizers import l1, l1l2, l2

__all__ = [
    "Input", "KerasNet", "Model", "Sequential", "merge",
    "get_optimizer", "l1", "l2", "l1l2", "layers",
] + layers.__all__

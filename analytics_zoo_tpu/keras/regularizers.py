"""L1/L2 weight regularizers (ref: keras-API `W_regularizer=l2(...)`,
zoo/pipeline/api/keras — BigDL L1L2Regularizer).

A regularizer is a spec object; the penalty is computed by ``KerasNet`` by
walking the param tree at loss time, so it fuses into the jitted train step.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp


@dataclass(frozen=True)
class Regularizer:
    l1: float = 0.0
    l2: float = 0.0

    def __call__(self, w: jnp.ndarray) -> jnp.ndarray:
        pen = jnp.zeros((), dtype=jnp.float32)
        if self.l1:
            pen = pen + self.l1 * jnp.sum(jnp.abs(w))
        if self.l2:
            pen = pen + self.l2 * jnp.sum(jnp.square(w))
        return pen


def l1(v: float = 0.01) -> Regularizer:
    return Regularizer(l1=v)


def l2(v: float = 0.01) -> Regularizer:
    return Regularizer(l2=v)


def l1l2(l1_v: float = 0.01, l2_v: float = 0.01) -> Regularizer:
    return Regularizer(l1=l1_v, l2=l2_v)

"""Keras objective catalog (ref: zoo/pipeline/api/keras/objectives/ —
MeanSquaredError, KullbackLeiblerDivergence, Poisson, CosineProximity,
Hinge, SquaredHinge, MSLE, MAPE, ...).  Extends the shared Estimator loss
registry; all are pure jnp `(preds, targets) -> scalar` so they fuse into
the train step."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.learn.objectives import (  # noqa: F401
    LossFn, binary_crossentropy, categorical_crossentropy, huber,
    mean_absolute_error, mean_squared_error,
    sparse_categorical_crossentropy)
from analytics_zoo_tpu.learn import objectives as _base

__all__ = [
    "get_loss", "kullback_leibler_divergence", "poisson",
    "cosine_proximity", "hinge", "squared_hinge",
    "mean_squared_logarithmic_error", "mean_absolute_percentage_error",
]

_EPS = 1e-7


def kullback_leibler_divergence(preds, targets):
    """Targets and preds are probability distributions over the last axis."""
    p = jnp.clip(targets, _EPS, 1.0)
    q = jnp.clip(preds, _EPS, 1.0)
    return jnp.mean(jnp.sum(p * jnp.log(p / q), axis=-1))


def poisson(preds, targets):
    return jnp.mean(preds - targets * jnp.log(preds + _EPS))


def cosine_proximity(preds, targets):
    p = preds / (jnp.linalg.norm(preds, axis=-1, keepdims=True) + _EPS)
    t = targets / (jnp.linalg.norm(targets, axis=-1, keepdims=True) + _EPS)
    return -jnp.mean(jnp.sum(p * t, axis=-1))


def hinge(preds, targets):
    """Targets in {-1, 1}."""
    return jnp.mean(jax.nn.relu(1.0 - targets * preds))


def squared_hinge(preds, targets):
    return jnp.mean(jnp.square(jax.nn.relu(1.0 - targets * preds)))


def mean_squared_logarithmic_error(preds, targets):
    a = jnp.log(jnp.clip(preds, _EPS, None) + 1.0)
    b = jnp.log(jnp.clip(targets, _EPS, None) + 1.0)
    return jnp.mean(jnp.square(a - b))


def mean_absolute_percentage_error(preds, targets):
    return 100.0 * jnp.mean(
        jnp.abs((targets - preds) / jnp.clip(jnp.abs(targets), _EPS, None)))


_EXTRA = {
    "kld": kullback_leibler_divergence,
    "kullback_leibler_divergence": kullback_leibler_divergence,
    "poisson": poisson,
    "cosine_proximity": cosine_proximity,
    "cosine": cosine_proximity,
    "hinge": hinge,
    "squared_hinge": squared_hinge,
    "msle": mean_squared_logarithmic_error,
    "mean_squared_logarithmic_error": mean_squared_logarithmic_error,
    "mape": mean_absolute_percentage_error,
    "mean_absolute_percentage_error": mean_absolute_percentage_error,
}

# one registry: keras names resolve everywhere Estimators resolve losses
_base._LOSSES.update(_EXTRA)
get_loss = _base.get_loss

"""Keras init strings → jax initializers (ref: keras-API `init=` arg,
zoo/pipeline/api/keras layers accept "glorot_uniform", "one", ...)."""

from __future__ import annotations

from typing import Callable, Union

import jax.numpy as jnp
from jax.nn import initializers as ji


_INITS = {
    "glorot_uniform": lambda: ji.glorot_uniform(),
    "glorot_normal": lambda: ji.glorot_normal(),
    "he_uniform": lambda: ji.he_uniform(),
    "he_normal": lambda: ji.he_normal(),
    "lecun_uniform": lambda: ji.lecun_uniform(),
    "lecun_normal": lambda: ji.lecun_normal(),
    "uniform": lambda: ji.uniform(scale=0.05),
    "normal": lambda: ji.normal(stddev=0.05),
    "zero": lambda: ji.zeros,
    "zeros": lambda: ji.zeros,
    "one": lambda: ji.ones,
    "ones": lambda: ji.ones,
    "orthogonal": lambda: ji.orthogonal(),
}


def get_initializer(init: Union[str, Callable, None], default="glorot_uniform"):
    if init is None:
        init = default
    if callable(init):
        return init
    try:
        return _INITS[init.lower()]()
    except KeyError:
        raise ValueError(f"unknown initializer {init!r}; one of {sorted(_INITS)}")


def constant_init(value: float):
    def init(key, shape, dtype=jnp.float32):
        return jnp.full(shape, value, dtype)
    return init

"""TorchNet — run PyTorch models on TPU by converting them to JAX.

Reference surface (SURVEY.md §2.3, ref: zoo pipeline/api/net/TorchNet.scala
+ native libtorch JNI bindings): the reference executes TorchScript modules
inside the JVM via libtorch so torch models can ride its optimizer/serving
stack.

TPU re-design: instead of embedding libtorch (CPU-only here, and a foreign
runtime XLA cannot fuse into), the module's ``torch.fx`` graph is converted
ONCE into a pure JAX function + param pytrees pulled from ``state_dict``.
The converted model is a first-class citizen: it jits, shards, trains under
the pjit Estimator (``Estimator.from_torch``), and serves through
InferenceModel — the whole forward compiles to one XLA program.

State split: trainable weights live in the ``params`` collection; BatchNorm
running stats and ``get_attr`` buffers live in ``batch_stats`` (flax's
non-trainable collection), so ``fit`` never optimizer-updates them —
frozen-stats fine-tune semantics, matching how the reference ran TorchNet
forward passes in eval mode.  Param paths keep the module tree nesting
(``block.0.weight`` -> params["block"]["0"]["weight"]), so distinct torch
paths can never collide.

Scope: the fx-traceable eval-mode subset that covers the reference's
TorchNet usage (MLPs, ConvNets, embeddings, attention-free nets).
Unsupported layers/configs raise NotImplementedError at conversion time —
never convert silently wrong.  Dynamic control flow in ``forward`` is
rejected by fx tracing itself, the same limitation TorchScript tracing had.
"""

from __future__ import annotations

import operator
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _np(t) -> jnp.ndarray:
    return jnp.asarray(t.detach().cpu().numpy())


def _set_nested(tree: Dict, path: Tuple[str, ...], value):
    for part in path[:-1]:
        tree = tree.setdefault(part, {})
    tree[path[-1]] = value


def _get_nested(tree: Dict, path: Tuple[str, ...], default=None):
    for part in path:
        if not isinstance(tree, dict) or part not in tree:
            return default if default is not None else {}
        tree = tree[part]
    return tree


def _merge_trees(a: Dict, b: Dict) -> Dict:
    """Recursive dict union (b wins on leaf conflicts — there are none by
    construction: trainable and frozen leaves have distinct names)."""
    out = dict(a)
    for k, v in b.items():
        if k in out and isinstance(out[k], dict) and isinstance(v, dict):
            out[k] = _merge_trees(out[k], v)
        else:
            out[k] = v
    return out


# ---------------------------------------------------------------------------
# module handlers: torch module -> (trainable, frozen, jax fn(p, *inputs))
# ---------------------------------------------------------------------------

_MODULE_HANDLERS: Dict[type, Callable] = {}


def register_module(torch_type):
    def deco(fn):
        _MODULE_HANDLERS[torch_type] = fn
        return fn
    return deco


def _build_module_handlers():
    import torch.nn as tnn

    @register_module(tnn.Linear)
    def linear(m):
        p = {"weight": _np(m.weight)}
        if m.bias is not None:
            p["bias"] = _np(m.bias)

        def fn(p, x):
            y = x @ p["weight"].T
            return y + p["bias"] if "bias" in p else y
        return p, {}, fn

    @register_module(tnn.Embedding)
    def embedding(m):
        p = {"weight": _np(m.weight)}
        return p, {}, lambda p, x: jnp.take(p["weight"], x, axis=0)

    def _conv(m, nd):
        p = {"weight": _np(m.weight)}
        if m.bias is not None:
            p["bias"] = _np(m.bias)
        stride = m.stride if isinstance(m.stride, tuple) else (m.stride,) * nd
        dil = m.dilation if isinstance(m.dilation, tuple) \
            else (m.dilation,) * nd
        groups = m.groups
        pad = m.padding
        if isinstance(pad, str):
            pad = pad.upper()       # "same"/"valid"
        else:
            pad = pad if isinstance(pad, tuple) else (pad,) * nd
            pad = [(p_, p_) for p_ in pad]
        dims = ("NCH", "OIH", "NCH") if nd == 1 else ("NCHW", "OIHW", "NCHW")

        def fn(p, x):
            y = jax.lax.conv_general_dilated(
                x, p["weight"], window_strides=stride, padding=pad,
                rhs_dilation=dil, dimension_numbers=dims,
                feature_group_count=groups)
            if "bias" in p:
                y = y + p["bias"].reshape((1, -1) + (1,) * nd)
            return y
        return p, {}, fn

    register_module(tnn.Conv1d)(lambda m: _conv(m, 1))
    register_module(tnn.Conv2d)(lambda m: _conv(m, 2))

    def _bn(m):
        # running stats are FROZEN state (batch_stats collection), not
        # trainable params — fit must never optimizer-update them
        frozen = {"mean": _np(m.running_mean), "var": _np(m.running_var)}
        p = {}
        if m.affine:
            p = {"weight": _np(m.weight), "bias": _np(m.bias)}
        eps = m.eps

        def fn(p, x):
            shape = (1, -1) + (1,) * (x.ndim - 2)
            y = (x - p["mean"].reshape(shape)) * jax.lax.rsqrt(
                p["var"].reshape(shape) + eps)
            if "weight" in p:
                y = y * p["weight"].reshape(shape) + p["bias"].reshape(shape)
            return y
        return p, frozen, fn

    register_module(tnn.BatchNorm1d)(_bn)
    register_module(tnn.BatchNorm2d)(_bn)

    @register_module(tnn.LayerNorm)
    def layernorm(m):
        p = {}
        if m.elementwise_affine:
            p = {"weight": _np(m.weight), "bias": _np(m.bias)}
        nd, eps = len(m.normalized_shape), m.eps

        def fn(p, x):
            axes = tuple(range(x.ndim - nd, x.ndim))
            mu = jnp.mean(x, axes, keepdims=True)
            var = jnp.var(x, axes, keepdims=True)
            y = (x - mu) * jax.lax.rsqrt(var + eps)
            if "weight" in p:
                y = y * p["weight"] + p["bias"]
            return y
        return p, {}, fn

    @register_module(tnn.GroupNorm)
    def groupnorm(m):
        p = {"weight": _np(m.weight), "bias": _np(m.bias)} if m.affine \
            else {}
        g, eps = m.num_groups, m.eps

        def fn(p, x):
            n, c = x.shape[:2]
            xg = x.reshape((n, g, c // g) + x.shape[2:])
            axes = tuple(range(2, xg.ndim))
            mu = jnp.mean(xg, axes, keepdims=True)
            var = jnp.var(xg, axes, keepdims=True)
            y = ((xg - mu) * jax.lax.rsqrt(var + eps)).reshape(x.shape)
            if "weight" in p:
                shape = (1, -1) + (1,) * (x.ndim - 2)
                y = y * p["weight"].reshape(shape) + p["bias"].reshape(shape)
            return y
        return p, {}, fn

    # -- stateless modules ------------------------------------------------
    def _stateless(make):
        return lambda m: ({}, {}, make(m))

    register_module(tnn.ReLU)(_stateless(lambda m: lambda p, x:
                                         jax.nn.relu(x)))
    register_module(tnn.ReLU6)(_stateless(lambda m: lambda p, x:
                                          jnp.clip(x, 0, 6)))
    register_module(tnn.Sigmoid)(_stateless(lambda m: lambda p, x:
                                            jax.nn.sigmoid(x)))
    register_module(tnn.Tanh)(_stateless(lambda m: lambda p, x:
                                         jnp.tanh(x)))
    register_module(tnn.GELU)(_stateless(
        lambda m: lambda p, x: jax.nn.gelu(
            x, approximate=m.approximate != "none")))
    register_module(tnn.SiLU)(_stateless(lambda m: lambda p, x:
                                         jax.nn.silu(x)))
    register_module(tnn.LeakyReLU)(_stateless(
        lambda m: lambda p, x: jax.nn.leaky_relu(x, m.negative_slope)))
    register_module(tnn.Softmax)(_stateless(
        lambda m: lambda p, x: jax.nn.softmax(x, axis=m.dim)))
    register_module(tnn.LogSoftmax)(_stateless(
        lambda m: lambda p, x: jax.nn.log_softmax(x, axis=m.dim)))
    register_module(tnn.Dropout)(_stateless(
        lambda m: lambda p, x: x))          # eval semantics
    register_module(tnn.Identity)(_stateless(lambda m: lambda p, x: x))
    register_module(tnn.Flatten)(_stateless(
        lambda m: lambda p, x: _flatten(x, m.start_dim, m.end_dim)))

    def _pool(m, nd, kind):
        if getattr(m, "ceil_mode", False):
            raise NotImplementedError(
                f"{type(m).__name__}(ceil_mode=True) is not supported")
        if kind == "max" and getattr(m, "dilation", 1) not in (1, (1,) * nd):
            raise NotImplementedError(
                f"{type(m).__name__}(dilation != 1) is not supported")
        if kind == "avg" and not getattr(m, "count_include_pad", True):
            raise NotImplementedError(
                f"{type(m).__name__}(count_include_pad=False) is not "
                "supported")
        ks = m.kernel_size if isinstance(m.kernel_size, tuple) \
            else (m.kernel_size,) * nd
        st = m.stride if isinstance(m.stride, tuple) else \
            (m.stride,) * nd if m.stride else ks
        pd = m.padding if isinstance(m.padding, tuple) \
            else (m.padding,) * nd
        window = (1, 1) + ks
        strides = (1, 1) + st
        pads = ((0, 0), (0, 0)) + tuple((p_, p_) for p_ in pd)

        def maxfn(p, x):
            return jax.lax.reduce_window(
                x, -jnp.inf, jax.lax.max, window, strides, pads)

        def avgfn(p, x):
            s = jax.lax.reduce_window(
                x, 0.0, jax.lax.add, window, strides, pads)
            return s / float(np.prod(ks))
        return {}, {}, (maxfn if kind == "max" else avgfn)

    register_module(tnn.MaxPool1d)(lambda m: _pool(m, 1, "max"))
    register_module(tnn.MaxPool2d)(lambda m: _pool(m, 2, "max"))
    register_module(tnn.AvgPool1d)(lambda m: _pool(m, 1, "avg"))
    register_module(tnn.AvgPool2d)(lambda m: _pool(m, 2, "avg"))

    @register_module(tnn.AdaptiveAvgPool2d)
    def adaptive_avg(m):
        out = m.output_size
        out = (out, out) if isinstance(out, int) else out
        if tuple(out) != (1, 1):
            raise NotImplementedError(
                f"AdaptiveAvgPool2d{tuple(out)}: only (1, 1) supported")
        return {}, {}, lambda p, x: jnp.mean(x, axis=(2, 3), keepdims=True)


def _flatten(x, start_dim=1, end_dim=-1):
    end = end_dim if end_dim >= 0 else x.ndim + end_dim
    shape = x.shape[:start_dim] + (-1,) + x.shape[end + 1:]
    return jnp.reshape(x, shape)


def _chunk(x, n, dim=0):
    """torch.chunk semantics: ceil-sized chunks, short last chunk OK
    (jnp.split requires even division; np.array_split balances — both
    differ from torch)."""
    size = -(-x.shape[dim] // n)
    cuts = list(range(size, x.shape[dim], size))
    return jnp.split(x, cuts, axis=dim)


# ---------------------------------------------------------------------------
# function / method translation tables
# ---------------------------------------------------------------------------

def _function_table() -> Dict[Any, Callable]:
    import torch
    import torch.nn.functional as F

    t = {
        operator.add: operator.add, operator.sub: operator.sub,
        operator.mul: operator.mul, operator.truediv: operator.truediv,
        operator.neg: operator.neg, operator.matmul: jnp.matmul,
        operator.getitem: lambda x, i: x[i],
        operator.pow: operator.pow,
        torch.add: jnp.add, torch.sub: jnp.subtract,
        torch.mul: jnp.multiply, torch.div: jnp.divide,
        torch.matmul: jnp.matmul, torch.bmm: jnp.matmul,
        torch.relu: jax.nn.relu, F.relu: jax.nn.relu,
        torch.sigmoid: jax.nn.sigmoid, F.sigmoid: jax.nn.sigmoid,
        torch.tanh: jnp.tanh, F.tanh: jnp.tanh,
        # torch default is the exact erf GELU; 'tanh' selects the approx
        F.gelu: lambda x, approximate="none": jax.nn.gelu(
            x, approximate=approximate != "none"),
        F.silu: jax.nn.silu,
        torch.exp: jnp.exp, torch.log: jnp.log, torch.sqrt: jnp.sqrt,
        torch.abs: jnp.abs, torch.clamp: jnp.clip,
        torch.squeeze: jnp.squeeze,
        torch.flatten: lambda x, start_dim=0, end_dim=-1:
            _flatten(x, start_dim, end_dim),
        torch.sum: lambda x, dim=None, keepdim=False:
            jnp.sum(x, axis=dim, keepdims=keepdim),
        torch.mean: lambda x, dim=None, keepdim=False:
            jnp.mean(x, axis=dim, keepdims=keepdim),
        torch.unsqueeze: lambda x, dim: jnp.expand_dims(x, dim),
        torch.transpose: lambda x, a, b: jnp.swapaxes(x, a, b),
        torch.permute: lambda x, dims: jnp.transpose(x, dims),
        torch.reshape: lambda x, shape: jnp.reshape(x, shape),
        torch.cat: lambda ts, dim=0: jnp.concatenate(ts, axis=dim),
        torch.stack: lambda ts, dim=0: jnp.stack(ts, axis=dim),
        torch.chunk: _chunk,
        torch.softmax: lambda x, dim: jax.nn.softmax(x, axis=dim),
        F.softmax: lambda x, dim=None: jax.nn.softmax(x, axis=dim),
        F.log_softmax: lambda x, dim=None: jax.nn.log_softmax(x, axis=dim),
        F.dropout: lambda x, p=0.5, training=False: x,
    }
    return t


_METHODS: Dict[str, Callable] = {
    "view": lambda x, *shape: jnp.reshape(
        x, shape[0] if len(shape) == 1 and isinstance(shape[0], (tuple,
                                                                 list))
        else shape),
    "reshape": lambda x, *shape: jnp.reshape(
        x, shape[0] if len(shape) == 1 and isinstance(shape[0], (tuple,
                                                                 list))
        else shape),
    # Tensor.flatten() defaults start_dim=0 (nn.Flatten defaults to 1)
    "flatten": lambda x, start_dim=0, end_dim=-1:
        _flatten(x, start_dim, end_dim),
    "permute": lambda x, *dims: jnp.transpose(
        x, dims[0] if len(dims) == 1 and isinstance(dims[0], (tuple, list))
        else dims),
    "transpose": lambda x, a, b: jnp.swapaxes(x, a, b),
    "contiguous": lambda x: x,
    "squeeze": lambda x, dim=None: jnp.squeeze(x, dim),
    "unsqueeze": lambda x, dim: jnp.expand_dims(x, dim),
    "size": lambda x, dim=None: x.shape if dim is None else x.shape[dim],
    "mean": lambda x, dim=None, keepdim=False:
        jnp.mean(x, axis=dim, keepdims=keepdim),
    "sum": lambda x, dim=None, keepdim=False:
        jnp.sum(x, axis=dim, keepdims=keepdim),
    "float": lambda x: x.astype(jnp.float32),
    "t": lambda x: x.T,
    "repeat": lambda x, *reps: jnp.tile(x, reps),
    "chunk": _chunk,
}


# ---------------------------------------------------------------------------
# the converter
# ---------------------------------------------------------------------------

class TorchNet:
    """A torch module converted to (param pytrees, pure JAX function).

    Implements the flax init/apply protocol the pjit Estimator consumes, so
    a converted model trains/predicts exactly like a native flax model:

        net = TorchNet.from_torch(torch_module)
        y = net(net.params, x)                      # trainable-only call
        est = Estimator.from_torch(model=torch_module, loss=..., ...)

    ``params`` holds trainable weights; ``buffers`` holds BatchNorm running
    stats and registered buffers (exposed to the Estimator as the
    ``batch_stats`` collection so the optimizer never touches them).
    """

    def __init__(self, fn: Callable, params: Dict[str, Any],
                 buffers: Dict[str, Any], n_inputs: int):
        self._fn = fn
        self.params = params
        self.buffers = buffers
        self.n_inputs = n_inputs

    def __call__(self, params, *inputs):
        return self._fn(_merge_trees(self.buffers, params), *inputs)

    # -- flax protocol (FlaxEstimator / InferenceModel) ------------------
    def init(self, rngs, *inputs, **kw):
        out = {"params": self.params}
        if self.buffers:
            out["batch_stats"] = self.buffers
        return out

    def apply(self, variables, *inputs, mutable=None, rngs=None, **kw):
        merged = _merge_trees(variables.get("batch_stats") or {},
                              variables["params"])
        out = self._fn(merged, *inputs)
        if mutable:
            # stats are frozen by design: echo them back unchanged
            return out, {"batch_stats": variables.get("batch_stats")}
        return out

    @staticmethod
    def from_torch(module, example_inputs=None) -> "TorchNet":
        """Convert a torch module via torch.fx tracing (weights are read in
        eval mode; the module's own train/eval flag is restored after)."""
        import torch.fx as fx

        was_training = module.training
        module.eval()
        try:
            return TorchNet._convert(module, fx, example_inputs)
        finally:
            module.train(was_training)

    @staticmethod
    def _convert(module, fx, example_inputs):
        gm = fx.symbolic_trace(module)
        ftable = _function_table()

        params: Dict[str, Any] = {}
        buffers: Dict[str, Any] = {}
        handlers: Dict[str, Tuple[Tuple[str, ...], Callable]] = {}
        n_inputs = 0
        for node in gm.graph.nodes:
            if node.op == "placeholder":
                n_inputs += 1
            elif node.op == "call_module":
                sub = gm.get_submodule(node.target)
                h = _MODULE_HANDLERS.get(type(sub))
                if h is None:
                    raise NotImplementedError(
                        f"no TorchNet handler for {type(sub).__name__} "
                        f"(at '{node.target}'); supported: "
                        f"{sorted(t.__name__ for t in _MODULE_HANDLERS)}")
                p, frozen, fn = h(sub)
                path = tuple(node.target.split("."))
                if p:
                    _set_nested(params, path, p)
                if frozen:
                    _set_nested(buffers, path, frozen)
                handlers[node.target] = (path, fn)
            elif node.op == "get_attr":
                import torch

                t = gm
                for part in node.target.split("."):
                    t = getattr(t, part)
                # direct nn.Parameter attributes (e.g. self.scale used in
                # forward) are TRAINABLE; registered buffers/constants are
                # not
                dest = params if isinstance(t, torch.nn.Parameter) \
                    else buffers
                _set_nested(dest, ("_attrs",) + tuple(
                    node.target.split(".")), _np(t))
            elif node.op == "call_function":
                if node.target not in ftable:
                    raise NotImplementedError(
                        f"no TorchNet translation for function "
                        f"{getattr(node.target, '__name__', node.target)}")
            elif node.op == "call_method":
                if node.target not in _METHODS:
                    raise NotImplementedError(
                        f"no TorchNet translation for method "
                        f".{node.target}()")

        graph = gm.graph
        from torch.fx.node import map_arg

        def run(p, *inputs):
            env: Dict[str, Any] = {}
            it = iter(inputs)

            def lookup(a):
                # fx's own arg mapper: resolves Nodes inside its immutable
                # list/dict containers (which jax.tree_map treats as leaves)
                return map_arg(a, lambda n: env[n.name])

            for node in graph.nodes:
                if node.op == "placeholder":
                    env[node.name] = next(it)
                elif node.op == "get_attr":
                    env[node.name] = _get_nested(
                        p, ("_attrs",) + tuple(node.target.split(".")))
                elif node.op == "call_module":
                    path, fn = handlers[node.target]
                    args = lookup(list(node.args))
                    kwargs = lookup(dict(node.kwargs))
                    env[node.name] = fn(_get_nested(p, path), *args,
                                        **kwargs)
                elif node.op == "call_function":
                    args = lookup(list(node.args))
                    kwargs = lookup(dict(node.kwargs))
                    env[node.name] = ftable[node.target](*args, **kwargs)
                elif node.op == "call_method":
                    args = lookup(list(node.args))
                    kwargs = lookup(dict(node.kwargs))
                    env[node.name] = _METHODS[node.target](*args, **kwargs)
                elif node.op == "output":
                    return lookup(node.args[0])
            raise RuntimeError("fx graph had no output node")

        net = TorchNet(run, params, buffers, n_inputs)
        if example_inputs is not None:
            xs = [jnp.asarray(np.asarray(x)) for x in example_inputs]
            net(net.params, *xs)   # smoke-run the conversion eagerly
        return net


_build_module_handlers()

"""TFNet — foreign TensorFlow model import, compiled to TPU via JAX.

Reference surface (SURVEY.md §2.3; ref: zoo pipeline/api/net/TFNet.scala +
GraphRunner): load a frozen TF graph / SavedModel and serve forward-only
``predict`` as a layer of the native runtime.  The reference executed the
graph with libtensorflow JNI; translating that design would put a TF
interpreter in the serving path and keep the model off the TPU.

TPU re-design: the TF graph is *translated once, at load time*, into a pure
JAX function (GraphDef node -> jnp/lax op), with the frozen weights lifted
into a param pytree.  The result jits, shards, and fuses under XLA exactly
like a native flax model — TF is needed only at import time, never at
serving time.

Import paths:
  TFNet.from_saved_model(dir)        SavedModel signature -> TFNet
  TFNet.from_keras(model_or_path)    tf.keras model / .keras / .h5 file
  TFNet.from_concrete_function(fn)   any tf.function concrete fn

Supported op set covers the inference graphs tf.keras emits for MLP / CNN /
BN / pooling / embedding / attention-free models; unsupported ops raise
NotImplementedError naming the op so coverage gaps are explicit, mirroring
TorchNet's conversion contract (torch_net.py).
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# consts with at least this many elements become trainable-tree params
# (weights); smaller ones stay static (shapes, axes, paddings, scalars)
_PARAM_MIN_ELEMS = 16


def _sanitize(name: str) -> str:
    return re.sub(r"[^0-9a-zA-Z_]", "_", name)


def _attr(node, key, default=None):
    if key not in node.attr:
        return default
    a = node.attr[key]
    kind = a.WhichOneof("value")
    if kind == "i":
        return a.i
    if kind == "f":
        return a.f
    if kind == "b":
        return a.b
    if kind == "s":
        return a.s.decode()
    if kind == "type":
        return a.type
    if kind == "shape":
        return [d.size for d in a.shape.dim]
    if kind == "list":
        lst = a.list
        for f in ("i", "f", "b", "s"):
            vals = list(getattr(lst, f))
            if vals:
                return vals
        return []
    return default


def _tf_dtype_to_np(enum) -> np.dtype:
    from tensorflow.python.framework import dtypes

    return np.dtype(dtypes.as_dtype(enum).as_numpy_dtype)


def _const_value(node) -> np.ndarray:
    from tensorflow.python.framework import tensor_util

    return tensor_util.MakeNdarray(node.attr["value"].tensor)


def _pool(x, node, kind):
    ksize = _attr(node, "ksize")
    strides = _attr(node, "strides")
    pad = _attr(node, "padding")
    if _attr(node, "data_format", "NHWC") != "NHWC":
        raise NotImplementedError("only NHWC pooling is supported")
    dims = (1, ksize[1], ksize[2], 1)
    strd = (1, strides[1], strides[2], 1)
    if kind == "max":
        return jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, dims, strd, pad)
    summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, strd, pad)
    if pad == "VALID":
        return summed / (ksize[1] * ksize[2])
    ones = jnp.ones(x.shape[1:3] + (1,), x.dtype)[None]
    counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, dims, strd, pad)
    return summed / counts


def _conv2d(x, w, node):
    strides = _attr(node, "strides")
    pad = _attr(node, "padding")
    if _attr(node, "data_format", "NHWC") != "NHWC":
        raise NotImplementedError("only NHWC Conv2D is supported")
    dil = _attr(node, "dilations") or (1, 1, 1, 1)
    if pad == "EXPLICIT":
        ep = _attr(node, "explicit_paddings")
        padding = [(ep[2], ep[3]), (ep[4], ep[5])]
    else:
        padding = pad
    return jax.lax.conv_general_dilated(
        x, w, window_strides=strides[1:3], padding=padding,
        rhs_dilation=dil[1:3],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _depthwise_conv2d(x, w, node):
    strides = _attr(node, "strides")
    pad = _attr(node, "padding")
    if _attr(node, "data_format", "NHWC") != "NHWC":
        raise NotImplementedError("only NHWC depthwise conv is supported")
    H, W, C, M = w.shape
    w = w.reshape(H, W, 1, C * M)
    return jax.lax.conv_general_dilated(
        x, w, window_strides=strides[1:3], padding=pad,
        feature_group_count=C,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _strided_slice(x, begin, end, strides, node):
    bm = _attr(node, "begin_mask", 0)
    em = _attr(node, "end_mask", 0)
    sm = _attr(node, "shrink_axis_mask", 0)
    nm = _attr(node, "new_axis_mask", 0)
    if _attr(node, "ellipsis_mask", 0):
        raise NotImplementedError("StridedSlice ellipsis_mask")
    idx = []
    for i in range(len(begin)):
        if nm & (1 << i):
            idx.append(None)
            continue
        if sm & (1 << i):
            idx.append(int(begin[i]))
            continue
        b = None if bm & (1 << i) else int(begin[i])
        e = None if em & (1 << i) else int(end[i])
        idx.append(slice(b, e, int(strides[i])))
    return x[tuple(idx)]


def _batch_norm(args, node):
    x, scale, offset, mean, var = args[:5]
    eps = _attr(node, "epsilon", 1e-3)
    inv = scale * jax.lax.rsqrt(var + eps)
    return x * inv + (offset - mean * inv)


_UNARY = {
    "Relu": jax.nn.relu,
    "Relu6": lambda x: jnp.clip(x, 0, 6),
    "Elu": jax.nn.elu,
    "Selu": jax.nn.selu,
    "Softplus": jax.nn.softplus,
    "Sigmoid": jax.nn.sigmoid,
    "Tanh": jnp.tanh,
    "Exp": jnp.exp,
    "Log": jnp.log,
    "Neg": jnp.negative,
    "Sqrt": jnp.sqrt,
    "Rsqrt": jax.lax.rsqrt,
    "Square": jnp.square,
    "Abs": jnp.abs,
    "Erf": jax.lax.erf,
    "Floor": jnp.floor,
    "Ceil": jnp.ceil,
    "Round": jnp.round,
    "Identity": lambda x: x,
    "StopGradient": jax.lax.stop_gradient,
    "Snapshot": lambda x: x,
}

_BINARY = {
    "Add": jnp.add, "AddV2": jnp.add, "Sub": jnp.subtract,
    "Mul": jnp.multiply, "RealDiv": jnp.divide, "Div": jnp.divide,
    "FloorDiv": jnp.floor_divide, "Maximum": jnp.maximum,
    "Minimum": jnp.minimum, "Pow": jnp.power,
    "SquaredDifference": lambda a, b: jnp.square(a - b),
    "Greater": jnp.greater, "GreaterEqual": jnp.greater_equal,
    "Less": jnp.less, "LessEqual": jnp.less_equal,
    "Equal": jnp.equal, "NotEqual": jnp.not_equal,
    "LogicalAnd": jnp.logical_and, "LogicalOr": jnp.logical_or,
}

_REDUCE = {"Mean": jnp.mean, "Sum": jnp.sum, "Max": jnp.max,
           "Min": jnp.min, "Prod": jnp.prod, "Any": jnp.any,
           "All": jnp.all}


_EXPLICIT_OPS = {
    "Placeholder", "_Arg", "Const", "LeakyRelu", "AddN", "MatMul",
    "BatchMatMul", "BatchMatMulV2", "BatchMatMulV3", "BiasAdd", "Conv2D",
    "DepthwiseConv2dNative", "MaxPool", "AvgPool", "FusedBatchNorm",
    "FusedBatchNormV2", "FusedBatchNormV3", "Softmax", "LogSoftmax",
    "Reshape", "Squeeze", "ExpandDims", "Transpose", "ConcatV2", "Pack",
    "Unpack", "Pad", "PadV2", "StridedSlice", "Slice", "GatherV2",
    "Gather", "ResourceGather", "Cast", "Shape", "Select", "SelectV2",
    "ArgMax", "ArgMin", "Fill", "Tile", "Split", "SplitV", "NoOp",
}


class _GraphBuilder:
    """Translates a frozen GraphDef into (params, forward closure)."""

    def __init__(self, graph_def, input_names: Sequence[str],
                 output_names: Sequence[str]):
        self.nodes = {n.name: n for n in graph_def.node}
        self.inputs = [self._base(n) for n in input_names]
        self.outputs = list(output_names)
        supported = (_EXPLICIT_OPS | _UNARY.keys() | _BINARY.keys()
                     | _REDUCE.keys())
        unknown = sorted({n.op for n in graph_def.node
                          if n.op not in supported})
        if unknown:
            # fail at LOAD, not first predict — coverage gaps must be
            # explicit up front (TorchNet conversion contract)
            raise NotImplementedError(
                f"TF ops {unknown} have no JAX translation yet — "
                "supported set targets tf.keras inference graphs; extend "
                "net/tf_net.py for these ops")
        self.params: Dict[str, np.ndarray] = {}
        self.static: Dict[str, np.ndarray] = {}
        for n in graph_def.node:
            if n.op == "Const":
                v = _const_value(n)
                if v.size >= _PARAM_MIN_ELEMS and \
                        np.issubdtype(v.dtype, np.floating):
                    self.params[_sanitize(n.name)] = v
                else:
                    self.static[n.name] = v

    @staticmethod
    def _base(ref: str) -> str:
        return ref.split(":")[0].lstrip("^")

    def static_value(self, ref: str) -> np.ndarray:
        """Resolve a node ref that MUST be compile-time static (shapes,
        axes, paddings).  Param-lifted consts are still available here."""
        name = self._base(ref)
        if name in self.static:
            return self.static[name]
        key = _sanitize(name)
        if key in self.params:
            return self.params[key]
        node = self.nodes[name]
        if node.op in ("Identity", "Snapshot", "StopGradient"):
            return self.static_value(node.input[0])
        raise NotImplementedError(
            f"node '{name}' (op {node.op}) feeds a static operand but is "
            "not a constant — dynamic shapes are not importable to XLA")

    def build(self) -> Tuple[Dict[str, np.ndarray], Callable]:
        builder = self

        def forward(params, *feed):
            env: Dict[str, Any] = {}

            def out_of(ref):
                name, _, idx = ref.partition(":")
                name = name.lstrip("^")
                v = evaluate(name)
                if isinstance(v, (tuple, list)):
                    return v[int(idx or 0)]
                return v

            def evaluate(name):
                if name in env:
                    return env[name]
                node = builder.nodes[name]
                env[name] = v = builder._eval_node(
                    node, out_of, params, feed)
                return v

            outs = [out_of(o) for o in builder.outputs]
            return outs[0] if len(outs) == 1 else tuple(outs)

        return dict(self.params), forward

    # -- single-node translation ----------------------------------------
    def _eval_node(self, node, out_of, params, feed):
        op = node.op
        name = node.name
        if op in ("Placeholder", "_Arg"):
            try:
                return feed[self.inputs.index(name)]
            except ValueError:
                raise KeyError(f"graph input {name} not fed")
        if op == "Const":
            key = _sanitize(name)
            if key in self.params:
                return params[key]
            return jnp.asarray(self.static[name])
        args = [out_of(i) for i in node.input if not i.startswith("^")]
        if op in _UNARY:
            return _UNARY[op](args[0])
        if op in _BINARY:
            return _BINARY[op](args[0], args[1])
        if op in _REDUCE:
            axes = tuple(np.atleast_1d(self.static_value(node.input[1])))
            return _REDUCE[op](args[0], axis=axes,
                               keepdims=bool(_attr(node, "keep_dims")))
        if op == "LeakyRelu":
            return jax.nn.leaky_relu(args[0], _attr(node, "alpha", 0.2))
        if op == "AddN":
            out = args[0]
            for a in args[1:]:
                out = out + a
            return out
        if op == "MatMul":
            a, b = args
            if _attr(node, "transpose_a"):
                a = a.T
            if _attr(node, "transpose_b"):
                b = b.T
            return a @ b
        if op in ("BatchMatMul", "BatchMatMulV2", "BatchMatMulV3"):
            a, b = args
            if _attr(node, "adj_x"):
                a = jnp.swapaxes(a, -1, -2)
            if _attr(node, "adj_y"):
                b = jnp.swapaxes(b, -1, -2)
            return jnp.matmul(a, b)
        if op == "BiasAdd":
            if _attr(node, "data_format", "NHWC") == "NCHW":
                return args[0] + args[1][None, :, None, None]
            return args[0] + args[1]
        if op == "Conv2D":
            return _conv2d(args[0], args[1], node)
        if op == "DepthwiseConv2dNative":
            return _depthwise_conv2d(args[0], args[1], node)
        if op == "MaxPool":
            return _pool(args[0], node, "max")
        if op == "AvgPool":
            return _pool(args[0], node, "avg")
        if op in ("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3"):
            if _attr(node, "is_training", True):
                raise NotImplementedError(
                    "FusedBatchNorm with is_training=True — export the "
                    "graph in inference mode")
            return (_batch_norm(args, node),)
        if op == "Softmax":
            return jax.nn.softmax(args[0], axis=-1)
        if op == "LogSoftmax":
            return jax.nn.log_softmax(args[0], axis=-1)
        if op == "Reshape":
            shape = [int(d) for d in self.static_value(node.input[1])]
            return jnp.reshape(args[0], shape)
        if op == "Squeeze":
            dims = _attr(node, "squeeze_dims") or None
            return jnp.squeeze(args[0],
                               axis=tuple(dims) if dims else None)
        if op == "ExpandDims":
            return jnp.expand_dims(
                args[0], int(self.static_value(node.input[1])))
        if op == "Transpose":
            perm = [int(d) for d in self.static_value(node.input[1])]
            return jnp.transpose(args[0], perm)
        if op == "ConcatV2":
            axis = int(self.static_value(node.input[-1]))
            return jnp.concatenate(args[:-1], axis=axis)
        if op == "Pack":
            return jnp.stack(args, axis=_attr(node, "axis", 0))
        if op == "Unpack":
            axis = _attr(node, "axis", 0)
            n = _attr(node, "num")
            return tuple(jnp.squeeze(s, axis=axis) for s in
                         jnp.split(args[0], n, axis=axis))
        if op in ("Pad", "PadV2"):
            pads = np.asarray(self.static_value(node.input[1]))
            cval = args[2] if len(args) > 2 else 0
            return jnp.pad(args[0], [(int(a), int(b)) for a, b in pads],
                           constant_values=cval)
        if op == "StridedSlice":
            begin = self.static_value(node.input[1])
            end = self.static_value(node.input[2])
            strides = self.static_value(node.input[3])
            return _strided_slice(args[0], begin, end, strides, node)
        if op == "Slice":
            begin = [int(b) for b in self.static_value(node.input[1])]
            size = [int(s) for s in self.static_value(node.input[2])]
            idx = tuple(slice(b, None if s == -1 else b + s)
                        for b, s in zip(begin, size))
            return args[0][idx]
        if op in ("GatherV2", "Gather", "ResourceGather"):
            axis = int(self.static_value(node.input[2])) \
                if op == "GatherV2" and len(node.input) > 2 else 0
            return jnp.take(args[0], args[1].astype(jnp.int32), axis=axis)
        if op == "Cast":
            return args[0].astype(_tf_dtype_to_np(_attr(node, "DstT")))
        if op == "Shape":
            return jnp.asarray(args[0].shape, jnp.int32)
        if op == "Select" or op == "SelectV2":
            return jnp.where(args[0], args[1], args[2])
        if op == "ArgMax":
            return jnp.argmax(
                args[0], axis=int(self.static_value(node.input[1])))
        if op == "ArgMin":
            return jnp.argmin(
                args[0], axis=int(self.static_value(node.input[1])))
        if op == "Fill":
            dims = [int(d) for d in self.static_value(node.input[0])]
            return jnp.full(dims, args[1])
        if op == "Tile":
            reps = [int(r) for r in self.static_value(node.input[1])]
            return jnp.tile(args[0], reps)
        if op == "Split":
            axis = int(self.static_value(node.input[0]))
            return tuple(jnp.split(args[1], _attr(node, "num_split"),
                                   axis=axis))
        if op == "SplitV":
            sizes = [int(s) for s in self.static_value(node.input[1])]
            axis = int(self.static_value(node.input[2]))
            return tuple(jnp.split(args[0], np.cumsum(sizes)[:-1].tolist(),
                                   axis=axis))
        if op == "NoOp":
            return None
        raise NotImplementedError(
            f"TF op '{op}' (node {name}) has no JAX translation yet — "
            "supported set targets tf.keras inference graphs; extend "
            "net/tf_net.py _eval_node for this op")


class TFNet:
    """A frozen TF graph translated to a pure JAX function + param tree.

    Implements the flax init/apply protocol (like TorchNet), so it serves
    through InferenceModel and predicts through the Estimator:

        net = TFNet.from_saved_model("/models/resnet_sm")
        y = net(net.params, x)
        InferenceModel().load_flax(net, net.init(None))

    Forward-only by design (reference TFNet was a frozen-graph predictor);
    training imports belong to Net.load_torch / native flax models.
    """

    def __init__(self, fn: Callable, params: Dict[str, np.ndarray],
                 input_names: List[str], output_names: List[str]):
        self._fn = fn
        self.params = params
        self.input_names = input_names
        self.output_names = output_names

    def __call__(self, params, *inputs):
        return self._fn(params, *inputs)

    # -- flax protocol ---------------------------------------------------
    def init(self, rngs, *inputs, **kw):
        return {"params": self.params}

    def apply(self, variables, *inputs, mutable=None, rngs=None, **kw):
        out = self._fn(variables["params"], *inputs)
        if mutable:
            return out, {}
        return out

    # -- importers -------------------------------------------------------
    @staticmethod
    def from_concrete_function(fn) -> "TFNet":
        """Any tf.function concrete function -> TFNet (variables frozen)."""
        from tensorflow.python.framework.convert_to_constants import (
            convert_variables_to_constants_v2)

        frozen = convert_variables_to_constants_v2(fn)
        gdef = frozen.graph.as_graph_def()
        inputs = [t.name for t in frozen.inputs]
        outputs = [t.name for t in frozen.outputs]
        builder = _GraphBuilder(gdef, inputs, outputs)
        params, forward = builder.build()
        return TFNet(forward, params, inputs, outputs)

    @staticmethod
    def from_saved_model(path: str, signature: str = "serving_default",
                         ) -> "TFNet":
        """SavedModel dir -> TFNet via the given serving signature."""
        import tensorflow as tf

        loaded = tf.saved_model.load(path)
        sigs = getattr(loaded, "signatures", {})
        if signature in sigs:
            fn = sigs[signature]
        elif callable(loaded):
            raise ValueError(
                f"signature {signature!r} not found; available: "
                f"{list(sigs)} — export with a serving signature or use "
                "from_concrete_function on a concrete tf.function")
        else:
            raise ValueError(f"no signatures in SavedModel at {path}")
        net = TFNet.from_concrete_function(fn)
        # signature fns return {output_name: tensor} dicts; order outputs
        # by the structured outputs for a deterministic tuple
        return net

    @staticmethod
    def from_keras(model_or_path, input_shape=None) -> "TFNet":
        """tf.keras model (or .keras/.h5 path) -> TFNet, inference mode."""
        import tensorflow as tf

        model = model_or_path
        if isinstance(model, (str, bytes)):
            model = tf.keras.models.load_model(model)
        if input_shape is None:
            shapes = model.input_shape
            shapes = [shapes] if isinstance(shapes, tuple) else shapes
            specs = [tf.TensorSpec([None] + list(s[1:]), tf.float32)
                     for s in shapes]
        else:
            specs = [tf.TensorSpec(s, tf.float32) for s in input_shape]
        wrapped = tf.function(lambda *xs: model(
            xs[0] if len(xs) == 1 else list(xs), training=False))
        return TFNet.from_concrete_function(
            wrapped.get_concrete_function(*specs))


__all__ = ["TFNet"]

"""HuggingFace checkpoint import — GPT-2 family → TransformerLM.

Reference parity role: the reference's ``Net.load_tf``/``load_torch``
imported foreign checkpoints into its runtime (SURVEY.md §2.4); this is
the same capability pointed at the de-facto LLM checkpoint ecosystem.
A ``transformers`` GPT-2 (``GPT2LMHeadModel`` instance, or anything
``from_pretrained`` can load from local disk — this environment has no
egress, but user machines do) converts into the zoo's ``TransformerLM``
and from there gets everything the framework has: pjit fine-tuning,
LoRA, generation, speculative decoding, continuous-batching serving.

Architectural fit is exact, not approximate: GPT-2 is pre-LN with
tanh-GELU, learned positions, and tied embeddings — precisely
``TransformerLM``'s default configuration (the LN epsilon difference,
1e-5 vs flax's 1e-6, is carried through ``ln_eps``).  The parity test
asserts logits agreement against the torch forward.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

import jax.numpy as jnp

from analytics_zoo_tpu.models.lm import TransformerLM


def _np(t) -> np.ndarray:
    # .float(): torch bf16/fp16 tensors (torch_dtype=bfloat16 loads, the
    # normal way to hold a big checkpoint) cannot convert to numpy
    # directly
    return t.detach().cpu().float().numpy()


def from_hf_gpt2(model_or_path, dtype=None
                 ) -> Tuple[TransformerLM, dict]:
    """Convert a HF ``GPT2LMHeadModel`` (instance or local path) to
    ``(TransformerLM, variables)``.

    ``dtype`` sets the compute dtype of the returned model (default
    f32; params are stored f32 as HF ships them; pass ``jnp.bfloat16``
    for TPU serving).
    """
    import torch  # noqa: F401  (transformers needs it importable)
    from transformers import GPT2LMHeadModel

    hf = model_or_path
    if not isinstance(hf, GPT2LMHeadModel):
        hf = GPT2LMHeadModel.from_pretrained(model_or_path)
    cfg = hf.config
    # every config knob that would silently change the FUNCTION (not
    # just the weights) is checked: a wrong-but-running conversion is
    # the worst outcome an importer can produce
    if getattr(cfg, "activation_function", "gelu_new") not in (
            "gelu_new", "gelu_pytorch_tanh"):
        raise NotImplementedError(
            f"GPT-2 activation {cfg.activation_function!r}: TransformerLM "
            f"uses tanh-GELU (gelu_new); other activations would silently "
            f"change the function")
    if not getattr(cfg, "tie_word_embeddings", True):
        raise NotImplementedError(
            "untied lm_head (tie_word_embeddings=False): TransformerLM "
            "ties logits to the embedding table")
    if getattr(cfg, "scale_attn_by_inverse_layer_idx", False):
        raise NotImplementedError(
            "scale_attn_by_inverse_layer_idx=True: TransformerLM scales "
            "attention by 1/sqrt(D) only")
    if getattr(cfg, "reorder_and_upcast_attn", False):
        raise NotImplementedError("reorder_and_upcast_attn=True is not "
                                  "replicated")
    if not getattr(cfg, "scale_attn_weights", True):
        raise NotImplementedError(
            "scale_attn_weights=False: TransformerLM always scales "
            "attention by 1/sqrt(D)")
    H = cfg.n_embd
    heads = cfg.n_head
    D = H // heads
    if dtype is None:
        dtype = jnp.float32

    model = TransformerLM(
        vocab_size=cfg.vocab_size, hidden_size=H, num_layers=cfg.n_layer,
        num_heads=heads,
        intermediate_size=int(getattr(cfg, "n_inner", None) or 4 * H),
        max_position=cfg.n_positions, dropout=0.0, dtype=dtype,
        pos_encoding="learned", ln_eps=float(cfg.layer_norm_epsilon))

    sd = hf.state_dict()
    params = {
        "embed": {"embedding": _np(sd["transformer.wte.weight"])},
        "pos_embed": {"embedding": _np(sd["transformer.wpe.weight"])},
        "ln_f": {"scale": _np(sd["transformer.ln_f.weight"]),
                 "bias": _np(sd["transformer.ln_f.bias"])},
    }
    for i in range(cfg.n_layer):
        pre = f"transformer.h.{i}."
        # HF Conv1D stores [in, out] — already the flax kernel layout
        w_qkv = _np(sd[pre + "attn.c_attn.weight"])      # [H, 3H]
        b_qkv = _np(sd[pre + "attn.c_attn.bias"])        # [3H]
        wq, wk, wv = np.split(w_qkv, 3, axis=1)
        bq, bk, bv = np.split(b_qkv, 3)
        w_o = _np(sd[pre + "attn.c_proj.weight"])        # [H, H]
        b_o = _np(sd[pre + "attn.c_proj.bias"])
        params[f"layer_{i}"] = {
            "ln_attn": {"scale": _np(sd[pre + "ln_1.weight"]),
                        "bias": _np(sd[pre + "ln_1.bias"])},
            "ln_ffn": {"scale": _np(sd[pre + "ln_2.weight"]),
                       "bias": _np(sd[pre + "ln_2.bias"])},
            "attention": {
                # DenseGeneral((heads, D)): kernel [H, heads, D]
                "query": {"kernel": wq.reshape(H, heads, D),
                          "bias": bq.reshape(heads, D)},
                "key": {"kernel": wk.reshape(H, heads, D),
                        "bias": bk.reshape(heads, D)},
                "value": {"kernel": wv.reshape(H, heads, D),
                          "bias": bv.reshape(heads, D)},
                # DenseGeneral(H, axis=(-2, -1)): kernel [heads, D, H]
                "attn_out": {"kernel": w_o.reshape(heads, D, H),
                             "bias": b_o},
            },
            "ffn_up": {"kernel": _np(sd[pre + "mlp.c_fc.weight"]),
                       "bias": _np(sd[pre + "mlp.c_fc.bias"])},
            "ffn_down": {"kernel": _np(sd[pre + "mlp.c_proj.weight"]),
                         "bias": _np(sd[pre + "mlp.c_proj.bias"])},
        }
    # lm_head is tied to wte in GPT-2, exactly TransformerLM's tied
    # head — nothing to copy
    return model, {"params": params}


def from_hf_llama(model_or_path, dtype=None
                  ) -> Tuple[TransformerLM, dict]:
    """Convert a HF ``LlamaForCausalLM`` (instance or local path) to
    ``(TransformerLM, variables)`` — rmsnorm + SwiGLU + rope + GQA +
    bias-free projections, via the model's llama-family knobs.

    torch ``Linear`` stores ``[out, in]``; every kernel transposes into
    the flax ``[in, out]`` layout (unlike GPT-2's Conv1D, which already
    matches)."""
    import torch  # noqa: F401
    from transformers import LlamaForCausalLM

    hf = model_or_path
    if not isinstance(hf, LlamaForCausalLM):
        hf = LlamaForCausalLM.from_pretrained(model_or_path)
    cfg = hf.config
    if getattr(cfg, "mlp_bias", False):
        raise NotImplementedError(
            "mlp_bias=True llama projections are not mapped "
            "(TransformerLM's SwiGLU is bias-free)")
    # attention_bias=True (community llamas) is the qwen2 layout —
    # the shared body handles it directly
    return _from_llama_family(
        hf, cfg, dtype,
        qkv_bias=bool(getattr(cfg, "attention_bias", False)))


def _from_llama_family(hf, cfg, dtype, qkv_bias: bool
                       ) -> Tuple[TransformerLM, dict]:
    """Shared llama-family conversion body (llama: bias-free; qwen2:
    biased q/k/v)."""
    H = cfg.hidden_size
    heads = cfg.num_attention_heads
    D = H // heads
    kvh = getattr(cfg, "num_key_value_heads", heads)
    # function-changing knobs fail loud (same policy as GPT-2)
    if getattr(cfg, "rope_scaling", None):
        raise NotImplementedError(
            f"rope_scaling={cfg.rope_scaling!r}: TransformerLM applies "
            f"plain rotary embeddings")
    if getattr(cfg, "head_dim", None) not in (None, D):
        raise NotImplementedError(
            f"head_dim={cfg.head_dim} != hidden/heads={D}: "
            f"TransformerLM derives head dim from hidden_size")
    if getattr(cfg, "hidden_act", "silu") != "silu":
        raise NotImplementedError(
            f"hidden_act {cfg.hidden_act!r}: TransformerLM's SwiGLU "
            f"uses silu")
    tied = bool(getattr(cfg, "tie_word_embeddings", False))
    if dtype is None:
        dtype = jnp.float32

    model = TransformerLM(
        vocab_size=cfg.vocab_size, hidden_size=H,
        num_layers=cfg.num_hidden_layers, num_heads=heads,
        intermediate_size=cfg.intermediate_size,
        max_position=cfg.max_position_embeddings, dropout=0.0,
        dtype=dtype, pos_encoding="rope",
        rope_base=float(getattr(cfg, "rope_theta", 10000.0)),
        num_kv_heads=kvh, norm="rmsnorm", mlp="swiglu",
        use_bias=False, qkv_bias=qkv_bias, tied_head=tied,
        ln_eps=float(cfg.rms_norm_eps))

    sd = hf.state_dict()

    def lin(name):                          # torch [out, in] -> [in, out]
        return _np(sd[name]).T

    params = {
        "embed": {"embedding": _np(sd["model.embed_tokens.weight"])},
        "ln_f": {"scale": _np(sd["model.norm.weight"])},
    }
    if not tied:
        params["lm_head"] = {"kernel": lin("lm_head.weight")}
    for i in range(cfg.num_hidden_layers):
        pre = f"model.layers.{i}."
        attn = {
            "query": {"kernel": lin(pre + "self_attn.q_proj.weight")
                      .reshape(H, heads, D)},
            "key": {"kernel": lin(pre + "self_attn.k_proj.weight")
                    .reshape(H, kvh, D)},
            "value": {"kernel": lin(pre + "self_attn.v_proj.weight")
                      .reshape(H, kvh, D)},
            "attn_out": {"kernel": lin(pre + "self_attn.o_proj.weight")
                         .reshape(heads, D, H)},
        }
        if qkv_bias:                # qwen2-style biased projections
            attn["query"]["bias"] = _np(
                sd[pre + "self_attn.q_proj.bias"]).reshape(heads, D)
            attn["key"]["bias"] = _np(
                sd[pre + "self_attn.k_proj.bias"]).reshape(kvh, D)
            attn["value"]["bias"] = _np(
                sd[pre + "self_attn.v_proj.bias"]).reshape(kvh, D)
        params[f"layer_{i}"] = {
            "ln_attn": {"scale": _np(sd[pre + "input_layernorm.weight"])},
            "ln_ffn": {"scale": _np(
                sd[pre + "post_attention_layernorm.weight"])},
            "attention": attn,
            "ffn_gate": {"kernel": lin(pre + "mlp.gate_proj.weight")},
            "ffn_up": {"kernel": lin(pre + "mlp.up_proj.weight")},
            "ffn_down": {"kernel": lin(pre + "mlp.down_proj.weight")},
        }
    return model, {"params": params}


def from_hf_mistral(model_or_path, dtype=None
                    ) -> Tuple[TransformerLM, dict]:
    """Convert a HF ``MistralForCausalLM`` — llama-shaped when its
    sliding window is off (None or >= the position budget); windowed
    attention is not replicated and fails loud."""
    import torch  # noqa: F401
    from transformers import MistralForCausalLM

    hf = model_or_path
    if not isinstance(hf, MistralForCausalLM):
        hf = MistralForCausalLM.from_pretrained(model_or_path)
    cfg = hf.config
    sw = getattr(cfg, "sliding_window", None)
    if sw is not None and sw < cfg.max_position_embeddings:
        raise NotImplementedError(
            f"sliding_window={sw} < max_position "
            f"{cfg.max_position_embeddings}: TransformerLM attends the "
            f"full causal window (a windowed checkpoint would silently "
            f"attend differently)")
    return _from_llama_family(hf, cfg, dtype, qkv_bias=False)


def from_hf_qwen2(model_or_path, dtype=None
                  ) -> Tuple[TransformerLM, dict]:
    """Convert a HF ``Qwen2ForCausalLM`` — llama-shaped (rmsnorm,
    SwiGLU, rope, GQA, untied or tied head) plus BIASED q/k/v
    projections (``qkv_bias``)."""
    import torch  # noqa: F401
    from transformers import Qwen2ForCausalLM

    hf = model_or_path
    if not isinstance(hf, Qwen2ForCausalLM):
        hf = Qwen2ForCausalLM.from_pretrained(model_or_path)
    cfg = hf.config
    # HF qwen2 windows only layers with idx >= max_window_layers: the
    # guard fires only when some layer would ACTUALLY window
    if (getattr(cfg, "use_sliding_window", False)
            and getattr(cfg, "max_window_layers", 0)
            < cfg.num_hidden_layers
            and (getattr(cfg, "sliding_window", None) or 0)
            < cfg.max_position_embeddings):
        raise NotImplementedError(
            "use_sliding_window=True with windowed layers: "
            "TransformerLM attends the full causal window")
    return _from_llama_family(hf, cfg, dtype, qkv_bias=True)

"""Net — external-model import (SURVEY.md §2.4 "net loading").

Reference surface (ref: zoo pipeline/api/net/ — ``Net.load_bigdl``,
``load_caffe``, ``load_keras``, ``load_tf``, ``load_torch``): import
foreign-framework models as graph nodes of the native runtime.

TPU rebuild: torch imports via ``TorchNet`` (torch.fx -> pure JAX function,
torch_net.py); TensorFlow imports via ``TFNet`` (frozen GraphDef -> pure
JAX function, tf_net.py) — both become first-class XLA programs, with the
foreign framework needed only at load time.  Keras models are native here
(analytics_zoo_tpu.keras builds flax modules directly); ``load_keras`` also
accepts tf.keras models/files through TFNet.  Caffe/BigDL runtimes are not
in this environment, so their loaders raise with the supported migration
path spelled out.
"""

from __future__ import annotations

import os
import re

from analytics_zoo_tpu.net.tf_net import TFNet
from analytics_zoo_tpu.net.torch_net import TorchNet

# gs://, hdfs://, s3://, ... — handled by TF's filesystem layer, not ours;
# os.path.exists would falsely reject them
_REMOTE_SCHEME = re.compile(r"^[A-Za-z][A-Za-z0-9+.-]*://")


def _is_local_path(p: str) -> bool:
    return not _REMOTE_SCHEME.match(p)


class Net:
    """ref-parity constructor facade for external model import."""

    @staticmethod
    def load_torch(module_or_path, example_inputs=None) -> TorchNet:
        """A torch nn.Module (or a path to a pickled/state-dict one
        loadable by ``torch.load``) -> TorchNet running on TPU."""
        import torch

        m = module_or_path
        if isinstance(m, (str, bytes)):
            m = torch.load(m, weights_only=False, map_location="cpu")
        if not isinstance(m, torch.nn.Module):
            raise TypeError(f"expected nn.Module or path, got {type(m)}")
        return TorchNet.from_torch(m, example_inputs)

    @staticmethod
    def load_keras(model) -> "object":
        """Native analytics_zoo_tpu.keras models pass through; tf.keras
        models / .keras / .h5 files import via TFNet (ref: Net.load_keras
        imported HDF5 topologies into the native graph runtime)."""
        from analytics_zoo_tpu.keras.engine import KerasNet

        if isinstance(model, KerasNet):
            return model
        if isinstance(model, (str, bytes, os.PathLike)):
            p = os.fspath(model)
            if _is_local_path(p) and not os.path.exists(p):
                raise FileNotFoundError(f"no such keras model file: {p!r}")
        return TFNet.from_keras(model)

    @staticmethod
    def load_tf(path_or_fn, signature: str = "serving_default") -> TFNet:
        """ref-parity: TFNet — SavedModel dir (or concrete tf.function) ->
        forward-only JAX callable served by InferenceModel/Estimator."""
        if isinstance(path_or_fn, (str, bytes, os.PathLike)):
            p = os.fspath(path_or_fn)
            if not _is_local_path(p):
                return TFNet.from_saved_model(p, signature=signature)
            if not os.path.exists(p):
                raise FileNotFoundError(f"no such TF model path: {p!r}")
            if os.path.isdir(p):
                return TFNet.from_saved_model(p, signature=signature)
            return TFNet.from_keras(p)
        return TFNet.from_concrete_function(path_or_fn)

    @staticmethod
    def load_openvino(xml_path, bin_path=None) -> "object":
        """ref-parity: load an OpenVINO IR (.xml + .bin) — the graph is
        translated to one pure JAX function (net/openvino_ir.py), no IE
        runtime involved.  Forward-only."""
        from analytics_zoo_tpu.net.openvino_ir import OpenVINONet

        p = os.fspath(xml_path)
        if _is_local_path(p) and not os.path.exists(p):
            raise FileNotFoundError(f"no such IR xml: {p!r}")
        return OpenVINONet.from_ir(p, bin_path)

    @staticmethod
    def load_hf_gpt2(model_or_path, dtype=None):
        """A HuggingFace GPT-2 (``GPT2LMHeadModel`` instance or a local
        ``from_pretrained`` path) -> ``(TransformerLM, variables)`` with
        exact logit parity (net/hf_net.py) — the checkpoint then gets
        pjit training, LoRA, generation, speculative decoding, and
        continuous-batching serving."""
        from analytics_zoo_tpu.net.hf_net import from_hf_gpt2

        return from_hf_gpt2(model_or_path, dtype=dtype)

    @staticmethod
    def load_hf_llama(model_or_path, dtype=None):
        """A HuggingFace Llama (``LlamaForCausalLM`` instance or local
        path) -> ``(TransformerLM, variables)``: rmsnorm + SwiGLU +
        rope + GQA + untied head, exact logit parity (net/hf_net.py)."""
        from analytics_zoo_tpu.net.hf_net import from_hf_llama

        return from_hf_llama(model_or_path, dtype=dtype)

    @staticmethod
    def load_hf_mistral(model_or_path, dtype=None):
        """A HuggingFace Mistral (non-windowed) -> ``(TransformerLM,
        variables)`` via the llama family (net/hf_net.py)."""
        from analytics_zoo_tpu.net.hf_net import from_hf_mistral

        return from_hf_mistral(model_or_path, dtype=dtype)

    @staticmethod
    def load_hf_qwen2(model_or_path, dtype=None):
        """A HuggingFace Qwen2 (``Qwen2ForCausalLM`` instance or local
        path) -> ``(TransformerLM, variables)``: the llama family plus
        biased q/k/v projections (net/hf_net.py)."""
        from analytics_zoo_tpu.net.hf_net import from_hf_qwen2

        return from_hf_qwen2(model_or_path, dtype=dtype)

    @staticmethod
    def load_bigdl(*a, **kw):
        raise NotImplementedError(
            "BigDL JVM models are not loadable without a JVM; rebuild the "
            "topology with analytics_zoo_tpu.keras (layer set matches the "
            "BigDL keras API) and load weights via set_weights()")

    @staticmethod
    def load_caffe(*a, **kw):
        raise NotImplementedError(
            "Caffe is not available in this environment; convert the "
            "model to torch (e.g. via torchvision ports) and use "
            "Net.load_torch")


from analytics_zoo_tpu.net.openvino_ir import OpenVINONet  # noqa: E402

__all__ = ["TorchNet", "TFNet", "OpenVINONet", "Net"]

"""Net — external-model import (SURVEY.md §2.4 "net loading").

Reference surface (ref: zoo pipeline/api/net/ — ``Net.load_bigdl``,
``load_caffe``, ``load_keras``, ``load_tf``, ``load_torch``): import
foreign-framework models as graph nodes of the native runtime.

TPU rebuild: torch is the supported import path (``TorchNet`` converts via
torch.fx to a pure JAX function — see torch_net.py); Keras models are
native here (analytics_zoo_tpu.keras builds flax modules directly).
TensorFlow/Caffe/BigDL runtimes are not in this environment, so their
loaders raise with the supported migration path spelled out.
"""

from __future__ import annotations

from analytics_zoo_tpu.net.torch_net import TorchNet


class Net:
    """ref-parity constructor facade for external model import."""

    @staticmethod
    def load_torch(module_or_path, example_inputs=None) -> TorchNet:
        """A torch nn.Module (or a path to a pickled/state-dict one
        loadable by ``torch.load``) -> TorchNet running on TPU."""
        import torch

        m = module_or_path
        if isinstance(m, (str, bytes)):
            m = torch.load(m, weights_only=False, map_location="cpu")
        if not isinstance(m, torch.nn.Module):
            raise TypeError(f"expected nn.Module or path, got {type(m)}")
        return TorchNet.from_torch(m, example_inputs)

    @staticmethod
    def load_keras(model) -> "object":
        """Our keras API builds flax modules natively — pass them straight
        to Estimator/InferenceModel (ref load_keras imported HDF5 models
        into BigDL; here the keras layer library IS the native one)."""
        from analytics_zoo_tpu.keras.engine import KerasNet

        if isinstance(model, KerasNet):
            return model
        raise TypeError(
            "load_keras takes an analytics_zoo_tpu.keras model; HDF5 "
            "import of tf.keras models needs tensorflow, which is not in "
            "this environment — rebuild the topology with "
            "analytics_zoo_tpu.keras and load weights via set_weights()")

    @staticmethod
    def load_tf(*a, **kw):
        raise NotImplementedError(
            "TensorFlow is not available in this environment; export the "
            "graph's weights and rebuild with analytics_zoo_tpu.keras or "
            "flax, or convert a torch port via Net.load_torch")

    @staticmethod
    def load_bigdl(*a, **kw):
        raise NotImplementedError(
            "BigDL JVM models are not loadable without a JVM; rebuild the "
            "topology with analytics_zoo_tpu.keras (layer set matches the "
            "BigDL keras API) and load weights via set_weights()")

    @staticmethod
    def load_caffe(*a, **kw):
        raise NotImplementedError(
            "Caffe is not available in this environment; convert the "
            "model to torch (e.g. via torchvision ports) and use "
            "Net.load_torch")


__all__ = ["TorchNet", "Net"]

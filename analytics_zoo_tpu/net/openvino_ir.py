"""OpenVINO IR import — the reference's `.xml + .bin` inference artifact.

Reference surface (SURVEY.md §2.3; ref: pipeline/inference/
OpenVinoInferenceSupportive + zoo.orca.learn.openvino.Estimator): load an
OpenVINO Intermediate Representation and serve batched inference from it.
Earlier rounds answered this with "re-export your model" (the x86 IE
RUNTIME is genuinely absent here); this module removes the remaining gap
by reading the IR FORMAT directly — no OpenVINO toolchain involved:

- the ``.xml`` graph (opset-v10+ layer/edge schema) parses with stdlib
  ElementTree;
- ``Const`` payloads are sliced out of the ``.bin`` at their
  ``offset/size`` and become the param tree (so ``quantize="int8"``
  covers the IR int8-calibration role too);
- each supported layer type lowers to the jax/lax op with the same
  NCHW semantics OpenVINO defines, and the whole graph becomes ONE pure
  function compiled by XLA — the TPU-native replacement for the IE
  executable network.

Curated op set (the layers OpenVINO's own model-optimizer emits for the
reference's CV/recommendation zoos): Parameter, Const, Result,
Convolution, GroupConvolution, MatMul, Add, Subtract, Multiply, Divide,
Maximum, Minimum, ReLU, PReLU, Sigmoid, Tanh, Clamp, Gelu, Exp, Sqrt,
Softmax, MaxPool, AvgPool, ReduceMean, Reshape, Squeeze, Unsqueeze,
Transpose, Concat, Gather, BatchNormInference.  Anything else raises
with the layer type named — a loud subset, not a silent wrong answer.
"""

from __future__ import annotations

import os
import xml.etree.ElementTree as ET
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_ELEMENT_TYPES = {
    "f32": np.float32, "f16": np.float16, "f64": np.float64,
    "i64": np.int64, "i32": np.int32, "i16": np.int16, "i8": np.int8,
    "u64": np.uint64, "u32": np.uint32, "u16": np.uint16, "u8": np.uint8,
    "boolean": np.bool_,
}

# ops whose listed input positions are SHAPE-LIKE: their producers must
# be Const and are resolved at build time (a traced reshape target or
# transpose permutation cannot exist under jit)
_STATIC_INPUTS = {
    "Reshape": (1,), "Transpose": (1,), "Squeeze": (1,),
    "Unsqueeze": (1,), "ReduceMean": (1,), "Gather": (2,),
}


def _ints(s: str) -> Tuple[int, ...]:
    s = (s or "").strip()
    return tuple(int(v) for v in s.split(",")) if s else ()


class _Layer:
    def __init__(self, el):
        self.id = el.get("id")
        self.type = el.get("type")
        self.name = el.get("name") or f"layer_{self.id}"
        d = el.find("data")
        self.attrs = dict(d.attrib) if d is not None else {}
        self.in_ports: List[str] = []
        self.out_ports: List[str] = []
        inp = el.find("input")
        if inp is not None:
            self.in_ports = [p.get("id") for p in inp.findall("port")]
        out = el.find("output")
        if out is not None:
            self.out_ports = [p.get("id") for p in out.findall("port")]


def _parse_ir(xml_path: str):
    root = ET.parse(xml_path).getroot()
    layers = {}
    order = []
    for el in root.find("layers").findall("layer"):
        ly = _Layer(el)
        layers[ly.id] = ly
        order.append(ly.id)
    producer: Dict[Tuple[str, str], Tuple[str, str]] = {}
    edges = root.find("edges")
    if edges is not None:
        for e in edges.findall("edge"):
            producer[(e.get("to-layer"), e.get("to-port"))] = (
                e.get("from-layer"), e.get("from-port"))
    return layers, order, producer


def _read_const(ly: _Layer, blob: bytes) -> np.ndarray:
    dt = _ELEMENT_TYPES[ly.attrs["element_type"]]
    shape = _ints(ly.attrs.get("shape", ""))
    off = int(ly.attrs["offset"])
    size = int(ly.attrs["size"])
    arr = np.frombuffer(blob[off:off + size], dtype=dt)
    return arr.reshape(shape) if shape else arr.reshape(())


def _pool(x, kernel, strides, pads_b, pads_e, kind, exclude_pad):
    """NCHW reduce-window pooling with explicit pads."""
    window = (1, 1) + kernel
    stride = (1, 1) + strides
    pads = ((0, 0), (0, 0)) + tuple(zip(pads_b, pads_e))
    if kind == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
            jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, init, lax.max, window, stride, pads)
    s = lax.reduce_window(x.astype(jnp.float32), 0.0, lax.add, window,
                          stride, pads)
    if exclude_pad:
        ones = jnp.ones(x.shape[2:], jnp.float32)[None, None]
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, stride, pads)
        return (s / jnp.maximum(cnt, 1.0)).astype(x.dtype)
    return (s / float(np.prod(kernel))).astype(x.dtype)


class OpenVINONet:
    """An OpenVINO IR translated to a pure JAX function + param tree.

    Same flax init/apply protocol as TFNet/TorchNet, so it serves through
    ``InferenceModel`` and predicts through the Estimator:

        net = OpenVINONet.from_ir("/models/m.xml")
        y = net(net.params, x)
        InferenceModel().load_flax(net, net.init(None), quantize="int8")

    Forward-only by design (an IR is an inference artifact)."""

    def __init__(self, fn: Callable, params: Dict[str, np.ndarray],
                 input_names: List[str], output_names: List[str]):
        self._fn = fn
        self.params = params
        self.input_names = input_names
        self.output_names = output_names

    def __call__(self, params, *inputs):
        return self._fn(params, *inputs)

    # -- flax protocol ---------------------------------------------------
    def init(self, rngs, *inputs, **kw):
        return {"params": self.params}

    def apply(self, variables, *inputs, mutable=None, rngs=None, **kw):
        out = self._fn(variables["params"], *inputs)
        if mutable:
            return out, {}
        return out

    # -- importer --------------------------------------------------------
    @staticmethod
    def from_ir(xml_path: str,
                bin_path: Optional[str] = None) -> "OpenVINONet":
        if bin_path is None:
            bin_path = os.path.splitext(xml_path)[0] + ".bin"
        with open(bin_path, "rb") as f:
            blob = f.read()
        layers, order, producer = _parse_ir(xml_path)

        # only out_ports[0] of each layer is registered in the forward
        # env; an edge consuming any OTHER output port (e.g. MaxPool-8's
        # indices output) must fail HERE with the curated error, not as
        # a raw KeyError at trace time
        for (dst, _), (src, src_port) in producer.items():
            src_ly = layers.get(src)
            if src_ly is None or not src_ly.out_ports:
                continue
            if src_port != src_ly.out_ports[0]:
                dst_ly = layers.get(dst)
                dst_name = dst_ly.name if dst_ly is not None else dst
                raise NotImplementedError(
                    f"{src_ly.type} '{src_ly.name}': output port "
                    f"{src_port} is consumed by layer "
                    f"'{dst_name}', but only the first output "
                    f"port of a layer is supported")

        const_vals: Dict[str, np.ndarray] = {}
        pnames: Dict[str, str] = {}     # layer id -> param key
        params: Dict[str, np.ndarray] = {}
        inputs: List[str] = []
        results: List[str] = []
        for lid in order:
            ly = layers[lid]
            if ly.type == "Const":
                const_vals[lid] = _read_const(ly, blob)
            elif ly.type == "Parameter":
                inputs.append(lid)
            elif ly.type == "Result":
                results.append(lid)

        # which Const ids are consumed ONLY as static (shape-like) inputs?
        tensor_consts = set()
        for lid in order:
            ly = layers[lid]
            static_slots = _STATIC_INPUTS.get(ly.type, ())
            for slot, port in enumerate(ly.in_ports):
                src = producer.get((lid, port))
                if src and src[0] in const_vals and \
                        slot not in static_slots:
                    tensor_consts.add(src[0])
        for lid in sorted(tensor_consts, key=int):
            key = layers[lid].name
            if key in params:       # name collision: disambiguate by id
                key = f"{key}_{lid}"
            pnames[lid] = key
            # jax canonicalizes i64->i32 under disabled x64; pre-cast so
            # the param tree round-trips through device_put unchanged
            v = const_vals[lid]
            params[key] = v.astype(jax.dtypes.canonicalize_dtype(v.dtype))

        # resolve every shape-like input NOW (build time): the values
        # must be static under jit anyway, and copying just these few
        # small arrays lets const_vals/blob (np.frombuffer views pinning
        # the whole .bin in host RAM) be garbage-collected — params
        # already hold their own copies of the tensor Consts
        static_vals: Dict[Tuple[str, int], np.ndarray] = {}
        for lid in order:
            ly = layers[lid]
            for slot in _STATIC_INPUTS.get(ly.type, ()):
                if slot >= len(ly.in_ports):
                    continue    # optional input omitted (e.g. axis-less
                    #             Squeeze) — the op handles its absence
                src = producer.get((lid, ly.in_ports[slot]))
                if not src or src[0] not in const_vals:
                    raise NotImplementedError(
                        f"{ly.type} '{ly.name}': input {slot} must be a "
                        f"Const (shape-like inputs are resolved at load "
                        f"time)")
                static_vals[(lid, slot)] = const_vals[src[0]].copy()
        del const_vals, blob

        def static_in(lid, slot, default=None):
            if (lid, slot) not in static_vals:
                return default
            return static_vals[(lid, slot)]

        def forward(p, *xs):
            env: Dict[Tuple[str, str], jax.Array] = {}
            for lid, x in zip(inputs, xs):
                env[(lid, layers[lid].out_ports[0])] = x
            for lid in order:
                ly = layers[lid]
                if ly.type in ("Parameter", "Result"):
                    continue
                if ly.type == "Const":
                    if lid in pnames:
                        env[(lid, ly.out_ports[0])] = p[pnames[lid]]
                    continue
                static_slots = _STATIC_INPUTS.get(ly.type, ())
                ins = []
                for slot, port in enumerate(ly.in_ports):
                    if slot in static_slots:
                        # shape-like input: resolved at build time via
                        # static_in, never a traced value
                        ins.append(None)
                        continue
                    src = producer[(lid, port)]
                    ins.append(env[src])
                env[(lid, ly.out_ports[0])] = _lower(ly, ins, static_in)
            outs = []
            for lid in results:
                src = producer[(lid, layers[lid].in_ports[0])]
                outs.append(env[src])
            return outs[0] if len(outs) == 1 else tuple(outs)

        def _lower(ly, ins, static_in):
            t = ly.type
            a = ly.attrs
            if t in ("ReLU", "Relu"):
                return jax.nn.relu(ins[0])
            if t == "Sigmoid":
                return jax.nn.sigmoid(ins[0])
            if t == "Tanh":
                return jnp.tanh(ins[0])
            if t == "Exp":
                return jnp.exp(ins[0])
            if t == "Sqrt":
                return jnp.sqrt(ins[0])
            if t == "Gelu":
                approx = a.get("approximation_mode", "ERF").upper()
                return jax.nn.gelu(ins[0], approximate=approx != "ERF")
            if t == "Clamp":
                return jnp.clip(ins[0], float(a["min"]), float(a["max"]))
            if t == "PReLU":
                slope = ins[1]
                if jnp.ndim(slope) == 1 and jnp.ndim(ins[0]) > 1:
                    # OpenVINO: a 1-D slope of length C is CHANNEL-wise
                    # on NCHW data, not trailing-axis numpy broadcast
                    slope = slope.reshape(
                        (1, -1) + (1,) * (jnp.ndim(ins[0]) - 2))
                return jnp.where(ins[0] >= 0, ins[0], ins[0] * slope)
            if t in ("Add", "Subtract", "Multiply", "Divide", "Maximum",
                     "Minimum"):
                f = {"Add": jnp.add, "Subtract": jnp.subtract,
                     "Multiply": jnp.multiply, "Divide": jnp.divide,
                     "Maximum": jnp.maximum, "Minimum": jnp.minimum}[t]
                return f(ins[0], ins[1])
            if t == "MatMul":
                x, w = ins
                if a.get("transpose_a", "false") == "true":
                    x = jnp.swapaxes(x, -1, -2)
                if a.get("transpose_b", "false") == "true":
                    w = jnp.swapaxes(w, -1, -2)
                return jnp.matmul(x, w)
            if t == "Softmax":
                return jax.nn.softmax(ins[0], axis=int(a.get("axis", 1)))
            if t in ("Convolution", "GroupConvolution"):
                x, w = ins
                ap = a.get("auto_pad", "explicit")
                if ap not in ("explicit", "notset", "NOTSET"):
                    # same_upper/same_lower ignore pads_begin/end per the
                    # spec; lowering them as explicit would be silently
                    # wrong — loud subset, not wrong answers
                    raise NotImplementedError(
                        f"{t} '{ly.name}': auto_pad={ap!r} is not "
                        f"supported (re-export with explicit pads)")
                strides = _ints(a.get("strides", "1,1"))
                pb = _ints(a.get("pads_begin", "0,0"))
                pe = _ints(a.get("pads_end", "0,0"))
                dil = _ints(a.get("dilations", "1,1"))
                groups = 1
                if t == "GroupConvolution":
                    # IR group weights: [G, O/G, I/G, kH, kW] -> OIHW
                    g = w.shape[0]
                    w = w.reshape((w.shape[0] * w.shape[1],) + w.shape[2:])
                    groups = g
                return lax.conv_general_dilated(
                    x, w, window_strides=strides,
                    padding=tuple(zip(pb, pe)), rhs_dilation=dil,
                    dimension_numbers=("NCHW", "OIHW", "NCHW"),
                    feature_group_count=groups)
            if t in ("MaxPool", "AvgPool"):
                ap = a.get("auto_pad", "explicit")
                if ap not in ("explicit", "notset", "NOTSET"):
                    raise NotImplementedError(
                        f"{t} '{ly.name}': auto_pad={ap!r} is not "
                        f"supported (re-export with explicit pads)")
                if a.get("rounding_type", "floor") != "floor":
                    # lax.reduce_window floors the output extent; a ceil
                    # pool would silently compute different windows
                    raise NotImplementedError(
                        f"{t} '{ly.name}': rounding_type="
                        f"{a['rounding_type']!r} is not supported (only "
                        f"floor)")
                if t == "MaxPool":
                    return _pool(ins[0], _ints(a["kernel"]),
                                 _ints(a.get("strides", "1,1")),
                                 _ints(a.get("pads_begin", "0,0")),
                                 _ints(a.get("pads_end", "0,0")), "max",
                                 True)
                return _pool(ins[0], _ints(a["kernel"]),
                             _ints(a.get("strides", "1,1")),
                             _ints(a.get("pads_begin", "0,0")),
                             _ints(a.get("pads_end", "0,0")), "avg",
                             a.get("exclude-pad",
                                   a.get("exclude_pad",
                                         "true")) == "true")
            if t == "ReduceMean":
                axes = tuple(int(v) for v in
                             np.ravel(static_in(ly.id, 1)))
                keep = a.get("keep_dims", "true") == "true"
                return jnp.mean(ins[0], axis=axes, keepdims=keep)
            if t == "Reshape":
                target = [int(v) for v in np.ravel(static_in(ly.id, 1))]
                if a.get("special_zero", "true") == "true":
                    target = [ins[0].shape[i] if v == 0 else v
                              for i, v in enumerate(target)]
                return jnp.reshape(ins[0], target)
            if t == "Squeeze":
                ax_arr = static_in(ly.id, 1)
                if ax_arr is None:      # optional input: drop ALL 1-dims
                    return jnp.squeeze(ins[0])
                axes = tuple(int(v) for v in np.ravel(ax_arr))
                return jnp.squeeze(ins[0], axis=axes)
            if t == "Unsqueeze":
                raw = [int(v) for v in np.ravel(static_in(ly.id, 1))]
                # axes are OUTPUT-rank positions and may be negative:
                # normalise against the output rank BEFORE sorting, or
                # mixed/negative axes land in the wrong positions
                out_rank = jnp.ndim(ins[0]) + len(raw)
                axes = sorted(ax if ax >= 0 else ax + out_rank
                              for ax in raw)
                out = ins[0]
                for ax in axes:
                    out = jnp.expand_dims(out, ax)
                return out
            if t == "Transpose":
                perm = tuple(int(v) for v in
                             np.ravel(static_in(ly.id, 1)))
                return jnp.transpose(ins[0], perm)
            if t == "Concat":
                return jnp.concatenate(ins, axis=int(a.get("axis", 0)))
            if t == "Gather":
                # opset Gather: (data, indices, axis) — axis arrives as
                # a Const third input; the embedding-lookup workhorse of
                # recommendation IRs
                if int(a.get("batch_dims", 0)) != 0:
                    raise NotImplementedError(
                        f"Gather '{ly.name}': batch_dims != 0 is not "
                        f"supported")
                axis = int(np.ravel(static_in(ly.id, 2,
                                              np.zeros(1, np.int64)))[0])
                return jnp.take(ins[0], ins[1].astype(jnp.int32),
                                axis=axis)
            if t == "BatchNormInference":
                x, gamma, beta, mean, var = ins
                eps = float(a.get("epsilon", a.get("eps", 1e-5)))
                shape = (1, -1) + (1,) * (x.ndim - 2)
                return (x - mean.reshape(shape)) * gamma.reshape(shape) \
                    / jnp.sqrt(var.reshape(shape) + eps) \
                    + beta.reshape(shape)
            raise NotImplementedError(
                f"OpenVINO layer type {t!r} ('{ly.name}') is outside the "
                f"supported subset — see net/openvino_ir.py's module "
                f"docstring for the curated op list")

        in_names = [layers[i].name for i in inputs]
        out_names = [layers[i].name for i in results]
        return OpenVINONet(forward, params, in_names, out_names)

"""Anomaly detection — stacked-LSTM next-step regressor + detectors.

Reference surface (SURVEY.md §2.5; ref: pyzoo/zoo/models/anomalydetection/
anomaly_detector.py + Scala models/anomalydetection/): ``AnomalyDetector(
feature_shape, hidden_layers, dropouts)`` — LSTM stack → Dense(1) trained
on sliding windows; ``detect_anomalies(y_true, y_pred, anomaly_size)``
ranks absolute prediction error.

TPU-first: the LSTM stack is one lax.scan (models/rnn.py); detection is a
host-side numpy ranking (sorting has no business on the MXU).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.models.rnn import RNNStack


class AnomalyDetector(nn.Module):
    """ref-parity ctor: feature_shape=(unroll_length, n_features),
    hidden_layers, dropouts."""

    feature_shape: Tuple[int, int]
    hidden_layers: Sequence[int] = (8, 32, 15)
    dropouts: Sequence[float] = (0.2, 0.2, 0.2)
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = RNNStack(self.hidden_layers, rnn_type="lstm",
                     dropouts=self.dropouts, dtype=self.dtype,
                     name="lstm_stack")(x.astype(self.dtype), train)
        return nn.Dense(1, dtype=jnp.float32, name="head")(h)[:, 0]


def unroll(data: np.ndarray, unroll_length: int, predict_step: int = 1):
    """Sliding windows (ref: AnomalyDetector.unroll): returns
    (x [N, unroll_length, F], y [N]) where y is the first feature
    ``predict_step`` after each window.  Delegates to the canonical
    window generator in zouwu.preprocessing."""
    from analytics_zoo_tpu.zouwu.preprocessing import roll

    x, y = roll(data, unroll_length, horizon=predict_step,
                target_cols=[0])
    return x, y[:, -1, 0]


def detect_anomalies(y_true: np.ndarray, y_pred: np.ndarray,
                     anomaly_size: int = 5) -> np.ndarray:
    """Indices of the ``anomaly_size`` largest |error| points
    (ref: AnomalyDetector.detect_anomalies)."""
    err = np.abs(np.asarray(y_true).ravel() - np.asarray(y_pred).ravel())
    return np.argsort(err)[::-1][:anomaly_size]

"""Decoder-only causal language model + KV-cache generation.

The reference's generative surface is the RNN ``Seq2seq`` (SURVEY.md §2.5,
upstream ``pyzoo/zoo/models/seq2seq``) — it predates decoder-only LMs.
This module completes the family the TPU-native way:

- **Training** is one causal transformer forward: full attention on a
  single chip, the fused Pallas flash kernel where measured to win, and
  causal RING attention over the ``sp`` axis for long sequences (the same
  `parallel/ring_attention.py` machinery BERT uses, with the causal mask
  staying exact across ring hops).
- **Generation** is ONE ``lax.scan`` over positions with a preallocated
  KV cache threaded through the carry — static shapes, no Python loop, no
  per-token dispatch; prompt prefill and sampling are the same scan
  (prompt positions teacher-force, later positions feed back argmax).
- Weights are tied (logits = hidden @ embed.T) and carry the same
  Megatron tp layout as BERT, so ``LM_PARTITION_RULES`` compose with
  dp/sp/tp meshes unchanged.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from analytics_zoo_tpu.models.transformer import (
    _constrain_seq, attention_dispatch)
from analytics_zoo_tpu.parallel.pipeline import pp_stage_rules as _ppsr

LM_PARTITION_RULES = (
    (r"pos_embed/embedding", P()),      # positions replicate (before the
    (r"embed/embedding", P("tp", None)),   # vocab rule can re.search-match)
    # NOTE (GQA): key/value kernels have num_kv_heads on the sharded
    # head dim — keep num_kv_heads a multiple of the tp size (or
    # override these two rules to P()) when sharding narrow-KV models
    (r"(query|key|value)/kernel", P(None, "tp")),
    (r"attn_out/kernel", P("tp", None)),
    (r"ffn_up/kernel", P(None, "tp")),
    (r"ffn_gate/kernel", P(None, "tp")),   # SwiGLU gate: column-parallel
    (r"ffn_down/kernel", P("tp", None)),
    (r"lm_head/kernel", P(None, "tp")),    # untied head: vocab-sharded
    (r".*", P()),
)


# TransformerLM(pp_stages=N): GPipe-stacked stage params sharded over pp
# on the stage dim; embeddings/head follow the non-pp rules.  NOTE: no tp
# entries for the trunk — pipeline stages execute inside shard_map, where
# a tp-sharded weight would just be all-gathered every tick (memory at
# rest, zero compute parallelism); combine pp with dp/fsdp instead.
LM_PP_PARTITION_RULES = _ppsr() + LM_PARTITION_RULES

# TransformerLM(pp_stages=v*S, pp_schedule="interleaved") on a pp=S
# mesh: stage params are stored CHUNKED [v, S, ...] (round-robin
# placement — parallel/pipeline.py), so the pp shard moves to dim 1.
# n_chunks here is only a LAYOUT FLAG (any value > 1 selects the
# chunked specs) — these rules apply to every v, not just v=2.
LM_PP_INTERLEAVED_PARTITION_RULES = _ppsr(n_chunks=2) + LM_PARTITION_RULES


# MoE-LM (moe_experts > 0): expert weights over ep(+tp) + the LM rules.
# (moe.py imports no LM/transformer modules at top level — no cycle.)
from analytics_zoo_tpu.models.moe import MOE_PARTITION_RULES as _MOE_RULES

LM_MOE_PARTITION_RULES = _MOE_RULES + LM_PARTITION_RULES


def beam_search(model: TransformerLM, variables, prompt,
                max_new_tokens: int, beam_size: int = 4, *,
                prompt_len=None, eos_id=None,
                length_penalty: float = 0.0) -> tuple:
    """Beam-search decoding as lax.scans (compiler-friendly: the beam
    lives as an extra leading dim, KV caches reorder on-device with a
    batched gather instead of host-side bookkeeping).

    prompt: [B, P] int32.  ``prompt_len`` (optional [B] int32) gives each
    row's true length for right-padded ragged batches — same contract as
    ``generate()``.  Returns ``(tokens [B, beam, max_new], scores
    [B, beam])`` with beams sorted best-first.

    ``eos_id``: a beam that emits it (past its prompt) FREEZES — its
    score stops accumulating and its tail fills with eos (fixed shapes;
    the frozen hypothesis keeps competing in top-k on its final score,
    the standard finished-beam semantics).  Without EOS handling a beam
    would keep scoring past end-of-sequence and eos-trained models would
    rank garbage continuations.

    ``length_penalty`` (alpha): beams are ranked by
    ``score / ((5 + n_tokens) / 6) ** alpha`` (GNMT), where ``n_tokens``
    counts real tokens up to and including eos.  ``alpha=0`` (default)
    ranks by raw sum log-prob; returned ``scores`` are always the
    ranking scores.

    Uniform prompts run a width-1 PREFILL scan first (K-wide prefill
    would waste (K-1)/K of the prefill FLOPs); ragged batches run one
    K-wide scan with per-row teacher-forcing, like ``generate()``.
    """
    B, Pn = prompt.shape
    K = int(beam_size)
    L = Pn + max_new_tokens
    if max_new_tokens <= 0:
        return (jnp.zeros((B, K, 0), jnp.int32),
                jnp.zeros((B, K), jnp.float32))
    if L > model.max_position:
        raise ValueError(f"prompt+new = {L} exceeds max_position "
                         f"{model.max_position}")
    V = model.vocab_size
    H = model.kv_heads                  # GQA: cache stores KV heads only
    D = model.hidden_size // model.num_heads
    cdtype = jnp.dtype(model.dtype)
    ragged = prompt_len is not None
    plen = (jnp.full((B,), Pn, jnp.int32) if not ragged
            else jnp.clip(jnp.asarray(prompt_len, jnp.int32), 1, Pn))

    def step(carry, t):
        """One K-wide position step: decode, expand/teacher-force, reorder.

        Rows still inside their prompt (t+1 < plen) teacher-force it on
        all K identical beams; a row's FIRST expansion (t+1 == plen)
        draws candidates from beam 0 only (the clones would produce K
        duplicate hypotheses); after that it's standard K*V expansion.
        """
        tok, ck, cv, scores, toks, done, nlen = carry
        logits, ck, cv = model.apply(
            variables, tok, ck, cv, t, method=TransformerLM.decode_step)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32),
                                  axis=-1).reshape(B, K, V)
        if eos_id is not None:
            # frozen beams: the only continuation is eos at logp 0, so
            # the finished score competes unchanged in top-k
            frozen = jnp.full((V,), -jnp.inf,
                              jnp.float32).at[eos_id].set(0.0)
            logp = jnp.where(done[:, :, None], frozen[None, None, :], logp)
        cand = scores[:, :, None] + logp                 # [B, K, V]
        first = (t + 1 == plen)                          # [B]
        cand = jnp.where(
            (first[:, None] & (jnp.arange(K) > 0)[None, :])[:, :, None],
            -jnp.inf, cand)
        top_s, top_i = lax.top_k(cand.reshape(B, K * V), K)
        src_beam = top_i // V
        nxt = (top_i % V).astype(jnp.int32)
        # a row is INACTIVE while still teacher-forcing its prompt
        # (w < 0) and again once its own max_new window is complete
        # (w >= max_new: ragged batches keep scanning for longer-prompt
        # rows — a completed row must freeze its scores and beam order,
        # not keep re-ranking on tokens outside its window)
        w = t + 1 - plen                # [B] generated-token index
        teach = w < 0
        inactive = teach | (w >= max_new_tokens)
        active = ~inactive
        # reorder beam state to follow the winning hypotheses; inactive
        # rows gather identity (no reorder)
        src_eff = jnp.where(inactive[:, None], jnp.arange(K)[None, :],
                            src_beam)
        new_toks = jnp.take_along_axis(toks, src_eff[:, :, None], axis=1)
        new_done = jnp.take_along_axis(done, src_eff, axis=1)
        new_len = jnp.take_along_axis(nlen, src_eff, axis=1)
        gidx = (jnp.arange(B)[:, None] * K + src_eff).reshape(-1)
        ck, cv = ck[:, gidx], cv[:, gidx]
        p_tok = prompt[:, jnp.minimum(t + 1, Pn - 1)]    # [B]
        nxt = jnp.where(teach[:, None], p_tok[:, None], nxt)
        top_s = jnp.where(inactive[:, None], scores, top_s)
        new_len = jnp.where(active[:, None] & ~new_done, new_len + 1,
                            new_len)
        if eos_id is not None:
            new_done = new_done | (active[:, None] & (nxt == eos_id))
        new_toks = lax.dynamic_update_index_in_dim(
            new_toks.transpose(2, 0, 1), nxt, t, 0).transpose(1, 2, 0)
        return (nxt.reshape(B * K), ck, cv, top_s, new_toks, new_done,
                new_len), None

    def tile(c):        # [layers, B, L, H, D] -> [layers, B*K, L, H, D]
        return jnp.repeat(c, K, axis=1)

    if not ragged and Pn > 1:
        # ---- width-1 prefill over the shared prompt ------------------
        ck1 = jnp.zeros((model.num_layers, B, L, H, D), cdtype)
        cv1 = jnp.zeros_like(ck1)

        def prefill(carry, t):
            ck, cv = carry
            _, ck, cv = model.apply(
                variables, prompt[:, t], ck, cv, t,
                method=TransformerLM.decode_step)
            return (ck, cv), None

        (ck1, cv1), _ = lax.scan(prefill, (ck1, cv1), jnp.arange(Pn - 1))
        if max_new_tokens == 1:
            # single-token beams need one more decode step but never the
            # K-wide cache tile or the generation scan; with every
            # hypothesis the same length the penalty only rescales
            logits, _, _ = model.apply(
                variables, prompt[:, Pn - 1], ck1, cv1, Pn - 1,
                method=TransformerLM.decode_step)
            logp0 = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            scores0, tok0_k = lax.top_k(logp0, K)
            # GNMT lp(1) == 1, so the penalty cannot reorder or rescale
            return tok0_k[:, :, None], scores0
        ck0, cv0 = tile(ck1), tile(cv1)
        t0 = Pn - 1
        tok0 = jnp.repeat(prompt[:, Pn - 1], K)
    else:
        ck0 = jnp.zeros((model.num_layers, B * K, L, H, D), cdtype)
        cv0 = jnp.zeros_like(ck0)
        t0 = 0
        tok0 = jnp.repeat(prompt[:, 0], K)

    # toks buffer covers every position the scan writes; the per-row
    # generated window [plen-1, plen-1+max_new) is gathered at the end
    carry = (tok0, ck0, cv0, jnp.zeros((B, K), jnp.float32),
             jnp.zeros((B, K, L - 1), jnp.int32),
             jnp.zeros((B, K), bool), jnp.zeros((B, K), jnp.int32))
    (_, _, _, scores, toks, done, nlen), _ = lax.scan(
        step, carry, t0 + jnp.arange(L - 1 - t0))
    widx = jnp.clip(plen[:, None, None] - 1
                    + jnp.arange(max_new_tokens)[None, None, :], 0, L - 2)
    toks = jnp.take_along_axis(toks, jnp.broadcast_to(
        widx, (B, K, max_new_tokens)), axis=2)
    if length_penalty:
        lp = ((5.0 + nlen.astype(jnp.float32)) / 6.0) ** float(
            length_penalty)
        scores = scores / lp
        order = jnp.argsort(-scores, axis=1)
        toks = jnp.take_along_axis(toks, order[:, :, None], axis=1)
        scores = jnp.take_along_axis(scores, order, axis=1)
    # without a penalty, lax.top_k already left beams sorted best-first
    return toks, scores


def unstack_pp_params(params, n_chunks: int = 1):
    """pp-trained param tree (``trunk/stages/...`` with a leading stage
    dim) -> the flat ``layer_{i}`` tree a ``pp_stages=0`` TransformerLM
    expects.  The bridge from pipeline training to cached-decode serving:
    train with pp, ``unstack_pp_params``, generate on a non-pp model of
    the same dimensions.

    ``pp_schedule="interleaved"`` models store stages CHUNKED
    [v, S, ...] (logical stage k*S + r at leaf[k, r] — round-robin
    placement, parallel/pipeline.py); pass the model's ``n_chunks``
    (= pp_stages / mesh pp size) so the logical order is reassembled."""
    out = {k: v for k, v in params.items() if k != "trunk"}
    stacked = params["trunk"]["stages"]
    stage_layers = sorted(
        (k for k in stacked if k.startswith("layer_")),
        key=lambda k: int(k.split("_")[1]))
    k_per = len(stage_layers)
    lead = jax.tree.leaves(stacked)[0].shape
    if n_chunks > 1:
        v, S = int(n_chunks), lead[1]
        if lead[0] != v:
            raise ValueError(
                f"n_chunks={n_chunks} does not match the chunked stage "
                f"leaves' leading dims {lead[:2]}; pass the value the "
                f"model was built with (pp_stages / mesh pp size)")
        for k in range(v):
            for r in range(S):
                for j, name in enumerate(stage_layers):
                    out[f"layer_{(k * S + r) * k_per + j}"] = \
                        jax.tree.map(lambda a: a[k, r], stacked[name])
        return out
    S = lead[0]
    for s in range(S):
        for j, name in enumerate(stage_layers):
            out[f"layer_{s * k_per + j}"] = jax.tree.map(
                lambda a: a[s], stacked[name])
    return out


def _make_norm(kind: str, eps: float, name: str):
    """One norm selector for block norms and the final norm — the two
    must never drift (a mismatch would silently skew logits)."""
    if kind == "rmsnorm":
        return nn.RMSNorm(dtype=jnp.float32, name=name, epsilon=eps)
    return nn.LayerNorm(dtype=jnp.float32, name=name, epsilon=eps)


def _apply_rope(x, pos, base: float):
    """Rotary position embedding (rotate-half convention).

    x: [..., T, H, D] (D even); pos: positions broadcastable against the
    T axis — ``arange(T)`` for the training forward, a scalar-as-[1] or
    per-row [B] vector for cached decode.  K is stored POST-rotation in
    the KV cache (absolute rotation per position; the relative-offset
    property emerges in the q.k dot product), so decode and forward see
    identical keys."""
    D = x.shape[-1]
    if D % 2:
        raise ValueError(
            f"rotary positions need an even head dim, got {D} "
            f"(hidden_size must be divisible by 2*num_heads)")
    half = D // 2
    freqs = base ** (-jnp.arange(half, dtype=jnp.float32) * 2.0 / D)
    ang = pos[..., None].astype(jnp.float32) * freqs    # [..., T, half]
    cos = jnp.cos(ang)[..., None, :]                    # [..., T, 1, half]
    sin = jnp.sin(ang)[..., None, :]
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin,
                           x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _stack_kv(xs):
    """``jnp.stack`` over per-layer KV pools that also works for the
    quantized pools (``ops.flash_attention.QuantKV`` pytrees): every
    leaf (data, scale) is stacked along a new leading layers axis."""
    return jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *xs)


class DecoderAttention(nn.Module):
    """Causal self-attention with a training path and a cached decode path
    sharing the same projections (setup-style module).

    ``num_kv_heads < num_heads`` is grouped-query attention (MQA at 1):
    K/V project to fewer heads, shared by groups of query heads.  The
    TRAINING forward broadcasts K/V up to full width (same FLOPs as MHA
    — flash/ring paths work unchanged); the win is the DECODE cache,
    which stores only ``num_kv_heads`` heads: H/KV_H times smaller KV
    per token, which multiplies continuous-serving arena capacity and
    long-generation memory headroom the same way."""

    hidden_size: int
    num_heads: int
    num_kv_heads: Optional[int] = None
    dtype: jnp.dtype = jnp.bfloat16
    mesh: Optional[Mesh] = None
    use_flash: Optional[bool] = None
    sp_strategy: str = "ring"
    # "learned": the LM adds position embeddings before the trunk;
    # "rope": q/k rotate here (applied pre-dispatch on global positions,
    # so flash/ring/GQA paths run unchanged)
    pos_encoding: str = "learned"
    rope_base: float = 10000.0
    use_bias: bool = True       # llama-family imports project bias-free
    # qwen2-style split: biased q/k/v with a bias-free o_proj/mlp
    # (None follows use_bias)
    qkv_bias: Optional[bool] = None

    def setup(self):
        H = self.num_heads
        KH = self.num_kv_heads or H
        if H % KH:
            raise ValueError(
                f"num_heads {H} must be a multiple of num_kv_heads {KH}")
        D = self.hidden_size // H
        self._h, self._kh, self._d = H, KH, D
        qkvb = self.use_bias if self.qkv_bias is None else self.qkv_bias
        self.query = nn.DenseGeneral((H, D), dtype=self.dtype,
                                     use_bias=qkvb,
                                     name="query")
        self.key = nn.DenseGeneral((KH, D), dtype=self.dtype,
                                   use_bias=qkvb, name="key")
        self.value = nn.DenseGeneral((KH, D), dtype=self.dtype,
                                     use_bias=qkvb,
                                     name="value")
        self.attn_out = nn.DenseGeneral(self.hidden_size, axis=(-2, -1),
                                        dtype=self.dtype,
                                        use_bias=self.use_bias,
                                        name="attn_out")

    def _expand_kv(self, t):
        """[B, T, KH, D] -> [B, T, H, D] by repeating each KV head over
        its query group (training path: keeps flash/ring unchanged)."""
        if self._kh == self._h:
            return t
        return jnp.repeat(t, self._h // self._kh, axis=2)

    def __call__(self, x, train: bool = False, return_kv: bool = False):
        """Training/scoring: [B, T, E] -> [B, T, E], causal.
        ``return_kv=True`` also returns this layer's K/V projections
        ``[B, T, KV_H, D]`` (KV-arena prefill for continuous batching)."""
        q, k, v = self.query(x), self.key(x), self.value(x)
        if self.pos_encoding == "rope":
            t_pos = jnp.arange(x.shape[1])
            q = _apply_rope(q, t_pos, self.rope_base)
            k = _apply_rope(k, t_pos, self.rope_base)
        o = attention_dispatch(q, self._expand_kv(k), self._expand_kv(v),
                               None, causal=True, mesh=self.mesh,
                               use_flash=self.use_flash,
                               sp_strategy=self.sp_strategy)
        out = self.attn_out(o)
        return (out, k, v) if return_kv else out

    def decode(self, x1, cache_k, cache_v, pos):
        """One cached decode step.

        x1: [B, 1, E] current-position hidden; cache_k/v: [B, L, KV_H,
        D] preallocated; pos: int32 current position — a SCALAR advances
        the whole batch in lockstep (generate/beam_search); a VECTOR [B]
        gives each row its own position (the continuous-batching engine,
        where co-resident requests are at different depths).  Returns
        (y1 [B, 1, E], new_cache_k, new_cache_v).
        """
        B = x1.shape[0]
        L = cache_k.shape[1]
        KH = self._kh
        G = self._h // KH                   # query heads per KV head
        q = self.query(x1)                              # [B, 1, H, D]
        k1 = self.key(x1)                               # [B, 1, KH, D]
        v1 = self.value(x1)
        if self.pos_encoding == "rope":
            # rotate at the CURRENT position; the cache already holds
            # post-rotation keys for earlier positions
            p = (jnp.reshape(pos, (1,)) if jnp.ndim(pos) == 0
                 else pos[:, None])
            q = _apply_rope(q, p, self.rope_base)
            k1 = _apply_rope(k1, p, self.rope_base)
        if jnp.ndim(pos) == 0:
            cache_k = lax.dynamic_update_slice(
                cache_k, k1.astype(cache_k.dtype), (0, pos, 0, 0))
            cache_v = lax.dynamic_update_slice(
                cache_v, v1.astype(cache_v.dtype), (0, pos, 0, 0))
            mask = (jnp.arange(L) <= pos)[None, None, None, None, :]
        else:
            # per-row scatter: row b writes its K/V at pos[b] and attends
            # positions <= pos[b] (O(B*L*KH*D) masked write — the same
            # bandwidth the attention read below already pays)
            hit = (jnp.arange(L)[None, :] == pos[:, None])[:, :, None, None]
            cache_k = jnp.where(hit, k1.astype(cache_k.dtype), cache_k)
            cache_v = jnp.where(hit, v1.astype(cache_v.dtype), cache_v)
            mask = (jnp.arange(L)[None, :]
                    <= pos[:, None])[:, None, None, None, :]
        scale = 1.0 / jnp.sqrt(self._d).astype(jnp.float32)
        # grouped attention: q regroups [B, 1, KH, G, D] so each KV head
        # serves its G query heads without materialising expanded KV
        qg = q.reshape(B, 1, KH, G, self._d)
        logits = jnp.einsum("bqhgd,bkhd->bhgqk", qg, cache_k,
                            preferred_element_type=jnp.float32) * scale
        logits = jnp.where(mask, logits, -jnp.inf)
        w = jax.nn.softmax(logits, axis=-1)
        o = jnp.einsum("bhgqk,bkhd->bqhgd", w.astype(cache_v.dtype),
                       cache_v, preferred_element_type=jnp.float32)
        o = o.reshape(B, 1, self._h, self._d)
        return self.attn_out(o.astype(self.dtype)), cache_k, cache_v

    def decode_k(self, xs, cache_k, cache_v, pos):
        """Cached decode of S tokens AT ONCE — the verify pass of
        speculative decoding (models/speculative.py): the S draft tokens
        run one MXU-friendly forward instead of S sequential steps.

        xs: [B, S, E] hiddens of the S new tokens; pos: [B] int32, row
        b's tokens occupy cache positions pos[b]..pos[b]+S-1.  Token j
        attends cache entries < its own position plus itself (block-
        causal against the cache, exactly the mask sequential decode
        would have produced).  Returns (ys [B, S, E], cache_k, cache_v)
        with all S K/V written; the CALLER decides how much of the write
        becomes durable by how far it advances pos (rejected tokens'
        entries are never attended once pos stops short of them, and the
        next round overwrites them).
        """
        B, S = xs.shape[0], xs.shape[1]
        L = cache_k.shape[1]
        KH = self._kh
        G = self._h // KH
        q = self.query(xs)                              # [B, S, H, D]
        ks = self.key(xs)                               # [B, S, KH, D]
        vs = self.value(xs)
        p = pos[:, None] + jnp.arange(S)[None, :]       # [B, S]
        if self.pos_encoding == "rope":
            q = _apply_rope(q, p, self.rope_base)
            ks = _apply_rope(ks, p, self.rope_base)
        # scatter the S new K/V rows to their per-row positions: one-hot
        # matmul [B,S,L] — O(B·S·L·KH·D), the bandwidth the attention
        # read below pays anyway (S is the small speculation depth)
        hit = (jnp.arange(L)[None, None, :] == p[:, :, None])  # [B,S,L]
        scat = hit.astype(cache_k.dtype)
        wrote = hit.any(axis=1)[:, :, None, None]              # [B,L,1,1]
        new_k = jnp.einsum("bsl,bshd->blhd", scat,
                           ks.astype(cache_k.dtype))
        new_v = jnp.einsum("bsl,bshd->blhd", scat,
                           vs.astype(cache_v.dtype))
        cache_k = jnp.where(wrote, new_k, cache_k)
        cache_v = jnp.where(wrote, new_v, cache_v)
        # token j sees cache position l iff l <= pos[b]+j
        mask = (jnp.arange(L)[None, None, :]
                <= p[:, :, None])[:, None, None, :, :]  # [B,1,1,S,L]
        scale = 1.0 / jnp.sqrt(self._d).astype(jnp.float32)
        qg = q.reshape(B, S, KH, G, self._d)
        logits = jnp.einsum("bqhgd,bkhd->bhgqk", qg, cache_k,
                            preferred_element_type=jnp.float32) * scale
        logits = jnp.where(mask, logits, -jnp.inf)
        w = jax.nn.softmax(logits, axis=-1)
        o = jnp.einsum("bhgqk,bkhd->bqhgd", w.astype(cache_v.dtype),
                       cache_v, preferred_element_type=jnp.float32)
        o = o.reshape(B, S, self._h, self._d)
        return self.attn_out(o.astype(self.dtype)), cache_k, cache_v

    def decode_paged(self, xs, pool_k, pool_v, tables, pos, limit=None,
                     kernel="gather", mesh=None, kv_sharded=True):
        """Cached decode of S tokens per row against a PAGED KV cache.

        Same contract as :meth:`decode_k` except the cache is one flat
        head-major block pool shared by every resident: pool_k/pool_v
        ``[N, KH, bs, D]`` (or QuantKV int8 pools of that geometry),
        tables ``[B, M]`` int32 mapping row b's logical block j to a
        physical pool block (the serving BlockPool keeps unallocated
        table entries pointed at the sink block 0).  xs: [B, S, E];
        pos: [B] int32, row b's tokens occupy logical positions
        pos[b]..pos[b]+S-1.  S=1 is the plain decode step; S>1 is the
        block-causal prefill/verify forward.  Returns (ys [B, S, E],
        pool_k, pool_v) with the S new K/V rows scattered through the
        tables (write precedes the attention read, so each token
        attends itself).  ``limit`` ([B] int32, optional) drops writes
        at positions >= limit[b] — chunked prefill's padding guard (see
        ops.flash_attention.paged_kv_update).  ``kernel`` selects the
        attention read path (``"gather"`` fallback or the ``"fused"``
        Pallas kernel — ops.flash_attention.paged_attention).  ``mesh``
        + ``kv_sharded`` (fused only) run the kernel per-chip under
        shard_map against a tp-sharded (or, hatch, replicated) pool —
        passed explicitly by the serving engine rather than read from
        ``self.mesh`` because the engine owns the pool placement.
        """
        from analytics_zoo_tpu.ops.flash_attention import (
            paged_attention, paged_kv_update)

        q = self.query(xs)                              # [B, S, H, D]
        ks = self.key(xs)                               # [B, S, KH, D]
        vs = self.value(xs)
        if self.pos_encoding == "rope":
            p = pos[:, None] + jnp.arange(xs.shape[1])[None, :]
            q = _apply_rope(q, p, self.rope_base)
            ks = _apply_rope(ks, p, self.rope_base)
        pool_k, pool_v = paged_kv_update(pool_k, pool_v, tables, pos,
                                         ks, vs, limit=limit)
        o = paged_attention(q, pool_k, pool_v, tables, pos,
                            kernel=kernel, mesh=mesh,
                            kv_sharded=kv_sharded)
        return self.attn_out(o.astype(self.dtype)), pool_k, pool_v


class DecoderLayer(nn.Module):
    """Pre-LN causal decoder block (pre-LN trains stably at depth without
    the reference's warmup tricks; BERT keeps post-LN for ref parity)."""

    hidden_size: int
    num_heads: int
    intermediate_size: int
    dropout: float = 0.1
    dtype: jnp.dtype = jnp.bfloat16
    mesh: Optional[Mesh] = None
    use_flash: Optional[bool] = None
    sp_strategy: str = "ring"
    num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    num_kv_heads: Optional[int] = None
    pos_encoding: str = "learned"
    rope_base: float = 10000.0
    # LayerNorm epsilon: flax's 1e-6 by default; importers of foreign
    # checkpoints (net/hf_net.py — GPT-2 uses 1e-5) must match it or
    # logits drift
    ln_eps: float = 1e-6
    # "layernorm" | "rmsnorm"; "gelu" | "swiglu" — the llama family is
    # rmsnorm + swiglu + bias-free projections (net/hf_net.py)
    norm: str = "layernorm"
    mlp: str = "gelu"
    use_bias: bool = True
    qkv_bias: Optional[bool] = None

    def setup(self):
        self.ln_attn = _make_norm(self.norm, self.ln_eps, "ln_attn")
        self.attention = DecoderAttention(
            self.hidden_size, self.num_heads,
            num_kv_heads=self.num_kv_heads, dtype=self.dtype,
            mesh=self.mesh, use_flash=self.use_flash,
            sp_strategy=self.sp_strategy,
            pos_encoding=self.pos_encoding, rope_base=self.rope_base,
            use_bias=self.use_bias, qkv_bias=self.qkv_bias,
            name="attention")
        self.ln_ffn = _make_norm(self.norm, self.ln_eps,
                                 "ln_ffn")
        if self.num_experts > 0:
            from analytics_zoo_tpu.models.moe import MoEMLP

            self.moe = MoEMLP(self.num_experts, self.intermediate_size,
                              top_k=self.moe_top_k,
                              capacity_factor=self.moe_capacity_factor,
                              dtype=self.dtype, mesh=self.mesh,
                              name="moe")
        else:
            self.ffn_up = nn.Dense(self.intermediate_size,
                                   dtype=self.dtype,
                                   use_bias=self.use_bias, name="ffn_up")
            self.ffn_down = nn.Dense(self.hidden_size, dtype=self.dtype,
                                     use_bias=self.use_bias,
                                     name="ffn_down")
            if self.mlp == "swiglu":
                self.ffn_gate = nn.Dense(self.intermediate_size,
                                         dtype=self.dtype,
                                         use_bias=self.use_bias,
                                         name="ffn_gate")
        self.drop = nn.Dropout(self.dropout)

    def _mlp(self, x, train):
        if self.num_experts > 0:
            # Per-token routing runs for both the [B, T, E] training
            # forward and the [B, 1, E] cached decode step.  NOTE: the
            # capacity pool differs (B*T tokens jointly vs B per decode
            # step), so under skewed routing decode logits can deviate
            # slightly from the teacher-forced forward — the same
            # batch-coupling property documented on MoEMLP; raise
            # moe_capacity_factor where that matters.
            h = self.moe(x, train)
        elif self.mlp == "swiglu":
            h = self.ffn_down(nn.silu(self.ffn_gate(x))
                              * self.ffn_up(x))
        else:
            h = self.ffn_down(nn.gelu(self.ffn_up(x)))
        return self.drop(h, deterministic=not train)

    def __call__(self, x, train: bool = False):
        a = self.attention(self.ln_attn(x).astype(self.dtype), train)
        x = x + self.drop(a, deterministic=not train)
        x = _constrain_seq(x, self.mesh)
        x = x + self._mlp(self.ln_ffn(x).astype(self.dtype), train)
        return _constrain_seq(x, self.mesh)

    def decode(self, x1, cache_k, cache_v, pos):
        a, ck, cv = self.attention.decode(
            self.ln_attn(x1).astype(self.dtype), cache_k, cache_v, pos)
        x1 = x1 + a
        x1 = x1 + self._mlp(self.ln_ffn(x1).astype(self.dtype), False)
        return x1, ck, cv

    def decode_k(self, xs, cache_k, cache_v, pos):
        a, ck, cv = self.attention.decode_k(
            self.ln_attn(xs).astype(self.dtype), cache_k, cache_v, pos)
        xs = xs + a
        xs = xs + self._mlp(self.ln_ffn(xs).astype(self.dtype), False)
        return xs, ck, cv

    def decode_paged(self, xs, pool_k, pool_v, tables, pos, limit=None,
                     kernel="gather", mesh=None, kv_sharded=True):
        a, pk, pv = self.attention.decode_paged(
            self.ln_attn(xs).astype(self.dtype), pool_k, pool_v,
            tables, pos, limit=limit, kernel=kernel, mesh=mesh,
            kv_sharded=kv_sharded)
        xs = xs + a
        xs = xs + self._mlp(self.ln_ffn(xs).astype(self.dtype), False)
        return xs, pk, pv

    def forward_kv(self, x, train: bool = False):
        """``__call__`` that also returns this layer's K/V ``[B, T, H,
        D]`` — the prompt-prefill payload the continuous-batching engine
        writes into its KV arena.  Same math as ``__call__`` (constraints
        included), so prefilled logits equal the training forward's."""
        a, k, v = self.attention(self.ln_attn(x).astype(self.dtype),
                                 train, return_kv=True)
        x = x + self.drop(a, deterministic=not train)
        x = _constrain_seq(x, self.mesh)
        x = x + self._mlp(self.ln_ffn(x).astype(self.dtype), train)
        return _constrain_seq(x, self.mesh), k, v


class _LMStage(nn.Module):
    """One pipeline stage: a block of consecutive decoder layers with a
    plain ``x -> x`` signature (the GPipe stage contract)."""

    layers_per_stage: int
    hidden_size: int
    num_heads: int
    intermediate_size: int
    dtype: jnp.dtype = jnp.bfloat16
    use_flash: Optional[bool] = None
    num_kv_heads: Optional[int] = None
    pos_encoding: str = "learned"
    rope_base: float = 10000.0
    ln_eps: float = 1e-6
    norm: str = "layernorm"
    mlp: str = "gelu"
    use_bias: bool = True
    qkv_bias: Optional[bool] = None

    @nn.compact
    def __call__(self, x):
        for i in range(self.layers_per_stage):
            # stages run inside shard_map: no mesh constraints (manual
            # SPMD there), no dropout (no rng plumbing through the ticks)
            x = DecoderLayer(self.hidden_size, self.num_heads,
                             self.intermediate_size, dropout=0.0,
                             dtype=self.dtype, mesh=None,
                             use_flash=self.use_flash,
                             num_kv_heads=self.num_kv_heads,
                             pos_encoding=self.pos_encoding,
                             rope_base=self.rope_base,
                             ln_eps=self.ln_eps,
                             norm=self.norm, mlp=self.mlp,
                             use_bias=self.use_bias,
                             qkv_bias=self.qkv_bias,
                             name=f"layer_{i}")(x, False)
        return x


class TransformerLM(nn.Module):
    """Decoder-only LM with tied embeddings.

    ``__call__(tokens)`` -> next-token logits ``[B, T, V]`` (causal);
    ``decode_step`` runs one cached generation step (used by
    ``generate``).

    ``pp_stages > 0`` pipelines the trunk over the mesh's ``pp`` axis
    (SPMD GPipe, parallel/pipeline.py): ``num_layers`` must divide into
    ``pp_stages`` equal blocks, dropout must be 0, and generation is a
    training-cluster non-goal there (``decode_step`` raises — serve a
    non-pp restore of the same weights instead).
    """

    vocab_size: int
    hidden_size: int = 256
    num_layers: int = 4
    num_heads: int = 4
    intermediate_size: int = 1024
    max_position: int = 512
    dropout: float = 0.0
    dtype: jnp.dtype = jnp.bfloat16
    mesh: Optional[Mesh] = None
    use_flash: Optional[bool] = None
    remat: bool = False
    pp_stages: int = 0
    pp_microbatches: int = 4
    # "gpipe" | "1f1b" | "interleaved": training schedule for the
    # pipelined trunk (parallel/pipeline.py — 1f1b bounds activation
    # residency at O(S); interleaved additionally needs pp_stages to be
    # a multiple v*S of the mesh's pp size and cuts the bubble v-fold,
    # with LM_PP_INTERLEAVED_PARTITION_RULES for the chunked layout)
    pp_schedule: str = "gpipe"
    sp_strategy: str = "ring"
    # MoE-LM: every moe_every-th layer gets an expert-parallel MoE FFN.
    # Cached decode routes per step (B tokens) while the forward routes
    # B*T jointly, so capacity-dropped tokens can differ between the two
    # under skew — see DecoderLayer._mlp / MoEMLP docstrings.
    moe_experts: int = 0
    moe_every: int = 2
    moe_top_k: int = 2
    # decode routes only B tokens/step: raise this where batch-coupled
    # capacity drops matter (MoEMLP docstring)
    moe_capacity_factor: float = 1.25
    # Grouped-query attention: K/V project to this many heads (must
    # divide num_heads; None = MHA, 1 = MQA).  Training FLOPs are
    # unchanged (K/V broadcast up); the DECODE KV cache shrinks
    # num_heads/num_kv_heads-fold — allocate caches with `.kv_heads`.
    num_kv_heads: Optional[int] = None
    # "learned" (ref-style absolute table) | "rope" (rotary q/k — no
    # position table; max_position still bounds sequence/cache length)
    pos_encoding: str = "learned"
    rope_base: float = 10000.0
    # LayerNorm epsilon — foreign-checkpoint importers must match the
    # source model's (GPT-2: 1e-5; net/hf_net.py sets this)
    ln_eps: float = 1e-6
    # llama-family knobs (net/hf_net.py from_hf_llama): rmsnorm blocks,
    # SwiGLU MLP, bias-free projections, untied lm_head.  Defaults are
    # the GPT-2-shaped configuration every existing user of this class
    # already has.
    norm: str = "layernorm"         # "layernorm" | "rmsnorm"
    mlp: str = "gelu"               # "gelu" | "swiglu"
    use_bias: bool = True
    # qwen2-style: biased q/k/v despite bias-free o_proj/mlp
    qkv_bias: Optional[bool] = None
    tied_head: bool = True

    @property
    def kv_heads(self) -> int:
        """Heads actually stored in the KV cache (GQA-aware; every cache
        allocation site — generate/beam/engine — sizes with this)."""
        return self.num_kv_heads or self.num_heads

    def setup(self):
        if self.pos_encoding not in ("learned", "rope"):
            raise ValueError(
                f"pos_encoding must be 'learned' or 'rope', got "
                f"{self.pos_encoding!r}")
        self.embed = nn.Embed(self.vocab_size, self.hidden_size,
                              name="embed")
        # rope rotates q/k inside attention: no absolute position table
        self.pos_embed = (
            nn.Embed(self.max_position, self.hidden_size,
                     name="pos_embed")
            if self.pos_encoding == "learned" else None)
        self.ln_f = _make_norm(self.norm, self.ln_eps, "ln_f")
        if not self.tied_head:
            self.lm_head = nn.Dense(self.vocab_size, use_bias=False,
                                    dtype=jnp.float32, name="lm_head")
        if self.pp_stages > 0:
            from analytics_zoo_tpu.parallel.pipeline import GPipe

            if self.num_layers % self.pp_stages:
                raise ValueError(
                    f"num_layers {self.num_layers} must divide into "
                    f"pp_stages {self.pp_stages}")
            if self.dropout:
                raise ValueError("pp_stages needs dropout=0 (stages run "
                                 "without rng plumbing)")
            if self.remat:
                raise ValueError(
                    "remat is not applied to pipelined trunks (the GPipe "
                    "scan already bounds live activations to one "
                    "microbatch per stage); set remat=False")
            if self.moe_experts:
                raise ValueError(
                    "moe_experts is not supported with pp_stages (MoE "
                    "dispatch inside shard_map stages would not see the "
                    "ep axis); use MoE without pp, or pp without MoE")
            self.trunk = GPipe(
                stage=_LMStage(self.num_layers // self.pp_stages,
                               self.hidden_size, self.num_heads,
                               self.intermediate_size, dtype=self.dtype,
                               use_flash=self.use_flash,
                               num_kv_heads=self.num_kv_heads,
                               pos_encoding=self.pos_encoding,
                               rope_base=self.rope_base,
                               ln_eps=self.ln_eps,
                               norm=self.norm, mlp=self.mlp,
                               use_bias=self.use_bias,
                               qkv_bias=self.qkv_bias),
                n_stages=self.pp_stages,
                n_microbatches=self.pp_microbatches,
                schedule=self.pp_schedule,
                mesh=self.mesh, name="trunk")
            self.layers = ()
            return
        # remat checkpoints each block's training __call__ (recompute in
        # backward instead of storing activations); decode is untouched
        # (no gradients there)
        layer_cls = nn.remat(DecoderLayer, static_argnums=(2,),
                             methods=["__call__"]) if self.remat \
            else DecoderLayer
        self.layers = [
            layer_cls(self.hidden_size, self.num_heads,
                      self.intermediate_size, self.dropout,
                      dtype=self.dtype, mesh=self.mesh,
                      use_flash=self.use_flash,
                      sp_strategy=self.sp_strategy,
                      num_experts=(self.moe_experts if self.moe_experts > 0
                                   and (i + 1) % max(1, self.moe_every) == 0
                                   else 0),
                      moe_top_k=self.moe_top_k,
                      moe_capacity_factor=self.moe_capacity_factor,
                      num_kv_heads=self.num_kv_heads,
                      pos_encoding=self.pos_encoding,
                      rope_base=self.rope_base,
                      ln_eps=self.ln_eps,
                      norm=self.norm, mlp=self.mlp,
                      use_bias=self.use_bias, qkv_bias=self.qkv_bias,
                      name=f"layer_{i}")
            for i in range(self.num_layers)]

    def _logits(self, x):
        if not self.tied_head:
            return self.lm_head(x.astype(jnp.float32))
        # tied head: f32 logits for a stable softmax/CE
        emb = self.embed.embedding.astype(jnp.float32)
        return jnp.einsum("bte,ve->btv", x.astype(jnp.float32), emb)

    def hidden_states(self, tokens, train: bool = False):
        """Final-LayerNorm hidden states [B, T, H] — the forward minus
        the vocab head.  ``LMWithFusedLoss`` consumes this to compute CE
        blockwise without ever materialising the [B, T, V] logits."""
        B, T = tokens.shape
        if T > self.max_position:
            raise ValueError(
                f"sequence length {T} exceeds max_position "
                f"{self.max_position} (out-of-range position lookups "
                "would silently return NaN/clamped rows)")
        x = self.embed(tokens)
        if self.pos_embed is not None:
            x = x + self.pos_embed(jnp.arange(T)[None])
        x = _constrain_seq(x.astype(self.dtype), self.mesh)
        if self.pp_stages > 0:
            x = self.trunk(x)
        else:
            for layer in self.layers:
                x = layer(x, train)
        return self.ln_f(x)

    def __call__(self, tokens, train: bool = False):
        return self._logits(self.hidden_states(tokens, train))

    def decode_step(self, tok, caches_k, caches_v, pos):
        """tok: [B] current tokens; caches_k/v: [n_layers, B, L,
        kv_heads, D] (GQA models cache only their KV heads); pos: scalar
        int32 (lockstep batch) or [B] vector (per-row positions,
        continuous batching).  Returns (logits [B, V], caches_k,
        caches_v)."""
        if self.pp_stages > 0:
            raise NotImplementedError(
                "cached decode is not pipelined; convert the params with "
                "models.lm.unstack_pp_params and generate on a "
                "pp_stages=0 TransformerLM of the same dimensions")
        x = self.embed(tok)[:, None]
        if self.pos_embed is not None:
            x = x + (self.pos_embed(pos)[None, None]
                     if jnp.ndim(pos) == 0
                     else self.pos_embed(pos)[:, None])
        x = x.astype(self.dtype)
        ks, vs = [], []
        for i, layer in enumerate(self.layers):
            x, ck, cv = layer.decode(x, caches_k[i], caches_v[i], pos)
            ks.append(ck)
            vs.append(cv)
        logits = self._logits(self.ln_f(x))[:, 0]
        return logits, jnp.stack(ks), jnp.stack(vs)

    def verify_step(self, toks, caches_k, caches_v, pos):
        """Cached decode of S tokens per row in ONE forward — the
        speculative-decoding verify pass (models/speculative.py).

        toks: [B, S]; caches as in decode_step; pos: [B] int32, row b's
        tokens land at cache positions pos[b]..pos[b]+S-1.  Returns
        (logits [B, S, V], caches_k, caches_v).  All S K/V entries are
        written; advancing pos by fewer than S on the next call makes
        the surplus entries dead (never attended, later overwritten) —
        that is the rejection mechanism."""
        h, ck, cv = self.verify_hidden(toks, caches_k, caches_v, pos)
        return self._logits(h), ck, cv

    def verify_hidden(self, toks, caches_k, caches_v, pos):
        """``verify_step`` minus the vocab head: (hidden [B, S, H],
        caches).  Callers that consume ONE position per row (the greedy
        forward prefill) gather the hidden state first and apply the
        head to [B, 1, H] — materialising [B, S, V] logits for a long
        prompt is exactly the multi-GB residency LMWithFusedLoss exists
        to avoid."""
        if self.pp_stages > 0:
            raise NotImplementedError(
                "verify_step is not pipelined (same restriction as "
                "decode_step); convert with models.lm.unstack_pp_params")
        B, S = toks.shape
        x = self.embed(toks)
        if self.pos_embed is not None:
            p = pos[:, None] + jnp.arange(S)[None, :]
            x = x + self.pos_embed(p)
        x = x.astype(self.dtype)
        ks, vs = [], []
        for i, layer in enumerate(self.layers):
            x, ck, cv = layer.decode_k(x, caches_k[i], caches_v[i], pos)
            ks.append(ck)
            vs.append(cv)
        return self.ln_f(x), jnp.stack(ks), jnp.stack(vs)

    def decode_step_paged(self, tok, pools_k, pools_v, tables, pos,
                          kernel="gather", mesh=None, kv_sharded=True):
        """One cached decode step against a PAGED KV cache.

        tok: [B] current tokens; pools_k/v: [n_layers, N, kv_heads, bs,
        D] (plain arrays or ops.flash_attention.QuantKV int8 pools) —
        ONE flat block pool per layer shared by all residents;
        tables: [B, M] int32 per-row block tables (logical block j ->
        physical pool block); pos: [B] int32 per-row positions.
        Returns (logits [B, V], pools_k, pools_v) with each row's new
        K/V written through its table at position pos[b] — attention
        reads only logical positions <= pos[b], so garbage in
        unwritten/sink blocks is never attended.  ``kernel`` picks the
        gather fallback or the fused Pallas paged-attention kernel;
        ``mesh``/``kv_sharded`` run the fused kernel per-chip under
        shard_map against the engine's tp-sharded (or replicated-hatch)
        pool layout (ops.flash_attention.paged_attention).
        """
        if self.pp_stages > 0:
            raise NotImplementedError(
                "cached decode is not pipelined; convert the params "
                "with models.lm.unstack_pp_params and generate on a "
                "pp_stages=0 TransformerLM of the same dimensions")
        x = self.embed(tok)[:, None]
        if self.pos_embed is not None:
            x = x + self.pos_embed(pos)[:, None]
        x = x.astype(self.dtype)
        ks, vs = [], []
        for i, layer in enumerate(self.layers):
            x, pk, pv = layer.decode_paged(x, pools_k[i], pools_v[i],
                                           tables, pos, kernel=kernel,
                                           mesh=mesh,
                                           kv_sharded=kv_sharded)
            ks.append(pk)
            vs.append(pv)
        logits = self._logits(self.ln_f(x))[:, 0]
        return logits, _stack_kv(ks), _stack_kv(vs)

    def verify_step_paged(self, toks, pools_k, pools_v, tables, pos,
                          kernel="gather", mesh=None, kv_sharded=True):
        """``verify_step`` against a paged cache: S tokens per row in one
        block-causal forward, K/V scattered through the block tables.
        Returns (logits [B, S, V], pools_k, pools_v).

        Same rejection mechanism as :meth:`verify_step`, expressed in
        pages: all S entries are written through the table, and the
        caller advancing ``pos`` by fewer than S makes the surplus
        entries dead — the next verify overwrites them in place before
        the causal mask ever exposes them, so speculative rollback
        costs zero block copies (ops/flash_attention.paged_kv_update
        documents the write/clamp contract)."""
        h, pk, pv = self.verify_hidden_paged(toks, pools_k, pools_v,
                                             tables, pos, kernel=kernel,
                                             mesh=mesh,
                                             kv_sharded=kv_sharded)
        return self._logits(h), pk, pv

    def verify_hidden_paged(self, toks, pools_k, pools_v, tables, pos,
                            limit=None, kernel="gather", mesh=None,
                            kv_sharded=True):
        """``verify_step_paged`` minus the vocab head: (hidden [B, S,
        H], pools).  The paged-admission prefill consumes ONE position
        per row, gathers that hidden state, and applies the head to
        [B, 1, H] — same logits-residency rationale as
        :meth:`verify_hidden`.  ``limit`` ([B] int32, optional) drops
        K/V writes at positions >= limit[b] (padding columns of a
        chunk/suffix grid write nothing at all)."""
        if self.pp_stages > 0:
            raise NotImplementedError(
                "verify_step is not pipelined (same restriction as "
                "decode_step); convert with models.lm.unstack_pp_params")
        B, S = toks.shape
        x = self.embed(toks)
        if self.pos_embed is not None:
            p = pos[:, None] + jnp.arange(S)[None, :]
            x = x + self.pos_embed(p)
        x = x.astype(self.dtype)
        ks, vs = [], []
        for i, layer in enumerate(self.layers):
            x, pk, pv = layer.decode_paged(x, pools_k[i], pools_v[i],
                                           tables, pos, limit=limit,
                                           kernel=kernel, mesh=mesh,
                                           kv_sharded=kv_sharded)
            ks.append(pk)
            vs.append(pv)
        return self.ln_f(x), _stack_kv(ks), _stack_kv(vs)

    def prefill_chunk(self, toks, caches_k, caches_v, pos, lens):
        """One CHUNKED-PREFILL step against the slot-arena cache: run a
        ``[B, C]`` chunk of each row's prompt block-causally at its own
        position offset (``verify_hidden`` — the same offset attention
        the speculative verify and prefix admission use), write the
        chunk's K/V into the per-row cache, and return each row's
        last-real-position logits ``[B, V]`` (head applied to
        ``[B, 1, H]`` — never a ``[B, C, V]`` cube).

        toks: [B, C] chunk tokens (right-padded); caches as in
        :meth:`decode_step`; pos: [B] int32 — row b's chunk starts at
        cache position pos[b] (its fill frontier); lens: [B] int32 true
        chunk lengths.  On the FINAL chunk of a prompt the returned
        logits are exactly the monolithic prefill's last-position
        logits, so the caller picks the request's first token from
        them; mid-prompt the return value is dead.  Padding columns
        write dead K/V past the frontier that the next chunk (or
        decode) overwrites before anything attends them — the arena
        rows are private, so unlike the paged twin no write-limit is
        needed."""
        h, ck, cv = self.verify_hidden(toks, caches_k, caches_v, pos)
        last_h = jnp.take_along_axis(h, (lens - 1)[:, None, None],
                                     axis=1)
        return self._logits(last_h)[:, 0], ck, cv

    def prefill_chunk_paged(self, toks, pools_k, pools_v, tables, pos,
                            lens, kernel="gather", mesh=None,
                            kv_sharded=True):
        """The paged twin of :meth:`prefill_chunk`: the chunk's K/V
        scatter through per-row block tables into the shared pool, with
        writes LIMITED to ``pos + lens`` — padding columns write
        nothing, so a narrow table window (sliced to the fill frontier
        for bounded compile shapes) can never clamp a padding write
        into a live block.  Also the whole of paged admission: a
        prompt's unshared suffix IS its one big chunk."""
        h, pk, pv = self.verify_hidden_paged(toks, pools_k, pools_v,
                                             tables, pos,
                                             limit=pos + lens,
                                             kernel=kernel, mesh=mesh,
                                             kv_sharded=kv_sharded)
        last_h = jnp.take_along_axis(h, (lens - 1)[:, None, None],
                                     axis=1)
        return self._logits(last_h)[:, 0], pk, pv

    def prefill(self, tokens):
        """Causal forward that ALSO returns every layer's K/V: ``(logits
        [B, T, V], ks [n_layers, B, T, H, D], vs)``.  One MXU-friendly
        forward replaces T sequential decode steps when a new request
        joins the continuous-batching KV arena."""
        if self.pp_stages > 0:
            raise NotImplementedError(
                "prefill is not pipelined (same restriction as "
                "decode_step); serve a pp_stages=0 restore instead")
        B, T = tokens.shape
        if T > self.max_position:
            raise ValueError(
                f"sequence length {T} exceeds max_position "
                f"{self.max_position}")
        x = self.embed(tokens)
        if self.pos_embed is not None:
            x = x + self.pos_embed(jnp.arange(T)[None])
        x = _constrain_seq(x.astype(self.dtype), self.mesh)
        ks, vs = [], []
        for layer in self.layers:
            x, k, v = layer.forward_kv(x)
            ks.append(k)
            vs.append(v)
        return self._logits(self.ln_f(x)), jnp.stack(ks), jnp.stack(vs)


def top_p_filter(scaled, top_p):
    """Nucleus filter over the last axis: keep the smallest set of
    tokens whose (temperature-scaled) probability mass reaches
    ``top_p``; everything else goes to -inf.  The highest-probability
    token always survives (cumulative > p can exclude everything at
    tiny p otherwise).  top_p may be a scalar or broadcastable
    per-row [..., 1] array; values >= 1 or <= 0 disable the filter
    row-wise."""
    probs = jax.nn.softmax(scaled, axis=-1)
    sorted_probs = jnp.sort(probs, axis=-1)[..., ::-1]
    csum = jnp.cumsum(sorted_probs, axis=-1)
    # rank of the last kept token: first index where csum >= top_p
    keep_n = jnp.sum((csum < top_p).astype(jnp.int32), axis=-1,
                     keepdims=True) + 1
    kth = jnp.take_along_axis(sorted_probs,
                              jnp.minimum(keep_n - 1,
                                          scaled.shape[-1] - 1),
                              axis=-1)
    active = (top_p > 0.0) & (top_p < 1.0)
    return jnp.where(active & (probs < kth), -jnp.inf, scaled)


def _gen_state(model, prompt, max_new_tokens, prompt_len):
    """The prompt-length clamp + KV-cache allocation BOTH generate paths
    share — one definition, so cache sizing and the length-degradation
    rule can never drift between them (their token-identical guarantee
    depends on it)."""
    B, Pn = prompt.shape
    L = Pn + max_new_tokens
    plen = (jnp.full((B,), Pn, jnp.int32) if prompt_len is None
            else jnp.clip(jnp.asarray(prompt_len, jnp.int32), 1, Pn))
    H = model.kv_heads                  # GQA: cache stores KV heads only
    D = model.hidden_size // model.num_heads
    ck = jnp.zeros((model.num_layers, B, L, H, D),
                   jnp.dtype(model.dtype))
    return L, plen, ck, jnp.zeros_like(ck)


def _generate_forward_prefill(model, variables, prompt, max_new_tokens,
                              prompt_len, eos_id):
    """Greedy generation, forward-prefill variant (see generate()):
    one verify_step over the padded prompt + a max_new-step scan at
    per-row positions — the continuous engine's admission pattern
    applied to the batch path."""
    B, Pn = prompt.shape
    L, plen, ck, cv = _gen_state(model, prompt, max_new_tokens,
                                 prompt_len)
    # one block-causal forward writes K/V for every prompt position;
    # entries past a row's true length are dead (mask never reaches
    # them) and generation overwrites them in order.  Hidden-only: the
    # head applies to ONE gathered position per row, so [B, P, V]
    # logits are never materialised (that tensor is ~8 GB for a
    # llama-vocab model at P=2048).
    hidden, ck, cv = model.apply(
        variables, prompt, ck, cv, jnp.zeros((B,), jnp.int32),
        method=TransformerLM.verify_hidden)
    last_h = jnp.take_along_axis(
        hidden, (plen - 1)[:, None, None], axis=1)        # [B, 1, H]
    first_logits = model.apply(variables, last_h,
                               method=TransformerLM._logits)[:, 0]
    tok0 = jnp.argmax(first_logits, axis=-1).astype(jnp.int32)
    done0 = jnp.zeros((B,), bool)
    if eos_id is not None:
        done0 = tok0 == eos_id

    def step(carry, _):
        tok, pos, done, ck, cv = carry
        logits, ck, cv = model.apply(
            variables, tok, ck, cv, pos,
            method=TransformerLM.decode_step)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        if eos_id is not None:
            nxt = jnp.where(done, jnp.int32(eos_id), nxt)
            done = done | (nxt == eos_id)
        # last write lands at plen+max_new-2 <= L-2: no clamp needed
        return (nxt, pos + 1, done, ck, cv), nxt

    if max_new_tokens == 1:
        return tok0[:, None]
    (_, _, _, _, _), toks = lax.scan(
        step, (tok0, plen, done0, ck, cv), None,
        length=max_new_tokens - 1)
    return jnp.concatenate([tok0[:, None], toks.transpose(1, 0)], axis=1)


def lm_loss(logits, tokens):
    """Shifted next-token CE (mean over B x (T-1))."""
    import optax

    return jnp.mean(optax.softmax_cross_entropy_with_integer_labels(
        logits[:, :-1], tokens[:, 1:]))


def fused_lm_loss(per_sample_losses, _tokens):
    """Estimator loss for ``LMWithFusedLoss`` models: the model output
    already IS per-sample CE, so the loss is just its mean."""
    return jnp.mean(per_sample_losses)


class LMWithFusedLoss(nn.Module):
    """Training wrapper that computes the shifted next-token CE
    BLOCKWISE over the sequence, never materialising the [B, T, V]
    logits tensor.

    Why: the plain path writes f32 logits (B=8, T=2048, V=32000 →
    2.1 GB), reads them through softmax-CE, and materialises the same
    shape again as dlogits in backward — several full HBM passes over
    multi-GB tensors per step, and an O(T·V) residency that forbids
    long-context training (T=8192 would need 8.4 GB for logits alone).
    Here each ``t_block`` slice runs head-matmul + CE inside a
    ``lax.scan`` whose body is ``jax.checkpoint``-ed: backward
    recomputes the block's logits from the (tiny) hidden slice, so peak
    residency is O(B · t_block · V) regardless of T.  Cost: one extra
    head matmul per block in backward — the standard remat trade, paid
    where the tensor is bandwidth-monstrous and the matmul is cheap.

    Contract: ``__call__(tokens, train) -> [B]`` per-sample mean CE
    (use ``loss=fused_lm_loss`` with the Estimator; ``predict`` on this
    wrapper returns losses, not logits — serve/generate with the inner
    ``lm`` instead).  ``mean(wrapper(tokens)) == lm_loss(lm(tokens),
    tokens)`` exactly (tested)."""

    lm: TransformerLM
    t_block: int = 512

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        import optax

        if not self.lm.tied_head:
            raise ValueError(
                "LMWithFusedLoss computes blockwise logits from the TIED "
                "embedding table; an untied-head model (tied_head=False, "
                "e.g. a llama import) would silently train the wrong "
                "projection — use loss=lm_loss on the plain model")
        h = self.lm.hidden_states(tokens, train)
        emb = self.lm.embed.embedding.astype(jnp.float32)
        hs = h[:, :-1].astype(jnp.float32)
        ys = tokens[:, 1:]
        B, n, H = hs.shape
        tb = min(int(self.t_block), n)
        pad = (-n) % tb
        if pad:
            hs = jnp.pad(hs, ((0, 0), (0, pad), (0, 0)))
            ys = jnp.pad(ys, ((0, 0), (0, pad)))
        nb = (n + pad) // tb
        hb = hs.reshape(B, nb, tb, H).transpose(1, 0, 2, 3)
        yb = ys.reshape(B, nb, tb).transpose(1, 0, 2)
        mask = (jnp.arange(nb * tb) < n).astype(
            jnp.float32).reshape(nb, tb)

        def body(acc, blk):
            hx, yx, mx = blk
            logits = jnp.einsum("bth,vh->btv", hx, emb)
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, yx)
            return acc + jnp.sum(ce * mx[None, :], axis=1), None

        acc0 = jnp.zeros((B,), jnp.float32)
        total, _ = lax.scan(jax.checkpoint(body), acc0, (hb, yb, mask))
        return total / n


def generate(model: TransformerLM, variables, prompt,
             max_new_tokens: int, prompt_len=None, *,
             temperature: float = 0.0, top_k: int = 0,
             top_p: float = 0.0,
             rng=None, eos_id=None, prefill: str = "auto") -> jax.Array:
    """Generation with a threaded KV cache.

    prompt: [B, P] int32; ``prompt_len`` (optional [B] int32) gives each
    row's true prompt length for right-padded ragged batches (the serving
    path) — defaults to the full width P.  Returns [B, max_new_tokens]:
    row i's tokens generated after its own prompt end.

    ``prefill``: GREEDY decoding defaults to the FORWARD prefill — one
    block-causal ``verify_step`` over the whole (padded) prompt fills
    the cache in a single MXU-friendly forward, then a ``max_new``-step
    scan decodes at per-row positions: P + max_new sequential steps
    become max_new.  Token output is identical to the scan path
    (``decode_k`` is bitwise-equal to sequential decode; tested), and
    pad positions' K/V are dead entries the per-row mask never reaches.
    ``prefill="scan"`` forces the original single-scan path (prompt
    positions teacher-force; also what SAMPLED decoding always uses —
    its batch rng draws are tied to the lockstep scan and are kept
    exactly reproducible).

    Sampling: ``temperature=0`` (default) is greedy argmax;
    ``temperature>0`` samples from logits/temperature (pass ``rng``, a
    ``jax.random`` key — required then), optionally truncated to the
    ``top_k`` highest-probability tokens and/or the ``top_p`` nucleus
    (the smallest set of tokens whose probability mass reaches top_p;
    0 or >=1 disables).  Both filters compose (top_k first).

    ``eos_id``: once a row emits it (past its prompt), the rest of the
    row freezes at eos — the fixed-shape analog of stop-on-EOS (same
    contract as seq2seq.greedy_generate; output stays [B, max_new]).
    """
    B, Pn = prompt.shape
    L = Pn + max_new_tokens
    if L > model.max_position:
        raise ValueError(f"prompt+new = {L} exceeds max_position "
                         f"{model.max_position}")
    if prefill not in ("auto", "forward", "scan"):
        raise ValueError(f"prefill must be auto|forward|scan, got "
                         f"{prefill!r}")
    can_forward = (temperature <= 0.0 and max_new_tokens > 0
                   and model.pp_stages == 0)
    if prefill == "forward" and not can_forward:
        # an explicit request that silently measured the scan path
        # would invalidate whatever comparison the caller is making
        raise ValueError(
            "prefill='forward' needs greedy decoding (temperature=0), "
            "max_new_tokens > 0, and pp_stages=0; use 'auto' to fall "
            "back silently")
    if prefill != "scan" and can_forward:
        return _generate_forward_prefill(model, variables, prompt,
                                         max_new_tokens, prompt_len,
                                         eos_id)
    # prompt_len outside [1, P] has no defined meaning (the scan must
    # start from SOME real token, and can't teacher-force past the row):
    # _gen_state clamps both ends so bad rows degrade to defined
    # behavior (length-1 / full-width prompt) instead of off-by-one
    # garbage — values are traced, so raising is not an option here.
    # Callers that can reject bad lengths per-request (serving) do so
    # before this.
    _, plen, ck0, cv0 = _gen_state(model, prompt, max_new_tokens,
                                   prompt_len)

    if temperature > 0.0 and rng is None:
        raise ValueError("temperature > 0 needs a jax.random key via rng=")

    def pick(logits, t):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        scaled = logits.astype(jnp.float32) / temperature
        if top_k > 0:
            kth = lax.top_k(scaled, top_k)[0][:, -1][:, None]
            scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
        if top_p > 0.0:
            scaled = top_p_filter(scaled, jnp.float32(top_p))
        key = jax.random.fold_in(rng, t)
        return jax.random.categorical(key, scaled, axis=-1).astype(
            jnp.int32)

    def step(carry, t):
        tok, ck, cv, done = carry
        logits, ck, cv = model.apply(
            variables, tok, ck, cv, t, method=TransformerLM.decode_step)
        nxt = pick(logits, t)
        if eos_id is not None:
            # frozen-tail EOS: finished rows keep emitting eos (fixed
            # shapes; the caller trims)
            nxt = jnp.where(done, jnp.int32(eos_id), nxt)
            done = done | ((nxt == eos_id) & (t + 1 >= plen))
        # rows still inside their own prompt replay it
        nxt = jnp.where(t + 1 < plen, prompt[:, jnp.minimum(t + 1, Pn - 1)],
                        nxt)
        return (nxt, ck, cv, done), nxt

    done0 = jnp.zeros((B,), bool)
    (_, _, _, _), toks = lax.scan(
        step, (prompt[:, 0], ck0, cv0, done0), jnp.arange(L - 1))
    # toks[t] is the token at position t+1; row i's generated span is
    # positions [plen_i, plen_i + max_new) -> rows plen_i-1 .. of toks
    toks = toks.transpose(1, 0)                       # [B, L-1]
    idx = jnp.clip(plen[:, None] - 1 + jnp.arange(max_new_tokens)[None],
                   0, L - 2)
    return jnp.take_along_axis(toks, idx, axis=1)

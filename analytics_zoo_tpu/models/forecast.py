"""Forecasting networks backing the Zouwu toolkit.

Reference surface (SURVEY.md §2.5; ref: pyzoo/zoo/zouwu/model/forecast.py —
``LSTMForecaster``, ``MTNetForecaster``, ``TCNForecaster``,
``Seq2SeqForecaster`` wrap Keras/TF nets from pyzoo/zoo/automl/model/):
these are the bare networks; the user-facing wrappers live in
``analytics_zoo_tpu.zouwu``.

All take [B, T, F] windows and emit [B, horizon, target_dim]
(squeezed to [B, target_dim] when horizon == 1 at the wrapper level).

TPU-first: TCN is dilated 1-D convs (pure MXU, no recurrence — the
preferred TPU forecaster); LSTM/Seq2Seq compile to lax.scans; MTNet's
memory attention is batched matmuls.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from analytics_zoo_tpu.models.rnn import RNNStack, make_cell


class LSTMNet(nn.Module):
    """ref: automl/model/VanillaLSTM — LSTM stack → dense head."""

    output_dim: int = 1
    horizon: int = 1
    hidden_sizes: Sequence[int] = (32, 32)
    dropouts: Sequence[float] = (0.2, 0.2)
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = RNNStack(self.hidden_sizes, rnn_type="lstm",
                     dropouts=self.dropouts, dtype=self.dtype,
                     name="lstm")(x.astype(self.dtype), train)
        out = nn.Dense(self.horizon * self.output_dim, dtype=jnp.float32,
                       name="head")(h)
        return out.reshape((x.shape[0], self.horizon, self.output_dim))


class TCNBlock(nn.Module):
    channels: int
    kernel_size: int
    dilation: int
    dropout: float
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x, train: bool = False):
        # causal padding: pad left only so step t sees <= t.
        pad = (self.kernel_size - 1) * self.dilation
        y = x
        for i in range(2):
            y = jnp.pad(y, ((0, 0), (pad, 0), (0, 0)))
            y = nn.Conv(self.channels, (self.kernel_size,),
                        kernel_dilation=(self.dilation,), padding="VALID",
                        dtype=self.dtype, name=f"conv{i}")(y)
            y = nn.relu(y)
            y = nn.Dropout(self.dropout, deterministic=not train)(y)
        if x.shape[-1] != self.channels:
            x = nn.Conv(self.channels, (1,), dtype=self.dtype,
                        name="proj")(x)
        return nn.relu(x + y)


class TCN(nn.Module):
    """ref: zouwu TCNForecaster net — stacked dilated causal conv blocks."""

    output_dim: int = 1
    horizon: int = 1
    channels: Sequence[int] = (32, 32, 32)
    kernel_size: int = 3
    dropout: float = 0.1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        y = x.astype(self.dtype)
        for i, c in enumerate(self.channels):
            y = TCNBlock(c, self.kernel_size, 2 ** i, self.dropout,
                         self.dtype, name=f"block{i}")(y, train)
        out = nn.Dense(self.horizon * self.output_dim, dtype=jnp.float32,
                       name="head")(y[:, -1])
        return out.reshape((x.shape[0], self.horizon, self.output_dim))


class MTNet(nn.Module):
    """ref: zouwu MTNetForecaster (MTNet, Chang et al.) — long-term memory
    blocks encoded by CNN+GRU, attention against the short-term encoding,
    plus an autoregressive highway on the last ``ar_window`` steps.

    Input [B, (long_num+1)*series_length, F]: the first ``long_num``
    chunks are the memory; the last chunk is the current window.
    """

    output_dim: int = 1
    horizon: int = 1
    long_num: int = 4
    series_length: int = 8
    ar_window: int = 4
    cnn_filters: int = 32
    cnn_kernel: int = 3
    rnn_hidden: int = 32
    dropout: float = 0.1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        B, T, F = x.shape
        L, q = self.long_num, self.series_length
        if T != (L + 1) * q:
            raise ValueError(f"expected T={(L + 1) * q}, got {T}")
        xf = x.astype(self.dtype)
        mem = xf[:, : L * q].reshape(B, L, q, F)
        cur = xf[:, L * q:]                           # [B, q, F]

        conv = nn.Conv(self.cnn_filters, (self.cnn_kernel,),
                       dtype=self.dtype, name="encoder_conv")
        gru = make_cell("gru", self.rnn_hidden, dtype=self.dtype)

        def encode(seq, rnn_name):
            h = nn.relu(conv(seq))                    # [.., q, filters]
            h = nn.Dropout(self.dropout, deterministic=not train)(h)
            return nn.RNN(gru, name=rnn_name)(h)[:, -1]  # [.., hidden]

        m = encode(mem.reshape(B * L, q, F),
                   "encoder_rnn").reshape(B, L, self.rnn_hidden)
        u = encode(cur, "encoder_rnn_cur")            # [B, hidden]

        # attention over memory blocks.
        att = jnp.einsum("blh,bh->bl", m, u) / jnp.sqrt(
            jnp.asarray(self.rnn_hidden, self.dtype))
        w = nn.softmax(att.astype(jnp.float32), axis=-1).astype(self.dtype)
        ctx = jnp.einsum("bl,blh->bh", w, m)
        h = jnp.concatenate([u, ctx], axis=-1)
        nn_out = nn.Dense(self.horizon * self.output_dim,
                          dtype=jnp.float32, name="head")(h)
        nn_out = nn_out.reshape(B, self.horizon, self.output_dim)

        # AR highway over the raw last ar_window steps of the targets
        # (first output_dim features by convention).
        ar_in = x[:, -self.ar_window:, : self.output_dim]  # [B, w, D]
        ar = nn.Dense(self.horizon, dtype=jnp.float32, name="ar")(
            ar_in.transpose(0, 2, 1))                 # [B, D, horizon]
        return nn_out + ar.transpose(0, 2, 1)


class Seq2SeqTS(nn.Module):
    """ref: zouwu Seq2SeqForecaster net — LSTM encoder-decoder over
    continuous features; decoder is teacher-free (feeds its own output)."""

    output_dim: int = 1
    horizon: int = 1
    hidden_size: int = 64
    num_layers: int = 1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        B = x.shape[0]
        h = x.astype(self.dtype)
        carries = []
        for i in range(self.num_layers):
            cell = make_cell("lstm", self.hidden_size, dtype=self.dtype)
            carry, h = nn.RNN(cell, return_carry=True,
                              name=f"enc_{i}")(h)
            carries.append(carry)
        # decoder: unroll horizon steps feeding back the projection.
        dec_cells = [make_cell("lstm", self.hidden_size, dtype=self.dtype)
                     for _ in range(self.num_layers)]
        head = nn.Dense(self.output_dim, dtype=jnp.float32, name="head")
        prev = jnp.zeros((B, self.output_dim), self.dtype)
        outs = []
        for _ in range(self.horizon):  # static horizon: unrolled by trace
            z = prev
            new_carries = []
            for cell, c in zip(dec_cells, carries):
                c2, z = cell(c, z)
                new_carries.append(c2)
            carries = new_carries
            y = head(z.astype(jnp.float32))
            outs.append(y)
            prev = y.astype(self.dtype)
        return jnp.stack(outs, axis=1)  # [B, horizon, output_dim]

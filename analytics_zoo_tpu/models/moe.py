"""Mixture-of-Experts layers with expert parallelism over the ``ep`` axis.

The reference has no MoE and no expert parallelism (SURVEY.md §2.3 item 6:
the stack predates LLM-scale training).  Like ring attention (`parallel/
ring_attention.py`), this is a TPU-native extension: the mesh already
declares an ``ep`` axis (parallel/mesh.py CANONICAL_AXES) and this module
makes it real.

TPU-first design, not a port of any GPU MoE runtime:

- **Einsum dispatch, not gather/scatter.**  Tokens are routed through dense
  one-hot dispatch/combine tensors (the Switch-Transformer formulation), so
  the whole layer is three einsums + a softmax — static shapes, MXU-friendly,
  and XLA turns the token→expert regrouping into exactly the ``all_to_all``
  the sharding implies.  A scatter-based router would serialise on TPU.
- **Sharding-implied collectives.**  Expert weights are sharded
  ``P("ep", ...)`` (stacked expert dim over the ep axis) and expert
  activations are constrained to ``P("ep", ...)``; with tokens sharded over
  ``dp``, XLA inserts the dispatch/return all_to_alls over ICI.  No manual
  collective calls.
- **Capacity-bounded, f32 router.**  Router logits/softmax in float32
  (bf16 routing is unstable), experts compute in bfloat16 on the MXU.
  Per-expert capacity = ``ceil(top_k * tokens/experts * capacity_factor)``;
  overflow tokens fall through the residual connection (standard Switch
  behavior) rather than introducing data-dependent shapes.

The auxiliary load-balancing loss is sown into the ``"losses"`` collection;
``Estimator`` collects that collection in its train step, so MoE models
train through the ordinary ``fit()`` path with no special wiring.
"""

from __future__ import annotations

import math
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from analytics_zoo_tpu.parallel.partition import with_sharding_constraint

# Expert weights: stacked expert dim over ep, Megatron tp layout within each
# expert (up-projection sharded on the output dim, down on the input dim).
# Patterns match the MoE-unique PARAM names (not the instance path), so the
# rules apply under any module name, not just name="moe".  Compose with
# BERT_PARTITION_RULES for a full MoE transformer.
MOE_PARTITION_RULES = (
    (r"w_up$", P("ep", None, "tp")),
    (r"w_down$", P("ep", "tp", None)),
    (r"b_up$", P("ep", None)),
    (r"b_down$", P("ep", None)),
    (r"router/kernel", P()),
)


def load_balancing_loss(router_probs: jax.Array,
                        expert_index: jax.Array,
                        num_experts: int) -> jax.Array:
    """Switch-Transformer aux loss: ``E * sum_e f_e * p_e`` where ``f_e`` is
    the fraction of tokens whose top-1 choice is expert e and ``p_e`` the
    mean router probability for e.  Equals 1.0 under perfect balance."""
    f = jnp.mean(
        jax.nn.one_hot(expert_index, num_experts, dtype=jnp.float32), axis=0)
    p = jnp.mean(router_probs.astype(jnp.float32), axis=0)
    return num_experts * jnp.sum(f * p)


class MoEMLP(nn.Module):
    """Token-choice top-k MoE feed-forward block.

    Input ``[B, T, E]`` (or ``[N, E]``) → same shape.  Each token is routed
    to its ``top_k`` experts; each expert is a gelu MLP
    ``E -> intermediate_size -> E`` computed in ``dtype`` on the MXU.
    Tokens over an expert's capacity are dropped (their contribution is 0 —
    callers keep a residual connection so dropped tokens pass through).

    Capacity-bounded routing makes outputs weakly BATCH-COUPLED: tokens
    compete for expert slots, so a row's output can shift slightly with
    its batchmates (including padding rows at serving time).  This is
    inherent to capacity-style MoE, not a bug; raise ``capacity_factor``
    where batch-composition independence matters more than compute.

    Measured bound (tests/test_moe.py::
    test_moe_decode_capacity_agreement_bound — skew-trained MoE-LM,
    decode pools B=32 tokens/step vs the forward's B*T jointly): greedy
    decode-vs-forward max |logit delta| is 1.98 at capacity_factor=0.25
    and 1.19 at 1.0, yet greedy-token agreement stayed 100% (residuals
    absorb the drops); at capacity_factor=2.0 both paths serve every
    token and the logits are IDENTICAL (delta 0.0).  So CF=2 is the
    "exact decode parity" setting for skewed routing, not just a >=99%
    heuristic.
    """

    num_experts: int
    intermediate_size: int
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    dtype: jnp.dtype = jnp.bfloat16
    mesh: Optional[Mesh] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        orig_shape = x.shape
        E = orig_shape[-1]
        X, F, K = self.num_experts, self.intermediate_size, self.top_k
        if not 1 <= K <= X:
            raise ValueError(f"top_k={K} must be in [1, {X}]")
        xt = x.reshape(-1, E)                       # [N, E] tokens
        N = xt.shape[0]

        # --- routing (f32) -------------------------------------------------
        logits = nn.Dense(X, dtype=jnp.float32, param_dtype=jnp.float32,
                          use_bias=False, name="router")(
            xt.astype(jnp.float32))                 # [N, X]
        probs = jax.nn.softmax(logits, axis=-1)
        gate_vals, gate_idx = jax.lax.top_k(probs, K)       # [N, K]
        # renormalise the selected gates so contributions sum to 1
        gate_vals = gate_vals / jnp.maximum(
            jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)

        if train:
            aux = load_balancing_loss(probs, gate_idx[:, 0], X)
            self.sow("losses", "moe_aux_loss",
                     self.aux_loss_weight * aux,
                     reduce_fn=lambda a, b: a + b, init_fn=lambda: 0.0)

        # --- capacity-bounded one-hot dispatch ----------------------------
        capacity = max(K, math.ceil(K * N / X * self.capacity_factor))
        # [N, K, X] assignment one-hots, k-major priority order
        assign = jax.nn.one_hot(gate_idx, X, dtype=jnp.float32)
        # position of each (token, k) within its expert's queue: cumsum over
        # the flattened (k, token) order so k=0 choices get priority
        flat = assign.transpose(1, 0, 2).reshape(K * N, X)  # [K*N, X]
        pos_flat = jnp.cumsum(flat, axis=0) - flat          # arrivals before
        pos = pos_flat.reshape(K, N, X).transpose(1, 0, 2)  # [N, K, X]
        within = (pos < capacity) * assign                  # keep in-capacity
        pos_id = jnp.sum(pos * assign, axis=-1).astype(jnp.int32)   # [N, K]
        slot_oh = jax.nn.one_hot(pos_id, capacity, dtype=jnp.float32)
        # dispatch [N, X, C]: token n occupies slot pos_id[n,k] of expert
        dispatch = jnp.einsum("nkx,nkc->nxc", within, slot_oh)
        combine = jnp.einsum("nkx,nk,nkc->nxc", within, gate_vals, slot_oh)

        # --- expert computation (bf16, ep-sharded) ------------------------
        w_up = self.param("w_up", nn.initializers.lecun_normal(),
                          (X, E, F), jnp.float32)
        b_up = self.param("b_up", nn.initializers.zeros, (X, F), jnp.float32)
        w_down = self.param("w_down", nn.initializers.lecun_normal(),
                            (X, F, E), jnp.float32)
        b_down = self.param("b_down", nn.initializers.zeros, (X, E),
                            jnp.float32)

        ein = xt.astype(self.dtype)
        expert_in = jnp.einsum("nxc,ne->xce", dispatch.astype(self.dtype),
                               ein)                        # [X, C, E]
        expert_in = self._constrain(expert_in, tp_last=False)
        h = jnp.einsum("xce,xef->xcf", expert_in,
                       w_up.astype(self.dtype)) + \
            b_up.astype(self.dtype)[:, None, :]
        h = nn.gelu(h)
        h = self._constrain(h, tp_last=True)
        out_e = jnp.einsum("xcf,xfe->xce", h,
                           w_down.astype(self.dtype)) + \
            b_down.astype(self.dtype)[:, None, :]
        out_e = self._constrain(out_e, tp_last=False)
        y = jnp.einsum("nxc,xce->ne", combine.astype(self.dtype), out_e)
        return y.reshape(orig_shape).astype(x.dtype)

    def _constrain(self, t, *, tp_last: bool):
        """Expert-major activations: stacked expert dim over ep.  Only the
        intermediate ``h`` ([X, C, F]) carries tp on its last dim — its F
        dim matches w_up's tp-sharded output / w_down's tp-sharded input, so
        the up-projection shards and the down-projection reduce-scatters
        over tp.  ``expert_in``/``out_e`` end in the model dim E, which the
        weights keep replicated; constraining E onto tp would force a
        reshard collective around every einsum for no compute split."""
        if self.mesh is None or "ep" not in self.mesh.axis_names:
            return t
        tp = "tp" if (tp_last and "tp" in self.mesh.axis_names) else None
        return with_sharding_constraint(t, P("ep", None, tp))


class MoETransformerLayer(nn.Module):
    """Post-LN encoder block with an MoE FFN (attention as in
    models/transformer.py).  Residual connections mean capacity-dropped
    tokens degrade gracefully to identity."""

    hidden_size: int
    num_heads: int
    intermediate_size: int
    num_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25
    dropout: float = 0.1
    dtype: jnp.dtype = jnp.bfloat16
    mesh: Optional[Mesh] = None

    @nn.compact
    def __call__(self, x, kv_mask=None, train: bool = False):
        from analytics_zoo_tpu.models.transformer import (
            MultiHeadAttention, _constrain_seq)

        H = self.num_heads
        a = MultiHeadAttention(H, self.hidden_size // H, dtype=self.dtype,
                               mesh=self.mesh, name="attention")(
            x, kv_mask, train)
        a = nn.Dropout(self.dropout, deterministic=not train)(a)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_attn")(x + a)
        x = _constrain_seq(x, self.mesh)
        h = MoEMLP(self.num_experts, self.intermediate_size,
                   top_k=self.top_k, capacity_factor=self.capacity_factor,
                   dtype=self.dtype, mesh=self.mesh, name="moe")(x, train)
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_ffn")(x + h)
        return _constrain_seq(x, self.mesh)


class MoETransformerClassifier(nn.Module):
    """Small MoE encoder classifier — the e2e surface for tests/examples
    (embeds token ids, N MoE blocks, mean-pool, linear head)."""

    vocab_size: int
    num_classes: int
    hidden_size: int = 64
    num_layers: int = 2
    num_heads: int = 4
    intermediate_size: int = 128
    num_experts: int = 4
    top_k: int = 2
    dtype: jnp.dtype = jnp.bfloat16
    mesh: Optional[Mesh] = None

    @nn.compact
    def __call__(self, token_ids, train: bool = False):
        x = nn.Embed(self.vocab_size, self.hidden_size,
                     name="embed")(token_ids).astype(self.dtype)
        for i in range(self.num_layers):
            x = MoETransformerLayer(
                self.hidden_size, self.num_heads, self.intermediate_size,
                self.num_experts, top_k=self.top_k, dtype=self.dtype,
                mesh=self.mesh, name=f"layer_{i}")(x, None, train)
        pooled = jnp.mean(x.astype(jnp.float32), axis=1)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="classifier")(pooled)


# Classifier rules: MoE expert layout + Megatron attention TP.
MOE_CLASSIFIER_PARTITION_RULES = MOE_PARTITION_RULES + (
    (r"(query|key|value)/kernel", P(None, "tp")),
    (r"attn_out/kernel", P("tp", None)),
    (r".*", P()),
)

"""Speculative decoding: a small draft model proposes k tokens, the
target model verifies all of them in ONE cached forward.

Beyond-parity extension (the reference has no generative serving at
all).  Why it fits the TPU: sequential decode is latency-bound — each
token is a tiny matmul plus a host round-trip — while the verify pass
is a [B, k+1]-token forward that actually feeds the MXU, and on the
tunneled single-chip serving path it also cuts host round-trips per
emitted token by the acceptance rate.

Greedy contract: the emitted sequence is EXACTLY what greedy decoding
of the target model alone would produce (the classic speculative
guarantee specialised to argmax — a draft token is accepted iff it
equals the target's argmax given the accepted prefix, so every emitted
token is the target's argmax; tested against models.lm.generate).

Mechanics per round, per row (pointer ``ptr`` = number of durable cache
entries, starting at prompt_len - 1):

  draft   : k greedy cached steps from ``last`` -> proposals d_0..d_{k-1}
  verify  : target ``verify_step`` on [last, d_0..d_{k-1}] at positions
            ptr..ptr+k (k+1 logits in one forward)
  accept  : a = longest prefix with argmax_j == d_j; emit argmaxes
            t_0..t_a (a accepted tokens + 1 free target token — the
            correction when a < k, the bonus when a == k)
  advance : both pointers += a+1.  Cache entries written past the new
            pointer are DEAD: the attention mask never reaches them and
            the next round overwrites them — rejection costs no
            bookkeeping (models/lm.py decode_k).

Rows advance at different rates (per-row pointers, as in the continuous
engine); finished rows re-verify their frozen ``last`` harmlessly and
emit nothing.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from analytics_zoo_tpu.models.lm import TransformerLM


def accept_proposals(logits, d, last, done, *, k, eos_id,
                     budget=None):
    """The speculative acceptance rule — ONE definition shared by
    batch ``speculative_generate`` and the continuous engine's
    spec-round programs (arena AND paged), so the greedy contract can
    never drift between surfaces.

    ``logits`` [B, k+1, V] are the target's verify outputs for inputs
    [last, d_0..d_{k-1}]; ``d`` [B, k] the draft proposals; ``last``
    [B] each row's previous emitted token; ``done`` [B] frozen rows.
    ``budget`` optionally clips emission to each row's remaining token
    allowance (batch generate; the engine drops surplus host-side).

    Returns ``(t, n_emit, new_last, done)``: ``t`` [B, k+1] the target
    argmaxes with everything after a row's first in-window eos frozen
    AT eos (the emitted prefix of a row therefore never needs host
    patching), ``n_emit`` [B] in 0..k+1 (0 only for done rows or an
    exhausted budget), ``new_last`` the last emitted token (the old
    ``last`` where nothing emitted)."""
    t = jnp.argmax(logits, axis=-1).astype(jnp.int32)   # [B, k+1]
    match = (t[:, :k] == d)                             # [B, k]
    a = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)
    n_emit = a + 1                                      # t_0..t_a
    if budget is not None:
        n_emit = jnp.minimum(n_emit, budget)
    if eos_id is not None:
        js = jnp.arange(k + 1)[None, :]
        is_eos = (t == eos_id) & (js < n_emit[:, None])
        first_eos = jnp.where(is_eos.any(axis=1),
                              jnp.argmax(is_eos, axis=1), k + 1)
        n_emit = jnp.minimum(n_emit, first_eos + 1)
        # frozen tail on-device: everything after a row's first eos
        # reads as eos (emitted entries sit at js <= first_eos, so
        # freezing changes no emitted value)
        t = jnp.where(js > first_eos[:, None], jnp.int32(eos_id), t)
    n_emit = jnp.where(done, 0, n_emit)
    new_last = jnp.where(
        n_emit > 0,
        jnp.take_along_axis(t, jnp.maximum(n_emit - 1, 0)[:, None],
                            axis=1)[:, 0],
        last)
    if eos_id is not None:
        done = done | ((n_emit > 0) & (new_last == eos_id))
    return t, n_emit, new_last, done


def _prefill_caches(model, variables, prompt, L):
    """One batched causal forward (TransformerLM.prefill) padded into an
    L-long cache — NOT Pn sequential decode steps; the prompt is the
    one place generation gets a full MXU-friendly forward for free.
    Ragged rows' tail entries (past their true length) are dead until
    the advancing pointer overwrites them."""
    _, ks, vs = model.apply(variables, prompt,
                            method=TransformerLM.prefill)
    pad = L - ks.shape[2]
    ks = jnp.pad(ks, ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0)))
    vs = jnp.pad(vs, ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0)))
    return (ks.astype(jnp.dtype(model.dtype)),
            vs.astype(jnp.dtype(model.dtype)))


@functools.partial(jax.jit, static_argnames=(
    "model", "draft_model", "k", "max_new", "eos_id"))
def _spec_round(model, variables, draft_model, draft_variables,
                carry, *, k, max_new, eos_id):
    (last, tck, tcv, ptr, dck, dcv, dptr,
     out, gen_len, done) = carry
    B = last.shape[0]

    # ---- draft: k proposals via k+1 greedy cached steps ---------------
    # k+1 feeds (last, d_0..d_{k-1}) so the draft writes the SAME k+1
    # cache entries the target's verify does: after a full-acceptance
    # round the durable range includes d_{k-1}'s KV, which only the
    # (k+1)-th feed computes (the extra feed's OUTPUT is discarded).
    def dstep(c, _):
        tok, dck, dcv, p = c
        logits, dck, dcv = draft_model.apply(
            draft_variables, tok, dck, dcv, p,
            method=TransformerLM.decode_step)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (nxt, dck, dcv, p + 1), nxt

    (_, dck, dcv, _), d = lax.scan(
        dstep, (last, dck, dcv, dptr), None, length=k + 1)
    d = d.T[:, :k]                                      # [B, k]

    # ---- verify: one (k+1)-token cached forward of the target ---------
    inputs = jnp.concatenate([last[:, None], d], axis=1)  # [B, k+1]
    logits, tck, tcv = model.apply(
        variables, inputs, tck, tcv, ptr,
        method=TransformerLM.verify_step)

    # ---- accept the longest matching prefix (shared rule) -------------
    t, n_emit, new_last, done = accept_proposals(
        logits, d, last, done, k=k, eos_id=eos_id,
        budget=max_new - gen_len)

    # ---- scatter emitted tokens into the output buffer ----------------
    js = jnp.arange(k + 1)[None, :]
    dest = gen_len[:, None] + js                        # [B, k+1]
    live = js < n_emit[:, None]
    hit = (jnp.arange(max_new)[None, None, :]
           == dest[:, :, None]) & live[:, :, None]     # [B, k+1, max_new]
    out = jnp.where(hit.any(axis=1), jnp.einsum(
        "bjm,bj->bm", hit.astype(jnp.int32), t), out)

    # ---- advance ------------------------------------------------------
    # next round's first input is the last EMITTED token (computed by
    # accept_proposals); its KV is not durable yet (pointer stops just
    # before it), mirroring decode_step
    ptr = ptr + n_emit
    dptr = dptr + n_emit
    gen_len = gen_len + n_emit
    done = done | (gen_len >= max_new)
    return ((new_last, tck, tcv, ptr, dck, dcv, dptr,
             out, gen_len, done),
            n_emit)


def speculative_generate(model: TransformerLM, variables,
                         draft_model: TransformerLM, draft_variables,
                         prompt, max_new_tokens: int, *, k: int = 4,
                         eos_id: Optional[int] = None,
                         prompt_len=None):
    """Greedy generation of ``max_new_tokens`` with draft-model
    speculation.  Returns (tokens [B, max_new_tokens] int32, stats dict)
    where stats reports rounds and mean accepted-per-round — the
    speedup diagnostic.  Output rows equal models.lm.generate(greedy)
    on the target model exactly, including the eos contract: after a
    row's first ``eos_id`` the row FREEZES at eos (fixed-shape
    stop-on-EOS, same as generate()).
    """
    if model.vocab_size != draft_model.vocab_size:
        raise ValueError(
            f"draft vocab {draft_model.vocab_size} != target vocab "
            f"{model.vocab_size}: speculative tokens must share one id "
            f"space")
    prompt = jnp.asarray(prompt, jnp.int32)
    B, Pn = prompt.shape
    L = Pn + max_new_tokens + k + 1
    for m, which in ((model, "target"), (draft_model, "draft")):
        if L > m.max_position:
            raise ValueError(
                f"prompt+new+k = {L} exceeds {which} max_position "
                f"{m.max_position}")
    plen = (jnp.full((B,), Pn, jnp.int32) if prompt_len is None
            else jnp.clip(jnp.asarray(prompt_len, jnp.int32), 1, Pn))

    tck, tcv = _prefill_caches(model, variables, prompt, L)
    dck, dcv = _prefill_caches(draft_model, draft_variables, prompt, L)
    last = jnp.take_along_axis(prompt, (plen - 1)[:, None], axis=1)[:, 0]
    carry = (last, tck, tcv, plen - 1, dck, dcv, plen - 1,
             jnp.zeros((B, max_new_tokens), jnp.int32),
             jnp.zeros((B,), jnp.int32), jnp.zeros((B,), bool))

    rounds = 0
    emitted = 0
    # worst case every round emits 1 token (all rejections)
    for _ in range(max_new_tokens):
        carry, n_emit = _spec_round(
            model, variables, draft_model, draft_variables, carry,
            k=k, max_new=max_new_tokens, eos_id=eos_id)
        rounds += 1
        # one fetch per round for BOTH loop controls (emit count and
        # the all-done flag) instead of two separate blocking reads
        n_round, all_done = jax.device_get((jnp.sum(n_emit),
                                            carry[-1].all()))
        emitted += int(n_round)
        if bool(all_done):
            break
    out = carry[7]
    if eos_id is not None:
        # generate() parity: after a row's first eos the row FREEZES at
        # eos (fixed-shape stop-on-EOS, models/lm.py generate docstring)
        o = np.asarray(out)
        m = np.cumsum(o == eos_id, axis=1)
        o = np.where((m - (o == eos_id)) > 0, eos_id, o)
        out = jnp.asarray(o, jnp.int32)
    stats = {"rounds": rounds,
             "emitted_tokens": emitted,
             "batch": B,
             # per-row totals let callers exclude phantom rows (serving
             # pads batches to buckets; those rows aren't traffic)
             "per_row_emitted": np.asarray(carry[8]),
             "mean_accepted_per_round":
                 emitted / max(1, rounds * B)}
    return out, stats

"""Transformer / BERT family.

Reference surface (SURVEY.md §2.4, ref: pipeline/api/keras/layers/
self_attention.py — Keras-API ``TransformerLayer`` and ``BERT`` layers, used
by tfpark NLP estimators): full-attention encoder blocks with word/position/
token-type embeddings and a pooler.

TPU-first re-design, not a translation:
- attention runs through ``ring_self_attention`` — sequence-sharded (``sp``)
  exact attention with ICI ppermute rotation — whenever the active mesh has
  an sp axis, full attention otherwise;
- all matmuls bfloat16 on the MXU, LayerNorm/softmax accumulate f32;
- weights carry tensor-parallel partition rules (qkv/up projections sharded
  on the output dim, out/down on the input dim — Megatron layout — so XLA
  inserts exactly one all-reduce per block per direction);
- activations are sharding-constrained to (dp, sp) so long sequences scale
  across the mesh (no reference counterpart; SURVEY §2.3 item 6).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from analytics_zoo_tpu.parallel.partition import with_sharding_constraint
from analytics_zoo_tpu.parallel.ring_attention import (
    full_attention, ring_self_attention)

# Megatron-style TP layout + sp activation sharding.
BERT_PARTITION_RULES = (
    (r"word_embeddings/embedding", P("tp", None)),
    (r"(query|key|value)/kernel", P(None, "tp")),
    (r"attn_out/kernel", P("tp", None)),
    (r"ffn_up/kernel", P(None, "tp")),
    (r"ffn_down/kernel", P("tp", None)),
    (r".*", P()),
)


# MoE-BERT (moe_experts > 0): expert weights over ep(+tp), attention and
# dense layers Megatron-tp as above.  moe.py imports transformer only
# inside a method, so this top-level import cannot cycle.
from analytics_zoo_tpu.models.moe import MOE_PARTITION_RULES as _MOE_RULES

BERT_MOE_PARTITION_RULES = _MOE_RULES + BERT_PARTITION_RULES


def flash_ok(use_flash: Optional[bool], seq_len: int) -> bool:
    """Fused-kernel dispatch policy — ONE home for the measured numbers.

    use_flash=None means auto; the kill-switch env var covers Mosaic
    lowering failures on future TPU generations without code changes.
    Measured on v5e (BERT-base fine-tune through fit, bf16): XLA wins at
    seq 128 (+44%) and 256 (+15%); the Pallas kernel wins from seq 512
    (+20%), where attention turns HBM-bound and fusion pays.  At seq 2048
    (111M-param causal LM) the kernel is +94% and survives batch sizes
    whose full-attention logits OOM."""
    if use_flash is not None:
        return use_flash
    if os.environ.get("ZOO_DISABLE_FLASH", "").lower() not in (
            "", "0", "false"):
        return False
    return jax.default_backend() == "tpu" and seq_len >= 512


def attention_dispatch(q, k, v, kv_mask, *, causal: bool,
                       mesh: Optional[Mesh],
                       use_flash: Optional[bool],
                       sp_strategy: str = "ring") -> jax.Array:
    """The three-way attention dispatch every attention layer shares:
    sequence-parallel attention (ring ppermute or ulysses all_to_all,
    ``sp_strategy``) when the mesh shards the sequence, the Pallas flash
    kernel where measured to win, XLA full attention otherwise."""
    if mesh is not None and "sp" in mesh.axis_names and \
            mesh.shape["sp"] > 1:
        return ring_self_attention(q, k, v, mesh, kv_mask, causal=causal,
                                   strategy=sp_strategy)
    if flash_ok(use_flash, q.shape[1]):
        from analytics_zoo_tpu.ops import (
            flash_attention, sharded_flash_attention)

        if mesh is not None and mesh.size > 1:
            return sharded_flash_attention(q, k, v, mesh, kv_mask,
                                           causal=causal)
        return flash_attention(q, k, v, kv_mask, causal=causal)
    return full_attention(q, k, v, kv_mask, causal=causal)


def _constrain_seq(x, mesh: Optional[Mesh]):
    """hidden states: [B, T, E] -> shard B over dp(+fsdp), T over sp."""
    if mesh is None:
        return x
    from analytics_zoo_tpu.parallel.mesh import batch_axes
    batch = batch_axes(mesh) or None
    seq = "sp" if "sp" in mesh.axis_names else None
    return with_sharding_constraint(x, P(batch, seq, None))


class MultiHeadAttention(nn.Module):
    """Self-attention; ring attention when the mesh has sp > 1, the fused
    Pallas flash kernel (ops.flash_attention) on single-sequence-shard TPU
    runs, XLA full attention otherwise.  use_flash=None means auto."""

    num_heads: int
    head_dim: int
    dtype: jnp.dtype = jnp.bfloat16
    mesh: Optional[Mesh] = None
    use_flash: Optional[bool] = None
    sp_strategy: str = "ring"

    @nn.compact
    def __call__(self, x, kv_mask=None, train: bool = False):
        B, T, E = x.shape
        H, D = self.num_heads, self.head_dim
        dense = lambda name: nn.DenseGeneral(
            (H, D), dtype=self.dtype, name=name)
        q, k, v = dense("query")(x), dense("key")(x), dense("value")(x)
        o = attention_dispatch(q, k, v, kv_mask, causal=False,
                               mesh=self.mesh, use_flash=self.use_flash,
                               sp_strategy=self.sp_strategy)
        return nn.DenseGeneral(E, axis=(-2, -1), dtype=self.dtype,
                               name="attn_out")(o)


class TransformerLayer(nn.Module):
    """ref-parity: Keras-API TransformerLayer (post-LN encoder block).

    ``num_experts > 0`` swaps the dense FFN for an expert-parallel MoE
    block (models/moe.py) — a TPU-native extension with no reference
    counterpart; the residual connection carries capacity-dropped tokens."""

    hidden_size: int
    num_heads: int
    intermediate_size: int
    dropout: float = 0.1
    dtype: jnp.dtype = jnp.bfloat16
    mesh: Optional[Mesh] = None
    use_flash: Optional[bool] = None
    num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    sp_strategy: str = "ring"

    @nn.compact
    def __call__(self, x, kv_mask=None, train: bool = False):
        H = self.num_heads
        D = self.hidden_size // H
        a = MultiHeadAttention(H, D, dtype=self.dtype, mesh=self.mesh,
                               use_flash=self.use_flash,
                               sp_strategy=self.sp_strategy,
                               name="attention")(x, kv_mask, train)
        a = nn.Dropout(self.dropout, deterministic=not train)(a)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_attn")(x + a)
        x = _constrain_seq(x, self.mesh)
        if self.num_experts > 0:
            from analytics_zoo_tpu.models.moe import MoEMLP

            h = MoEMLP(self.num_experts, self.intermediate_size,
                       top_k=self.moe_top_k,
                       capacity_factor=self.moe_capacity_factor,
                       dtype=self.dtype, mesh=self.mesh,
                       name="moe")(x, train)
        else:
            h = nn.Dense(self.intermediate_size, dtype=self.dtype,
                         name="ffn_up")(x)
            h = nn.gelu(h)
            h = nn.Dense(self.hidden_size, dtype=self.dtype,
                         name="ffn_down")(h)
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_ffn")(x + h)
        return _constrain_seq(x, self.mesh)


class BERT(nn.Module):
    """ref-parity: Keras-API BERT layer — returns (sequence, pooled)."""

    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 512
    type_vocab: int = 2
    dropout: float = 0.1
    dtype: jnp.dtype = jnp.bfloat16
    mesh: Optional[Mesh] = None
    remat: bool = False
    use_flash: Optional[bool] = None
    # MoE-BERT: every `moe_every`-th layer gets an expert-parallel MoE FFN
    # (interleaved dense/MoE, the standard sparse-transformer layout)
    moe_experts: int = 0
    moe_every: int = 2
    moe_top_k: int = 2
    sp_strategy: str = "ring"

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 train: bool = False) -> Tuple[jax.Array, jax.Array]:
        B, T = input_ids.shape
        word = nn.Embed(self.vocab_size, self.hidden_size,
                        name="word_embeddings")(input_ids)
        pos = nn.Embed(self.max_position, self.hidden_size,
                       name="position_embeddings")(jnp.arange(T)[None])
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        typ = nn.Embed(self.type_vocab, self.hidden_size,
                       name="token_type_embeddings")(token_type_ids)
        x = nn.LayerNorm(dtype=jnp.float32, name="emb_ln")(word + pos + typ)
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        x = _constrain_seq(x.astype(self.dtype), self.mesh)
        kv_mask = None if attention_mask is None else attention_mask > 0
        layer_cls = TransformerLayer
        if self.remat:
            layer_cls = nn.remat(TransformerLayer, static_argnums=(3,))
        for i in range(self.num_layers):
            moe = self.moe_experts if (
                self.moe_experts > 0 and
                (i + 1) % max(1, self.moe_every) == 0) else 0
            x = layer_cls(self.hidden_size, self.num_heads,
                          self.intermediate_size, self.dropout,
                          dtype=self.dtype, mesh=self.mesh,
                          use_flash=self.use_flash,
                          num_experts=moe, moe_top_k=self.moe_top_k,
                          sp_strategy=self.sp_strategy,
                          name=f"layer_{i}")(x, kv_mask, train)
        pooled = nn.tanh(nn.Dense(self.hidden_size, dtype=jnp.float32,
                                  name="pooler")(x[:, 0].astype(jnp.float32)))
        return x.astype(jnp.float32), pooled


class BERTForSequenceClassification(nn.Module):
    num_classes: int = 2
    bert: Optional[BERT] = None

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 train: bool = False):
        bert = self.bert if self.bert is not None else BERT(name="bert")
        _, pooled = bert(input_ids, token_type_ids, attention_mask, train)
        return nn.Dense(self.num_classes, name="classifier")(pooled)


class BERTForQuestionAnswering(nn.Module):
    """SQuAD head (config #3): start/end logits over sequence positions."""

    bert: Optional[BERT] = None

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 train: bool = False):
        bert = self.bert if self.bert is not None else BERT(name="bert")
        seq, _ = bert(input_ids, token_type_ids, attention_mask, train)
        logits = nn.Dense(2, name="qa_outputs")(seq)  # [B, T, 2]
        return logits  # start = [..., 0], end = [..., 1]


def qa_loss(logits, targets):
    """SQuAD loss: mean CE over start+end positions.
    targets: (start_positions, end_positions) int arrays [B]."""
    import optax

    start, end = targets
    ls = optax.softmax_cross_entropy_with_integer_labels(
        logits[..., 0], start.astype(jnp.int32))
    le = optax.softmax_cross_entropy_with_integer_labels(
        logits[..., 1], end.astype(jnp.int32))
    return jnp.mean(ls + le) / 2.0

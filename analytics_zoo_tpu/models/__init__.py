from analytics_zoo_tpu.models.ncf import NeuralCF, NCF_PARTITION_RULES
from analytics_zoo_tpu.models.transformer import (
    BERT, BERTForSequenceClassification, BERTForQuestionAnswering,
    TransformerLayer, MultiHeadAttention, BERT_PARTITION_RULES, qa_loss)

__all__ = [
    "NeuralCF", "NCF_PARTITION_RULES",
    "BERT", "BERTForSequenceClassification", "BERTForQuestionAnswering",
    "TransformerLayer", "MultiHeadAttention", "BERT_PARTITION_RULES",
    "qa_loss",
]

from analytics_zoo_tpu.models.ncf import NeuralCF, NCF_PARTITION_RULES

__all__ = ["NeuralCF", "NCF_PARTITION_RULES"]

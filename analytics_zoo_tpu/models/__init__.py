"""Built-in model zoo (SURVEY.md §2.5 — ref: pyzoo/zoo/models/ + Scala
models/): recommendation, text, anomaly detection, seq2seq, image
classification, transformer/BERT, plus the forecasting nets Zouwu wraps."""

from analytics_zoo_tpu.models.ncf import NeuralCF, NCF_PARTITION_RULES
from analytics_zoo_tpu.models.transformer import (
    BERT, BERTForSequenceClassification, BERTForQuestionAnswering,
    TransformerLayer, MultiHeadAttention, BERT_PARTITION_RULES,
    BERT_MOE_PARTITION_RULES, qa_loss)
from analytics_zoo_tpu.models.recommendation import (
    ColumnFeatureInfo, WideAndDeep, SessionRecommender, DIEN,
    WND_PARTITION_RULES)
from analytics_zoo_tpu.models.text import TextClassifier, KNRM
from analytics_zoo_tpu.models.anomaly import (
    AnomalyDetector, unroll, detect_anomalies)
from analytics_zoo_tpu.models.seq2seq import Seq2Seq, greedy_generate
from analytics_zoo_tpu.models.image import (
    ResNet, SimpleCNN, ImageClassifier, resnet18, resnet34, resnet50)
from analytics_zoo_tpu.models.detection import (
    SSD, SSDDetector, ssd_anchors, multibox_loss, decode_detections)
from analytics_zoo_tpu.models.forecast import (
    LSTMNet, TCN, MTNet, Seq2SeqTS)
from analytics_zoo_tpu.models.rnn import RNNStack
from analytics_zoo_tpu.models.lm import (
    TransformerLM, DecoderLayer, LM_PARTITION_RULES, LM_PP_PARTITION_RULES,
    LM_PP_INTERLEAVED_PARTITION_RULES,
    LM_MOE_PARTITION_RULES, lm_loss, fused_lm_loss, LMWithFusedLoss,
    generate, beam_search, unstack_pp_params)
from analytics_zoo_tpu.models.speculative import speculative_generate
from analytics_zoo_tpu.models.distill import (
    DistillLM, distill_draft, distill_loss)
from analytics_zoo_tpu.models.moe import (
    MoEMLP, MoETransformerLayer, MoETransformerClassifier,
    MOE_PARTITION_RULES, MOE_CLASSIFIER_PARTITION_RULES,
    load_balancing_loss)

__all__ = [
    "NeuralCF", "NCF_PARTITION_RULES",
    "BERT", "BERTForSequenceClassification", "BERTForQuestionAnswering",
    "TransformerLayer", "MultiHeadAttention", "BERT_PARTITION_RULES",
    "BERT_MOE_PARTITION_RULES",
    "qa_loss",
    "ColumnFeatureInfo", "WideAndDeep", "SessionRecommender", "DIEN",
    "WND_PARTITION_RULES",
    "TextClassifier", "KNRM",
    "AnomalyDetector", "unroll", "detect_anomalies",
    "Seq2Seq", "greedy_generate",
    "ResNet", "SimpleCNN", "ImageClassifier", "resnet18", "resnet34", "resnet50",
    "SSD", "SSDDetector", "ssd_anchors", "multibox_loss",
    "decode_detections",
    "LSTMNet", "TCN", "MTNet", "Seq2SeqTS",
    "RNNStack",
    "TransformerLM", "DecoderLayer", "LM_PARTITION_RULES",
    "LM_PP_PARTITION_RULES", "LM_PP_INTERLEAVED_PARTITION_RULES",
    "LM_MOE_PARTITION_RULES", "lm_loss",
    "generate", "beam_search", "speculative_generate",
    "DistillLM", "distill_draft", "distill_loss",
    "unstack_pp_params", "fused_lm_loss", "LMWithFusedLoss",
    "MoEMLP", "MoETransformerLayer", "MoETransformerClassifier",
    "MOE_PARTITION_RULES", "MOE_CLASSIFIER_PARTITION_RULES",
    "load_balancing_loss",
]

"""Seq2Seq — RNN encoder/decoder (chatbot / translation family).

Reference surface (SURVEY.md §2.5; ref: pyzoo/zoo/models/seq2seq/ + Scala
models/seq2seq/Seq2seq.scala): ``Seq2seq(encoder, decoder, input_shape,
output_shape, bridge, generator)`` — stacked RNN encoder, bridge mapping
final encoder states into decoder initial states, teacher-forced training
and step-wise ``infer``.

TPU-first: training is two lax.scans (encoder + teacher-forced decoder) —
one fused XLA program, no per-step Python. Greedy generation wraps the
single-step decoder in an outer ``lax.scan`` over ``model.apply`` (pure),
so inference is also one compiled program with static max_len.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from analytics_zoo_tpu.models.rnn import make_cell


class Seq2Seq(nn.Module):
    """ref-parity ctor (re-shaped): rnn_type, hidden_sizes, vocab_size,
    embed_dim, bridge (copy|dense), tied decoder vocab."""

    vocab_size: int
    embed_dim: int = 128
    hidden_sizes: Sequence[int] = (128,)
    rnn_type: str = "gru"
    bridge: str = "copy"
    dtype: jnp.dtype = jnp.bfloat16

    def setup(self):
        self.embedding = nn.Embed(self.vocab_size, self.embed_dim,
                                  name="embedding")
        # The same nn.RNN modules serve full-sequence (training) and
        # length-1 (greedy step) calls, so params are shared by scope.
        self.enc_rnns = [
            nn.RNN(make_cell(self.rnn_type, h, dtype=self.dtype),
                   return_carry=True, name=f"enc_rnn_{i}")
            for i, h in enumerate(self.hidden_sizes)]
        self.dec_rnns = [
            nn.RNN(make_cell(self.rnn_type, h, dtype=self.dtype),
                   return_carry=True, name=f"dec_rnn_{i}")
            for i, h in enumerate(self.hidden_sizes)]
        if self.bridge == "dense":
            self.bridges = [nn.Dense(h, name=f"bridge_{i}")
                            for i, h in enumerate(self.hidden_sizes)]
        self.head = nn.Dense(self.vocab_size, dtype=jnp.float32,
                             name="generator")

    # ---- pieces ------------------------------------------------------

    def _bridge(self, carries):
        if self.bridge == "copy":
            return carries
        out = []
        for i, c in enumerate(carries):
            out.append(jax.tree.map(lambda t: self.bridges[i](t), c))
        return out

    def encode(self, enc_tokens):
        """Returns decoder initial carries (post-bridge)."""
        x = self.embedding(enc_tokens).astype(self.dtype)
        carries = []
        for rnn in self.enc_rnns:
            carry, x = rnn(x)
            carries.append(carry)
        return self._bridge(carries)

    def decode_step(self, tok, carries):
        """One greedy step: tok [B] -> (logits [B, V], new carries)."""
        x = self.embedding(tok)[:, None].astype(self.dtype)  # len-1 seq
        new = []
        for rnn, c in zip(self.dec_rnns, carries):
            c2, x = rnn(x, initial_carry=c)
            new.append(c2)
        return self.head(x[:, 0].astype(jnp.float32)), new

    # ---- training forward -------------------------------------------

    def __call__(self, enc_tokens, dec_tokens, train: bool = False):
        """Teacher-forced: logits [B, T_dec, vocab] for next-token CE."""
        carries = self.encode(enc_tokens)
        x = self.embedding(dec_tokens).astype(self.dtype)
        for rnn, c in zip(self.dec_rnns, carries):
            _, x = rnn(x, initial_carry=c)
        return self.head(x.astype(jnp.float32))


def greedy_generate(model: Seq2Seq, variables, enc_tokens,
                    max_len: int, bos_id: int = 1,
                    eos_id: Optional[int] = None) -> jax.Array:
    """Greedy decode as one lax.scan over the pure apply fn
    (ref-parity: Seq2seq.infer). Returns [B, max_len] token ids; positions
    after eos are frozen at eos."""
    carries = model.apply(variables, enc_tokens, method=Seq2Seq.encode)
    B = enc_tokens.shape[0]
    tok0 = jnp.full((B,), bos_id, jnp.int32)
    done0 = jnp.zeros((B,), bool)

    def step(state, _):
        tok, carries, done = state
        logits, new_carries = model.apply(
            variables, tok, carries, method=Seq2Seq.decode_step)
        nxt = jnp.argmax(logits, -1).astype(jnp.int32)
        if eos_id is not None:
            nxt = jnp.where(done, eos_id, nxt)
            done = done | (nxt == eos_id)
        return (nxt, new_carries, done), nxt

    _, toks = jax.lax.scan(step, (tok0, carries, done0), None,
                           length=max_len)
    return toks.T  # [B, max_len]

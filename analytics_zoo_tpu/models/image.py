"""Image models — ResNet family + ImageClassifier wrapper.

Reference surface (SURVEY.md §2.5; ref: pyzoo/zoo/models/image/
imageclassification/image_classifier.py, objectdetection/): the reference
ships *loaders* for pretrained BigDL/Caffe/TF image models plus the
ImageSet preprocessing chain. Here the classifier is a native flax ResNet
(trainable from scratch or loadable from an orbax export via
``Estimator.load`` / ``InferenceModel``).

TPU-first: NHWC layout (XLA:TPU's native conv layout), bfloat16 convs on
the MXU, f32 batch-norm statistics, stride-2 convs instead of pooling where
the reference's imported models used LRN/maxpool variants.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp


class ResNetBlock(nn.Module):
    filters: int
    stride: int = 1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = lambda name: nn.BatchNorm(
            use_running_average=not train, dtype=jnp.float32, name=name)
        y = nn.Conv(self.filters, (3, 3), strides=(self.stride,) * 2,
                    use_bias=False, dtype=self.dtype, name="conv1")(x)
        y = nn.relu(norm("bn1")(y).astype(self.dtype))
        y = nn.Conv(self.filters, (3, 3), use_bias=False,
                    dtype=self.dtype, name="conv2")(y)
        y = norm("bn2")(y).astype(self.dtype)
        if x.shape[-1] != self.filters or self.stride != 1:
            x = nn.Conv(self.filters, (1, 1), strides=(self.stride,) * 2,
                        use_bias=False, dtype=self.dtype, name="proj")(x)
            x = norm("bn_proj")(x).astype(self.dtype)
        return nn.relu(x + y)


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1(x4) bottleneck (ResNet-50/101/152 blocks)."""

    filters: int
    stride: int = 1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = lambda name: nn.BatchNorm(
            use_running_average=not train, dtype=jnp.float32, name=name)
        out = self.filters * 4
        y = nn.Conv(self.filters, (1, 1), use_bias=False,
                    dtype=self.dtype, name="conv1")(x)
        y = nn.relu(norm("bn1")(y).astype(self.dtype))
        y = nn.Conv(self.filters, (3, 3), strides=(self.stride,) * 2,
                    use_bias=False, dtype=self.dtype, name="conv2")(y)
        y = nn.relu(norm("bn2")(y).astype(self.dtype))
        y = nn.Conv(out, (1, 1), use_bias=False, dtype=self.dtype,
                    name="conv3")(y)
        y = norm("bn3")(y).astype(self.dtype)
        if x.shape[-1] != out or self.stride != 1:
            x = nn.Conv(out, (1, 1), strides=(self.stride,) * 2,
                        use_bias=False, dtype=self.dtype, name="proj")(x)
            x = norm("bn_proj")(x).astype(self.dtype)
        return nn.relu(x + y)


class ResNet(nn.Module):
    """ResNet for NHWC inputs — basic blocks (18/34) or bottleneck
    (50/101/152) via ``bottleneck=True``."""

    num_classes: int
    stage_sizes: Sequence[int] = (2, 2, 2, 2)
    width: int = 64
    small_inputs: bool = False  # True: 3x3 stem for CIFAR-size images
    bottleneck: bool = False
    return_features: bool = False  # True: pyramid (C2..C5) for detection
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        if self.small_inputs:
            x = nn.Conv(self.width, (3, 3), use_bias=False,
                        dtype=self.dtype, name="stem")(x)
        else:
            x = nn.Conv(self.width, (7, 7), strides=(2, 2), use_bias=False,
                        dtype=self.dtype, name="stem")(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = nn.relu(nn.BatchNorm(use_running_average=not train,
                                 dtype=jnp.float32,
                                 name="stem_bn")(x).astype(self.dtype))
        block_cls = BottleneckBlock if self.bottleneck else ResNetBlock
        features = []
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                stride = 2 if (i > 0 and j == 0) else 1
                x = block_cls(self.width * (2 ** i), stride,
                              dtype=self.dtype,
                              name=f"stage{i}_block{j}")(x, train)
            features.append(x)
        if self.return_features:
            return tuple(features)      # strides /4, /8, /16, /32 (or
            #                             /1../8 with small_inputs)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="head")(x.astype(jnp.float32))


def resnet18(num_classes: int, **kw) -> ResNet:
    return ResNet(num_classes, stage_sizes=(2, 2, 2, 2), **kw)


def resnet34(num_classes: int, **kw) -> ResNet:
    return ResNet(num_classes, stage_sizes=(3, 4, 6, 3), **kw)


def resnet50(num_classes: int, **kw) -> ResNet:
    return ResNet(num_classes, stage_sizes=(3, 4, 6, 3), bottleneck=True,
                  **kw)


class SimpleCNN(nn.Module):
    """Small conv net (LeNet-class; the reference examples' starter model)."""

    num_classes: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        for f in (32, 64):
            x = nn.relu(nn.Conv(f, (3, 3), dtype=self.dtype)(x))
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


_BACKBONES = {
    "simple": lambda n, **kw: SimpleCNN(n, **kw),
    "resnet18": resnet18,
    "resnet34": resnet34,
    "resnet50": resnet50,
}


def ImageClassifier(num_classes: int, backbone: str = "resnet18",
                    **kw) -> nn.Module:
    """ref-parity entry (ImageClassifier.load_model analog): named backbone
    -> flax module; weights restore via Estimator.load / InferenceModel."""
    if backbone not in _BACKBONES:
        raise ValueError(
            f"unknown backbone {backbone!r}; known: {sorted(_BACKBONES)}")
    return _BACKBONES[backbone](num_classes, **kw)

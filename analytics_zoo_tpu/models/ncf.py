"""NeuralCF — neural collaborative filtering (flagship config #1).

Reference surface (SURVEY.md §2.5, ref: pyzoo/zoo/models/recommendation/
neuralcf.py + Scala models/recommendation/NeuralCF.scala): dual-branch
GMF (elementwise product of user/item embeddings) + MLP tower, merged into
a rating/classification head; ``include_mf``/``mf_embed`` knobs.

TPU-first notes: embedding lookups are gathers that XLA lays out in HBM —
large tables shard over the ``tp`` axis on their vocab dim (partition
rules below); the dense tower runs in bfloat16 on the MXU.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

# vocab-dim sharding for the embedding tables; replicated dense tower.
NCF_PARTITION_RULES = (
    (r"embedding", P("tp", None)),
    (r".*", P()),
)


class NeuralCF(nn.Module):
    """ref-parity ctor args: user_count, item_count, class_num, user_embed,
    item_embed, hidden_layers, include_mf, mf_embed."""

    user_count: int
    item_count: int
    class_num: int = 2  # 2 -> implicit feedback (binary logit pair)
    user_embed: int = 20
    item_embed: int = 20
    hidden_layers: Sequence[int] = (40, 20, 10)
    include_mf: bool = True
    mf_embed: int = 20
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, user_ids, item_ids, train: bool = False):
        # ids are 1-based in the reference (MovieLens); allocate +1 rows so
        # both conventions work without an off-by-one trap.
        u_mlp = nn.Embed(self.user_count + 1, self.user_embed,
                         name="mlp_user_embedding")(user_ids)
        i_mlp = nn.Embed(self.item_count + 1, self.item_embed,
                         name="mlp_item_embedding")(item_ids)
        x = jnp.concatenate([u_mlp, i_mlp], -1).astype(self.dtype)
        for h in self.hidden_layers:
            x = nn.relu(nn.Dense(h, dtype=self.dtype)(x))
        if self.include_mf:
            u_mf = nn.Embed(self.user_count + 1, self.mf_embed,
                            name="mf_user_embedding")(user_ids)
            i_mf = nn.Embed(self.item_count + 1, self.mf_embed,
                            name="mf_item_embedding")(item_ids)
            mf = (u_mf * i_mf).astype(self.dtype)
            x = jnp.concatenate([x, mf], -1)
        logits = nn.Dense(self.class_num, dtype=jnp.float32,
                          name="head")(x)
        return logits

"""Recommendation models — WideAndDeep, SessionRecommender.

Reference surface (SURVEY.md §2.5; ref: pyzoo/zoo/models/recommendation/
wide_and_deep.py, session_recommender.py + Scala models/recommendation/):

- ``WideAndDeep(class_num, column_info, model_type, hidden_layers)`` — wide
  (cross-product sparse logistic) + deep (embeddings → MLP) branches over a
  ``ColumnFeatureInfo`` schema; model_type in {wide, deep, wide_n_deep}.
- ``SessionRecommender(item_count, item_embed, rnn_hidden_layers,
  session_length, include_history, mlp_hidden_layers, history_length)`` —
  GRU over the current session + optional MLP over history, softmax over
  the item catalog.

TPU-first notes: the wide branch is a sparse multi-hot logistic layer —
implemented as an embedding-gather sum (one HBM gather, no scipy CSR as in
the reference, which shipped SparseTensor through the JVM); deep embeddings
shard over ``tp`` on the vocab dim; towers run bfloat16 on the MXU; session
GRU compiles to one lax.scan.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from analytics_zoo_tpu.models.rnn import RNNStack

WND_PARTITION_RULES = (
    (r"embedding", P("tp", None)),
    (r".*", P()),
)


@dataclasses.dataclass
class ColumnFeatureInfo:
    """Schema for WideAndDeep inputs (ref-parity field names).

    Batch keys expected by the model:
      - ``wide_cols``:  int [B, n_wide]  — multi-hot bucket ids, already
        offset per-column (use ``wide_offsets()``; pad with 0 = no-op id).
      - ``indicator_cols``: int [B, n_ind] — one id per indicator column.
      - ``embed_cols``: int [B, n_embed] — one id per embedding column.
      - ``continuous_cols``: float [B, n_cont].
    """

    wide_base_cols: Sequence[str] = ()
    wide_base_dims: Sequence[int] = ()
    wide_cross_cols: Sequence[str] = ()
    wide_cross_dims: Sequence[int] = ()
    indicator_cols: Sequence[str] = ()
    indicator_dims: Sequence[int] = ()
    embed_cols: Sequence[str] = ()
    embed_in_dims: Sequence[int] = ()
    embed_out_dims: Sequence[int] = ()
    continuous_cols: Sequence[str] = ()

    @property
    def wide_dims(self) -> Sequence[int]:
        return tuple(self.wide_base_dims) + tuple(self.wide_cross_dims)

    @property
    def wide_dim_total(self) -> int:
        return int(sum(self.wide_dims))

    def wide_offsets(self):
        """Per-column offsets into the flattened wide id space (id 0 of the
        flattened space is reserved as padding/no-op)."""
        offs, acc = [], 1
        for d in self.wide_dims:
            offs.append(acc)
            acc += int(d)
        return offs


class WideAndDeep(nn.Module):
    """ref-parity ctor: class_num, column_info, model_type, hidden_layers."""

    class_num: int
    column_info: ColumnFeatureInfo
    model_type: str = "wide_n_deep"
    hidden_layers: Sequence[int] = (40, 20, 10)
    dtype: jnp.dtype = jnp.bfloat16

    def feature_groups(self):
        """Input groups this schema actually uses, in positional order —
        the estimator's ``feature_cols`` should name batch keys in this
        order (absent groups are skipped, so schemas without e.g.
        indicator columns don't misalign positional features)."""
        info = self.column_info
        groups = []
        if self.model_type in ("wide", "wide_n_deep") and info.wide_dims:
            groups.append("wide_cols")
        if self.model_type in ("deep", "wide_n_deep"):
            if info.indicator_cols:
                groups.append("indicator_cols")
            if info.embed_cols:
                groups.append("embed_cols")
            if info.continuous_cols:
                groups.append("continuous_cols")
        return groups

    @nn.compact
    def __call__(self, *cols, train: bool = False, **named):
        info = self.column_info
        groups = self.feature_groups()
        feats = dict(zip(groups, cols))
        feats.update({k: v for k, v in named.items() if v is not None})
        missing = [g for g in groups if g not in feats]
        if missing:
            raise ValueError(f"WideAndDeep missing inputs {missing}; "
                             f"expected positional order {groups}")
        wide_cols = feats.get("wide_cols")
        indicator_cols = feats.get("indicator_cols")
        embed_cols = feats.get("embed_cols")
        continuous_cols = feats.get("continuous_cols")
        logits = []
        if self.model_type in ("wide", "wide_n_deep") and \
                wide_cols is not None:
            # Sparse logistic regression as a gather-sum: id 0 is the
            # padding no-op — its gathered rows are masked to zero so the
            # row never trains and padding count cannot shift the logits.
            table = nn.Embed(info.wide_dim_total + 1, self.class_num,
                             embedding_init=nn.initializers.zeros,
                             name="wide_embedding")
            valid = (wide_cols > 0).astype(jnp.float32)[..., None]
            w = (table(wide_cols) * valid).sum(axis=1)  # [B, class_num]
            logits.append(w)
        if self.model_type in ("deep", "wide_n_deep"):
            parts = []
            if info.indicator_cols:
                # indicator = one-hot passthrough; as embeddings with
                # identity-sized output this is the same gather.
                for j, (name, d) in enumerate(
                        zip(info.indicator_cols, info.indicator_dims)):
                    oh = jnp.take(
                        jnp.eye(int(d) + 1, dtype=self.dtype),
                        indicator_cols[:, j], axis=0)
                    parts.append(oh)
            for j, (name, din, dout) in enumerate(
                    zip(info.embed_cols, info.embed_in_dims,
                        info.embed_out_dims)):
                e = nn.Embed(int(din) + 1, int(dout),
                             name=f"deep_embedding_{name}")(embed_cols[:, j])
                parts.append(e.astype(self.dtype))
            if info.continuous_cols:
                parts.append(continuous_cols.astype(self.dtype))
            x = jnp.concatenate(parts, axis=-1)
            for h in self.hidden_layers:
                x = nn.relu(nn.Dense(int(h), dtype=self.dtype)(x))
            logits.append(nn.Dense(self.class_num, dtype=jnp.float32,
                                   name="deep_head")(x))
        out = logits[0] if len(logits) == 1 else logits[0] + logits[1]
        return out.astype(jnp.float32)


class SessionRecommender(nn.Module):
    """ref-parity ctor: item_count, item_embed, rnn_hidden_layers,
    session_length, include_history, mlp_hidden_layers, history_length.

    Inputs: ``session`` int [B, session_length] (0 = padding) and, when
    ``include_history``, ``history`` int [B, history_length]. Output:
    logits over the item catalog [B, item_count + 1].
    """

    item_count: int
    item_embed: int = 100
    rnn_hidden_layers: Sequence[int] = (40, 20)
    session_length: int = 0
    include_history: bool = False
    mlp_hidden_layers: Sequence[int] = (40, 20)
    history_length: int = 0
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, session, history=None, train: bool = False):
        embed = nn.Embed(self.item_count + 1, self.item_embed,
                         name="item_embedding")
        x = embed(session).astype(self.dtype)
        x = RNNStack(self.rnn_hidden_layers, rnn_type="gru",
                     dtype=self.dtype, name="session_gru")(x, train)
        if self.include_history:
            if history is None:
                raise ValueError("include_history=True needs `history`")
            # mean-pool history embeddings (mask padding id 0), then MLP.
            h = embed(history).astype(self.dtype)
            mask = (history > 0).astype(self.dtype)[..., None]
            h = (h * mask).sum(1) / jnp.maximum(mask.sum(1), 1.0)
            for u in self.mlp_hidden_layers:
                h = nn.relu(nn.Dense(int(u), dtype=self.dtype)(h))
            x = jnp.concatenate([x, h], axis=-1)
        return nn.Dense(self.item_count + 1, dtype=jnp.float32,
                        name="head")(x)


class AUGRUCell(nn.Module):
    """GRU cell whose update gate is scaled by an attention score
    (DIEN's interest-evolution unit).  Carried through lax.scan — one
    fused XLA loop, no per-step Python."""

    features: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, carry, inputs):
        x, att = inputs                       # [B, F], [B]
        h = carry
        dense = lambda name: nn.Dense(self.features, dtype=self.dtype,
                                      name=name)
        r = jax.nn.sigmoid(dense("r_x")(x) + dense("r_h")(h))
        u = jax.nn.sigmoid(dense("u_x")(x) + dense("u_h")(h))
        u = u * att[:, None].astype(u.dtype)  # attention gates the update
        c = jnp.tanh(dense("c_x")(x) + dense("c_h")(r * h))
        new_h = ((1.0 - u) * h + u * c).astype(h.dtype)
        return new_h, new_h


class DIEN(nn.Module):
    """Deep Interest Evolution Network (BASELINE.md config #5 names the
    family; ref: the reference recommendation zoo's sequential-interest
    models — SessionRecommender — extended with the DIEN structure).

    Inputs: ``item`` int [B] (target), ``history`` int [B, T] (behaviour
    sequence, 0 = padding).  Interest extraction: GRU over the history
    embeddings; interest evolution: AUGRU whose update gates are the
    attention scores of each history step against the target item.
    Output: [B, 2] click logits.

    TPU-first: both recurrences are single `lax.scan` loops (via nn.RNN /
    scanned AUGRU), attention is one batched einsum, everything bf16 on
    the MXU with f32 head.
    """

    item_count: int
    item_embed: int = 32
    gru_hidden: int = 32
    mlp_hidden: Sequence[int] = (64, 32)
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, item, history, train: bool = False):
        embed = nn.Embed(self.item_count + 1, self.item_embed,
                         embedding_init=nn.initializers.normal(0.05),
                         name="item_embedding")
        tgt = embed(item).astype(self.dtype)            # [B, E]
        hist = embed(history).astype(self.dtype)        # [B, T, E]
        mask = (history > 0).astype(jnp.float32)        # [B, T]

        # interest extraction: GRU over the behaviour sequence
        interests = RNNStack((self.gru_hidden,), rnn_type="gru",
                             return_sequences=True, dtype=self.dtype,
                             name="interest_gru")(hist)  # [B, T, H]

        # attention of each interest state against the target item
        q = nn.Dense(self.gru_hidden, dtype=self.dtype,
                     name="att_proj")(tgt)              # [B, H]
        scores = jnp.einsum("bth,bh->bt",
                            interests.astype(jnp.float32),
                            q.astype(jnp.float32))
        scores = scores / np.sqrt(self.gru_hidden)
        scores = jnp.where(mask > 0, scores, -1e9)
        att = jax.nn.softmax(scores, axis=-1) * mask    # [B, T]

        # interest evolution: AUGRU scanned over time
        cell = AUGRUCell(self.gru_hidden, dtype=self.dtype,
                         name="augru")
        B = item.shape[0]
        h0 = jnp.zeros((B, self.gru_hidden), self.dtype)
        scan = nn.scan(lambda m, c, xs: m(c, xs),
                       variable_broadcast="params",
                       split_rngs={"params": False},
                       in_axes=1, out_axes=1)
        # evolution consumes the EXTRACTED interest states (the DIEN
        # structure), not the raw embeddings
        final, _ = scan(cell, h0,
                        (interests.astype(self.dtype),
                         att.astype(self.dtype)))

        x = jnp.concatenate([final.astype(jnp.float32),
                             tgt.astype(jnp.float32),
                             (final * q).sum(-1, keepdims=True)
                             .astype(jnp.float32)], axis=-1)
        for w in self.mlp_hidden:
            x = nn.relu(nn.Dense(w, dtype=self.dtype)(x))
        return nn.Dense(2, dtype=jnp.float32, name="head")(
            x.astype(jnp.float32))

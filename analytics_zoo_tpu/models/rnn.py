"""Recurrent building blocks shared by the model zoo + zouwu forecasters.

Reference (SURVEY.md §2.4/§2.5): the Keras-API LSTM/GRU layers
(ref: pipeline/api/keras/layers/recurrent.py) used by SessionRecommender,
AnomalyDetector, Zouwu forecasters and Seq2Seq.

TPU-first notes: recurrence compiles to one ``lax.scan`` (flax ``nn.RNN``),
so the whole unrolled sequence is a single XLA while-loop with static
shapes — no per-step Python. Cell matmuls run in the requested dtype
(bfloat16 by default) on the MXU; carries stay f32 for stability.
"""

from __future__ import annotations

from typing import Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp


def make_cell(rnn_type: str, features: int, dtype=None) -> nn.RNNCellBase:
    t = rnn_type.lower()
    if t == "lstm":
        return nn.LSTMCell(features, dtype=dtype)
    if t == "gru":
        return nn.GRUCell(features, dtype=dtype)
    if t in ("rnn", "simplernn"):
        return nn.SimpleCell(features, dtype=dtype)
    raise ValueError(f"unknown rnn_type {rnn_type!r} (lstm|gru|simplernn)")


class RNNStack(nn.Module):
    """Stacked recurrent layers over [B, T, F].

    Returns the full sequence [B, T, H] if ``return_sequences`` else the
    last step [B, H]. Dropout applies between layers (reference Keras
    semantics).
    """

    hidden_sizes: Sequence[int]
    rnn_type: str = "lstm"
    dropouts: Sequence[float] = ()
    return_sequences: bool = False
    dtype: Optional[jnp.dtype] = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        drops = list(self.dropouts) or [0.0] * len(self.hidden_sizes)
        if len(drops) != len(self.hidden_sizes):
            raise ValueError("dropouts must match hidden_sizes")
        for i, (h, d) in enumerate(zip(self.hidden_sizes, drops)):
            cell = make_cell(self.rnn_type, h, dtype=self.dtype)
            x = nn.RNN(cell, name=f"{self.rnn_type}_{i}")(x)
            if d:
                x = nn.Dropout(d, deterministic=not train)(x)
        return x if self.return_sequences else x[:, -1]

"""Text models — TextClassifier and KNRM text matching.

Reference surface (SURVEY.md §2.5; ref: pyzoo/zoo/models/textclassification/
text_classifier.py, pyzoo/zoo/models/textmatching/knrm.py + Scala mirrors):

- ``TextClassifier(class_num, embedding, sequence_length, encoder,
  encoder_output_dim)`` — token embedding → CNN / LSTM / GRU encoder →
  softmax head.
- ``KNRM(text1_length, text2_length, embedding, kernel_num, sigma,
  exact_sigma, target_mode)`` — kernel-pooled soft-TF matching: cosine
  interaction matrix → RBF kernel pooling → dense.

TPU-first notes: both are embarrassingly MXU-friendly — the CNN encoder is
one conv + max-pool, KNRM's interaction matrix is a batched matmul
[B,T1,E]x[B,E,T2] and the kernel pooling is a broadcasted elementwise
reduce that XLA fuses. Pretrained GloVe rows load as frozen or trainable
embedding tables via ``embed_weights``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.models.rnn import RNNStack


def _embedding(vocab_size: int, embed_dim: int,
               weights: Optional[np.ndarray], name: str) -> nn.Embed:
    if weights is None:
        init = nn.initializers.normal(0.02)
    else:
        def init(key, shape, dtype=jnp.float32):
            w = jnp.asarray(weights, dtype)
            if w.shape != tuple(shape):
                raise ValueError(
                    f"pretrained embedding shape {w.shape} != expected "
                    f"{tuple(shape)} (vocab_size x embed_dim)")
            return w
    return nn.Embed(vocab_size, embed_dim, embedding_init=init, name=name)


class TextClassifier(nn.Module):
    """ref-parity ctor: class_num, token_length(=embed dim),
    sequence_length, encoder (cnn|lstm|gru), encoder_output_dim."""

    class_num: int
    vocab_size: int
    token_length: int = 200
    sequence_length: int = 500
    encoder: str = "cnn"
    encoder_output_dim: int = 256
    embed_weights: Optional[np.ndarray] = None
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        x = _embedding(self.vocab_size, self.token_length,
                       self.embed_weights, "word_embedding")(tokens)
        x = x.astype(self.dtype)
        enc = self.encoder.lower()
        if enc == "cnn":
            # reference: Conv1D(k=5) + global max pool.
            h = nn.Conv(self.encoder_output_dim, kernel_size=(5,),
                        dtype=self.dtype, name="conv")(x)
            h = nn.relu(h)
            h = jnp.max(h, axis=1)
        elif enc in ("lstm", "gru"):
            h = RNNStack([self.encoder_output_dim], rnn_type=enc,
                         dtype=self.dtype, name="rnn")(x, train)
        else:
            raise ValueError(f"unknown encoder {self.encoder!r}")
        h = nn.Dropout(0.2, deterministic=not train)(h)
        h = nn.relu(nn.Dense(128, dtype=self.dtype)(h))
        return nn.Dense(self.class_num, dtype=jnp.float32, name="head")(h)


class KNRM(nn.Module):
    """ref-parity ctor: text1_length, text2_length, kernel_num, sigma,
    exact_sigma, target_mode (ranking|classification).

    Inputs: ``text1`` int [B, T1] (query), ``text2`` int [B, T2] (doc),
    id 0 = padding. Output: [B, 1] ranking score (sigmoid-able logit) or
    [B, 2] classification logits.
    """

    vocab_size: int
    text1_length: int = 10
    text2_length: int = 40
    embed_dim: int = 300
    kernel_num: int = 21
    sigma: float = 0.1
    exact_sigma: float = 0.001
    target_mode: str = "ranking"
    embed_weights: Optional[np.ndarray] = None
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, text1, text2, train: bool = False):
        embed = _embedding(self.vocab_size, self.embed_dim,
                           self.embed_weights, "word_embedding")
        q = embed(text1)                       # [B, T1, E]
        d = embed(text2)                       # [B, T2, E]

        def l2norm(x):
            return x / jnp.maximum(
                jnp.linalg.norm(x, axis=-1, keepdims=True), 1e-6)

        # cosine interaction matrix — one batched MXU matmul.
        inter = jnp.einsum("bqe,bde->bqd", l2norm(q).astype(self.dtype),
                           l2norm(d).astype(self.dtype)).astype(jnp.float32)
        qmask = (text1 > 0).astype(jnp.float32)[:, :, None]   # [B,T1,1]
        dmask = (text2 > 0).astype(jnp.float32)[:, None, :]   # [B,1,T2]
        pair_mask = qmask * dmask

        # kernel centers: mu_k spaced over [-1, 1], last kernel = exact
        # match (mu=1, tight sigma) — reference KNRM layout.
        K = self.kernel_num
        mus = [1.0]
        sigmas = [self.exact_sigma]
        if K > 1:
            step = 2.0 / (K - 1)
            mus += [1.0 - step / 2 - i * step for i in range(K - 1)]
            sigmas += [self.sigma] * (K - 1)
        mu = jnp.asarray(mus)[None, None, None, :]       # [1,1,1,K]
        sg = jnp.asarray(sigmas)[None, None, None, :]

        # RBF pooling: sum over doc dim, log, sum over query dim.
        kv = jnp.exp(-jnp.square(inter[..., None] - mu) / (2 * sg * sg))
        kv = (kv * pair_mask[..., None]).sum(axis=2)     # [B, T1, K]
        phi = (jnp.log1p(kv) * qmask).sum(axis=1)        # [B, K]

        if self.target_mode == "classification":
            return nn.Dense(2, name="head")(phi)
        return nn.Dense(1, name="head")(phi)

"""Object detection — SSD over a ResNet feature pyramid.

Reference surface (SURVEY.md §2.5 model zoo "image classification/object
detection loaders"; ref: zoo models/image/objectdetection/ — SSD-VGG /
SSD-MobileNet wrappers with a `Predictor` + `visualize` post-processing
chain): single-shot detection heads over backbone features, multibox
matching loss, and a decode step (offsets -> boxes, score filter, NMS).

TPU-first design decisions:
- **Anchor matching lives INSIDE the jitted train step** as dense IoU
  matrices ([anchors, max_boxes] per image, vmapped over batch) — no
  per-image Python, no ragged tensors, one fused XLA program.  Ground
  truth arrives padded to `max_boxes` with class -1 (the XShards/ImageSet
  collate convention).
- **Hard-negative mining is a sort, not a loop**: rank negative losses
  with top_k and keep 3:1 neg:pos, exactly the reference semantics but
  batch-vectorised on the MXU/VPU.
- **Decode + NMS run on host** (numpy): tiny tensors after score
  filtering, data-dependent shapes that would force padded worst-case
  compute on device — same split the reference used (JVM-side
  post-processing after the native forward).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.models.image import ResNet

# anchor aspect ratios per cell (w/h); one scale per pyramid level
DEFAULT_ASPECTS = (1.0, 2.0, 0.5)


# ---------------------------------------------------------------------------
# anchors
# ---------------------------------------------------------------------------

def ssd_anchors(image_size: int, strides: Sequence[int],
                scales: Sequence[float],
                aspects: Sequence[float] = DEFAULT_ASPECTS) -> np.ndarray:
    """Anchor grid [N, 4] as (cy, cx, h, w), normalised to [0, 1].

    Level k tiles `image_size/strides[k]` cells; each cell holds
    len(aspects) anchors of area scales[k]^2 (scales are fractions of the
    image side).  Matches the head layout in SSD.__call__ exactly:
    anchors iterate (row, col, aspect), levels concatenated in order.
    """
    if len(strides) != len(scales):
        raise ValueError("strides and scales must align per level")
    out = []
    for stride, scale in zip(strides, scales):
        # ceil-div: SAME-padded stride-2 convs produce ceil(in/2) per
        # downsample, and iterated ceil-halving equals ceil(n / 2^k) — so
        # this matches the head grid for ANY image size, not just
        # multiples of the deepest stride
        fm = -(-image_size // stride)
        cy, cx = np.meshgrid(
            (np.arange(fm) + 0.5) / fm, (np.arange(fm) + 0.5) / fm,
            indexing="ij")
        for ar in aspects:
            h = scale / np.sqrt(ar)
            w = scale * np.sqrt(ar)
            lvl = np.stack([cy, cx, np.full_like(cy, h),
                            np.full_like(cx, w)], axis=-1)
            out.append(lvl.reshape(-1, 4))
        # interleave aspects per cell: reorder so the fastest axis is the
        # aspect (head emits [H, W, A*4])
    per_level = []
    i = 0
    for stride in strides:
        fm = -(-image_size // stride)
        cells = fm * fm
        block = np.stack(out[i:i + len(aspects)], axis=1)  # [cells, A, 4]
        per_level.append(block.reshape(-1, 4))
        i += len(aspects)
    return np.concatenate(per_level).astype(np.float32)


def _encode_boxes(anchors: jnp.ndarray, boxes: jnp.ndarray) -> jnp.ndarray:
    """(ymin,xmin,ymax,xmax) gt vs (cy,cx,h,w) anchors -> regression
    targets (dy, dx, log dh, log dw) — standard SSD parameterisation."""
    b_cy = (boxes[..., 0] + boxes[..., 2]) / 2
    b_cx = (boxes[..., 1] + boxes[..., 3]) / 2
    b_h = jnp.maximum(boxes[..., 2] - boxes[..., 0], 1e-6)
    b_w = jnp.maximum(boxes[..., 3] - boxes[..., 1], 1e-6)
    return jnp.stack([
        (b_cy - anchors[..., 0]) / anchors[..., 2],
        (b_cx - anchors[..., 1]) / anchors[..., 3],
        jnp.log(b_h / anchors[..., 2]),
        jnp.log(b_w / anchors[..., 3]),
    ], axis=-1)


def _decode_boxes(anchors: np.ndarray, deltas: np.ndarray) -> np.ndarray:
    cy = deltas[..., 0] * anchors[..., 2] + anchors[..., 0]
    cx = deltas[..., 1] * anchors[..., 3] + anchors[..., 1]
    h = np.exp(deltas[..., 2]) * anchors[..., 2]
    w = np.exp(deltas[..., 3]) * anchors[..., 3]
    return np.stack([cy - h / 2, cx - w / 2, cy + h / 2, cx + w / 2],
                    axis=-1)


def _iou_matrix(anchors_yx: jnp.ndarray, boxes: jnp.ndarray) -> jnp.ndarray:
    """IoU [N_anchors, M_boxes]; both as (ymin,xmin,ymax,xmax)."""
    a = anchors_yx[:, None, :]
    b = boxes[None, :, :]
    inter_h = jnp.clip(jnp.minimum(a[..., 2], b[..., 2]) -
                       jnp.maximum(a[..., 0], b[..., 0]), 0)
    inter_w = jnp.clip(jnp.minimum(a[..., 3], b[..., 3]) -
                       jnp.maximum(a[..., 1], b[..., 1]), 0)
    inter = inter_h * inter_w
    area_a = (a[..., 2] - a[..., 0]) * (a[..., 3] - a[..., 1])
    area_b = jnp.clip((b[..., 2] - b[..., 0]) * (b[..., 3] - b[..., 1]), 0)
    return inter / jnp.maximum(area_a + area_b - inter, 1e-9)


# ---------------------------------------------------------------------------
# model
# ---------------------------------------------------------------------------

class SSD(nn.Module):
    """SSD heads over a ResNet pyramid.

    Inputs [B, S, S, 3] (S = image_size); outputs
    ``(loc [B, N, 4], cls_logits [B, N, num_classes+1])`` with class 0 =
    background.  Use :func:`multibox_loss` for training and
    :func:`decode_detections` / :class:`SSDDetector` for inference.
    """

    num_classes: int                      # foreground classes
    image_size: int = 256
    backbone_width: int = 64
    backbone_stages: Sequence[int] = (2, 2, 2, 2)   # resnet-18 layout
    levels: Sequence[int] = (1, 2, 3)     # pyramid stages (/8, /16, /32)
    scales: Sequence[float] = (0.15, 0.35, 0.6)
    aspects: Sequence[float] = DEFAULT_ASPECTS
    dtype: jnp.dtype = jnp.bfloat16

    def strides(self) -> List[int]:
        return [4 * (2 ** s) for s in self.levels]

    def anchors(self) -> np.ndarray:
        return ssd_anchors(self.image_size, self.strides(),
                           list(self.scales), list(self.aspects))

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.shape[1] != self.image_size or x.shape[2] != self.image_size:
            raise ValueError(
                f"SSD(image_size={self.image_size}) got {x.shape}")
        feats = ResNet(num_classes=1, width=self.backbone_width,
                       stage_sizes=tuple(self.backbone_stages),
                       return_features=True, dtype=self.dtype,
                       name="backbone")(x, train)
        A = len(self.aspects)
        locs, clss = [], []
        for li, s in enumerate(self.levels):
            f = feats[s]
            h = nn.relu(nn.Conv(128, (3, 3), dtype=self.dtype,
                                name=f"head{li}_conv")(f))
            loc = nn.Conv(A * 4, (3, 3), dtype=jnp.float32,
                          name=f"head{li}_loc")(h)
            cls = nn.Conv(A * (self.num_classes + 1), (3, 3),
                          dtype=jnp.float32, name=f"head{li}_cls")(h)
            B = x.shape[0]
            locs.append(loc.reshape(B, -1, 4))
            clss.append(cls.reshape(B, -1, self.num_classes + 1))
        return jnp.concatenate(locs, 1), jnp.concatenate(clss, 1)


# ---------------------------------------------------------------------------
# loss
# ---------------------------------------------------------------------------

def multibox_loss(anchors: np.ndarray, num_classes: int,
                  neg_pos_ratio: int = 3, iou_thresh: float = 0.5):
    """Returns an Estimator-compatible loss ``fn(preds, labels)``.

    labels = (boxes [B, M, 4] in (ymin,xmin,ymax,xmax) normalised,
    classes [B, M] int32 with -1 padding).  Matching, encoding and
    3:1 hard-negative mining are all dense ops inside the jit.
    """
    anc = jnp.asarray(anchors)
    anc_yx = jnp.stack([anc[:, 0] - anc[:, 2] / 2, anc[:, 1] - anc[:, 3] / 2,
                        anc[:, 0] + anc[:, 2] / 2, anc[:, 1] + anc[:, 3] / 2],
                       axis=-1)

    def one_image(loc, cls_logits, boxes, classes):
        import optax

        valid = classes >= 0                            # [M]
        iou = _iou_matrix(anc_yx, boxes)                # [N, M]
        iou = jnp.where(valid[None, :], iou, -1.0)
        best_iou = iou.max(axis=1)                      # [N]
        best_box = iou.argmax(axis=1)                   # [N]
        pos = best_iou >= iou_thresh
        # classic SSD: every valid gt also claims its single best anchor
        # (so tiny objects below iou_thresh still train)
        best_anchor = iou.argmax(axis=0)                # [M]
        # scatter only VALID boxes: padding rows all argmax to anchor 0,
        # and duplicate-index scatters with conflicting values resolve in
        # implementation-defined order — route invalid rows to an
        # out-of-bounds index that mode="drop" discards
        safe_anchor = jnp.where(valid, best_anchor, anc.shape[0])
        pos = pos | jnp.zeros_like(pos).at[safe_anchor].set(
            True, mode="drop")
        best_box = best_box.at[safe_anchor].set(
            jnp.arange(boxes.shape[0]), mode="drop")

        tgt_cls = jnp.where(pos, classes[best_box] + 1, 0)  # 0 = background
        tgt_loc = _encode_boxes(anc, boxes[best_box])

        ce = optax.softmax_cross_entropy_with_integer_labels(
            cls_logits, tgt_cls)                        # [N]
        n_pos = jnp.maximum(pos.sum(), 1)
        # hard negative mining: top (ratio * n_pos) negative CE values
        neg_ce = jnp.where(pos, -jnp.inf, ce)
        rank = jnp.argsort(jnp.argsort(-neg_ce))        # rank 0 = hardest
        neg = (~pos) & (rank < neg_pos_ratio * n_pos)
        cls_loss = jnp.where(pos | neg, ce, 0.0).sum() / n_pos
        loc_loss = jnp.where(
            pos, optax.huber_loss(loc, tgt_loc).sum(-1), 0.0).sum() / n_pos
        return cls_loss + loc_loss

    def loss_fn(preds, labels):
        loc, cls_logits = preds
        boxes, classes = labels
        per_img = jax.vmap(one_image)(loc, cls_logits,
                                      boxes.astype(jnp.float32),
                                      classes.astype(jnp.int32))
        return per_img.mean()

    return loss_fn


# ---------------------------------------------------------------------------
# decode (host)
# ---------------------------------------------------------------------------

def _nms(boxes: np.ndarray, scores: np.ndarray, iou_thresh: float,
         top_k: int) -> List[int]:
    order = np.argsort(-scores)[:top_k * 4]
    keep: List[int] = []
    while order.size and len(keep) < top_k:
        i = order[0]
        keep.append(int(i))
        if order.size == 1:
            break
        rest = order[1:]
        yx1 = np.maximum(boxes[i, :2], boxes[rest, :2])
        yx2 = np.minimum(boxes[i, 2:], boxes[rest, 2:])
        wh = np.clip(yx2 - yx1, 0, None)
        inter = wh[:, 0] * wh[:, 1]
        area_i = np.prod(boxes[i, 2:] - boxes[i, :2])
        area_r = np.prod(boxes[rest, 2:] - boxes[rest, :2], axis=1)
        iou = inter / np.maximum(area_i + area_r - inter, 1e-9)
        order = rest[iou <= iou_thresh]
    return keep


def decode_detections(loc: np.ndarray, cls_logits: np.ndarray,
                      anchors: np.ndarray, *, score_thresh: float = 0.5,
                      iou_thresh: float = 0.45, top_k: int = 100
                      ) -> List[dict]:
    """Raw head outputs -> per-image detections.

    Returns one dict per image: {"boxes" [K,4] (ymin,xmin,ymax,xmax in
    [0,1]), "scores" [K], "classes" [K] (0-based foreground ids)}.
    (ref: object-detection `Predictor` + `decode_output` chain.)
    """
    loc = np.asarray(loc)
    cls_logits = np.asarray(cls_logits)
    e = np.exp(cls_logits - cls_logits.max(-1, keepdims=True))
    probs = e / e.sum(-1, keepdims=True)
    out = []
    for b in range(loc.shape[0]):
        boxes = _decode_boxes(anchors, loc[b])
        fg = probs[b, :, 1:]                       # drop background
        cls_id = fg.argmax(-1)
        score = fg.max(-1)
        m = score >= score_thresh
        bx, sc, ci = boxes[m], score[m], cls_id[m]
        final_b, final_s, final_c = [], [], []
        for c in np.unique(ci):                    # per-class NMS
            sel = np.flatnonzero(ci == c)
            kept = _nms(bx[sel], sc[sel], iou_thresh, top_k)
            final_b.append(bx[sel[kept]])
            final_s.append(sc[sel[kept]])
            final_c.append(np.full(len(kept), c))
        if final_b:
            bx = np.concatenate(final_b)
            sc = np.concatenate(final_s)
            ci = np.concatenate(final_c)
            order = np.argsort(-sc)[:top_k]
            bx, sc, ci = bx[order], sc[order], ci[order]
        else:
            bx = np.zeros((0, 4), np.float32)
            sc = np.zeros((0,), np.float32)
            ci = np.zeros((0,), np.int64)
        out.append({"boxes": np.clip(bx, 0, 1), "scores": sc,
                    "classes": ci})
    return out


# ---------------------------------------------------------------------------
# user-facing wrapper (ref: ObjectDetector load/predict surface)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class SSDDetector:
    """Train/predict convenience over (SSD model + Estimator).

    ``fit(data)`` expects columns {"x" images, "boxes" [B,M,4],
    "classes" [B,M] (-1 padded)}; ``detect(images)`` returns decoded
    per-image detections.
    """

    num_classes: int
    image_size: int = 256
    backbone_width: int = 64
    max_boxes: int = 8
    optimizer: object = None
    score_thresh: float = 0.5

    def __post_init__(self):
        import optax

        from analytics_zoo_tpu.learn import Estimator

        self.model = SSD(num_classes=self.num_classes,
                         image_size=self.image_size,
                         backbone_width=self.backbone_width)
        self.anchors = self.model.anchors()
        self.estimator = Estimator.from_flax(
            model=self.model,
            loss=multibox_loss(self.anchors, self.num_classes),
            optimizer=self.optimizer or optax.adam(1e-3),
            feature_cols=("x",), label_cols=("boxes", "classes"))

    def fit(self, data, epochs: int = 1, batch_size: int = 8, **kw):
        return self.estimator.fit(data, epochs=epochs,
                                  batch_size=batch_size, **kw)

    def detect(self, images, batch_size: int = 8, **decode_kw):
        loc, cls_logits = self.estimator.predict(
            {"x": np.asarray(images)}, batch_size=batch_size)
        decode_kw.setdefault("score_thresh", self.score_thresh)
        return decode_detections(loc, cls_logits, self.anchors,
                                 **decode_kw)

    def save(self, path: str):
        self.estimator.save(path)

    def load(self, path: str, sample_images=None):
        sample = None
        if sample_images is not None:
            sample = {"x": np.asarray(sample_images),
                      "boxes": np.zeros((1, self.max_boxes, 4), np.float32),
                      "classes": np.full((1, self.max_boxes), -1, np.int32)}
        self.estimator.load(path, sample)


__all__ = ["SSD", "SSDDetector", "ssd_anchors", "multibox_loss",
           "decode_detections"]

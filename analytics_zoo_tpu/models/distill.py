"""Draft-model distillation — the companion of speculative decoding.

Speculation only pays when the draft guesses like the target
(models/speculative.py: acceptance rate IS the speedup).  An
independently-trained small LM guesses like itself; a DISTILLED one is
trained to match the target's token distribution, which is exactly the
acceptance criterion.  This module trains a small TransformerLM against
a frozen target's logits in one Estimator fit:

    draft_vars = distill_draft(target, target_vars, draft, data, ...)

Design: ``DistillLM`` wraps both models in one module — the jitted
train step runs the frozen target forward (``stop_gradient``) and the
draft forward on the same tokens and returns the per-sample distillation
loss (forward KL, temperature-scaled, optionally mixed with next-token
CE).  The target's params ride in the same tree under ``target/`` but
``freeze_target_optimizer`` masks them out of the optimizer
(``optax.multi_transform`` — no Adam moments for the big model, same
memory shape as learn/lora.py).  TPU fit: both forwards share one XLA
program, the target runs inference-only (no activation stashing), and
everything jits/shards like any other Estimator model.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from analytics_zoo_tpu.models.lm import TransformerLM


class DistillLM(nn.Module):
    """Train-time pair: frozen ``target`` teaches ``draft``.

    ``__call__(tokens, train)`` returns per-sample loss [B]:
    ``kl_weight * KL(target_T || draft_T) + ce_weight * CE(draft,
    next-token)`` where ``_T`` is temperature-softened.  Use with
    ``loss=distill_loss`` (the mean) and
    ``freeze_target_optimizer(tx)``."""

    draft: TransformerLM
    target: TransformerLM
    temperature: float = 1.0
    kl_weight: float = 1.0
    ce_weight: float = 0.0

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        if self.draft.vocab_size != self.target.vocab_size:
            raise ValueError(
                f"draft vocab {self.draft.vocab_size} != target vocab "
                f"{self.target.vocab_size}")
        t_logits = lax.stop_gradient(
            self.target(tokens, False).astype(jnp.float32))
        d_logits = self.draft(tokens, train).astype(jnp.float32)
        # next-token alignment: position t teaches token t+1
        t_logits = t_logits[:, :-1]
        d_logits = d_logits[:, :-1]
        inv_t = 1.0 / float(self.temperature)
        t_logp = jax.nn.log_softmax(t_logits * inv_t, axis=-1)
        d_logp = jax.nn.log_softmax(d_logits * inv_t, axis=-1)
        # forward KL, mean over positions -> [B]; the standard T^2
        # factor keeps gradient scale comparable across temperatures
        kl = jnp.sum(jnp.exp(t_logp) * (t_logp - d_logp), axis=-1)
        loss = self.kl_weight * float(self.temperature) ** 2 \
            * jnp.mean(kl, axis=-1)
        if self.ce_weight:
            import optax

            ce = optax.softmax_cross_entropy_with_integer_labels(
                d_logits, tokens[:, 1:])
            loss = loss + self.ce_weight * jnp.mean(ce, axis=-1)
        return loss


def distill_loss(per_sample, _tokens):
    """Estimator loss for DistillLM: the model output IS the loss."""
    return jnp.mean(per_sample)


def freeze_target_optimizer(tx):
    """Mask the optimizer to the draft's params: the frozen target gets
    ``set_to_zero`` labels, so no Adam moments are allocated for it."""
    import optax

    def labels(params):
        return {k: jax.tree.map(lambda _: "frozen" if k == "target"
                                else "train", v)
                for k, v in params.items()}

    return optax.multi_transform(
        {"train": tx, "frozen": optax.set_to_zero()}, labels)


def distill_draft(target: TransformerLM, target_variables,
                  draft: TransformerLM, data, *,
                  epochs: int = 3, batch_size: int = 8,
                  optimizer=None, temperature: float = 2.0,
                  ce_weight: float = 0.1,
                  partition_rules=None,
                  estimator_kwargs: Optional[dict] = None):
    """One-call distillation: fit ``draft`` to match ``target`` on
    ``data`` (dict with a ``tokens`` [N, T] int32 column).  Returns
    ``(draft_variables, history)`` — feed them straight into
    ``speculative_generate`` / ``load_flax_generator(draft_model=...)``.
    """
    import optax

    from analytics_zoo_tpu.learn import Estimator
    from analytics_zoo_tpu.models.lm import LM_PARTITION_RULES

    pair = DistillLM(draft=draft, target=target,
                     temperature=temperature, ce_weight=ce_weight)
    tx = optimizer if optimizer is not None else optax.adamw(3e-3)
    est = Estimator.from_flax(
        model=pair, loss=distill_loss,
        optimizer=freeze_target_optimizer(tx),
        feature_cols=("tokens",), label_cols=("tokens",),
        partition_rules=(partition_rules if partition_rules is not None
                         else LM_PARTITION_RULES),
        **(estimator_kwargs or {}))

    # seed the pair's param tree with the REAL target weights before the
    # first step: _ensure_state initialises both submodules, then the
    # target subtree is replaced wholesale (it never trains, so this is
    # the only write it ever sees)
    sample = {k: v[:batch_size] for k, v in data.items()}
    est._ensure_state(sample)
    params = dict(est.state.params)
    tgt = target_variables["params"] if "params" in target_variables \
        else target_variables
    import numpy as np

    def _shape_tree(t):
        return jax.tree.map(lambda x: tuple(x.shape), t)

    if _shape_tree(params["target"]) != _shape_tree(tgt):
        raise ValueError(
            "target_variables do not match the target model's shapes — "
            "wrong checkpoint?")
    params["target"] = jax.tree.map(
        # keep each leaf's dtype AND sharding (tp-sharded fits shard the
        # frozen teacher too)
        lambda dst, src: jax.device_put(
            np.asarray(src).astype(dst.dtype), dst.sharding),
        params["target"], tgt)
    est.state = est.state.replace(params=params)

    hist = est.fit(data, epochs=epochs, batch_size=batch_size)
    draft_params = jax.device_get(est.state.params)["draft"]
    return {"params": draft_params}, hist

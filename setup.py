"""Build glue: compile the native host data plane at install time.

The C++ data plane (analytics_zoo_tpu/native/dataplane.cpp — ring buffer,
parallel CSV, ZREC store) is a plain shared library bound via ctypes, not a
Python extension module, so it is built with a custom command rather than
Extension().  If no C++ toolchain exists at install time, the build is
skipped and the library compiles lazily on first use instead
(native.load_lib); pure-Python paths keep working either way.
"""

import subprocess
import sys
from pathlib import Path

from setuptools import setup
from setuptools.command.build_py import build_py


class BuildWithNative(build_py):
    def run(self):
        super().run()
        src = Path(__file__).parent / "analytics_zoo_tpu" / "native" / \
            "dataplane.cpp"
        for base in [Path(self.build_lib), Path(__file__).parent]:
            out = base / "analytics_zoo_tpu" / "native" / \
                "libzoo_dataplane.so"
            if not out.parent.exists():
                continue
            cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                   "-pthread", str(src), "-o", str(out)]
            try:
                subprocess.run(cmd, check=True, capture_output=True)
                print(f"built native data plane -> {out}")
            except (FileNotFoundError, subprocess.CalledProcessError) as e:
                print(f"warning: native build skipped ({e}); will compile "
                      "lazily on first use", file=sys.stderr)
            break


setup(cmdclass={"build_py": BuildWithNative})

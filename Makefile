# Developer entry points (ref: the reference's pyzoo/dev run scripts +
# make-dist.sh packaging glue).

PY ?= python

.PHONY: test verify examples bench native serve-smoke chaos-smoke \
	overload-smoke sim-gate lint clean

# full suite on the 8-virtual-device CPU mesh (tests/conftest.py forces it)
test:
	$(PY) -m pytest tests/ -q

# quick smoke: native build + fast test subset + every example vertical
# (examples run on the default platform — TPU when present; set
# EXAMPLE_PLATFORM=cpu to force host CPU)
verify: native
	$(PY) -m pytest tests/test_context.py tests/test_data.py \
	    tests/test_estimator.py -q
	$(PY) examples/train_ncf.py
	$(PY) examples/forecast_taxi.py
	$(PY) examples/serve_model.py

examples:
	$(PY) examples/train_ncf.py
	$(PY) examples/forecast_taxi.py
	$(PY) examples/serve_model.py
	$(PY) examples/multihost_fit.py
	$(PY) examples/train_moe_pipeline.py --devices 8 --epochs 2
	$(PY) examples/lm_generate.py --devices 8

# compile the C++ data plane in place (csv parser, zrec store, ring
# buffer, image decode)
native:
	$(PY) -c "from analytics_zoo_tpu import native; native.load_lib(); print('native data plane:', native.available())"

# JAX staging/tracing lint (TZ001..TZ008) + concurrency lock-discipline
# pass (TZ101..TZ108), docs/lint.md; exits non-zero on any finding not
# recorded in tpulint_baseline.json, or on stale baseline entries.
# Pass --no-concurrency to run the staging family alone.
lint:
	$(PY) -m analytics_zoo_tpu.lint analytics_zoo_tpu/ \
	    --baseline tpulint_baseline.json

# one-chip benchmark suite (writes the driver-facing JSON line)
bench:
	$(PY) bench.py

# serving smoke: the paged KV-cache + chunked-prefill + composed-mode
# (speculative over blocks/chunks) + telemetry + QoS front-door test
# files + a 20-request e2e wire-protocol bench leg (which drives the
# chunked scheduler end to end, runs a SPECULATIVE paged+chunked stack
# and scrapes /metrics + /healthz and schema-checks the dumped trace
# live, then the front-door leg: SSE streaming e2e, a mid-stream
# client disconnect with both KV pools reclaimed, and a 429 +
# Retry-After off a saturated admission queue), all forced onto host
# CPU (fast; fits the tier-1 timeout)
serve-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_paged_cache.py \
	    tests/test_chunked_prefill.py tests/test_telemetry.py \
	    tests/test_frontdoor.py tests/test_router.py -q -m "not slow"
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_spec_composed.py \
	    tests/test_flight.py tests/test_paged_fused.py -q
	# LockGuard leg: live paged+chunked engine ticks (speculative and
	# host-tier spill->readmit churn) with every lock instrumented and
	# jax.device_get/device_put patched — zero order inversions, zero
	# device transfers under a lock (docs/lint.md, TZ1xx runtime twin)
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_lockguard.py -q
	# fresh-bundle -> replay round trip + engine/sim decision equivalence
	# (slow-marked classes in test_sim.py run unfiltered here, like
	# test_flight.py above; docs/simulation.md)
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_sim.py -q
	JAX_PLATFORMS=cpu $(PY) bench_serving.py --smoke

# crash-tolerance chaos leg, standalone (also runs inside serve-smoke's
# bench_serving --smoke chain): a live 3-replica prefill/decode fleet
# under deterministic fault injection — one decode pump crashes and one
# KV handoff is dropped; every request must reach a terminal result
# with at-least-once `attempts` recorded, and /metrics must show the
# death, the redispatch, and the handoff ack-timeout recovery
# (docs/debugging.md "Crash recovery runbook").
chaos-smoke:
	JAX_PLATFORMS=cpu $(PY) bench_serving.py --chaos-smoke

# graceful-degradation overload leg, standalone (also runs inside
# serve-smoke's bench_serving --smoke chain): a live 2-replica fleet
# under a saturating mixed-class burst with a tiny brownout ladder —
# the ladder must ascend AND fully unwind on /metrics, expired-deadline
# requests must shed at admission (before prefill) as terminal
# deadline_exceeded errors, and every interactive request must finish
# (docs/serving_qos.md "Overload & brownout").
overload-smoke:
	JAX_PLATFORMS=cpu $(PY) bench_serving.py --overload-smoke

# CI gate for scheduler regressions: run the pinned golden scenario
# (tests/golden/sim_golden.json) through the offline discrete-event
# simulator and assert its envelopes (docs/simulation.md).  jax-free:
# also part of tier-1 via tests/test_sim.py::TestGoldenGate.
sim-gate:
	$(PY) -m analytics_zoo_tpu.serving.sim gate tests/golden/sim_golden.json

clean:
	rm -rf build dist *.egg-info analytics_zoo_tpu/native/*.so

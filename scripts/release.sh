#!/usr/bin/env bash
# Release build (ref: the reference's make-dist.sh + pyzoo packaging glue):
# green suite -> native build -> sdist/wheel into dist/ -> docker image.
# Usage: scripts/release.sh [--skip-tests] [--docker]
set -euo pipefail
cd "$(dirname "$0")/.."

SKIP_TESTS=0; DOCKER=0
for a in "$@"; do
  case "$a" in
    --skip-tests) SKIP_TESTS=1 ;;
    --docker) DOCKER=1 ;;
    *) echo "unknown arg $a" >&2; exit 2 ;;
  esac
done

if [ "$SKIP_TESTS" = 0 ]; then
  python -m pytest tests/ -q
fi

# native data plane compiles on install; delete any cached .so so a
# broken toolchain fails the release, not the user's first import
rm -f analytics_zoo_tpu/native/*.so
python -c "from analytics_zoo_tpu import native; native.load_lib(); print('native:', native.available())"

rm -rf dist
if python -c "import build" 2>/dev/null; then
  python -m build --sdist --wheel --no-isolation
else
  python setup.py -q sdist
  python setup.py -q bdist_wheel || \
    echo "WARNING: wheel build failed (is 'wheel' installed?); release has sdist only" >&2
fi
ls -l dist/

if [ "$DOCKER" = 1 ]; then
  docker build -t analytics-zoo-tpu:$(python -c "import analytics_zoo_tpu as z; print(getattr(z, '__version__', 'dev'))") -f docker/Dockerfile .
fi
echo "release artifacts in dist/"

#!/bin/bash
# One-shot: waits for TPU_ALIVE (touched by tpu_probe_loop.sh), then runs
# the prioritized bench capture (bench.py checkpoints BENCH_PARTIAL.json
# after every config) followed by the serving bench. BENCH_RUNNING pauses
# the probe loop so probe processes don't contend for the device grant.
cd /root/repo || exit 1
trap 'rm -f BENCH_RUNNING' EXIT INT TERM
while true; do
  if [ -f TPU_ALIVE ]; then
    TS=$(date -u +%Y-%m-%dT%H:%M:%SZ)
    echo "recovery detected at $TS - firing prioritized bench" >> bench_recovery.log
    touch BENCH_RUNNING
    timeout 10800 python bench.py > BENCH_SESSION_r05.json 2>> bench_recovery.log
    echo "bench.py rc=$? at $(date -u +%Y-%m-%dT%H:%M:%SZ)" >> bench_recovery.log
    timeout 5400 python bench_serving.py >> bench_recovery.log 2>&1
    echo "bench_serving.py rc=$? at $(date -u +%Y-%m-%dT%H:%M:%SZ)" >> bench_recovery.log
    rm -f BENCH_RUNNING
    break
  fi
  sleep 60
done

#!/bin/bash
# Opportunistic TPU capture (VERDICT r4 ask #1): waits for TPU_ALIVE
# (touched by tpu_probe_loop.sh), then runs the priority queue —
#   1. bench_serving.py   (regenerates SERVING_BENCH.json; int8-mxu +
#                          continuous-vs-convoy are the open claims)
#   2. scripts/profile_lm.py  (MFU ablation evidence -> PROFILE_LM.json)
#   3. bench.py           (full train-side capture incl. fused-loss LM)
# Each stage checkpoints its own artifact, so a re-wedge mid-queue keeps
# every completed stage.  On a wedge-abort the loop returns to waiting
# for the next recovery window and re-runs only the missing stages.
# BENCH_RUNNING pauses the probe loop so probes don't contend for the
# device grant mid-bench.
cd /root/repo || exit 1
# ownership-aware flag protocol (bench_guard.py): the flag records the
# owner pid; only the owner removes it, and a flag whose owner is dead
# is stale and reclaimable.
release_flag() {
  [ "$(cat BENCH_RUNNING 2>/dev/null)" = "$$" ] && rm -f BENCH_RUNNING
}
acquire_flag() {
  OWNER=$(cat BENCH_RUNNING 2>/dev/null)
  if [ -n "$OWNER" ] && [ "$OWNER" != "$$" ] \
      && kill -0 "$OWNER" 2>/dev/null; then
    return 1    # a live direct bench run holds the pause — defer to it
  fi
  # atomic publish (mirror of bench_guard._write_pid_atomic): readers
  # must never see an empty flag, or stale-reclaim kills a live pause
  echo "$$" > "BENCH_RUNNING.$$" && mv "BENCH_RUNNING.$$" BENCH_RUNNING
}
trap 'release_flag' EXIT INT TERM

probe() {   # shared probe (bench_serving.py --probe); rc 0 = alive
  timeout 90 python bench_serving.py --probe 2>/dev/null | grep -q PROBE_OK
}

ROUNDS=0
MAX_ROUNDS=12   # a stage failing DETERMINISTICALLY must not retry forever
while true; do
  if [ ! -f TPU_ALIVE ]; then
    sleep 60; continue
  fi
  ROUNDS=$((ROUNDS + 1))
  if [ "$ROUNDS" -gt "$MAX_ROUNDS" ]; then
    echo "giving up after $MAX_ROUNDS recovery rounds" >> bench_recovery.log
    break
  fi
  TS=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  echo "recovery round $ROUNDS at $TS" >> bench_recovery.log
  if ! acquire_flag; then
    echo "deferring: a live bench holds BENCH_RUNNING" >> bench_recovery.log
    ROUNDS=$((ROUNDS - 1))   # not a spent attempt
    sleep 120; continue
  fi
  if [ ! -f SERVING_DONE ]; then
    timeout 7200 python bench_serving.py >> bench_recovery.log 2>&1 \
      && touch SERVING_DONE
    echo "bench_serving rc=$? at $(date -u +%H:%M:%SZ)" >> bench_recovery.log
  fi
  if [ ! -f PROFILE_DONE ] && probe; then
    # tmp + mv: a retry must not truncate a good earlier capture
    timeout 3600 python scripts/profile_lm.py > PROFILE_LM.json.tmp \
      2>> bench_recovery.log \
      && mv PROFILE_LM.json.tmp PROFILE_LM.json \
      && touch PROFILE_DONE
    echo "profile_lm rc=$? at $(date -u +%H:%M:%SZ)" >> bench_recovery.log
  fi
  if [ ! -f TRAINBENCH_DONE ] && probe; then
    # write to a temp first: BENCH_SESSION_r05.json may already hold a
    # good earlier capture that a mid-run wedge must not destroy
    timeout 10800 python bench.py > BENCH_SESSION_r05.json.tmp \
      2>> bench_recovery.log \
      && mv BENCH_SESSION_r05.json.tmp BENCH_SESSION_r05.json \
      && touch TRAINBENCH_DONE
    echo "bench rc=$? at $(date -u +%H:%M:%SZ)" >> bench_recovery.log
  fi
  release_flag
  rm -f TPU_ALIVE   # force a fresh probe-loop verdict before next round
  if [ -f SERVING_DONE ] && [ -f PROFILE_DONE ] && [ -f TRAINBENCH_DONE ]; then
    echo "all stages captured at $(date -u +%H:%M:%SZ)" >> bench_recovery.log
    break
  fi
  sleep 120   # wedged mid-queue: wait for the next window
done

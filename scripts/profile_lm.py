#!/usr/bin/env python
"""Ablation profile for the two MFU laggards (VERDICT r4 ask #3):
the 111M LM at seq 2048 (bench: 31.8% MFU) and ResNet-50's compute
path (29.2%).  Instead of a trace viewer (no display here), each
suspect is isolated by measuring jitted step-time DELTAS:

  lm.full            train step exactly as bench_lm runs it
  lm.trunk_only      same but loss = mean(hidden) — no head matmul, no CE
                     (delta = logits materialisation + CE + their bwd)
  lm.dot_attention   use_flash=False (delta = flash kernel vs XLA dot)
  lm.fused_loss      LMWithFusedLoss blockwise CE (delta = the cost of
                     materialising [B, T, V] logits, the suspected sink)
  lm.no_remat_check  remat=False asserted at model build
  lm.flops           XLA cost-analysis FLOPs vs analytic FLOPs — pallas
                     kernels are invisible to cost_analysis, so reported
                     MFU undercounts when flash is on; the analytic
                     number is the honest numerator
  resnet.bs{128,256} compute-path samples/sec at both batch sizes

Each timing: compile excluded, one fetch barrier settles the link, then
N steps with a value-fetch barrier at the end (the platform's
block_until_ready only acknowledges enqueue).  Prints one JSON dict.
"""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import _peak_for  # noqa: E402  (device-keyed peak FLOP/s)
from bench_guard import probe_pause  # noqa: E402


def _peak() -> float:
    return _peak_for(jax.devices()[0]) or 197e12


# the ONE profiled LM config — build() and the analytic-FLOPs formula
# must agree on these or mfu_analytic silently measures a different model
LM_B, LM_T, LM_V = 8, 2048, 32000
LM_H, LM_L, LM_F, LM_HEADS = 768, 12, 3072, 12


def _merge_partial(updates):
    """Checkpoint into PROFILE_LM_PARTIAL.json by merge, never
    overwrite: each timing costs minutes of tunnel round-trips and a
    wedge (or a --lm-only/--resnet-only run) must not erase the other
    section's hard-won partials."""
    merged = {}
    try:
        with open("PROFILE_LM_PARTIAL.json") as f:
            merged = json.load(f)
    except (OSError, json.JSONDecodeError):
        pass
    merged.update(updates)
    with open("PROFILE_LM_PARTIAL.json", "w") as f:
        json.dump(merged, f, indent=1, default=float)


def _time_steps(step, state, batch, n=10):
    state2, mets = step(state, batch)
    float(np.asarray(jax.tree.leaves(mets)[0]))     # settle + barrier
    t0 = time.perf_counter()
    for _ in range(n):
        state2, mets = step(state2, batch)
    float(np.asarray(jax.tree.leaves(mets)[0]))
    return (time.perf_counter() - t0) / n


def lm_ablations():
    import optax

    from analytics_zoo_tpu import init_orca_context, stop_orca_context
    from analytics_zoo_tpu.learn import Estimator
    from analytics_zoo_tpu.models import (
        TransformerLM, LM_PARTITION_RULES, lm_loss)
    from analytics_zoo_tpu.data.loader import make_global_batch

    B, T, V = LM_B, LM_T, LM_V
    rng = np.random.default_rng(0)
    data = {"tokens": rng.integers(0, V, (B * 2, T)).astype(np.int32)}
    out = {}

    def ckpt():
        _merge_partial({"lm": out})

    def build(loss_fn, use_flash=True, wrap=None):
        model = TransformerLM(vocab_size=V, hidden_size=LM_H,
                              num_layers=LM_L, num_heads=LM_HEADS,
                              intermediate_size=LM_F, max_position=T,
                              use_flash=use_flash)
        assert not model.remat, "bench runs remat=False; profile must too"
        est = Estimator.from_flax(
            model=wrap(model) if wrap else model, loss=loss_fn,
            optimizer=optax.adamw(1e-4),
            feature_cols=("tokens",), label_cols=("tokens",),
            partition_rules=LM_PARTITION_RULES)
        est.config.log_every_steps = 1000
        batch = {k: v[:B] for k, v in data.items()}
        est._ensure_state(batch)
        est._build_jits()
        g = make_global_batch(est.mesh, batch, est._data_sharding)
        return est, g

    def trunk_only_loss(logits, tokens):
        # kills the head+CE: grads still flow through the whole trunk.
        # NOTE logits here IS the head output — to skip the head matmul
        # we need the model-side ablation below; this variant only
        # removes CE.
        return jnp.mean(logits)

    # full step, exactly as bench_lm
    est, g = build(lm_loss)
    out["full_step_s"] = _time_steps(
        lambda s, b: est._jit_train_step(s, b), est.state, g)
    lowered = est._jit_train_step.lower(est.state, g)
    cost = lowered.compile().cost_analysis()
    if isinstance(cost, list):
        cost = cost[0]
    xla_flops = float(cost.get("flops", 0.0)) if cost else 0.0
    out["xla_cost_flops"] = xla_flops
    del lowered
    # analytic: matmul 6*P_mat*tokens (fwd+bwd) + flash fwd 4BT^2H/layer
    # + flash bwd ~2.5x fwd (recompute) ; head fwd+bwd 3x2BTHV
    p_mat = LM_L * (4 * LM_H * LM_H + 2 * LM_H * LM_F)  # qkvo + ffn weights
    toks = B * T
    mm = 6 * p_mat * toks
    att = LM_L * 4 * B * T * T * LM_H * 3.5
    head = 3 * 2 * B * T * LM_H * V
    out["analytic_flops"] = float(mm + att + head)
    out["mfu_xla"] = xla_flops / out["full_step_s"] / _peak()
    out["mfu_analytic"] = out["analytic_flops"] / out["full_step_s"] / _peak()

    ckpt()
    del est, g                      # free 111M params + adam state

    # CE removed (head matmul stays): delta isolates softmax-CE cost
    est2, g2 = build(trunk_only_loss)
    out["no_ce_step_s"] = _time_steps(
        lambda s, b: est2._jit_train_step(s, b), est2.state, g2)
    ckpt()
    del est2, g2

    # dot attention instead of the pallas flash kernel
    est3, g3 = build(lm_loss, use_flash=False)
    out["dot_attn_step_s"] = _time_steps(
        lambda s, b: est3._jit_train_step(s, b), est3.state, g3)
    ckpt()
    del est3, g3

    # fused blockwise loss (models/lm.py LMWithFusedLoss): [B,T,V] logits
    # never materialised — the HBM-bandwidth fix the full/no_ce delta
    # motivates; delta vs full_step_s is the end-to-end win
    from analytics_zoo_tpu.models import LMWithFusedLoss, fused_lm_loss

    est4, g4 = build(fused_lm_loss, wrap=lambda m: LMWithFusedLoss(lm=m))
    out["fused_loss_step_s"] = _time_steps(
        lambda s, b: est4._jit_train_step(s, b), est4.state, g4)
    out["mfu_analytic_fused"] = (
        out["analytic_flops"] / out["fused_loss_step_s"] / _peak())
    ckpt()
    del est4, g4

    out["ce_cost_s"] = out["full_step_s"] - out["no_ce_step_s"]
    out["flash_saving_s"] = out["dot_attn_step_s"] - out["full_step_s"]
    out["fused_loss_saving_s"] = (
        out["full_step_s"] - out["fused_loss_step_s"])
    out["tokens_per_sec"] = toks / out["full_step_s"]
    out["tokens_per_sec_fused"] = toks / out["fused_loss_step_s"]
    stop_orca_context()
    return out


def flash_block_ablation():
    """Standalone flash fwd+bwd at the bench's attention shape across
    block-size configs — the kernel's only tuning knobs.  Cheap (a few
    steps each); informs whether 512x512 (the default) is right for
    v5e's VMEM/MXU balance."""
    from analytics_zoo_tpu.ops import flash_attention

    B, T, H, D = LM_B, LM_T, LM_HEADS, LM_H // LM_HEADS
    key = jax.random.key(0)
    q = jax.random.normal(key, (B, T, H, D), jnp.bfloat16)
    k = jax.random.normal(key, (B, T, H, D), jnp.bfloat16)
    v = jax.random.normal(key, (B, T, H, D), jnp.bfloat16)
    out = {}
    for bq, bk in ((256, 256), (512, 512), (1024, 512), (512, 1024)):
        @jax.jit
        def step(q, k, v, bq=bq, bk=bk):
            def f(q, k, v):
                return flash_attention(q, k, v, causal=True,
                                       block_q=bq, block_k=bk).sum()
            l, grads = jax.value_and_grad(f, argnums=(0, 1, 2))(q, k, v)
            return l, grads

        try:
            l, _ = step(q, k, v)
            float(np.asarray(l))                    # compile + settle
            t0 = time.perf_counter()
            for _ in range(10):
                l, _ = step(q, k, v)
            float(np.asarray(l))
            out[f"bq{bq}_bk{bk}_s"] = (time.perf_counter() - t0) / 10
        except Exception as e:                      # VMEM overflow etc.
            out[f"bq{bq}_bk{bk}_s"] = f"failed: {type(e).__name__}"
    return out


def resnet_ablations():
    import flax.linen as nn
    import optax

    from analytics_zoo_tpu import init_orca_context, stop_orca_context
    from analytics_zoo_tpu.learn import Estimator
    from analytics_zoo_tpu.models import resnet50
    from analytics_zoo_tpu.data.loader import make_global_batch

    out = {}
    rng = np.random.default_rng(0)

    class TrainResNet50(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = False):
            x = x.astype(jnp.float32) / 255.0
            mean = jnp.asarray([0.485, 0.456, 0.406])
            std = jnp.asarray([0.229, 0.224, 0.225])
            return resnet50(1000)((x - mean) / std, train=train)

    est = None
    for bs in (128, 256):
        del est
        data = {
            "x": rng.integers(0, 256, (bs, 224, 224, 3)).astype(np.uint8),
            "y": rng.integers(0, 1000, bs).astype(np.int32),
        }
        est = Estimator.from_flax(
            model=TrainResNet50(), loss="sparse_categorical_crossentropy",
            optimizer=optax.sgd(0.1, momentum=0.9),
            feature_cols=("x",), label_cols=("y",))
        est.config.log_every_steps = 1000
        est._ensure_state(data)
        est._build_jits()
        g = make_global_batch(est.mesh, data, est._data_sharding)
        dt = _time_steps(lambda s, b: est._jit_train_step(s, b),
                         est.state, g, n=8)
        lowered = est._jit_train_step.lower(est.state, g)
        cost = lowered.compile().cost_analysis()
        if isinstance(cost, list):
            cost = cost[0]
        fl = float(cost.get("flops", 0.0)) if cost else 0.0
        out[f"bs{bs}_step_s"] = dt
        out[f"bs{bs}_samples_per_sec"] = bs / dt
        out[f"bs{bs}_mfu"] = fl / dt / _peak()
    return out


def main():
    from analytics_zoo_tpu import init_orca_context, stop_orca_context

    ckpt = _merge_partial

    res = {}
    if "--resnet-only" not in sys.argv:
        init_orca_context("local")
        res["lm"] = lm_ablations()      # stops its own context
        ckpt(res)
        res["flash_blocks"] = flash_block_ablation()
        ckpt(res)
    if "--lm-only" not in sys.argv:
        init_orca_context("local")
        res["resnet"] = resnet_ablations()
        stop_orca_context()
        ckpt(res)
    print(json.dumps(res, indent=1, default=float))


if __name__ == "__main__":
    with probe_pause():     # pause the probe loop when run directly
        main()

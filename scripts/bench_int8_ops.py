#!/usr/bin/env python
"""Op-level int8 benchmark: f32 vs weight-only-int8 vs on-MXU int8.

Times the three execution modes of the same Dense-stack forward (the
serving hot path) on the current JAX backend and prints one JSON line.
On TPU the int8_mxu mode rides the MXU's ~2x int8 throughput; on CPU
the numbers only establish that the path compiles and runs — record
them as structure, not as the speed claim (BASELINE.md "int8 serving").

Usage: python scripts/bench_int8_ops.py [--dim 4096] [--layers 8]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=4096)
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--iters", type=int, default=30)
    args = ap.parse_args()

    import flax.linen as nn

    from analytics_zoo_tpu.learn.quantize import (
        dequantize, int8_call, quantize_params)

    class Stack(nn.Module):
        @nn.compact
        def __call__(self, x):
            for _ in range(args.layers):
                x = nn.relu(nn.Dense(args.dim, use_bias=False)(x))
            return x

    model = Stack()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(args.batch, args.dim)).astype(np.float32)
    variables = model.init(jax.random.key(0), x[:1])
    qv, stats = quantize_params(variables, "int8")
    qv = jax.device_put(qv)
    variables = jax.device_put(variables)
    xd = jax.device_put(x)

    modes = {
        "f32": jax.jit(lambda v, a: model.apply(v, a)),
        "int8_weight_only": jax.jit(
            lambda v, a: model.apply(dequantize(v), a)),
        "int8_mxu": jax.jit(lambda v, a: int8_call(model, v, a)),
    }
    flops = 2 * args.batch * args.dim * args.dim * args.layers
    out = {"backend": jax.devices()[0].platform,
           "device_kind": jax.devices()[0].device_kind,
           "dim": args.dim, "layers": args.layers, "batch": args.batch,
           "compression": stats["compression"]}
    for name, fn in modes.items():
        v = qv if name != "f32" else variables
        r = fn(v, xd)
        float(jnp.sum(r))               # compile + real barrier
        t0 = time.perf_counter()
        for _ in range(args.iters):
            r = fn(v, xd)
        float(jnp.sum(r))
        dt = (time.perf_counter() - t0) / args.iters
        out[f"{name}_ms"] = round(dt * 1e3, 3)
        out[f"{name}_tflops"] = round(flops / dt / 1e12, 2)
    out["mxu_speedup_vs_f32"] = round(
        out["f32_ms"] / out["int8_mxu_ms"], 3)
    out["mxu_speedup_vs_weight_only"] = round(
        out["int8_weight_only_ms"] / out["int8_mxu_ms"], 3)
    print(json.dumps(out))


if __name__ == "__main__":
    main()

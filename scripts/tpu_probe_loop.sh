#!/bin/bash
# Continuous TPU-tunnel probe (VERDICT r4 ask #1a).
# Probes the tunneled TPU every ~120s with a hard timeout; appends one JSON
# line per attempt to tpu_probe_log.jsonl. On the first success it touches
# TPU_ALIVE so an opportunistic bench can be fired immediately.
LOG=/root/repo/tpu_probe_log.jsonl
FLAG=/root/repo/TPU_ALIVE
while true; do
  if [ -f /root/repo/BENCH_RUNNING ]; then
    sleep 120; continue   # don't contend for the grant mid-bench
  fi
  TS=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  RAW=$(timeout 120 python -c "
import jax, jax.numpy as jnp
d = jax.devices()
x = jnp.ones((256,256), jnp.bfloat16)
y = (x@x).sum()
print('PROBE_OK', d[0].platform, d[0].device_kind, float(y))
" 2>&1)
  RC=$?
  OUT=$(echo "$RAW" | grep PROBE_OK | head -1)
  if [ -n "$OUT" ]; then
    echo "{\"ts\": \"$TS\", \"ok\": true, \"out\": \"$OUT\"}" >> "$LOG"
    touch "$FLAG"
  else
    SAFE=$(echo "$RAW" | tail -1 | tr -d '"\\' | head -c 160)
    echo "{\"ts\": \"$TS\", \"ok\": false, \"rc\": $RC, \"out\": \"$SAFE\"}" >> "$LOG"
    rm -f "$FLAG"
  fi
  sleep 120
done

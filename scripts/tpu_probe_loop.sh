#!/bin/bash
# Continuous TPU-tunnel probe (VERDICT r4 ask #1a).
# Probes the tunneled TPU every ~120s with a hard timeout; appends one JSON
# line per attempt to tpu_probe_log.jsonl. On the first success it touches
# TPU_ALIVE so an opportunistic bench can be fired immediately.
# The probe itself is `bench_serving.py --probe` — the ONE shared
# implementation (also used by bench_serving's inter-scenario gate and
# bench_on_recovery.sh), so every caller agrees on what "alive" means.
LOG=/root/repo/tpu_probe_log.jsonl
FLAG=/root/repo/TPU_ALIVE
BFLAG=/root/repo/BENCH_RUNNING
while true; do
  if [ -f "$BFLAG" ]; then
    # the flag records its owner pid (bench_guard.py); a dead owner
    # (SIGKILLed bench) must not pause probing forever
    OWNER=$(cat "$BFLAG" 2>/dev/null)
    if [ -n "$OWNER" ] && kill -0 "$OWNER" 2>/dev/null; then
      sleep 120; continue   # live bench: don't contend for the grant
    fi
    # reclaim only if the content still matches what we judged stale —
    # a fresh bench may have republished the flag since we read it
    if [ "$(cat "$BFLAG" 2>/dev/null)" = "$OWNER" ]; then
      rm -f "$BFLAG"        # stale flag from a hard-killed bench
    fi
    sleep 5; continue       # re-evaluate next round
  fi
  TS=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  RAW=$(timeout 120 python /root/repo/bench_serving.py --probe 2>&1)
  RC=$?
  OUT=$(echo "$RAW" | grep PROBE_OK | head -1)
  if [ -n "$OUT" ]; then
    echo "{\"ts\": \"$TS\", \"ok\": true, \"out\": \"$OUT\"}" >> "$LOG"
    touch "$FLAG"
  else
    SAFE=$(echo "$RAW" | tail -1 | tr -d '"\\' | head -c 160)
    echo "{\"ts\": \"$TS\", \"ok\": false, \"rc\": $RC, \"out\": \"$SAFE\"}" >> "$LOG"
    rm -f "$FLAG"
  fi
  sleep 120
done

#!/usr/bin/env python
"""Elastic training supervisor — crash-and-restart orchestration.

SURVEY §5 failure model: JAX's coordination service detects a dead host
(lost heartbeat) and ABORTS the surviving processes; recovery is a fresh
incarnation of the whole process group restoring the last checkpoint.
This supervisor automates that loop on one machine (the single-box
multi-process doctrine; on a real pod, the platform's VM manager
respawns hosts and the same `fit(auto_resume=True)` contract applies):

    python scripts/run_elastic.py --nprocs 2 --max-restarts 3 -- \
        python train.py --my-args...

The training script needs NO resume logic: it calls
``init_orca_context("multihost")`` (coordinator/process-id arrive via
ZOO_COORDINATOR / ZOO_NUM_PROCESSES / ZOO_PROCESS_ID env, set here) and
``est.fit(..., auto_resume=True)`` with a ``checkpoint_dir`` — a
respawned group restores the last checkpoint and trains only the
remaining epochs.

Exit status: 0 when an incarnation finishes with every worker at rc=0;
non-zero when ``--max-restarts`` incarnations all failed.

Runbook: docs/architecture.md "Failure recovery".
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def run_group(cmd, nprocs: int, incarnation: int,
              extra_env: dict, timeout_s: float = 0) -> list:
    """One incarnation: spawn nprocs workers, wait for all, return
    returncodes.  On the FIRST failure the rest are terminated — they
    are either already aborting (coordination-service detection) or
    doomed to hang in the dead peer's collective.  ``timeout_s`` > 0
    converts an alive-but-hung incarnation (e.g. a deadlocked
    collective no process dies from) into the restart this supervisor
    exists to provide."""
    port = _free_port()
    t_start = time.monotonic()
    procs = []
    for pid in range(nprocs):
        env = dict(os.environ)
        env.update(extra_env)
        env["ZOO_COORDINATOR"] = f"localhost:{port}"
        env["ZOO_NUM_PROCESSES"] = str(nprocs)
        env["ZOO_PROCESS_ID"] = str(pid)
        env["ZOO_INCARNATION"] = str(incarnation)
        procs.append(subprocess.Popen(cmd, env=env))
    rcs = [None] * nprocs
    try:
        while any(rc is None for rc in rcs):
            for i, p in enumerate(procs):
                if rcs[i] is None:
                    rcs[i] = p.poll()
            bad = [i for i, rc in enumerate(rcs)
                   if rc is not None and rc != 0]
            if not bad and timeout_s > 0 and \
                    time.monotonic() - t_start > timeout_s:
                print(f"[run_elastic] incarnation timed out after "
                      f"{timeout_s:.0f}s (hung collective?) — killing "
                      f"the group", file=sys.stderr)
                for p in procs:
                    if p.poll() is None:
                        p.kill()
                for p in procs:
                    p.wait()
                return [p.poll() if p.poll() != 0 else -1 for p in procs]
            if bad:
                # give the coordination service a moment to abort the
                # survivors on its own (clean diagnostics beat SIGTERM),
                # then terminate whatever is left
                deadline = time.monotonic() + 30
                while time.monotonic() < deadline and \
                        any(p.poll() is None for p in procs):
                    time.sleep(0.5)
                for p in procs:
                    if p.poll() is None:
                        p.terminate()
                for p in procs:
                    try:
                        p.wait(timeout=15)
                    except subprocess.TimeoutExpired:
                        p.kill()
                return [p.poll() for p in procs]
            time.sleep(0.5)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return rcs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="restart-on-failure supervisor for multihost training")
    ap.add_argument("--nprocs", type=int, required=True)
    ap.add_argument("--max-restarts", type=int, default=3,
                    help="restarts AFTER the first attempt")
    ap.add_argument("--incarnation-timeout", type=float, default=0,
                    help="seconds before an alive-but-hung incarnation "
                         "is killed and counted as a failure (0 = no "
                         "timeout)")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="-- training command (python train.py ...)")
    args = ap.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd and args.cmd[0] == "--" else args.cmd
    if not cmd:
        ap.error("no training command given (append: -- python train.py)")
    for incarnation in range(args.max_restarts + 1):
        t0 = time.monotonic()
        rcs = run_group(cmd, args.nprocs, incarnation, {},
                        timeout_s=args.incarnation_timeout)
        if all(rc == 0 for rc in rcs):
            print(f"[run_elastic] incarnation {incarnation} succeeded "
                  f"({time.monotonic() - t0:.0f}s)")
            return 0
        print(f"[run_elastic] incarnation {incarnation} failed "
              f"(rcs={rcs}, {time.monotonic() - t0:.0f}s)"
              + ("; restarting from last checkpoint"
                 if incarnation < args.max_restarts else ""),
              file=sys.stderr)
    print(f"[run_elastic] giving up after {args.max_restarts + 1} "
          f"incarnations", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())

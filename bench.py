#!/usr/bin/env python
"""Benchmark: BERT-base fine-tune throughput through Estimator.fit()
(BASELINE.md config #3 — the north star), plus NCF (config #1).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.

Both models are measured through the REAL training path — ``fit()`` with
host batching, shuffling, and double-buffered device_put prefetch in the
measured window — not a bare pre-staged step function.  ``vs_baseline``
compares BERT against the same fit() loop on this host's CPU via a
subprocess (the reference stack is CPU-only — Xeon/MKL — so TPU-vs-host-CPU
is the honest capability-parity ratio measurable here; BASELINE.md: no
published reference numbers exist).  ``extra.bert_mfu`` is measured step
FLOPs (XLA cost analysis of the compiled train step) over the chip's peak.
"""

import json
import os
import subprocess
import sys

BERT_SEQ = 128
BERT_BATCH = 64
BERT_STEPS_PER_EPOCH = 20
NCF_BATCH = 32768
N_USERS, N_ITEMS = 6040, 3706      # MovieLens-1M cardinalities

# peak dense FLOP/s per chip (bf16 matmul) by device_kind prefix
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,      # v5e
    "TPU v5": 459e12,           # v5p
    "TPU v4": 275e12,
    "TPU v6": 918e12,           # v6e (Trillium)
}


def _peak_for(device) -> float:
    kind = getattr(device, "device_kind", "")
    for prefix, peak in sorted(PEAK_FLOPS.items(), key=lambda kv: -len(kv[0])):
        if kind.startswith(prefix):
            return peak
    return 0.0


def _warm_compile(est, data, batch_size):
    """Run ONE real train step to populate the jit cache without any D2H.

    The measured window must exclude compile AND stay in the tunnel's
    fast-transfer mode: this platform's device link permanently drops from
    ~1.7 GB/s to ~30 MB/s H2D after the first device->host fetch, so the
    warmup must not read anything back."""
    import jax
    import numpy as np

    from analytics_zoo_tpu.data.loader import make_global_batch

    batch = {k: np.asarray(v[:batch_size]) for k, v in data.items()}
    est._ensure_state(batch)
    est._build_jits()
    g = make_global_batch(est.mesh, batch, est._data_sharding)
    state, _ = est._jit_train_step(est.state, g)
    jax.block_until_ready(state.params)     # wait only — no data fetched
    est.state = state


def _fit_throughput(est, data, batch_size, epochs=1):
    """samples/sec through fit() — host batching, shuffling and H2D
    prefetch inside the measured window; compile excluded via warmup.
    fit's per-epoch timer stops before its own metric fetch, so epoch 1
    runs entirely in fast-transfer mode."""
    _warm_compile(est, data, batch_size)
    hist = est.fit(data, epochs=epochs, batch_size=batch_size)
    return max(h["samples_per_sec"] for h in hist)


def bench_bert(platform: str):
    if platform == "cpu":
        # env JAX_PLATFORMS=cpu does not survive this image's
        # sitecustomize jax import; the config override does
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import numpy as np
    import optax

    from analytics_zoo_tpu import init_orca_context, stop_orca_context
    from analytics_zoo_tpu.learn import Estimator
    from analytics_zoo_tpu.models import (
        BERT, BERTForSequenceClassification, BERT_PARTITION_RULES)

    init_orca_context("local")
    model = BERTForSequenceClassification(
        num_classes=2, bert=BERT())     # real BERT-base config (~110M)
    est = Estimator.from_flax(
        model=model, loss="sparse_categorical_crossentropy",
        optimizer=optax.adamw(2e-5),
        feature_cols=("input_ids",), label_cols=("label",),
        partition_rules=BERT_PARTITION_RULES)
    est.config.log_every_steps = 1000   # keep host syncs out of the window
    rng = np.random.default_rng(0)
    n = BERT_BATCH * BERT_STEPS_PER_EPOCH
    data = {
        "input_ids": rng.integers(0, 30522, (n, BERT_SEQ)).astype(np.int32),
        "label": rng.integers(0, 2, n).astype(np.int32),
    }
    if platform == "cpu":
        data = {k: v[:BERT_BATCH * 2] for k, v in data.items()}
    sps = _fit_throughput(est, data, BERT_BATCH)
    mfu = None
    if platform != "cpu":
        try:
            flops = _step_flops(est, data)
            step_time = BERT_BATCH / sps
            peak = _peak_for(jax.devices()[0])
            if flops and peak:
                mfu = round(flops / step_time / peak, 4)
        except Exception as e:
            print(f"mfu estimate failed: {e!r}", file=sys.stderr)
    stop_orca_context()
    return sps, mfu


def _step_flops(est, data):
    """FLOPs of one compiled train step (XLA cost analysis)."""
    import numpy as np

    from analytics_zoo_tpu.data.loader import make_global_batch

    batch = {k: np.asarray(v[:BERT_BATCH]) for k, v in data.items()}
    gbatch = make_global_batch(est.mesh, batch, est._data_sharding)
    lowered = est._jit_train_step.lower(est.state, gbatch)
    cost = lowered.compile().cost_analysis()
    if isinstance(cost, list):
        cost = cost[0]
    return float(cost.get("flops", 0.0)) if cost else 0.0


def bench_resnet50():
    """ResNet-50 ImageNet-shape training throughput (config #2)."""
    import numpy as np
    import optax

    from analytics_zoo_tpu import init_orca_context, stop_orca_context
    from analytics_zoo_tpu.learn import Estimator
    from analytics_zoo_tpu.models import resnet50

    init_orca_context("local")
    rng = np.random.default_rng(0)
    bs, steps = 128, 10
    n = bs * steps
    data = {
        "x": rng.normal(size=(n, 224, 224, 3)).astype(np.float32),
        "y": rng.integers(0, 1000, n).astype(np.int32),
    }
    est = Estimator.from_flax(
        model=resnet50(1000), loss="sparse_categorical_crossentropy",
        optimizer=optax.sgd(0.1, momentum=0.9),
        feature_cols=("x",), label_cols=("y",))
    est.config.log_every_steps = 1000
    sps = _fit_throughput(est, data, bs)
    stop_orca_context()
    return sps


def bench_ncf():
    import numpy as np
    import optax

    from analytics_zoo_tpu import init_orca_context, stop_orca_context
    from analytics_zoo_tpu.learn import Estimator
    from analytics_zoo_tpu.models import NeuralCF, NCF_PARTITION_RULES

    init_orca_context("local")
    rng = np.random.default_rng(0)
    n = NCF_BATCH * 8
    data = {
        "user": rng.integers(1, N_USERS + 1, n).astype(np.int32),
        "item": rng.integers(1, N_ITEMS + 1, n).astype(np.int32),
        "label": rng.integers(0, 2, n).astype(np.int32),
    }
    est = Estimator.from_flax(
        model=NeuralCF(user_count=N_USERS, item_count=N_ITEMS,
                       user_embed=64, item_embed=64, mf_embed=64,
                       hidden_layers=(128, 64, 32)),
        loss="sparse_categorical_crossentropy",
        optimizer=optax.adam(1e-3),
        feature_cols=("user", "item"), label_cols=("label",),
        partition_rules=NCF_PARTITION_RULES)
    est.config.log_every_steps = 1000
    sps = _fit_throughput(est, data, NCF_BATCH)
    stop_orca_context()
    return sps


def main():
    if "--cpu-baseline" in sys.argv:
        sps, _ = bench_bert("cpu")
        print(json.dumps({"cpu_samples_per_sec": sps}))
        return
    bert_sps, bert_mfu = bench_bert("tpu")
    ncf_sps = bench_ncf()
    try:
        resnet_sps = bench_resnet50()
    except Exception as e:
        print(f"resnet bench failed: {e!r}", file=sys.stderr)
        resnet_sps = None
    cpu_sps = None
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--cpu-baseline"],
            capture_output=True, text=True, timeout=1800,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        for line in out.stdout.splitlines():
            if line.startswith("{"):
                cpu_sps = json.loads(line)["cpu_samples_per_sec"]
    except Exception as e:
        print(f"cpu baseline failed: {e!r}", file=sys.stderr)
    # vs_baseline is null (not 1.0) when the CPU baseline could not be
    # measured — 1.0 would read as "exactly at parity".
    print(json.dumps({
        "metric": "bert_base_ft_samples_per_sec_per_chip",
        "value": round(bert_sps, 1),
        "unit": "samples/sec",
        "vs_baseline": round(bert_sps / cpu_sps, 2) if cpu_sps else None,
        "extra": {
            "bert_mfu": bert_mfu,
            "bert_seq_len": BERT_SEQ,
            "bert_global_batch": BERT_BATCH,
            "measured_through": "Estimator.fit (host batching + prefetch)",
            "ncf_train_samples_per_sec_per_chip": round(ncf_sps, 1),
            "resnet50_train_samples_per_sec_per_chip":
                round(resnet_sps, 1) if resnet_sps else None,
        },
    }))


if __name__ == "__main__":
    main()

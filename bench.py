#!/usr/bin/env python
"""Benchmark: BERT-base fine-tune throughput through Estimator.fit()
(BASELINE.md config #3 — the north star), plus NCF (config #1) and
ResNet-50 (config #2).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.

All models are measured through the REAL training path — ``fit()`` with
host batching, shuffling, and double-buffered device_put prefetch in the
measured window — not a bare pre-staged step function.  Every model bench
runs in its OWN subprocess: this platform's device link permanently drops
from ~1.7 GB/s to ~30 MB/s H2D after the first device->host fetch, so one
bench's metric fetches must not poison the next bench's input pipeline
(round-2 ResNet measured exactly that artifact).  ``vs_baseline`` compares
BERT against the same fit() loop on this host's CPU via a subprocess (the
reference stack is CPU-only — Xeon/MKL — so TPU-vs-host-CPU is the honest
capability-parity ratio measurable here; BASELINE.md: no published
reference numbers exist).  ``extra.*_mfu`` is measured step FLOPs (XLA
cost analysis of the compiled train step) over the chip's peak.
"""

import json
import os
import subprocess
import sys
import time

BERT_SEQ = 128
BERT_BATCH = 64
BERT_STEPS_PER_EPOCH = 20
NCF_BATCH = 32768
N_USERS, N_ITEMS = 6040, 3706      # MovieLens-1M cardinalities

# peak dense FLOP/s per chip (bf16 matmul) by device_kind prefix
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,      # v5e
    "TPU v5": 459e12,           # v5p
    "TPU v4": 275e12,
    "TPU v6": 918e12,           # v6e (Trillium)
}


def _peak_for(device) -> float:
    kind = getattr(device, "device_kind", "")
    for prefix, peak in sorted(PEAK_FLOPS.items(), key=lambda kv: -len(kv[0])):
        if kind.startswith(prefix):
            return peak
    return 0.0


def _warm_compile(est, data, batch_size):
    """Populate the jit cache AND settle the device link into its
    steady-state mode before the measured window.

    Platform facts this encodes (measured, round 3): on the tunneled
    device, (a) ``jax.block_until_ready`` acknowledges enqueue, not
    completion — only a value fetch is a real barrier; (b) the FIRST
    device->host fetch of a process pays a one-time multi-second link
    reconfiguration and drops H2D from ~1.6 GB/s to ~55 MB/s permanently.
    An honest steady-state measurement therefore takes that fetch BEFORE
    the window — every epoch of a real training run after the first
    metric read lives in this regime."""
    import numpy as np

    from analytics_zoo_tpu.data.loader import make_global_batch

    batch = {k: np.asarray(v[:batch_size]) for k, v in data.items()}
    est._ensure_state(batch)
    est._build_jits()
    g = make_global_batch(est.mesh, batch, est._data_sharding)
    state, mets = est._jit_train_step(est.state, g)
    float(np.asarray(mets["loss"]))     # real barrier + link settle
    est.state = state


def _fit_throughput(est, data, batch_size, epochs=2):
    """Steady-state samples/sec through fit() — host batching, shuffling,
    H2D prefetch and the epoch metric fetch all inside the measured
    window; compile and the one-time link reconfiguration excluded via
    warmup.  fit's epoch barrier is a real value fetch (estimator.py)."""
    _warm_compile(est, data, batch_size)
    hist = est.fit(data, epochs=epochs, batch_size=batch_size)
    return max(h["samples_per_sec"] for h in hist)


def _compute_throughput(est, data, batch_size, steps=20, n_buf=4):
    """Pure per-chip compute rate: batches pre-staged in HBM, no H2D in
    the loop, real fetch barrier at the end.  This is what the chip
    sustains when the input pipeline keeps up — the number to compare
    against MFU/peak (the tunnel's ~55 MB/s H2D cap is a harness
    artifact real TPU-VM hosts don't have)."""
    import numpy as np

    from analytics_zoo_tpu.data.loader import make_global_batch

    bufs = []
    for i in range(n_buf):
        lo = (i * batch_size) % (len(next(iter(data.values()))) - batch_size)
        bufs.append(make_global_batch(
            est.mesh, {k: np.asarray(v[lo:lo + batch_size])
                       for k, v in data.items()}, est._data_sharding))
    # drain any queued work so the window starts clean
    state, mets = est._jit_train_step(est.state, bufs[0])
    est.state = state
    float(np.asarray(mets["loss"]))
    t0 = time.perf_counter()
    for i in range(steps):
        est.state, mets = est._jit_train_step(est.state, bufs[i % n_buf])
    float(np.asarray(mets["loss"]))     # real completion barrier
    dt = time.perf_counter() - t0
    return steps * batch_size / dt


def _mfu(est, data, batch_size, sps, flops=None):
    """Measured FLOP/s over chip peak for the compiled train step.  Pass
    `flops` when calling more than once — _step_flops re-lowers and
    re-compiles the whole train step each time."""
    import jax

    try:
        if flops is None:
            flops = _step_flops(est, data, batch_size)
        peak = _peak_for(jax.devices()[0])
        if flops and peak and sps:
            return round(flops / (batch_size / sps) / peak, 4)
    except Exception as e:
        print(f"mfu estimate failed: {e!r}", file=sys.stderr)
    return None


def _step_flops(est, data, batch_size):
    """FLOPs of one compiled train step (XLA cost analysis)."""
    import numpy as np

    from analytics_zoo_tpu.data.loader import make_global_batch

    batch = {k: np.asarray(v[:batch_size]) for k, v in data.items()}
    gbatch = make_global_batch(est.mesh, batch, est._data_sharding)
    lowered = est._jit_train_step.lower(est.state, gbatch)
    cost = lowered.compile().cost_analysis()
    if isinstance(cost, list):
        cost = cost[0]
    return float(cost.get("flops", 0.0)) if cost else 0.0


def _h2d_rate_mb_s(n_mb: int = 64) -> float:
    """Current host->device transfer rate (diagnoses the degraded-link
    mode; call AFTER the measured window — it is harmless there)."""
    import jax
    import numpy as np

    buf = np.ones((n_mb << 20) // 4, np.float32)
    a = jax.device_put(buf)
    float(np.asarray(a[0]))             # warm path; real completion barrier
    t0 = time.perf_counter()
    a = jax.device_put(buf)
    # block_until_ready only acknowledges enqueue on this platform — a
    # tiny value fetch is the real barrier (adds ~one round-trip of noise)
    float(np.asarray(a[0]))
    return n_mb / (time.perf_counter() - t0)


def bench_bert(platform: str):
    if platform == "cpu":
        # env JAX_PLATFORMS=cpu does not survive this image's
        # sitecustomize jax import; the config override does
        import jax

        jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import optax

    from analytics_zoo_tpu import init_orca_context, stop_orca_context
    from analytics_zoo_tpu.learn import Estimator
    from analytics_zoo_tpu.models import (
        BERT, BERTForSequenceClassification, BERT_PARTITION_RULES)

    init_orca_context("local")
    model = BERTForSequenceClassification(
        num_classes=2, bert=BERT())     # real BERT-base config (~110M)
    est = Estimator.from_flax(
        model=model, loss="sparse_categorical_crossentropy",
        optimizer=optax.adamw(2e-5),
        feature_cols=("input_ids",), label_cols=("label",),
        partition_rules=BERT_PARTITION_RULES)
    est.config.log_every_steps = 1000   # keep host syncs out of the window
    rng = np.random.default_rng(0)
    n = BERT_BATCH * BERT_STEPS_PER_EPOCH
    data = {
        "input_ids": rng.integers(0, 30522, (n, BERT_SEQ)).astype(np.int32),
        "label": rng.integers(0, 2, n).astype(np.int32),
    }
    if platform == "cpu":
        data = {k: v[:BERT_BATCH * 2] for k, v in data.items()}
        sps = _fit_throughput(est, data, BERT_BATCH, epochs=1)
        stop_orca_context()
        return {"samples_per_sec": sps, "mfu": None}
    sps = _fit_throughput(est, data, BERT_BATCH)
    comp = _compute_throughput(est, data, BERT_BATCH)
    flops = _step_flops(est, data, BERT_BATCH)
    out = {"samples_per_sec": sps,
           "compute_samples_per_sec": comp,
           "mfu": _mfu(est, data, BERT_BATCH, comp, flops),
           "fit_mfu": _mfu(est, data, BERT_BATCH, sps, flops)}
    stop_orca_context()
    return out


def bench_resnet50():
    """ResNet-50 ImageNet-shape training throughput (config #2).

    Must run in a FRESH process: its 77 MB/step input stream is the most
    transfer-sensitive bench, and any earlier D2H fetch leaves the link in
    the ~30 MB/s degraded mode (round-2 artifact).  extra reports the
    post-run H2D rate so a transfer-bound number is identifiable."""
    import numpy as np
    import optax

    from analytics_zoo_tpu import init_orca_context, stop_orca_context
    from analytics_zoo_tpu.learn import Estimator
    from analytics_zoo_tpu.models import resnet50

    import flax.linen as nn
    import jax.numpy as jnp

    init_orca_context("local")
    rng = np.random.default_rng(0)
    bs, steps = 128, 10
    n = bs * steps
    # uint8 pixels over the wire, normalisation on device — the
    # TPU-idiomatic ImageNet input pipeline (decoded JPEGs ARE uint8);
    # shipping f32 would 4x the H2D bytes for zero information
    data = {
        "x": rng.integers(0, 256, (n, 224, 224, 3)).astype(np.uint8),
        "y": rng.integers(0, 1000, n).astype(np.int32),
    }

    class TrainResNet50(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = False):
            x = x.astype(jnp.float32) / 255.0
            mean = jnp.asarray([0.485, 0.456, 0.406])
            std = jnp.asarray([0.229, 0.224, 0.225])
            return resnet50(1000)((x - mean) / std, train=train)

    est = Estimator.from_flax(
        model=TrainResNet50(), loss="sparse_categorical_crossentropy",
        optimizer=optax.sgd(0.1, momentum=0.9),
        feature_cols=("x",), label_cols=("y",))
    est.config.log_every_steps = 1000
    sps = _fit_throughput(est, data, bs)
    comp = _compute_throughput(est, data, bs, steps=10, n_buf=2)
    h2d = _h2d_rate_mb_s()
    stop_orca_context()
    # 128x224x224x3 uint8 = ~18 MB/step; the fit path is transfer-bound
    # when the steady-state H2D rate caps samples/sec below compute
    step_mb = bs * 224 * 224 * 3 / 2**20
    # the arithmetic that must travel WITH the number (VERDICT r3 weak
    # #3): at ~0.144 MB/sample uint8, the measured H2D rate bounds the
    # fit path at h2d/0.144 samples/s no matter how fast compute is
    per_sample_mb = step_mb / bs
    return {"samples_per_sec": sps,
            "compute_samples_per_sec": comp,
            "mfu": _mfu(est, data, bs, comp),
            "transfer_bound": sps < 0.8 * comp,
            "h2d_rate_mb_s": round(h2d, 1),
            "input_mb_per_step": round(step_mb, 1),
            "link_ceiling_samples_per_sec": round(h2d / per_sample_mb, 1),
            "link_ceiling_note": (
                "fit-path samples/s is capped at h2d_rate / "
                f"{per_sample_mb:.3f} MB-per-sample regardless of "
                "compute; compare samples_per_sec against this ceiling "
                "before reading it as a compute result")}


def bench_ncf():
    import numpy as np
    import optax

    from analytics_zoo_tpu import init_orca_context, stop_orca_context
    from analytics_zoo_tpu.learn import Estimator
    from analytics_zoo_tpu.models import NeuralCF, NCF_PARTITION_RULES

    init_orca_context("local")
    rng = np.random.default_rng(0)
    n = NCF_BATCH * 8
    data = {
        "user": rng.integers(1, N_USERS + 1, n).astype(np.int32),
        "item": rng.integers(1, N_ITEMS + 1, n).astype(np.int32),
        "label": rng.integers(0, 2, n).astype(np.int32),
    }
    est = Estimator.from_flax(
        model=NeuralCF(user_count=N_USERS, item_count=N_ITEMS,
                       user_embed=64, item_embed=64, mf_embed=64,
                       hidden_layers=(128, 64, 32)),
        loss="sparse_categorical_crossentropy",
        optimizer=optax.adam(1e-3),
        feature_cols=("user", "item"), label_cols=("label",),
        partition_rules=NCF_PARTITION_RULES)
    est.config.log_every_steps = 1000
    sps = _fit_throughput(est, data, NCF_BATCH, epochs=2)
    comp = _compute_throughput(est, data, NCF_BATCH)
    stop_orca_context()
    return {"samples_per_sec": sps, "compute_samples_per_sec": comp}


def bench_wide_and_deep():
    """Wide&Deep recommendation throughput (config #5)."""
    import numpy as np
    import optax

    from analytics_zoo_tpu import init_orca_context, stop_orca_context
    from analytics_zoo_tpu.learn import Estimator
    from analytics_zoo_tpu.models import (
        ColumnFeatureInfo, WideAndDeep, WND_PARTITION_RULES)

    init_orca_context("local")
    info = ColumnFeatureInfo(
        wide_base_cols=("b0", "b1"), wide_base_dims=(100, 100),
        indicator_cols=("gender",), indicator_dims=(3,),
        embed_cols=("user", "item"), embed_in_dims=(6040, 3706),
        embed_out_dims=(64, 64), continuous_cols=("age",))
    rng = np.random.default_rng(0)
    bs = 16384
    n = bs * 8
    data = {
        "wide_cols": np.stack([rng.integers(1, 101, n),
                               rng.integers(101, 201, n)], 1).astype(np.int32),
        "indicator_cols": rng.integers(0, 3, (n, 1)).astype(np.int32),
        "embed_cols": np.stack([rng.integers(0, 6040, n),
                                rng.integers(0, 3706, n)], 1).astype(np.int32),
        "continuous_cols": rng.normal(size=(n, 1)).astype(np.float32),
        "label": rng.integers(0, 2, n).astype(np.int32),
    }
    model = WideAndDeep(class_num=2, column_info=info)
    est = Estimator.from_flax(
        model=model, loss="sparse_categorical_crossentropy",
        optimizer=optax.adam(1e-3),
        feature_cols=tuple(model.feature_groups()), label_cols=("label",),
        partition_rules=WND_PARTITION_RULES)
    est.config.log_every_steps = 1000
    sps = _fit_throughput(est, data, bs, epochs=2)
    comp = _compute_throughput(est, data, bs)
    stop_orca_context()
    return {"samples_per_sec": sps, "compute_samples_per_sec": comp}


def bench_forecast():
    """Zouwu LSTM forecaster throughput (config #4) through the
    Forecaster.fit surface on NYC-taxi-shaped windows."""
    import numpy as np

    from analytics_zoo_tpu import init_orca_context, stop_orca_context
    from analytics_zoo_tpu.zouwu.forecaster import LSTMForecaster
    from analytics_zoo_tpu.zouwu.preprocessing import roll

    init_orca_context("local")
    from analytics_zoo_tpu.zouwu.preprocessing import StandardScaler

    t = np.arange(80_000, dtype=np.float32)
    series = (10 + 3 * np.sin(2 * np.pi * t / 48)
              + 0.3 * np.random.default_rng(0).normal(size=t.size))
    series = StandardScaler().fit_transform(series[:, None].astype(np.float32))
    x, y = roll(series, 96, 1)
    fc = LSTMForecaster(target_dim=1, feature_dim=1, lstm_units=(32, 16))
    fc.estimator.config.log_every_steps = 1000   # no mid-window fetches
    fc.fit(x[:1024], y[:1024], epochs=1, batch_size=512)   # warm compile
    # settle the device link (first fetch) before the measured window
    fc.evaluate(x[:512], y[:512])
    last = fc.fit(x, y, epochs=1, batch_size=512)   # returns last-epoch stats
    sps = last["samples_per_sec"]
    mse = fc.evaluate(x[-2048:], y[-2048:])["mse"]
    stop_orca_context()
    return {"samples_per_sec": sps, "holdout_mse": round(float(mse), 4)}


def bench_lm():
    """Beyond-parity extension: 111M-param causal LM at seq 2048 through
    fit() — long-context throughput via the Pallas flash path (the
    reference has no generative-LM capability at all)."""
    import numpy as np
    import optax

    from analytics_zoo_tpu import init_orca_context, stop_orca_context
    from analytics_zoo_tpu.learn import Estimator
    from analytics_zoo_tpu.models import (
        TransformerLM, LM_PARTITION_RULES, LMWithFusedLoss, lm_loss,
        fused_lm_loss)

    init_orca_context("local")
    rng = np.random.default_rng(0)
    B, T = 8, 2048
    data = {"tokens": rng.integers(0, 32000, (B * 8, T)).astype(np.int32)}
    model = TransformerLM(vocab_size=32000, hidden_size=768, num_layers=12,
                          num_heads=12, intermediate_size=3072,
                          max_position=T)

    # plain path: full [B, T, V] logits materialised, then CE
    est = Estimator.from_flax(
        model=model, loss=lm_loss, optimizer=optax.adamw(1e-4),
        feature_cols=("tokens",), label_cols=("tokens",),
        partition_rules=LM_PARTITION_RULES)
    est.config.log_every_steps = 1000
    sps_plain = _fit_throughput(est, data, B)
    # model-math FLOPs from the plain step; the fused step does the SAME
    # model math (its extra head-matmul recompute is a hardware cost, not
    # model FLOPs, so sharing this numerator keeps MFU comparable)
    flops = _step_flops(est, data, B)

    # fused blockwise loss: logits never materialised (models/lm.py
    # LMWithFusedLoss) — trades one head-matmul recompute in backward for
    # several full HBM passes over a 2.1 GB logits tensor.  Best-effort:
    # a fused-path failure must not discard the plain number already
    # paid for in scarce tunnel time.
    sps_fused = None
    try:
        est_f = Estimator.from_flax(
            model=LMWithFusedLoss(lm=model), loss=fused_lm_loss,
            optimizer=optax.adamw(1e-4),
            feature_cols=("tokens",), label_cols=("tokens",),
            partition_rules=LM_PARTITION_RULES)
        est_f.config.log_every_steps = 1000
        sps_fused = _fit_throughput(est_f, data, B)
        est = est_f
    except Exception as e:
        print(f"fused-loss LM path failed ({e!r}); "
              f"keeping plain-loss numbers", file=sys.stderr)

    sps = max(sps_plain, sps_fused or 0.0)
    out = {"samples_per_sec": sps,
           "tokens_per_sec": sps * T,
           "seq_len": T,
           "mfu": _mfu(est, data, B, sps, flops),
           "samples_per_sec_plain_loss": sps_plain,
           "samples_per_sec_fused_loss": sps_fused,
           "mfu_plain_loss": _mfu(est, data, B, sps_plain, flops)}
    stop_orca_context()
    return out


BENCHES = {
    "bert": lambda: bench_bert("tpu"),
    "ncf": bench_ncf,
    "resnet": bench_resnet50,
    "wnd": bench_wide_and_deep,
    "forecast": bench_forecast,
    "lm": bench_lm,
    "cpu-baseline": lambda: bench_bert("cpu"),
}


def _run_sub(name: str, timeout: int = 1800):
    """One bench in its own process — a pristine device link each time."""
    env = dict(os.environ)
    if name == "cpu-baseline":
        env["JAX_PLATFORMS"] = "cpu"
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--bench", name],
            capture_output=True, text=True, timeout=timeout, env=env)
        for line in out.stdout.splitlines():
            if line.startswith("{"):
                return json.loads(line)
        print(f"{name} bench produced no JSON:\n{out.stderr[-2000:]}",
              file=sys.stderr)
    except Exception as e:
        print(f"{name} bench failed: {e!r}", file=sys.stderr)
    return None


def _device_preflight(timeout: int = 300, attempts: int = 2):
    """The tunneled TPU can wedge hard (jax.devices() blocks forever — a
    lost remote grant; observed in round 3, with recovery windows after
    remote cleanup).  Probe in a subprocess with a timeout, retrying
    once (grant handoff after a previous holder exits can itself take
    minutes), so a dead device costs minutes and a clear message, not
    len(BENCHES) x 1800 s of silent hanging.  Returns (ok, reason); a
    non-TPU device kind also fails — a silent CPU fallback would
    otherwise produce fast, wrong 'TPU' numbers."""
    code = ("import jax; d = jax.devices(); "
            "import jax.numpy as jnp; float(jnp.ones(2).sum()); "
            "print('kind:', d[0].device_kind)")
    out = None
    for i in range(max(1, attempts)):
        try:
            out = subprocess.run([sys.executable, "-c", code],
                                 capture_output=True, text=True,
                                 timeout=timeout)
            break
        except subprocess.TimeoutExpired:
            out = None
    if out is None:
        return False, (f"jax.devices() unresponsive in {attempts} x "
                       f"{timeout}s probes (wedged device tunnel); no "
                       "benchmarks ran")
    if out.returncode != 0:
        return False, ("device probe crashed (rc="
                       f"{out.returncode}): {out.stderr[-500:]}")
    kind = next((l.split("kind:", 1)[1].strip()
                 for l in out.stdout.splitlines() if "kind:" in l), "")
    if not kind.startswith("TPU"):
        return False, (f"probe found device kind {kind!r}, not a TPU — "
                       "refusing to record CPU-fallback numbers as "
                       "chip throughput")
    return True, kind


def main():
    # Pause any background probe loop (scripts/tpu_probe_loop.sh) for
    # the whole run: probe processes contending for the single device
    # grant mid-bench corrupt timings — and this must hold when the
    # DRIVER invokes bench.py directly, not just under
    # scripts/bench_on_recovery.sh.  bench_guard owns the protocol
    # (atomic acquire, SIGTERM unwind, stale-owner cleanup).
    from bench_guard import probe_pause

    with probe_pause():
        _main_inner()


def _main_inner():
    if "--bench" in sys.argv:
        name = sys.argv[sys.argv.index("--bench") + 1]
        print(json.dumps(BENCHES[name]()))
        return
    if "--cpu-baseline" in sys.argv:      # CPU-only: no TPU preflight
        res = bench_bert("cpu")
        res["cpu_samples_per_sec"] = res["samples_per_sec"]  # old key
        print(json.dumps(res))
        return
    ok, reason = _device_preflight()
    if not ok:
        # A wedged/absent device is an ENVIRONMENT condition, not a
        # bench failure: emit a structured "skipped" record and exit 0
        # so the driver records a clean skip instead of rc=1 with a
        # null metric (BENCH_r05 did exactly that).
        print(json.dumps({
            "metric": "bert_base_ft_samples_per_sec_per_chip",
            "value": None, "unit": "samples/sec", "vs_baseline": None,
            "status": "skipped",
            "extra": {"skipped": f"device preflight failed: {reason}"}}))
        return
    # Priority order (VERDICT r4 ask #1b): a mid-run re-wedge keeps what
    # was won.  After any bench FAILURE, a cheap re-probe decides between
    # "that bench broke" (continue) and "the tunnel wedged" (bail with
    # partial results now — every remaining bench would burn its full
    # subprocess timeout against a dead device).  Partial results are
    # checkpointed to BENCH_PARTIAL.json after every bench.
    results = {}
    wedged_after = None
    for name in ("bert", "ncf", "resnet", "wnd", "forecast", "lm",
                 "cpu-baseline"):
        results[name] = _run_sub(name)
        try:
            with open(os.path.join(os.path.dirname(
                    os.path.abspath(__file__)), "BENCH_PARTIAL.json"),
                    "w") as f:
                json.dump({k: v for k, v in results.items()}, f)
        except OSError:
            pass
        if results[name] is None and name != "cpu-baseline":
            ok2, _ = _device_preflight(timeout=120, attempts=1)
            if not ok2:
                wedged_after = name
                break
    bert, ncf, resnet = (results.get(k) for k in ("bert", "ncf", "resnet"))
    wnd, fcst, lm = (results.get(k) for k in ("wnd", "forecast", "lm"))
    cpu = results.get("cpu-baseline")
    if cpu is None and wedged_after is not None:
        # the CPU baseline needs no TPU; still collect it for the ratio
        cpu = _run_sub("cpu-baseline")
        results["cpu-baseline"] = cpu
        try:
            with open(os.path.join(os.path.dirname(
                    os.path.abspath(__file__)), "BENCH_PARTIAL.json"),
                    "w") as f:
                json.dump(results, f)
        except OSError:
            pass
    bert_sps = bert["samples_per_sec"] if bert else None
    cpu_sps = cpu["samples_per_sec"] if cpu else None
    # vs_baseline is null (not 1.0) when the CPU baseline could not be
    # measured — 1.0 would read as "exactly at parity".  The CPU run is
    # short (2 batches), so the ratio is an order-of-magnitude figure:
    # quote it to 2 significant digits, not 4.
    print(json.dumps({
        "metric": "bert_base_ft_samples_per_sec_per_chip",
        "value": round(bert_sps, 1) if bert_sps else None,
        "unit": "samples/sec",
        "vs_baseline": float(f"{bert_sps / cpu_sps:.2g}")
        if bert_sps and cpu_sps else None,
        "extra": {
            "bert_mfu": bert and bert.get("mfu"),
            "bert_fit_mfu": bert and bert.get("fit_mfu"),
            "bert_compute_samples_per_sec":
                bert and round(bert["compute_samples_per_sec"], 1),
            "bert_seq_len": BERT_SEQ,
            "bert_global_batch": BERT_BATCH,
            "measured_through":
                "Estimator.fit steady state (host batching + prefetch + "
                "epoch metric fetch); *_compute_* = pre-staged batches, "
                "value-fetch barrier; mfu uses the compute rate",
            "isolation": "each model benched in its own subprocess "
                         "(pristine device link)",
            "ncf_train_samples_per_sec_per_chip":
                ncf and round(ncf["samples_per_sec"], 1),
            "ncf_compute_samples_per_sec":
                ncf and round(ncf["compute_samples_per_sec"], 1),
            "fit_vs_compute_note":
                "this harness's tunneled device serialises H2D with "
                "compute (measured: interleaved puts+compute = sum, not "
                "max), so the fit path's floor is transfer + compute per "
                "step; the threaded prefetch overlaps them on real "
                "TPU-VM hosts",
            "resnet50_train_samples_per_sec_per_chip":
                resnet and round(resnet["samples_per_sec"], 1),
            "resnet50_compute_samples_per_sec":
                resnet and round(resnet["compute_samples_per_sec"], 1),
            "resnet50_mfu": resnet and resnet.get("mfu"),
            "resnet50_transfer_bound": resnet
                and resnet.get("transfer_bound"),
            "resnet50_h2d_rate_mb_s": resnet
                and resnet.get("h2d_rate_mb_s"),
            "resnet50_input_mb_per_step":
                resnet and resnet.get("input_mb_per_step"),
            "wide_and_deep_train_samples_per_sec_per_chip":
                wnd and round(wnd["samples_per_sec"], 1),
            "wide_and_deep_compute_samples_per_sec":
                wnd and round(wnd["compute_samples_per_sec"], 1),
            "forecaster_train_samples_per_sec_per_chip":
                fcst and round(fcst["samples_per_sec"], 1),
            "forecaster_holdout_mse": fcst and fcst.get("holdout_mse"),
            "lm_111m_seq2048_tokens_per_sec":
                lm and round(lm["tokens_per_sec"], 0),
            "lm_111m_seq2048_mfu": lm and lm.get("mfu"),
            "wedged_mid_run_after": wedged_after,
        },
    }))


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Benchmark: NCF training throughput (config #1 in BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Runs the flagship NCF train step on the real TPU chip via the same
Estimator path users take.  ``vs_baseline`` compares against the same
training loop run on this host's CPU via a subprocess (the reference stack
is CPU-only — Xeon/MKL — so TPU-vs-host-CPU is the honest
capability-parity ratio we can measure in this environment; BASELINE.md:
no published reference numbers exist).
"""

import json
import os
import subprocess
import sys
import time

N_USERS, N_ITEMS = 6040, 3706      # MovieLens-1M cardinalities
# 32k keeps the MXU fed: at 8k the ~2ms fixed step dispatch dominates and
# measured throughput drops ~5x (swept 8k/32k/128k on one v5e chip)
GLOBAL_BATCH = 32768
WARMUP_STEPS, BENCH_STEPS = 5, 100
CPU_BENCH_STEPS = 10


def run_bench(platform: str):
    if platform == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import numpy as np
    import optax

    from analytics_zoo_tpu import init_orca_context
    from analytics_zoo_tpu.data.loader import make_global_batch
    from analytics_zoo_tpu.learn import Estimator
    from analytics_zoo_tpu.models import NeuralCF, NCF_PARTITION_RULES

    ctx = init_orca_context("local")
    rng = np.random.default_rng(0)
    n = GLOBAL_BATCH * 4
    data = {
        "user": rng.integers(1, N_USERS + 1, n).astype(np.int32),
        "item": rng.integers(1, N_ITEMS + 1, n).astype(np.int32),
        "label": rng.integers(0, 2, n).astype(np.int32),
    }
    est = Estimator.from_flax(
        model=NeuralCF(user_count=N_USERS, item_count=N_ITEMS,
                       user_embed=64, item_embed=64, mf_embed=64,
                       hidden_layers=(128, 64, 32)),
        loss="sparse_categorical_crossentropy",
        optimizer=optax.adam(1e-3),
        feature_cols=("user", "item"), label_cols=("label",),
        partition_rules=NCF_PARTITION_RULES)
    est._ensure_state(data)
    est._build_jits()
    batch = {k: v[:GLOBAL_BATCH] for k, v in data.items()}
    gbatch = make_global_batch(ctx.mesh, batch, est._data_sharding)
    # warmup (compile)
    state = est.state
    for _ in range(WARMUP_STEPS):
        state, mets = est._jit_train_step(state, gbatch)
    jax.block_until_ready(mets["loss"])
    steps = BENCH_STEPS if platform != "cpu" else CPU_BENCH_STEPS
    t0 = time.perf_counter()
    for _ in range(steps):
        state, mets = est._jit_train_step(state, gbatch)
    jax.block_until_ready(mets["loss"])
    dt = time.perf_counter() - t0
    return steps * GLOBAL_BATCH / dt


def main():
    if "--cpu-baseline" in sys.argv:
        print(json.dumps({"cpu_samples_per_sec": run_bench("cpu")}))
        return
    tpu_sps = run_bench("tpu")
    cpu_sps = None
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--cpu-baseline"],
            capture_output=True, text=True, timeout=900,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        for line in out.stdout.splitlines():
            if line.startswith("{"):
                cpu_sps = json.loads(line)["cpu_samples_per_sec"]
    except Exception as e:
        print(f"cpu baseline failed: {e!r}", file=sys.stderr)
    # vs_baseline is null (not 1.0) when the CPU baseline could not be
    # measured — 1.0 would read as "exactly at parity".
    print(json.dumps({
        "metric": "ncf_train_samples_per_sec_per_chip",
        "value": round(tpu_sps, 1),
        "unit": "samples/sec",
        "vs_baseline": round(tpu_sps / cpu_sps, 2) if cpu_sps else None,
    }))


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Example: Zouwu time-series forecasting on a synthetic NYC-taxi-shaped
signal (daily + weekly seasonality with noise).

Run:  python examples/forecast_taxi.py
(ref vertical: zouwu network-traffic / NYC-taxi notebooks.)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("EXAMPLE_PLATFORM"):
    import jax

    jax.config.update("jax_platforms", os.environ["EXAMPLE_PLATFORM"])

import numpy as np

from analytics_zoo_tpu import init_orca_context, stop_orca_context
from analytics_zoo_tpu.zouwu.forecaster import LSTMForecaster
from analytics_zoo_tpu.zouwu.preprocessing import StandardScaler, roll


def main():
    init_orca_context("local")
    # half-hourly counts with daily (48) + weekly (336) cycles
    t = np.arange(8000, dtype=np.float32)
    series = (10 + 3 * np.sin(2 * np.pi * t / 48)
              + 1.5 * np.sin(2 * np.pi * t / 336)
              + 0.3 * np.random.default_rng(0).normal(size=t.size)
              ).astype(np.float32)
    scaler = StandardScaler()
    series = scaler.fit_transform(series[:, None])
    lookback, horizon = 96, 1
    x, y = roll(series, lookback, horizon)

    split = int(len(x) * 0.9)
    fc = LSTMForecaster(target_dim=1, feature_dim=1,
                        lstm_units=(32, 16), horizon=horizon, lr=3e-3)
    fc.fit(x[:split], y[:split], epochs=5, batch_size=256)
    ev = fc.evaluate(x[split:], y[split:], metrics=("mse", "mae"))
    print(f"holdout: {ev}")
    preds = scaler.inverse_transform(fc.predict(x[split:split + 5])[:, 0])
    actual = scaler.inverse_transform(y[split:split + 5][:, 0])
    print("next-step forecasts:", np.round(preds.squeeze(), 2).tolist())
    print("actuals:            ", np.round(actual.squeeze(), 2).tolist())
    assert ev["mse"] < 0.15, "forecaster failed to beat the noise floor"
    stop_orca_context()


if __name__ == "__main__":
    main()

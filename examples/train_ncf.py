#!/usr/bin/env python
"""Example: train NeuralCF on synthetic MovieLens-shaped data.

Run:  python examples/train_ncf.py
(ref vertical: zoo recommendation examples — NCF on MovieLens-1M.)

Works on TPU (default platform) or CPU (EXAMPLE_PLATFORM=cpu).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("EXAMPLE_PLATFORM"):
    import jax

    jax.config.update("jax_platforms", os.environ["EXAMPLE_PLATFORM"])

import numpy as np
import optax

from analytics_zoo_tpu import init_orca_context, stop_orca_context
from analytics_zoo_tpu.learn import Estimator
from analytics_zoo_tpu.models import NCF_PARTITION_RULES, NeuralCF


def main():
    init_orca_context("local")
    n_users, n_items, n = 6040, 3706, 200_000
    rng = np.random.default_rng(0)
    user = rng.integers(1, n_users + 1, n).astype(np.int32)
    item = rng.integers(1, n_items + 1, n).astype(np.int32)
    # learnable, generalising signal: even-id items are "liked" — the
    # item embedding must encode it, and unseen (user, item) pairs in the
    # validation split still classify correctly
    label = (item % 2 == 0).astype(np.int32)

    est = Estimator.from_flax(
        model=NeuralCF(user_count=n_users, item_count=n_items,
                       mf_embed=16, user_embed=16, item_embed=16,
                       hidden_layers=(32, 16)),
        loss="sparse_categorical_crossentropy",
        optimizer=optax.adam(3e-3),
        metrics=("accuracy",),
        feature_cols=("user", "item"), label_cols=("label",),
        partition_rules=NCF_PARTITION_RULES)

    split = int(n * 0.9)
    train = {k: v[:split] for k, v in
             {"user": user, "item": item, "label": label}.items()}
    val = {k: v[split:] for k, v in
           {"user": user, "item": item, "label": label}.items()}

    hist = est.fit(train, epochs=5, batch_size=4096, validation_data=val)
    for i, h in enumerate(hist):
        print(f"epoch {i + 1}: loss={h['loss']:.4f} "
              f"acc={h.get('accuracy', float('nan')):.3f} "
              f"({h['samples_per_sec']:,.0f} samples/s)")
    ev = est.evaluate(val, batch_size=8192)
    print(f"validation: {ev}")
    assert ev["accuracy"] > 0.9, "NCF failed to learn the parity signal"
    est.save("/tmp/zoo_example_ncf")
    print("saved model to /tmp/zoo_example_ncf")
    stop_orca_context()


if __name__ == "__main__":
    main()

"""Example: train a small causal LM and generate from it.

Shows the decoder-only surface end-to-end: Estimator.fit on a synthetic
next-token task, greedy + temperature sampling via the KV-cache scan, and
the same weights served through InferenceModel.load_flax_generator.

    python examples/lm_generate.py              # default platform
    python examples/lm_generate.py --devices 8  # 8-device virtual CPU mesh
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=0)
    ap.add_argument("--epochs", type=int, default=8)
    args = ap.parse_args()
    if args.devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") +
            f" --xla_force_host_platform_device_count={args.devices}")
        import jax

        jax.config.update("jax_platforms", "cpu")

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import analytics_zoo_tpu as zoo
    from analytics_zoo_tpu.learn import Estimator
    from analytics_zoo_tpu.learn.inference_model import InferenceModel
    from analytics_zoo_tpu.models import (
        TransformerLM, LM_PARTITION_RULES, generate, lm_loss)

    zoo.init_orca_context("local")
    # task: arithmetic sequences mod V — next token is fully determined
    # by (start, step), so a small LM learns it quickly
    rng = np.random.default_rng(0)
    n, t, vocab = 2048, 16, 64
    start = rng.integers(0, vocab, n)
    step = rng.integers(1, 5, n)
    toks = ((start[:, None] + step[:, None] * np.arange(t)) % vocab
            ).astype(np.int32)

    model = TransformerLM(vocab_size=vocab, hidden_size=64, num_layers=2,
                          num_heads=4, intermediate_size=128,
                          max_position=64)
    est = Estimator.from_flax(
        model=model, loss=lm_loss, optimizer=optax.adam(3e-3),
        feature_cols=("tokens",), label_cols=("tokens",),
        partition_rules=LM_PARTITION_RULES)
    hist = est.fit({"tokens": toks}, epochs=args.epochs, batch_size=256)
    print(f"final loss: {hist[-1]['loss']:.4f}")

    params = {"params": jax.device_get(est.state.params)}
    prompt = ((3 + 2 * np.arange(6)) % vocab)[None].astype(np.int32)
    greedy = np.asarray(generate(model, params, jnp.asarray(prompt), 8))
    sampled = np.asarray(generate(model, params, jnp.asarray(prompt), 8,
                                  temperature=0.8, top_k=4,
                                  rng=jax.random.key(0)))
    print(f"prompt : {prompt[0].tolist()}")
    print(f"greedy : {greedy[0].tolist()}  (want +2 steps mod {vocab})")
    print(f"sampled: {sampled[0].tolist()}")

    # the serving face: ragged prompts through the generator model
    im = InferenceModel().load_flax_generator(
        model, params, max_new_tokens=8, prompt_buckets=(8, 16))
    ragged = np.zeros((2, 6), np.int32)
    ragged[0] = prompt[0]
    ragged[1, :4] = (10 + 3 * np.arange(4)) % vocab   # shorter prompt
    out = im.predict(ragged, np.asarray([6, 4], np.int32))
    print(f"served : {out.tolist()}")

    # continuous batching: requests join the RUNNING decode arena
    # in-flight (no convoying behind the longest co-batched generation),
    # each with its own token budget / sampling controls
    from analytics_zoo_tpu.serving import (
        ClusterServing, InputQueue, OutputQueue, ServingConfig)

    cfg = ServingConfig(prompt_col="prompt", continuous_batching=True,
                        engine_slots=4, engine_ticks=4)
    srv = ClusterServing(im, cfg, embedded_broker=True).start()
    iq, oq = InputQueue(port=srv.port), OutputQueue(port=srv.port)
    iq.enqueue("greedy", prompt=prompt[0])
    iq.enqueue("short", prompt=ragged[1, :4], max_new=np.int32(3))
    iq.enqueue("sampled", prompt=prompt[0],
               temperature=np.float32(0.8), seed=np.int32(7))
    for uri in ("greedy", "short", "sampled"):
        print(f"cb[{uri}]: {np.asarray(oq.query(uri, timeout=120)).tolist()}")
    srv.stop()
    zoo.stop_orca_context()


if __name__ == "__main__":
    main()

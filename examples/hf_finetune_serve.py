"""The README's LLM path, runnable at toy scale: construct a GPT-2
(stand-in for from_pretrained on a real checkpoint), import it, LoRA-
fine-tune ON the imported weights, and serve text-in/text-out over HTTP
with per-request controls.

Run: python examples/hf_finetune_serve.py
"""

import http.client
import json

import numpy as np
import optax

from analytics_zoo_tpu.learn import Estimator, LoRAConfig
from analytics_zoo_tpu.learn.inference_model import InferenceModel
from analytics_zoo_tpu.models import LM_PARTITION_RULES, lm_loss
from analytics_zoo_tpu.net import Net
from analytics_zoo_tpu.serving import (ClusterServing, HttpFrontend,
                                       ServingConfig)


def main():
    # a local random GPT-2 stands in for GPT2LMHeadModel.from_pretrained
    import torch
    from tokenizers import Tokenizer, models, pre_tokenizers, trainers
    from transformers import GPT2Config, GPT2LMHeadModel

    torch.manual_seed(0)
    tok = Tokenizer(models.BPE(unk_token="[UNK]"))
    tok.pre_tokenizer = pre_tokenizers.Whitespace()
    tok.train_from_iterator(
        ["the cat sat on the mat", "the dog ran after the cat",
         "a mat is where the cat sat"],
        trainers.BpeTrainer(vocab_size=64, special_tokens=["[UNK]"]))
    V = tok.get_vocab_size()
    hf = GPT2LMHeadModel(GPT2Config(
        vocab_size=V + 8, n_positions=64, n_embd=32, n_layer=2,
        n_head=2, resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0))

    model, variables = Net.load_hf_gpt2(hf)
    print(f"imported GPT-2: {model.num_layers} layers, vocab "
          f"{model.vocab_size}")

    # LoRA-fine-tune ON the imported weights
    corpus_text = ["the cat sat on the mat"] * 48
    ids = [tok.encode(t).ids for t in corpus_text]
    width = max(len(i) for i in ids)
    corpus = {"tokens": np.asarray(
        [i + [0] * (width - len(i)) for i in ids], np.int32)}
    est = Estimator.from_flax(
        model=model, loss=lm_loss, optimizer=optax.adamw(5e-3),
        feature_cols=("tokens",), label_cols=("tokens",),
        partition_rules=LM_PARTITION_RULES,
        initial_variables=variables,        # start from the import
        lora=LoRAConfig(rank=4))
    hist = est.fit(corpus, epochs=6, batch_size=8)
    print(f"LoRA fine-tune: loss {hist[0]['loss']:.3f} -> "
          f"{hist[-1]['loss']:.3f}")

    # serve the baked result, text in / text out
    im = InferenceModel().load_flax_generator(
        model, {"params": est.merged_params()}, max_new_tokens=6,
        prompt_buckets=(8, 16))
    srv = ClusterServing(
        im, ServingConfig(prompt_col="tokens", batch_size=8,
                          batch_timeout_ms=20.0),
        embedded_broker=True).start()
    fe = HttpFrontend(redis_port=srv.port, serving=srv,
                      tokenizer=tok).start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", fe.port,
                                          timeout=60)
        conn.request("POST", "/predict", json.dumps(
            {"instances": [{"text": "the cat sat"}]}),
            {"Content-Type": "application/json"})
        resp = conn.getresponse()
        out = json.loads(resp.read())["predictions"][0]
        print(f"HTTP text round trip ({resp.status}): "
              f"'the cat sat' -> {out!r}")
    finally:
        fe.stop()
        srv.stop()


if __name__ == "__main__":
    main()

"""Example: 5-axis parallel training — MoE-BERT on a dp x ep x tp mesh,
then a GPipe-pipelined trunk on a pp x dp mesh.

Runs anywhere: on a single chip the axes collapse to size 1 (same code);
pass --devices N to force an N-device virtual CPU mesh and see the real
collectives compile.  This is the capability the reference never had
(SURVEY.md §2.3 item 6: no TP/PP/SP/EP upstream) — on this framework a
parallelism strategy is a mesh shape plus partition rules.

    python examples/train_moe_pipeline.py --devices 8
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=0,
                    help="force an N-device virtual CPU mesh (0 = real)")
    ap.add_argument("--epochs", type=int, default=3)
    args = ap.parse_args()

    if args.devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") +
            f" --xla_force_host_platform_device_count={args.devices}")
        import jax

        jax.config.update("jax_platforms", "cpu")

    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import PartitionSpec as P

    import analytics_zoo_tpu as zoo
    from analytics_zoo_tpu.learn import Estimator
    from analytics_zoo_tpu.models import (
        BERT, BERTForSequenceClassification, BERT_MOE_PARTITION_RULES)
    from analytics_zoo_tpu.parallel import GPipe, pp_stage_rules

    n = len(jax.devices())

    # ---- phase 1: MoE-BERT, experts sharded over ep, attention over tp ----
    axes = {"dp": -1, "ep": 2 if n % 2 == 0 else 1,
            "tp": 2 if n % 4 == 0 else 1}
    ctx = zoo.init_orca_context("local", mesh_axes=axes)
    print(f"[moe] mesh: {dict(ctx.mesh.shape)}")
    rng = np.random.default_rng(0)
    n_rows, seq, vocab = 512, 16, 512
    data = {
        "input_ids": rng.integers(0, vocab, (n_rows, seq)).astype(np.int32),
        "label": rng.integers(0, 2, n_rows).astype(np.int32),
    }
    model = BERTForSequenceClassification(
        num_classes=2,
        bert=BERT(vocab_size=vocab, hidden_size=64, num_layers=2,
                  num_heads=4, intermediate_size=128, max_position=seq,
                  mesh=ctx.mesh, moe_experts=4, moe_every=1))
    est = Estimator.from_flax(
        model=model, loss="sparse_categorical_crossentropy",
        optimizer=optax.adamw(1e-3), metrics=("accuracy",),
        feature_cols=("input_ids",), label_cols=("label",),
        partition_rules=BERT_MOE_PARTITION_RULES)
    hist = est.fit(data, epochs=args.epochs, batch_size=128)
    print(f"[moe] final: {hist[-1]}")
    zoo.stop_orca_context()

    # ---- phase 2: GPipe trunk over pp ------------------------------------
    axes = {"pp": 2 if n % 2 == 0 else 1, "dp": -1}
    ctx = zoo.init_orca_context("local", mesh_axes=axes)
    print(f"[pipe] mesh: {dict(ctx.mesh.shape)}")

    class Stage(nn.Module):
        @nn.compact
        def __call__(self, x):
            h = nn.gelu(nn.Dense(128, name="up")(x))
            return nn.LayerNorm(name="ln")(x + nn.Dense(64, name="down")(h))

    mesh = ctx.mesh

    class PipedNet(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(64, name="embed")(x)
            x = GPipe(stage=Stage(), n_stages=max(2, mesh.shape["pp"]),
                      n_microbatches=4, mesh=mesh, name="trunk")(x)
            return nn.Dense(2, name="head")(x)

    xs = rng.normal(size=(512, 32)).astype(np.float32)
    ys = (xs.sum(-1) > 0).astype(np.int32)
    est = Estimator.from_flax(
        model=PipedNet(), loss="sparse_categorical_crossentropy",
        optimizer=optax.adam(3e-3), metrics=("accuracy",),
        feature_cols=("x",), label_cols=("y",),
        partition_rules=pp_stage_rules() + ((r".*", P()),))
    hist = est.fit({"x": xs, "y": ys}, epochs=args.epochs, batch_size=128)
    print(f"[pipe] final: {hist[-1]}")

    # ---- phase 3: the same trunk on the 1F1B schedule --------------------
    # GPipe autodiff keeps every microbatch's activations resident until
    # its backward; pipeline_value_and_grad interleaves fwd/bwd (flat
    # 1F1B) so residency is bounded by 2S microbatches no matter how many
    # microbatches shrink the bubble.
    from analytics_zoo_tpu.parallel import (pipeline_1f1b_stats,
                                            pipeline_value_and_grad)

    stage = Stage()
    S = max(2, mesh.shape["pp"])
    keys = jax.random.split(jax.random.key(0), S)
    probe = jnp.zeros((1, 64), jnp.float32)
    stacked = jax.vmap(lambda k: stage.init(k, probe)["params"])(keys)
    xe = jnp.asarray(rng.normal(size=(128, 64)).astype(np.float32))
    lbl = jnp.asarray(rng.normal(size=(128, 64)).astype(np.float32))
    mse = lambda y, t: jnp.mean((y - t) ** 2)
    M = 8
    loss, grads, dx = jax.jit(
        lambda p, x_, l_: pipeline_value_and_grad(
            lambda p_, a: stage.apply({"params": p_}, a), mse,
            p, x_, l_, mesh, M))(stacked, xe, lbl)
    st = pipeline_1f1b_stats(S, M)
    print(f"[1f1b] loss={float(loss):.4f} ticks={st['ticks']} "
          f"resident-acts/rank={st['residual_slots']} (GPipe would hold "
          f"{st['gpipe_resident_microbatches']}), bubble="
          f"{st['bubble_fraction']:.2%}")
    zoo.stop_orca_context()


if __name__ == "__main__":
    main()

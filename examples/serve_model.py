#!/usr/bin/env python
"""Example: Cluster Serving end-to-end — start the server with an
embedded RESP broker, enqueue tensor AND encoded-image requests through
the client queues, read results back.

Run:  python examples/serve_model.py
(ref vertical: Cluster Serving quickstart — config.yaml + InputQueue /
OutputQueue clients.)
"""

import io
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("EXAMPLE_PLATFORM"):
    import jax

    jax.config.update("jax_platforms", os.environ["EXAMPLE_PLATFORM"])

import flax.linen as nn
import jax
import numpy as np

from analytics_zoo_tpu.learn.inference_model import InferenceModel
from analytics_zoo_tpu.serving import (
    ClusterServing, InputQueue, OutputQueue, ServingConfig)


class TinyClassifier(nn.Module):
    """Mean-pixel "classifier" over [B, 32, 32, 3] uint8 images."""

    @nn.compact
    def __call__(self, x):
        x = x.astype(np.float32) / 255.0
        h = nn.relu(nn.Conv(8, (3, 3), strides=(2, 2))(x))
        h = h.mean(axis=(1, 2))
        return nn.Dense(10)(h)


def main():
    model = TinyClassifier()
    variables = model.init(jax.random.key(0),
                           np.zeros((1, 32, 32, 3), np.uint8))
    im = InferenceModel(batch_buckets=(1, 8, 32))
    im.load_flax(model, variables)
    cfg = ServingConfig(batch_size=32, batch_timeout_ms=5.0,
                        image_shape=[32, 32])
    serving = ClusterServing(im, cfg, embedded_broker=True).start()
    print(f"serving on 127.0.0.1:{serving.port} (RESP wire protocol)")

    inq = InputQueue(port=serving.port)
    outq = OutputQueue(port=serving.port)

    # 1) dense-tensor request
    uri = inq.enqueue("tensor-req",
                      x=np.random.default_rng(0).integers(
                          0, 256, (32, 32, 3)).astype(np.uint8))
    print("tensor logits:", np.round(outq.query(uri, timeout=30), 3))

    # 2) encoded-image request (JPEG over the wire, server-side decode)
    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(np.random.default_rng(1).integers(
        0, 256, (48, 48, 3)).astype(np.uint8)).save(buf, "JPEG")
    uri = inq.enqueue_image("image-req", image=buf.getvalue())
    print("image  logits:", np.round(outq.query(uri, timeout=30), 3))

    print("server stats:", serving.stats)
    inq.close()
    outq.close()
    serving.stop()


if __name__ == "__main__":
    main()

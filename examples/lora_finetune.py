"""LoRA fine-tune of the causal LM, then serve the baked result.

Base weights stay frozen; only rank-r adapters train (Adam state shrinks
to the adapter tree — for the 111M bench LM that is ~1.5 MB of moments
instead of ~900 MB).  `merged_params()` folds the adapters back into
plain params for InferenceModel/serving.

Run: python examples/lora_finetune.py
"""

import numpy as np
import optax

from analytics_zoo_tpu import init_orca_context, stop_orca_context
from analytics_zoo_tpu.learn import Estimator, LoRAConfig
from analytics_zoo_tpu.models import (
    TransformerLM, LM_PARTITION_RULES, lm_loss)


def main():
    init_orca_context("local")
    rng = np.random.default_rng(0)
    V, T, B = 1024, 128, 8
    # toy corpus with a learnable pattern: token t+1 = (t*3+1) % V
    start = rng.integers(0, V, (B * 16, 1))
    seqs = [start]
    for _ in range(T - 1):
        seqs.append((seqs[-1] * 3 + 1) % V)
    data = {"tokens": np.concatenate(seqs, axis=1).astype(np.int32)}

    model = TransformerLM(vocab_size=V, hidden_size=128, num_layers=4,
                          num_heads=4, intermediate_size=512,
                          max_position=T)
    est = Estimator.from_flax(
        model=model, loss=lm_loss, optimizer=optax.adamw(3e-3),
        feature_cols=("tokens",), label_cols=("tokens",),
        partition_rules=LM_PARTITION_RULES,
        lora=LoRAConfig(rank=8, alpha=16.0))
    hist = est.fit(data, epochs=5, batch_size=B)
    print("losses:", [round(h["loss"], 3) for h in hist])

    adapters = est.lora_params()
    n = sum(int(np.prod(x.shape))
            for ab in adapters.values() for x in ab.values())
    print(f"adapter tree: {len(adapters)} kernels, {n:,} params "
          f"({n * 4 / 2**20:.2f} MB f32)")

    baked = est.merged_params()          # plain params, ready to serve
    from analytics_zoo_tpu.learn.inference_model import InferenceModel

    im = InferenceModel()
    im.load_flax_generator(model, {"params": baked}, max_new_tokens=8)
    out = im.predict(data["tokens"][:2, :16])    # [2, 16] prompts
    print("generated:", np.asarray(out))
    stop_orca_context()


if __name__ == "__main__":
    main()

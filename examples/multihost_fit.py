#!/usr/bin/env python
"""Example: multi-host training — 2 processes, one jax.distributed
coordinator, host-local data shards, one global model.

Run:  python examples/multihost_fit.py
(self-spawns 2 worker processes on this box with 4 virtual CPU devices
each — the single-box analog of 2 TPU-VM hosts; on a real pod each host
runs the same worker code with its own process_id.)

What it demonstrates:
  * ``init_orca_context("multihost", ...)`` joining the coordinator
    (the Spark-submit + RayOnSpark analog — SURVEY §3.1),
  * replicated ndarray inputs deduplicated across hosts automatically,
  * per-host DiskFeatureSet shards ({host} path placeholder),
  * a checkpoint written collectively by both hosts.
"""

import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def worker(pid: int, nprocs: int, port: int, workdir: str):
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 4)
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    import numpy as np
    import optax
    import flax.linen as nn

    from analytics_zoo_tpu import init_orca_context
    from analytics_zoo_tpu.common.config import TrainConfig
    from analytics_zoo_tpu.data.feature_set import FeatureSet
    from analytics_zoo_tpu.learn import Estimator

    ctx = init_orca_context(
        "multihost", coordinator_address=f"localhost:{port}",
        num_processes=nprocs, process_id=pid, mesh_axes={"dp": -1})
    print(f"[host {pid}] joined: {ctx}", flush=True)

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(1)(nn.tanh(nn.Dense(32)(x)))

    rng = np.random.default_rng(0)          # same data on every host —
    x = rng.normal(size=(512, 8)).astype(np.float32)   # fit() dedups
    y = x.sum(1, keepdims=True).astype(np.float32)

    est = Estimator.from_flax(model=MLP(), loss="mse",
                              optimizer=optax.adam(1e-2),
                              config=TrainConfig(seed=0))
    hist = est.fit({"x": x, "y": y}, epochs=3, batch_size=64)
    if pid == 0:
        for i, h in enumerate(hist):
            print(f"[host 0] epoch {i + 1}: loss={h['loss']:.4f}",
                  flush=True)

    # per-host disk shards: each host spills ITS half and streams it
    half = len(x) // nprocs
    lo = pid * half
    dfs = FeatureSet({"x": x[lo:lo + half], "y": y[lo:lo + half]}).to_disk(
        os.path.join(workdir, "shard_{host}.zrec"))
    h2 = est.fit(dfs, epochs=1, batch_size=64)
    if pid == 0:
        print(f"[host 0] disk-tier epoch: loss={h2[-1]['loss']:.4f} "
              f"({int(h2[-1]['num_samples'])} global samples)", flush=True)

    est.save_checkpoint(os.path.join(workdir, "ckpt"))
    if pid == 0:
        print(f"[host 0] collective checkpoint written; final step "
              f"{int(est.state.step)}", flush=True)


def main():
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    workdir = tempfile.mkdtemp(prefix="zoo_multihost_")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker",
             str(i), "2", str(port), workdir], env=env)
        for i in range(2)
    ]
    try:
        rcs = [p.wait(timeout=600) for p in procs]
    finally:
        # a crashed worker leaves its peer blocked in a gloo collective —
        # never leak a hung process (same pattern as tests/test_multihost)
        for p in procs:
            if p.poll() is None:
                p.kill()
    if any(rcs):
        raise SystemExit(f"worker exit codes: {rcs}")
    print("multihost example complete")


if __name__ == "__main__":
    if "--worker" in sys.argv:
        i = sys.argv.index("--worker")
        worker(int(sys.argv[i + 1]), int(sys.argv[i + 2]),
               int(sys.argv[i + 3]), sys.argv[i + 4])
    else:
        main()

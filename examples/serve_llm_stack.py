"""The modern LLM-serving stack, end to end on one chip:

  1. distill a draft from the target        (models/distill.py)
  2. build a SPECULATIVE continuous engine  (serving/continuous.py)
  3. register a shared system-prompt prefix (prefix caching)
  4. serve a mixed burst — suffix-only requests at different lengths,
     co-resident in the slot arena, each advancing by its own
     acceptance rate

Every emitted stream is exactly what solo greedy generate() would
produce for the concatenated prompt (the engine's tested contract).

Run: python examples/serve_llm_stack.py
"""

import numpy as np

import jax

from analytics_zoo_tpu.models import TransformerLM
from analytics_zoo_tpu.models.distill import distill_draft
from analytics_zoo_tpu.serving.continuous import ContinuousEngine


def main():
    V, T = 512, 256
    target = TransformerLM(vocab_size=V, hidden_size=128, num_layers=4,
                           num_heads=4, intermediate_size=512,
                           max_position=T)
    draft = TransformerLM(vocab_size=V, hidden_size=64, num_layers=2,
                          num_heads=4, intermediate_size=256,
                          max_position=T)
    rng = np.random.default_rng(0)
    tv = {"params": target.init(
        jax.random.key(0), np.zeros((1, 8), np.int32))["params"]}

    # 1. distill: the draft learns to guess like the target
    start = rng.integers(0, V, (64, 1))
    seqs = [start]
    for _ in range(47):
        seqs.append((seqs[-1] * 5 + 3) % V)
    corpus = {"tokens": np.concatenate(seqs, 1).astype(np.int32)}
    dv, hist = distill_draft(target, tv, draft, corpus,
                             epochs=4, batch_size=8)
    print(f"distilled draft: loss {hist[0]['loss']:.3f} -> "
          f"{hist[-1]['loss']:.3f}")

    # 2.+3. speculative engine + shared system prompt
    eng = ContinuousEngine(target, tv, max_new_tokens=16, max_slots=4,
                           prompt_buckets=(16, 32),
                           draft_model=draft, draft_variables=dv,
                           speculation_k=4)
    system = rng.integers(1, V, 12).astype(np.int32)
    pid = eng.register_prefix(system)
    rep = eng.capacity_report()
    print(f"arena {rep['arena_bytes']/1e3:.0f} kB + draft arena "
          f"{rep['draft_arena_bytes']/1e3:.0f} kB + prefix "
          f"{rep['prefix_bytes']/1e3:.0f} kB")

    # 4. mixed burst
    results = {}
    for i in range(6):
        sfx = rng.integers(1, V, int(rng.integers(2, 8))).astype(
            np.int32)
        eng.submit(f"req{i}", sfx, prefix=pid,
                   on_done=lambda u, t: results.__setitem__(u, t))
        if i % 2:                               # plus plain traffic
            p = rng.integers(1, V, 10).astype(np.int32)
            eng.submit(f"plain{i}", p,
                       on_done=lambda u, t: results.__setitem__(u, t))
    eng.drain()
    acc = eng._spec_emitted / max(1, eng._spec_rounds)
    print(f"served {len(results)} requests in {eng._spec_rounds} "
          f"speculative rounds ({acc:.1f} tokens/round/arena)")
    print("sample output:", results["req0"][:8], "...")


if __name__ == "__main__":
    main()

"""TZ106 fixture: manual acquire() with a leaky early exit."""
import threading


class Leaky:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def pop_bad(self):
        self._lock.acquire()
        if not self._items:
            return None                         # LINE: leak
        out = self._items.pop()
        self._lock.release()
        return out

    def pop_good(self):
        self._lock.acquire()
        try:
            if not self._items:
                return None
            return self._items.pop()
        finally:
            self._lock.release()

    def pop_silenced(self):
        self._lock.acquire()
        if not self._items:
            return None  # tpulint: disable=TZ106
        self._lock.release()
        return True

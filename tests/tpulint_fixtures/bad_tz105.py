"""TZ105 fixture: double-acquire of a non-reentrant Lock."""
import threading


class Direct:
    def __init__(self):
        self._lock = threading.Lock()

    def outer(self):
        with self._lock:
            with self._lock:                    # LINE: direct
                pass


class ViaCall:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = []

    def flush(self):
        with self._lock:
            self._drain()

    def _drain(self):
        with self._lock:                        # LINE: propagated
            self._q.clear()


class Reentrant:
    """RLock: same shape, no finding — re-acquire is legal."""

    def __init__(self):
        self._lock = threading.RLock()

    def outer(self):
        with self._lock:
            with self._lock:
                pass


class Silenced:
    def __init__(self):
        self._lock = threading.Lock()

    def outer(self):
        with self._lock:
            with self._lock:  # tpulint: disable=TZ105
                pass

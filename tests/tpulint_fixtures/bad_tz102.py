"""TZ102 fixture: blocking calls while holding a lock."""
import threading
import time

import jax


class Engine:
    def __init__(self):
        self._pool_lock = threading.Lock()
        self._host = {}

    def spill(self, arr):
        with self._pool_lock:
            self._host["x"] = jax.device_get(arr)   # LINE: device_get

    def nap(self):
        with self._pool_lock:
            time.sleep(0.01)                        # LINE: sleep

    def baselined_nap(self):
        with self._pool_lock:
            time.sleep(0.01)  # tpulint: disable=TZ102

    def fine(self, arr):
        # record under the lock, do the device work after release
        with self._pool_lock:
            pending = list(self._host)
        return jax.device_get(arr), pending

"""TZ107 fixture: threaded entry points touching shared state bare."""
import threading

STATS = {}

_stats_lock = threading.Lock()


class Router:
    inflight = 0

    def _route_loop(self):
        STATS["last"] = 1                       # LINE: module
        Router.inflight = 5                     # LINE: classattr

    def _pump(self):
        with _stats_lock:
            STATS["ok"] = 1


class Worker(threading.Thread):
    def run(self):
        STATS["worker"] = 1  # tpulint: disable=TZ107

"""TZ006 fixture: host RNG inside traced code (baked into the trace)."""
import random

import jax
import numpy as np


@jax.jit
def np_random(x):
    noise = np.random.normal(size=4)        # LINE: np
    return x + noise


@jax.jit
def py_random(x):
    return x * random.random()              # LINE: py

"""TZ103 fixture: callbacks under lock and non-record-only hooks."""
import threading

import jax.numpy as jnp
from collections import OrderedDict as external_cb

EVENTS = []


def record_event(kind, **info):
    EVENTS.append((kind, info))


def heavy_hook(block, hash_):
    return jnp.zeros((block,), jnp.float32)


class Pool:
    def __init__(self, event_cb=None):
        self.event_cb = event_cb


class Engine:
    def __init__(self, on_done):
        self._lock = threading.Lock()
        self.on_done = on_done
        self.clean = Pool(event_cb=record_event)    # record-only: fine
        self.bad = Pool(event_cb=heavy_hook)        # LINE: impure
        self.ext = Pool(event_cb=external_cb)       # LINE: foreign

    def finish(self, req):
        with self._lock:
            self.on_done(req)                       # LINE: invoke

    def finish_deferred(self, req):
        with self._lock:
            done = self.on_done
        done(req)

    def finish_suppressed(self, req):
        with self._lock:
            self.on_done(req)  # tpulint: disable=TZ103

"""TZ004 fixture: jax.jit constructed per call."""
import jax
import jax.numpy as jnp


def jit_in_loop(fn, xs):
    out = []
    for x in xs:
        out.append(jax.jit(fn)(x))          # LINE: loop
    return out


def jit_immediate(x):
    return jax.jit(jnp.tanh)(x)             # LINE: immediate

"""TZ008 fixture: train-step-shaped jit without donate_argnums."""
from functools import partial

import jax


def train_step(state, batch):
    return state, 0.0


def update_step(state, batch):
    return state, 0.0


def eval_step(state, batch):
    return state, 0.0


jitted_train = jax.jit(train_step)          # LINE: train

jitted_update = jax.jit(partial(update_step, batch=None))  # LINE: update

jitted_eval = jax.jit(eval_step)            # not flagged: not a train step

jitted_good = jax.jit(train_step, donate_argnums=(0,))  # not flagged: donates

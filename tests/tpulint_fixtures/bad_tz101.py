"""TZ101 fixture: guarded-attribute writes outside the owning lock."""
import threading


class Counter:
    """Guard inferred: `_count` is assigned under `_lock` in bump()."""

    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0         # __init__ writes are exempt (setup)

    def bump(self):
        with self._lock:
            self._count += 1

    def race(self):
        self._count = 0                         # LINE: inferred

    def reset_quiesced(self):
        self._count = -1  # tpulint: disable=TZ101


class Declared:
    """Guard declared: the annotation names `_b` as the true owner, so
    the write under `_a` (which bare inference would call ambiguous)
    is exposed as a straggler."""

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._mode = "idle"

    def set_a(self):
        with self._a:
            self._mode = "a"                    # LINE: declared

    def set_b(self):
        with self._b:
            self._mode = "b"  # tpulint: guarded-by(_b)


class Clean:
    """Annotated AND consistent: every write holds the declared lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._state = None

    def put(self, v):
        with self._lock:
            self._state = v  # tpulint: guarded-by(_lock)

    def put_pair(self, v):
        with self._lock:
            self._state = (v, v)

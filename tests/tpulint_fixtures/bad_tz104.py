"""TZ104 fixture: inconsistent lock-acquisition order.

Deliberately importable (stdlib threading only): test_lockguard.py
drives the SAME seeded inversion through the runtime LockGuard, so the
static pass and the dynamic guard are cross-validated on one fixture.
"""
import threading


class Transfer:
    def __init__(self):
        self._pool_lock = threading.Lock()
        self._store_lock = threading.Lock()
        self.spilled = 0
        self.readmitted = 0

    def spill(self):
        with self._pool_lock:
            with self._store_lock:              # LINE: forward
                self.spilled += 1

    def readmit(self):
        with self._store_lock:
            with self._pool_lock:               # LINE: inverted
                self.readmitted += 1


class Suppressed:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def one(self):
        with self._a:
            with self._b:  # tpulint: disable=TZ104
                pass

    def two(self):
        with self._b:
            with self._a:  # tpulint: disable=TZ104
                pass

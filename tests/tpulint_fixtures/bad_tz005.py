"""TZ005 fixture: mutable / array default arguments on jitted functions."""
import jax
import jax.numpy as jnp


@jax.jit
def mutable_default(x, scales=[1.0, 2.0]):  # LINE: list
    return x * scales[0]


@jax.jit
def array_default(x, bias=jnp.zeros(4)):    # LINE: array
    return x + bias

"""TZ003 fixture: unrolled jnp work in Python loops over dynamic or
shape-dependent ranges."""
import jax
import jax.numpy as jnp


@jax.jit
def unrolled_shape(x):
    acc = jnp.zeros_like(x[0])
    for i in range(x.shape[0]):             # LINE: shape
        acc = acc + jnp.exp(x[i])
    return acc


@jax.jit
def unrolled_len(x, n):
    y = x
    for _ in range(len(x)):                 # LINE: len
        y = jnp.tanh(y)
    return y

"""TZ001 fixture: host-device syncs reachable from a jitted entry and
sync-per-iteration host loops."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def traced_item(x):
    s = jnp.sum(x)
    return s.item()                         # LINE: item


@jax.jit
def traced_float(x):
    s = jnp.sum(x)
    return float(s)                         # LINE: float


@jax.jit
def traced_np(x):
    return np.asarray(jnp.exp(x))           # LINE: np


def helper(y):
    return jax.device_get(y)                # LINE: helper


@jax.jit
def calls_helper(x):
    return helper(x * 2)


def host_loop(xs):
    total = 0.0
    for x in xs:
        loss = jnp.sum(x)
        total += float(loss)                # LINE: loop
    return total

"""TZ002 fixture: Python control flow branching on traced values."""
import jax
import jax.numpy as jnp


@jax.jit
def branch_on_tracer(x):
    s = jnp.sum(x)
    if s > 0:                               # LINE: if
        return x * 2
    return x


@jax.jit
def while_on_tracer(x):
    n = jnp.sum(x)
    while n > 0:                            # LINE: while
        n = n - 1
    return n

"""Clean concurrency idioms: everything the TZ1xx pass must accept.

Consistent pool -> store order, record-only hook, blocking work done
after release, guarded writes, try/finally manual region, predicate
loop around Condition.wait.
"""
import threading


class Engine:
    def __init__(self):
        self._pool_lock = threading.Lock()
        self._store_lock = threading.Lock()
        self._pending = []
        self._count = 0

    def _note_spill(self, block, hash_):
        # record-only hook body: appends, no locks, no device work
        self._pending.append((block, hash_))

    def bump(self):
        with self._pool_lock:
            self._count += 1

    def spill(self):
        with self._pool_lock:
            with self._store_lock:
                work = list(self._pending)
        return work

    def readmit(self):
        # same order as spill(): pool before store, always
        with self._pool_lock:
            with self._store_lock:
                return len(self._pending)


class Mailbox:
    def __init__(self):
        self._cond = threading.Condition()
        self._items = []

    def put(self, item):
        with self._cond:
            self._items.append(item)
            self._cond.notify()

    def take(self):
        with self._cond:
            while not self._items:
                self._cond.wait()
            return self._items.pop(0)

    def snapshot(self):
        self._cond.acquire()
        try:
            return list(self._items)
        finally:
            self._cond.release()

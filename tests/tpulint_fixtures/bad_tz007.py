"""TZ007 fixture: implicit-dtype conversions in serving hot paths.

This file is only flagged when analyzed with a hot-path pattern that
matches it (the tests pass ``--hot-path tpulint_fixtures``).
"""
import jax.numpy as jnp
import numpy as np


def admit(tokens):
    padded = np.zeros((4, 16), np.int32)
    return jnp.asarray(padded)              # LINE: asarray


def build(v):
    return jnp.full((v,), -jnp.inf)         # LINE: full


def ok_explicit(tokens):
    return jnp.asarray(tokens, jnp.int32)   # not flagged: explicit dtype

"""TZ108 fixture: Condition.wait without a predicate re-check loop."""
import threading


class Mailbox:
    def __init__(self):
        self._cond = threading.Condition()
        self._msgs = []

    def take_bad(self):
        with self._cond:
            if not self._msgs:
                self._cond.wait()               # LINE: bare
            return self._msgs.pop()

    def take_good(self):
        with self._cond:
            while not self._msgs:
                self._cond.wait()
            return self._msgs.pop()

    def take_wait_for(self):
        with self._cond:
            self._cond.wait_for(lambda: self._msgs)
            return self._msgs.pop()

    def take_napped(self):
        with self._cond:
            self._cond.wait(0.1)  # tpulint: disable=TZ108
            return self._msgs.pop()

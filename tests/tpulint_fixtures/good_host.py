"""Negative fixture: ordinary host orchestration that must NOT be
flagged.  Every pattern here is one the analyzer previously
false-positived on somewhere, or a near-miss of a rule."""
import concurrent.futures as cf

import jax
import jax.numpy as jnp
import numpy as np


def read_one(path):
    # host callback handed to a thread pool — pool.map is NOT a JAX
    # combinator, so nothing here is traced (no TZ001/TZ006)
    data = np.fromfile(path, np.uint8)
    return float(data.mean())


def load_all(paths):
    with cf.ThreadPoolExecutor() as pool:
        return list(pool.map(read_one, paths))


@jax.jit
def static_branch(x, training: bool = False):
    # bool param is a static argument in spirit: branch compiles away
    if training:
        return x * 2
    return x


@jax.jit
def shape_branch(x):
    # .shape is trace-static — branching on it is fine (no TZ002)
    if x.shape[0] > 1:
        return x.sum(axis=0)
    return x[0]


@jax.jit
def constant_unroll(x):
    # range over a literal is a bounded, deliberate unroll (no TZ003)
    for _ in range(4):
        x = jnp.tanh(x)
    return x


def epoch(step, state, batches):
    # the one-sync-per-epoch idiom: fetch AFTER the loop (no TZ001)
    losses = []
    for b in batches:
        state, loss = step(state, b)
        losses.append(loss)
    return state, [float(v) for v in jax.device_get(losses)]


def final_report(x):
    # syncs OUTSIDE any loop in host code are normal termination
    y = jnp.sum(jnp.asarray(x, jnp.float32))
    return float(jax.device_get(y))
